"""Pipeline parallelism: GPipe-style microbatching over a ``pp`` mesh axis.

Not present in the reference (SURVEY.md section 2 parallelism table: PP "—").
TPU-native design: stages are devices along a mesh axis, activations hop
stage-to-stage with ``lax.ppermute`` (one ICI neighbour hop), and the
microbatch schedule is a single ``lax.fori_loop`` — compiled once, no
data-dependent Python control flow. The bubble is the standard GPipe
(P-1)/(M+P-1) fraction; raise ``n_microbatches`` to amortise.

Usage (inside or outside jit):

    stages = stack_stage_params(per_stage_params)      # leading dim = P
    y = pipeline_apply(stage_fn, stages, x_microbatched, mesh=mesh)

where ``stage_fn(stage_params, x) -> y`` maps one microbatch through one
stage, and x_microbatched has shape [M, mb, ...].
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu.ops.compat import (
    axis_size as _axis_size,
    pcast_varying as _pcast_varying,
    shard_map_compat as _shard_map,
    vma_of as _vma_of,
)

StageFn = Callable[[Any, jax.Array], jax.Array]


def pipeline_local(
    stage_fn: StageFn,
    stage_params: Any,
    x: jax.Array,
    *,
    axis_name: str = "pp",
    with_aux: bool = False,
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Per-device GPipe schedule; call inside shard_map.

    ``x``: [M, mb, ...] microbatched input, replicated over the axis (only
    stage 0 reads it). Returns [M, mb, ...] outputs, replicated (the last
    stage's results are broadcast with a psum).

    ``with_aux``: stage_fn returns ``(y, aux_scalar)`` (e.g. a MoE
    load-balancing loss); real ticks' aux is accumulated per stage, summed
    over stages with a psum, and averaged over microbatches — the result is
    ``(out, aux)`` where aux matches the sequential trainer's
    sum-over-layers, mean-over-batch scalar.
    """
    n_stages = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    M = x.shape[0]
    n_ticks = M + n_stages - 1
    # stage s receives from s-1; the (n-1 -> 0) edge carries garbage that
    # stage 0 never reads (it pulls from x), but keeps the perm a bijection.
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def run_stage(params, batch):
        result = stage_fn(params, batch)
        return result if with_aux else (result, jnp.zeros((), jnp.float32))

    def probe_out():
        """Output structure for one microbatch (to size the buffers).

        The probe input must carry the same varying-axes type as the real
        per-tick input (pp-varying): a stage_fn that scans over pp-sharded
        layer params would otherwise fail vma typing at trace time.
        """
        xin = jax.tree.map(lambda a: _pcast_varying(a, (axis_name,)), x[0])
        return jax.eval_shape(lambda p, b: run_stage(p, b)[0], stage_params, xin)

    out_shape = probe_out()
    # pcast marks the zero buffers as device-varying along the pipeline axis
    # (jax>=0.9 shard_map typing: loop carries must match the outputs, which
    # become varying after ppermute/psum).
    recv0 = _pcast_varying(
        jnp.zeros(out_shape.shape, out_shape.dtype), (axis_name,)
    )
    out0 = _pcast_varying(
        jnp.zeros((M, *out_shape.shape), out_shape.dtype), (axis_name,)
    )
    aux0 = _pcast_varying(jnp.zeros((), jnp.float32), (axis_name,))

    def tick(t, carry):
        recv, out, aux_acc = carry
        feed_idx = jnp.clip(t, 0, M - 1)
        first_stage_in = lax.dynamic_index_in_dim(x, feed_idx, 0, keepdims=False)
        first_stage_in = _pcast_varying(
            first_stage_in.astype(recv.dtype), (axis_name,)
        )
        cur = jnp.where(my == 0, first_stage_in, recv)
        y, aux = run_stage(stage_params, cur)
        # stage s holds microbatch t-s at tick t; other ticks are warmup/
        # drain garbage whose aux must not pollute the accumulator
        valid = (t >= my) & (t - my < M)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
        updated = lax.dynamic_update_index_in_dim(out, y, out_idx, 0)
        out = jnp.where(t >= n_stages - 1, updated, out)
        recv = lax.ppermute(y, axis_name, perm)
        return recv, out, aux_acc

    _, out, aux_acc = lax.fori_loop(0, n_ticks, tick, (recv0, out0, aux0))
    # Broadcast the last stage's buffer to every stage.
    out = jnp.where(my == n_stages - 1, out, jnp.zeros_like(out))
    out = lax.psum(out, axis_name)
    if not with_aux:
        return out
    aux = lax.psum(aux_acc, axis_name) / M  # sum stages, mean microbatches
    return out, aux


def pipeline_apply(
    stage_fn: StageFn,
    stacked_stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = "pp",
) -> jax.Array:
    """Full-array entry: shard stage params over ``axis_name``, run the
    schedule, return outputs for all microbatches (replicated over the axis).

    ``stacked_stage_params``: pytree whose leaves have a leading stage dim of
    size mesh.shape[axis_name].
    """
    param_specs = jax.tree.map(lambda _: P(axis_name), stacked_stage_params)

    def body(params, xs):
        params = jax.tree.map(lambda a: a[0], params)  # drop unit stage dim
        return pipeline_local(stage_fn, params, xs, axis_name=axis_name)

    return _shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
    )(stacked_stage_params, x)


# --- 1F1B (memory-capped) training schedule ----------------------------------


def pipeline_train_1f1b(
    stage_fn: StageFn,
    head_fn: Callable[[Any, jax.Array, jax.Array], jax.Array],
    stage_params: Any,
    head_params: Any,
    xs: jax.Array,
    targets: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = "pp",
):
    """Pipelined training with 1F1B-style interleaving: loss with grads via
    a hand-scheduled backward (jax.custom_vjp), O(P) activation memory.

    GPipe under autodiff stores every microbatch's stage input until the
    backward phase — O(M) live activations per stage. Here each global tick
    runs one forward AND one backward slot per stage: microbatch i's forward
    hits stage s at tick ``i + s`` and its backward at tick ``i + 2P-2 - s``,
    so at most ``2P-1`` stage inputs are ever buffered (the eager variant of
    PipeDream-flush/1F1B, arXiv:2104.04473: same flush bubble, constant
    memory). The backward slot recomputes its stage forward from the saved
    input (per-microbatch remat) inside ``jax.vjp``.

    - ``stage_fn(stage_params, x_mb) -> y_mb`` — one microbatch through this
      stage's layers (differentiable).
    - ``head_fn(head_params, y_mb, tgt_mb) -> scalar`` — the per-microbatch
      loss (final norm + lm head + CE); runs on the last stage only.
    - ``xs``: [M, mb, ...] microbatched embedded inputs; ``targets``:
      [M, mb, ...] microbatched labels.

    Returns the scalar mean-over-microbatches loss. Gradients flow to
    stage_params / head_params / xs through the custom VJP (targets get
    zeros), so ``jax.value_and_grad`` over a loss built on this function
    computes pipeline-parallel gradients without ever materialising the
    GPipe activation tail.
    """
    return _pipeline_1f1b(
        stage_params, head_params, xs, targets, stage_fn, head_fn, mesh, axis_name
    )


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _pipeline_1f1b(stage_params, head_params, xs, targets,
                   stage_fn, head_fn, mesh, axis_name):
    loss, *_ = _run_1f1b(
        stage_params, head_params, xs, targets, stage_fn, head_fn, mesh, axis_name
    )
    return loss


def _pipeline_1f1b_fwd(stage_params, head_params, xs, targets,
                       stage_fn, head_fn, mesh, axis_name):
    loss, g_stage, g_head, dxs = _run_1f1b(
        stage_params, head_params, xs, targets, stage_fn, head_fn, mesh, axis_name
    )
    return loss, (g_stage, g_head, dxs, targets.shape)


def _pipeline_1f1b_bwd(stage_fn, head_fn, mesh, axis_name, res, g_loss):
    g_stage, g_head, dxs, tgt_shape = res
    scale = lambda t: jax.tree.map(lambda a: a * g_loss, t)  # noqa: E731
    # integer targets take a float0 cotangent
    dt = np.zeros(tgt_shape, jax.dtypes.float0)
    return scale(g_stage), scale(g_head), scale(dxs), dt


_pipeline_1f1b.defvjp(_pipeline_1f1b_fwd, _pipeline_1f1b_bwd)


def _run_1f1b(stage_params, head_params, xs, targets,
              stage_fn, head_fn, mesh, axis_name):
    """The combined fwd+bwd schedule; returns (loss, stage_grads,
    head_grads, dxs)."""
    P_ = int(mesh.shape[axis_name])
    M = xs.shape[0]
    layer_specs = jax.tree.map(lambda _: P(axis_name), stage_params)

    def body(sp, hp, xs_, tg_):
        return _1f1b_local(
            sp, hp, xs_, tg_, stage_fn=stage_fn, head_fn=head_fn,
            axis_name=axis_name, n_stages=P_, M=M,
        )

    return _shard_map(
        body,
        mesh=mesh,
        in_specs=(layer_specs, P(), P(), P()),
        out_specs=(P(), layer_specs, P(), P()),
        axis_names={axis_name},
    )(stage_params, head_params, xs, targets)


def _1f1b_local(stage_params, head_params, xs, targets, *,
                stage_fn, head_fn, axis_name, n_stages, M):
    my = lax.axis_index(axis_name)
    # stage_params arrive pp-sharded on dim 0: each stage sees its own
    # [L/P, ...] layer stack and stage_fn owns its interpretation (scan
    # over it for a transformer; index [0] for one-param-per-stage)
    sp_local = stage_params
    n_ticks = M + 2 * n_stages - 2
    buf_n = max(1, 2 * n_stages - 1)  # max in-flight inputs (stage 0)
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]

    def vary(a):
        # idempotent: zeros_like of pp-sharded params is already varying
        if axis_name in _vma_of(a):
            return a
        return _pcast_varying(a, (axis_name,))

    xin0 = jax.tree.map(vary, xs[0])
    y_shape = jax.eval_shape(lambda p, b: stage_fn(p, b), sp_local, xin0)
    # the head vjp must see VARYING head params: differentiating a varying
    # computation w.r.t. an unvarying input makes jax insert an implicit
    # psum over the axis (transpose of broadcast), which would sum the
    # non-last stages' garbage head grads into the real ones
    hp_var = jax.tree.map(vary, head_params)

    recv_f0 = vary(jnp.zeros(y_shape.shape, y_shape.dtype))
    recv_b0 = vary(jnp.zeros(y_shape.shape, y_shape.dtype))
    inbuf0 = vary(jnp.zeros((buf_n, *xs.shape[1:]), xs.dtype))
    g_stage0 = jax.tree.map(lambda a: vary(jnp.zeros_like(a)), sp_local)
    g_head0 = jax.tree.map(lambda a: vary(jnp.zeros_like(a)), head_params)
    dxs0 = vary(jnp.zeros_like(xs))
    loss0 = vary(jnp.zeros((), jnp.float32))

    last = n_stages - 1

    def zeros_of(tree):
        # a*0 (not zeros_like) keeps the varying-axes type on the zeros
        return jax.tree.map(lambda a: a * 0, tree)

    def tick(t, carry):
        recv_f, recv_b, inbuf, loss, g_stage, g_head, dxs = carry

        # ---- forward slot: microbatch i_f enters this stage -------------
        i_f = t - my
        valid_f = (i_f >= 0) & (i_f < M)
        idx_f = jnp.clip(i_f, 0, M - 1)
        first_in = vary(
            lax.dynamic_index_in_dim(xs, idx_f, 0, keepdims=False).astype(
                recv_f.dtype
            )
        )
        x_in = jnp.where(my == 0, first_in, recv_f)
        inbuf = jnp.where(
            valid_f,
            lax.dynamic_update_index_in_dim(inbuf, x_in, idx_f % buf_n, 0),
            inbuf,
        )
        # warmup/drain ticks skip the stage compute entirely (lax.cond is
        # per-device inside the manual region and both branches are
        # collective-free): a stage whose slot is empty must not make its
        # ppermute partners wait on garbage compute
        y = lax.cond(
            valid_f,
            lambda a: stage_fn(sp_local, a),
            lambda a: recv_f * 0,  # y-shaped varying zeros
            x_in,
        )
        send_f = lax.ppermute(y, axis_name, fwd_perm)

        # ---- backward slot: microbatch i_b leaves this stage ------------
        i_b = t - (2 * n_stages - 2 - my)
        valid_b = (i_b >= 0) & (i_b < M)
        idx_b = jnp.clip(i_b, 0, M - 1)
        x_saved = lax.dynamic_index_in_dim(inbuf, idx_b % buf_n, 0, keepdims=False)
        tgt = vary(lax.dynamic_index_in_dim(targets, idx_b, 0, keepdims=False))
        one = vary(jnp.asarray(1.0, jnp.float32))  # varying scalar seed

        def bwd_slot(op):
            x_saved_, recv_b_, tgt_, one_ = op
            y_b, pull = jax.vjp(lambda p, a: stage_fn(p, a), sp_local, x_saved_)

            # the loss head (final norm + vocab matmul + CE) runs ONLY on
            # the last stage — running it everywhere and masking after the
            # fact would add P-1 redundant vocab-sized fwd+bwd per tick
            def head_slot(hy):
                hp_, y_ = hy
                loss_i, head_pull = jax.vjp(
                    lambda hp, a: head_fn(hp, a, tgt_), hp_, y_
                )
                dhead_i, dy_head = head_pull(one_ / M)
                return loss_i, dhead_i, dy_head

            def head_skip(hy):
                hp_, y_ = hy
                return one_ * 0, zeros_of(hp_), y_ * 0

            loss_i, dhead_i, dy_head = lax.cond(
                my == last, head_slot, head_skip, (hp_var, y_b)
            )
            ct = jnp.where(my == last, dy_head.astype(y_b.dtype), recv_b_)
            dstage_i, dx_i = pull(ct)
            return loss_i, dhead_i, dstage_i, dx_i

        def bwd_skip(op):
            x_saved_, recv_b_, tgt_, one_ = op
            return (
                one_ * 0,
                zeros_of(hp_var),
                zeros_of(sp_local),
                x_saved_ * 0,
            )

        loss_i, dhead_i, dstage_i, dx_i = lax.cond(
            valid_b, bwd_slot, bwd_skip, (x_saved, recv_b, tgt, one)
        )
        g_stage = jax.tree.map(lambda acc, gi: acc + gi, g_stage, dstage_i)
        g_head = jax.tree.map(lambda acc, gi: acc + gi, g_head, dhead_i)
        loss = loss + loss_i / M
        # stage 0's input cotangent feeds the embedding backward
        dxs = jnp.where(
            valid_b & (my == 0),
            lax.dynamic_update_index_in_dim(dxs, dx_i.astype(dxs.dtype), idx_b, 0),
            dxs,
        )
        # the receiver uses this as a cotangent for ITS output (y dtype),
        # mirroring the forward slot's first_in cast
        send_b = lax.ppermute(dx_i.astype(recv_b.dtype), axis_name, bwd_perm)

        return send_f, send_b, inbuf, loss, g_stage, g_head, dxs

    _, _, _, loss, g_stage, g_head, dxs = lax.fori_loop(
        0, n_ticks, tick,
        (recv_f0, recv_b0, inbuf0, loss0, g_stage0, g_head0, dxs0),
    )
    # loss/head grads live on the last stage, dxs on stage 0: psum replicates
    loss = lax.psum(loss, axis_name)
    g_head = jax.tree.map(lambda a: lax.psum(a, axis_name), g_head)
    dxs = lax.psum(dxs, axis_name)
    # g_stage already has the local [L/P, ...] stack shape of the
    # P(axis_name) out_spec
    return loss, g_stage, g_head, dxs


def microbatch(x: jax.Array, n_microbatches: int) -> jax.Array:
    """[B, ...] -> [M, B/M, ...]."""
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by n_microbatches={n_microbatches}")
    return x.reshape(n_microbatches, B // n_microbatches, *x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    """[M, mb, ...] -> [M*mb, ...]."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


__all__ = [
    "microbatch", "pipeline_apply", "pipeline_local",
    "pipeline_train_1f1b", "unmicrobatch",
]

"""Pipeline parallelism: GPipe-style microbatching over a ``pp`` mesh axis.

Not present in the reference (SURVEY.md section 2 parallelism table: PP "—").
TPU-native design: stages are devices along a mesh axis, activations hop
stage-to-stage with ``lax.ppermute`` (one ICI neighbour hop), and the
microbatch schedule is a single ``lax.fori_loop`` — compiled once, no
data-dependent Python control flow. The bubble is the standard GPipe
(P-1)/(M+P-1) fraction; raise ``n_microbatches`` to amortise.

Usage (inside or outside jit):

    stages = stack_stage_params(per_stage_params)      # leading dim = P
    y = pipeline_apply(stage_fn, stages, x_microbatched, mesh=mesh)

where ``stage_fn(stage_params, x) -> y`` maps one microbatch through one
stage, and x_microbatched has shape [M, mb, ...].
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

StageFn = Callable[[Any, jax.Array], jax.Array]


def pipeline_local(
    stage_fn: StageFn,
    stage_params: Any,
    x: jax.Array,
    *,
    axis_name: str = "pp",
    with_aux: bool = False,
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Per-device GPipe schedule; call inside shard_map.

    ``x``: [M, mb, ...] microbatched input, replicated over the axis (only
    stage 0 reads it). Returns [M, mb, ...] outputs, replicated (the last
    stage's results are broadcast with a psum).

    ``with_aux``: stage_fn returns ``(y, aux_scalar)`` (e.g. a MoE
    load-balancing loss); real ticks' aux is accumulated per stage, summed
    over stages with a psum, and averaged over microbatches — the result is
    ``(out, aux)`` where aux matches the sequential trainer's
    sum-over-layers, mean-over-batch scalar.
    """
    n_stages = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    M = x.shape[0]
    n_ticks = M + n_stages - 1
    # stage s receives from s-1; the (n-1 -> 0) edge carries garbage that
    # stage 0 never reads (it pulls from x), but keeps the perm a bijection.
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def run_stage(params, batch):
        result = stage_fn(params, batch)
        return result if with_aux else (result, jnp.zeros((), jnp.float32))

    def probe_out():
        """Output structure for one microbatch (to size the buffers).

        The probe input must carry the same varying-axes type as the real
        per-tick input (pp-varying): a stage_fn that scans over pp-sharded
        layer params would otherwise fail vma typing at trace time.
        """
        xin = jax.tree.map(lambda a: lax.pcast(a, (axis_name,), to="varying"), x[0])
        return jax.eval_shape(lambda p, b: run_stage(p, b)[0], stage_params, xin)

    out_shape = probe_out()
    # pcast marks the zero buffers as device-varying along the pipeline axis
    # (jax>=0.9 shard_map typing: loop carries must match the outputs, which
    # become varying after ppermute/psum).
    recv0 = lax.pcast(
        jnp.zeros(out_shape.shape, out_shape.dtype), (axis_name,), to="varying"
    )
    out0 = lax.pcast(
        jnp.zeros((M, *out_shape.shape), out_shape.dtype), (axis_name,), to="varying"
    )
    aux0 = lax.pcast(jnp.zeros((), jnp.float32), (axis_name,), to="varying")

    def tick(t, carry):
        recv, out, aux_acc = carry
        feed_idx = jnp.clip(t, 0, M - 1)
        first_stage_in = lax.dynamic_index_in_dim(x, feed_idx, 0, keepdims=False)
        first_stage_in = lax.pcast(
            first_stage_in.astype(recv.dtype), (axis_name,), to="varying"
        )
        cur = jnp.where(my == 0, first_stage_in, recv)
        y, aux = run_stage(stage_params, cur)
        # stage s holds microbatch t-s at tick t; other ticks are warmup/
        # drain garbage whose aux must not pollute the accumulator
        valid = (t >= my) & (t - my < M)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
        updated = lax.dynamic_update_index_in_dim(out, y, out_idx, 0)
        out = jnp.where(t >= n_stages - 1, updated, out)
        recv = lax.ppermute(y, axis_name, perm)
        return recv, out, aux_acc

    _, out, aux_acc = lax.fori_loop(0, n_ticks, tick, (recv0, out0, aux0))
    # Broadcast the last stage's buffer to every stage.
    out = jnp.where(my == n_stages - 1, out, jnp.zeros_like(out))
    out = lax.psum(out, axis_name)
    if not with_aux:
        return out
    aux = lax.psum(aux_acc, axis_name) / M  # sum stages, mean microbatches
    return out, aux


def pipeline_apply(
    stage_fn: StageFn,
    stacked_stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = "pp",
) -> jax.Array:
    """Full-array entry: shard stage params over ``axis_name``, run the
    schedule, return outputs for all microbatches (replicated over the axis).

    ``stacked_stage_params``: pytree whose leaves have a leading stage dim of
    size mesh.shape[axis_name].
    """
    param_specs = jax.tree.map(lambda _: P(axis_name), stacked_stage_params)

    def body(params, xs):
        params = jax.tree.map(lambda a: a[0], params)  # drop unit stage dim
        return pipeline_local(stage_fn, params, xs, axis_name=axis_name)

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
    )(stacked_stage_params, x)


def microbatch(x: jax.Array, n_microbatches: int) -> jax.Array:
    """[B, ...] -> [M, B/M, ...]."""
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by n_microbatches={n_microbatches}")
    return x.reshape(n_microbatches, B // n_microbatches, *x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    """[M, mb, ...] -> [M*mb, ...]."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


__all__ = ["microbatch", "pipeline_apply", "pipeline_local", "unmicrobatch"]

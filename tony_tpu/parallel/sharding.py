"""Logical-axis sharding rules.

The scaling-book recipe: name every tensor dimension logically, map logical
names to mesh axes with a rules table, and let XLA insert the collectives.
Models annotate parameters with logical axis names (tuples of strings); this
module turns those into ``NamedSharding``s for a concrete mesh.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical dimension name -> mesh axis (or tuple of axes, or None = replicate)
Rules = Mapping[str, str | tuple[str, ...] | None]

# Default rules for a Megatron-sharded decoder transformer + FSDP:
#   - "embed"  (model dim)        sharded over fsdp  (ZeRO-style param shard)
#   - "heads"/"ffn" (wide dims)   sharded over tp
#   - "vocab"  sharded over tp    (output projection column-parallel)
#   - "batch"  over dp+fsdp+ep, "seq" over sp (activations)
#   - "expert" over ep            (GShard: the dispatch/combine einsums lower
#                                  to the expert all-to-all; ep doubles as a
#                                  batch axis for the non-expert layers)
#   - "layers" replicated; the PP train step overrides it to "pp" (stage dim)
DEFAULT_RULES: Rules = {
    "batch": ("dp", "fsdp", "ep"),
    "seq": "sp",
    "embed": "fsdp",
    "heads": "tp",
    "kv_heads": "tp",
    "ffn": "tp",
    "vocab": "tp",
    "expert": "ep",
    "layers": None,
    "head_dim": None,
    "norm": None,
}


def spec_for(logical_axes: tuple[str | None, ...], rules: Rules = DEFAULT_RULES) -> P:
    """Translate a tuple of logical axis names into a PartitionSpec."""
    parts = []
    used: set[str] = set()
    for name in logical_axes:
        axis = rules.get(name) if name is not None else None
        # a mesh axis may appear at most once in a spec; later dims replicate
        if axis is None:
            parts.append(None)
        elif isinstance(axis, tuple):
            fresh = tuple(a for a in axis if a not in used)
            used.update(fresh)
            parts.append(fresh if fresh else None)
        elif axis in used:
            parts.append(None)
        else:
            used.add(axis)
            parts.append(axis)
    return P(*parts)


def overlap_gather_dim(
    logical_axes: tuple[str | None, ...],
    rules: Rules = DEFAULT_RULES,
    mesh_axis: str = "fsdp",
) -> int | None:
    """Which positional dim of a weight the rules shard over ``mesh_axis``
    — the dim the decomposed all-gather-matmul ring rotates
    (tony_tpu.ops.overlap). None when the weight carries no shard on that
    axis (nothing to overlap) or more than one dim maps to it (the ring
    decomposition assumes a single gathered dim).
    """
    dims = []
    for i, name in enumerate(logical_axes):
        axis = rules.get(name) if name is not None else None
        axes = axis if isinstance(axis, tuple) else (axis,)
        if mesh_axis in axes:
            dims.append(i)
    return dims[0] if len(dims) == 1 else None


def attn_spec(mesh: Mesh, seq_axis: str | None = None) -> P:
    """PartitionSpec for [B, S, H, head_dim] attention activations.

    Batch over dp/fsdp, heads over tp (each only if present in the mesh),
    sequence over ``seq_axis`` when given (ring/Ulysses context parallelism).
    Shared by every AttnFn wrapper so the sharding policy lives in one place.
    """
    axes = set(mesh.axis_names)
    batch = tuple(a for a in ("dp", "fsdp", "ep") if a in axes) or None
    heads = "tp" if "tp" in axes else None
    seq = seq_axis if seq_axis in axes else None
    return P(batch, seq, heads, None)


def tree_shardings(
    logical_tree: Any, mesh: Mesh, rules: Rules = DEFAULT_RULES
) -> Any:
    """Map a pytree of logical-axes tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, spec_for(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def tree_specs(logical_tree: Any, rules: Rules = DEFAULT_RULES) -> Any:
    """Map a pytree of logical-axes tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: spec_for(axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )

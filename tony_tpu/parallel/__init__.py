"""Parallelism library: mesh, sharding rules, DP/FSDP/TP/SP/PP/EP.

The training-side layer the reference delegated to user frameworks
(SURVEY.md section 2 "Parallelism strategies"), built TPU-first: one mesh,
logical-axis sharding rules, and compiled XLA collectives.
"""

from tony_tpu.parallel.mesh import (
    MESH_AXES,
    MeshShape,
    build_mesh,
    build_multislice_mesh,
    default_shape,
    get_default_mesh,
    set_default_mesh,
    single_device_mesh,
)
from tony_tpu.parallel.moe import MoEConfig, init_moe_params, moe_block
from tony_tpu.parallel.pipeline import (
    pipeline_train_1f1b,
    microbatch,
    pipeline_apply,
    pipeline_local,
    unmicrobatch,
)
from tony_tpu.parallel.ring_attention import (
    make_ring_attention,
    make_ring_flash_attention,
    ring_attention,
    ring_attention_local,
)
from tony_tpu.parallel.sharding import DEFAULT_RULES, Rules, spec_for, tree_shardings
from tony_tpu.parallel.ulysses import make_ulysses_attention, ulysses_attention_local

__all__ = [
    "DEFAULT_RULES",
    "MESH_AXES",
    "MeshShape",
    "MoEConfig",
    "Rules",
    "build_mesh",
    "build_multislice_mesh",
    "default_shape",
    "get_default_mesh",
    "init_moe_params",
    "make_ring_attention",
    "make_ring_flash_attention",
    "make_ulysses_attention",
    "microbatch",
    "moe_block",
    "pipeline_apply",
    "pipeline_train_1f1b",
    "pipeline_local",
    "ring_attention",
    "ring_attention_local",
    "set_default_mesh",
    "single_device_mesh",
    "spec_for",
    "tree_shardings",
    "ulysses_attention_local",
    "unmicrobatch",
]

"""Chaos: fault injection + recovery-invariant checking (docs/CHAOS.md).

Two halves:

- :mod:`tony_tpu.chaos.faults` — a declarative, config-driven fault
  schedule (``chaos.*`` keys) fired through cheap hooks in the AM,
  executors, lease store, RPC server, and backends. Hooks are strict
  no-ops unless the process explicitly arms an injector.
- :mod:`tony_tpu.chaos.invariants` — a post-mortem checker that reads a
  finished job's artifacts (status.json, the .jhist journal, the shared
  lease store) and asserts the recovery contract: client-visible terminal
  status, no stranded leases past TTL, no double-booked host capacity,
  monotonic restart generations.

:mod:`tony_tpu.chaos.runner` + ``tony chaos`` run a real job under a
seeded schedule and emit the invariant report — converting recovery bugs
from "found by reading" into "caught by CI".

Only the hook surface is imported here; the checker/runner import heavier
modules and load lazily at their call sites.
"""

from tony_tpu.chaos.faults import (
    ChaosInjector,
    FaultSpec,
    POINTS,
    active_injector,
    chaos_hook,
    install_from_config,
    parse_faults,
    uninstall,
)

__all__ = [
    "ChaosInjector",
    "FaultSpec",
    "POINTS",
    "active_injector",
    "chaos_hook",
    "install_from_config",
    "parse_faults",
    "uninstall",
]

"""Post-mortem recovery-invariant checker.

After a (chaos or plain) job finishes, this module re-reads the durable
artifacts the orchestration layer is contractually obliged to leave
behind and verifies the recovery contract — machine-checkable versions of
the guarantees the module docstrings promise in prose:

``terminal-status``
    Every checked job reached a client-visible terminal status:
    ``status.json`` exists, its state is SUCCEEDED/FAILED/KILLED, and the
    exit code is consistent (0 iff SUCCEEDED). This is the invariant the
    fence-path wedge (ADVICE round 5, medium) violated: an AM that hangs
    in teardown never writes the file.

``events-complete``
    The .jhist journal carries an APPLICATION_FINISHED whose state
    matches ``status.json`` — history consumers (portal, latency
    tooling) must never see a job that just stops mid-journal.

``generation-monotonic``
    Restart generations recorded in the journal (GANG_RESTART
    ``generation``, AM-recovery METADATA ``recovered_generation``)
    strictly increase: a generation reuse would let ghost executors from
    a previous incarnation poison the new gang's barrier.

``lease-no-strand``
    No lease-store entry of a terminal job outlives it past reclaim:
    every surviving entry must be reapable — owner provably dead on this
    host (pid reaping catches it on the next locked op) or TTL-expiring
    (survivors reap at ``renewed_at + ttl``). A live owner still holding
    leases for a finished job, or a TTL-less entry with an unreachable
    owner, is a stranded chip.

``lease-no-double-book``
    Per host, the sum of all apps' leased resources never exceeds the
    host's registered capacity — two owners can never hold the same slot.

``lease-events-audit``
    The store's grow/shrink event log (elastic grow-back, serve
    autoscale) is well-formed: every event names its op, owner, app and a
    registered host, in order — the audit trail that makes a capacity
    change attributable after the fact.

``health-verdict-surfaced``
    A job whose numerics sentinel tripped (obs/health.py wrote a
    ``tripped`` verdict under ``<app_dir>/health/``) must not report
    clean: the trip is either the silent-ruin case (job SUCCEEDED on
    NaN'd numbers) or the cause-of-death a restart decision needs — in
    both cases the post-mortem surfaces it as a violation, never swallows
    it into an all-clear.

``slo-surfaced``
    A job whose SLO engine tripped (obs/slo.py wrote a ``tripped``
    verdict under ``<app_dir>/slo/``) must not report clean: a burned
    error budget — TTFT collapse under a partitioned host, goodput below
    floor through a restart — is the contract violation the chaos run
    exists to surface, and the post-mortem must say so even when the job
    itself SUCCEEDED.

``serve-no-request-lost``
    Over every serving ledger the gang frontend left under
    ``<app_dir>/serve/`` (docs/SERVE.md "Gang serving"): every ACCEPTED
    request completed (finish_reason eos/length, with tokens) — a host
    killed mid-stream must have had its in-flight requests re-queued and
    re-prefilled on a survivor, never dropped — and every replay was
    draw-for-draw deterministic (``replay_consistent``): the regenerated
    prefix matched what was already delivered. Explicit admission
    rejections are NOT losses; silent disappearance is.

``serve-ttft-bounded``
    When the ledger records a TTFT contract (``serve.gang.ttft_budget_s``
    > 0), no completed request's time-to-first-token exceeded it — the
    bounded-TTFT-under-kill serving contract.

``handoff-no-block-leak``
    Over every blockwise KV handoff the frontend ledgered (disaggregated
    prefill pools, docs/SERVE.md "Disaggregated serving"): a successful
    handoff accounted for every shipped block on the adopter
    (``shipped == adopted + freed`` — an adopter that silently dropped a
    block would leak it from the refcounted pool), and a failed handoff
    (prefill host killed mid-ship, adopter refused the payload) stranded
    nothing: the request must still have completed via re-prefill on the
    decode host.

``elastic-no-data-loss``
    Over every elastic journal (``<app_dir>/elastic/journal_m*.jsonl``,
    docs/ELASTIC.md): the consumed step sequence is contiguous (no batch
    repeated, none skipped), membership changes only at declared reshard
    boundaries, every gap in a member's participation is exactly covered
    by a declared skip range, and no two consecutive recorded batch
    fingerprints repeat — the machine-checkable form of "the stream
    skipped exactly the dead host's unconsumed batches".

``elastic-loss-continuity``
    At every reshard boundary the post-boundary losses stay within the
    journal's declared tolerance of the pre-boundary window (mean +
    max(z·std, frac·|mean|)) and remain finite: survivors continued the
    SAME training run from in-memory state, not a degraded restart. The
    tolerance is read from the journal's meta record — the post-mortem
    judges by the contract the trainer declared, never one it invents.

The checker reads the store's ``state.json`` RAW (no LeaseStore handle):
going through the store would run its reapers and destroy the evidence.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any

from tony_tpu.am.events import EventType, read_history
from tony_tpu.cluster.lease import STATE_FILE, _pid_alive, _this_host
from tony_tpu.obs.health import read_verdicts
from tony_tpu.obs.slo import read_verdicts as read_slo_verdicts

TERMINAL_STATES = ("SUCCEEDED", "FAILED", "KILLED")


@dataclass(frozen=True)
class Violation:
    invariant: str
    subject: str  # app id / host / store entry the violation is about
    detail: str

    def to_dict(self) -> dict[str, str]:
        return {"invariant": self.invariant, "subject": self.subject, "detail": self.detail}


@dataclass
class InvariantReport:
    checked_apps: list[str] = field(default_factory=list)
    violations: list[Violation] = field(default_factory=list)
    notes: list[str] = field(default_factory=list)  # non-fatal observations

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict[str, Any]:
        return {
            "ok": self.ok,
            "checked_apps": list(self.checked_apps),
            "violations": [v.to_dict() for v in self.violations],
            "notes": list(self.notes),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)


def _read_status(app_dir: str) -> dict | None:
    path = os.path.join(app_dir, "status.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _read_events(app_dir: str) -> list[dict]:
    ev_dir = os.path.join(app_dir, "events")
    if not os.path.isdir(ev_dir):
        return []
    events: list[dict] = []
    for name in sorted(os.listdir(ev_dir)):
        if name.endswith(".jhist.jsonl"):
            events.extend(read_history(os.path.join(ev_dir, name)))
    return events


def _check_job(app_dir: str, report: InvariantReport) -> tuple[str, str]:
    """Check one finished job's artifacts; returns (app_id, state)."""
    app_id = os.path.basename(os.path.abspath(app_dir).rstrip("/"))
    report.checked_apps.append(app_id)
    status = _read_status(app_dir)
    if status is None:
        report.violations.append(
            Violation(
                "terminal-status", app_id,
                "no status.json: the client can never learn this job's outcome "
                "(AM wedged or died before _write_status)",
            )
        )
        return app_id, ""
    state = str(status.get("state", ""))
    code = status.get("exit_code")
    if state not in TERMINAL_STATES:
        report.violations.append(
            Violation("terminal-status", app_id, f"non-terminal final state {state!r}")
        )
    if state == "SUCCEEDED" and code != 0:
        report.violations.append(
            Violation("terminal-status", app_id, f"SUCCEEDED with exit_code {code!r}")
        )
    if state in ("FAILED", "KILLED") and code == 0:
        report.violations.append(
            Violation("terminal-status", app_id, f"{state} with exit_code 0")
        )

    events = _read_events(app_dir)
    finished = [e for e in events if e.get("type") == EventType.APPLICATION_FINISHED]
    if not finished:
        report.violations.append(
            Violation("events-complete", app_id, "no APPLICATION_FINISHED in the .jhist journal")
        )
    elif state and finished[-1].get("state") != state:
        report.violations.append(
            Violation(
                "events-complete", app_id,
                f"journal final state {finished[-1].get('state')!r} != status.json {state!r}",
            )
        )

    # journal order is emit order; restarts of either kind must never
    # reuse or rewind a generation — collect in ONE pass so a gang restart
    # after an AM recovery compares against it, not past it
    generations = []
    for e in events:
        if e.get("type") == EventType.GANG_RESTART and "generation" in e:
            generations.append(e["generation"])
        elif e.get("type") == EventType.METADATA and "recovered_generation" in e:
            generations.append(e["recovered_generation"])
    for prev, cur in zip(generations, generations[1:]):
        if cur <= prev:
            report.violations.append(
                Violation(
                    "generation-monotonic", app_id,
                    f"restart generation went {prev} -> {cur} (sequence {generations})",
                )
            )
            break

    # a tripped numerics verdict must reach the post-mortem reader: a
    # SUCCEEDED job hid a ruined run, a FAILED/KILLED one died of (or
    # with) bad numbers — either way the report cannot be clean
    tripped = {
        proc: v for proc, v in read_verdicts(app_dir).items()
        if v.get("verdict") == "tripped"
    }
    if tripped:
        rules = sorted({
            rule for v in tripped.values() for rule in (v.get("rules") or {})
        })
        what = (
            "job SUCCEEDED while the numerics verdict tripped — a silently "
            "ruined run reported clean"
            if state == "SUCCEEDED"
            else f"job ended {state or 'without status'} with a tripped "
            "numerics verdict — the restart decision needs the health "
            "forensics, not an all-clear"
        )
        report.violations.append(
            Violation(
                "health-verdict-surfaced", app_id,
                f"{what} (rules: {', '.join(rules)}; procs: "
                f"{', '.join(sorted(tripped))})",
            )
        )
    # a tripped SLO verdict is the same class of evidence as a tripped
    # numerics verdict: the run burned its error budget, and a clean
    # post-mortem would bury exactly the contract the SLO declares
    tripped_slo = {
        proc: v for proc, v in read_slo_verdicts(app_dir).items()
        if v.get("verdict") == "tripped"
    }
    if tripped_slo:
        names = sorted({
            name for v in tripped_slo.values() for name in (v.get("slos") or {})
        })
        report.violations.append(
            Violation(
                "slo-surfaced", app_id,
                f"job ended {state or 'without status'} with tripped SLO(s) "
                f"{', '.join(names)} (procs: {', '.join(sorted(tripped_slo))})"
                " — the burn-rate verdict must reach the post-mortem, never "
                "an all-clear",
            )
        )
    _check_serve_ledgers(app_dir, app_id, report)
    _check_elastic(app_dir, app_id, report)
    return app_id, state


def _member_gaps(steps: list[dict], member: int) -> list[tuple[int, int]]:
    """[from, to) step ranges inside the journal where ``member`` was NOT
    in the membership (the intervals a skip declaration must cover)."""
    gaps: list[tuple[int, int]] = []
    start = None
    for rec in steps:
        absent = member not in rec.get("members", [])
        if absent and start is None:
            start = rec["step"]
        elif not absent and start is not None:
            gaps.append((start, rec["step"]))
            start = None
    if start is not None:
        gaps.append((start, steps[-1]["step"] + 1))
    return gaps


def _check_elastic(app_dir: str, app_id: str, report: InvariantReport) -> None:
    """Audit the elastic trainer journals: no data repeated or lost across
    generation boundaries, loss trajectory continuous through them."""
    from tony_tpu.elastic.protocol import (
        DEFAULT_TOLERANCE, journal_files, read_journal,
    )

    for path in journal_files(app_dir):
        recs = read_journal(path)
        subject = f"{app_id}/{os.path.basename(path)}"
        meta = next((r for r in recs if r.get("type") == "meta"), {})
        tol = {**DEFAULT_TOLERANCE, **(meta.get("tolerance") or {})}
        steps = [r for r in recs if r.get("type") == "step"]
        reshards = [r for r in recs if r.get("type") == "reshard"]
        losses = [r for r in recs if r.get("type") == "loss"]
        if not steps:
            continue

        # --- elastic-no-data-loss ------------------------------------------
        for a, b in zip(steps, steps[1:]):
            if b["step"] == a["step"] + 1:
                continue
            what = "repeated" if b["step"] <= a["step"] else "skipped"
            report.violations.append(
                Violation(
                    "elastic-no-data-loss", subject,
                    f"step sequence {what} data: {a['step']} -> {b['step']}",
                )
            )
            break
        boundaries = {r.get("at_step") for r in reshards}
        for a, b in zip(steps, steps[1:]):
            if (set(a.get("members", [])) != set(b.get("members", []))
                    and b["step"] not in boundaries):
                report.violations.append(
                    Violation(
                        "elastic-no-data-loss", subject,
                        f"membership changed {a.get('members')} -> "
                        f"{b.get('members')} at step {b['step']} without a "
                        "declared reshard boundary",
                    )
                )
                break
        # every member's absence must be exactly a declared skip range
        # (open ranges -1 close at the journal's end)
        # a shrink declares an OPEN range ([from, -1]); the matching grow
        # re-declares it closed with the same start — journal order wins,
        # and a still-open range closes at the journal's end
        declared: dict[int, dict[int, int]] = {}
        end_step = steps[-1]["step"] + 1
        for r in reshards:
            for m, rng in (r.get("skipped") or {}).items():
                declared.setdefault(int(m), {})[int(rng[0])] = int(rng[1])
        members_seen = {m for rec in steps for m in rec.get("members", [])}
        members_seen |= set(declared)
        for m in sorted(members_seen):
            gaps = _member_gaps(steps, m)
            merged = sorted(
                (lo, end_step if hi < 0 else min(hi, end_step))
                for lo, hi in declared.get(m, {}).items()
                if lo < end_step
            )
            if gaps != merged:
                report.violations.append(
                    Violation(
                        "elastic-no-data-loss", subject,
                        f"member {m}: journal gaps {gaps} != declared "
                        f"skip ranges {merged} — data silently lost or "
                        "skipped without declaration",
                    )
                )
        fps = [(r["step"], r["fp"]) for r in losses if "fp" in r]
        for (s0, f0), (s1, f1) in zip(fps, fps[1:]):
            if f0 == f1:
                report.violations.append(
                    Violation(
                        "elastic-no-data-loss", subject,
                        f"batch fingerprint repeated across steps {s0} -> "
                        f"{s1} (fp={f1}): the stream replayed data",
                    )
                )
                break

        # --- elastic-loss-continuity ---------------------------------------
        window = int(tol.get("window", 8))
        for r in reshards:
            at = r.get("at_step", 0)
            before = [x["loss"] for x in losses if x["step"] < at][-window:]
            after = [x["loss"] for x in losses if x["step"] >= at]
            after = after[: max(window // 2, 1)]
            if not before or not after:
                report.notes.append(
                    f"{subject}: reshard at step {at} has too few recorded "
                    "losses to judge continuity"
                )
                continue
            if any(x != x or x in (float("inf"), float("-inf")) for x in after):
                report.violations.append(
                    Violation(
                        "elastic-loss-continuity", subject,
                        f"non-finite loss after the generation boundary at "
                        f"step {at}",
                    )
                )
                continue
            mean_b = sum(before) / len(before)
            var = (
                sum((x - mean_b) ** 2 for x in before) / (len(before) - 1)
                if len(before) > 1 else 0.0
            )
            bound = mean_b + max(
                float(tol.get("z", 4.0)) * var ** 0.5,
                float(tol.get("frac", 0.25)) * abs(mean_b),
            )
            mean_a = sum(after) / len(after)
            if mean_a > bound:
                report.violations.append(
                    Violation(
                        "elastic-loss-continuity", subject,
                        f"loss discontinuity at the generation boundary "
                        f"(step {at}): post-boundary mean {mean_a:.4f} "
                        f"exceeds the declared tolerance bound {bound:.4f} "
                        f"(pre-boundary mean {mean_b:.4f})",
                    )
                )


def _check_serve_ledgers(app_dir: str, app_id: str, report: InvariantReport) -> None:
    """Audit the gang frontend's request ledgers (serve/frontend.py):
    no accepted request lost, replays deterministic, TTFT under contract."""
    serve_dir = os.path.join(app_dir, "serve")
    if not os.path.isdir(serve_dir):
        return
    names = sorted(
        n for n in os.listdir(serve_dir)
        if n.startswith("requests_") and n.endswith(".json")
    )
    for name in names:
        try:
            with open(os.path.join(serve_dir, name)) as f:
                ledger = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            report.violations.append(
                Violation(
                    "serve-no-request-lost", app_id,
                    f"unreadable serve ledger {name}: {e}",
                )
            )
            continue
        subject = f"{app_id}/{name}"
        budget = float(ledger.get("ttft_budget_s", 0) or 0)
        completed = {
            e.get("rid") for e in ledger.get("requests", [])
            if e.get("finish_reason") in ("eos", "length")
        }
        for h in ledger.get("handoffs", []):
            rid = h.get("rid", "?")
            if h.get("ok"):
                shipped = int(h.get("shipped", 0) or 0)
                adopted = int(h.get("adopted", 0) or 0)
                freed = int(h.get("freed", 0) or 0)
                if shipped != adopted + freed:
                    report.violations.append(
                        Violation(
                            "handoff-no-block-leak", subject,
                            f"handoff for {rid} shipped {shipped} block(s) "
                            f"but the adopter accounted for "
                            f"{adopted} adopted + {freed} freed — the "
                            "difference leaked from the refcounted pool",
                        )
                    )
            elif rid not in completed:
                report.violations.append(
                    Violation(
                        "handoff-no-block-leak", subject,
                        f"handoff for {rid} failed "
                        f"({h.get('message', '') or 'no message'}) and the "
                        "request never completed — a dead prefill host must "
                        "strand nothing: the decode host re-prefills",
                    )
                )
        for rid in ledger.get("pending", []):
            report.violations.append(
                Violation(
                    "serve-no-request-lost", subject,
                    f"request {rid} was accepted but never completed "
                    "(still pending at ledger time)",
                )
            )
        for entry in ledger.get("requests", []):
            rid = entry.get("rid", "?")
            reason = entry.get("finish_reason", "")
            if reason in ("rejected", "draining"):
                continue  # explicit backpressure, not a loss
            if reason not in ("eos", "length") or not entry.get("tokens"):
                report.violations.append(
                    Violation(
                        "serve-no-request-lost", subject,
                        f"request {rid} ended {reason or 'nowhere'} with "
                        f"{entry.get('tokens', 0)} token(s): "
                        f"{entry.get('message', '')}",
                    )
                )
                continue
            if not entry.get("replay_consistent", True):
                report.violations.append(
                    Violation(
                        "serve-no-request-lost", subject,
                        f"request {rid} replayed NON-deterministically "
                        f"(the regenerated prefix diverged after "
                        f"{entry.get('replays', 0)} replay(s))",
                    )
                )
            if budget > 0 and float(entry.get("ttft_s", 0.0)) > budget:
                report.violations.append(
                    Violation(
                        "serve-ttft-bounded", subject,
                        f"request {rid} TTFT {entry.get('ttft_s')}s exceeds "
                        f"the {budget}s contract",
                    )
                )


def _check_store(rm_root: str, terminal_apps: dict[str, str], report: InvariantReport) -> None:
    """Raw-read the lease store and apply the strand/double-book rules."""
    state_path = os.path.join(os.path.abspath(os.path.expanduser(rm_root)), STATE_FILE)
    if not os.path.exists(state_path):
        report.notes.append(f"lease store {rm_root}: no state file (never used)")
        return
    with open(state_path) as f:
        try:
            store = json.load(f)
        except json.JSONDecodeError as e:
            report.violations.append(
                Violation("lease-no-strand", rm_root, f"unreadable store state: {e}")
            )
            return
    here = _this_host()
    now = time.time()

    def reclaimable(entry: dict) -> str:
        """Why this entry will be reclaimed without an operator ('' = never)."""
        if entry.get("owner_host") == here and not _pid_alive(
            int(entry.get("owner_pid", 0)), int(entry.get("owner_start", 0))
        ):
            return "owner dead on this host (pid reap on next store access)"
        ttl = float(entry.get("ttl_s", 0) or 0)
        if ttl > 0:
            lapse = now - float(entry.get("renewed_at", 0) or 0)
            if entry.get("owner_host") == here:
                # owner alive here: local liveness blocks TTL reaping
                return ""
            return f"TTL reaping due in {max(ttl - lapse, 0.0):.0f}s"
        return ""

    for app_id, app in store.get("apps", {}).items():
        if app_id not in terminal_apps:
            continue  # another tenant's live job: not ours to judge
        why = reclaimable(app)
        if why:
            report.notes.append(f"store entry {app_id}: reclaimable ({why})")
        else:
            report.violations.append(
                Violation(
                    "lease-no-strand", app_id,
                    f"leases outlive terminal job ({terminal_apps[app_id]}) with no "
                    f"reclaim path: owner {app.get('owner_host')}:{app.get('owner_pid')} "
                    f"ttl_s={app.get('ttl_s')}",
                )
            )
    for t in store.get("queue", []):
        app_id = t.get("app_id", "")
        if app_id in terminal_apps and not reclaimable(t):
            report.violations.append(
                Violation(
                    "lease-no-strand", app_id,
                    f"queue ticket seq={t.get('seq')} outlives terminal job with no reclaim path",
                )
            )

    # grow/shrink audit trail (LeaseStore._emit_event): every elastic /
    # autoscale capacity change must name an owner and a registered host,
    # in order — an event that fails this is a store whose accounting can
    # no longer be trusted by the double-book check below
    hosts = store.get("hosts", {})
    last_ts = 0.0
    for i, ev in enumerate(store.get("events", [])):
        what = ""
        if not isinstance(ev, dict) or ev.get("op") not in ("grow", "shrink"):
            what = f"malformed op {ev!r}"
        elif not ev.get("app_id") or not ev.get("owner"):
            what = "missing app_id/owner attribution"
        elif ev.get("host") not in hosts:
            what = f"unregistered host {ev.get('host')!r}"
        elif float(ev.get("ts", 0) or 0) + 1.0 < last_ts:
            what = "events out of order"
        if what:
            report.violations.append(
                Violation("lease-events-audit", rm_root, f"event[{i}]: {what}")
            )
            break
        last_ts = max(last_ts, float(ev.get("ts", 0) or 0))

    leased: dict[str, list[int]] = {h: [0, 0, 0] for h in hosts}
    for app_id, app in store.get("apps", {}).items():
        for gang in app.get("gangs", []):
            for ask, host in zip(gang.get("asks", []), gang.get("hosts", [])):
                if host in leased:
                    leased[host][0] += int(ask.get("memory_mb", 0))
                    leased[host][1] += int(ask.get("cpus", 0))
                    leased[host][2] += int(ask.get("tpu_chips", 0))
    for host, (mem, cpus, chips) in leased.items():
        cap = hosts[host]
        if (
            mem > int(cap.get("memory_mb", 0))
            or cpus > int(cap.get("cpus", 0))
            or chips > int(cap.get("tpu_chips", 0))
        ):
            report.violations.append(
                Violation(
                    "lease-no-double-book", host,
                    f"leased (mem={mem} cpus={cpus} chips={chips}) exceeds registered "
                    f"capacity (mem={cap.get('memory_mb')} cpus={cap.get('cpus')} "
                    f"chips={cap.get('tpu_chips')})",
                )
            )


def check_invariants(app_dirs: list[str] | str, rm_root: str = "") -> InvariantReport:
    """Verify the recovery contract over finished application dir(s) and,
    when ``rm_root`` is given, the shared lease store they ran against."""
    if isinstance(app_dirs, str):
        app_dirs = [app_dirs]
    report = InvariantReport()
    terminal_apps: dict[str, str] = {}
    for d in app_dirs:
        app_id, state = _check_job(d, report)
        if state in TERMINAL_STATES:
            terminal_apps[app_id] = state
    if rm_root:
        _check_store(rm_root, terminal_apps, report)
    return report


__all__ = ["InvariantReport", "Violation", "check_invariants", "TERMINAL_STATES"]

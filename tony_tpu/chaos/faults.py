"""Declarative fault schedules and the process-global injector.

Recovery code is only as good as the failures it has actually seen
(ROADMAP north star; ADVICE.md round 5 found four failure-window bugs in
freshly-reviewed lease code). This module makes faults first-class:
a config-driven schedule (``chaos.*`` keys) of deterministic faults fired
from cheap hooks compiled into the hot paths — AM supervision, executor
heartbeats, lease-store access, RPC dispatch, container allocation.

The contract that keeps this safe to ship in production binaries:

- ``chaos_hook(point, **ctx)`` is the ONLY runtime surface. When no
  injector is armed (the default — ``chaos.enabled`` false or absent) it
  is a single global-load + ``None`` compare and returns ``None``.
- An injector is armed explicitly per process (``install_from_config`` in
  the AM / executor entrypoints), never as an import side effect, so
  client processes and library consumers can never trip a fault.
- Fault firing is deterministic: triggers are invocation counts per hook
  point and wall-time windows since arming; the only randomness (delay
  jitter) comes from a seeded RNG (``chaos.seed``).

Fault types (point they attach to):

====================  =================  =======================================
type                  point              effect
====================  =================  =======================================
``kill_container``    executor.beat      SIGKILL the executor's process group
``kill_am``           am.tick            SIGKILL the AM process mid-supervision
``hang_store``        lease.locked       block lease-store open/flock for
                                         ``duration_s`` (hard-mount hang)
``partition_host``    lease.locked       raise OSError from store access in
                                         THIS process only (one-owner partition)
``drop_heartbeats``   executor.beat      suppress executor→AM heartbeats
``delay_rpc``         rpc.server         sleep ``delay_ms`` (+ seeded jitter)
                                         before serving a control-plane RPC
``delay_point``       (explicit)         generic latency at any hook point,
                                         e.g. ``backend.allocate``
====================  =================  =======================================
"""

from __future__ import annotations

import json
import logging
import os
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Mapping

from tony_tpu.config.keys import Keys

log = logging.getLogger(__name__)

# hook points wired into the codebase (see module docstring table)
POINTS = (
    "am.tick",            # each AM supervision-loop iteration
    "executor.beat",      # each executor heartbeat-loop iteration
    "lease.locked",       # before each LeaseStore open/flock
    "rpc.server",         # before each served control-plane RPC
    "backend.allocate",   # before each container launch
    "serve.handoff",      # after KV-block export, before ShipBlocks lands
)

_POINT_OF_TYPE = {
    "kill_container": "executor.beat",
    "kill_am": "am.tick",
    "hang_store": "lease.locked",
    "partition_host": "lease.locked",
    "drop_heartbeats": "executor.beat",
    "delay_rpc": "rpc.server",
    "delay_point": "",  # must name its point explicitly
}

_DEFAULT_ROLE = {
    "kill_container": "executor",
    "kill_am": "am",
    "hang_store": "am",
    "partition_host": "am",
    "drop_heartbeats": "executor",
}


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault. Triggers compose with AND: the fault fires only
    when every specified count/time window matches. Counts are 1-based
    per-point invocation counts inside the armed process."""

    type: str
    point: str
    role: str = ""            # only fire in processes armed with this role
    task: str = ""            # executor filter, e.g. "worker:0"
    method: str = ""          # rpc method filter, e.g. "Heartbeat"
    attempt: int | None = None  # task attempt / AM attempt filter
    at_count: int = 0         # fire exactly at the Nth hook invocation
    from_count: int = 0       # fire from the Nth invocation onward...
    to_count: int = 0         # ...up to this one (0 = no upper bound)
    after_s: float = 0.0      # fire only this long after arming...
    until_s: float = 0.0      # ...and before this (0 = no upper bound)
    # condition trigger: fire only once this file exists — the test/driver
    # creates it when the system reaches the state under attack (e.g. "kill
    # the decode host only once its streams are provably mid-flight"),
    # which count/time triggers can only approximate racily
    on_file: str = ""
    duration_s: float = 30.0  # hang_store block length
    delay_ms: float = 0.0     # delay_rpc / delay_point latency
    jitter_ms: float = 0.0    # extra random latency from the seeded RNG
    raw: Mapping[str, Any] = field(default_factory=dict, compare=False)

    def describe(self) -> str:
        parts = [self.type, f"point={self.point}"]
        for name in ("role", "task", "method"):
            v = getattr(self, name)
            if v:
                parts.append(f"{name}={v}")
        if self.attempt is not None:
            parts.append(f"attempt={self.attempt}")
        for name in ("at_count", "from_count", "to_count"):
            v = getattr(self, name)
            if v:
                parts.append(f"{name}={v}")
        for name in ("after_s", "until_s"):
            v = getattr(self, name)
            if v:
                parts.append(f"{name}={v:g}")
        if self.on_file:
            parts.append(f"on_file={self.on_file}")
        return " ".join(parts)


def parse_faults(raw: Any) -> list[FaultSpec]:
    """Parse ``chaos.faults``: a JSON string, or an already-parsed list of
    dicts (TOML array / programmatic config). Raises ``ValueError`` on an
    unknown fault type or malformed spec — a schedule that silently drops
    faults would report a vacuous all-clear."""
    if raw is None or raw == "":
        return []
    if isinstance(raw, str):
        try:
            raw = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValueError(f"chaos.faults is not valid JSON: {e}") from e
    if isinstance(raw, Mapping):
        raw = [raw]
    if not isinstance(raw, list):
        raise ValueError(f"chaos.faults must be a list of fault objects, got {type(raw).__name__}")
    specs: list[FaultSpec] = []
    for i, d in enumerate(raw):
        if not isinstance(d, Mapping):
            raise ValueError(f"chaos.faults[{i}] must be an object, got {d!r}")
        ftype = str(d.get("type", ""))
        if ftype not in _POINT_OF_TYPE:
            raise ValueError(
                f"chaos.faults[{i}]: unknown fault type {ftype!r} "
                f"(expected one of {sorted(_POINT_OF_TYPE)})"
            )
        point = str(d.get("point", "") or _POINT_OF_TYPE[ftype])
        if not point:
            raise ValueError(f"chaos.faults[{i}]: fault type {ftype!r} needs an explicit 'point'")
        if point not in POINTS:
            raise ValueError(
                f"chaos.faults[{i}]: unknown hook point {point!r} (expected one of {POINTS})"
            )
        known = {
            "type", "point", "role", "task", "method", "attempt", "at_count",
            "from_count", "to_count", "after_s", "until_s", "duration_s",
            "delay_ms", "jitter_ms", "on_file",
        }
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"chaos.faults[{i}]: unknown field(s) {sorted(unknown)}")
        attempt = d.get("attempt", 0 if ftype in ("kill_container", "kill_am") else None)
        specs.append(
            FaultSpec(
                type=ftype,
                point=point,
                role=str(d.get("role", _DEFAULT_ROLE.get(ftype, ""))),
                task=str(d.get("task", "")),
                method=str(d.get("method", "")),
                attempt=None if attempt is None else int(attempt),
                at_count=int(d.get("at_count", 0)),
                from_count=int(d.get("from_count", 0)),
                to_count=int(d.get("to_count", 0)),
                after_s=float(d.get("after_s", 0.0)),
                until_s=float(d.get("until_s", 0.0)),
                duration_s=float(d.get("duration_s", 30.0)),
                delay_ms=float(d.get("delay_ms", 0.0)),
                jitter_ms=float(d.get("jitter_ms", 0.0)),
                on_file=str(d.get("on_file", "")),
                raw=dict(d),
            )
        )
    return specs


class ChaosInjector:
    """Evaluates the fault schedule at each hook invocation.

    One instance per armed process; hooks route here through the module
    global. Per-point invocation counters give deterministic count
    triggers (e.g. in an executor, ``executor.beat`` count == heartbeat
    number of that executor)."""

    def __init__(self, faults: list[FaultSpec], *, role: str, seed: int = 0):
        self.role = role
        self.faults = faults
        self._t0 = time.monotonic()
        self._counts: dict[str, int] = {}
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.fired: list[str] = []  # describe() of every fault that fired

    def fire(self, point: str, ctx: Mapping[str, Any]) -> FaultSpec | None:
        with self._lock:
            count = self._counts.get(point, 0) + 1
            self._counts[point] = count
        now = time.monotonic() - self._t0
        suppressed: FaultSpec | None = None
        for f in self.faults:
            if f.point != point or not self._matches(f, ctx, count, now):
                continue
            self._act(f, count, now)
            if f.type == "drop_heartbeats":
                suppressed = f
        return suppressed

    def _matches(self, f: FaultSpec, ctx: Mapping[str, Any], count: int, now: float) -> bool:
        if f.role and f.role != self.role:
            return False
        if f.task and f.task != ctx.get("task"):
            return False
        if f.method and f.method != ctx.get("method"):
            return False
        if f.attempt is not None and ctx.get("attempt") is not None and f.attempt != ctx["attempt"]:
            return False
        if f.at_count and count != f.at_count:
            return False
        if f.from_count and count < f.from_count:
            return False
        if f.to_count and count > f.to_count:
            return False
        if f.after_s and now < f.after_s:
            return False
        if f.until_s and now > f.until_s:
            return False
        if f.on_file and not os.path.exists(f.on_file):
            return False
        return True

    def _act(self, f: FaultSpec, count: int, now: float) -> None:
        with self._lock:
            self.fired.append(f.describe())
        # the injected fault lands on the shared trace timeline as an
        # instant event, so a post-mortem sees it BETWEEN the spans it
        # interrupted (no-op when this process is untraced)
        from tony_tpu.obs import trace

        trace.instant(
            f"chaos.{f.type}", point=f.point, count=count, fault=f.describe()
        )
        if f.type in ("kill_container", "kill_am"):
            # log + flush first: the kill is immediate and unhandled — the
            # trace journal must land NOW, including the spans still OPEN
            # (they are what the fault interrupts; they die with the process)
            log.warning("chaos: firing %s (count=%d t=%.2fs) — SIGKILL", f.describe(), count, now)
            trace.emergency_flush()
            for h in logging.getLogger().handlers:
                try:
                    h.flush()
                except Exception:
                    pass
            if f.type == "kill_container":
                # the executor is its process group's leader
                # (start_new_session): take the user process down with it,
                # exactly like an OOM-killed container
                os.killpg(os.getpgrp(), signal.SIGKILL)
            else:
                os.kill(os.getpid(), signal.SIGKILL)
        elif f.type == "hang_store":
            log.warning("chaos: firing %s — blocking %.1fs", f.describe(), f.duration_s)
            time.sleep(f.duration_s)
        elif f.type == "partition_host":
            log.warning("chaos: firing %s — store unreachable", f.describe())
            raise OSError(f"chaos: lease store partitioned from this owner ({f.describe()})")
        elif f.type in ("delay_rpc", "delay_point"):
            delay = f.delay_ms
            if f.jitter_ms:
                with self._lock:
                    delay += self._rng.uniform(0.0, f.jitter_ms)
            time.sleep(delay / 1000.0)
        # drop_heartbeats: no side effect here; fire() returns it and the
        # call site skips its send


# --- process-global arming ---------------------------------------------------

_injector: ChaosInjector | None = None


def chaos_hook(point: str, **ctx: Any) -> FaultSpec | None:
    """The injection seam compiled into hot paths. Disarmed (the default):
    one global load + None compare, returns None. Armed: evaluates the
    schedule; side-effect faults act in place, suppression faults are
    returned for the call site to honour."""
    inj = _injector
    if inj is None:
        return None
    return inj.fire(point, ctx)


def install_from_config(config, role: str) -> bool:
    """Arm this process from ``chaos.*`` config. Returns True when armed.
    Strictly inert unless ``chaos.enabled`` is true AND the schedule is
    non-empty; call sites (AM / executor entrypoints) pay one config read."""
    if not config.get_bool(Keys.CHAOS_ENABLED, False):
        return False
    faults = parse_faults(config.get(Keys.CHAOS_FAULTS))
    if not faults:
        return False
    global _injector
    _injector = ChaosInjector(
        faults, role=role, seed=config.get_int(Keys.CHAOS_SEED, 0)
    )
    log.warning(
        "chaos injector ARMED (role=%s, seed=%d): %s",
        role,
        config.get_int(Keys.CHAOS_SEED, 0),
        "; ".join(f.describe() for f in faults),
    )
    return True


def uninstall() -> None:
    """Disarm (tests)."""
    global _injector
    _injector = None


def active_injector() -> ChaosInjector | None:
    return _injector


__all__ = [
    "ChaosInjector",
    "FaultSpec",
    "POINTS",
    "active_injector",
    "chaos_hook",
    "install_from_config",
    "parse_faults",
    "uninstall",
]

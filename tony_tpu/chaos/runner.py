"""Chaos runner: one real job under a seeded fault schedule + the report.

The ``tony chaos`` entrypoint (cli/main.py) and the test suite both drive
this: stage and run a genuine job (LocalProcessBackend or
RemoteBackend(local) — every orchestration path real, only the substrate
faked), with ``chaos.*`` config arming the AM/executor injectors, then
re-read the artifacts and emit the invariant report. The run "passes"
when the report is clean — NOT when the job succeeds: many schedules
exist precisely to prove a job fails *visibly and cleanly*.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass

from tony_tpu.chaos.faults import parse_faults
from tony_tpu.chaos.invariants import InvariantReport, check_invariants
from tony_tpu.cli.client import TonyClient
from tony_tpu.config.config import TonyConfig
from tony_tpu.config.keys import Keys

log = logging.getLogger(__name__)


@dataclass
class ChaosRunResult:
    app_id: str
    app_dir: str
    exit_code: int   # the job's client exit code (faults may legitimately fail the job)
    state: str       # final state from status.json ("" if never written)
    report: InvariantReport
    # OOM forensics bundles any process dumped under <app_dir>/oom/ —
    # a RESOURCE_EXHAUSTED death during the run is a finding the
    # post-mortem must surface, not a silent exit code (obs/hbm.py)
    oom_forensics: list[str] | None = None
    # numerics forensics bundles under <app_dir>/health/ (obs/health.py):
    # a tripped sentinel during the run is likewise a post-mortem finding
    # (the invariant checker separately refuses to report clean over a
    # tripped verdict — health-verdict-surfaced)
    health_forensics: list[str] | None = None

    def to_dict(self) -> dict:
        return {
            "app_id": self.app_id,
            "app_dir": self.app_dir,
            "exit_code": self.exit_code,
            "state": self.state,
            "report": self.report.to_dict(),
            "oom_forensics": self.oom_forensics or [],
            "health_forensics": self.health_forensics or [],
        }


def run_chaos_job(config: TonyConfig, src_dir: str = "", quiet: bool = True) -> ChaosRunResult:
    """Run one job under the config's fault schedule and check invariants.

    ``chaos.enabled`` is forced on and the schedule is validated BEFORE
    submission — a typo'd fault type must fail the operator, not arm a
    vacuous run that reports all-clear.
    """
    config.set(Keys.CHAOS_ENABLED, True)
    faults = parse_faults(config.get(Keys.CHAOS_FAULTS))
    if not faults:
        raise ValueError("no faults scheduled (chaos.faults is empty)")
    log.warning("chaos run: %d fault(s): %s", len(faults), "; ".join(f.describe() for f in faults))
    client = TonyClient(config, src_dir=src_dir)
    code = client.run(quiet=quiet)
    state = ""
    status_path = os.path.join(client.app_dir, "status.json")
    if os.path.exists(status_path):
        with open(status_path) as f:
            state = str(json.load(f).get("state", ""))
    report = check_invariants(
        [client.app_dir], rm_root=config.get_str(Keys.CLUSTER_RM_ROOT, "")
    )
    from tony_tpu.obs import health
    from tony_tpu.obs.hbm import forensics_files

    return ChaosRunResult(
        app_id=client.app_id,
        app_dir=client.app_dir,
        exit_code=code,
        state=state,
        report=report,
        oom_forensics=forensics_files(client.app_dir),
        health_forensics=health.forensics_files(client.app_dir),
    )


__all__ = ["ChaosRunResult", "run_chaos_job"]

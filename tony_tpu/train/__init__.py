"""Training library: sharded train step, loop, data, checkpointing."""

from tony_tpu.train.data import DataConfig, make_batches
from tony_tpu.train.loop import FitConfig, fit
from tony_tpu.train.prefetch import PrefetchIterator
from tony_tpu.train.trainer import (
    TrainState,
    default_optimizer,
    make_train_state,
    make_train_step,
    state_shardings,
)

__all__ = [
    "DataConfig",
    "FitConfig",
    "PrefetchIterator",
    "TrainState",
    "default_optimizer",
    "fit",
    "make_batches",
    "make_train_state",
    "make_train_step",
    "state_shardings",
]

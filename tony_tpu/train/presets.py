"""Named training presets: model + mesh + batch recipes pinned by artifacts.

The reference ships runbook configs (BASELINE.md milestone configs 1-4);
here the north-star recipe is code, so the bench, the AOT analysis
(scripts/aot_7b_v4_32.py), and a production ``fit()`` all share one
definition instead of three copies drifting apart.
"""

from __future__ import annotations

import jax.numpy as jnp

from tony_tpu.models.llama import LlamaConfig
from tony_tpu.parallel.mesh import MeshShape


def north_star_7b_v4_32() -> tuple[LlamaConfig, MeshShape, int, int]:
    """BASELINE.json ``north_star`` / SURVEY.md section 6 config #4:
    Llama-2-7B on a v4-32 slice (32 chips x 32GB HBM).

    Returns ``(cfg, mesh_shape, global_batch, seq_len)``.

    - ZeRO-3 layout: params + AdamW state sharded over ``fsdp=32``
      (13.5GB bf16 params + 27GB bf16 mu / bf16 nu split 32 ways is
      ~1.3GB resident per chip; per-layer all-gathers ride ICI).
      For two-slice deployments use
      ``build_multislice_mesh(MeshShape(fsdp=16), n_slices=2)`` — the
      gradient-psum ``dp`` axis crosses DCN, fsdp stays intra-slice.
    - batch 32 x seq 4096 = 131072 tokens/step (1 sequence per chip),
      the bench remat policy (``save_attn_kernel``) and the pallas flash
      kernel, exactly the single-chip-validated production path.
    """
    cfg = LlamaConfig.llama2_7b(
        dtype=jnp.bfloat16,
        remat=True,
        remat_policy="save_attn_kernel",
        attention_impl="flash",
    )
    return cfg, MeshShape(fsdp=32), 32, 4096


__all__ = ["north_star_7b_v4_32"]

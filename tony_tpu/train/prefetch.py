"""Bounded device-prefetch for batch streams: overlap host input work with
the device step.

Every ``make_batches`` stream does real host work per step — synthetic token
sampling or an mmap window copy, then the H2D transfer inside
``jax.make_array_from_process_local_data`` — and the train loop used to pay
it synchronously between dispatches, so the device idled while the host
built batch N+1. :class:`PrefetchIterator` moves that work to a single
background thread feeding a bounded FIFO queue: while the device executes
step N, batches N+1..N+depth are generated and placed, and the loop's
``next()`` is a queue pop.

Guarantees (pinned by tests/test_train.py):

- **Deterministic ordering** — one producer thread, one FIFO queue: the
  consumer sees exactly the wrapped iterator's sequence, so ``prefetch=0``
  and ``prefetch>0`` yield bitwise-identical streams.
- **Exact resume** — resume position is the wrapped iterator's business
  (``make_batches(..., start_step=N)``); the prefetcher never skips or
  buffers across a restart because each fit() builds a fresh instance.
- **Clean shutdown** — ``close()`` (also ``__exit__``/``__del__``) stops
  the producer and joins it; no thread outlives the iterator.
- **Error transparency** — an exception in the producer (bad token file,
  device OOM) is re-raised from the consumer's ``next()``, not swallowed.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, TypeVar

T = TypeVar("T")

_END = object()  # wrapped iterator exhausted


class PrefetchIterator(Iterator[T]):
    """Wrap ``it`` so up to ``depth`` items are produced ahead of the
    consumer on a daemon thread. ``depth`` must be >= 1 (callers gate the
    synchronous path themselves; see ``make_batches``)."""

    def __init__(self, it: Iterator[T], depth: int = 2, name: str = "tony-prefetch"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._it = it  # kept so close() can release the stream's resources
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err: BaseException | None = None
        self._thread = threading.Thread(
            target=self._produce, args=(it,), name=name, daemon=True
        )
        self._thread.start()

    def _produce(self, it: Iterator[T]) -> None:
        try:
            for item in it:
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                else:
                    return
        except BaseException as e:  # surfaced from next(), incl. KeyboardInterrupt
            self._err = e
        # unblock a consumer waiting on get() (exhaustion or error)
        while not self._stop.is_set():
            try:
                self._q.put(_END, timeout=0.1)
                break
            except queue.Full:
                continue

    def __iter__(self) -> "PrefetchIterator[T]":
        return self

    def __next__(self) -> T:
        while True:
            try:
                item = self._q.get(timeout=0.5)
            except queue.Empty:
                if not self._thread.is_alive():
                    # producer died without posting _END (should not happen,
                    # but never hang the train loop on it)
                    if self._err is not None:
                        raise self._err
                    raise StopIteration
                continue
            if item is _END:
                self._q.put(_END)  # keep subsequent next() calls terminal
                if self._err is not None:
                    raise self._err
                raise StopIteration
            return item

    def close(self, timeout: float = 5.0) -> None:
        """Stop the producer and join it; safe to call more than once."""
        self._stop.set()
        # the producer may be blocked in put(); drain so its timeout loop
        # observes _stop promptly
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
        # release the wrapped stream's resources (native loader handle,
        # mmap) deterministically, not at GC — only once its thread is gone
        if not self._thread.is_alive():
            wrapped_close = getattr(self._it, "close", None)
            if callable(wrapped_close):
                try:
                    wrapped_close()
                except Exception:
                    pass

    def __enter__(self) -> "PrefetchIterator[T]":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:  # last-resort cleanup for unclosed streams
        try:
            self.close(timeout=1.0)
        except Exception:
            pass


def close_batches(it) -> None:
    """Shut down a stream returned by ``make_batches`` if it owns a thread
    (PrefetchIterator); plain generators are a no-op."""
    close = getattr(it, "close", None)
    if callable(close):
        close()


__all__ = ["PrefetchIterator", "close_batches"]

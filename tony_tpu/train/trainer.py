"""Training-step construction: sharded init, jitted update, metrics.

Replaces the reference's delegated data plane (Horovod allreduce / TF
parameter servers, SURVEY.md section 2 "Distributed communication backend")
with compiled XLA collectives: parameters and batch carry NamedShardings and
XLA inserts the psum/all-gather/reduce-scatter pattern implied by the mesh --
pure DP produces a gradient psum, FSDP produces reduce-scatter + all-gather,
TP produces activation collectives, with zero framework code per strategy.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu.models import llama
from tony_tpu.ops.compat import pcast_varying as _pcast_varying, shard_map_compat as _shard_map
from tony_tpu.parallel.sharding import DEFAULT_RULES, Rules, spec_for, tree_shardings

Params = dict[str, Any]


def _ensure_partitionable_threefry() -> None:
    """Partitionable threefry makes jax.random values independent of the
    mesh/sharding they are generated under (the default on current jax
    lines; old 0.4.x defaults to False, under which make_train_state's
    jit-sharded init produced DIFFERENT params per mesh — a pp mesh and
    its sequential reference trained two different models, and
    schedule-parity could only fail). Flipped at the trainer entrypoints
    rather than at import so merely importing configs doesn't mutate
    process-global RNG semantics."""
    if not jax.config.jax_threefry_partitionable:
        jax.config.update("jax_threefry_partitionable", True)


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    step: jax.Array
    params: Params
    opt_state: Any


def default_optimizer(
    lr: float = 3e-4, weight_decay: float = 0.1, warmup_steps: int = 100,
    decay_steps: int = 10000, grad_clip: float = 1.0,
    mu_dtype: Any = jnp.float32,
) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps, max(decay_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        # mu_dtype pins the first moment's dtype regardless of param dtype;
        # nu follows the params dtype in optax. fp32 mu is the conservative
        # default; bf16 frees 2 bytes/param of HBM, which on a memory-bound
        # chip funds activation-saving remat (bench.py uses it, +5 MFU pts
        # at 1.35B on 16GB). Full mixed-precision (fp32 master params) is a
        # separate concern from the moment dtype.
        optax.adamw(
            sched, b1=0.9, b2=0.95, weight_decay=weight_decay,
            mu_dtype=mu_dtype,
        ),
    )


def state_shardings(
    cfg: llama.LlamaConfig, mesh: Mesh, optimizer: optax.GradientTransformation,
    rules: Rules = DEFAULT_RULES,
) -> Any:
    """Shardings for the full TrainState (optimizer state mirrors params).

    Optimizer-state leaves are matched to parameters *structurally*: optax
    states embed param-shaped pytrees (Adam mu/nu) whose key paths end with
    the parameter's own path, so a path-suffix match recovers the exact
    sharding even when distinct params share a shape (e.g. wq/wk/wv/wo are
    all (L, 4096, 4096) in llama2_7b but shard differently). Scalar leaves
    (step counts) replicate.
    """
    p_shard = tree_shardings(llama.logical_axes(cfg), mesh, rules)
    params_shape = jax.eval_shape(partial(llama.init_params, cfg=cfg), jax.random.key(0))
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    replicated = NamedSharding(mesh, P())

    param_paths, _ = jax.tree_util.tree_flatten_with_path(params_shape)
    shard_leaves = jax.tree.leaves(p_shard)
    by_path = {tuple(str(k) for k in path): s
               for (path, _), s in zip(param_paths, shard_leaves)}
    shape_by_path = {tuple(str(k) for k in path): leaf.shape
                     for path, leaf in param_paths}

    def opt_leaf_sharding(path: tuple, leaf: jax.ShapeDtypeStruct) -> NamedSharding:
        keys = tuple(str(k) for k in path)
        for plen in range(len(keys), 0, -1):
            suffix = keys[-plen:]
            if suffix in by_path and shape_by_path[suffix] == leaf.shape:
                return by_path[suffix]
        return replicated

    o_shard = jax.tree_util.tree_map_with_path(opt_leaf_sharding, opt_shape)
    return TrainState(step=replicated, params=p_shard, opt_state=o_shard)


def train_state_avals(
    cfg: llama.LlamaConfig, optimizer: optax.GradientTransformation,
) -> TrainState:
    """Abstract (ShapeDtypeStruct) TrainState matching make_train_state's
    output — enough to ``step_fn.lower(...)`` before any array exists, so
    the train-step compile can run concurrently with state init and
    checkpoint restore (fit()'s compile-ahead path)."""
    params = jax.eval_shape(partial(llama.init_params, cfg=cfg), jax.random.key(0))
    return TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params=params,
        opt_state=jax.eval_shape(optimizer.init, params),
    )


def make_train_state(
    rng: jax.Array, cfg: llama.LlamaConfig, mesh: Mesh,
    optimizer: optax.GradientTransformation, rules: Rules = DEFAULT_RULES,
) -> TrainState:
    """Initialise the TrainState directly sharded (no host-side full copy --
    required for models that don't fit one host/chip)."""
    _ensure_partitionable_threefry()
    shardings = state_shardings(cfg, mesh, optimizer, rules)

    def init(rng: jax.Array) -> TrainState:
        params = llama.init_params(rng, cfg)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
        )

    return jax.jit(init, out_shardings=shardings)(rng)


def make_train_step(
    cfg: llama.LlamaConfig, mesh: Mesh,
    optimizer: optax.GradientTransformation, rules: Rules = DEFAULT_RULES,
    *, n_microbatches: int = 0, pp_schedule: str = "gpipe",
    monitors: bool | None = None, grad_bucket_bytes: int | None = None,
) -> Callable[..., tuple[TrainState, dict[str, jax.Array]]]:
    """Build the jitted train step:
    ``(state, inputs[B,S], targets[B,S]) -> (state, metrics)``.

    Inputs/targets are pre-shifted next-token pairs (see
    llama.loss_from_pairs) so the seq axis shards cleanly over ``sp``.
    Gradients are computed in the params' dtype (Adam's first moment is kept
    fp32 via mu_dtype); donation avoids a second copy of state.

    ``monitors`` fuses the numerics-health value monitors (nonfinite
    counts, update-to-param ratio, per-layer grad RMS, batch fingerprint —
    obs/health.py) into the step's metrics; None resolves to "is a health
    sentinel armed in this process", so a disarmed run compiles none of
    them (bench.py's ``health_overhead`` measures the armed delta).

    A mesh with ``pp > 1`` selects a pipeline loss (layer stages over the
    ``pp`` axis, ``n_microbatches`` microbatches — default 2 per stage):
    ``pp_schedule='gpipe'`` (autodiff backward, O(M) activations) or
    ``'1f1b'`` (hand-scheduled interleaved backward, O(P) activations —
    raise n_microbatches freely to shrink the bubble). The caller's rules
    must map "layers" to "pp" (fit() does this automatically;
    :func:`pp_rules` applies the override).

    ``grad_bucket_bytes`` (> 0, dp > 1, pp == 1) switches the dp gradient
    reduction from GSPMD's single fused all-reduce to the async bucketed
    path: value_and_grad runs inside a shard_map manual over ``dp`` and the
    grads all-reduce in byte-budgeted buckets (ops.overlap.bucketed_psum),
    one collective per bucket in leaf order — each bucket's reduce
    dispatches as soon as its leaves' backward is done and rides behind the
    remaining backward compute. Size the budget off the measured anatomy
    report (ops.overlap.bucket_bytes_from_report). Value-exact: bucketing
    never changes the sums, so the loss trajectory is bitwise-identical to
    the unbucketed (single-bucket) manual path.
    """
    _ensure_partitionable_threefry()
    if pp_schedule not in ("gpipe", "1f1b"):
        # validate even on pp=1 meshes: a typo'd schedule must fail loudly,
        # not silently run the sequential loss
        raise ValueError(
            f"unknown pp_schedule {pp_schedule!r} (expected gpipe | 1f1b)"
        )
    pp = int(mesh.shape.get("pp", 1))
    # pin [B, S, D] activations to the canonical batch/seq sharding at the
    # trunk boundaries: without the constraint the partitioner propagates
    # the fsdp/tp weight shardings into the embedding gather / loss-head
    # reshape and resolves the conflict with involuntary full-remat
    # all-gathers (fwd AND bwd — the constraint's transpose pins the
    # cotangents), visible as "[SPMD] Involuntary full rematerialization"
    # warnings in the multichip dryrun log
    act_sharding = (
        NamedSharding(mesh, spec_for(("batch", "seq", "embed"), rules))
        if mesh.size > 1 else None
    )
    if pp > 1:
        rules = pp_rules(rules)
        pp_loss = pp_loss_from_pairs if pp_schedule == "gpipe" else pp_1f1b_loss_from_pairs
        loss_fn = partial(
            pp_loss, cfg=cfg, mesh=mesh,
            n_microbatches=n_microbatches or 2 * pp,
            act_sharding=act_sharding,
        )
    else:
        loss_fn = partial(
            llama.loss_from_pairs, cfg=cfg, act_sharding=act_sharding
        )
    dp = int(mesh.shape.get("dp", 1))
    if grad_bucket_bytes and dp > 1 and pp == 1:
        # async bucketed dp grad reduce: manualize the dp axis so the
        # reduction is OUR schedule (one psum per bucket, leaf order), not
        # the partitioner's single fused all-reduce. The local loss is the
        # mean over this shard's rows; psum/dp restores the global mean
        # (equal shard sizes), and grads pre-scale by 1/dp so the bucketed
        # psums land on the global-mean gradient directly.
        from tony_tpu.ops.compat import axis_size as _axis_size
        from tony_tpu.ops.overlap import bucketed_psum

        # no activation pinning inside the manual region: the constraint
        # names mesh axes the region has manualized (and there is no
        # partitioner decision left to pin on this side of the boundary)
        inner_loss = partial(llama.loss_from_pairs, cfg=cfg, act_sharding=None)

        def _local_vg(params, inputs, targets):
            loss, grads = jax.value_and_grad(inner_loss)(
                params, inputs, targets
            )
            n = _axis_size("dp")
            loss = jax.lax.psum(loss, "dp") / n
            grads = jax.tree.map(lambda g: g / n, grads)
            grads = bucketed_psum(
                grads, "dp", bucket_bytes=int(grad_bucket_bytes)
            )
            return loss, grads

        batch_spec = P("dp", None)  # [B, S] token pairs, rows over dp
        bucketed_vg = _shard_map(
            _local_vg, mesh=mesh,
            in_specs=(P(), batch_spec, batch_spec),
            out_specs=(P(), P()),
            axis_names={"dp"},
        )
    else:
        bucketed_vg = None

    def value_and_grad_fn(params, inputs, targets):
        if bucketed_vg is not None:  # build-time constant, not a tracer
            return bucketed_vg(params, inputs, targets)
        return jax.value_and_grad(loss_fn)(params, inputs, targets)

    shardings = state_shardings(cfg, mesh, optimizer, rules)
    batch_sharding = NamedSharding(mesh, spec_for(("batch", "seq"), rules))
    replicated = NamedSharding(mesh, P())

    from tony_tpu.obs import health as _health

    if monitors is None:
        monitors = _health.active_sentinel() is not None
    # numerics chaos seam: poison the REPORTED loss with an in-graph NaN
    # from a chosen step onward (TONY_CHAOS_NAN_STEP; chaos-style jobs
    # export it into worker env) so a tier-1 job can prove injection ->
    # sentinel trip -> forensics end to end. Persistent like a real NaN'd
    # state — a one-step blip could fall between sampling strides, which
    # a genuine numerics death never does. Grads are untouched: the fault
    # is in the value telemetry, exactly what the sentinel watches.
    nan_step = _health.nan_inject_step()

    def step(state: TrainState, inputs: jax.Array, targets: jax.Array):
        loss, grads = value_and_grad_fn(state.params, inputs, targets)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        if nan_step is not None:
            loss = loss + jnp.where(
                state.step + 1 >= nan_step, jnp.float32(jnp.nan), jnp.float32(0.0)
            )
        metrics = {"loss": loss, "grad_norm": gnorm, "step": state.step + 1}
        if monitors:
            metrics.update(_health.graph_monitors(
                loss, grads, new_params, updates, inputs
            ))
        return TrainState(state.step + 1, new_params, new_opt), metrics

    return jax.jit(
        step,
        in_shardings=(shardings, batch_sharding, batch_sharding),
        out_shardings=(shardings, replicated),
        donate_argnums=(0,),
    )


def pp_1f1b_loss_from_pairs(
    params: Params, inputs: jax.Array, targets: jax.Array, *,
    cfg: llama.LlamaConfig, mesh: Mesh, n_microbatches: int,
    act_sharding=None,
) -> jax.Array:
    """1F1B pipeline loss: same stage decomposition as the GPipe loss, but
    the backward is hand-scheduled (parallel.pipeline.pipeline_train_1f1b)
    with O(P) live activations instead of autodiff's O(M) — the loss head
    (final norm + lm head + CE) moves INSIDE the last stage so each
    microbatch's cotangent is seeded the moment its forward finishes.
    """
    from tony_tpu.parallel.pipeline import microbatch, pipeline_train_1f1b

    if cfg.is_moe:
        raise NotImplementedError(
            "pp_schedule='1f1b' + MoE not supported (aux loss is not "
            "threaded through the interleaved schedule); use 'gpipe'"
        )
    _pp_guard(cfg, mesh)

    x = llama.embed_tokens(params, inputs, act_sharding)
    cos, sin = llama.rope_table(cfg, inputs.shape[1])
    xs = microbatch(x, n_microbatches)
    tgts = microbatch(targets, n_microbatches)

    shared_stage = _pp_stage_fn(cfg, cos, sin)

    def stage_fn(lp_stack: Params, mb: jax.Array) -> jax.Array:
        return shared_stage(lp_stack, mb)[0]  # dense: aux is always 0

    def head_fn(hp: Params, y: jax.Array, tgt: jax.Array) -> jax.Array:
        return _ce_head(hp["final_norm"], hp["lm_head"], y, tgt, cfg)

    head_params = {"final_norm": params["final_norm"], "lm_head": params["lm_head"]}
    return pipeline_train_1f1b(
        stage_fn, head_fn, params["layers"], head_params, xs, tgts, mesh=mesh
    )


def _pp_guard(cfg: llama.LlamaConfig, mesh: Mesh) -> None:
    if cfg.attention_impl in ("ring", "ring_flash", "ulysses"):
        # shardy cannot re-bind collective axes inside the pp-manual stage
        # region (verifier rejects nested manual computations over sp)
        raise NotImplementedError(
            f"pp + attention_impl={cfg.attention_impl!r} is not supported: "
            "sequence-parallel attention cannot nest inside pipeline stages; "
            "use 'flash' or 'dot' with pp, or sp without pp"
        )
    pp = int(mesh.shape["pp"])
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={pp}")


def _ce_head(final_norm: jax.Array, lm_head: jax.Array, h: jax.Array,
             targets: jax.Array, cfg: llama.LlamaConfig) -> jax.Array:
    """final norm + lm head + mean cross-entropy — the ONE copy both
    pipeline schedules share. Routes through llama.ce_tokens, so the fused
    chunked CE (ce_impl scan/pallas — no [mb, S, V] logits or dlogits per
    microbatch) and the dense reference stay interchangeable here exactly
    as in the sequential loss."""
    h = llama.rms_norm(h, final_norm, cfg.norm_eps)
    return jnp.mean(llama.ce_tokens(h, lm_head, targets, cfg))


def _pp_stage_fn(cfg: llama.LlamaConfig, cos: jax.Array, sin: jax.Array):
    """One pipeline stage: scan this stage's [L/P] layer stack over a
    microbatch, returning (y, summed aux). Shared by both schedules."""

    def stage_fn(lp_stack: Params, mb: jax.Array):
        def blk(carry, lp: Params):
            h, aux_acc = carry
            out, aux = llama.transformer_block(h, lp, cfg, cos, sin)
            return (out, aux_acc + aux), None

        if cfg.remat:
            blk = jax.checkpoint(
                blk, policy=jax.checkpoint_policies.nothing_saveable
            )
        # the aux carry must be pp-varying like the stage's layer params
        aux0 = _pcast_varying(jnp.zeros((), jnp.float32), ("pp",))
        (y, aux), _ = jax.lax.scan(blk, (mb, aux0), lp_stack)
        return y, aux

    return stage_fn


def pp_rules(rules: Rules = DEFAULT_RULES) -> Rules:
    """Rules for pipeline training: the stacked-layer dim becomes the stage
    dim, sharded over ``pp`` (each stage owns n_layers/pp layers)."""
    return {**rules, "layers": "pp"}


def pp_loss_from_pairs(
    params: Params, inputs: jax.Array, targets: jax.Array, *,
    cfg: llama.LlamaConfig, mesh: Mesh, n_microbatches: int,
    act_sharding=None,
) -> jax.Array:
    """GPipe pipeline loss: embedding and head run auto-sharded outside the
    pipeline; the layer stack runs as pp stages under a shard_map that is
    manual over ``pp`` only (dp/fsdp/tp/sp stay XLA-auto inside the stages,
    so the same Megatron/FSDP shardings compose with pipelining).

    Reference: GPipe (arXiv:1811.06965) schedule; bubble (P-1)/(M+P-1).
    """
    from tony_tpu.parallel.pipeline import microbatch, pipeline_local, unmicrobatch

    _pp_guard(cfg, mesh)

    x = llama.embed_tokens(params, inputs, act_sharding)
    cos, sin = llama.rope_table(cfg, inputs.shape[1])
    xs = microbatch(x, n_microbatches)  # [M, mb, S, D]

    def body(stage_layers: Params, xs_: jax.Array, cos_: jax.Array, sin_: jax.Array):
        return pipeline_local(
            _pp_stage_fn(cfg, cos_, sin_), stage_layers, xs_,
            axis_name="pp", with_aux=True,
        )

    layer_specs = jax.tree.map(lambda _: P("pp"), params["layers"])
    h, aux = _shard_map(
        body,
        mesh=mesh,
        in_specs=(layer_specs, P(), P(), P()),
        out_specs=(P(), P()),
        axis_names={"pp"},  # manual over pp; all other axes stay auto
    )(params["layers"], xs, cos, sin)
    h = unmicrobatch(h)
    if act_sharding is not None:
        # the CE head mixes h with batch-sharded targets; pin h to the same
        # layout so the partitioner doesn't invent a reshard
        h = jax.lax.with_sharding_constraint(h, act_sharding)

    ce = _ce_head(params["final_norm"], params["lm_head"], h, targets, cfg)
    if cfg.is_moe:
        # mirror loss_from_pairs: aux averaged over layers, scaled by coef
        ce = ce + cfg.moe_aux_coef * aux / cfg.n_layers
    return ce

"""The user-facing training loop: fit() for tony-tpu jobs.

Ties together the pieces a reference-TonY user had to hand-roll in their
script: jax.distributed bootstrap (from the AM env), mesh construction,
sharded state init, orbax checkpoint resume (the elastic-restart contract,
milestone config #5), the jitted train step, and per-step throughput/MFU
metrics. A complete distributed trainer is:

    from tony_tpu.train import fit, FitConfig
    fit(FitConfig(model=LlamaConfig.llama2_7b(), steps=1000, ...))
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Callable

import jax
from jax.sharding import NamedSharding

from tony_tpu.models.llama import LlamaConfig, train_flops_per_token
from tony_tpu.obs.metrics import StepTimer, chip_peak_flops
from tony_tpu.parallel.mesh import MeshShape, build_mesh
from tony_tpu.parallel.sharding import DEFAULT_RULES, Rules, spec_for
from tony_tpu.runtime import jax_tpu
from tony_tpu.train.data import DataConfig, make_batches
from tony_tpu.train.trainer import default_optimizer, make_train_state, make_train_step

log = logging.getLogger(__name__)


@dataclass
class FitConfig:
    model: LlamaConfig = field(default_factory=LlamaConfig.tiny)
    data: DataConfig = field(default_factory=DataConfig)
    mesh_shape: MeshShape | None = None   # None -> FSDP over all devices
    steps: int = 100
    log_every: int = 10
    checkpoint_dir: str = ""
    checkpoint_every: int = 0
    checkpoint_keep: int = 3
    lr: float = 3e-4
    warmup_steps: int = 100
    rules: Rules = field(default_factory=lambda: dict(DEFAULT_RULES))
    # pipeline microbatches when mesh_shape.pp > 1 (0 -> 2 per stage)
    pp_microbatches: int = 0
    # 'gpipe' (autodiff bwd, O(M) activations) | '1f1b' (interleaved
    # hand-scheduled bwd, O(P) activations)
    pp_schedule: str = "gpipe"
    # hook called every log_every steps with a metrics dict (obs -> AM push)
    on_metrics: Callable[[dict], None] | None = None
    resume: bool = True  # restore from checkpoint_dir if a checkpoint exists

    def apply_job_env(self) -> None:
        """Fill unset checkpoint fields from the TONY_CHECKPOINT_* env the
        executor exported (the checkpoint.dir / checkpoint.interval_steps /
        restart.resume_from_checkpoint job-config glue)."""
        if not self.checkpoint_dir and os.environ.get("TONY_CHECKPOINT_DIR"):
            self.checkpoint_dir = os.environ["TONY_CHECKPOINT_DIR"]
            if self.checkpoint_every == 0:
                self.checkpoint_every = int(
                    os.environ.get("TONY_CHECKPOINT_INTERVAL_STEPS", "0")
                )
            self.checkpoint_keep = int(
                os.environ.get("TONY_CHECKPOINT_KEEP", str(self.checkpoint_keep))
            )
            self.resume = os.environ.get("TONY_RESUME_FROM_CHECKPOINT", "true") == "true"


def fit(cfg: FitConfig) -> dict:
    """Run the training loop to cfg.steps; returns final metrics."""
    from tony_tpu.obs.diagnostics import diagnostics_context

    with diagnostics_context():
        return _fit(cfg)


def _fit(cfg: FitConfig) -> dict:
    jax_tpu.initialize()  # no-op outside a tony-tpu job
    cfg.apply_job_env()
    cache_dir = os.environ.get("TONY_JAX_CACHE_DIR", "")
    if cache_dir:
        # persistent XLA compilation cache (train.jax_cache, default on):
        # a resubmitted or gang-restarted job loads its executables instead
        # of recompiling — the dominant submit->first-step cost on TPU
        # (docs/PERF.md latency section)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    if os.environ.get("TONY_PROFILER_PORT"):
        from tony_tpu.obs.profiler import start_server

        # one server per process; offset by rank so co-hosted processes
        # (the local backend) don't collide on the port
        start_server(int(os.environ["TONY_PROFILER_PORT"]) + jax_tpu.process_id())
    reporter = None
    on_metrics = cfg.on_metrics
    if on_metrics is None and jax_tpu.in_tony_job():
        # push step metrics to the AM (TaskMonitor/MetricsRpc pipeline)
        from tony_tpu.obs.reporter import MetricsReporter

        reporter = MetricsReporter()
        if reporter.active:
            on_metrics = reporter.push
    mesh = build_mesh(cfg.mesh_shape)
    # model-level attention hooks ('ring'/'flash') resolve this mesh
    from tony_tpu.parallel.mesh import set_default_mesh

    set_default_mesh(mesh)
    if jax.process_index() == 0:
        log.info("mesh: %s over %d devices", dict(mesh.shape), mesh.size)

    optimizer = default_optimizer(
        lr=cfg.lr, warmup_steps=cfg.warmup_steps, decay_steps=max(cfg.steps, cfg.warmup_steps + 1)
    )
    rules = cfg.rules
    if int(mesh.shape.get("pp", 1)) > 1:
        from tony_tpu.train.trainer import pp_rules

        rules = pp_rules(rules)
    state = make_train_state(jax.random.key(0), cfg.model, mesh, optimizer, rules)
    step_fn = make_train_step(
        cfg.model, mesh, optimizer, rules,
        n_microbatches=cfg.pp_microbatches, pp_schedule=cfg.pp_schedule,
    )

    manager = None
    start_step = 0
    if cfg.checkpoint_dir:
        from tony_tpu.train.checkpoint import CheckpointManager

        manager = CheckpointManager(
            cfg.checkpoint_dir,
            keep=cfg.checkpoint_keep,
            save_interval_steps=cfg.checkpoint_every,
        )
        if cfg.resume:
            state, restored = manager.restore(state)
            if restored >= 0:
                start_step = restored
                log.info("resumed from checkpoint step %d", restored)

    batch_sharding = NamedSharding(mesh, spec_for(("batch", "seq"), cfg.rules))
    batches = make_batches(cfg.data, batch_sharding, start_step=start_step)
    flops_per_token = train_flops_per_token(cfg.model, cfg.data.seq_len)
    tokens_per_step = cfg.data.global_batch * cfg.data.seq_len

    metrics = {}
    t_window = time.perf_counter()
    window = 0
    for step in range(start_step, cfg.steps):
        inputs, targets = next(batches)
        state, metrics = step_fn(state, inputs, targets)
        window += 1
        # the very first step always logs/pushes: it closes the AM-submit ->
        # first-step loop (the north-star latency metric — the AM timestamps
        # the resulting METRICS event) and gives users signal before a long
        # log_every window elapses
        if step == start_step or (step + 1) % cfg.log_every == 0 or step + 1 == cfg.steps:
            loss = float(metrics["loss"])  # device sync point
            timer = StepTimer(
                flops_per_token=flops_per_token,
                tokens_per_step=tokens_per_step,
                n_chips=mesh.size,
            )
            timer.record(time.perf_counter() - t_window, window)
            out = {
                "step": step + 1,
                "loss": round(loss, 4),
                "tokens_per_sec": round(timer.tokens_per_sec, 1),
                "tokens_per_sec_per_chip": round(timer.tokens_per_sec_per_chip, 1),
                "mfu": round(timer.mfu(), 4),
                "grad_norm": round(float(metrics["grad_norm"]), 4),
            }
            # HBM usage from the device this process owns (the nvidia-smi
            # sampling analogue; empty on platforms without memory_stats)
            from tony_tpu.obs.tpu_metrics import tpu_metrics_dict

            out.update(tpu_metrics_dict())
            if jax.process_index() == 0:
                log.info(
                    "step %(step)d loss=%(loss)s %(tokens_per_sec_per_chip)s tok/s/chip "
                    "mfu=%(mfu)s", out,
                )
            if on_metrics:
                on_metrics(out)
            t_window = time.perf_counter()
            window = 0
        if manager is not None and manager.should_save(step + 1):
            manager.save(step + 1, state)
    if manager is not None:
        manager.wait()  # settle async saves before checking what exists
        if manager.latest_step() != cfg.steps:
            manager.save(cfg.steps, state, force=True)
        manager.close()
    if reporter is not None:
        reporter.close()
    final = {"final_loss": float(metrics.get("loss", float("nan"))), "steps": cfg.steps}
    return final


__all__ = ["FitConfig", "fit"]

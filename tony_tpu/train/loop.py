"""The user-facing training loop: fit() for tony-tpu jobs.

Ties together the pieces a reference-TonY user had to hand-roll in their
script: jax.distributed bootstrap (from the AM env), mesh construction,
sharded state init, orbax checkpoint resume (the elastic-restart contract,
milestone config #5), the jitted train step, and per-step throughput/MFU
metrics. A complete distributed trainer is:

    from tony_tpu.train import fit, FitConfig
    fit(FitConfig(model=LlamaConfig.llama2_7b(), steps=1000, ...))

Startup and the steady-state loop are overlapped (docs/PERF.md "Overlap"):

- **compile-ahead**: the train step is AOT-lowered and compiled on a worker
  thread, concurrently with sharded state init, checkpoint restore, and
  input warmup — registered->first-step pays max(compile, restore,
  first-batch) instead of their sum, compounding with the persistent XLA
  cache (TONY_JAX_CACHE_DIR).
- **device prefetch**: with DataConfig.prefetch > 0 (default 2) the batch
  stream runs on a background thread (train/prefetch.py), so host batch
  synthesis + H2D placement for step N+1 overlap the device's step N.
- **stall-free telemetry**: metrics pushes are queued to a daemon thread
  (obs/reporter.py) and the log-boundary device sync is deferred until the
  next step is dispatched, so neither an AM RPC stall nor a loss fetch
  drains the pipeline. The very first step still syncs and pushes
  immediately — it timestamps the submit->first-step north-star metric.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from tony_tpu.models.llama import LlamaConfig, train_flops_per_token
from tony_tpu.obs import hbm, health, profile, series, slo, trace
from tony_tpu.obs import compiles as compile_ledger
from tony_tpu.obs.metrics import StepTimer, chip_peak_flops
from tony_tpu.obs.registry import HistogramWindow, Registry, snapshot_to_app_dir
from tony_tpu.parallel.mesh import MeshShape, build_mesh
from tony_tpu.parallel.sharding import DEFAULT_RULES, Rules, spec_for
from tony_tpu.runtime import jax_tpu
from tony_tpu.train.data import DataConfig, make_batches
from tony_tpu.train.prefetch import close_batches
from tony_tpu.train.trainer import (
    default_optimizer,
    make_train_state,
    make_train_step,
    train_state_avals,
)

log = logging.getLogger(__name__)


@dataclass
class FitConfig:
    model: LlamaConfig = field(default_factory=LlamaConfig.tiny)
    data: DataConfig = field(default_factory=DataConfig)
    mesh_shape: MeshShape | None = None   # None -> FSDP over all devices
    steps: int = 100
    log_every: int = 10
    checkpoint_dir: str = ""
    checkpoint_every: int = 0
    checkpoint_keep: int = 3
    lr: float = 3e-4
    warmup_steps: int = 100
    rules: Rules = field(default_factory=lambda: dict(DEFAULT_RULES))
    # pipeline microbatches when mesh_shape.pp > 1 (0 -> 2 per stage)
    pp_microbatches: int = 0
    # 'gpipe' (autodiff bwd, O(M) activations) | '1f1b' (interleaved
    # hand-scheduled bwd, O(P) activations)
    pp_schedule: str = "gpipe"
    # hook called every log_every steps with a metrics dict (obs -> AM push)
    on_metrics: Callable[[dict], None] | None = None
    resume: bool = True  # restore from checkpoint_dir if a checkpoint exists
    # AOT-compile the train step on a worker thread during startup (overlaps
    # state init / restore / input warmup); False pins the lazy jit path
    compile_ahead: bool = True
    # Adam first-moment dtype ('float32' | 'bfloat16'); bf16 frees
    # 2 bytes/param of HBM (see default_optimizer / docs/PERF.md)
    mu_dtype: str = "float32"
    # loss-head implementation override: '' keeps model.ce_impl; 'scan' /
    # 'pallas' select the fused chunked CE (tony_tpu.ops.fused_ce — no
    # [B,S,V] logits transient), 'dense' the legacy full-logits head
    ce_impl: str = ""
    # MoE dispatch override: '' keeps model.moe_dispatch; 'grouped' selects
    # the dropless sorted grouped GEMM, 'gather'/'einsum' the capacity paths
    # (tony_tpu.parallel.moe — docs/PERF.md "Grouped MoE")
    moe_dispatch: str = ""
    # comm/compute overlap override (tony_tpu.ops.overlap, docs/PERF.md
    # "Overlap (collectives)"): '' keeps model.overlap_impl; 'scan'/'pallas'
    # stream the fsdp weight all-gathers per-chunk through the decomposed
    # ppermute-ring matmuls instead of blocking up front
    overlap_impl: str = ""
    # dp gradient-reduction bucket size in MiB (0 disables — GSPMD's single
    # fused all-reduce): > 0 switches the step to the manual-dp bucketed
    # path, one collective per ~bucket of grad leaves so each reduce
    # dispatches as its layers' backward completes. Size it from the
    # measured anatomy report: ops.overlap.bucket_bytes_from_report
    # (achieved_gbps x per-layer backward window). Needs dp > 1, pp == 1.
    grad_bucket_mb: float = 0.0
    # grouped-GEMM row tile override (0 keeps model.moe_group_block)
    moe_group_block: int = 0
    # MoE ep-combine overlap override (tony_tpu.ops.moe_overlap, docs/
    # PERF.md "Round 20"): '' keeps model.moe_overlap_impl; 'scan'/'pallas'
    # decompose the grouped path's post-FFN combine psum into per-token-
    # chunk partial combines so expert compute overlaps combine traffic;
    # 'off' pins the single blocking psum
    moe_overlap_impl: str = ""
    # overlap chunk tokens per shard override (0 keeps
    # model.moe_overlap_chunk; size measured captures via
    # ops.moe_overlap.chunk_tokens_from_report)
    moe_overlap_chunk: int = 0
    # elastic training (tony_tpu/elastic/, docs/ELASTIC.md): gang size at
    # full strength. 0 disables; >= 2 makes the mesh runtime-swappable —
    # the dp axis maps to members and shrinks/grows at AM-declared
    # generation boundaries while training continues from the in-memory
    # state of survivors. mesh_shape then means the PER-MEMBER shape
    # (dp must stay 1) and data.global_batch the full-membership batch.
    elastic_members: int = 0
    # scripted membership plan {step: (member, ...)} applied at step
    # boundaries — the in-process twin of the AM's generation broadcast
    # (bench `elastic` section + tests drive shrink/grow through it)
    elastic_plan: dict | None = None
    # broadcast + journal root; empty -> TONY_APP_DIR (the shared app dir
    # the AM writes generation.json into)
    elastic_dir: str = ""
    # checkpoint-shadow stride in steps (0 -> env/default 16)
    elastic_shadow_steps: int = 0

    def apply_job_env(self) -> None:
        """Fill unset checkpoint fields from the TONY_CHECKPOINT_* env the
        executor exported (the checkpoint.dir / checkpoint.interval_steps /
        restart.resume_from_checkpoint job-config glue), and arm elastic
        membership from the TONY_ELASTIC* env the ElasticRuntime exports."""
        if not self.checkpoint_dir and os.environ.get("TONY_CHECKPOINT_DIR"):
            self.checkpoint_dir = os.environ["TONY_CHECKPOINT_DIR"]
            if self.checkpoint_every == 0:
                self.checkpoint_every = int(
                    os.environ.get("TONY_CHECKPOINT_INTERVAL_STEPS", "0")
                )
            self.checkpoint_keep = int(
                os.environ.get("TONY_CHECKPOINT_KEEP", str(self.checkpoint_keep))
            )
            self.resume = os.environ.get("TONY_RESUME_FROM_CHECKPOINT", "true") == "true"
        if self.elastic_members == 0 and os.environ.get("TONY_ELASTIC") == "1":
            self.elastic_members = int(
                os.environ.get("TONY_ELASTIC_MEMBERS", "0") or 0
            )


def fit(cfg: FitConfig) -> dict:
    """Run the training loop to cfg.steps; returns final metrics."""
    from tony_tpu.obs.diagnostics import diagnostics_context

    # join the job's trace spine (no-op outside a traced tony-tpu job);
    # every span below nests under train.fit on the merged timeline — the
    # root handle rides into _fit because the compile-ahead worker thread
    # has an empty span stack and must parent on it explicitly
    trace.install_from_env()
    # arm the HBM observatory (idempotent; TONY_OBS_HBM=0 disables) and the
    # OOM guard: a RESOURCE_EXHAUSTED escaping the loop dumps the device
    # memory profile + compile ledger + watermark history into the app dir
    # before re-raising (obs/hbm.py, docs/OBS.md "Memory and compiles")
    hbm.install_from_env()
    # arm the numerics sentinel (idempotent; TONY_OBS_HEALTH=0 disables)
    # BEFORE the train step is built, so the in-graph value monitors are
    # fused into it (obs/health.py, docs/OBS.md "Numerics health")
    health.install_from_env()
    # arm the live time-series recorder + SLO engine (idempotent;
    # TONY_OBS_SERIES=0 disables): stride-scraped step/goodput/HBM points
    # journal under the app dir and feed burn-rate alerting
    # (obs/series.py, obs/slo.py, docs/OBS.md "SLO + time series")
    series.install_from_env()
    # arm the coordinated-profiling controller (idempotent; TONY_OBS_PROFILE=0
    # disables): `tony profile <app_id>` broadcasts a bounded window and the
    # maybe_capture seam in the step loop captures a jax.profiler device
    # trace into <app_dir>/profile/<proc>/ (obs/profile.py, docs/OBS.md
    # "Step anatomy")
    profile.install_from_env()
    with diagnostics_context(), trace.span("train.fit", steps=cfg.steps) as root:
        with hbm.oom_guard("fit"):
            return _fit(cfg, root)


def _start_async_host_copy(metrics: dict) -> None:
    """Kick off D2H transfers for the scalars a log boundary will read, so
    the later float() is a cheap wait instead of a fresh blocking fetch."""
    for key in ("loss", "grad_norm"):
        arr = metrics.get(key)
        if hasattr(arr, "copy_to_host_async"):
            try:
                arr.copy_to_host_async()
            except Exception:
                pass


class _Elastic:
    """fit()'s elastic runtime: the swappable topology + its bookkeeping.

    Owns the member-granular :class:`~tony_tpu.elastic.ElasticTopology`,
    the generation watcher, the host-RAM checkpoint shadow, and the
    membership-aware batch stream; :meth:`reshard` is the generation
    boundary — fence, donate, rebuild, continue (docs/ELASTIC.md).
    """

    def __init__(self, cfg: FitConfig):
        from tony_tpu import elastic

        if jax.process_count() > 1:
            raise NotImplementedError(
                "elastic fit() is single-controller: the trainer process "
                "owns every live member's devices (jax.process_count() "
                "must be 1; member seats are separate non-jax agents)"
            )
        self._elastic = elastic
        self.cfg = cfg
        # ONE parser for the TONY_ELASTIC* contract (ElasticSettings
        # .from_env); FitConfig fields override what they own
        settings = (
            elastic.ElasticSettings.from_env() or elastic.ElasticSettings()
        )
        settings.members = cfg.elastic_members or settings.members
        if cfg.elastic_dir:
            settings.app_dir = cfg.elastic_dir
        elif not settings.app_dir:
            # FitConfig-armed elastic inside a tony job still journals to
            # the shared app dir
            settings.app_dir = os.environ.get("TONY_APP_DIR", "")
        if cfg.elastic_shadow_steps:
            settings.shadow_interval_steps = cfg.elastic_shadow_steps
        self.controller = elastic.ElasticController(
            settings, watch=bool(settings.app_dir)
        )
        self.topology = elastic.ElasticTopology(
            cfg.elastic_members, per_member=cfg.mesh_shape
        )
        self.shadow = elastic.ShadowStore(
            interval_steps=settings.shadow_interval_steps
        )
        self.mesh = self.topology.mesh_for(self.controller.members)
        self.stream = None   # built once fit knows the batch sharding
        self.plan = dict(cfg.elastic_plan or {})
        self.reshards = 0
        self.reshard_s = 0.0

    @property
    def journal(self):
        return self.controller.journal

    def make_stream(self, batch_sharding, start_step: int):
        self.stream = self._elastic.ElasticBatchStream(
            self.cfg.data, self.cfg.elastic_members, self.controller.members,
            batch_sharding, start_step=start_step,
        )
        return self.stream

    def pending(self, step: int):
        """The membership change to apply at this boundary, if any: the
        scripted plan (bench/tests) outranks the file broadcast so a plan
        stays deterministic even inside a traced job. A record whose
        membership already matches (e.g. a member died and grew back
        between two boundaries — net no-op) is adopted here, where
        membership is settled, without a reshard."""
        members = self.plan.pop(step, None)
        if members is not None and set(members) != set(self.controller.members):
            old = set(self.controller.members)
            new = set(int(m) for m in members)
            return self._elastic.GenerationRecord(
                generation=self.controller.generation + 1,
                members=tuple(sorted(new)),
                boundary="shrink" if old - new else "grow",
                dead=tuple(sorted(old - new)),
                added=tuple(sorted(new - old)),
                reason="scripted plan",
            )
        rec = self.controller.pending()
        if rec is not None and set(rec.members) == set(self.controller.members):
            self.controller.applied(rec)
            return None
        return rec

    def note_step(self, step: int) -> None:
        if self.journal is not None:
            self.journal.step(
                step, self.controller.generation, self.controller.members
            )

    def reshard(self, rec, step: int, state, optimizer, rules, ledger):
        """One generation boundary: returns the rebuilt
        ``(state, step_fn, compiled_step, mesh, batch_sharding)``.

        The span is the restart-cost evidence: ``tony trace`` goodput's
        ``restart_s`` bucket sums ``elastic.reshard`` spans (the warm
        path) next to relaunch gaps (the cold one).
        """
        from tony_tpu.parallel.mesh import set_default_mesh
        from tony_tpu.parallel.sharding import spec_for
        from tony_tpu.train.trainer import (
            make_train_step, state_shardings, train_state_avals,
        )

        cfg = self.cfg
        members = tuple(sorted(rec.members))
        t0 = time.perf_counter()
        members_str = ",".join(str(m) for m in members)
        dead_str = ",".join(str(m) for m in rec.dead)
        with trace.span(
            "elastic.reshard", generation=rec.generation,
            boundary=rec.boundary, at_step=step,
            members=members_str, dead=dead_str,
        ):
            # fence: drain the dispatch backlog, then take the exact
            # current state device->host — the donation every survivor
            # (and a grown-back member) reshards from. Zero steps lost:
            # the recovery point IS the fenced state, the periodic shadow
            # is only the fallback when a fence cannot complete.
            jax.block_until_ready(state)
            host_state = self.shadow.capture_sync(step, state)
            self.mesh = self.topology.mesh_for(members)
            set_default_mesh(self.mesh)
            shardings = state_shardings(cfg.model, self.mesh, optimizer, rules)
            state = self._elastic.reshard_state(host_state, shardings)
            step_fn = make_train_step(
                cfg.model, self.mesh, optimizer, rules,
                n_microbatches=cfg.pp_microbatches,
                pp_schedule=cfg.pp_schedule,
                grad_bucket_bytes=int(cfg.grad_bucket_mb * (1 << 20)),
            )
            batch_sharding = NamedSharding(
                self.mesh, spec_for(("batch", "seq"), cfg.rules)
            )
            skipped = self.stream.reshard(members, batch_sharding)
            compiled = None
            if cfg.compile_ahead:
                # re-lower against the shrunk/grown topology through the
                # same AOT path startup uses (persistent XLA cache makes a
                # grow back to a previously-seen shape a cache hit)
                batch_aval = jax.ShapeDtypeStruct(
                    (self.stream.global_batch, cfg.data.seq_len), jnp.int32
                )
                try:
                    with ledger.label("train.step"):
                        compiled = step_fn.lower(
                            train_state_avals(cfg.model, optimizer),
                            batch_aval, batch_aval,
                        ).compile()
                except Exception:
                    log.warning(
                        "elastic re-lower failed; jit dispatch compiles "
                        "lazily", exc_info=True,
                    )
        dt = time.perf_counter() - t0
        self.reshards += 1
        self.reshard_s += dt
        if self.journal is not None:
            self.journal.reshard(
                generation=rec.generation, at_step=step,
                boundary=rec.boundary, members=members, dead=rec.dead,
                added=rec.added, skipped=skipped, reshard_s=dt,
                lost_steps=0,
            )
        self.controller.applied(rec)
        if jax.process_index() == 0:
            log.warning(
                "elastic generation %d (%s) applied at step %d in %.2fs: "
                "members=%s global_batch=%d",
                rec.generation, rec.boundary, step, dt, list(members),
                self.stream.global_batch,
            )
        return state, step_fn, compiled, self.mesh, batch_sharding

    def summary(self) -> dict:
        return {
            "generation": self.controller.generation,
            "members": list(self.controller.members),
            "reshards": self.reshards,
            "reshard_s": round(self.reshard_s, 3),
            "shadow_dropped": self.shadow.dropped,
        }

    def close(self) -> None:
        self.shadow.close()
        if self.stream is not None:
            self.stream.close()
        self.controller.close()


def _fit(cfg: FitConfig, fit_span=trace.NOOP_SPAN) -> dict:
    jax_tpu.initialize()  # no-op outside a tony-tpu job
    # always-on compile journal (obs/compiles.py): every XLA backend
    # compile during this run is an entry; the shutdown summary and
    # `tony compiles <app_id>` report from it
    ledger = compile_ledger.get_ledger()
    compiles_t0 = ledger.backend_compiles
    hbm_watch = hbm.active_watch()
    # run-scoped watermark mark: the shutdown summary reports THIS run's
    # peak via the attribution rule (hbm.measure_since), not the process's
    # cumulative counter — a second fit() in the same process (bench
    # sweeps) must not inherit the first one's peak
    hbm_mark = hbm_watch.mark() if hbm_watch is not None else None
    cfg.apply_job_env()
    if (cfg.ce_impl or cfg.moe_dispatch or cfg.moe_group_block
            or cfg.overlap_impl or cfg.moe_overlap_impl
            or cfg.moe_overlap_chunk):
        from dataclasses import replace as _replace

        overrides = {}
        if cfg.ce_impl:
            overrides["ce_impl"] = cfg.ce_impl
        if cfg.moe_dispatch:
            overrides["moe_dispatch"] = cfg.moe_dispatch
        if cfg.moe_group_block:
            overrides["moe_group_block"] = cfg.moe_group_block
        if cfg.overlap_impl:
            overrides["overlap_impl"] = cfg.overlap_impl
        if cfg.moe_overlap_impl:
            overrides["moe_overlap_impl"] = cfg.moe_overlap_impl
        if cfg.moe_overlap_chunk:
            overrides["moe_overlap_chunk"] = cfg.moe_overlap_chunk
        cfg.model = _replace(cfg.model, **overrides)
    cache_dir = os.environ.get("TONY_JAX_CACHE_DIR", "")
    if cache_dir and cfg.elastic_members >= 2:
        # elastic runs re-lower the step per generation; round-tripping
        # those executables through the persistent cache corrupts the
        # process on this jax line (a deserialized executable for a
        # previously-seen topology aborts a few steps after a grow
        # boundary). The cache's win is submit->first-step; the elastic
        # warm path keeps survivors' executables in memory anyway.
        log.info("elastic fit: persistent XLA cache disabled")
        cache_dir = ""
    if cache_dir:
        # persistent XLA compilation cache (train.jax_cache, default on):
        # a resubmitted or gang-restarted job loads its executables instead
        # of recompiling — the dominant submit->first-step cost on TPU
        # (docs/PERF.md latency section)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    if os.environ.get("TONY_PROFILER_PORT"):
        from tony_tpu.obs.profiler import start_server

        # one server per process; offset by rank so co-hosted processes
        # (the local backend) don't collide on the port
        start_server(int(os.environ["TONY_PROFILER_PORT"]) + jax_tpu.process_id())
    reporter = None
    on_metrics = cfg.on_metrics
    if on_metrics is None and jax_tpu.in_tony_job():
        # push step metrics to the AM (TaskMonitor/MetricsRpc pipeline);
        # pushes are queued + drained by a daemon thread so an AM stall
        # can never block the step loop
        from tony_tpu.obs.reporter import MetricsReporter

        reporter = MetricsReporter()
        if reporter.active:
            on_metrics = reporter.push
    el = None
    if cfg.elastic_members >= 2:
        # elastic job: the mesh is a function of the current membership
        # (dp = live members), swapped at generation boundaries below
        el = _Elastic(cfg)
        mesh = el.mesh
    else:
        mesh = build_mesh(cfg.mesh_shape)
    # model-level attention hooks ('ring'/'flash') resolve this mesh
    from tony_tpu.parallel.mesh import set_default_mesh

    set_default_mesh(mesh)
    if jax.process_index() == 0:
        log.info("mesh: %s over %d devices", dict(mesh.shape), mesh.size)

    optimizer = default_optimizer(
        lr=cfg.lr, warmup_steps=cfg.warmup_steps,
        decay_steps=max(cfg.steps, cfg.warmup_steps + 1),
        mu_dtype=jnp.dtype(cfg.mu_dtype),
    )
    rules = cfg.rules
    if int(mesh.shape.get("pp", 1)) > 1:
        from tony_tpu.train.trainer import pp_rules

        rules = pp_rules(rules)
    step_fn = make_train_step(
        cfg.model, mesh, optimizer, rules,
        n_microbatches=cfg.pp_microbatches, pp_schedule=cfg.pp_schedule,
        grad_bucket_bytes=int(cfg.grad_bucket_mb * (1 << 20)),
    )

    # --- compile-ahead: AOT-lower/compile the step on a worker thread while
    # the main thread initialises state, restores the checkpoint, and the
    # prefetcher warms the input pipeline. Shapes suffice to lower (the jit
    # carries in_shardings), so no array needs to exist yet.
    startup: dict[str, float] = {}
    aot: dict[str, object] = {}
    compile_thread = None
    if cfg.compile_ahead:
        state_avals = train_state_avals(cfg.model, optimizer)
        batch_aval = jax.ShapeDtypeStruct(
            (cfg.data.global_batch, cfg.data.seq_len), jnp.int32
        )

        def _compile_ahead() -> None:
            t0 = time.perf_counter()
            # runs on the compile-ahead thread (empty span stack): parent
            # on train.fit explicitly or this lands beside it, not inside
            with trace.span("fit.startup.compile", parent=fit_span.sid or None):
                try:
                    with ledger.label("train.step"):
                        aot["step"] = step_fn.lower(
                            state_avals, batch_aval, batch_aval
                        ).compile()
                except Exception:
                    log.debug(
                        "compile-ahead failed; jit dispatch compiles lazily",
                        exc_info=True,
                    )
            startup["compile_s"] = round(time.perf_counter() - t0, 3)
            if "step" in aot:
                # AOT entry point: journal the measured memory plan
                # (temp/arg/output/code bytes) + cost-analysis FLOPs
                ledger.record_aot("train.step", aot["step"], startup["compile_s"])

        compile_thread = threading.Thread(
            target=_compile_ahead, name="tony-compile-ahead", daemon=True
        )
        compile_thread.start()

    state = make_train_state(jax.random.key(0), cfg.model, mesh, optimizer, rules)

    manager = None
    start_step = 0
    if cfg.checkpoint_dir:
        from tony_tpu.train.checkpoint import CheckpointManager

        manager = CheckpointManager(
            cfg.checkpoint_dir,
            keep=cfg.checkpoint_keep,
            save_interval_steps=cfg.checkpoint_every,
        )
        if cfg.resume:
            t0 = time.perf_counter()
            with trace.span("fit.startup.restore"):
                state, restored = manager.restore(state)
            startup["restore_s"] = round(time.perf_counter() - t0, 3)
            if restored >= 0:
                start_step = restored
                log.info("resumed from checkpoint step %d", restored)

    batch_sharding = NamedSharding(mesh, spec_for(("batch", "seq"), cfg.rules))
    # the prefetch producer (data.prefetch > 0) starts generating + placing
    # batches here, concurrent with the compile-ahead join below
    if el is not None:
        batches = el.make_stream(batch_sharding, start_step)
    else:
        batches = make_batches(cfg.data, batch_sharding, start_step=start_step)
    if compile_thread is not None:
        compile_thread.join()
    compiled_step = aot.get("step")

    flops_per_token = train_flops_per_token(cfg.model, cfg.data.seq_len)
    tokens_per_step = cfg.data.global_batch * cfg.data.seq_len

    def _emit(snap: dict) -> None:
        """Resolve a log boundary: device sync on the (already in-flight)
        scalars, then log + push. Called AFTER the next step is dispatched,
        so the sync never leaves the device idle."""
        m = snap["metrics"]
        # EXPLICIT device sync point (jax.device_get, not bare float()):
        # under GRAFT_SANITIZE the steady-state loop runs with implicit
        # device-to-host transfers disallowed — the log boundary is the
        # one place a sync is intended, so it is spelled out
        loss = float(jax.device_get(m["loss"]))
        # scale from the snapshot, not the live loop: the deferred emit
        # may resolve after an elastic reshard rebound tokens_per_step
        # and the mesh, and the straddling window must report at the
        # scale it actually ran at
        timer = StepTimer(
            flops_per_token=flops_per_token,
            tokens_per_step=snap.get("tokens_per_step", tokens_per_step),
            n_chips=snap.get("n_chips", mesh.size),
        )
        timer.record(snap["dt"], snap["window"], host_blocked_s=snap["host_s"])
        out = {
            "step": snap["step"],
            "loss": round(loss, 4),
            "tokens_per_sec": round(timer.tokens_per_sec, 1),
            "tokens_per_sec_per_chip": round(timer.tokens_per_sec_per_chip, 1),
            "mfu": round(timer.mfu(), 4),
            "grad_norm": round(float(jax.device_get(m["grad_norm"])), 4),
            "host_blocked_ms_per_step": round(timer.host_blocked_ms_per_step, 2),
        }
        if snap.get("startup"):
            # first step only: the startup-phase breakdown rides the first
            # METRICS push so submit_latency() can report compile vs restore
            # vs first-batch (am/events.py)
            out.update({f"startup_{k}": v for k, v in snap["startup"].items()})
        # HBM usage from the device this process owns (the nvidia-smi
        # sampling analogue; empty on platforms without memory_stats)
        from tony_tpu.obs.tpu_metrics import tpu_metrics_dict

        out.update(tpu_metrics_dict())
        if el is not None and el.journal is not None:
            # loss-continuity evidence: the log boundary's already-synced
            # scalars ride into the elastic journal (0-based step index,
            # generation captured at snapshot time — a deferred emit must
            # not stamp a boundary it predates)
            fp = m.get("health/batch_fingerprint")
            el.journal.loss(
                snap["step"] - 1, snap.get("gen", 0), loss,
                int(jax.device_get(fp)) if fp is not None else None,
            )
        if jax.process_index() == 0:
            log.info(
                "step %(step)d loss=%(loss)s %(tokens_per_sec_per_chip)s tok/s/chip "
                "mfu=%(mfu)s", out,
            )
        if on_metrics:
            on_metrics(out)

    metrics: dict = {}
    pending = None          # boundary snapshot deferred past the next dispatch
    host_window_s = 0.0     # input-blocked time in the current log window
    host_steady_s = 0.0     # input-blocked time after the first step
    steady_t0 = None        # wall clock after the first step fully resolved
    t_window = time.perf_counter()
    window = 0

    def _dispatch(state, inputs, targets):
        nonlocal compiled_step
        if compiled_step is not None:
            try:
                return compiled_step(state, inputs, targets)
            except (TypeError, ValueError):
                # aval/sharding mismatch between the AOT signature and
                # the live arrays (raised before execution, so nothing
                # was donated) — fall back to jit dispatch permanently;
                # real runtime faults (OOM etc.) propagate as usual
                log.warning(
                    "compile-ahead executable rejected live args; "
                    "falling back to jit dispatch", exc_info=True,
                )
                compiled_step = None
        return step_fn(state, inputs, targets)

    # trace spine: every trace.sample_steps-th step is a span, mirrored
    # onto the device timeline via jax.profiler.TraceAnnotation with the
    # SAME name so a Perfetto/XPlane capture lines up with tony trace
    tracer = trace.active_tracer()
    # steady-state step-time distribution (p50/p95/p99 in the final report
    # and on the portal /metrics endpoint); host-side loop cadence —
    # individual iterations are noisy under async dispatch, the
    # distribution over a run is the signal
    # per-run registry: a second fit() in the same process (bench sweeps)
    # must report THIS run's distribution, not a blend with the last one
    registry = Registry()
    h_step = registry.histogram(
        "tony_step_time_seconds",
        "train step wall time (synced sampled steps; log-window means when untraced)",
    )
    from tony_tpu.obs.profiler import annotate

    # live-series source: step progress, since-last-scrape step-time
    # quantiles, and the goodput split — read on scrape stride hits only,
    # all host-side locals (the closure reads the loop's live variables;
    # no device sync ever happens here). The SLO engine's
    # step_time_p99_s / goodput_floor inputs come from these keys.
    recorder = series.active_recorder()
    step = start_step  # the source may be scraped before the first step
    step_window = HistogramWindow()

    def _series_source() -> dict:
        out = {"step": float(step + 1)}
        if steady_t0 is not None:
            elapsed = max(time.perf_counter() - steady_t0, 1e-9)
            out["host_blocked_frac"] = round(host_steady_s / elapsed, 4)
            out["goodput_frac"] = round(
                max(1.0 - host_steady_s / elapsed, 0.0), 4
            )
        d = step_window.delta(h_step)
        if d["count"]:
            out["step_time_p50_s"] = round(d["p50"], 4)
            out["step_time_p99_s"] = round(d["p99"], 4)
            out["step_time_n"] = d["count"]
        return out

    if recorder is not None:
        recorder.attach("fit", _series_source)

    # runtime sanitizer (GRAFT_SANITIZE=1, analysis/sanitize.py): armed
    # once the first step has fully resolved — steady state must neither
    # implicitly host-sync nor compile (docs/ANALYSIS.md "Sanitizer")
    from tony_tpu.analysis import sanitize

    san_stack = contextlib.ExitStack()
    watchdog = None
    try:
        for step in range(start_step, cfg.steps):
            if el is not None:
                # elastic generation boundary: a pending membership change
                # (AM broadcast or scripted plan) is applied HERE — fence,
                # donate from the fenced state, rebuild mesh/step/stream
                # against the new topology, keep stepping
                rec = el.pending(step)
                if rec is not None:
                    if watchdog is not None:
                        # a reshard legitimately re-compiles: step out of
                        # the sanitizer for the boundary and re-arm after,
                        # so the compile watchdog budgets steady state only
                        san_stack.close()
                        watchdog = None
                    (state, step_fn, compiled_step, mesh,
                     batch_sharding) = el.reshard(
                        rec, step, state, optimizer, rules, ledger
                    )
                    batches = el.stream
                    tokens_per_step = (
                        el.stream.global_batch * cfg.data.seq_len
                    )
                    if sanitize.enabled() and steady_t0 is not None:
                        watchdog = san_stack.enter_context(
                            sanitize.sanitized_loop("fit")
                        )
            t_fetch = time.perf_counter()
            if step == start_step:
                with trace.span("fit.startup.first_batch"):
                    inputs, targets = next(batches)
                fetch_s = time.perf_counter() - t_fetch
                startup["first_batch_s"] = round(fetch_s, 3)
            else:
                inputs, targets = next(batches)
                fetch_s = time.perf_counter() - t_fetch
                host_window_s += fetch_s
                host_steady_s += fetch_s
            # coordinated-profiling seam: one global load + None compare
            # disarmed; during an AM-broadcast window this boundary starts/
            # advances the device-trace capture and attributes this step's
            # input wait (fetch_s is a precomputed local — GL005)
            profile.maybe_capture(fetch_s=fetch_s)
            # first step excluded from sampling (like h_step below): its
            # compile/warmup-inflated duration would be stride-scaled by
            # the goodput roll-up, and its fetch is already attributed to
            # the fit.startup.first_batch span
            sp = trace.NOOP_SPAN
            if tracer is not None and step != start_step:
                sp = tracer.sampled_span(
                    "train.step", step=step + 1,
                    fetch_ms=round(fetch_s * 1e3, 3),
                )
            if sp is not trace.NOOP_SPAN:
                # dispatch is async: an unsynced span times the enqueue
                # (microseconds) and the goodput roll-up would misattribute
                # the whole window. Drain the dispatch backlog BEFORE the
                # span, sync on the result inside it, so the span covers
                # exactly this step's device time; the cost is one pipeline
                # sync per sample_steps — same class as the deferred
                # log-boundary sync.
                jax.block_until_ready(state)
                t_sync = time.perf_counter()
                with sp, annotate("train.step"):
                    state, metrics = _dispatch(state, inputs, targets)
                    jax.block_until_ready(metrics)
                # the synced iteration observes the span-internal time (true
                # device step, backlog excluded). Unsampled iterations never
                # observe: under async dispatch they time only the enqueue,
                # and mixing the two classes makes the quantiles bimodal
                # nonsense. Disarmed runs fall back to log-window means at
                # the boundary below — every observation in one histogram is
                # measured the same way.
                h_step.observe(time.perf_counter() - t_sync)
            else:
                state, metrics = _dispatch(state, inputs, targets)
            hbm.sample()  # stride-counted device-memory reading (no sync)
            # stride-counted health sample: enqueues DEVICE references for
            # the sentinel's worker thread (the device_get sync happens
            # there, never here — the step loop stays unblocked)
            health.sample(metrics=metrics)
            # stride-counted series scrape: host-side locals + counters
            # only; journaling happens on the recorder's writer thread
            series.sample()
            if el is not None:
                # membership evidence (host-side append, no sync) + the
                # async device->host checkpoint shadow on its stride
                el.note_step(step)
                el.shadow.maybe_update(step + 1, state)
            window += 1
            if pending is not None:
                _emit(pending)  # previous boundary, now that N+1 is in flight
                pending = None
            # the very first step always logs/pushes: it closes the AM-submit
            # -> first-step loop (the north-star latency metric — the AM
            # timestamps the resulting METRICS event) and gives users signal
            # before a long log_every window elapses
            if step == start_step or (step + 1) % cfg.log_every == 0 or step + 1 == cfg.steps:
                now = time.perf_counter()
                snap = {
                    "step": step + 1,
                    "metrics": metrics,
                    "dt": now - t_window,
                    "window": window,
                    "host_s": host_window_s,
                    "startup": dict(startup) if step == start_step else None,
                    "gen": el.controller.generation if el is not None else 0,
                    "tokens_per_step": tokens_per_step,
                    "n_chips": mesh.size,
                }
                _start_async_host_copy(metrics)
                if tracer is None and step != start_step:
                    # disarmed step-time source: the window mean (wall time
                    # over completed steps — accurate without a per-step
                    # sync). The first window is excluded like everywhere
                    # else: it absorbs compile/warmup.
                    h_step.observe(snap["dt"] / max(snap["window"], 1))
                if step == start_step or step + 1 == cfg.steps:
                    # first step: latency metric, sync now; last step: the
                    # loop ends here, nothing left to overlap with
                    _emit(snap)
                else:
                    pending = snap
                t_window = time.perf_counter()
                window = 0
                host_window_s = 0.0
                if step == start_step:
                    steady_t0 = time.perf_counter()
                    if sanitize.enabled():
                        watchdog = san_stack.enter_context(
                            sanitize.sanitized_loop("fit")
                        )
            if watchdog is not None:
                watchdog.check()  # fail at the offending step, not the end
            if manager is not None and manager.should_save(step + 1):
                manager.save(step + 1, state)
        san_stack.close()  # sanitizer covers exactly the steady-state steps
        if pending is not None:
            _emit(pending)
            pending = None
        steady_end = time.perf_counter()  # before checkpoint settling
    finally:
        san_stack.close()
        # a profile window still open when the loop ends (requested window
        # longer than the remaining steps, exception mid-capture) finalises
        # here — the partial trace + manifest land instead of vanishing
        profile.finish_capture()
        close_batches(batches)
        if el is not None:
            # shadow thread + generation watcher + journal handle; the
            # stream was closed above (close_batches), close() tolerates it
            el.close()
        if recorder is not None:
            # final scrape (the shutdown state lands in the journal, and
            # any last-window SLO trip evaluates) before the source whose
            # locals are about to die is detached
            recorder.force_sample()
            recorder.drain()
            recorder.detach("fit")
    if manager is not None:
        manager.wait()  # settle async saves before checking what exists
        if manager.latest_step() != cfg.steps:
            manager.save(cfg.steps, state, force=True)
        manager.close()
    final = {"final_loss": float(metrics.get("loss", float("nan"))), "steps": cfg.steps}
    if h_step.count:
        # step-time distribution (bucketed quantiles): the portal /metrics
        # endpoint re-renders the full histogram from the snapshot below
        final["step_time_p50_s"] = round(h_step.quantile(0.5), 4)
        final["step_time_p95_s"] = round(h_step.quantile(0.95), 4)
        final["step_time_p99_s"] = round(h_step.quantile(0.99), 4)
    if reporter is not None:
        reporter.close()
        if reporter.dropped:
            final["metrics_dropped"] = reporter.dropped
    # health verdict: drain the sentinel's queue so a trip on the final
    # steps lands in the final report, then export tony_health_* into the
    # per-run registry (snapshotted below) and persist the verdict file
    # the portal /healthz and `tony health` read
    sentinel = health.active_sentinel()
    if sentinel is not None:
        sentinel.drain()
        final["health_verdict"] = sentinel.verdict
        trips = sentinel.trip_counts()
        if trips:
            final["health_trips"] = trips
        sentinel.export(registry)
        sentinel.write_verdict()
    # SLO verdict (obs/slo.py): the burn-rate engine evaluated async on
    # the series writer thread (drained above); export tony_slo_* into
    # the per-run registry and persist the verdict — `met` is recorded,
    # so a missing verdict stays distinguishable from a passing one
    slo_engine = slo.active_engine()
    if slo_engine is not None:
        final["slo_verdict"] = slo_engine.verdict
        slo_trips = slo_engine.trip_counts()
        if slo_trips:
            final["slo_trips"] = slo_trips
        slo_engine.export(registry)
        slo_engine.write_verdict()
    # registry snapshot into the job history (no-op outside a tony job);
    # suffixed so a train-then-serve user process cannot overwrite one
    # component's snapshot with the other's. The HBM gauges export into
    # THIS registry first, so tony_hbm_* reaches the portal /metrics.
    if hbm_watch is not None:
        hbm_watch.export_gauges(registry)
    snapshot_to_app_dir(trace.default_proc_name("train") + "_fit", registry)
    # compile-ledger snapshot for `tony compiles <app_id>` (process-scoped,
    # so the bare proc name; no-op outside a tony job) + summary lines
    compile_ledger.snapshot_to_app_dir()
    final["xla_compiles"] = ledger.backend_compiles - compiles_t0
    if hbm_mark is not None:
        peak_gb, peak_exact = hbm_watch.peak_since(hbm_mark)
        if peak_gb:
            final["peak_hbm_gb"] = peak_gb
            final["peak_hbm_exact"] = peak_exact
    # steady-state input-stall + throughput accounting (first step excluded:
    # it absorbs warmup). The last boundary _emit synced the final step, so
    # the wall-clock window below covers completed work only.
    steady_steps = max(cfg.steps - start_step - 1, 0)
    if steady_t0 is not None and steady_steps > 0:
        steady_elapsed = max(steady_end - steady_t0, 1e-9)
        final["tokens_per_sec_per_chip"] = round(
            steady_steps * tokens_per_step / steady_elapsed / mesh.size, 1
        )
        final["host_blocked_ms_per_step"] = round(
            host_steady_s / steady_steps * 1e3, 2
        )
        final["host_blocked_frac"] = round(host_steady_s / steady_elapsed, 4)
    if startup:
        final["startup"] = dict(startup)
    if el is not None:
        # elastic roll-up: final generation/membership, warm-restart count
        # + cost (the same number `tony trace` goodput reads off the
        # elastic.reshard spans as restart_s)
        final["elastic"] = el.summary()
    if jax.process_index() == 0:
        # shutdown summary: silent metric loss must be visible in the
        # worker log, not only behind the portal
        log.info(
            "fit summary: steps=%d loss=%.4f step_p50=%.3fs step_p99=%.3fs "
            "host_blocked=%s metrics_dropped=%d peak_hbm_gb=%s xla_compiles=%d",
            cfg.steps, final["final_loss"],
            final.get("step_time_p50_s", 0.0), final.get("step_time_p99_s", 0.0),
            final.get("host_blocked_frac", 0.0), final.get("metrics_dropped", 0),
            final.get("peak_hbm_gb", 0.0), final["xla_compiles"],
        )
    return final


__all__ = ["FitConfig", "fit"]

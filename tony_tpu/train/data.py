"""Input pipelines: synthetic + memory-mapped token streams.

The reference has no data layer (user scripts bring their own input_fn);
this module provides the minimum a training job needs in a zero-egress
environment: a deterministic synthetic LM stream (benchmarks, tests) and a
memory-mapped binary token file reader (real corpora), both yielding
pre-shifted (inputs, targets) pairs shaped for the mesh's batch sharding.

Per-process sharding follows the jax.distributed contract: each process
yields only its slice of the global batch
(process_index/process_count), and jax.make_array_from_process_local_data
assembles the global array.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

Batch = tuple[jax.Array, jax.Array]  # (inputs [B,S], targets [B,S])


@dataclass(frozen=True)
class DataConfig:
    global_batch: int = 8
    seq_len: int = 2048
    vocab_size: int = 32000
    seed: int = 0
    path: str = ""  # empty -> synthetic
    # token files route through the C++ prefetching loader (shuffled epochs,
    # IO off the GIL) when it can build; False pins the numpy mmap path
    # (deterministic sequential windows)
    native: bool = True
    # device-prefetch depth: batches N+1..N+prefetch are host-generated and
    # device-placed on a background thread while the device runs step N
    # (train/prefetch.py). 0 pins the legacy synchronous path. The stream
    # order is identical either way (FIFO, single producer).
    prefetch: int = 2


def _local_slice(global_batch: int) -> tuple[int, int]:
    n, i = jax.process_count(), jax.process_index()
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by {n} processes")
    per = global_batch // n
    return per, i * per


def synthetic_batches(
    cfg: DataConfig, sharding: NamedSharding | None = None, start_step: int = 0
) -> Iterator[Batch]:
    """Endless deterministic token stream (Zipf-ish marginals so the loss
    moves like text, not uniform noise). ``start_step`` keys the generator
    per batch, so a checkpoint-resumed job continues the stream instead of
    replaying it."""
    per, _ = _local_slice(cfg.global_batch)
    ranks = np.arange(1, cfg.vocab_size + 1, dtype=np.float64)
    probs = (1.0 / ranks) / np.sum(1.0 / ranks)
    # inverse-CDF sampling over a cumulative table built ONCE: rng.choice(p=)
    # rebuilds its alias/sampling setup every call, which at vocab 32k was
    # the dominant host cost per batch. searchsorted(cum, U) draws the same
    # Zipf marginals (token t iff cum[t-1] <= U < cum[t]); the tail is
    # pinned to 1.0 so float rounding can never index past vocab_size-1.
    cum = np.cumsum(probs)
    cum[-1] = 1.0
    step = start_step
    while True:
        rng = np.random.default_rng((cfg.seed + jax.process_index(), step))
        draws = rng.random((per, cfg.seq_len + 1))
        tokens = np.searchsorted(cum, draws, side="right").astype(np.int32)
        step += 1
        yield _to_global(tokens, sharding)


def mmap_batches(
    cfg: DataConfig, sharding: NamedSharding | None = None, start_step: int = 0
) -> Iterator[Batch]:
    """Sequential reader over a flat binary int32 token file (np.memmap).

    Each process strides disjoint windows; wraps around at EOF. ``start_step``
    resumes the stream at the position step N would have read (elastic
    restart: no token is replayed or skipped).
    """
    data = np.memmap(cfg.path, dtype=np.int32, mode="r")
    per, off = _local_slice(cfg.global_batch)
    window = cfg.seq_len + 1
    stride = cfg.global_batch * window
    n = len(data)
    if n < stride:
        raise ValueError(f"token file too small: {n} tokens < one global batch {stride}")
    steps_per_epoch = n // stride  # windows before wrap-around
    step = start_step
    while True:
        pos = (step % steps_per_epoch) * stride + off * window
        chunk = data[pos : pos + per * window].reshape(per, window)
        # One contiguous copy per array instead of two strided views into
        # the page cache: the sharding assembler can then zero-copy whole
        # row-contiguous shards. The pair must be freshly owned by its
        # batch — jax's CPU device_put aliases compatible host buffers, so
        # a reused/preallocated ring would let a later copy corrupt a batch
        # still queued on device (breaks prefetch>0 determinism).
        out = (
            np.empty((per, cfg.seq_len), np.int32),
            np.empty((per, cfg.seq_len), np.int32),
        )
        step += 1
        yield _to_global(chunk, sharding, out=out)


def _to_global(
    tokens: np.ndarray,
    sharding: NamedSharding | None,
    out: tuple[np.ndarray, np.ndarray] | None = None,
) -> Batch:
    """Shift ``tokens`` into (inputs, targets) and assemble device arrays.

    ``out`` is an optional preallocated (inputs, targets) buffer pair: the
    shifted slices are written there in one contiguous pass each, so the
    assembler receives C-contiguous in-memory arrays instead of strided
    views into an mmap.
    """
    if out is not None:
        inputs, targets = out
        np.copyto(inputs, tokens[:, :-1])
        np.copyto(targets, tokens[:, 1:])
    else:
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
    return _assemble(inputs, targets, sharding)


def _assemble(
    inputs: np.ndarray, targets: np.ndarray, sharding: NamedSharding | None
) -> Batch:
    if sharding is None:
        return jnp.asarray(inputs), jnp.asarray(targets)
    return (
        jax.make_array_from_process_local_data(sharding, inputs),
        jax.make_array_from_process_local_data(sharding, targets),
    )


def native_batches(
    cfg: DataConfig, sharding: NamedSharding | None = None, start_step: int = 0
) -> Iterator[Batch]:
    """Prefetched shuffled windows via the C++ loader (train/native_loader).

    Same contract as mmap_batches — per-process [per, seq_len+1] chunks,
    ``start_step`` resume-exact via seek() — but each epoch visits every
    window of this process's shard once in a seeded order, and the read +
    shuffle + copy happens on a native thread that overlaps the device step.
    """
    from tony_tpu.train.native_loader import NativeTokenLoader

    per, _ = _local_slice(cfg.global_batch)
    loader = NativeTokenLoader(
        cfg.path, cfg.seq_len, per,
        n_shards=jax.process_count(), shard_id=jax.process_index(),
        seed=cfg.seed,
    )
    try:
        loader.seek(start_step)
        while True:
            # fresh owned contiguous pair per batch (same aliasing rule as
            # mmap_batches), filled by the loader without an extra copy
            out = (
                np.empty((per, cfg.seq_len), np.int32),
                np.empty((per, cfg.seq_len), np.int32),
            )
            loader.next_into(*out)
            yield _assemble(out[0], out[1], sharding)
    finally:
        # generator close (incl. PrefetchIterator.close / GC) frees the
        # native handle + mmap deterministically
        loader.close()


def make_batches(
    cfg: DataConfig, sharding: NamedSharding | None = None, start_step: int = 0
) -> Iterator[Batch]:
    """Build the configured batch stream; with ``cfg.prefetch > 0`` it is
    wrapped in a :class:`~tony_tpu.train.prefetch.PrefetchIterator` (same
    element order, host+H2D work overlapped with the device step). Streams
    that own a thread expose ``close()``; ``fit()`` calls it on exit."""
    it = _make_batches_raw(cfg, sharding, start_step)
    if cfg.prefetch > 0:
        from tony_tpu.train.prefetch import PrefetchIterator

        return PrefetchIterator(it, depth=cfg.prefetch)
    return it


def _make_batches_raw(
    cfg: DataConfig, sharding: NamedSharding | None = None, start_step: int = 0
) -> Iterator[Batch]:
    if cfg.path:
        if cfg.native:
            from tony_tpu.train import native_loader

            if native_loader.available():
                # in a gang, every process must take this same branch; a
                # process whose build fails raises below instead of silently
                # mixing shuffled and sequential sampling in one global batch
                return native_batches(cfg, sharding, start_step)
            if jax.process_count() > 1:
                raise RuntimeError(
                    "native token loader unavailable on this host but "
                    "data.native=True in a multi-process job — the gang "
                    "would mix sampling schemes. Install g++ everywhere or "
                    "set DataConfig(native=False)."
                )
            import logging

            logging.getLogger(__name__).warning(
                "native loader unavailable; falling back to sequential "
                "mmap windows (different sampling + resume stream)"
            )
        return mmap_batches(cfg, sharding, start_step)
    return synthetic_batches(cfg, sharding, start_step)


__all__ = [
    "Batch", "DataConfig", "make_batches", "mmap_batches", "native_batches",
    "synthetic_batches",
]

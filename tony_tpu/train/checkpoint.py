"""Checkpoint/resume glue: orbax async multi-host checkpointing.

The reference does NOT checkpoint model state — that is the user script's
job; TonY contributes restart orchestration + durable paths (SURVEY.md
section 5 "Checkpoint/resume"). This module keeps the same separation but
ships the glue first-class: a CheckpointManager wired to the AM's restart
path, so a gang-restarted job resumes at the last step (milestone config #5).

Works single-process and multi-process (orbax coordinates across
jax.distributed automatically; saves are async so the train loop never
blocks on HBM->disk).
"""

from __future__ import annotations

import logging
import os
import shutil
from typing import Any

import jax
import orbax.checkpoint as ocp

log = logging.getLogger(__name__)

# orbax's in-progress marker: saves land in `<step>.orbax-checkpoint-tmp-*`
# and are atomically renamed on commit, so a SIGKILL mid-save (the exact
# elastic-preemption scenario) leaves a tmp dir, never a torn final step
_TMP_MARKER = ".orbax-checkpoint-tmp"


class CheckpointManager:
    """Thin orbax wrapper bound to a directory and keep policy.

    Crash-safety contract (pinned by tests/test_elastic.py's
    kill-mid-save test): a process SIGKILLed at ANY point during save can
    never corrupt the latest checkpoint — in-progress saves live in a
    temp dir and only an atomic rename publishes them. This wrapper adds
    the two pieces orbax leaves to the caller: stale tmp dirs from a
    killed predecessor are reaped at open (they would otherwise
    accumulate forever under the job dir), and ``restore`` falls back to
    the previous durable step if the newest one turns out unreadable
    (e.g. a non-atomic-rename filesystem) instead of wedging the restart
    on the exact artifact the crash produced.
    """

    def __init__(self, directory: str, *, keep: int = 3, save_interval_steps: int = 0):
        self.directory = directory
        self._interval = save_interval_steps
        self._reap_interrupted_saves()
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                save_interval_steps=max(save_interval_steps, 1),
                enable_async_checkpointing=True,
            ),
        )

    def _reap_interrupted_saves(self) -> None:
        """Drop tmp dirs a SIGKILLed save left behind. Only ever touches
        ``*.orbax-checkpoint-tmp-*`` names — committed steps are plain
        ``<step>/`` dirs and can never match."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if _TMP_MARKER not in name:
                continue
            path = os.path.join(self.directory, name)
            log.warning(
                "reaping interrupted checkpoint save %s (crashed mid-save)",
                path,
            )
            shutil.rmtree(path, ignore_errors=True)

    def should_save(self, step: int) -> bool:
        return self._interval > 0 and step % self._interval == 0

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Async save; returns whether a save was started."""
        return self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )

    def restore(self, state_template: Any, step: int | None = None) -> tuple[Any, int]:
        """Restore the latest (or given) step into the template's shardings.

        Returns (state, step); (template, -1) when no checkpoint exists —
        the caller starts from scratch. When restoring the LATEST step, an
        unreadable newest checkpoint (a crash mid-save on a filesystem
        without atomic rename) falls back to the PREVIOUS durable step —
        the elastic restart must come back from something, not wedge on
        the one artifact the crash produced. The fallback is exactly one
        step deep: only the newest step can be crash-torn, so a second
        consecutive failure is a systematic problem (changed model shape,
        corrupt store) and re-raises rather than silently walking every
        checkpoint back to from-scratch training. An explicitly-requested
        step always raises: the caller asked for that exact state.
        """
        target = step if step is not None else self.latest_step()
        if target is None or target < 0:
            return state_template, -1
        template = jax.tree.map(_as_restore_leaf, state_template)
        try:
            return self._mgr.restore(
                target, args=ocp.args.StandardRestore(template)
            ), target
        except Exception:
            if step is not None:
                raise
            earlier = [s for s in (self._mgr.all_steps() or []) if s < target]
            if not earlier:
                raise
            prev = max(earlier)
            log.warning(
                "checkpoint step %d unreadable (interrupted save?); "
                "falling back to step %d", target, prev, exc_info=True,
            )
            return self._mgr.restore(
                prev, args=ocp.args.StandardRestore(template)
            ), prev

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def wait(self) -> None:
        """Block until in-flight async saves are durable."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def _as_restore_leaf(x: Any) -> Any:
    """Restore into abstract shaped leaves so orbax re-shards on load."""
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x


__all__ = ["CheckpointManager"]

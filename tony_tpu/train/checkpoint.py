"""Checkpoint/resume glue: orbax async multi-host checkpointing.

The reference does NOT checkpoint model state — that is the user script's
job; TonY contributes restart orchestration + durable paths (SURVEY.md
section 5 "Checkpoint/resume"). This module keeps the same separation but
ships the glue first-class: a CheckpointManager wired to the AM's restart
path, so a gang-restarted job resumes at the last step (milestone config #5).

Works single-process and multi-process (orbax coordinates across
jax.distributed automatically; saves are async so the train loop never
blocks on HBM->disk).
"""

from __future__ import annotations

import logging
from typing import Any

import jax
import orbax.checkpoint as ocp

log = logging.getLogger(__name__)


class CheckpointManager:
    """Thin orbax wrapper bound to a directory and keep policy."""

    def __init__(self, directory: str, *, keep: int = 3, save_interval_steps: int = 0):
        self.directory = directory
        self._interval = save_interval_steps
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                save_interval_steps=max(save_interval_steps, 1),
                enable_async_checkpointing=True,
            ),
        )

    def should_save(self, step: int) -> bool:
        return self._interval > 0 and step % self._interval == 0

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Async save; returns whether a save was started."""
        return self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )

    def restore(self, state_template: Any, step: int | None = None) -> tuple[Any, int]:
        """Restore the latest (or given) step into the template's shardings.

        Returns (state, step); (template, -1) when no checkpoint exists —
        the caller starts from scratch.
        """
        target = step if step is not None else self.latest_step()
        if target is None or target < 0:
            return state_template, -1
        restored = self._mgr.restore(
            target,
            args=ocp.args.StandardRestore(jax.tree.map(_as_restore_leaf, state_template)),
        )
        return restored, target

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def wait(self) -> None:
        """Block until in-flight async saves are durable."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def _as_restore_leaf(x: Any) -> Any:
    """Restore into abstract shaped leaves so orbax re-shards on load."""
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x


__all__ = ["CheckpointManager"]

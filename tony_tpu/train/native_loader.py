"""ctypes binding for the native token loader (tony_tpu/native/tonyloader.cpp).

The C++ loader prefetches shuffled (seq_len+1)-token windows from a
memory-mapped corpus on a real thread, off the GIL — the trainer's host step
overlaps with input IO. Built on demand with g++ (pybind11 is not in the
image; the C ABI + ctypes needs no build-time Python headers).

Falls back cleanly: ``available()`` is False when no compiler/binary exists,
and train/data.py keeps its pure-numpy path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Iterator

import numpy as np

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "..", "native", "tonyloader.cpp")
_LIB_NAME = "libtonyloader.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def _build_dir() -> str:
    d = os.environ.get("TONY_NATIVE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "tony-tpu"
    )
    os.makedirs(d, exist_ok=True)
    return d


def _load() -> ctypes.CDLL | None:
    global _lib
    # the lock's purpose is to serialize the ONE-TIME native build across
    # threads racing the first loader construction; after that it guards a
    # cached-handle read. Holding it across the compile is the design.
    with _lock:
        if _lib is not None:
            return _lib
        lib_path = os.path.join(_build_dir(), _LIB_NAME)  # graft-lint: disable=GL004
        src = os.path.abspath(_SRC)
        if not os.path.exists(src):
            return None
        if (not os.path.exists(lib_path)
                or os.path.getmtime(lib_path) < os.path.getmtime(src)):
            try:
                subprocess.run(  # graft-lint: disable=GL004
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
                     src, "-o", lib_path],
                    check=True, capture_output=True, timeout=120,
                )
            except (OSError, subprocess.SubprocessError) as e:
                log.warning("native loader build failed: %s", e)
                return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError as e:
            log.warning("native loader load failed: %s", e)
            return None
        lib.tl_open.restype = ctypes.c_void_p
        lib.tl_open.argtypes = [ctypes.c_char_p, ctypes.c_long, ctypes.c_long,
                                ctypes.c_long, ctypes.c_long, ctypes.c_ulonglong]
        lib.tl_next.restype = ctypes.c_long
        lib.tl_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
        lib.tl_windows_per_epoch.restype = ctypes.c_long
        lib.tl_windows_per_epoch.argtypes = [ctypes.c_void_p]
        lib.tl_seek.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.tl_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class NativeTokenLoader:
    """Shuffled, prefetched batches from a flat int32 token file.

    Yields [batch, seq_len+1] int32 arrays; each epoch covers every window
    of this shard exactly once in a seeded order. ``seek(step)`` gives
    resume-exact positioning for elastic restart.
    """

    def __init__(self, path: str, seq_len: int, batch: int,
                 n_shards: int = 1, shard_id: int = 0, seed: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native loader unavailable (no g++ or build failed)")
        self._lib = lib
        self._handle = lib.tl_open(
            path.encode(), seq_len, batch, n_shards, shard_id, seed
        )
        if not self._handle:
            raise ValueError(
                f"tl_open failed for {path!r} (missing file, too few windows "
                f"for batch={batch} x shards={n_shards}, or shard_id "
                f"{shard_id} outside [0, {n_shards}))"
            )
        self.batch = batch
        self.window = seq_len + 1
        self._buf = np.empty((batch, self.window), np.int32)

    @property
    def steps_per_epoch(self) -> int:
        return self._lib.tl_windows_per_epoch(self._handle)

    def seek(self, step: int) -> None:
        self._lib.tl_seek(self._handle, step)

    def next(self) -> np.ndarray:
        rc = self._lib.tl_next(
            self._handle, self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        )
        if rc != 0:
            raise RuntimeError("native loader stopped")
        return self._buf.copy()

    def next_into(self, inputs: np.ndarray, targets: np.ndarray) -> None:
        """Read the next window's pre-shifted (inputs, targets) pair directly
        into caller-owned contiguous buffers — skips next()'s intermediate
        defensive copy (the data layer's single-contiguous-copy contract)."""
        rc = self._lib.tl_next(
            self._handle, self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        )
        if rc != 0:
            raise RuntimeError("native loader stopped")
        np.copyto(inputs, self._buf[:, :-1])
        np.copyto(targets, self._buf[:, 1:])

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next()

    def close(self) -> None:
        if self._handle:
            self._lib.tl_close(self._handle)
            self._handle = None

    def __enter__(self) -> "NativeTokenLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = ["NativeTokenLoader", "available"]

// tonyloader: native token-stream loader for tony-tpu.
//
// The reference framework's runtime is JVM-native and delegates input
// pipelines to the frameworks it launches; here the training-side input path
// is first-class, and the hot part — striding shuffled windows out of a
// memory-mapped token file while the trainer computes — is implemented in
// C++ so prefetch runs on a real thread, off the Python GIL.
//
// Design:
//   - mmap the int32 token file (zero-copy reads, page cache does the IO)
//   - windows of (seq_len + 1) tokens; each epoch visits every window of
//     this shard once, in a deterministic Fisher-Yates order seeded by
//     (seed, epoch) — restart-reproducible, matching train/data.py contracts
//   - a background thread keeps a small ring of batches filled; tl_next()
//     blocks only when the trainer outruns the disk
//
// C ABI (ctypes binding in tony_tpu/train/native_loader.py):
//   void* tl_open(const char* path, long seq_len, long batch,
//                 long n_shards, long shard_id, unsigned long long seed)
//   long  tl_next(void* h, int* out)   // fills batch*(seq_len+1); 0 on ok
//   long  tl_windows_per_epoch(void* h)
//   void  tl_seek(void* h, long step)  // resume-exact positioning
//   void  tl_close(void* h)
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread tonyloader.cpp -o libtonyloader.so
// (tony_tpu/train/native_loader.py does this on demand)

#include <array>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr int kRingSlots = 4;

struct Loader {
  const int32_t* data = nullptr;
  size_t n_tokens = 0;
  size_t file_bytes = 0;
  int fd = -1;

  long seq_len = 0;
  long batch = 0;
  long n_shards = 1;
  long shard_id = 0;
  uint64_t seed = 0;

  size_t window = 0;            // seq_len + 1
  size_t windows_total = 0;     // in the whole file
  size_t windows_shard = 0;     // owned by this shard
  std::vector<uint32_t> order;  // permutation of this shard's windows
  uint64_t order_epoch = ~0ull; // epoch the permutation was built for

  // ring buffer of prefetched batches
  std::vector<std::vector<int32_t>> ring;
  std::array<std::atomic<bool>, kRingSlots> ready{};
  std::atomic<long> head{0};    // next batch step to produce
  long tail = 0;                // next batch step to consume
  std::mutex mu;
  std::condition_variable cv_produce, cv_consume;
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> generation{0};  // bumped by tl_seek; stale fills dropped
  std::thread worker;

  ~Loader() {
    {
      // stop must flip under mu: a waiter that has evaluated its predicate
      // but not yet blocked would otherwise miss the notify forever.
      std::lock_guard<std::mutex> lock(mu);
      stop.store(true);
      cv_produce.notify_all();
      cv_consume.notify_all();
    }
    if (worker.joinable()) worker.join();
    if (data != nullptr) munmap(const_cast<int32_t*>(data), file_bytes);
    if (fd >= 0) close(fd);
  }

  void build_order(uint64_t epoch) {
    if (order_epoch == epoch) return;
    order.resize(windows_shard);
    for (size_t i = 0; i < windows_shard; ++i) order[i] = static_cast<uint32_t>(i);
    std::mt19937_64 rng(seed * 0x9E3779B97F4A7C15ull + epoch);
    for (size_t i = windows_shard - 1; i > 0; --i) {
      size_t j = rng() % (i + 1);
      std::swap(order[i], order[j]);
    }
    order_epoch = epoch;
  }

  // copy the tokens of global batch-step `step` into dst
  void fill(long step, int32_t* dst) {
    const long per_epoch = static_cast<long>(windows_shard / batch);
    const uint64_t epoch = static_cast<uint64_t>(step / per_epoch);
    const long in_epoch = step % per_epoch;
    build_order(epoch);
    for (long b = 0; b < batch; ++b) {
      const uint32_t local = order[in_epoch * batch + b];
      // shard w owns windows (w, w + n_shards, w + 2*n_shards, ...)
      const size_t global_win = static_cast<size_t>(local) * n_shards + shard_id;
      const size_t off = global_win * window;
      std::memcpy(dst + b * window, data + off, window * sizeof(int32_t));
    }
  }

  void run() {
    while (!stop.load()) {
      uint64_t gen;
      long step;
      int slot;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_produce.wait(lock, [&] {
          return stop.load() || !ready[head.load() % kRingSlots].load();
        });
        if (stop.load()) return;
        gen = generation.load();
        step = head.load();
        slot = static_cast<int>(step % kRingSlots);
      }
      fill(step, ring[slot].data());
      std::unique_lock<std::mutex> lock(mu);
      if (generation.load() != gen) continue;  // superseded by a seek
      ready[slot].store(true);
      head.fetch_add(1);
      cv_consume.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* tl_open(const char* path, long seq_len, long batch, long n_shards,
              long shard_id, unsigned long long seed) {
  auto* L = new Loader();
  L->seq_len = seq_len;
  L->batch = batch;
  L->n_shards = n_shards > 0 ? n_shards : 1;
  if (shard_id < 0 || shard_id >= L->n_shards) { delete L; return nullptr; }
  L->shard_id = shard_id;
  L->seed = seed;
  L->window = static_cast<size_t>(seq_len) + 1;

  L->fd = open(path, O_RDONLY);
  if (L->fd < 0) { delete L; return nullptr; }
  struct stat st;
  if (fstat(L->fd, &st) != 0) { delete L; return nullptr; }
  L->file_bytes = static_cast<size_t>(st.st_size);
  L->n_tokens = L->file_bytes / sizeof(int32_t);
  L->windows_total = L->n_tokens / L->window;
  L->windows_shard = L->windows_total / L->n_shards;
  if (L->windows_shard < static_cast<size_t>(batch)) { delete L; return nullptr; }

  void* mem = mmap(nullptr, L->file_bytes, PROT_READ, MAP_PRIVATE, L->fd, 0);
  if (mem == MAP_FAILED) { delete L; return nullptr; }
  L->data = static_cast<const int32_t*>(mem);
  madvise(mem, L->file_bytes, MADV_RANDOM);  // shuffled window order

  L->ring.assign(kRingSlots, std::vector<int32_t>(batch * L->window));
  for (auto& r : L->ready) r.store(false);
  L->worker = std::thread([L] { L->run(); });
  return L;
}

long tl_windows_per_epoch(void* h) {
  auto* L = static_cast<Loader*>(h);
  return static_cast<long>(L->windows_shard / L->batch);
}

void tl_seek(void* h, long step) {
  auto* L = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lock(L->mu);
  // drop everything prefetched (and anything mid-fill, via the generation
  // bump) and restart production at `step`
  L->generation.fetch_add(1);
  for (auto& r : L->ready) r.store(false);
  L->head.store(step);
  L->tail = step;
  L->cv_produce.notify_all();
}

long tl_next(void* h, int32_t* out) {
  auto* L = static_cast<Loader*>(h);
  int slot = static_cast<int>(L->tail % kRingSlots);
  {
    std::unique_lock<std::mutex> lock(L->mu);
    L->cv_consume.wait(lock, [&] { return L->stop.load() || L->ready[slot].load(); });
    if (L->stop.load()) return -1;
  }
  // The producer never touches a slot while ready[slot] is true, so the copy
  // can run unlocked; the hand-back (ready=false) must happen under mu so the
  // producer's predicate check and our notify can't interleave into a lost
  // wakeup that parks the prefetch thread forever.
  std::memcpy(out, L->ring[slot].data(), L->batch * L->window * sizeof(int32_t));
  {
    std::lock_guard<std::mutex> lock(L->mu);
    L->ready[slot].store(false);
    L->tail += 1;
    L->cv_produce.notify_one();
  }
  return 0;
}

void tl_close(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"

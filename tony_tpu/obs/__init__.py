"""Observability: step metrics, resource monitor, profiler glue, portal.

Only the stdlib-only TaskMonitor is exported eagerly; metrics.py imports jax
at module top, so it is deliberately NOT re-exported here — executors for
non-JAX frameworks import this package from the metrics thread and must not
pay (or fail on) a jax import.
"""

from tony_tpu.obs.monitor import TaskMonitor

__all__ = ["TaskMonitor"]

"""Observability: step metrics, resource monitor, profiler, portal, proxy.

Only the stdlib-only TaskMonitor is exported eagerly; metrics.py imports jax
at module top, so it is deliberately NOT re-exported here — executors for
non-JAX frameworks import this package from the metrics thread and must not
pay (or fail on) a jax import. Portal/proxy/profiler/reporter are run or
imported as submodules.
"""

from tony_tpu.obs.monitor import TaskMonitor

__all__ = ["TaskMonitor"]

"""``tony top <app_id>``: a live terminal view of one application.

The ``yarn top`` analogue, fed by the live observability stack instead of
the scheduler alone: per-host rows come from the series journals
(obs/series.py) and the AM's heartbeat-path rollup, sparklines render the
recent TTFT / queue-depth / step trend, straggler flags reuse the trace
tool's heartbeat-progress analysis (obs/trace_tool.stragglers), and the
SLO / health columns read the verdict files — everything a deviceless
read, so ``tony top`` works on a live job, a dead one, and from any
machine that can see the app dir.

``--once`` prints a single frame (scripts, tests); the default loop
redraws every ``--interval`` seconds until Ctrl-C.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

from tony_tpu.obs import series, slo
from tony_tpu.obs.health import rollup as health_rollup
from tony_tpu.obs.trace_tool import stragglers

_SPARK = "▁▂▃▄▅▆▇█"

# sparkline metric per row, first key present wins: serve hosts trend
# queue depth, trainers step time, the frontend gang TTFT
_TREND_KEYS = ("queue_depth", "step_time_p99_s", "ttft_p99_s", "step")

# columns: (header, point key, format)
_VALUE_COLS = (
    ("step", "step", "{:.0f}"),
    ("ttft_p99", "ttft_p99_s", "{:.3f}s"),
    ("queue", "queue_depth", "{:.0f}"),
    ("occup", "occupancy", "{:.2f}"),
    ("hit%", "prefix_hit_rate", "{:.2f}"),  # prefix-store reuse (serve)
    ("tok/st", "tokens_per_step", "{:.2f}"),  # >1 = speculation paying off
    ("kvB/t", "kv_bytes_per_token", "{:.0f}"),  # drops under quantized KV
    ("goodput", "goodput_frac", "{:.2f}"),
    ("hbm_gb", "hbm_live_bytes", None),  # formatted specially
)


def sparkline(values: list[float], width: int = 16) -> str:
    """Unicode block sparkline over the last ``width`` values (flat
    series render as a flat midline; empty as blanks)."""
    values = [v for v in values if isinstance(v, (int, float))][-width:]
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return _SPARK[3] * len(values)
    return "".join(
        _SPARK[min(int((v - lo) / span * (len(_SPARK) - 1)), len(_SPARK) - 1)]
        for v in values
    )


def _task_of_proc(proc: str) -> str:
    """Journal proc names (``worker_0_user``, ``decode_1_exec_a0``) map
    loosely onto AM task ids (``worker:0``) for straggler correlation."""
    parts = proc.split("_")
    if len(parts) >= 2 and parts[1].isdigit():
        return f"{parts[0]}:{parts[1]}"
    return proc


def _pool_of(task: str, latest: dict) -> str:
    """Pool column for serving rows. The engine journals its own pool
    label (a string riding the series point); AM-rollup rows lost it (the
    metrics push is numeric-only), so the task TYPE is the membership —
    pool assignment in a disaggregated gang is by task type. Non-serve
    rows stay blank."""
    pool = latest.get("pool")
    if isinstance(pool, str) and pool:
        return pool
    if any(k in latest for k in ("occupancy", "tpot_p50_s", "ttft_p50_s")):
        jt = task.partition(":")[0]
        if jt in ("prefill", "decode"):
            return jt
    return ""


def _pool_rollup(rows: list[dict]) -> dict[str, dict]:
    """Split TTFT/TPOT view per pool. Per-host quantiles cannot be merged
    exactly, so the rollup reports the observation-weighted mean p50 and
    the WORST host's p99 — the per-pool SLO question is "is any host of
    this pool blowing its tail", and max answers it conservatively."""
    pools: dict[str, dict] = {}
    for row in rows:
        pool = row.get("pool")
        if not pool:
            continue
        latest = row["latest"]
        agg = pools.setdefault(pool, {"hosts": 0, "queue_depth": 0.0})
        agg["hosts"] += 1
        agg["queue_depth"] += float(latest.get("queue_depth") or 0.0)
        for prefix in ("ttft", "tpot"):
            n = latest.get(f"{prefix}_n")
            p50 = latest.get(f"{prefix}_p50_s")
            p99 = latest.get(f"{prefix}_p99_s")
            if not n or p50 is None or p99 is None:
                continue
            agg[f"{prefix}_n"] = agg.get(f"{prefix}_n", 0.0) + float(n)
            agg[f"_{prefix}_p50_sum"] = (
                agg.get(f"_{prefix}_p50_sum", 0.0) + float(p50) * float(n)
            )
            agg[f"{prefix}_p99_s"] = max(
                agg.get(f"{prefix}_p99_s", 0.0), float(p99)
            )
    for agg in pools.values():
        for prefix in ("ttft", "tpot"):
            n = agg.get(f"{prefix}_n", 0.0)
            s = agg.pop(f"_{prefix}_p50_sum", 0.0)
            if n:
                agg[f"{prefix}_p50_s"] = round(s / n, 4)
    return pools


def build_view(app_dir: str, *, now: float | None = None) -> dict[str, Any]:
    """Everything one frame renders, as data (tests assert on this; the
    renderer only formats)."""
    now = time.time() if now is None else now
    status = {}
    try:
        with open(os.path.join(app_dir, "status.json"), encoding="utf-8") as f:
            status = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass
    roll = series.fleet_rollup(app_dir, now=now)
    slo_roll = slo.rollup(app_dir)
    health_roll = health_rollup(app_dir)
    lagging = {s["task"]: s for s in stragglers(app_dir)}
    # tripped SLOs/rules per proc for the status column
    slo_by_proc = {
        proc: sorted((v.get("slos") or {}))
        for proc, v in slo_roll["procs"].items()
        if v.get("verdict") == "tripped"
    }
    rows = []
    seen_tasks = set()
    for proc, rec in roll["procs"].items():
        task = _task_of_proc(proc)
        seen_tasks.add(task)
        rows.append(_row(proc, task, rec, slo_by_proc, lagging))
    # AM-rollup tasks with no local journal (remote hosts): still rows —
    # the fleet view must not depend on a shared filesystem
    am_roll = _read_am_rollup(app_dir, now)
    for tid, rec in am_roll.items():
        if tid in seen_tasks:
            continue
        rows.append(_row(tid, tid, rec, slo_by_proc, lagging))
    rows.sort(key=lambda r: r["proc"])
    return {
        "app_dir": app_dir,
        "state": str(status.get("state", "RUNNING?")),
        "ts": now,
        "rows": rows,
        "pools": _pool_rollup(rows),
        "slo": {"verdict": slo_roll["verdict"], "tripped": slo_roll["slos"]},
        "health": {"verdict": health_roll["verdict"],
                   "rules": health_roll["rules"]},
        "stragglers": sorted(lagging),
    }


def _read_am_rollup(app_dir: str, now: float) -> dict[str, dict]:
    path = os.path.join(app_dir, "series", "am_rollup.json")
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    out = {}
    for tid, rec in (raw.get("tasks") or {}).items():
        points = [p for p in rec.get("points", []) if isinstance(p, dict)]
        if not points:
            continue
        last_ts = float(rec.get("last_ts", 0.0) or 0.0)
        out[tid] = {
            "points": points,
            "latest": {k: v for k, v in points[-1].items() if k != "ts"},
            "age_s": round(max(now - last_ts, 0.0), 1),
            "n": len(points),
        }
    return out


def _row(proc: str, task: str, rec: dict, slo_by_proc: dict,
         lagging: dict) -> dict[str, Any]:
    latest = rec.get("latest", {})
    points = rec.get("points", [])
    trend_key = next((k for k in _TREND_KEYS if k in latest), None)
    trend = [
        p[trend_key] for p in points
        if isinstance(p, dict) and trend_key in p
    ] if trend_key else []
    tripped = slo_by_proc.get(proc) or slo_by_proc.get(task) or []
    flags = []
    if task in lagging:
        flags.append(f"straggler(-{lagging[task]['behind_steps']:.0f})")
    if latest.get("health_tripped"):
        flags.append("health!")
    return {
        "proc": proc,
        "task": task,
        "pool": _pool_of(task, latest),
        "latest": latest,
        "age_s": rec.get("age_s", 0.0),
        "stale": rec.get("age_s", 0.0) > 30.0,
        "trend_key": trend_key,
        "trend": trend,
        "slo": "TRIP:" + ",".join(tripped) if tripped else "ok",
        "flags": flags,
    }


def render(view: dict[str, Any]) -> str:
    """One frame as text (pure formatting over build_view's data)."""
    lines = [
        f"tony top — {os.path.basename(view['app_dir'].rstrip('/'))}  "
        f"state={view['state']}  slo={view['slo']['verdict']}  "
        f"health={view['health']['verdict']}  "
        f"{time.strftime('%H:%M:%S', time.localtime(view['ts']))}",
    ]
    if view["slo"]["tripped"]:
        lines.append(
            "  TRIPPED SLOs: " + ", ".join(sorted(view["slo"]["tripped"]))
        )
    for pool in sorted(view.get("pools") or {}):
        agg = view["pools"][pool]
        parts = [f"{pool}: {agg['hosts']} host(s)"]
        for prefix in ("ttft", "tpot"):
            if f"{prefix}_p50_s" in agg:
                parts.append(
                    f"{prefix} p50/p99 {agg[f'{prefix}_p50_s']:.3f}/"
                    f"{agg[f'{prefix}_p99_s']:.3f}s"
                )
        parts.append(f"queue {agg['queue_depth']:.0f}")
        lines.append("  pool " + "  ".join(parts))
    header = (
        f"{'proc':<26} {'pool':<8} {'age':>6} "
        + " ".join(f"{h:>9}" for h, _, _ in _VALUE_COLS)
        + f" {'trend':<18} {'slo':<14} flags"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for row in view["rows"]:
        latest = row["latest"]
        cells = []
        for _, key, fmt in _VALUE_COLS:
            v = latest.get(key)
            if v is None:
                cells.append(f"{'-':>9}")
            elif key == "hbm_live_bytes":
                cells.append(f"{v / 2**30:>9.2f}")
            else:
                cells.append(f"{fmt.format(float(v)):>9}")
        age = f"{row['age_s']:.0f}s" + ("!" if row["stale"] else "")
        trend = sparkline(row["trend"])
        if row["trend_key"]:
            trend = f"{trend} {row['trend_key'].split('_')[0]}"
        lines.append(
            f"{row['proc']:<26} {row.get('pool') or '-':<8} {age:>6} "
            + " ".join(cells)
            + f" {trend:<18} {row['slo']:<14} {' '.join(row['flags'])}"
        )
    if not view["rows"]:
        lines.append("(no series yet — job predates the recorder, or "
                     "obs.series.enabled is false)")
    return "\n".join(lines)


def run_top(app_dir: str, *, once: bool = False,
            interval_s: float = 2.0, out=None) -> int:
    """The CLI loop: redraw until Ctrl-C (or a single frame with
    ``once``). Returns 0; a tripped SLO shows in the view, not the exit
    code — ``top`` is a viewer, not a gate."""
    import sys

    out = out or sys.stdout
    while True:
        frame = render(build_view(app_dir))
        if once:
            print(frame, file=out)
            return 0
        # ANSI clear + home keeps the terminal stable between redraws
        print("\x1b[2J\x1b[H" + frame, file=out, flush=True)
        try:
            time.sleep(max(interval_s, 0.2))
        except KeyboardInterrupt:
            return 0


__all__ = ["build_view", "render", "run_top", "sparkline"]

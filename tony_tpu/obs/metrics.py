"""Throughput / MFU accounting.

The reference's TaskMonitor samples cpu/mem + nvidia-smi GPU utilisation
(SURVEY.md section 2 "TaskMonitor"); on TPU the meaningful utilisation number
is MFU -- achieved model FLOP/s over the chip's peak -- which is also the
north-star metric (BASELINE.md: >= 45% MFU target).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax

# Peak dense bf16 FLOP/s per chip by TPU generation (public spec-sheet numbers).
PEAK_BF16_FLOPS: dict[str, float] = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,  # axon device_kind for v5e
    "v5p": 459e12,
    "v6e": 918e12,
    "cpu": 1e12,  # nominal; keeps MFU finite in CPU tests
}


def chip_peak_flops(device: jax.Device | None = None) -> float:
    d = device or jax.devices()[0]
    kind = d.device_kind.lower()
    for name, peak in PEAK_BF16_FLOPS.items():
        if name in kind:
            return peak
    return PEAK_BF16_FLOPS["cpu"]


@dataclass
class StepTimer:
    """Accumulates steps and wall time to report tokens/sec and MFU."""

    flops_per_token: float
    tokens_per_step: int
    n_chips: int = 1
    elapsed_s: float = 0.0
    steps: int = 0
    # wall time the host spent blocked producing/placing input batches
    # (time inside next(batches)); the device is idle for that span unless
    # the data layer prefetches (train/prefetch.py)
    host_blocked_s: float = 0.0

    def record(self, dt_s: float, n_steps: int = 1, host_blocked_s: float = 0.0) -> None:
        self.elapsed_s += dt_s
        self.steps += n_steps
        self.host_blocked_s += host_blocked_s

    @property
    def host_blocked_ms_per_step(self) -> float:
        if self.steps == 0:
            return 0.0
        return self.host_blocked_s / self.steps * 1e3

    @property
    def host_blocked_frac(self) -> float:
        """Fraction of wall time spent input-blocked (0 = stall-free loop)."""
        if self.elapsed_s == 0:
            return 0.0
        return self.host_blocked_s / self.elapsed_s

    @property
    def tokens_per_sec(self) -> float:
        if self.elapsed_s == 0:
            return 0.0
        return self.steps * self.tokens_per_step / self.elapsed_s

    @property
    def tokens_per_sec_per_chip(self) -> float:
        return self.tokens_per_sec / self.n_chips

    def mfu(self, peak_flops_per_chip: float | None = None) -> float:
        peak = peak_flops_per_chip or chip_peak_flops()
        return self.tokens_per_sec_per_chip * self.flops_per_token / peak


@dataclass
class DecodeMetrics:
    """Serving-side counters fed by the decode engine (serve/engine.py).

    The serving counterpart of StepTimer: decode tokens/s/chip is the
    throughput headline, time-to-first-token the latency one, and slot
    occupancy the continuous-batching health signal (a well-fed engine
    keeps it near 1.0; a draining or admission-starved one decays toward
    1/slots)."""

    n_chips: int = 1
    generated_tokens: int = 0      # sampled tokens (prefill firsts + decode)
    decode_s: float = 0.0          # wall time inside decode steps
    prefill_s: float = 0.0         # wall time inside prefill calls
    decode_steps: int = 0
    occupancy_sum: float = 0.0     # sum over decode steps of live/slots
    ttft_sum_s: float = 0.0        # submit -> first token, summed
    ttft_max_s: float = 0.0
    requests_started: int = 0
    requests_finished: int = 0
    prefill_compiles: int = 0      # distinct prefill buckets compiled
    decode_compiles: int = 0       # distinct pool/table signatures compiled
    prompt_tokens: int = 0         # prompt tokens admitted
    prefix_hit_tokens: int = 0     # prompt tokens served from the prefix
    #                                store (no re-prefill; serve/prefix.py)
    decode_tokens: int = 0         # tokens emitted by decode steps only
    decode_live_sum: int = 0       # sum over decode steps of live slots
    draft_proposed: int = 0        # speculative draft tokens proposed
    draft_accepted: int = 0        # ... of which the target accepted
    spec_rollbacks: int = 0        # ... of which were rejected (discarded)
    kv_bytes_per_token: float = 0.0  # HBM per cached token (block bytes /
    #                                  positions; halves with quantized pools)

    def record_prompt(self, plen: int, hit_tokens: int = 0) -> None:
        self.prompt_tokens += plen
        self.prefix_hit_tokens += hit_tokens

    def record_spec(self, proposed: int, accepted: int) -> None:
        """One speculative step's draft accounting (serve/spec.py)."""
        self.draft_proposed += proposed
        self.draft_accepted += accepted
        self.spec_rollbacks += proposed - accepted

    def record_prefill(self, dt_s: float, ttft_s: float) -> None:
        self.prefill_s += dt_s
        self.ttft_sum_s += ttft_s
        self.ttft_max_s = max(self.ttft_max_s, ttft_s)
        self.requests_started += 1
        self.generated_tokens += 1  # prefill samples the first token

    def record_decode(self, dt_s: float, new_tokens: int, live: int,
                      slots: int) -> None:
        self.decode_s += dt_s
        self.decode_steps += 1
        self.generated_tokens += new_tokens
        self.decode_tokens += new_tokens
        self.decode_live_sum += live
        self.occupancy_sum += live / max(slots, 1)

    @property
    def elapsed_s(self) -> float:
        return self.decode_s + self.prefill_s

    @property
    def tokens_per_sec(self) -> float:
        if self.elapsed_s == 0:
            return 0.0
        return self.generated_tokens / self.elapsed_s

    @property
    def tokens_per_sec_per_chip(self) -> float:
        return self.tokens_per_sec / self.n_chips

    @property
    def slot_occupancy(self) -> float:
        if self.decode_steps == 0:
            return 0.0
        return self.occupancy_sum / self.decode_steps

    @property
    def ttft_avg_s(self) -> float:
        if self.requests_started == 0:
            return 0.0
        return self.ttft_sum_s / self.requests_started

    @property
    def prefix_hit_rate(self) -> float:
        if self.prompt_tokens == 0:
            return 0.0
        return self.prefix_hit_tokens / self.prompt_tokens

    @property
    def tokens_per_step(self) -> float:
        """Decode tokens per step per LIVE slot — exactly 1.0
        autoregressively at any batch size, up to ``spec_max_draft + 1``
        with speculative decoding accepting (serve/spec.py)."""
        if self.decode_live_sum == 0:
            return 0.0
        return self.decode_tokens / self.decode_live_sum

    @property
    def draft_accept_rate(self) -> float:
        if self.draft_proposed == 0:
            return 0.0
        return self.draft_accepted / self.draft_proposed

    def summary(self) -> dict:
        out = {
            "tokens_per_sec_per_chip": round(self.tokens_per_sec_per_chip, 1),
            "generated_tokens": self.generated_tokens,
            "ttft_avg_s": round(self.ttft_avg_s, 4),
            "ttft_max_s": round(self.ttft_max_s, 4),
            "slot_occupancy": round(self.slot_occupancy, 3),
            "decode_steps": self.decode_steps,
            "requests_finished": self.requests_finished,
            "prefill_compiles": self.prefill_compiles,
            "decode_compiles": self.decode_compiles,
        }
        if self.decode_steps:
            out["tokens_per_step"] = round(self.tokens_per_step, 3)
        if self.prompt_tokens:
            out["prefix_hit_tokens"] = self.prefix_hit_tokens
            out["prefix_hit_rate"] = round(self.prefix_hit_rate, 4)
        if self.draft_proposed:
            out["draft_accept_rate"] = round(self.draft_accept_rate, 4)
            out["spec_rollbacks"] = self.spec_rollbacks
        if self.kv_bytes_per_token:
            out["kv_bytes_per_token"] = round(self.kv_bytes_per_token, 2)
        return out

"""Port-forwarding proxy: the tony-proxy analogue.

The reference ships a small proxy so users can reach services running inside
cluster containers — notebooks, TensorBoard — from outside the cluster
network (SURVEY.md section 2 "tony-proxy"). Same role here: a threaded TCP
relay from a local listen port to a task's host:port (taken from `tony
status` output or the cluster spec).

Run:  python -m tony_tpu.obs.proxy --listen 9000 --target host:6006
"""

from __future__ import annotations

import argparse
import socket
import threading


def _pump(src: socket.socket, dst: socket.socket) -> None:
    try:
        while True:
            data = src.recv(1 << 16)
            if not data:
                break
            dst.sendall(data)
    except OSError:
        pass
    finally:
        for s in (src, dst):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class ProxyServer:
    """Accept loop on (host, listen_port), relaying to target host:port."""

    def __init__(self, target: str, listen_port: int = 0, host: str = "127.0.0.1"):
        t_host, _, t_port = target.rpartition(":")
        self.target = (t_host or "127.0.0.1", int(t_port))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, listen_port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)

    def start(self) -> "ProxyServer":
        self._thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._sock.accept()
            except OSError:
                return
            try:
                upstream = socket.create_connection(self.target, timeout=10)
            except OSError:
                client.close()
                continue
            threading.Thread(target=_pump, args=(client, upstream), daemon=True).start()
            threading.Thread(target=_pump, args=(upstream, client), daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def main() -> None:
    p = argparse.ArgumentParser(description="tony-tpu port-forwarding proxy")
    p.add_argument("--listen", type=int, required=True)
    p.add_argument("--target", required=True, help="host:port inside the cluster")
    args = p.parse_args()
    proxy = ProxyServer(args.target, args.listen, host="0.0.0.0").start()
    print(f"proxying :{proxy.port} -> {args.target}")
    threading.Event().wait()


if __name__ == "__main__":
    main()

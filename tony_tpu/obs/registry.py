"""Counters / gauges / fixed-bucket histograms with Prometheus exposition.

The per-component accounting objects (StepTimer, DecodeMetrics) report
averages; a production serving/training plane needs *distributions* — p50
vs p99 TTFT are different operational stories. This registry is the shared
sink: instrumented code observes into named metrics, each process snapshots
its registry into ``<app_dir>/metrics/<proc>.json`` at shutdown (the job-
history record), and the portal's ``/metrics`` endpoint renders every
snapshot under its apps root in Prometheus text exposition format (0.0.4)
with ``app``/``proc`` labels, so a scrape of one portal covers the fleet.

Histograms are fixed-bucket (Prometheus-style, cumulative at render time):
``observe()`` is a bisect + two adds — cheap enough for per-step and
per-token paths — and ``quantile()`` interpolates within the bucket that
crosses the requested rank, which is exactly the precision a bucketed
histogram can honestly claim.

Metric name catalogue (docs/OBS.md): ``tony_step_time_seconds``,
``tony_ttft_seconds``, ``tony_tpot_seconds``, ``tony_decode_step_seconds``,
``tony_queue_depth``, ``tony_rpc_requests_total``, and friends.

Stdlib-only (imported from executors for non-JAX frameworks).
"""

from __future__ import annotations

import bisect
import json
import math
import os
import threading
from typing import Any, Iterable

# latency-shaped default buckets (seconds), 1ms .. 120s — the top must
# cover big-model step times and worst-case TTFT, because quantile()
# clamps to the largest finite bound (Prometheus semantics): a saturated
# histogram reports the top bound, not the true quantile
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _label_str(labels: dict[str, str], extra: dict[str, str] | None = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(merged.items())
    )
    return "{" + inner + "}"


class Counter:
    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: dict[str, str]):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: dict[str, str]):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        self._value = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._value += v

    def dec(self, v: float = 1.0) -> None:
        self.inc(-v)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed upper-bound buckets; counts are per-bucket internally and
    cumulated at render/quantile time (the Prometheus ``le`` convention)."""

    __slots__ = ("name", "labels", "bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, labels: dict[str, str],
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.labels = labels
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> list[int]:
        out, acc = [], 0
        for c in self._counts:
            acc += c
            out.append(acc)
        return out

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile (0 when empty). q in [0, 1].
        Clamps to the largest finite bound when the rank falls in the
        +Inf bucket (Prometheus ``histogram_quantile`` semantics) — size
        buckets to the workload or the top quantiles saturate."""
        with self._lock:
            counts = list(self._counts)
        return quantile_from_counts(self.bounds, counts, q)


def quantile_from_counts(bounds: tuple[float, ...], counts: list[int],
                         q: float) -> float:
    """The bucket-interpolation rule over an explicit per-bucket count
    vector (``Histogram.quantile`` and the windowed-delta readers share
    it — ONE quantile semantics for cumulative and since-last-scrape)."""
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = q * total
    acc = 0
    lo = 0.0
    for i, c in enumerate(counts):
        if acc + c >= rank and c > 0:
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            frac = (rank - acc) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        acc += c
        lo = bounds[i] if i < len(bounds) else bounds[-1]
    return bounds[-1]


class HistogramWindow:
    """Since-last-call quantile reader over histograms.

    A cumulative histogram answers "over the whole run"; a live series
    point wants "since the last scrape" — p99 TTFT *now*, not blended
    with an hour-old warmup. ``delta(hist)`` diffs the per-bucket counts
    against this window's previous reading of the same histogram family
    and returns ``{count, p50, p99}`` over just the new observations
    (zeros when nothing landed). A replaced histogram object (engine
    ``reset_metrics`` builds a fresh registry) re-baselines from zero
    instead of reporting negative deltas."""

    def __init__(self) -> None:
        self._prev: dict[str, tuple[Any, list[int]]] = {}

    def delta(self, hist: Histogram) -> dict[str, float]:
        with hist._lock:
            counts = list(hist._counts)
        prev_obj, prev_counts = self._prev.get(hist.name, (None, None))
        if prev_obj is not hist or prev_counts is None:
            prev_counts = [0] * len(counts)
        d = [max(a - b, 0) for a, b in zip(counts, prev_counts)]
        self._prev[hist.name] = (hist, counts)
        n = sum(d)
        return {
            "count": float(n),
            "p50": quantile_from_counts(hist.bounds, d, 0.5),
            "p99": quantile_from_counts(hist.bounds, d, 0.99),
        }


class Registry:
    """Named metric families; a family's children differ by labels."""

    def __init__(self) -> None:
        self._metrics: dict[tuple, Any] = {}
        self._help: dict[str, tuple[str, str]] = {}  # name -> (kind, help)
        self._lock = threading.Lock()

    def _get(self, kind: str, cls, name: str, help_: str,
             labels: dict[str, str], **kw):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            # kind conflicts must fail for existing children too — handing
            # a Counter to a caller that asked for a gauge corrupts the
            # export (or explodes later inside the instrumented path)
            known = self._help.get(name)
            if known is not None and known[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {known[0]}"
                )
            m = self._metrics.get(key)
            if m is None:
                self._help.setdefault(name, (kind, help_))
                m = self._metrics[key] = cls(name, dict(labels), **kw)
            return m

    def counter(self, name: str, help_: str = "", **labels: str) -> Counter:
        return self._get("counter", Counter, name, help_, labels)

    def gauge(self, name: str, help_: str = "", **labels: str) -> Gauge:
        return self._get("gauge", Gauge, name, help_, labels)

    def histogram(self, name: str, help_: str = "",
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                  **labels: str) -> Histogram:
        return self._get("histogram", Histogram, name, help_, labels,
                         buckets=buckets)

    # --- snapshot / render ----------------------------------------------------

    def snapshot(self) -> list[dict]:
        """JSON-able dump, one entry per metric child (the on-disk form the
        portal re-renders; see :func:`write_snapshot`)."""
        out = []
        with self._lock:
            metrics = list(self._metrics.values())
            helps = dict(self._help)
        for m in metrics:
            kind, help_ = helps.get(m.name, ("counter", ""))
            entry: dict[str, Any] = {
                "kind": kind, "name": m.name, "help": help_,
                "labels": dict(m.labels),
            }
            if isinstance(m, Histogram):
                entry["bounds"] = list(m.bounds)
                entry["counts"] = list(m._counts)
                entry["sum"] = m.sum
                entry["count"] = m.count
            else:
                entry["value"] = m.value
            out.append(entry)
        return out

    def render(self) -> str:
        return render_snapshots([({}, self.snapshot())])


def render_snapshots(
    snaps: Iterable[tuple[dict[str, str], list[dict]]]
) -> str:
    """Prometheus text exposition (0.0.4) over snapshot dumps, each with
    extra labels (the portal attaches ``app``/``proc``). One HELP/TYPE
    header per family regardless of how many snapshots carry it."""
    families: dict[str, list[tuple[dict, dict]]] = {}
    meta: dict[str, tuple[str, str]] = {}
    for extra, entries in snaps:
        for e in entries:
            # one malformed snapshot entry (older format, hand-edited
            # file) must not take down the whole fleet-wide scrape
            if not isinstance(e, dict) or not e.get("name"):
                continue
            families.setdefault(e["name"], []).append((extra, e))
            meta.setdefault(e["name"], (e.get("kind", "counter"), e.get("help", "")))
    lines: list[str] = []
    for name in sorted(families):
        kind, help_ = meta[name]
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")
        for extra, e in families[name]:
            labels = dict(e.get("labels", {}))
            try:
                if kind == "histogram":
                    bucket_lines = []
                    bounds = list(e["bounds"]) + [math.inf]
                    acc = 0
                    for b, c in zip(bounds, e["counts"]):
                        acc += c
                        le = _label_str(labels, {**extra, "le": _fmt(b)})
                        bucket_lines.append(f"{name}_bucket{le} {acc}")
                    ls = _label_str(labels, extra)
                    bucket_lines.append(f"{name}_sum{ls} {_fmt(e['sum'])}")
                    bucket_lines.append(f"{name}_count{ls} {e['count']}")
                    lines.extend(bucket_lines)
                else:
                    ls = _label_str(labels, extra)
                    lines.append(f"{name}{ls} {_fmt(e['value'])}")
            except (KeyError, TypeError, ValueError):
                continue  # skip the malformed entry, keep the scrape alive
    return "\n".join(lines) + "\n"


# --- process-global default registry -----------------------------------------

_registry = Registry()


def get_registry() -> Registry:
    return _registry


def write_snapshot(path: str, registry: Registry | None = None,
                   proc: str = "") -> None:
    """Atomically journal a registry snapshot (fit()/engine shutdown →
    ``<app_dir>/metrics/<proc>.json``; the portal's /metrics source)."""
    reg = registry if registry is not None else _registry
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {"proc": proc, "metrics": reg.snapshot()}
    with open(path + ".tmp", "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(path + ".tmp", path)


def snapshot_to_app_dir(proc: str, registry: Registry | None = None) -> str:
    """Write this process's snapshot under the job's app dir when running
    inside a tony-tpu job (TONY_APP_DIR); returns the path ('' outside)."""
    app_dir = os.environ.get("TONY_APP_DIR", "")
    if not app_dir:
        return ""
    from tony_tpu.obs.trace import sanitize_proc  # one shared naming rule

    proc = sanitize_proc(proc)
    path = os.path.join(app_dir, "metrics", f"{proc}.json")
    try:
        write_snapshot(path, registry, proc=proc)
    except OSError:
        return ""
    return path


__all__ = [
    "Counter", "DEFAULT_BUCKETS", "Gauge", "Histogram", "HistogramWindow",
    "Registry", "get_registry", "quantile_from_counts", "render_snapshots",
    "snapshot_to_app_dir", "write_snapshot",
]

"""TaskMonitor: executor-side resource sampler.

Rebuild of the reference's ``TaskMonitor`` (SURVEY.md section 2): a sampler
the executor runs beside the user process, pushing samples to the AM's
metrics RPC. The reference reads /proc for cpu/mem and shells out to
``nvidia-smi -q -x`` for GPU utilisation; here cpu/mem still come from /proc
(no psutil dependency) and the accelerator numbers come from the TPU runtime
metrics JAX exposes, with step-level throughput/MFU reported by the trainer
through the same channel.
"""

from __future__ import annotations

import os
import time

# name, value, unix-seconds — matches rpc MetricSample
Sample = tuple[str, float, float]

_CLK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _proc_stat_jiffies(pid: int) -> float:
    """utime+stime (+children) of a process, in clock ticks."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            parts = f.read().rsplit(") ", 1)[-1].split()
        # fields after comm: state is parts[0]; utime=parts[11], stime=parts[12]
        return float(parts[11]) + float(parts[12])
    except (OSError, IndexError, ValueError):
        return 0.0


def _proc_rss_bytes(pid: int) -> float:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return float(f.read().split()[1]) * _PAGE
    except (OSError, IndexError, ValueError):
        return 0.0


def _children(pid: int) -> list[int]:
    """Direct + transitive children via /proc/<pid>/task/*/children."""
    out, stack = [], [pid]
    while stack:
        p = stack.pop()
        try:
            for tid in os.listdir(f"/proc/{p}/task"):
                path = f"/proc/{p}/task/{tid}/children"
                try:
                    with open(path) as f:
                        kids = [int(c) for c in f.read().split()]
                except OSError:
                    continue
                out.extend(kids)
                stack.extend(kids)
        except OSError:
            continue
    return out


class TaskMonitor:
    """Samples this process tree's cpu%/rss; extend via ``extra_sources``."""

    def __init__(self, pid: int | None = None, extra_sources: list | None = None):
        self.pid = pid or os.getpid()
        self._last_jiffies = 0.0
        self._last_t = 0.0
        # callables returning extra samples — e.g. obs.tpu_metrics.
        # tpu_memory_samples in a process that owns the TPU (the executor's
        # own monitor must NOT import jax: the chip belongs to the child)
        self.extra_sources: list = list(extra_sources or [])

    def sample(self) -> list[Sample]:
        now = time.time()
        pids = [self.pid, *_children(self.pid)]
        jiffies = sum(_proc_stat_jiffies(p) for p in pids)
        rss = sum(_proc_rss_bytes(p) for p in pids)
        samples: list[Sample] = [("rss_mb", rss / 1e6, now)]
        if self._last_t > 0 and now > self._last_t:
            cpu = (jiffies - self._last_jiffies) / _CLK / (now - self._last_t) * 100
            samples.append(("cpu_percent", max(cpu, 0.0), now))
        self._last_jiffies, self._last_t = jiffies, now
        for source in self.extra_sources:
            try:
                samples.extend(source())
            except Exception:
                pass
        return samples


__all__ = ["Sample", "TaskMonitor"]

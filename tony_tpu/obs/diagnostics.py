"""cloud-tpu-diagnostics glue: stack traces out of hung/faulted TPU jobs.

SURVEY.md section 5 ("Metrics/observability": replace nvidia-smi with
libtpu/cloud-tpu-diagnostics): the library periodically collects per-thread
Python stack traces to /tmp/debugging (and optionally Cloud Logging), which
is exactly what you want from a wedged collective or a host stuck in a gang
barrier. Opt in per job with ``diagnostics.enabled = true``; the executor
exports TONY_TPU_DIAGNOSTICS and fit() wraps training in this context.
"""

from __future__ import annotations

import contextlib
import logging
import os

log = logging.getLogger(__name__)


def diagnostics_context():
    """Context manager wrapping a training run; nullcontext unless the job
    opted in (TONY_TPU_DIAGNOSTICS env) and the library is importable."""
    if not os.environ.get("TONY_TPU_DIAGNOSTICS"):
        return contextlib.nullcontext()
    raw_interval = os.environ.get("TONY_TPU_DIAGNOSTICS_INTERVAL_S", "60")
    try:
        interval = int(raw_interval)
    except ValueError:
        log.warning(
            "TONY_TPU_DIAGNOSTICS_INTERVAL_S=%r is not an integer; using 60",
            raw_interval,
        )
        interval = 60
    try:
        from cloud_tpu_diagnostics import diagnostic
        from cloud_tpu_diagnostics.configuration import (
            debug_configuration,
            diagnostic_configuration,
            stack_trace_configuration,
        )

        # NOTE: the library's collection daemon sleeps the whole interval
        # between dumps and clean exit joins it — keep it modest so a
        # finished job doesn't hang in teardown
        config = diagnostic_configuration.DiagnosticConfig(
            debug_config=debug_configuration.DebugConfig(
                stack_trace_config=stack_trace_configuration.StackTraceConfig(
                    collect_stack_trace=True,
                    stack_trace_to_cloud=False,  # zero-egress: local dir only
                    stack_trace_interval_seconds=interval,
                )
            )
        )
        log.info("cloud-tpu-diagnostics stack-trace collection enabled")
        return diagnostic.diagnose(config)
    except Exception:
        log.warning("cloud-tpu-diagnostics unavailable; continuing without",
                    exc_info=True)
        return contextlib.nullcontext()


__all__ = ["diagnostics_context"]

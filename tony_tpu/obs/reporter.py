"""Training-metrics reporter: user process -> AM metrics RPC.

Closes the loop the reference draws as TaskMonitor -> MetricsRpc -> AM ->
history events -> portal (SURVEY.md section 5 "Metrics"): beyond the
executor's generic cpu/rss sampler, the *training* process can push
step-level throughput/loss/MFU — the numbers that actually matter on TPU —
through the same channel. fit() wires this automatically when running under
a tony-tpu job (the TONY_AM_ADDR env is present).

Pushes are asynchronous: ``push()`` enqueues onto a bounded queue drained
by a daemon thread, so a stalled or tearing-down AM can never block the
train loop (an RPC hang used to stall the step for up to the 3s client
timeout). When the queue is full the sample is dropped and counted;
``dropped`` rides along as a ``metrics_dropped`` sample on the next
successful push so the loss is visible in the job history, not silent.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time

log = logging.getLogger(__name__)


class MetricsReporter:
    """Best-effort pusher; never lets metrics failures hurt training."""

    def __init__(self, client=None, maxsize: int = 64) -> None:
        self._client = client
        self.dropped = 0  # samples lost to a full queue (training never waits)
        self.job_name = os.environ.get("TONY_JOB_NAME", "")
        self.index = int(os.environ.get("TONY_TASK_INDEX", "0"))
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if self._client is None:
            addr = os.environ.get("TONY_AM_ADDR", "")
            if not addr:
                return
            try:
                from tony_tpu.rpc import ApplicationRpcClient
                from tony_tpu.rpc.auth import read_token

                token = read_token(os.environ.get("TONY_APP_DIR", ""))
                self._client = ApplicationRpcClient(addr, timeout_s=3.0, token=token)
            except Exception:
                log.debug("metrics reporter disabled", exc_info=True)
                return
        self._thread = threading.Thread(
            target=self._drain, name="tony-metrics-push", daemon=True
        )
        self._thread.start()

    @property
    def active(self) -> bool:
        return self._client is not None

    def push(self, metrics: dict) -> None:
        """Enqueue; never blocks. A full queue (AM slower than the step
        cadence) drops the sample and bumps ``dropped``."""
        if self._client is None:
            return
        try:
            self._q.put_nowait((dict(metrics), time.time()))
        except queue.Full:
            self.dropped += 1

    def _drain(self) -> None:
        while True:
            try:
                metrics, ts = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            samples = [
                (k, float(v), ts)
                for k, v in metrics.items()
                if isinstance(v, (int, float))
            ]
            if self.dropped:
                samples.append(("metrics_dropped", float(self.dropped), ts))
            try:
                self._client.push_metrics(self.job_name, self.index, samples)
            except Exception:
                pass  # AM busy/tearing down; training goes on

    def register_tensorboard(self, url: str) -> None:
        if self._client is None:
            return
        try:
            self._client.register_tensorboard_url(url)
        except Exception:
            pass

    def close(self, timeout: float = 5.0) -> None:
        """Flush what the drain thread can send within ``timeout`` and shut
        down. A wedged AM RPC cannot hang shutdown: the thread is a daemon
        and is abandoned after the join timeout."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if self.dropped:
            # a permanently wedged AM means no metrics_dropped sample ever
            # reached the history — make the loss visible in worker logs too
            log.warning("%d metric pushes dropped (AM slower than the step "
                        "cadence or unreachable)", self.dropped)
        if self._client is not None:
            self._client.close()
            self._client = None


__all__ = ["MetricsReporter"]

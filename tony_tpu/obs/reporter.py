"""Training-metrics reporter: user process -> AM metrics RPC.

Closes the loop the reference draws as TaskMonitor -> MetricsRpc -> AM ->
history events -> portal (SURVEY.md section 5 "Metrics"): beyond the
executor's generic cpu/rss sampler, the *training* process can push
step-level throughput/loss/MFU — the numbers that actually matter on TPU —
through the same channel. fit() wires this automatically when running under
a tony-tpu job (the TONY_AM_ADDR env is present).
"""

from __future__ import annotations

import logging
import os
import time

log = logging.getLogger(__name__)


class MetricsReporter:
    """Best-effort pusher; never lets metrics failures hurt training."""

    def __init__(self) -> None:
        self._client = None
        self.job_name = os.environ.get("TONY_JOB_NAME", "")
        self.index = int(os.environ.get("TONY_TASK_INDEX", "0"))
        addr = os.environ.get("TONY_AM_ADDR", "")
        if not addr:
            return
        try:
            from tony_tpu.rpc import ApplicationRpcClient
            from tony_tpu.rpc.auth import read_token

            token = read_token(os.environ.get("TONY_APP_DIR", ""))
            self._client = ApplicationRpcClient(addr, timeout_s=3.0, token=token)
        except Exception:
            log.debug("metrics reporter disabled", exc_info=True)

    @property
    def active(self) -> bool:
        return self._client is not None

    def push(self, metrics: dict) -> None:
        if self._client is None:
            return
        now = time.time()
        samples = [
            (k, float(v), now)
            for k, v in metrics.items()
            if isinstance(v, (int, float))
        ]
        try:
            self._client.push_metrics(self.job_name, self.index, samples)
        except Exception:
            pass  # AM busy/tearing down; training goes on

    def register_tensorboard(self, url: str) -> None:
        if self._client is None:
            return
        try:
            self._client.register_tensorboard_url(url)
        except Exception:
            pass

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


__all__ = ["MetricsReporter"]

"""TPU device metrics: the nvidia-smi analogue.

The reference's TaskMonitor shells out to ``nvidia-smi -q -x`` and parses the
XML for GPU utilisation (SURVEY.md section 2 "TaskMonitor"). There is no
device-side daemon to query on TPU; the equivalents live in the runtime the
training process already holds:

- ``device.memory_stats()`` — HBM bytes in use / peak / limit (PJRT exposes
  this on real TPU backends; interpreters and some relay platforms return
  None, in which case the source simply yields nothing).
- device duty cycle is not exposed through JAX's public API; the meaningful
  utilisation number on TPU is MFU, which the trainer computes from step
  timing (obs.metrics.StepTimer) and pushes through the same channel.

Because one TPU chip cannot be shared across processes, this source is only
useful *inside* the process that owns the device — fit() attaches it to its
metrics push; TaskMonitor.extra_sources takes it for user processes that run
their own sampler.
"""

from __future__ import annotations

import time

from tony_tpu.obs.monitor import Sample


def tpu_memory_samples() -> list[Sample]:
    """HBM usage samples for every local device; [] when unavailable."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return []
    now = time.time()
    out: list[Sample] = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        suffix = f"_dev{d.id}" if len(devices) > 1 else ""
        if "bytes_in_use" in stats:
            out.append((f"hbm_mb{suffix}", stats["bytes_in_use"] / 1e6, now))
        if "peak_bytes_in_use" in stats:
            out.append((f"hbm_peak_mb{suffix}", stats["peak_bytes_in_use"] / 1e6, now))
        if "bytes_limit" in stats:
            out.append((f"hbm_limit_mb{suffix}", stats["bytes_limit"] / 1e6, now))
    return out


def tpu_metrics_dict() -> dict[str, float]:
    """Same numbers keyed for a metrics-dict push (fit()'s on_metrics)."""
    return {name: value for name, value, _ in tpu_memory_samples()}


__all__ = ["tpu_memory_samples", "tpu_metrics_dict"]

"""``tony perf diff``: cross-run performance regression verdicts.

The repo accumulates one BENCH json per merged PR (BENCH_r01..r05 at the
root) — and until now no tool read them: a PR that tanked tok/s/chip or
TTFT would sail through review unless a human eyeballed two json blobs.
This module compares two bench reports (or two live-series rollups) key
by key under per-section tolerance rules and emits a machine-checkable
verdict; ``tests/test_perf_diff.py`` wires it as a tier-1 gate against
committed fixtures, so the gate itself cannot rot.

Inputs it understands (auto-detected):

- a driver **BENCH_r*.json wrapper** (``{"parsed": ..., "tail": "...",
  ...}``) — the embedded bench-report JSON line is extracted from the
  tail;
- a raw **bench report** (bench.py stdout: ``{"metric", "value",
  "extra": {...}}``);
- a **series rollup** (obs/series.fleet_rollup or the portal
  ``/api/series/<app>`` payload) — each proc's numeric keys reduce to the
  median over its recorded points.

Rules: every numeric key flattens to a dotted path and is matched against
an ordered pattern list declaring *direction* (is bigger better?) and a
relative tolerance. Keys matching a ``config`` rule (batch sizes, param
counts, steps) are compared for *identity* — a changed config is reported
separately, never as a perf regression. Keys no rule claims are listed as
``unjudged`` rather than silently dropped: the diff never pretends to
have covered what it cannot judge.
"""

from __future__ import annotations

import json
import re
import statistics
from typing import Any

# (pattern, kind, rel_tol) — FIRST match wins, so configs and exclusions
# outrank the broad latency catch-alls below them. kind: "higher" =
# bigger is better, "lower" = smaller is better, "config" = must match
# exactly, "skip" = meta/noise, never compared.
DEFAULT_RULES: tuple[tuple[str, str, float], ...] = (
    # meta / driver plumbing
    (r"(^|\.)(n|rc|ts|vs_baseline|every|count|step|steps)$", "skip", 0.0),
    (r"(^|\.)(at|last_ts|age_s|_n)$", "skip", 0.0),
    (r"_n$", "skip", 0.0),
    # configuration identity (not performance)
    (r"(n_params|n_active_params|batch|seq|vocab|n_layers|n_heads|"
     r"capacity_factor|top_k|slots_formula|kv_block|window)", "config", 0.0),
    # decomposed-collective overlap (ops/overlap.py, bench `overlap`
    # section): the within-run |on - off| loss delta is a value-safety
    # cross-check (≈0 by construction, asserted directly by tests), not
    # a judged metric — it must outrank the loss rule below or a
    # 1e-7 -> 2e-7 float jitter would flag as an infinite relative
    # regression. Pure-comm step counts are trace-shaped.
    (r"(loss_delta|pure_comm_steps)", "skip", 0.0),
    # quality: loss/perplexity may not silently regress either
    (r"(loss|perplexity)", "lower", 0.02),
    # elastic restart cost (tony_tpu/elastic/, bench `elastic` section):
    # a lost step is a regression with ZERO tolerance (the whole point of
    # elastic is losing none); warm-restart seconds and the post-shrink
    # step-time ratio get timing slack. These must outrank the throughput
    # rule below — `goodput.restart_s` would otherwise match its
    # `goodput` pattern and be judged higher-better. Scenario shape
    # (member count, boundary count) is configuration identity.
    (r"(elastic.*(members|reshards)$|generation_changes)", "config", 0.0),
    (r"(lost_steps)", "lower", 0.0),
    (r"(restart_s|reshard_s|shrunk_step_ratio)", "lower", 0.25),
    # disaggregated serving (engine chunked prefill + serve/gang.py pool
    # handoff, bench `decode.disagg`): the chunked/unchunked TPOT-p99
    # ratio is the long-prompt-interference headline — lower is better,
    # and it carries no terminal latency token so it would otherwise go
    # unjudged. The chunk size and the scenario's long-prompt length are
    # configuration identity: silently shrinking the chunk (or the
    # prompt) would make interference look "fixed". The handoff payload
    # is trace-shaped — blocks/bytes scale with the shipped prefix, so
    # the memory catch-all below must not judge a longer handoff as a
    # regression (handoff_ms stays judged by the latency rule).
    (r"tpot_p99_chunked_ratio", "lower", 0.10),
    (r"(chunk_tokens|long_prompt_tokens)", "config", 0.0),
    (r"handoff_.*(bytes|blocks)", "skip", 0.0),
    # MoE fast path (parallel/moe + ops/moe_overlap, bench `moe_top2`):
    # the PR-4 dispatch gate, resolved — the grouped/gather tokens-per-sec
    # ratio is the judged headline (higher is better; it carries no
    # throughput token so it would otherwise go unjudged), and the
    # recorded dispatch decision bits are configuration identity: a
    # silent flip back to gather (or the gate silently ceasing to hold
    # while grouped stays default) must surface as a diff failure, not
    # hide inside a judged metric. The overlap section's chunk size rides
    # the `chunk_tokens` config rule above; its exposed/overlap keys ride
    # the step-anatomy rules below.
    (r"grouped_vs_gather", "higher", 0.05),
    (r"dispatch_(gate_holds|default_grouped)", "config", 0.0),
    # throughput-shaped (and headroom: MORE free HBM is better — this
    # must outrank the broad memory rule below or a headroom collapse
    # would be judged as a memory improvement): higher is better
    (r"(tokens_per_sec|tok_s|tflops|mfu|goodput|headroom|occupancy|"
     r"slots$|requests_per_s|steps_per_s)", "higher", 0.05),
    # step anatomy (obs/anatomy.py): overlap (collective time hidden under
    # compute) and achieved collective bandwidth are higher-better;
    # exposed collective time lower-better. These must outrank the broad
    # memory/latency rules: `achieved_gbps` would otherwise be unjudged
    # and `overlap_frac` has no other match. A collective's payload size
    # is a STATIC property of the compiled program (configuration
    # identity, like n_params) — without the config rule the memory
    # catch-all below would judge a deliberate sharding change's bigger
    # payload as a perf regression even when the step got faster.
    (r"top_collective\.bytes", "config", 0.0),
    (r"(overlap_frac|achieved_gbps)", "higher", 0.05),
    (r"(exposed_collective)", "lower", 0.10),
    # decomposed-collective overlap, bench `overlap` section
    # (collective_overlap_bench): the on/off exposed-collective and
    # step-time ratios are the overlap headline — lower is better, and
    # `step_ms_ratio` carries no terminal latency token so it would
    # otherwise go unjudged. The gradient-bucket budget is SIZED from
    # the measured bandwidth (bucket_bytes_from_report): a changed
    # budget means the measurement changed, not that memory regressed —
    # like top_collective.bytes it is configuration identity and must
    # outrank the memory catch-all below.
    (r"(exposed_ratio|step_ms_ratio)", "lower", 0.10),
    (r"grad_bucket_bytes", "config", 0.0),
    # prefix store (serve/prefix.py, bench `decode.prefix_trace`): hit
    # rate/tokens are higher-better; the TTFT and prefill-FLOPs on/off
    # ratios are the reuse headline — lower is better, and they must
    # outrank the memory rule (flops_ratio carries no memory-ish token
    # but resident bytes do: residency is trace-shaped, skip it)
    (r"prefix_(hit_rate|hit_tokens)", "higher", 0.05),
    (r"prefix.*(ttft|flops).*ratio", "lower", 0.10),
    (r"prefix_(resident|evicted|nodes)", "skip", 0.0),
    # speculative decoding (serve/spec.py, bench `decode.spec_trace`):
    # tokens emitted per decode step, the draft accept rate, and the
    # spec-on/off speedup are the headline — higher is better; rollback
    # counts are trace-shaped (they scale with how much was proposed),
    # skip them. Compile counts fall through to the compile rule below.
    (r"(max_draft|gen_tokens)", "config", 0.0),
    (r"(tokens_per_step|accept_rate|speedup)", "higher", 0.05),
    (r"(spec_rollbacks|draft_proposed|draft_accepted)", "skip", 0.0),
    # quantized serving (serve/cache.py int8/fp8 KV, bench
    # `decode.quant` + `gqa_capacity`): the slot budget — measured
    # max_slots_* and the quant/bf16 ratio — is the capacity headline,
    # higher is better, and it must outrank the memory rule (the keys
    # carry no memory token but a budget collapse must not go unjudged).
    # The stated accuracy tolerance and the KV storage dtype are
    # configuration identity: silently loosening the tolerance (or
    # switching int8 -> fp8) would make a worse kernel look "within
    # tolerance", so drift is a diff failure, not a judged metric.
    (r"(max_slots|slot_ratio)", "higher", 0.05),
    (r"(quant_kv$|tolerance)", "config", 0.0),
    # memory: lower is better, generous tolerance (allocator noise)
    (r"(hbm|bytes|_gb$|_mb$|rss)", "lower", 0.10),
    # compile counts: lower is better (a silent recompile regression)
    (r"(compiles|recompile)", "lower", 0.0),
    # latency-shaped: lower is better
    (r"(ttft|tpot|_ms$|_s$|_seconds$|latency|host_blocked|time)", "lower", 0.10),
)


def load_report(path: str) -> dict[str, Any]:
    """Parse one input file into a raw report dict (see module docstring
    for the accepted shapes). Raises ValueError on unusable input."""
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: not a JSON object")
    if "tail" in raw and isinstance(raw.get("tail"), str):
        # driver wrapper: the bench report is the last JSON-object line of
        # the captured tail (warnings precede it)
        for line in reversed(raw["tail"].splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                report = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(report, dict):
                return report
        # fall back to the driver's parsed headline
        parsed = raw.get("parsed")
        if isinstance(parsed, dict):
            return parsed
        raise ValueError(f"{path}: wrapper carries no parseable bench report")
    if "procs" in raw and isinstance(raw.get("procs"), dict):
        return _rollup_to_report(raw)
    return raw


def _rollup_to_report(rollup: dict[str, Any]) -> dict[str, Any]:
    """Reduce a series rollup to comparable scalars: per proc, the median
    of each numeric key over its points (median, not last — one straggler
    scrape must not define the run)."""
    out: dict[str, Any] = {}
    for proc, rec in sorted(rollup.get("procs", {}).items()):
        values: dict[str, list[float]] = {}
        for point in rec.get("points", []) or []:
            if not isinstance(point, dict):
                continue
            for k, v in point.items():
                if k == "ts" or isinstance(v, bool):
                    continue
                if isinstance(v, (int, float)):
                    values.setdefault(k, []).append(float(v))
        out[proc] = {
            k: round(statistics.median(vs), 6) for k, vs in values.items()
        }
    return out


def flatten(obj: Any, prefix: str = "") -> dict[str, float]:
    """Numeric leaves as dotted keys (bools excluded — they are flags,
    not measurements; strings and lists are structure, not data)."""
    out: dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(flatten(v, f"{prefix}.{k}" if prefix else str(k)))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix] = float(obj)
    return out


def rule_for(key: str, rules=DEFAULT_RULES) -> tuple[str, float] | None:
    for pattern, kind, tol in rules:
        if re.search(pattern, key):
            return kind, tol
    return None


def diff(old: dict[str, Any], new: dict[str, Any], *,
         rules=DEFAULT_RULES, tol_scale: float = 1.0) -> dict[str, Any]:
    """Compare two loaded reports; the verdict dict. ``ok`` is False iff
    any judged key regressed past its tolerance (scaled by ``tol_scale``
    for noisier rigs). The identity diff of any report against itself is
    ok by construction."""
    fo, fn = flatten(old), flatten(new)
    shared = sorted(set(fo) & set(fn))
    out: dict[str, Any] = {
        "compared": 0,
        "regressions": [],
        "improvements": [],
        "config_changed": [],
        "unjudged": [],
        "only_old": sorted(set(fo) - set(fn)),
        "only_new": sorted(set(fn) - set(fo)),
    }
    for key in shared:
        r = rule_for(key, rules)
        if r is None:
            out["unjudged"].append(key)
            continue
        kind, tol = r
        if kind == "skip":
            continue
        a, b = fo[key], fn[key]
        if kind == "config":
            if a != b:
                out["config_changed"].append(
                    {"key": key, "old": a, "new": b}
                )
            continue
        out["compared"] += 1
        base = abs(a)
        delta = (b - a) / base if base > 0 else (0.0 if b == a else float("inf"))
        tol = tol * tol_scale
        entry = {
            "key": key, "old": a, "new": b,
            "delta_frac": round(delta, 4) if delta != float("inf") else "inf",
            "tol": tol, "direction": kind,
        }
        if kind == "higher":
            if delta < -tol:
                out["regressions"].append(entry)
            elif delta > tol:
                out["improvements"].append(entry)
        else:  # lower is better
            if delta > tol:
                out["regressions"].append(entry)
            elif delta < -tol:
                out["improvements"].append(entry)
    # worst first: the headline regression leads the report
    def _sev(e) -> float:
        d = e["delta_frac"]
        return float("inf") if d == "inf" else abs(d)

    out["regressions"].sort(key=_sev, reverse=True)
    out["improvements"].sort(key=_sev, reverse=True)
    out["ok"] = not out["regressions"]
    return out


def diff_files(old_path: str, new_path: str, *,
               tol_scale: float = 1.0) -> dict[str, Any]:
    """Load + diff two report files (the ``tony perf diff`` body)."""
    verdict = diff(
        load_report(old_path), load_report(new_path), tol_scale=tol_scale
    )
    verdict["old"] = old_path
    verdict["new"] = new_path
    return verdict


__all__ = [
    "DEFAULT_RULES", "diff", "diff_files", "flatten", "load_report",
    "rule_for",
]

"""Declarative SLO engine: multi-window burn-rate alerting over the series.

The ROADMAP's open items all name operational contracts — TTFT collapse,
goodput under preemption, slot-budget headroom — but nothing *watches*
them. This module is the SRE-shaped answer (multi-window burn rates,
Beyer et al., "Site Reliability Engineering" ch. 5 alerting): declare
targets as ``slo.*`` config keys, and the engine evaluates them over the
live time-series (obs/series.py) as points arrive.

Targets (0 / unset = not contracted; only nonzero targets are watched):

- ``slo.ttft_p99_s``       — windowed p99 TTFT must stay UNDER the target
- ``slo.step_time_p99_s``  — windowed p99 train-step time, ditto
- ``slo.goodput_floor``    — ``goodput_frac`` must stay ABOVE the floor
- ``slo.hbm_headroom_frac``— device HBM headroom must stay ABOVE the floor
- ``slo.error_rate``       — serve error fraction must stay UNDER the target

Burn-rate semantics: a point is *bad* when its metric violates the target.
Each SLO is evaluated over TWO windows — fast (``slo.fast_window_s``,
default 5m: catches an incident now) and slow (``slo.slow_window_s``,
default 1h: proves it is sustained, clipped to the data actually
recorded) — and trips only when the bad fraction exceeds the error budget
(``slo.budget_frac``) in BOTH, with at least ``min_points`` samples in the
fast window so a single blip cannot page. The reported ``burn`` is
``bad_frac / budget_frac`` (1.0 = exactly consuming budget).

A trip follows the health-sentinel latch pattern: it latches for the
engine's lifetime, emits an ``slo.<name>`` trace instant (flushed
immediately — survives a chaos SIGKILL), bumps
``tony_slo_trips_total{slo=}`` + ``tony_slo_verdict`` registry metrics,
writes ``<app_dir>/slo/verdict_<proc>.json``, and dumps a forensics
bundle (the series window at trip + the offending values) next to it.
``tony top`` renders the verdict column; the chaos invariant checker's
``slo-surfaced`` rule refuses to report a tripped run clean.

Stdlib-only; evaluation runs on the series recorder's writer thread.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass
from typing import Any

log = logging.getLogger(__name__)

# env contract (AM -> executor -> user process): the resolved slo.* group
# as one JSON blob, so workers need no config-file round trip
ENV_SLO = "TONY_SLO"

# slo name -> (series point key, bad direction): "above" = a value above
# the target violates it, "below" = a value below the floor does
RULES: dict[str, tuple[str, str]] = {
    "ttft_p99_s": ("ttft_p99_s", "above"),
    "step_time_p99_s": ("step_time_p99_s", "above"),
    "goodput_floor": ("goodput_frac", "below"),
    "hbm_headroom_frac": ("hbm_headroom_frac", "below"),
    "error_rate": ("error_rate", "above"),
}


@dataclass(frozen=True)
class SloConfig:
    """Resolved ``slo.*`` key group (docs/OBS.md "SLO + time series")."""

    ttft_p99_s: float = 0.0
    step_time_p99_s: float = 0.0
    goodput_floor: float = 0.0
    hbm_headroom_frac: float = 0.0
    error_rate: float = 0.0
    budget_frac: float = 0.1
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    min_points: int = 3

    @classmethod
    def from_config(cls, config) -> "SloConfig":
        from tony_tpu.config.keys import Keys

        return cls(
            ttft_p99_s=config.get_float(Keys.SLO_TTFT_P99_S, 0.0),
            step_time_p99_s=config.get_float(Keys.SLO_STEP_TIME_P99_S, 0.0),
            goodput_floor=config.get_float(Keys.SLO_GOODPUT_FLOOR, 0.0),
            hbm_headroom_frac=config.get_float(Keys.SLO_HBM_HEADROOM_FRAC, 0.0),
            error_rate=config.get_float(Keys.SLO_ERROR_RATE, 0.0),
            budget_frac=config.get_float(Keys.SLO_BUDGET_FRAC, 0.1),
            fast_window_s=config.get_float(Keys.SLO_FAST_WINDOW_S, 300.0),
            slow_window_s=config.get_float(Keys.SLO_SLOW_WINDOW_S, 3600.0),
            min_points=config.get_int(Keys.SLO_MIN_POINTS, 3),
        )

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, blob: str) -> "SloConfig":
        return cls(**json.loads(blob))

    def active(self) -> list[str]:
        """The SLO names with a nonzero target — what the engine watches."""
        return [name for name in RULES if getattr(self, name) > 0]


class SloEngine:
    """Latching burn-rate evaluator over series points.

    ``observe(point)`` is the feed (the series recorder calls it from its
    writer thread — never the step loop). Points older than the slow
    window are evicted; each active SLO re-evaluates on every new point
    that carries its metric. Trips latch: one forensics bundle per cause,
    repeats counted but not re-reported (the health-sentinel discipline).
    """

    def __init__(self, cfg: SloConfig, *, registry=None,
                 app_dir: str | None = None, proc: str = ""):
        from tony_tpu.obs import trace

        self.cfg = cfg
        self._registry = registry
        self.app_dir = (
            app_dir if app_dir is not None
            else os.environ.get("TONY_APP_DIR", "")
        )
        self.proc = proc or trace.default_proc_name()
        self._active = cfg.active()
        self._points: deque = deque()
        self._newest = 0.0
        self._trips: dict[str, int] = {}
        self._trip_detail: dict[str, dict] = {}
        self._lock = threading.Lock()

    # --- evaluation -----------------------------------------------------------

    def observe(self, point: dict[str, Any]) -> None:
        if not self._active:
            return
        ts = float(point.get("ts", 0.0) or time.time())
        self._points.append((ts, point))
        # evict by the NEWEST timestamp seen, not wall clock: replayed or
        # clock-skewed journals still window consistently
        newest = self._newest = max(self._newest, ts)
        horizon = newest - self.cfg.slow_window_s
        while self._points and self._points[0][0] < horizon:
            self._points.popleft()
        for name in self._active:
            if name in self._trips:
                with self._lock:
                    self._trips[name] += 1 if self._bad(name, point) else 0
                continue
            self._evaluate(name, newest)

    def _bad(self, name: str, point: dict[str, Any]) -> bool | None:
        """Whether one point violates the SLO; None when the point does
        not carry the metric (no data is never a violation)."""
        key, direction = RULES[name]
        v = point.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            return None
        target = getattr(self.cfg, name)
        return v > target if direction == "above" else v < target

    def _window_frac(self, name: str, since: float) -> tuple[int, int]:
        """(bad, total) over points carrying the metric since ``since``."""
        bad = total = 0
        for ts, point in self._points:
            if ts < since:
                continue
            verdict = self._bad(name, point)
            if verdict is None:
                continue
            total += 1
            bad += int(verdict)
        return bad, total

    def _evaluate(self, name: str, now: float) -> None:
        cfg = self.cfg
        fast_bad, fast_n = self._window_frac(name, now - cfg.fast_window_s)
        if fast_n < max(cfg.min_points, 1):
            return  # a blip (or an empty/single-sample window) never pages
        slow_bad, slow_n = self._window_frac(name, now - cfg.slow_window_s)
        budget = max(cfg.budget_frac, 1e-9)
        fast_frac = fast_bad / fast_n
        slow_frac = slow_bad / max(slow_n, 1)
        if fast_frac <= budget or slow_frac <= budget:
            return
        key, direction = RULES[name]
        offending = [
            point.get(key) for ts, point in self._points
            if ts >= now - cfg.fast_window_s and self._bad(name, point)
        ]
        self._trip(name, {
            "metric": key,
            "direction": direction,
            "target": getattr(cfg, name),
            "fast_bad_frac": round(fast_frac, 4),
            "slow_bad_frac": round(slow_frac, 4),
            "burn_fast": round(fast_frac / budget, 2),
            "burn_slow": round(slow_frac / budget, 2),
            "fast_points": fast_n,
            "slow_points": slow_n,
            "worst": (
                max(offending) if direction == "above" else min(offending)
            ) if offending else None,
        })

    # --- tripping (the health-sentinel latch pattern) -------------------------

    def _trip(self, name: str, detail: dict[str, Any]) -> None:
        with self._lock:
            if name in self._trips:
                return
            self._trips[name] = 1
            self._trip_detail[name] = {"ts": time.time(), **detail}
        log.error("SLO %r tripped: %s", name, detail)
        from tony_tpu.obs import trace

        # precomputed args (GL005 discipline), flushed immediately so a
        # chaos SIGKILL racing the flusher cannot outrun the marker
        args = {
            k: v for k, v in detail.items()
            if isinstance(v, (int, float, str, bool)) or v is None
        }
        trace.instant(f"slo.{name}", **args)
        trace.flush()
        if self._registry is not None:
            self._export_into(self._registry)
        self._dump_bundle(name, detail)
        self.write_verdict()

    def _export_into(self, registry) -> None:
        with self._lock:
            trips = dict(self._trips)
        for name, n in trips.items():
            c = registry.counter(
                "tony_slo_trips_total",
                "SLO burn-rate trips (latched; counts repeat violations)",
                slo=name,
            )
            c.inc(n - c.value)
        registry.gauge(
            "tony_slo_verdict", "SLO verdict: 0 met, 1 tripped",
        ).set(1.0 if trips else 0.0)

    def export(self, registry) -> None:
        """Write ``tony_slo_*`` into ``registry`` (fit()/engine call this
        on their per-run registry before the shutdown snapshot, the
        health/hbm export pattern, so the portal ``/metrics`` serves it)."""
        self._export_into(registry)

    # --- forensics / verdict --------------------------------------------------

    def _slo_dir(self) -> str:
        return os.path.join(self.app_dir, "slo") if self.app_dir else ""

    def _dump_bundle(self, name: str, detail: dict[str, Any]) -> None:
        """One bundle per tripped SLO, written synchronously at trip time:
        the fast-window series slice that burned the budget plus the
        offending quantiles — the "what did the incident look like"
        evidence. Best effort: a full disk costs the bundle, not the run."""
        out_dir = self._slo_dir()
        if not out_dir:
            return
        now = time.time()
        # the window slices by the NEWEST point ts, exactly like
        # evaluation — a wall-clock filter would ship an empty bundle for
        # a skew-lagged or replayed feed (the very trip it documents)
        horizon = self._newest - self.cfg.fast_window_s
        window = [point for ts, point in self._points if ts >= horizon]
        bundle = {
            "slo": name,
            "ts": now,
            "proc": self.proc,
            "detail": detail,
            "config": asdict(self.cfg),
            "window": window[-256:],
        }
        path = os.path.join(out_dir, f"{self.proc}_{name}.trip.json")
        try:
            os.makedirs(out_dir, exist_ok=True)
            with open(path + ".tmp", "w", encoding="utf-8") as f:
                json.dump(bundle, f, default=str)
            os.replace(path + ".tmp", path)
        except OSError:
            log.warning("could not write SLO bundle %s", path, exc_info=True)

    def write_verdict(self) -> None:
        out_dir = self._slo_dir()
        if not out_dir:
            return
        with self._lock:
            payload = {
                "verdict": "tripped" if self._trips else "met",
                "proc": self.proc,
                "ts": time.time(),
                "watched": list(self._active),
                "slos": {
                    name: {"trips": n, **self._trip_detail.get(name, {})}
                    for name, n in self._trips.items()
                },
            }
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, f"verdict_{self.proc}.json")
            with open(path + ".tmp", "w", encoding="utf-8") as f:
                json.dump(payload, f, default=str)
            os.replace(path + ".tmp", path)
        except OSError:
            log.warning("could not write SLO verdict", exc_info=True)

    # --- reporting ------------------------------------------------------------

    @property
    def verdict(self) -> str:
        return "tripped" if self._trips else "met"

    def trip_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._trips)

    def summary(self) -> dict[str, Any]:
        with self._lock:
            return {
                "verdict": "tripped" if self._trips else "met",
                "watched": list(self._active),
                "trips": dict(self._trips),
                "detail": dict(self._trip_detail),
            }


# --- process-global arming ----------------------------------------------------

_engine: SloEngine | None = None


def active_engine() -> SloEngine | None:
    return _engine


def install(engine: SloEngine) -> SloEngine:
    global _engine
    _engine = engine
    return engine


def uninstall() -> None:
    global _engine
    _engine = None


def attach_from_env(recorder, proc: str = "") -> SloEngine | None:
    """Wire an SLO engine onto a series recorder from the ``TONY_SLO`` env
    the AM exported. No active targets (or no env) = nothing installed —
    the recorder keeps journaling, nothing alerts. Idempotent."""
    if _engine is not None:
        return _engine
    blob = os.environ.get(ENV_SLO, "")
    if not blob:
        return None
    try:
        cfg = SloConfig.from_json(blob)
    except (ValueError, TypeError):
        log.warning("malformed %s env; SLO engine not armed", ENV_SLO)
        return None
    if not cfg.active():
        return None
    from tony_tpu.obs.registry import get_registry

    engine = install(SloEngine(cfg, registry=get_registry(), proc=proc))
    recorder.add_observer(engine.observe)
    return engine


# --- read paths (CLI, portal, invariant checker) ------------------------------


def read_verdicts(app_dir: str) -> dict[str, dict]:
    """Per-process SLO verdicts under ``<app_dir>/slo/`` (proc -> payload).
    Deviceless read path shared by ``tony top``, the portal, and the chaos
    invariant checker — ONE reader, one layout."""
    sdir = os.path.join(app_dir, "slo")
    out: dict[str, dict] = {}
    try:
        names = sorted(os.listdir(sdir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("verdict_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(sdir, name), encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(payload, dict):
            out[payload.get("proc") or name[len("verdict_"):-5]] = payload
    return out


def forensics_files(app_dir: str) -> list[str]:
    sdir = os.path.join(app_dir, "slo")
    try:
        return sorted(n for n in os.listdir(sdir) if n.endswith(".trip.json"))
    except OSError:
        return []


def rollup(app_dir: str) -> dict[str, Any]:
    """Merged per-app SLO view (`tony top`'s status column): ``tripped``
    when ANY process tripped, ``met`` when at least one verdict exists and
    none tripped, ``unwatched`` otherwise (no targets configured, or the
    job predates the engine)."""
    verdicts = read_verdicts(app_dir)
    bundles = forensics_files(app_dir)
    tripped = {
        proc: v for proc, v in verdicts.items()
        if v.get("verdict") == "tripped"
    }
    slos: dict[str, int] = {}
    for v in tripped.values():
        for name, info in (v.get("slos") or {}).items():
            slos[name] = slos.get(name, 0) + int((info or {}).get("trips", 1) or 1)
    if tripped or bundles:
        verdict = "tripped"
    elif verdicts:
        verdict = "met"
    else:
        verdict = "unwatched"
    return {
        "verdict": verdict,
        "procs": verdicts,
        "slos": slos,
        "bundles": bundles,
    }


__all__ = [
    "ENV_SLO", "RULES", "SloConfig", "SloEngine", "active_engine",
    "attach_from_env", "forensics_files", "install", "read_verdicts",
    "rollup", "uninstall",
]

"""Numerics health sentinel: in-graph value monitors, anomaly rules, and
bad-step forensics.

The flight recorder's first two axes answer *where the time went* (the
trace spine, obs/trace.py) and *where the HBM went* (the memory/compile
observatory, obs/hbm.py). This module completes the third axis — whether
the *numbers* are healthy. A NaN'd optimizer state, a loss spike, or a
degenerate sampler otherwise surfaces only as a silently ruined run; a
supervised-restart decision (the TonY mandate) needs a machine-readable
health verdict to act on.

Three layers, mirroring the established observatory shape:

- **In-graph monitors** (:func:`graph_monitors`, :func:`decode_monitors`)
  are pure jnp reductions fused into the already-jitted train/decode
  steps: summed-``isfinite`` nonfinite counts over grads/params/loss,
  update-to-param ratio, per-layer grad RMS over the stacked layer dim,
  a positional batch fingerprint (data-pipeline skew detection), and —
  serve side — per-slot logits-nonfinite counts and sampling entropy.
  They cost a few extra reductions inside an XLA program that already
  reads every operand; when no sentinel is armed they are not compiled
  in at all (bench.py's ``health_overhead`` section measures the delta).
- **The hot-path seam** (:func:`sample`) holds the trace-span/hbm-sample
  contract: disarmed it is ONE global load + ``None`` compare (tier-1
  ≤5µs guard, graft-lint GL005); armed off-stride it is one counter
  bump. Every ``sample_steps``-th call enqueues the step's *device
  references* onto a bounded queue drained by a daemon thread — the
  ``jax.device_get`` sync happens on the worker, never the step loop.
- **The rule engine** (:class:`HealthSentinel`) evaluates host-side
  anomaly rules over the dequeued samples: NaN/Inf trip, loss-spike
  z-score over a rolling window, grad-norm explosion/collapse,
  stagnation, repeated-batch pipeline skew, and the serve-side
  logits-nonfinite + entropy-floor (degenerate sampling) detectors with
  per-request attribution. A tripped rule latches, emits a
  ``health.<rule>`` trace instant + ``tony_health_*`` registry metrics,
  flips the per-app verdict (``<app_dir>/health/verdict_<proc>.json`` —
  the portal's ``/healthz`` and ``tony health <app_id>`` read it), and
  dumps a forensics bundle (last-k step-stats ring, per-layer stats at
  trip, offending batch fingerprint + stream position, latest checkpoint
  pointer) — written synchronously at trip time so a chaos SIGKILL
  cannot outrun the marker.

The module imports jax lazily (the AM exports the ``obs.health.*`` env
contract without owning a device; the CLI/portal read paths run in
deviceless processes).
"""

from __future__ import annotations

import json
import logging
import math
import os
import queue
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass
from typing import Any

log = logging.getLogger(__name__)

# env contract (AM -> executor -> user process, next to TONY_TRACE_* and
# TONY_OBS_HBM*)
ENV_ENABLED = "TONY_OBS_HEALTH"          # "0" disables arming
ENV_SAMPLE = "TONY_OBS_HEALTH_SAMPLE"    # rule-evaluation stride (steps)
ENV_WINDOW = "TONY_OBS_HEALTH_WINDOW"    # rolling-stats window + ring size

# numerics chaos seam (tests / chaos jobs): fit()'s train step adds an
# in-graph NaN to the reported loss from this step onward (persistent,
# like a real NaN'd state), so a tier-1 job can prove injection -> trip
# -> forensics end to end
ENV_NAN_STEP = "TONY_CHAOS_NAN_STEP"

# every rule the engine can trip (docs/OBS.md "Numerics health")
RULES = (
    "nonfinite",        # NaN/Inf in loss, grads, or params
    "loss_spike",       # loss z-score over the rolling window
    "grad_explosion",   # global grad norm above the absolute ceiling
    "grad_collapse",    # global grad norm ~0 for k consecutive samples
    "stagnation",       # loss flat to within rel tolerance over the window
    "repeated_batch",   # identical batch fingerprint k times in a row
    "serve_nonfinite",  # NaN/Inf logits in a live decode slot
    "entropy_floor",    # sampling entropy under the floor for k steps
)


# --- in-graph monitors --------------------------------------------------------


def _is_float_dtype(dtype) -> bool:
    """Static dtype predicate (host-side metadata, never a traced value):
    numpy floats plus the ml_dtypes families numpy cannot classify."""
    import numpy as np

    return bool(np.issubdtype(dtype, np.floating)) or str(dtype).startswith(
        ("bfloat16", "float8")
    )


def graph_monitors(loss, grads, params, updates, inputs) -> dict:
    """Fused value monitors for the train step: a dict of small device
    arrays computed inside the jitted step (callers merge it into the
    step's metrics; everything here is reductions over operands the step
    already touches). Keys are namespaced ``health/...`` so the host-side
    engine can split them from the ordinary metrics."""
    import jax
    import jax.numpy as jnp

    def _nonfinite_count(tree) -> Any:
        # float32 accumulation: counts only gate on > 0, and f32 keeps the
        # sum exact far past any plausible poisoned-element count
        total = jnp.float32(0.0)
        for leaf in jax.tree.leaves(tree):
            if _is_float_dtype(leaf.dtype):
                total = total + jnp.sum(
                    (~jnp.isfinite(leaf)).astype(jnp.float32)
                )
        return total

    def _sq_norm(tree) -> Any:
        total = jnp.float32(0.0)
        for leaf in jax.tree.leaves(tree):
            if _is_float_dtype(leaf.dtype):
                total = total + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        return total

    p_sq = _sq_norm(params)
    out = {
        "health/nonfinite_loss": (~jnp.isfinite(loss)).astype(jnp.float32),
        "health/nonfinite_grads": _nonfinite_count(grads),
        "health/nonfinite_params": _nonfinite_count(params),
        # update-to-param ratio |Δθ|/|θ|: the classic silent-divergence
        # telltale (a healthy Adam run sits around lr-scale; 0 means a
        # dead optimizer, >>lr means a blowup in progress)
        "health/update_ratio": jnp.sqrt(_sq_norm(updates))
        / (jnp.sqrt(p_sq) + jnp.float32(1e-12)),
        "health/batch_fingerprint": batch_fingerprint(inputs),
    }
    rms = layer_grad_rms(grads)
    if rms is not None:
        out["health/layer_grad_rms"] = rms
    return out


def layer_grad_rms(grads) -> Any:
    """Per-layer grad RMS over the stacked layer dim ([L] vector), the
    which-layer-went-bad attribution a forensics bundle carries. None when
    the tree has no ``layers`` stack (non-transformer params)."""
    import jax
    import jax.numpy as jnp

    layers = grads.get("layers") if isinstance(grads, dict) else None
    if not layers:
        return None
    leaves = [
        leaf for leaf in jax.tree.leaves(layers)
        if getattr(leaf, "ndim", 0) >= 1 and _is_float_dtype(leaf.dtype)
    ]
    if not leaves:
        return None
    n_layers = leaves[0].shape[0]
    leaves = [leaf for leaf in leaves if leaf.shape[0] == n_layers]
    sq = jnp.zeros((n_layers,), jnp.float32)
    count = 0
    for leaf in leaves:
        axes = tuple(range(1, leaf.ndim))
        sq = sq + jnp.sum(jnp.square(leaf.astype(jnp.float32)), axis=axes)
        count += int(math.prod(leaf.shape[1:]) or 1)
    return jnp.sqrt(sq / jnp.float32(max(count, 1)))


def batch_fingerprint(inputs) -> Any:
    """Position-weighted uint32 checksum of a token batch. Equal batches
    produce equal fingerprints, permuted or shifted ones do not — the
    repeated-batch rule detects a wedged input pipeline feeding the same
    data every step (a real failure mode of stuck prefetch rings)."""
    import jax.numpy as jnp

    flat = inputs.astype(jnp.uint32).reshape(-1)
    weights = (
        jnp.arange(flat.shape[0], dtype=jnp.uint32)
        * jnp.uint32(2654435761)  # Knuth multiplicative hash step
        + jnp.uint32(1)
    )
    return jnp.sum(flat * weights, dtype=jnp.uint32)


def decode_monitors(logits) -> dict:
    """Fused value monitors for the serve decode step: per-slot nonfinite
    counts over the sampling logits and the softmax entropy (nats) of the
    distribution the sampler draws from. [S]-shaped so the host engine can
    attribute a trip to the request occupying the slot."""
    import jax
    import jax.numpy as jnp

    finite = jnp.isfinite(logits)
    safe = jnp.where(finite, logits, -jnp.inf)
    logp = jax.nn.log_softmax(safe, axis=-1)
    p = jnp.exp(logp)
    entropy = -jnp.sum(jnp.where(p > 0, p * logp, 0.0), axis=-1)
    return {
        "logits_nonfinite": jnp.sum(
            (~finite).astype(jnp.float32), axis=-1
        ),
        "entropy": entropy.astype(jnp.float32),
    }


def nan_inject_step() -> int | None:
    """The numerics chaos seam: step number from which the train step
    poisons its reported loss with an in-graph NaN (``TONY_CHAOS_NAN_STEP``,
    exported into worker env by a chaos-style job config). None = off."""
    raw = os.environ.get(ENV_NAN_STEP, "")
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


# --- host-side rule engine ----------------------------------------------------


@dataclass(frozen=True)
class HealthRules:
    """Rule thresholds (docs/OBS.md has the semantics table)."""

    window: int = 64          # rolling-stats window AND forensics ring size
    min_samples: int = 8      # samples before z-score rules may fire
    loss_spike_z: float = 8.0
    grad_explode: float = 1e4
    grad_collapse: float = 1e-8
    collapse_k: int = 4
    stagnation_rel: float = 1e-9  # (max-min)/|mean| over a FULL window
    repeat_k: int = 3
    entropy_floor: float = 0.05   # nats; vocab-V healthy decode is O(ln V)
    entropy_k: int = 8


class HealthSentinel:
    """Asynchronous anomaly-rule engine over sampled step values.

    ``sample(**args)`` is the armed hot path: stride-counted, and a stride
    hit enqueues the kwargs (device references — no sync) for the daemon
    worker, which fetches them to host and evaluates the rules. Train
    samples carry ``metrics`` (the step's metrics dict, ``health/*`` keys
    included); serve samples carry ``metrics`` (``logits_nonfinite`` /
    ``entropy``), ``slot_rids``, and ``live_slots``.

    A tripped rule latches for the sentinel's lifetime (``reset()`` in
    tests): the first firing writes the forensics bundle + verdict file,
    emits the trace instant, and bumps the registry counter; repeats of a
    latched rule are not re-reported — a NaN'd run stays NaN'd every step
    and one bundle per cause is the signal, not thousands.
    """

    def __init__(self, rules: HealthRules | None = None, *,
                 sample_every: int = 16, registry=None,
                 app_dir: str | None = None, proc: str = "",
                 checkpoint_dir: str = "", queue_size: int = 64):
        from tony_tpu.obs import trace

        self.rules = rules or HealthRules()
        self.sample_every = max(int(sample_every), 1)
        self._registry = registry
        self.app_dir = (
            app_dir if app_dir is not None
            else os.environ.get("TONY_APP_DIR", "")
        )
        self.proc = proc or trace.default_proc_name()
        self.checkpoint_dir = (
            checkpoint_dir or os.environ.get("TONY_CHECKPOINT_DIR", "")
        )
        self.dropped = 0          # queue overflow (worker slower than steps)
        self._n = 0               # seam stride counter
        self._pending = 0         # enqueued-but-unevaluated samples
        self._trips: dict[str, int] = {}       # rule -> trip count (latched)
        self._trip_detail: dict[str, dict] = {}  # rule -> first-trip detail
        self._bundles: list[str] = []
        self._ring: deque = deque(maxlen=max(self.rules.window, 8))
        self._losses: deque = deque(maxlen=max(self.rules.window, 8))
        self._last_step: int | None = None
        self._last_layers: list[float] | None = None
        self._collapse_run = 0
        self._repeat_run = 0
        self._last_fingerprint: float | None = None
        self._serve_step = 0
        self._entropy_runs: dict[int, int] = {}  # rid -> consecutive low
        self._lock = threading.Lock()
        self._q: queue.Queue = queue.Queue(maxsize=max(int(queue_size), 4))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name="tony-health"
        )
        self._thread.start()

    # --- hot path -------------------------------------------------------------

    def sample(self, **args: Any) -> None:
        """Stride-counted enqueue; the off-stride cost is one increment +
        modulo, a stride hit is one bounded queue put of references."""
        self._n += 1
        if self._n % self.sample_every:
            return
        self.observe_async(args)

    def observe_async(self, args: dict[str, Any]) -> None:
        try:
            with self._lock:
                self._pending += 1
            self._q.put_nowait(args)
        except queue.Full:
            with self._lock:
                self._pending -= 1
                self.dropped += 1

    # --- worker ---------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            try:
                args = self._q.get(timeout=0.25)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if args is None:  # close() sentinel
                return
            try:
                self._evaluate(self._fetch(args))
            except Exception:
                log.debug("health sample evaluation failed", exc_info=True)
            finally:
                with self._lock:
                    self._pending -= 1

    @staticmethod
    def _fetch(args: dict[str, Any]) -> dict[str, Any]:
        """Device -> host for the enqueued references; the sync lands on
        this worker thread, never the step loop. Pass-through when jax is
        absent (unit tests, deviceless processes feed plain floats)."""
        try:
            import jax

            return jax.device_get(args)
        except Exception:
            return args

    # --- rule evaluation ------------------------------------------------------

    def _evaluate(self, args: dict[str, Any]) -> None:
        metrics = args.get("metrics") or {}
        if "logits_nonfinite" in metrics or "entropy" in metrics:
            self._eval_serve(args, metrics)
        else:
            self._eval_train(metrics)

    @staticmethod
    def _scalar(v, default: float = 0.0) -> float:
        try:
            return float(v)
        except (TypeError, ValueError):
            return default

    def _eval_train(self, metrics: dict[str, Any]) -> None:
        r = self.rules
        step = int(self._scalar(metrics.get("step"), 0))
        # absence is not NaN: a caller sampling only a subset of metrics
        # (custom step loop) must never trip the nonfinite rule on keys it
        # simply did not report
        loss_raw = metrics.get("loss")
        gnorm_raw = metrics.get("grad_norm")
        loss = self._scalar(loss_raw, math.nan)
        gnorm = self._scalar(gnorm_raw, math.nan)
        health = {
            k.split("/", 1)[1]: v for k, v in metrics.items()
            if isinstance(k, str) and k.startswith("health/")
        }
        if self._last_step is not None and step <= self._last_step:
            # a new run re-entered this process (bench sweeps, tests):
            # rolling statistics must not blend two runs' trajectories
            self._reset_windows()
        self._last_step = step

        layers = health.get("layer_grad_rms")
        if layers is not None:
            try:
                self._last_layers = [round(float(x), 6) for x in layers]
            except TypeError:
                pass
        rec = {
            "step": step,
            "loss": None if math.isnan(loss) else round(loss, 6),
            "grad_norm": None if math.isnan(gnorm) else round(gnorm, 6),
        }
        for key in ("nonfinite_loss", "nonfinite_grads", "nonfinite_params",
                    "update_ratio", "batch_fingerprint"):
            if key in health:
                rec[key] = self._scalar(health[key])
        self._ring.append(rec)

        # nonfinite: the unambiguous trip — any NaN/Inf in loss/grads/params
        bad = {
            k: self._scalar(health.get(k))
            for k in ("nonfinite_loss", "nonfinite_grads", "nonfinite_params")
            if self._scalar(health.get(k)) > 0
        }
        if not health and loss_raw is not None and not math.isfinite(loss):
            bad["loss"] = loss  # monitor-less sample: the loss itself tells
        if (loss_raw is not None and math.isnan(loss)) or (
            gnorm_raw is not None and math.isnan(gnorm)
        ):
            bad.setdefault("nonfinite_loss", 1.0)
        if bad:
            self._trip("nonfinite", step, {"counts": bad})

        # loss spike: z-score against the rolling window of FINITE losses
        if math.isfinite(loss):
            if len(self._losses) >= r.min_samples:
                mean = sum(self._losses) / len(self._losses)
                var = sum((x - mean) ** 2 for x in self._losses) / len(self._losses)
                std = math.sqrt(var)
                if std > 0 and (loss - mean) / std > r.loss_spike_z:
                    self._trip("loss_spike", step, {
                        "loss": loss, "window_mean": round(mean, 6),
                        "window_std": round(std, 6),
                        "z": round((loss - mean) / std, 2),
                    })
                # stagnation: a FULL window flat to relative tolerance —
                # the loop is running but learning nothing (dead optimizer,
                # zero lr, detached graph)
                if (
                    len(self._losses) == self._losses.maxlen
                    and max(self._losses) - min(self._losses)
                    <= r.stagnation_rel * max(abs(mean), 1e-12)
                    and abs(loss - mean) <= r.stagnation_rel * max(abs(mean), 1e-12)
                ):
                    self._trip("stagnation", step, {
                        "loss": loss, "window": len(self._losses),
                        "spread": max(self._losses) - min(self._losses),
                    })
            self._losses.append(loss)

        # grad explosion / collapse
        if math.isfinite(gnorm):
            if gnorm > r.grad_explode:
                self._trip("grad_explosion", step, {
                    "grad_norm": gnorm, "ceiling": r.grad_explode,
                })
            if gnorm < r.grad_collapse:
                self._collapse_run += 1
                if self._collapse_run >= r.collapse_k:
                    self._trip("grad_collapse", step, {
                        "grad_norm": gnorm, "consecutive": self._collapse_run,
                    })
            else:
                self._collapse_run = 0

        # repeated batch: the data pipeline is feeding the same tokens
        fp = health.get("batch_fingerprint")
        if fp is not None:
            fp = self._scalar(fp)
            if self._last_fingerprint is not None and fp == self._last_fingerprint:
                self._repeat_run += 1
                if self._repeat_run + 1 >= r.repeat_k:
                    self._trip("repeated_batch", step, {
                        "fingerprint": int(fp),
                        "consecutive": self._repeat_run + 1,
                        "stream_step": step,
                    })
            else:
                self._repeat_run = 0
            self._last_fingerprint = fp

    def _eval_serve(self, args: dict[str, Any], metrics: dict[str, Any]) -> None:
        r = self.rules
        self._serve_step += 1
        step = self._serve_step
        slot_rids = list(args.get("slot_rids") or [])
        live = args.get("live_slots")
        nonfinite = metrics.get("logits_nonfinite")
        entropy = metrics.get("entropy")
        n_slots = len(slot_rids)
        live_idx = (
            [int(s) for s in live] if live is not None else list(range(n_slots))
        )
        rec: dict[str, Any] = {"step": step, "live": len(live_idx)}
        for s in live_idx:
            rid = slot_rids[s] if s < n_slots else None
            if nonfinite is not None and self._scalar(nonfinite[s]) > 0:
                rec["nonfinite_slot"] = s
                self._trip("serve_nonfinite", step, {
                    "rid": rid, "slot": s,
                    "nonfinite_logits": self._scalar(nonfinite[s]),
                })
            if entropy is not None:
                ent = self._scalar(entropy[s], math.inf)
                key = rid if rid is not None else -1 - s
                if ent < r.entropy_floor:
                    run = self._entropy_runs.get(key, 0) + 1
                    self._entropy_runs[key] = run
                    if run >= r.entropy_k:
                        self._trip("entropy_floor", step, {
                            "rid": rid, "slot": s,
                            "entropy": round(ent, 5),
                            "floor": r.entropy_floor,
                            "consecutive": run,
                        })
                else:
                    self._entropy_runs.pop(key, None)
        # slots freed between samples keep no stale low-entropy run
        live_keys = {
            slot_rids[s] if s < n_slots and slot_rids[s] is not None else -1 - s
            for s in live_idx
        }
        for key in list(self._entropy_runs):
            if key not in live_keys:
                del self._entropy_runs[key]
        self._ring.append(rec)

    # --- tripping -------------------------------------------------------------

    def _trip(self, rule: str, step: int, detail: dict[str, Any]) -> None:
        with self._lock:
            if rule in self._trips:
                self._trips[rule] += 1
                return
            self._trips[rule] = 1
            self._trip_detail[rule] = {"step": step, **detail}
        log.error("health rule %r tripped at step %d: %s", rule, step, detail)
        from tony_tpu.obs import trace

        # the instant lands between the step spans it interrupted on the
        # merged timeline; flush immediately so a chaos SIGKILL racing the
        # flusher thread cannot outrun the marker
        trace.instant(f"health.{rule}", step=step, **{
            k: v for k, v in detail.items()
            if isinstance(v, (int, float, str, bool)) or v is None
        })
        trace.flush()
        if self._registry is not None:
            self._export_into(self._registry)
        self._dump_bundle(rule, step, detail)
        self.write_verdict()

    def _export_into(self, registry) -> None:
        with self._lock:
            trips = dict(self._trips)
        for rule, n in trips.items():
            c = registry.counter(
                "tony_health_trips_total",
                "health-rule trips (latched; counts repeats of the cause)",
                rule=rule,
            )
            c.inc(n - c.value)
        registry.gauge(
            "tony_health_verdict",
            "numerics verdict: 0 healthy, 1 tripped",
        ).set(1.0 if trips else 0.0)

    def export(self, registry) -> None:
        """Write ``tony_health_*`` into ``registry`` (fit()/engine call
        this on their per-run registry right before the shutdown snapshot,
        the hbm.export_gauges pattern, so the portal ``/metrics`` serves
        the verdict)."""
        self._export_into(registry)

    # --- forensics ------------------------------------------------------------

    def _health_dir(self) -> str:
        return os.path.join(self.app_dir, "health") if self.app_dir else ""

    def _latest_checkpoint(self) -> dict[str, Any]:
        out: dict[str, Any] = {"dir": self.checkpoint_dir}
        if self.checkpoint_dir and os.path.isdir(self.checkpoint_dir):
            steps = [
                int(d) for d in os.listdir(self.checkpoint_dir) if d.isdigit()
            ]
            if steps:
                out["latest_step"] = max(steps)
        return out

    def _dump_bundle(self, rule: str, step: int, detail: dict[str, Any]) -> None:
        """One forensics bundle per tripped rule, written synchronously at
        trip time (the marker must survive an immediate SIGKILL). Best
        effort: a full disk costs the bundle, never the run."""
        out_dir = self._health_dir()
        if not out_dir:
            return
        bundle = {
            "rule": rule,
            "step": step,
            "ts": time.time(),
            "proc": self.proc,
            "detail": detail,
            "rules": asdict(self.rules),
            "sample_every": self.sample_every,
            # the last-k step-stats ring: the trajectory INTO the bad step
            "ring": list(self._ring),
            # per-layer grad RMS at (or just before) the trip: which layer
            "layer_grad_rms": self._last_layers,
            # where the input stream was: step N is stream position N for
            # every built-in stream (synthetic keys the rng by step, mmap/
            # native seek by step), so a resume can replay the batch
            "batch": {
                "stream_step": step,
                "fingerprint": self._last_fingerprint,
                "repeats": self._repeat_run + 1 if self._repeat_run else 0,
            },
            "checkpoint": self._latest_checkpoint(),
        }
        name = f"{self.proc}_{rule}_step{step}.trip.json"
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, name)
            with open(path + ".tmp", "w", encoding="utf-8") as f:
                json.dump(bundle, f, default=str)
            os.replace(path + ".tmp", path)
            self._bundles.append(path)
        except OSError:
            log.warning("could not write health bundle %s", name, exc_info=True)

    def write_verdict(self) -> None:
        out_dir = self._health_dir()
        if not out_dir:
            return
        with self._lock:
            payload = {
                "verdict": "tripped" if self._trips else "healthy",
                "proc": self.proc,
                "ts": time.time(),
                "rules": {
                    rule: {"trips": n, **self._trip_detail.get(rule, {})}
                    for rule, n in self._trips.items()
                },
                "dropped_samples": self.dropped,
            }
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, f"verdict_{self.proc}.json")
            with open(path + ".tmp", "w", encoding="utf-8") as f:
                json.dump(payload, f, default=str)
            os.replace(path + ".tmp", path)
        except OSError:
            log.warning("could not write health verdict", exc_info=True)

    # --- lifecycle / reporting ------------------------------------------------

    @property
    def verdict(self) -> str:
        return "tripped" if self._trips else "healthy"

    def trip_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._trips)

    def summary(self) -> dict[str, Any]:
        with self._lock:
            return {
                "verdict": "tripped" if self._trips else "healthy",
                "trips": dict(self._trips),
                "detail": dict(self._trip_detail),
                "dropped_samples": self.dropped,
            }

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Wait (bounded) until every enqueued sample has been evaluated —
        fit()/engine shutdown call this so a trip on the final steps lands
        in the final report. Returns False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._pending == 0:
                    return True
            time.sleep(0.005)
        return False

    def _reset_windows(self) -> None:
        # everything trajectory-shaped resets together: the forensics ring
        # and the per-layer snapshot must not blend a previous run's tail
        # into a new run's bundle any more than the z-score window may
        self._losses.clear()
        self._collapse_run = 0
        self._repeat_run = 0
        self._last_fingerprint = None
        self._entropy_runs.clear()
        self._ring.clear()
        self._last_layers = None

    def reset(self) -> None:
        """Full reset incl. trip latches (tests, explicit re-runs)."""
        self.drain(timeout_s=2.0)
        with self._lock:
            self._trips.clear()
            self._trip_detail.clear()
        self._reset_windows()
        self._ring.clear()
        self._last_step = None
        self._serve_step = 0

    def close(self, join_timeout_s: float = 2.0) -> None:
        self.drain(timeout_s=join_timeout_s)
        self._stop.set()
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=max(join_timeout_s, 0.0))
        self.write_verdict()


# --- process-global arming (the trace/hbm pattern) ----------------------------

_sentinel: HealthSentinel | None = None


def active_sentinel() -> HealthSentinel | None:
    return _sentinel


def install(sentinel: HealthSentinel) -> HealthSentinel:
    global _sentinel
    if _sentinel is not None and _sentinel is not sentinel:
        _sentinel.close()
    _sentinel = sentinel
    return sentinel


def uninstall() -> None:
    global _sentinel
    if _sentinel is not None:
        _sentinel.close()
        _sentinel = None


def sample(**args: Any) -> None:
    """The hot-path seam (train/serve step loops). Disarmed: one global
    load + ``None`` compare. Call sites must pass precomputed names only
    (graft-lint GL005 enforces this like the trace/chaos/hbm hooks)."""
    s = _sentinel
    if s is not None:
        s.sample(**args)


def install_from_env() -> HealthSentinel | None:
    """Arm this process from the ``TONY_OBS_HEALTH*`` env the AM exported
    (defaults apply standalone — a bare fit() or engine gets the sentinel
    without a job). Idempotent; ``TONY_OBS_HEALTH=0`` disables."""
    if _sentinel is not None:
        return _sentinel
    if os.environ.get(ENV_ENABLED, "") == "0":
        return None

    def _env_int(key: str, default: int) -> int:
        try:
            return int(os.environ.get(key, "") or default)
        except ValueError:
            return default

    from tony_tpu.obs.registry import get_registry

    window = _env_int(ENV_WINDOW, 64)
    return install(HealthSentinel(
        HealthRules(window=window),
        sample_every=_env_int(ENV_SAMPLE, 16),
        registry=get_registry(),
    ))


# --- read paths (CLI, portal, invariant checker) ------------------------------


def read_verdicts(app_dir: str) -> dict[str, dict]:
    """Per-process verdicts under ``<app_dir>/health/`` (proc -> payload).
    Deviceless read path shared by ``tony health``, the portal ``/healthz``
    endpoint, and the chaos invariant checker — ONE reader, one layout."""
    hdir = os.path.join(app_dir, "health")
    out: dict[str, dict] = {}
    try:
        names = sorted(os.listdir(hdir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("verdict_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(hdir, name), encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(payload, dict):
            out[payload.get("proc") or name[len("verdict_"):-5]] = payload
    return out


def forensics_files(app_dir: str) -> list[str]:
    """Forensics bundle filenames under an app dir (the chaos runner lists
    these next to the OOM bundles)."""
    hdir = os.path.join(app_dir, "health")
    try:
        return sorted(n for n in os.listdir(hdir) if n.endswith(".trip.json"))
    except OSError:
        return []


def rollup(app_dir: str) -> dict[str, Any]:
    """The ``tony health <app_id>`` report: per-process verdicts, merged
    tripped rules, and the bundle listing. ``verdict`` is ``tripped`` when
    ANY process tripped, ``healthy`` when at least one verdict file exists
    and none tripped, ``unknown`` otherwise (job predates the sentinel, or
    it died before writing)."""
    verdicts = read_verdicts(app_dir)
    bundles = forensics_files(app_dir)
    tripped = {
        proc: v for proc, v in verdicts.items()
        if v.get("verdict") == "tripped"
    }
    rules: dict[str, int] = {}
    for v in tripped.values():
        for rule, info in (v.get("rules") or {}).items():
            rules[rule] = rules.get(rule, 0) + int(
                (info or {}).get("trips", 1) or 1
            )
    if tripped or bundles:
        verdict = "tripped"
    elif verdicts:
        verdict = "healthy"
    else:
        verdict = "unknown"
    return {
        "verdict": verdict,
        "procs": verdicts,
        "rules": rules,
        "bundles": bundles,
    }


__all__ = [
    "ENV_ENABLED", "ENV_NAN_STEP", "ENV_SAMPLE", "ENV_WINDOW",
    "HealthRules", "HealthSentinel", "RULES", "active_sentinel",
    "batch_fingerprint", "decode_monitors", "forensics_files",
    "graph_monitors", "install", "install_from_env", "layer_grad_rms",
    "nan_inject_step", "read_verdicts", "rollup", "sample", "uninstall",
]

"""Fleet-coordinated profiling: the AM-broadcast capture window.

The flight recorder answers *what* is slow (trace spans, series/SLO, HBM
watermarks) but not *why a step costs what it costs* — that needs a real
device trace, captured on every host of the job over the SAME window.
``tony profile <app_id> --steps 3`` drives it end to end:

1. the client calls the new ``StartProfile`` ApplicationRpc on the AM;
2. the AM broadcasts the window by writing ``<app_dir>/profile/request.json``
   (the same shared-app-dir channel status.json and the series rollup use —
   every process of the job can read it, none needs a new RPC surface);
3. each armed process's :class:`ProfileController` picks the request up
   (a daemon watcher polls the file; the check also runs synchronously at
   arm time so a request staged before launch is honoured exactly) and, at
   the next ``maybe_capture()`` step boundary, opens a ``jax.profiler``
   device trace via the ONE capture primitive (obs/profiler.trace_window),
   brackets each captured step with a ``jax.profiler.TraceAnnotation``
   named :data:`STEP_ANNOTATION`, and records host boundary timings +
   per-step input-wait;
4. after N steps (or T seconds) the controller stops the trace, writes
   ``<app_dir>/profile/<proc>/<id>/manifest.json`` next to the artifacts,
   and snapshots the compile ledger so the anatomy report (obs/anatomy.py)
   can pair measured collective time with the AOT executables' extracted
   collective set (obs/comms.py).

:func:`maybe_capture` holds the established disarmed-hook contract
(trace/hbm/health/series twins; graft-lint GL005,
tests/test_perf_guard.py): disarmed it is ONE global load + ``None``
compare; armed outside a window it is two attribute loads + compares. jax
imports lazily at capture start only — arming costs nothing in processes
that never profile.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import asdict, dataclass
from typing import Any

log = logging.getLogger(__name__)

# env contract (AM -> executor -> user process, next to TONY_TRACE_* /
# TONY_OBS_HBM* / TONY_OBS_HEALTH* / TONY_OBS_SERIES*)
ENV_ENABLED = "TONY_OBS_PROFILE"                 # "0" disables arming
ENV_POLL = "TONY_OBS_PROFILE_POLL_S"             # request-file poll cadence
ENV_MAX_STEPS = "TONY_OBS_PROFILE_MAX_STEPS"     # per-window step cap

REQUEST_FILE = "request.json"
MANIFEST_FILE = "manifest.json"
# device-timeline step bracket: the anatomy report aligns device events to
# step windows by these annotation spans (obs/anatomy.py reads the name)
STEP_ANNOTATION = "anatomy.step"

# a request older than this can never arm a capture: a worker relaunched
# hours later must not re-profile a long-forgotten window
DEFAULT_TTL_S = 600.0


@dataclass(frozen=True)
class ProfileRequest:
    """One broadcast capture window (the request.json payload)."""

    id: str
    steps: int = 0            # capture N steps (0 -> duration_s)
    duration_s: float = 0.0   # wall-clock window when steps == 0
    issued_ts: float = 0.0
    deadline_ts: float = 0.0  # watchers ignore the request past this

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ProfileRequest":
        return cls(
            id=str(d.get("id", "")),
            steps=int(d.get("steps", 0) or 0),
            duration_s=float(d.get("duration_s", 0.0) or 0.0),
            issued_ts=float(d.get("issued_ts", 0.0) or 0.0),
            deadline_ts=float(d.get("deadline_ts", 0.0) or 0.0),
        )


def profile_dir(app_dir: str) -> str:
    return os.path.join(app_dir, "profile")


def request_path(app_dir: str) -> str:
    return os.path.join(profile_dir(app_dir), REQUEST_FILE)


def write_request(app_dir: str, *, steps: int = 0, duration_s: float = 0.0,
                  ttl_s: float = DEFAULT_TTL_S) -> ProfileRequest:
    """The AM's broadcast: atomically publish one capture window for every
    process of the job. The id is time-ordered and unique per request, so
    a repeated ``tony profile`` yields distinct artifact dirs."""
    now = time.time()
    req = ProfileRequest(
        id=f"p{int(now)}_{os.urandom(3).hex()}",
        steps=max(int(steps), 0),
        duration_s=max(float(duration_s), 0.0),
        issued_ts=now,
        deadline_ts=now + max(float(duration_s), 0.0) + max(ttl_s, 1.0),
    )
    path = request_path(app_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(req.to_dict(), f)
    os.replace(tmp, path)
    return req


def read_request(path: str) -> ProfileRequest | None:
    try:
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(d, dict) or not d.get("id"):
        return None
    return ProfileRequest.from_dict(d)


class ProfileController:
    """Per-process capture state machine driven from the step loop.

    ``maybe_capture()`` (the module seam) forwards to :meth:`step`:

    - no pending request and no active window: two attribute loads — the
      armed-but-idle cost, held to the same perf budget as the other
      observatory seams;
    - a pending request: the window OPENS at this boundary (device trace
      starts, the step annotation enters);
    - an active window: one boundary — host step time + input wait
      recorded, annotation re-entered; the window CLOSES here once the
      requested steps (or seconds, or the request deadline) are spent.

    The controller never raises into the step loop: a failing profiler
    (already tracing, unwritable disk) marks the request consumed and logs.
    """

    def __init__(self, out_root: str, proc: str, *,
                 request_path: str = "", poll_interval_s: float = 0.5,
                 max_steps: int = 64, watch: bool = True):
        self.out_root = out_root
        self.proc = proc
        self.max_steps = max(int(max_steps), 1)
        self._request_path = request_path
        self._poll_interval_s = max(float(poll_interval_s), 0.05)
        self._req: ProfileRequest | None = None   # active window
        self._pending: ProfileRequest | None = None
        self._last_id = ""
        self._last_mtime = 0.0
        self._window = None       # trace_window context manager
        self._handle = None       # CaptureHandle
        self._ann = None          # entered TraceAnnotation
        self._out_dir = ""
        self._t0_wall = 0.0
        self._t0 = 0.0
        self._boundaries: list[float] = []
        self._waits: list[float] = []
        self._stop_evt = threading.Event()
        self._thread = None
        if request_path and watch:
            # synchronous first check: a request staged before this process
            # armed (the e2e path — tony profile issued while workers boot)
            # is picked up deterministically at the first step boundary
            self.check_request()
            self._thread = threading.Thread(
                target=self._watch_loop, daemon=True, name="tony-profile-watch"
            )
            self._thread.start()

    # --- request watching -----------------------------------------------------

    def _watch_loop(self) -> None:
        while not self._stop_evt.wait(self._poll_interval_s):
            try:
                self.check_request()
            except Exception:
                log.debug("profile request check failed", exc_info=True)

    def check_request(self) -> None:
        """Stat + parse the broadcast file; arm ``_pending`` on a new,
        unexpired request id. Runs on the watcher thread (and once at
        construction); the step loop only ever reads ``_pending``."""
        try:
            mtime = os.stat(self._request_path).st_mtime
        except OSError:
            return
        if mtime == self._last_mtime:
            return
        self._last_mtime = mtime
        req = read_request(self._request_path)
        if req is None or req.id == self._last_id:
            return
        if req.deadline_ts and time.time() > req.deadline_ts:
            self._last_id = req.id  # expired: consumed, never armed
            return
        self._last_id = req.id
        self._pending = req
        log.info("profile request %s armed (steps=%d duration_s=%.1f)",
                 req.id, req.steps, req.duration_s)

    def trigger(self, steps: int = 0, duration_s: float = 0.0,
                ttl_s: float = DEFAULT_TTL_S) -> ProfileRequest:
        """Arm a window directly (tests, bench) — the in-process twin of
        the AM broadcast."""
        now = time.time()
        req = ProfileRequest(
            id=f"p{int(now)}_{os.urandom(3).hex()}", steps=int(steps),
            duration_s=float(duration_s), issued_ts=now,
            deadline_ts=now + max(float(duration_s), 0.0) + max(ttl_s, 1.0),
        )
        self._last_id = req.id
        self._pending = req
        return req

    # --- step-loop side -------------------------------------------------------

    def step(self, fetch_s: float = 0.0, **args: Any) -> None:
        req = self._req
        if req is None:
            pending = self._pending
            if pending is None:
                return
            self._pending = None
            self._start(pending)
            return
        self._boundary(fetch_s)

    def _start(self, req: ProfileRequest) -> None:
        if req.deadline_ts and time.time() > req.deadline_ts:
            return
        steps = min(req.steps, self.max_steps) if req.steps else 0
        if req.steps and steps < req.steps:
            log.warning("profile %s: steps clamped %d -> %d "
                        "(obs.profile.max_steps)", req.id, req.steps, steps)
            req = ProfileRequest(
                id=req.id, steps=steps, duration_s=req.duration_s,
                issued_ts=req.issued_ts, deadline_ts=req.deadline_ts,
            )
        try:
            from tony_tpu.obs.profiler import annotate, trace_window

            self._out_dir = os.path.join(self.out_root, self.proc, req.id)
            os.makedirs(self._out_dir, exist_ok=True)
            self._window = trace_window(self._out_dir)
            self._handle = self._window.__enter__()
            self._ann = annotate(STEP_ANNOTATION)
            self._ann.__enter__()
        except Exception:
            # a wedged profiler (already tracing, read-only dir) must never
            # cost a step; the request is consumed so it cannot retry-loop
            log.warning("profile %s: capture failed to start", req.id,
                        exc_info=True)
            self._abort_window()
            return
        self._req = req
        self._t0_wall = time.time()
        self._t0 = time.perf_counter()
        self._boundaries = [self._t0]
        self._waits = []
        from tony_tpu.obs import trace

        trace.instant("profile.capture_start", id=req.id, steps=req.steps)
        log.info("profile %s: capturing into %s", req.id, self._out_dir)

    def _boundary(self, fetch_s: float) -> None:
        req = self._req
        now = time.perf_counter()
        self._boundaries.append(now)
        self._waits.append(round(float(fetch_s), 6))
        done = False
        captured = len(self._boundaries) - 1
        if req.steps and captured >= req.steps:
            done = True
        elif captured >= self.max_steps:
            # duration-based windows honour the step cap too: a fast step
            # loop under `--seconds T` must not record an unbounded trace
            done = True
        elif req.duration_s and now - self._t0 >= req.duration_s:
            done = True
        elif req.deadline_ts and time.time() > req.deadline_ts:
            done = True
        if done:
            self._stop()
            return
        try:
            # re-enter the bracket so each captured step is one annotation
            # span on the device timeline (the anatomy report's alignment)
            self._ann.__exit__(None, None, None)
            self._ann.__enter__()
        except Exception:
            self._ann = None

    def finish(self) -> None:
        """Close an open window (loop teardown, Engine.close): a capture
        interrupted mid-window still lands its manifest + partial trace."""
        if self._req is not None:
            self._stop()

    def _stop(self) -> None:
        req = self._req
        self._req = None
        try:
            if self._ann is not None:
                self._ann.__exit__(None, None, None)
        except Exception:
            pass
        self._ann = None
        artifact = ""
        try:
            if self._window is not None:
                self._window.__exit__(None, None, None)
                if self._handle is not None and self._handle.ok:
                    artifact = self._handle.path
        except Exception:
            log.warning("profile %s: capture failed to finalise", req.id,
                        exc_info=True)
        self._window = None
        self._handle = None
        steps = max(len(self._boundaries) - 1, 0)
        manifest = {
            "profile_id": req.id,
            "proc": self.proc,
            "steps": steps,
            "steps_requested": req.steps,
            "duration_s": req.duration_s,
            "t0_ts": round(self._t0_wall, 6),
            "ts": round(time.time(), 6),
            "step_time_s": [
                round(b - a, 6)
                for a, b in zip(self._boundaries, self._boundaries[1:])
            ],
            "input_wait_s": list(self._waits),
            "artifact": artifact,
            "out_dir": self._out_dir,
        }
        path = os.path.join(self._out_dir, MANIFEST_FILE)
        try:
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(manifest, f)
            os.replace(tmp, path)
        except OSError:
            log.warning("profile %s: manifest write failed", req.id,
                        exc_info=True)
        # snapshot the compile ledger NOW (not at fit/engine shutdown): the
        # report pairs measured collective time with the AOT executables'
        # extracted collective rows, and `tony profile` runs mid-job
        try:
            from tony_tpu.obs import compiles as compile_ledger

            compile_ledger.snapshot_to_app_dir(self.proc)
        except Exception:
            log.debug("profile ledger snapshot failed", exc_info=True)
        from tony_tpu.obs import trace

        trace.instant("profile.capture_end", id=req.id, steps=steps)
        log.info("profile %s: captured %d step(s) -> %s",
                 req.id, steps, artifact or self._out_dir)

    def _abort_window(self) -> None:
        try:
            if self._window is not None:
                self._window.__exit__(None, None, None)
        except Exception:
            pass
        self._window = None
        self._handle = None
        self._ann = None

    def close(self) -> None:
        self._stop_evt.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=2.0)
        self.finish()


# --- process-global arming (the trace/hbm/health/series pattern) --------------

_controller: ProfileController | None = None


def active_controller() -> ProfileController | None:
    return _controller


def install(controller: ProfileController) -> ProfileController:
    global _controller
    if _controller is not None and _controller is not controller:
        _controller.close()
    _controller = controller
    return controller


def uninstall() -> None:
    global _controller
    if _controller is not None:
        _controller.close()
        _controller = None


def maybe_capture(**args: Any) -> None:
    """The hot-path seam (train/serve step loops). Disarmed: one global
    load + ``None`` compare; armed outside a window: two attribute
    compares. Call sites must pass precomputed names only (graft-lint
    GL005 enforces this like the trace/chaos/hbm/health/series hooks)."""
    c = _controller
    if c is not None:
        c.step(**args)


def finish_capture() -> None:
    """Close an open window at loop teardown (fit finally, Engine.close)."""
    c = _controller
    if c is not None:
        c.finish()


def install_from_env(proc: str = "") -> ProfileController | None:
    """Arm this process from the ``TONY_OBS_PROFILE*`` env the AM exported.
    Needs a job app dir (the broadcast file and artifact root live there);
    idempotent; ``TONY_OBS_PROFILE=0`` disables."""
    if _controller is not None:
        return _controller
    if os.environ.get(ENV_ENABLED, "") == "0":
        return None
    app_dir = os.environ.get("TONY_APP_DIR", "")
    if not app_dir:
        return None

    def _env_float(key: str, default: float) -> float:
        try:
            return float(os.environ.get(key, "") or default)
        except ValueError:
            return default

    from tony_tpu.obs import trace

    proc = trace.sanitize_proc(proc) if proc else trace.default_proc_name()
    return install(ProfileController(
        profile_dir(app_dir), proc,
        request_path=request_path(app_dir),
        poll_interval_s=_env_float(ENV_POLL, 0.5),
        max_steps=int(_env_float(ENV_MAX_STEPS, 64)),
    ))


# --- read paths (tony profile report, anatomy, tests) -------------------------


def read_manifests(app_dir: str,
                   profile_id: str = "") -> dict[str, dict]:
    """Every per-process capture manifest under ``<app_dir>/profile/``
    (proc -> manifest), optionally filtered to one profile id. When no id
    is given, the NEWEST id any process captured wins — the common read
    is "the capture I just asked for"."""
    root = profile_dir(app_dir)
    found: list[dict] = []
    try:
        procs = sorted(os.listdir(root))
    except OSError:
        return {}
    for proc in procs:
        pdir = os.path.join(root, proc)
        if not os.path.isdir(pdir):
            continue
        for cap_id in sorted(os.listdir(pdir)):
            path = os.path.join(pdir, cap_id, MANIFEST_FILE)
            try:
                with open(path, encoding="utf-8") as f:
                    m = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(m, dict) and m.get("profile_id"):
                found.append(m)
    if not found:
        return {}
    if not profile_id:
        profile_id = max(found, key=lambda m: m.get("ts", 0.0))["profile_id"]
    return {
        m["proc"]: m for m in found if m["profile_id"] == profile_id
    }


def list_captures(app_dir: str) -> list[str]:
    """Distinct capture ids with at least one landed manifest (newest
    last) — the `tony trace` summary's pointer at available anatomies."""
    root = profile_dir(app_dir)
    ids: dict[str, float] = {}
    try:
        procs = sorted(os.listdir(root))
    except OSError:
        return []
    for proc in procs:
        pdir = os.path.join(root, proc)
        if not os.path.isdir(pdir):
            continue
        for cap_id in sorted(os.listdir(pdir)):
            path = os.path.join(pdir, cap_id, MANIFEST_FILE)
            try:
                ts = os.stat(path).st_mtime
            except OSError:
                continue
            ids[cap_id] = max(ids.get(cap_id, 0.0), ts)
    return [i for i, _ in sorted(ids.items(), key=lambda kv: kv[1])]


__all__ = [
    "ENV_ENABLED", "ENV_MAX_STEPS", "ENV_POLL", "MANIFEST_FILE",
    "ProfileController", "ProfileRequest", "REQUEST_FILE", "STEP_ANNOTATION",
    "active_controller", "finish_capture", "install", "install_from_env",
    "list_captures", "maybe_capture", "profile_dir", "read_manifests",
    "read_request", "request_path", "uninstall", "write_request",
]

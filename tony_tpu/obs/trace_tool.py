"""Trace post-processing: merge per-process journals, goodput, stragglers.

``tony trace <app_id>`` drives this module: every ``trace/*.jsonl`` journal
the processes of one application wrote (obs/trace.py) is merged into a
single Chrome-trace-event JSON loadable in Perfetto / chrome://tracing,
with each tony process as one Chrome "process" row. On top of the merged
timeline it computes:

- a **goodput roll-up**: productive step time vs compile / restore /
  input-blocked / restart over the job's span window — the "where did the
  wall clock go" answer a chaos post-mortem starts from;
- **straggler flagging** from heartbeat-reported step progress (the METRICS
  events each task pushes through the AM): a task whose latest reported
  step lags the fleet max by more than the threshold is flagged with its
  lag and step rate.

Sampled spans scale honestly: train/serve step spans carry their sampling
stride as the ``every`` arg, and the roll-up multiplies duration by it —
1-in-16 sampling yields an estimate, not a 16x undercount.
"""

from __future__ import annotations

import json
import os
from typing import Any

from tony_tpu.am.events import EventType, read_history


def load_journals(trace_dir: str) -> list[dict[str, Any]]:
    """Read every per-process journal: returns one entry per process,
    ``{"proc", "pid", "trace", "dropped", "spans": [...], "instants": [...],
    "counters": [...]}``.
    Torn trailing lines (a SIGKILLed writer) are skipped, not fatal; a
    rotated window (``<proc>.0.jsonl``, written when the journal hits its
    size cap) merges into the same process entry."""
    procs: list[dict[str, Any]] = []
    by_proc: dict[str, dict[str, Any]] = {}
    if not os.path.isdir(trace_dir):
        return procs
    for name in sorted(os.listdir(trace_dir)):
        if not name.endswith(".jsonl"):
            continue
        entry: dict[str, Any] = {
            "proc": name[:-len(".jsonl")], "pid": 0, "trace": "",
            "dropped": 0, "spans": [], "instants": [], "opens": [],
            "counters": [],
        }
        try:
            with open(os.path.join(trace_dir, name), encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail from a killed process
                    ph = rec.get("ph")
                    if ph == "M":
                        entry["proc"] = rec.get("proc", entry["proc"])
                        entry["pid"] = rec.get("pid", entry["pid"])
                        entry["trace"] = rec.get("trace", entry["trace"])
                        entry["dropped"] += int(rec.get("dropped", 0))
                    elif ph == "X":
                        entry["spans"].append(rec)
                    elif ph == "i":
                        entry["instants"].append(rec)
                    elif ph == "B":
                        # begin-only: a span open when a chaos SIGKILL hit
                        # (emergency_flush journals these pre-kill)
                        entry["opens"].append(rec)
                    elif ph == "C":
                        # counter-track sample (per-device HBM, obs/hbm.py)
                        entry["counters"].append(rec)
        except OSError:
            continue
        prev = by_proc.get(entry["proc"])
        if prev is None:
            by_proc[entry["proc"]] = entry
            procs.append(entry)
        else:
            prev["spans"].extend(entry["spans"])
            prev["instants"].extend(entry["instants"])
            prev["opens"].extend(entry["opens"])
            prev["counters"].extend(entry["counters"])
            prev["dropped"] += entry["dropped"]
            prev["pid"] = prev["pid"] or entry["pid"]
            prev["trace"] = prev["trace"] or entry["trace"]
    # a span can journal as begin-only more than once (emergency_flush at a
    # survived fault, then close()) or later complete normally — keep one B
    # per sid and drop it entirely when the finished X record exists
    for entry in procs:
        ended = {s.get("sid") for s in entry["spans"]}
        seen: set = set()
        uniq = []
        for o in entry["opens"]:
            sid = o.get("sid")
            if sid in ended or sid in seen:
                continue
            seen.add(sid)
            uniq.append(o)
        entry["opens"] = uniq
    return procs


def merge_chrome(app_dir: str,
                 procs: list[dict[str, Any]] | None = None) -> dict[str, Any]:
    """One Chrome-trace JSON over every process journal of the app."""
    if procs is None:
        procs = load_journals(os.path.join(app_dir, "trace"))
    events: list[dict[str, Any]] = []
    for i, p in enumerate(procs, start=1):
        events.append({
            "ph": "M", "name": "process_name", "pid": i, "tid": 0,
            "args": {"name": p["proc"], "os_pid": p["pid"],
                     "dropped_events": p["dropped"]},
        })
        for s in p["spans"]:
            events.append({
                "ph": "X", "name": s.get("name", "?"), "cat": "tony",
                "ts": s.get("ts", 0), "dur": s.get("dur", 0),
                "pid": i, "tid": s.get("tid", 0),
                "args": {**s.get("args", {}), "sid": s.get("sid", ""),
                         "psid": s.get("psid", "")},
            })
        for inst in p["instants"]:
            events.append({
                "ph": "i", "name": inst.get("name", "?"), "cat": "tony",
                "ts": inst.get("ts", 0), "pid": i, "tid": inst.get("tid", 0),
                "s": "p", "args": inst.get("args", {}),
            })
        for o in p["opens"]:
            # span open at a SIGKILL: a begin-only Chrome event (Perfetto
            # renders it as running until the end of the trace)
            events.append({
                "ph": "B", "name": o.get("name", "?"), "cat": "tony",
                "ts": o.get("ts", 0), "pid": i, "tid": o.get("tid", 0),
                "args": {**o.get("args", {}), "killed": True,
                         "sid": o.get("sid", ""), "psid": o.get("psid", "")},
            })
        for c in p["counters"]:
            # counter track (per-device HBM live/peak): each numeric arg
            # renders as one series on the process's memory timeline
            events.append({
                "ph": "C", "name": c.get("name", "?"), "cat": "tony",
                "ts": c.get("ts", 0), "pid": i, "tid": 0,
                "args": c.get("args", {}),
            })
    events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def goodput(app_dir: str,
            procs: list[dict[str, Any]] | None = None) -> dict[str, Any]:
    """Wall-clock attribution over the merged timeline (seconds).

    - ``productive_s``: train.step span time x sampling stride, plus serve
      prefill/decode-step time;
    - ``compile_s`` / ``restore_s`` / ``first_batch_s``: fit() startup
      phases (overlapped with each other — they can sum past wall time);
    - ``input_blocked_s``: per-step input fetch time carried on sampled
      step spans, scaled by the stride;
    - ``restart_s``: gaps between one task's consecutive user-process
      spans (the relaunch dead time a gang restart costs) PLUS
      ``elastic.reshard`` span time (the warm-restart cost of an elastic
      generation change — fence, donate, re-lower; docs/ELASTIC.md), so
      restart cost is read straight off the merged trace instead of
      inferred from ``unattributed_s``. ``generation_changes`` counts the
      elastic boundaries separately from cold ``restarts``;
    - ``window_s``: first span start to last span end across processes;
    - ``unattributed_s``: the window time NO bucket claims, reported
      explicitly instead of silently folding into the denominator — the
      reconciliation seam between this roll-up and the step-anatomy
      budget (obs/anatomy.py attributes *inside* the step; whatever
      neither tool claims is visible here, never hidden). A lower bound:
      startup phases overlap each other by design, so their sum can
      exceed their wall share.
    """
    if procs is None:
        procs = load_journals(os.path.join(app_dir, "trace"))
    spans = [s for p in procs for s in p["spans"]]
    opens = [o for p in procs for o in p["opens"]]
    out = {
        "window_s": 0.0, "productive_s": 0.0, "compile_s": 0.0,
        "restore_s": 0.0, "first_batch_s": 0.0, "input_blocked_s": 0.0,
        "restart_s": 0.0, "restarts": 0, "generation_changes": 0,
        "sampled_steps": 0,
    }
    if not spans and not opens:
        return out
    # begin-only records (SIGKILLed processes) count toward the window —
    # the tail up to the kill is exactly what a chaos post-mortem measures
    t_min = min(s["ts"] for s in spans + opens)
    t_max = max(s.get("fts", s["ts"] + s.get("dur", 0)) for s in spans + opens)
    out["window_s"] = round((t_max - t_min) / 1e6, 3)
    user_spans: dict[str, list[dict]] = {}
    for s in spans:
        name = s.get("name", "")
        args = s.get("args", {})
        dur_s = s.get("dur", 0) / 1e6
        if name in ("train.step", "serve.step"):
            every = max(int(args.get("every", 1) or 1), 1)
            out["productive_s"] += dur_s * every
            out["input_blocked_s"] += float(args.get("fetch_ms", 0.0)) / 1e3 * every
            out["sampled_steps"] += 1
        elif name == "serve.prefill":
            out["productive_s"] += dur_s
        elif name == "fit.startup.compile":
            out["compile_s"] += dur_s
        elif name == "fit.startup.restore":
            out["restore_s"] += dur_s
        elif name == "fit.startup.first_batch":
            out["first_batch_s"] += dur_s
        elif name == "executor.user_process":
            user_spans.setdefault(str(args.get("task", "?")), []).append(s)
        elif name == "am.gang_restart":
            out["restarts"] += 1
        elif name == "elastic.reshard":
            # warm restart: the generation boundary's fence+donate+relower
            # window, journaled by the trainer (train/loop.py _Elastic)
            out["restart_s"] += dur_s
            out["generation_changes"] += 1
    # a SIGKILLed attempt's user_process span is begin-only (``ph: "B"``,
    # emergency-flushed): its ``fts`` flush timestamp is the kill-time
    # proxy, without which restart_s misses exactly the kill_container
    # restarts the flight recorder exists to measure
    for p in procs:
        for o in p["opens"]:
            if o.get("name") == "executor.user_process" and o.get("fts"):
                user_spans.setdefault(
                    str(o.get("args", {}).get("task", "?")), []
                ).append({
                    "ts": o["ts"], "dur": max(o["fts"] - o["ts"], 0),
                    "args": o.get("args", {}),
                })
    # relaunch dead time: the hole between attempt N's user process ending
    # and attempt N+1's starting, per task
    for task_spans in user_spans.values():
        task_spans.sort(key=lambda s: s["ts"])
        for a, b in zip(task_spans, task_spans[1:]):
            gap = (b["ts"] - (a["ts"] + a.get("dur", 0))) / 1e6
            if gap > 0:
                out["restart_s"] += gap
    for k in ("productive_s", "compile_s", "restore_s", "first_batch_s",
              "input_blocked_s", "restart_s"):
        out[k] = round(out[k], 3)
    # explicit residual: window time no bucket above claims (clamped at 0
    # because the buckets can overlap — see the docstring). Goodput and
    # the anatomy budget reconcile through this number instead of both
    # quietly normalising by the window.
    attributed = sum(
        out[k] for k in ("productive_s", "compile_s", "restore_s",
                         "first_batch_s", "input_blocked_s", "restart_s")
    )
    out["unattributed_s"] = round(max(out["window_s"] - attributed, 0.0), 3)
    return out


def stragglers(app_dir: str, lag_frac: float = 0.2) -> list[dict[str, Any]]:
    """Cross-host straggler flags from heartbeat-reported step progress.

    Each task's latest ``step`` METRICS sample (pushed through the AM and
    journaled to .jhist) is compared against the fleet max; tasks lagging
    by more than ``lag_frac`` of the max are flagged with their lag and
    observed step rate. Empty when fewer than two tasks report steps."""
    events = _all_events(app_dir)
    progress: dict[str, list[tuple[float, float]]] = {}
    for e in events:
        if e.get("type") != EventType.METRICS:
            continue
        samples = e.get("samples", {})
        if not isinstance(samples, dict) or "step" not in samples:
            continue
        progress.setdefault(str(e.get("task", "?")), []).append(
            (float(e.get("ts", 0.0)), float(samples["step"]))
        )
    if len(progress) < 2:
        return []
    latest = {t: max(p, key=lambda x: x[0]) for t, p in progress.items()}
    max_step = max(s for _, s in latest.values())
    if max_step <= 0:
        return []
    flagged = []
    for task, (ts, step) in sorted(latest.items()):
        lag = max_step - step
        if lag / max_step <= lag_frac:
            continue
        pts = sorted(progress[task])
        rate = 0.0
        if len(pts) >= 2 and pts[-1][0] > pts[0][0]:
            rate = (pts[-1][1] - pts[0][1]) / (pts[-1][0] - pts[0][0])
        flagged.append({
            "task": task, "step": step, "behind_steps": lag,
            "behind_frac": round(lag / max_step, 3),
            "steps_per_s": round(rate, 3),
        })
    return flagged


def _all_events(app_dir: str) -> list[dict]:
    ev_dir = os.path.join(app_dir, "events")
    events: list[dict] = []
    if os.path.isdir(ev_dir):
        for name in sorted(os.listdir(ev_dir)):
            if name.endswith(".jsonl"):
                try:
                    events.extend(read_history(os.path.join(ev_dir, name)))
                except (OSError, json.JSONDecodeError):
                    pass
    return events


def report(app_dir: str,
           procs: list[dict[str, Any]] | None = None) -> dict[str, Any]:
    """Everything ``tony trace`` prints beside the merged file. Pass
    ``procs`` (from :func:`load_journals`) to avoid re-reading the
    journals the caller already parsed."""
    if procs is None:
        procs = load_journals(os.path.join(app_dir, "trace"))
    out = {
        "processes": [
            {"proc": p["proc"], "spans": len(p["spans"]),
             "instants": len(p["instants"]), "open_at_kill": len(p["opens"]),
             "counters": len(p["counters"]), "dropped": p["dropped"]}
            for p in procs
        ],
        "goodput": goodput(app_dir, procs),
        "stragglers": stragglers(app_dir),
    }
    # pointer at available step-anatomy captures (obs/profile.py): the
    # op-level drill-down of whatever this roll-up flags as slow
    from tony_tpu.obs.profile import list_captures

    captures = list_captures(app_dir)
    if captures:
        out["profile_captures"] = captures
    return out


__all__ = ["goodput", "load_journals", "merge_chrome", "report", "stragglers"]

"""Distributed trace spine: the cluster-wide flight recorder.

The repo's observability was a pile of uncorrelated per-component counters
(StepTimer, DecodeMetrics, TaskMonitor samples, ``.jhist`` events) that
cannot be lined up on one timeline — a chaos post-mortem or a TTFT
regression could not answer *where the time went across processes*. This
module is the Dapper-shaped (Sigelman et al., 2010) answer: a near-zero-
overhead process-local span recorder whose trace context propagates
AM→executor via env and through RPC metadata, journaling spans per process
as ``trace/*.jsonl`` under the app dir. ``tony trace <app_id>`` merges the
journals into one Chrome-trace JSON (obs/trace_tool.py).

The contract mirrors the chaos hooks (chaos/faults.py):

- ``span(name)`` / ``instant(name)`` are the ONLY hot-path surfaces. When
  no tracer is armed (the default) they are a single global-load + ``None``
  compare returning a shared no-op — safe to compile into train/serve
  steps (tests/test_perf_guard.py holds this to a few hundred ns).
- A tracer is armed explicitly per process (``install_from_config`` in the
  AM entrypoint, ``install_from_env`` in executors and fit()), never as an
  import side effect.
- Timestamps are wall-anchored monotonic: ``t0_wall + (mono - t0_mono)``,
  so spans are strictly ordered within a process and line up across
  processes to wall-clock accuracy (same-host chaos runs: exact).
- Completed spans land in a bounded ring drained by a daemon thread; a
  wedged disk can cost trace events (counted in ``dropped``), never stall
  the instrumented path. The journal rotates at ``trace.max_journal_mb``
  (newest window kept, oldest dropped — flight-recorder retention) so an
  always-on long job cannot fill a disk.

This module is stdlib-only on purpose: executors for non-JAX frameworks
arm it, so it must not pay (or fail on) a jax import. The device-timeline
bridge (``jax.profiler.TraceAnnotation`` with the same span names) lives
at the call sites that already import jax (train/loop.py).
"""

from __future__ import annotations

import atexit
import collections
import hashlib
import json
import logging
import os
import threading
import time
from typing import Any

log = logging.getLogger(__name__)

# env contract (AM -> executor -> user process; see am/app_master.py and
# executor/task_executor.py)
ENV_DIR = "TONY_TRACE_DIR"          # journal directory (arms the process)
ENV_TRACE_ID = "TONY_TRACE_ID"      # shared per-application trace id
ENV_PROC = "TONY_TRACE_PROC"        # this process's journal/display name
ENV_PARENT = "TONY_TRACE_PARENT"    # span id to root this process under
ENV_SAMPLE = "TONY_TRACE_SAMPLE"    # step-sampling stride (train/serve)
ENV_RING = "TONY_TRACE_RING"        # in-memory span ring size
ENV_JOURNAL_MB = "TONY_TRACE_JOURNAL_MB"  # journal rotation size

# gRPC metadata key carrying "<trace_id>/<span_id>" (rpc/service.py)
RPC_METADATA_KEY = "tony-trace-ctx"


class _NoopSpan:
    """The disarmed span: shared, reentrant, attribute-free."""

    __slots__ = ()
    sid = ""

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **args: Any) -> "_NoopSpan":
        return self

    def end(self, **args: Any) -> None:
        pass


NOOP_SPAN = _NoopSpan()


def _new_id() -> str:
    return os.urandom(8).hex()


class Span:
    """One recorded operation. Use as a context manager (nesting tracked
    per thread) or hold the handle and call :meth:`end` explicitly
    (cross-step spans like a serve request's decode lifetime)."""

    __slots__ = ("_tracer", "name", "sid", "psid", "args", "_t0", "_ended", "_entered")

    def __init__(self, tracer: "Tracer", name: str, sid: str, psid: str,
                 args: dict[str, Any], t0: float):
        self._tracer = tracer
        self.name = name
        self.sid = sid
        self.psid = psid
        self.args = args
        self._t0 = t0
        self._ended = False
        self._entered = False

    def set(self, **args: Any) -> "Span":
        self.args.update(args)
        return self

    def __enter__(self) -> "Span":
        self._entered = True
        self._tracer._push_ctx(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._tracer._pop_ctx(self)
        if exc_type is not None:
            self.args.setdefault("error", exc_type.__name__)
        self.end()
        return False

    def end(self, **args: Any) -> None:
        if self._ended:
            return
        self._ended = True
        if args:
            self.args.update(args)
        self._tracer._finish(self)


class Tracer:
    """Process-local recorder journaling to one ``<proc>.jsonl`` file."""

    def __init__(self, path: str, proc: str, trace_id: str, *,
                 sample_steps: int = 16, ring: int = 4096,
                 default_parent: str = "", max_journal_mb: int = 64,
                 flush_interval_s: float = 0.25):
        self.proc = proc
        self.trace_id = trace_id
        self.sample_steps = max(int(sample_steps), 1)
        self.ring_size = max(int(ring), 16)
        self.max_journal_mb = int(max_journal_mb)
        self.default_parent = default_parent
        self.path = path
        self.dropped = 0          # ring overflow (writer slower than spans)
        self._t0_wall = time.time()
        self._t0_mono = time.perf_counter()
        self._ring: collections.deque = collections.deque(maxlen=max(ring, 16))
        self._open: dict[str, Span] = {}  # live spans, for emergency_flush
        self._sample_counts: dict[str, int] = {}  # sampled_span strides
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._tls = threading.local()
        self._max_bytes = max_journal_mb * 2**20
        self._written = 0
        self._closed = False
        self._flush_interval_s = flush_interval_s
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        # append-mode reopen (re-arm cycles, relaunch reusing a proc name):
        # count what's already there or the 2x-cap disk bound breaks
        self._written = self._f.tell()
        self._write_line({
            "ph": "M", "proc": proc, "pid": os.getpid(), "trace": trace_id,
            "t0_us": int(self._t0_wall * 1e6),
        })
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._drain_loop, daemon=True, name="tony-trace-flush"
        )
        self._thread.start()

    # --- recording ------------------------------------------------------------

    def _now_us(self) -> int:
        return int((self._t0_wall + (time.perf_counter() - self._t0_mono)) * 1e6)

    def _ts_us(self, mono: float) -> int:
        return int((self._t0_wall + (mono - self._t0_mono)) * 1e6)

    def span(self, name: str, *, parent: str | None = None, **args: Any) -> Span:
        """Start a span NOW. Parent resolution: explicit ``parent`` >
        current thread's innermost entered span > the process default
        (``TONY_TRACE_PARENT``, i.e. the launcher's span)."""
        if parent is None:
            cur = self._current()
            parent = cur.sid if cur is not None else self.default_parent
        sp = Span(self, name, _new_id(), parent, dict(args), time.perf_counter())
        with self._lock:
            self._open[sp.sid] = sp
        return sp

    def sampled_span(self, name: str, *, parent: str | None = None,
                     **args: Any) -> "Span | _NoopSpan":
        """Every ``sample_steps``-th call per name starts a span carrying
        ``every=sample_steps``; the rest return the shared no-op. ONE owner
        for the stride counter and the ``every`` arg — the goodput roll-up
        multiplies span duration by ``every`` (obs/trace_tool.py), so a
        call site that re-implemented sampling and forgot the arg would
        silently undercount productive time by the stride. Callers can
        test ``is NOOP_SPAN`` to gate sampled-only work (e.g. the train
        loop's device sync)."""
        with self._lock:
            n = self._sample_counts[name] = self._sample_counts.get(name, 0) + 1
        if n % self.sample_steps:
            return NOOP_SPAN
        return self.span(name, parent=parent, every=self.sample_steps, **args)

    def instant(self, name: str, **args: Any) -> None:
        """A zero-duration marker (chaos fault injections, aborts)."""
        self._enqueue({
            "ph": "i", "name": name, "ts": self._now_us(),
            "tid": threading.get_native_id(), "args": args,
        })

    def counter(self, name: str, **values: float) -> None:
        """A counter-track sample (Chrome ``ph: "C"``): each numeric kwarg
        becomes one series on the ``name`` track in the merged timeline
        (the HBM observatory emits per-device live/peak memory this way,
        obs/hbm.py)."""
        self._enqueue({
            "ph": "C", "name": name, "ts": self._now_us(), "args": values,
        })

    def _finish(self, span: Span) -> None:
        end = time.perf_counter()
        self._enqueue({
            "ph": "X", "name": span.name, "ts": self._ts_us(span._t0),
            "dur": max(int((end - span._t0) * 1e6), 0),
            "tid": threading.get_native_id(), "sid": span.sid,
            "psid": span.psid, "args": span.args,
        })

    def _enqueue(self, rec: dict) -> None:
        with self._lock:
            if rec.get("ph") == "X":
                self._open.pop(rec["sid"], None)
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(rec)

    # --- thread-local nesting -------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _current(self) -> Span | None:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def _push_ctx(self, span: Span) -> None:
        self._stack().append(span)

    def _pop_ctx(self, span: Span) -> None:
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()

    def ctx(self) -> str:
        """Propagation context for the current thread: ``trace_id/span_id``."""
        cur = self._current()
        return f"{self.trace_id}/{cur.sid if cur is not None else self.default_parent}"

    # --- journaling -----------------------------------------------------------

    def _write_line(self, rec: dict) -> None:
        line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
        if self._written + len(line) > self._max_bytes:
            self._rotate()
        self._written += len(line)
        self._f.write(line)

    def _rotate(self) -> None:
        """Flight-recorder retention at the size cap: the current journal
        becomes ``<proc>.0.jsonl`` (replacing the previous rotated window)
        and a fresh file starts, so the NEWEST events survive — a post-
        mortem needs the crash window, not day one. Disk stays bounded at
        ~2x ``trace.max_journal_mb``; load_journals merges both windows."""
        try:
            self._f.close()
        except Exception:
            pass
        base, ext = os.path.splitext(self.path)
        os.replace(self.path, base + ".0" + ext)
        self._f = open(self.path, "w", encoding="utf-8")
        self._written = 0
        self._write_line({
            "ph": "M", "proc": self.proc, "pid": os.getpid(),
            "trace": self.trace_id, "rotated": True,
        })

    def flush(self) -> None:
        """Drain the ring to disk. Called by the flusher thread, at close,
        and by the chaos injector right before a SIGKILL fault so the
        fatal instant event outlives the process. A write error (ENOSPC,
        torn-down fs) costs the popped batch — counted in ``dropped``,
        never raised into the instrumented path."""
        with self._lock:
            if not self._ring:
                return
            recs = list(self._ring)
            self._ring.clear()
        # the io lock EXISTS to serialize journal writes (flusher thread vs
        # close vs pre-SIGKILL emergency flush); span producers never take
        # it — the ring decouples them — so holding it across file I/O is
        # the design, not a stall hazard
        with self._io_lock:
            if self._closed:
                # lost the race with close(): the popped batch can no longer
                # land, but the loss-accounting contract still holds
                with self._lock:
                    self.dropped += len(recs)
                return
            try:
                for r in recs:
                    self._write_line(r)  # graft-lint: disable=GL004
                self._f.flush()  # graft-lint: disable=GL004
            except OSError:
                with self._lock:
                    self.dropped += len(recs)  # upper bound: some may have landed

    def _open_records(self) -> list[dict]:
        """Snapshot the spans still OPEN as ``ph: "B"`` (begin-only)
        records. ``fts`` is the flush wall time — for a span that never
        ends (SIGKILL, unwound exception) it is the best available end
        proxy, which goodput uses to price relaunch dead time."""
        with self._lock:
            opens = list(self._open.values())
        fts = self._now_us()
        return [{
            "ph": "B", "name": sp.name, "ts": self._ts_us(sp._t0),
            "fts": fts, "sid": sp.sid, "psid": sp.psid, "args": sp.args,
        } for sp in opens]

    def emergency_flush(self) -> None:
        """flush() plus the spans still OPEN, journaled as begin-only
        records. Called by the chaos injector right before a SIGKILL: the
        spans the fault interrupts are exactly the ones the post-mortem
        needs, and they would otherwise die un-ended with the process
        (merge_chrome turns them into Chrome "B" events, which Perfetto
        renders as running until trace end)."""
        self.flush()
        recs = self._open_records()
        with self._io_lock:  # serializes journal I/O by design (see flush)
            if self._closed:
                return
            try:
                for r in recs:
                    self._write_line(r)  # graft-lint: disable=GL004
                self._f.flush()  # graft-lint: disable=GL004
            except OSError:
                with self._lock:
                    self.dropped += len(recs)

    def _drain_loop(self) -> None:
        # the thread outlives write errors: flush() swallows OSError (batch
        # counted as dropped) and a recovered disk resumes journaling
        while not self._stop.wait(self._flush_interval_s):
            try:
                self.flush()
            except Exception:
                pass

    def close(self, join_timeout_s: float = 2.0) -> None:
        """Stop the flusher, JOIN it (bounded), flush the residual ring,
        then journal still-open spans and close the file.

        The join is the shutdown contract for short-lived processes (CLI
        tools, chaos-killed children that catch the signal and exit): a
        daemon flusher abandoned mid-write at interpreter teardown would
        tear its current line AND make the subsequent residual flush race
        ``_closed`` — dropping the last window of spans, exactly the ones
        a post-mortem needs. Joining first means the drain loop has fully
        exited before the final flush drains what remains, so nothing is
        in flight. The timeout is bounded so a wedged disk (hard-mounted
        FS) can never hang process exit; whatever the wedged thread held
        is counted in ``dropped``, per the loss-accounting contract."""
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=max(join_timeout_s, 0.0))
            if thread.is_alive():
                # the flusher is wedged mid-write (hard-mounted FS) and may
                # hold _io_lock: touching the journal now would block
                # process exit on that lock — the very hang the bounded
                # join exists to prevent. Abandon the residual ring
                # (counted in ``dropped``) and leave the file to the OS.
                with self._lock:
                    self.dropped += len(self._ring)
                    self._ring.clear()
                # benign race: the wedged writer re-checks _closed under
                # _io_lock and drops its batch if it ever unwedges
                self._closed = True
                return
        try:
            self.flush()
        except Exception:
            pass
        # spans still open at shutdown (an exception unwound past their
        # holder, a Ctrl-C'd supervise loop) journal as begin-only records
        # — same rescue as a chaos SIGKILL, or the trace root (am.run,
        # executor.user_process) silently vanishes from the merge
        try:
            opens = self._open_records()
        except Exception:
            opens = []
        with self._io_lock:  # serializes journal I/O by design (see flush)
            if self._closed:
                return
            self._closed = True
            try:
                for r in opens:
                    self._write_line(r)  # graft-lint: disable=GL004
            except Exception:
                pass
            if self.dropped:
                try:
                    self._f.write(json.dumps(  # graft-lint: disable=GL004
                        {"ph": "M", "proc": self.proc, "dropped": self.dropped}
                    ) + "\n")
                except Exception:
                    pass
            try:
                self._f.close()
            except Exception:
                pass


# --- process-global arming ---------------------------------------------------

_tracer: Tracer | None = None


def active_tracer() -> Tracer | None:
    return _tracer


def span(name: str, **args: Any):
    """The instrumentation seam. Disarmed: one global load + ``None``
    compare, returns the shared no-op span."""
    t = _tracer
    if t is None:
        return NOOP_SPAN
    return t.span(name, **args)


def instant(name: str, **args: Any) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, **args)


def flush() -> None:
    t = _tracer
    if t is not None:
        t.flush()


def emergency_flush() -> None:
    """Pre-SIGKILL flush: completed AND still-open spans hit the journal."""
    t = _tracer
    if t is not None:
        t.emergency_flush()


def install(tracer: Tracer) -> Tracer:
    global _tracer
    if _tracer is not None and _tracer is not tracer:
        atexit.unregister(_tracer.close)  # arm/disarm cycles must not pile up
        _tracer.close()
    _tracer = tracer
    atexit.register(tracer.close)
    return tracer


def uninstall() -> None:
    """Disarm (tests). Closes and detaches the active tracer."""
    global _tracer
    if _tracer is not None:
        atexit.unregister(_tracer.close)
        _tracer.close()
        _tracer = None


def trace_id_for(app_id: str) -> str:
    """Deterministic per-application trace id: every process of the job
    derives the same id from TONY_APP_ID with no coordination."""
    return hashlib.md5(app_id.encode()).hexdigest()[:16]


def sanitize_proc(name: str) -> str:
    """Journal/snapshot-safe proc name — ONE shared rule, because the name
    keys the ``trace/<proc>.jsonl`` <-> ``metrics/<proc>.json`` correlation."""
    return name.replace(":", "_").replace("/", "_")


def default_proc_name(kind: str = "proc") -> str:
    """This process's journal/snapshot name: the AM-exported TONY_TRACE_PROC
    when present, else derived from the task identity env. The name is
    load-bearing — `tony trace` and the portal correlate ``trace/<proc>.jsonl``
    with ``metrics/<proc>.json`` by it — so every caller (install_from_env,
    fit(), the serve engine) must share ONE derivation."""
    proc = os.environ.get(ENV_PROC, "")
    if not proc:
        job = os.environ.get("TONY_JOB_NAME", kind)
        idx = os.environ.get("TONY_TASK_INDEX", "0")
        proc = f"{job}_{idx}_user"
    return sanitize_proc(proc)


def install_from_env(proc: str = "") -> Tracer | None:
    """Arm this process from the TONY_TRACE_* env the launcher exported.
    Idempotent; returns the active tracer, or None when tracing is off."""
    if _tracer is not None:
        return _tracer
    trace_dir = os.environ.get(ENV_DIR, "")
    if not trace_dir:
        return None
    proc = sanitize_proc(proc) if proc else default_proc_name()
    trace_id = os.environ.get(ENV_TRACE_ID, "") or trace_id_for(
        os.environ.get("TONY_APP_ID", "app")
    )
    def _env_int(key: str, default: int) -> int:
        try:
            return int(os.environ.get(key, "") or default)
        except ValueError:
            return default

    try:
        return install(Tracer(
            os.path.join(trace_dir, f"{proc}.jsonl"), proc, trace_id,
            sample_steps=_env_int(ENV_SAMPLE, 16),
            ring=_env_int(ENV_RING, 4096),
            max_journal_mb=_env_int(ENV_JOURNAL_MB, 64),
            default_parent=os.environ.get(ENV_PARENT, ""),
        ))
    except OSError:
        log.warning("could not open trace journal in %s", trace_dir, exc_info=True)
        return None


def install_from_config(config, app_dir: str, app_id: str, proc: str) -> Tracer | None:
    """Arm from ``trace.*`` config (the AM entrypoint). Inert unless
    ``trace.enabled`` (default: on — tracing is the always-on, sampled
    Dapper substrate, not a debug mode)."""
    from tony_tpu.config.keys import Keys

    if _tracer is not None:
        return _tracer
    if not config.get_bool(Keys.TRACE_ENABLED, True):
        return None
    proc = sanitize_proc(proc)
    try:
        return install(Tracer(
            os.path.join(app_dir, "trace", f"{proc}.jsonl"),
            proc,
            trace_id_for(app_id),
            sample_steps=config.get_int(Keys.TRACE_SAMPLE_STEPS, 16),
            ring=config.get_int(Keys.TRACE_RING_EVENTS, 4096),
            max_journal_mb=config.get_int(Keys.TRACE_MAX_JOURNAL_MB, 64),
        ))
    except OSError:
        log.warning("could not open trace journal under %s", app_dir, exc_info=True)
        return None


__all__ = [
    "ENV_DIR", "ENV_PARENT", "ENV_PROC", "ENV_SAMPLE", "ENV_TRACE_ID",
    "NOOP_SPAN", "RPC_METADATA_KEY", "Span", "Tracer", "active_tracer",
    "default_proc_name", "emergency_flush", "flush", "install",
    "install_from_config", "install_from_env", "instant", "sanitize_proc",
    "span", "trace_id_for", "uninstall",
]

"""Step anatomy: where one training/decode step's time actually goes.

The report every ROADMAP speed claim needs: per captured step, a budget —
**compute / exposed-collective / host-blocked / input-wait** — whose rows
sum to the measured step time, plus per-collective achieved bandwidth and
the compute/collective overlap fraction. Inputs are exactly what the
coordinated capture (obs/profile.py) already landed under
``<app_dir>/profile/``:

- the per-process **manifest** (host step boundaries + per-step input
  wait, measured at the ``maybe_capture`` seam);
- the **device trace** jax.profiler wrote (the ``*.trace.json.gz`` Chrome
  trace next to the xplane proto — stdlib-parseable): XLA op events carry
  the HLO op names, and the ``anatomy.step`` annotation spans bracket each
  captured step on the timeline, so device activity aligns to steps
  without any cross-clock arithmetic;
- the **compile ledger**'s AOT entries (obs/compiles.py), whose extracted
  collective rows (obs/comms.py) carry bytes + replica groups — paired
  with measured event time BY OP NAME to yield achieved GB/s.

Attribution rule (one rule, stated once): within a step window, device
activity is the wall-clock union of XLA op intervals; the part of
collective time not overlapped by any compute op is *exposed*; compute is
the union of non-collective op wall time; input-wait is the host fetch
the seam recorded; host-blocked is the non-negative residual — so the
four rows sum to the measured step time by construction, and the
``device_trace`` flag says whether compute/exposed are measured or the
capture yielded no device events (everything then lands in host-blocked).

Stdlib-only: the report builds in deviceless CLI processes.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import Any

from tony_tpu.obs import comms
from tony_tpu.obs import profile as profile_mod

# wrapper/runtime event names are never XLA ops: "ThunkExecutor::Execute",
# "TfrtCpuExecutable::ExecuteHelper", python tracer events ("$builtins ...")
_PY_PREFIX = "$"


# --- interval algebra ---------------------------------------------------------


def _merge(iv: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Sorted union of half-open intervals."""
    out: list[tuple[float, float]] = []
    for s, e in sorted(iv):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def _total(merged: list[tuple[float, float]]) -> float:
    return sum(e - s for s, e in merged)


def _clip(merged: list[tuple[float, float]],
          window: tuple[float, float]) -> list[tuple[float, float]]:
    ws, we = window
    return [(max(s, ws), min(e, we)) for s, e in merged
            if min(e, we) > max(s, ws)]


def _subtract(a: list[tuple[float, float]],
              b: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """a minus b, both merged; the exposed-collective computation."""
    out: list[tuple[float, float]] = []
    bi = 0
    for s, e in a:
        cur = s
        while bi < len(b) and b[bi][1] <= cur:
            bi += 1
        j = bi
        while j < len(b) and b[j][0] < e:
            bs, be = b[j]
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            if cur >= e:
                break
            j += 1
        if cur < e:
            out.append((cur, e))
    return out


# --- device-trace parsing -----------------------------------------------------


def _is_collective_event(name: str) -> bool:
    base = name.split(".")[0]
    if base in comms.COLLECTIVE_KINDS:
        return True
    for suffix in ("-start", "-done"):
        if base.endswith(suffix) and base[: -len(suffix)] in comms.COLLECTIVE_KINDS:
            return True
    return False


def load_device_trace(run_dir: str) -> dict[str, Any]:
    """Parse the ``*.trace.json[.gz]`` files of one profiler run dir into
    step windows + device-op intervals (seconds, trace timebase).

    Classification: an X event is a device op when it sits on a device
    plane (process name ``/device:...``) or an XLA runtime thread
    (``tf_...``) AND its name is an op name — not a python-tracer event
    (``$...``) and not a C++ wrapper (``Class::Method``). The
    ``anatomy.step`` annotation spans (host thread) become the step
    windows."""
    out: dict[str, Any] = {
        "found": False, "step_windows": [], "compute": [], "collective": [],
        "collective_events": [], "files": [],
    }
    if not run_dir or not os.path.isdir(run_dir):
        return out
    names = sorted(
        n for n in os.listdir(run_dir)
        if n.endswith(".trace.json.gz") or n.endswith(".trace.json")
    )
    for name in names:
        path = os.path.join(run_dir, name)
        try:
            if name.endswith(".gz"):
                with gzip.open(path, "rt", encoding="utf-8") as f:
                    data = json.load(f)
            else:
                with open(path, encoding="utf-8") as f:
                    data = json.load(f)
        except (OSError, ValueError):
            continue
        events = data.get("traceEvents") or []
        proc_names: dict[Any, str] = {}
        thread_names: dict[tuple, str] = {}
        for e in events:
            if e.get("ph") != "M":
                continue
            if e.get("name") == "process_name":
                proc_names[e.get("pid")] = str(
                    (e.get("args") or {}).get("name", "")
                )
            elif e.get("name") == "thread_name":
                thread_names[(e.get("pid"), e.get("tid"))] = str(
                    (e.get("args") or {}).get("name", "")
                )
        for e in events:
            if e.get("ph") != "X":
                continue
            ename = str(e.get("name", ""))
            ts = float(e.get("ts", 0.0)) / 1e6
            dur = float(e.get("dur", 0.0)) / 1e6
            if ename == profile_mod.STEP_ANNOTATION:
                out["step_windows"].append((ts, ts + dur))
                continue
            if not ename or ename.startswith(_PY_PREFIX) or "::" in ename:
                continue
            pname = proc_names.get(e.get("pid"), "")
            tname = thread_names.get((e.get("pid"), e.get("tid")), "")
            if not (pname.startswith("/device:") or tname.startswith("tf_")):
                continue
            iv = (ts, ts + dur)
            if _is_collective_event(ename):
                out["collective"].append(iv)
                out["collective_events"].append(
                    {"name": ename, "ts": ts, "dur_s": dur}
                )
            else:
                out["compute"].append(iv)
        out["files"].append(name)
        out["found"] = True
    out["step_windows"].sort()
    return out


# --- the budget table ---------------------------------------------------------


def step_budget(manifest: dict[str, Any],
                trace_data: dict[str, Any]) -> dict[str, Any]:
    """Per-step budget rows for one process's capture (see the module
    docstring for the attribution rule)."""
    step_times = [float(x) for x in manifest.get("step_time_s", [])]
    waits = [float(x) for x in manifest.get("input_wait_s", [])]
    windows = list(trace_data.get("step_windows", []))
    compute_all = _merge(trace_data.get("compute", []))
    coll_all = _merge(trace_data.get("collective", []))
    device_trace = bool(trace_data.get("found")) and bool(
        compute_all or coll_all
    )
    rows: list[dict[str, Any]] = []
    tot = {"step_time_s": 0.0, "compute_s": 0.0, "exposed_collective_s": 0.0,
           "input_wait_s": 0.0, "host_blocked_s": 0.0, "collective_s": 0.0}
    # overlap accumulators EXCLUDE pure-comm steps (collective time with
    # zero compute in the window — a sync barrier, an init broadcast): such
    # a step has no compute to hide under, so its collective time is 100%
    # exposed by construction and would dilute overlap_frac — one barrier
    # step could mask a real overlap regression in the training steps
    ov_coll = ov_exposed = 0.0
    pure_comm_steps = 0
    for i, step_time in enumerate(step_times):
        wait = waits[i] if i < len(waits) else 0.0
        compute_s = exposed_s = coll_s = 0.0
        if device_trace and i < len(windows):
            w = windows[i]
            compute = _clip(compute_all, w)
            coll = _clip(coll_all, w)
            compute_s = _total(compute)
            coll_s = _total(coll)
            exposed_s = _total(_subtract(coll, compute))
        host = max(step_time - compute_s - exposed_s - wait, 0.0)
        pure_comm = coll_s > 0.0 and compute_s == 0.0
        row = {
            "step": i + 1,
            "step_time_s": round(step_time, 6),
            "compute_s": round(compute_s, 6),
            "exposed_collective_s": round(exposed_s, 6),
            "input_wait_s": round(wait, 6),
            "host_blocked_s": round(host, 6),
        }
        if pure_comm:
            row["pure_comm"] = True
            pure_comm_steps += 1
        else:
            ov_coll += coll_s
            ov_exposed += exposed_s
        rows.append(row)
        tot["step_time_s"] += step_time
        tot["compute_s"] += compute_s
        tot["exposed_collective_s"] += exposed_s
        tot["input_wait_s"] += wait
        tot["host_blocked_s"] += host
        tot["collective_s"] += coll_s
    n = max(len(rows), 1)
    out = {
        "steps": len(rows),
        "device_trace": device_trace,
        "table": rows,
        "totals": {k: round(v, 6) for k, v in tot.items()},
        "per_step_ms": {
            k: round(tot[k] / n * 1e3, 3)
            for k in ("step_time_s", "compute_s", "exposed_collective_s",
                      "input_wait_s", "host_blocked_s")
        },
    }
    if pure_comm_steps:
        out["pure_comm_steps"] = pure_comm_steps
    if ov_coll > 0:
        # fraction of collective time hidden under compute: the overlap
        # number `tony perf diff` judges higher-is-better. Pure-comm steps
        # are excluded (flagged per row) — they have nothing to overlap.
        out["overlap_frac"] = round(1.0 - ov_exposed / ov_coll, 4)
    return out


def collective_table(trace_data: dict[str, Any],
                     ledger_rows: list[dict[str, Any]] | None
                     ) -> list[dict[str, Any]]:
    """Per-collective rows: static bytes/replica-groups from the compile
    ledger (obs/comms.py) joined with measured device-trace time BY OP
    NAME; achieved bandwidth where both sides exist. Ledger-only rows
    (never executed in the window) and trace-only rows (no AOT entry —
    e.g. a lazily jitted fn) are kept, flagged by what they miss — the
    table never silently drops either side."""
    measured: dict[str, dict[str, float]] = {}
    for ev in trace_data.get("collective_events", []):
        m = measured.setdefault(ev["name"], {"count": 0, "total_s": 0.0})
        m["count"] += 1
        m["total_s"] += ev["dur_s"]
    by_name: dict[str, dict[str, Any]] = {}
    for row in ledger_rows or []:
        by_name.setdefault(row["name"], {
            "name": row["name"], "kind": row["kind"],
            "bytes": int(row.get("bytes", 0)),
            "replica_groups": row.get("replica_groups", ""),
        })
    for name, m in measured.items():
        entry = by_name.setdefault(name, {
            "name": name, "kind": name.split(".")[0], "bytes": 0,
            "replica_groups": "",
        })
        entry["count"] = int(m["count"])
        entry["total_s"] = round(m["total_s"], 6)
        entry["mean_us"] = round(m["total_s"] / m["count"] * 1e6, 3)
        if entry["bytes"] and m["total_s"] > 0:
            # 4 significant figures, not fixed decimals: CPU-test and DCN
            # bandwidths live orders of magnitude below ICI ones
            entry["achieved_gbps"] = float(
                f"{entry['bytes'] * m['count'] / m['total_s'] / 1e9:.4g}"
            )
    rows = sorted(
        by_name.values(),
        key=lambda r: (-r.get("total_s", 0.0), -r.get("bytes", 0), r["name"]),
    )
    return rows


def ledger_collectives(ledger_payload: dict[str, Any] | None
                       ) -> list[dict[str, Any]]:
    """Flatten one process's compile-ledger snapshot (obs/compiles.py) to
    its AOT entries' collective rows, tagged with the entry fn."""
    rows: list[dict[str, Any]] = []
    for entry in (ledger_payload or {}).get("entries", []) or []:
        for c in entry.get("collectives") or []:
            rows.append({**c, "fn": entry.get("fn", "")})
    return rows


def proc_report(manifest: dict[str, Any],
                ledger_rows: list[dict[str, Any]] | None = None
                ) -> dict[str, Any]:
    """The full anatomy of ONE process's capture."""
    trace_data = load_device_trace(manifest.get("artifact", ""))
    budget = step_budget(manifest, trace_data)
    colls = collective_table(trace_data, ledger_rows)
    return {
        "profile_id": manifest.get("profile_id", ""),
        "proc": manifest.get("proc", ""),
        "artifact": manifest.get("artifact", ""),
        **budget,
        "collectives": colls,
    }


def build_anatomy(app_dir: str, profile_id: str = "") -> dict[str, Any]:
    """``tony profile report``: every process's budget table + collective
    rows for one capture (newest when unspecified), plus the cross-host
    critical path — per aligned step, the process whose step took longest
    is the one gating the gang (pipeline stage or decode host alike)."""
    from tony_tpu.obs.compiles import read_app_ledgers

    manifests = profile_mod.read_manifests(app_dir, profile_id)
    out: dict[str, Any] = {"profile_id": profile_id, "procs": {}}
    if not manifests:
        return out
    ledgers = read_app_ledgers(app_dir)
    for proc, manifest in sorted(manifests.items()):
        out["profile_id"] = manifest.get("profile_id", profile_id)
        out["procs"][proc] = proc_report(
            manifest, ledger_collectives(ledgers.get(proc))
        )
    # critical path: per step index, the slowest process owns the fleet's
    # wall clock for that step
    by_step: list[dict[str, Any]] = []
    n_steps = max(
        (r["steps"] for r in out["procs"].values()), default=0
    )
    dominated: dict[str, int] = {}
    for i in range(n_steps):
        best_proc, best_t = "", -1.0
        for proc, rep in out["procs"].items():
            if i < len(rep["table"]):
                t = rep["table"][i]["step_time_s"]
                if t > best_t:
                    best_proc, best_t = proc, t
        if best_proc:
            by_step.append({
                "step": i + 1, "proc": best_proc,
                "step_time_s": round(best_t, 6),
            })
            dominated[best_proc] = dominated.get(best_proc, 0) + 1
    if by_step:
        out["critical_path"] = {
            "proc": max(dominated, key=dominated.get),
            "dominated_steps": dominated,
            "by_step": by_step,
        }
    return out


__all__ = [
    "build_anatomy", "collective_table", "ledger_collectives",
    "load_device_trace", "proc_report", "step_budget",
]

"""HBM observatory: phase watermarks, sampled memory counters, OOM forensics.

PR 6's trace spine answers *where the time went*; this module answers
*where the HBM went*. The raw device counter (``device.memory_stats()``'s
``peak_bytes_in_use``) is a cumulative per-process high-water mark — within
one process a later phase inherits every earlier phase's peak, which is why
bench.py used to ship a ``cum_peak_after_moe`` naming workaround instead of
per-config numbers. :class:`HbmWatch` fixes the attribution:

- ``phase(name)`` marks live + cumulative-peak bytes on entry and measures
  on exit. When the cumulative peak ADVANCED during the phase, the phase
  owns the new high-water mark exactly (``peak_exact: True``); when it
  stayed under an earlier phase's peak, the best honest bound is the larger
  of the entry/exit live readings (``peak_exact: False``) — either way the
  number is *scoped to the phase*, never an inherited cumulative.
- ``sample()`` is the hot-path seam (one global load + ``None`` compare
  when disarmed, stride-counted when armed — the trace-hook contract,
  guarded by tests/test_perf_guard.py and graft-lint GL005): every
  ``sample_every``-th call reads per-device live/peak bytes, records them
  in a bounded history ring, updates registry gauges, and emits a Perfetto
  counter-track row (``ph: "C"``) through the armed tracer so ``tony
  trace`` merges a per-device memory timeline alongside the spans.
- :func:`oom_guard` wraps ``fit()`` and ``Engine.run``: a
  ``RESOURCE_EXHAUSTED`` escaping the loop dumps
  ``jax.profiler.device_memory_profile()`` (pprof), the compile ledger
  (obs/compiles.py), and the watermark/sample history into
  ``<app_dir>/oom/`` before re-raising — the forensics a post-mortem needs
  land next to the trace journals the chaos flow already reads.

jax is imported lazily (the AM exports the ``obs.hbm.*`` env contract
without owning a device; non-JAX executors must not pay the import).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from collections import deque
from typing import Any, Callable

log = logging.getLogger(__name__)

# env contract (AM -> executor -> user process, next to TONY_TRACE_*)
ENV_ENABLED = "TONY_OBS_HBM"          # "0" disables arming
ENV_SAMPLE = "TONY_OBS_HBM_SAMPLE"    # sampling stride (calls per reading)
ENV_HISTORY = "TONY_OBS_HBM_HISTORY"  # sample-history ring size

GB = float(2**30)

# stats keys this module reads (the PJRT memory_stats vocabulary)
_LIVE = "bytes_in_use"
_PEAK = "peak_bytes_in_use"
_LIMIT = "bytes_limit"


def default_stats_fn() -> list[tuple[str, dict]]:
    """Per-device ``memory_stats`` readings as ``(label, stats)`` pairs;
    devices without stats (CPU, interpreters) are skipped — an empty list
    means the platform has nothing to watch, which every consumer treats
    as "no data", never an error."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return []
    out = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out.append((f"dev{d.id}", dict(stats)))
    return out


class Phase:
    """One mark/measure window. Use as a context manager; ``result`` holds
    the per-device measurement after exit (``{}`` while still open)."""

    __slots__ = ("name", "args", "result", "_watch", "_t0", "_enter")

    def __init__(self, watch: "HbmWatch", name: str, args: dict[str, Any]):
        self._watch = watch
        self.name = name
        self.args = args
        self.result: dict[str, Any] = {}
        self._t0 = 0.0
        self._enter: dict[str, tuple[int, int]] = {}

    def __enter__(self) -> "Phase":
        self._t0 = time.perf_counter()
        self._enter = self._watch.mark()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.result = {
            "name": self.name,
            "ts": time.time(),
            "dur_s": round(time.perf_counter() - self._t0, 3),
            "devices": self._watch.measure_since(self._enter),
            **self.args,
        }
        self._watch._record_phase(self.result)
        return False

    def bench_keys(self) -> dict[str, Any]:
        """Device-0 watermarks as flat bench-JSON keys (``{}`` when the
        platform reports no stats)."""
        devices = self.result.get("devices", {})
        if not devices:
            return {}
        rec = next(iter(devices.values()))
        out = {
            "phase_peak_hbm_gb": round(rec["peak_bytes"] / GB, 3),
            "phase_delta_peak_gb": round(rec["delta_peak_bytes"] / GB, 3),
            "live_end_gb": round(rec["live_end_bytes"] / GB, 3),
            "peak_exact": rec["peak_exact"],
        }
        if "limit_bytes" in rec:
            out["hbm_limit_gb"] = round(rec["limit_bytes"] / GB, 2)
        return out


class HbmWatch:
    """Phase watermarks + stride-sampled per-device memory readings.

    ``stats_fn`` is pluggable (tests inject deterministic fakes; the
    default reads every local device's ``memory_stats``). The watch keeps
    a bounded phase list and sample-history ring — both land in the OOM
    forensics dump — and mirrors the newest reading into registry gauges
    (``tony_hbm_live_bytes`` / ``tony_hbm_peak_bytes``, labelled by
    device) and a tracer counter track when a tracer is armed."""

    def __init__(self, stats_fn: Callable[[], list] | None = None,
                 registry=None, sample_every: int = 16,
                 history: int = 512, max_phases: int = 256):
        self._stats_fn = stats_fn or default_stats_fn
        self._registry = registry
        self.sample_every = max(int(sample_every), 1)
        self.history: deque = deque(maxlen=max(int(history), 16))
        self.phases: deque = deque(maxlen=max(int(max_phases), 16))
        self._n = 0

    def read(self) -> list[tuple[str, dict]]:
        try:
            return list(self._stats_fn())
        except Exception:
            return []

    def phase(self, name: str, **args: Any) -> Phase:
        return Phase(self, name, dict(args))

    def mark(self) -> dict[str, tuple[int, int]]:
        """Per-device (live, cumulative-peak) snapshot — the entry half of
        the mark/measure watermark (``Phase`` and the fit()/engine
        shutdown summaries share it)."""
        return {
            label: (int(stats.get(_LIVE, 0)), int(stats.get(_PEAK, 0)))
            for label, stats in self.read()
        }

    def measure_since(self, marks: dict[str, tuple[int, int]]
                      ) -> dict[str, dict[str, Any]]:
        """Scoped watermark since :meth:`mark`, per device. THE attribution
        rule this module exists for: a window that advanced the process's
        cumulative peak OWNS the new mark exactly (``peak_exact``); one
        that stayed under an earlier window's peak can only be bounded by
        its own live readings — never report the inherited number."""
        devices: dict[str, dict[str, Any]] = {}
        for label, stats in self.read():
            live1 = int(stats.get(_LIVE, 0))
            cum1 = int(stats.get(_PEAK, 0))
            live0, cum0 = marks.get(label, (live1, cum1))
            peak_exact = cum1 > cum0
            peak = cum1 if peak_exact else max(live0, live1)
            rec: dict[str, Any] = {
                "live_start_bytes": live0,
                "live_end_bytes": live1,
                "live_delta_bytes": live1 - live0,
                "peak_bytes": peak,
                "delta_peak_bytes": max(peak - live0, 0),
                "peak_exact": peak_exact,
            }
            if _LIMIT in stats:
                rec["limit_bytes"] = int(stats[_LIMIT])
            devices[label] = rec
        return devices

    def peak_since(self, marks: dict[str, tuple[int, int]]
                   ) -> tuple[float, bool]:
        """(peak GB, exact?) across devices since :meth:`mark` — the
        shutdown-summary form of :meth:`measure_since`; (0.0, False) when
        the platform reports no stats."""
        devices = self.measure_since(marks)
        if not devices:
            return 0.0, False
        top = max(devices.values(), key=lambda rec: rec["peak_bytes"])
        # the exact flag belongs to the device whose peak is reported — a
        # sibling device's bound must not downgrade an exact measurement
        return round(top["peak_bytes"] / GB, 3), top["peak_exact"]

    def _record_phase(self, result: dict) -> None:
        self.phases.append(result)

    def sample(self, **args: Any) -> dict | None:
        """Stride-counted reading; returns the sample dict on a stride hit,
        None otherwise. The off-stride cost is one increment + modulo."""
        self._n += 1
        if self._n % self.sample_every:
            return None
        return self.force_sample(**args)

    def force_sample(self, **args: Any) -> dict | None:
        """Read now regardless of stride (phase boundaries, shutdown)."""
        readings = self.read()
        if not readings:
            return None
        sample: dict[str, Any] = {"ts": time.time(), **args}
        from tony_tpu.obs import trace

        tracer = trace.active_tracer()
        for label, stats in readings:
            live = int(stats.get(_LIVE, 0))
            peak = int(stats.get(_PEAK, 0))
            sample[label] = {"live_bytes": live, "peak_bytes": peak}
            if self._registry is not None:
                self._set_gauges(self._registry, label, live, peak)
            if tracer is not None:
                # one counter track per device: Perfetto renders each args
                # series as a line on the memory timeline
                tracer.counter(
                    f"hbm.{label}",
                    live_gb=round(live / GB, 4),
                    peak_gb=round(peak / GB, 4),
                )
        self.history.append(sample)
        return sample

    @staticmethod
    def _set_gauges(registry, label: str, live: int, peak: int) -> None:
        registry.gauge(
            "tony_hbm_live_bytes", "device HBM bytes in use", device=label,
        ).set(live)
        registry.gauge(
            "tony_hbm_peak_bytes", "device cumulative peak HBM bytes",
            device=label,
        ).set(peak)

    def export_gauges(self, registry) -> None:
        """Write a fresh reading's per-device gauges into ``registry`` —
        fit() and the engine call this right before their shutdown
        snapshot, so ``tony_hbm_*`` lands in the job-history metrics the
        portal's ``/metrics`` endpoint serves (the watch's own registry is
        the process-global one, which nothing snapshots)."""
        for label, stats in self.read():
            self._set_gauges(
                registry, label,
                int(stats.get(_LIVE, 0)), int(stats.get(_PEAK, 0)),
            )

    def to_dict(self) -> dict:
        """Everything the forensics dump wants: phases + sample history +
        a fresh reading."""
        return {
            "sample_every": self.sample_every,
            "phases": list(self.phases),
            "history": list(self.history),
            "current": {label: stats for label, stats in self.read()},
        }


# --- process-global arming (the trace.py pattern) ----------------------------

_watch: HbmWatch | None = None


def active_watch() -> HbmWatch | None:
    return _watch


def install(watch: HbmWatch) -> HbmWatch:
    global _watch
    _watch = watch
    return watch


def uninstall() -> None:
    global _watch
    _watch = None


def sample() -> None:
    """The hot-path seam (train/serve step loops). Disarmed: one global
    load + ``None`` compare. Call sites must pass no computed arguments
    (graft-lint GL005 enforces this like the trace/chaos hooks)."""
    w = _watch
    if w is not None:
        w.sample()


def install_from_env() -> HbmWatch | None:
    """Arm this process from the ``TONY_OBS_HBM*`` env the AM exported
    (defaults apply standalone — bench and bare fit() runs get watermarks
    without a job). Idempotent; ``TONY_OBS_HBM=0`` disables."""
    if _watch is not None:
        return _watch
    if os.environ.get(ENV_ENABLED, "") == "0":
        return None

    def _env_int(key: str, default: int) -> int:
        try:
            return int(os.environ.get(key, "") or default)
        except ValueError:
            return default

    from tony_tpu.obs.registry import get_registry

    return install(HbmWatch(
        registry=get_registry(),
        sample_every=_env_int(ENV_SAMPLE, 16),
        history=_env_int(ENV_HISTORY, 512),
    ))


# --- OOM forensics -----------------------------------------------------------


def is_oom(exc: BaseException) -> bool:
    """True for XLA's allocator failure surfaced through any wrapper
    (XlaRuntimeError carries the gRPC-style code in its message)."""
    return "RESOURCE_EXHAUSTED" in f"{type(exc).__name__}: {exc}"


def dump_oom(where: str, exc: BaseException,
             app_dir: str | None = None) -> list[str]:
    """Write the forensics bundle into ``<app_dir>/oom/`` and return the
    written paths. Best-effort by design: the process is dying of OOM, so
    every part is independently guarded and a failed part costs only
    itself."""
    app_dir = app_dir if app_dir is not None else os.environ.get("TONY_APP_DIR", "")
    if not app_dir:
        return []
    from tony_tpu.obs import trace

    proc = trace.default_proc_name()
    out_dir = os.path.join(app_dir, "oom")
    try:
        os.makedirs(out_dir, exist_ok=True)
    except OSError:
        return []
    written: list[str] = []
    report: dict[str, Any] = {
        "where": where,
        "proc": proc,
        "ts": time.time(),
        "error": f"{type(exc).__name__}: {str(exc)[:2000]}",
    }
    watch = _watch
    if watch is not None:
        try:
            report["hbm"] = watch.to_dict()
        except Exception:
            pass
    else:
        report["hbm"] = {"current": dict(default_stats_fn())}
    try:
        from tony_tpu.obs.compiles import get_ledger

        report["compiles"] = get_ledger().to_dict()
    except Exception:
        pass
    path = os.path.join(out_dir, f"{proc}_{where}.json")
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(report, f, default=str)
        written.append(path)
    except OSError:
        pass
    # the allocator's own view: a pprof protobuf of live device allocations
    # by call site — the "what exactly is resident" answer no watermark
    # has. device_memory_profile() already returns GZIPPED pprof bytes
    # (xla heap_profile), so they are written verbatim — compressing again
    # would make the artifact unreadable by pprof.
    try:
        import jax

        prof = jax.profiler.device_memory_profile()
        ppath = os.path.join(out_dir, f"{proc}_{where}.memprof.pb.gz")
        with open(ppath, "wb") as f:
            f.write(prof)
        written.append(ppath)
    except Exception:
        pass
    if written:
        log.error("OOM in %s: forensics written to %s", where, out_dir)
    return written


@contextlib.contextmanager
def oom_guard(where: str):
    """Re-raising RESOURCE_EXHAUSTED handler: the forensics bundle lands
    in the app dir (where the chaos post-mortem flow picks it up) and the
    exception continues to the caller unchanged."""
    try:
        yield
    except BaseException as e:  # noqa: B036 — inspect, dump, ALWAYS re-raise
        if is_oom(e):
            dump_oom(where, e)
        raise


def forensics_files(app_dir: str) -> list[str]:
    """OOM bundle filenames under an app dir (the chaos runner lists these
    in its post-mortem report)."""
    out_dir = os.path.join(app_dir, "oom")
    try:
        return sorted(os.listdir(out_dir))
    except OSError:
        return []


__all__ = [
    "ENV_ENABLED", "ENV_HISTORY", "ENV_SAMPLE", "HbmWatch", "Phase",
    "active_watch", "default_stats_fn", "dump_oom", "forensics_files",
    "install", "install_from_env", "is_oom", "oom_guard", "sample",
    "uninstall",
]

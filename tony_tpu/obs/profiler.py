"""Profiler glue: jax.profiler wired to a flag.

The reference has no profiler integration (SURVEY.md section 5 "Tracing":
logs + metrics sampler only); on TPU this is the highest-leverage
observability upgrade, kept deliberately thin: one flag
(``profiler.enabled``) starts the trace server inside the training process,
and ``trace_window`` dumps a perfetto-readable trace of N steps.

    with trace_window("/tmp/trace", enabled=step == 10):
        state, metrics = step_fn(state, ...)
        jax.block_until_ready(metrics)
"""

from __future__ import annotations

import contextlib
import logging

import jax

log = logging.getLogger(__name__)


def start_server(port: int = 9999) -> bool:
    """Start the profiler's TCP server (for `tensorboard --logdir` capture
    or `jax.profiler.trace` remote attach). Returns False if unavailable."""
    try:
        jax.profiler.start_server(port)
        log.info("jax profiler server on :%d", port)
        return True
    except Exception:
        log.warning("could not start profiler server", exc_info=True)
        return False


@contextlib.contextmanager
def trace_window(log_dir: str, enabled: bool = True):
    """Trace everything inside the block into ``log_dir`` (perfetto/XPlane).

    The caller must block_until_ready inside the window for device activity
    to be attributed (dispatch is async). Finalisation is try/finally: an
    exception inside the traced block still stops the trace and logs where
    it landed — the partial trace of a crashing step is exactly the one
    worth keeping, and an unfinalised profiler session would poison the
    next trace_window with a "already tracing" error."""
    if not enabled:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        # swallow a stop_trace failure (logging it): raising here would
        # mask an in-flight exception from the traced block, and the
        # success line must not lie about a trace that never landed
        try:
            jax.profiler.stop_trace()
        except Exception:
            log.warning(
                "profiler trace finalisation failed for %s", log_dir,
                exc_info=True,
            )
        else:
            log.info("profiler trace written to %s", log_dir)


def annotate(name: str):
    """Named region in traces: ``with annotate('data-load'): ...``"""
    return jax.profiler.TraceAnnotation(name)


__all__ = ["annotate", "start_server", "trace_window"]

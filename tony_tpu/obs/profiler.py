"""Profiler glue: jax.profiler wired to a flag.

The reference has no profiler integration (SURVEY.md section 5 "Tracing":
logs + metrics sampler only); on TPU this is the highest-leverage
observability upgrade, kept deliberately thin: one flag
(``profiler.enabled``) starts the trace server inside the training process,
and ``trace_window`` dumps a perfetto-readable trace of N steps.

    with trace_window("/tmp/trace", enabled=step == 10) as cap:
        state, metrics = step_fn(state, ...)
        jax.block_until_ready(metrics)
    print(cap.path)  # the run dir the artifacts actually landed in

``trace_window`` is the ONE capture primitive: the coordinated fleet
profiler (obs/profile.py) drives it too, so artifact layout and
finalisation semantics cannot fork between ad-hoc and coordinated
captures.
"""

from __future__ import annotations

import contextlib
import logging
import os

import jax

log = logging.getLogger(__name__)


def start_server(port: int = 9999) -> bool:
    """Start the profiler's TCP server (for `tensorboard --logdir` capture
    or `jax.profiler.trace` remote attach). Returns False if unavailable."""
    try:
        jax.profiler.start_server(port)
        log.info("jax profiler server on :%d", port)
        return True
    except Exception:
        log.warning("could not start profiler server", exc_info=True)
        return False


class CaptureHandle:
    """Where a trace_window capture landed. ``path`` is the timestamped
    run directory jax.profiler actually wrote
    (``<log_dir>/plugins/profile/<run>/``) — the profiler names it by
    wall time, so without this handle callers cannot locate their own
    capture deterministically. Empty until the window finalises; stays
    empty when finalisation failed (the ``ok`` flag says which)."""

    __slots__ = ("log_dir", "path", "ok")

    def __init__(self, log_dir: str = ""):
        self.log_dir = log_dir
        self.path = ""
        self.ok = False


def latest_run_dir(log_dir: str) -> str:
    """Newest profiler run directory under ``log_dir`` ('' when none):
    jax.profiler writes ``<log_dir>/plugins/profile/<wallclock_run>/``."""
    root = os.path.join(log_dir, "plugins", "profile")
    try:
        runs = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )
    except OSError:
        return ""
    return os.path.join(root, runs[-1]) if runs else ""


@contextlib.contextmanager
def trace_window(log_dir: str, enabled: bool = True):
    """Trace everything inside the block into ``log_dir`` (perfetto/XPlane);
    yields a :class:`CaptureHandle` whose ``path`` is the run directory the
    artifacts landed in once the block exits.

    The caller must block_until_ready inside the window for device activity
    to be attributed (dispatch is async). Finalisation is try/finally: an
    exception inside the traced block still stops the trace and logs where
    it landed — the partial trace of a crashing step is exactly the one
    worth keeping, and an unfinalised profiler session would poison the
    next trace_window with a "already tracing" error."""
    handle = CaptureHandle(log_dir)
    if not enabled:
        yield handle
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield handle
    finally:
        # swallow a stop_trace failure (logging it): raising here would
        # mask an in-flight exception from the traced block, and the
        # success line must not lie about a trace that never landed
        try:
            jax.profiler.stop_trace()
        except Exception:
            log.warning(
                "profiler trace finalisation failed for %s", log_dir,
                exc_info=True,
            )
        else:
            handle.ok = True
            # resolve the timestamped run dir so callers (the fleet
            # profiler's manifest, ad-hoc scripts) can point at THIS
            # capture instead of globbing the shared log_dir
            handle.path = latest_run_dir(log_dir) or log_dir
            log.info("profiler trace written to %s", handle.path)


def annotate(name: str):
    """Named region in traces: ``with annotate('data-load'): ...``"""
    return jax.profiler.TraceAnnotation(name)


__all__ = [
    "CaptureHandle", "annotate", "latest_run_dir", "start_server",
    "trace_window",
]

"""Live fleet time-series: the flight recorder's *now* axis.

The observability stack so far is retrospective — registry metrics are
shutdown snapshots, traces merge post-hoc, health verdicts land at trip
time. A production orchestrator must also answer *what is happening now*:
per-host step progress, TTFT/queue-depth trends, HBM headroom — the feed
``tony top`` renders and the SLO engine (obs/slo.py) alerts on.

:class:`SeriesRecorder` holds the established disarmed-hook contract
(trace/hbm/health twins; graft-lint GL005, tests/test_perf_guard.py):

- :func:`sample` is the hot-path seam. Disarmed it is ONE global load +
  ``None`` compare; armed off-stride it is one counter bump. Every
  ``sample_steps``-th call *scrapes* the attached sources (cheap host-side
  dict builders — the engine's :meth:`~tony_tpu.serve.engine.Engine.
  stats_snapshot`, fit()'s step/goodput closure) plus the built-in
  HBM/health readers into one flat point.
- The point is enqueued to a bounded queue drained by a daemon writer:
  JSON serialization and file I/O never land on the step loop. A full
  queue drops the point (counted in ``dropped``), never blocks.
- Points journal to ring-rotated ``series/<proc>.jsonl`` under the app
  dir (the trace.py retention scheme: at the size cap the journal rotates
  to ``<proc>.0.jsonl`` and the NEWEST window survives — disk stays
  bounded at ~2x ``obs.series.max_journal_mb``).
- Observers (the SLO engine) see each point on the writer thread — rule
  evaluation is asynchronous by construction, like the health sentinel.

Read paths (:func:`read_series`, :func:`fleet_rollup`) are deviceless and
shared by ``tony top``, the portal's ``/api/series`` endpoints, and tests
— ONE reader, one layout. Staleness is first-class: a dead host's frozen
series reports its ``age_s``, never masquerades as current.

Stdlib-only on purpose (the AM exports the env contract without owning a
device; the portal/CLI read paths run in deviceless processes).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from collections import deque
from typing import Any, Callable

log = logging.getLogger(__name__)

# env contract (AM -> executor -> user process, next to TONY_TRACE_* /
# TONY_OBS_HBM* / TONY_OBS_HEALTH*)
ENV_ENABLED = "TONY_OBS_SERIES"              # "0" disables arming
ENV_SAMPLE = "TONY_OBS_SERIES_SAMPLE"        # scrape stride (steps)
ENV_JOURNAL_MB = "TONY_OBS_SERIES_JOURNAL_MB"  # journal rotation size


class SeriesRecorder:
    """Stride-scraped time-series journal over pluggable sources.

    ``attach(name, fn)`` registers a source: a callable returning a flat
    ``{key: number}`` dict (cheap host-side reads only — sources must
    never sync a device; the engine's ``stats_snapshot`` and fit()'s
    closure are the wired shapes). A scrape merges every source into one
    point ``{"ts": ..., **kwargs, **source_values}``; later sources win
    key collisions (rare by construction: sources own their key
    vocabularies).

    ``path=None`` records to the in-memory ring only (standalone fit()/
    engine runs outside a job still feed the SLO engine and tests).
    """

    def __init__(self, path: str | None, proc: str, *,
                 sample_every: int = 16, max_journal_mb: int = 16,
                 ring: int = 512, queue_size: int = 64):
        from tony_tpu.obs import trace

        self.path = path
        self.proc = proc or trace.default_proc_name()
        self.sample_every = max(int(sample_every), 1)
        self.ring: deque = deque(maxlen=max(int(ring), 16))
        self.dropped = 0          # queue overflow (writer slower than scrapes)
        self._n = 0               # seam stride counter
        self._sources: dict[str, Callable[[], dict]] = {}
        self._observers: list[Callable[[dict], None]] = []
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._pending = 0
        self._closed = False
        self._max_bytes = max(int(max_journal_mb), 1) * 2**20
        self._written = 0
        self._f = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # append-mode reopen (re-arm cycles, relaunch reusing a proc
            # name): count what's there or the 2x disk bound breaks
            self._f = open(path, "a", encoding="utf-8")
            self._written = self._f.tell()
        self._q: queue.Queue = queue.Queue(maxsize=max(int(queue_size), 4))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name="tony-series"
        )
        self._thread.start()

    # --- sources / observers --------------------------------------------------

    def attach(self, name: str, fn: Callable[[], dict]) -> None:
        """Register a scrape source (idempotent per name; last wins)."""
        with self._lock:
            self._sources[name] = fn

    def detach(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def add_observer(self, fn: Callable[[dict], None]) -> None:
        """``fn(point)`` runs on the WRITER thread for every recorded
        point — the SLO engine's async evaluation seam."""
        with self._lock:
            self._observers.append(fn)

    # --- hot path -------------------------------------------------------------

    def sample(self, **args: Any) -> dict | None:
        """Stride-counted scrape; returns the point on a stride hit, None
        otherwise. The off-stride cost is one increment + modulo."""
        self._n += 1
        if self._n % self.sample_every:
            return None
        return self.force_sample(**args)

    def force_sample(self, **args: Any) -> dict | None:
        """Scrape now regardless of stride (shutdown, tests)."""
        point: dict[str, Any] = {"ts": time.time(), **args}
        with self._lock:
            sources = list(self._sources.items())
        for _, fn in sources:
            try:
                vals = fn()
            except Exception:
                log.debug("series source failed", exc_info=True)
                continue
            if vals:
                point.update(vals)
        self._builtin_readers(point)
        self.ring.append(point)
        if self._stop.is_set():
            # closed recorder (a holder outliving an uninstall): the ring
            # still records, nothing enqueues toward the dead writer
            return point
        try:
            with self._lock:
                self._pending += 1
            self._q.put_nowait(point)
        except queue.Full:
            with self._lock:
                self._pending -= 1
                self.dropped += 1
        return point

    @staticmethod
    def _builtin_readers(point: dict[str, Any]) -> None:
        """HBM live/peak/limit (device 0) and the health verdict ride every
        point without per-caller wiring — the SLO engine's
        ``hbm_headroom_frac`` input and ``tony top``'s health column."""
        from tony_tpu.obs import hbm, health

        watch = hbm.active_watch()
        if watch is not None:
            readings = watch.read()
            if readings:
                _, stats = readings[0]
                live = int(stats.get("bytes_in_use", 0))
                point["hbm_live_bytes"] = live
                point["hbm_peak_bytes"] = int(stats.get("peak_bytes_in_use", 0))
                limit = int(stats.get("bytes_limit", 0))
                if limit > 0:
                    point["hbm_limit_bytes"] = limit
                    point["hbm_headroom_frac"] = round(
                        max(1.0 - live / limit, 0.0), 4
                    )
        sentinel = health.active_sentinel()
        if sentinel is not None:
            point["health_tripped"] = 1.0 if sentinel.verdict == "tripped" else 0.0

    # --- writer thread --------------------------------------------------------

    def _worker(self) -> None:
        while True:
            try:
                point = self._q.get(timeout=0.25)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if point is None:  # close() sentinel
                return
            try:
                self._write_point(point)
                with self._lock:
                    observers = list(self._observers)
                for obs in observers:
                    try:
                        obs(point)
                    except Exception:
                        log.debug("series observer failed", exc_info=True)
            finally:
                with self._lock:
                    self._pending -= 1

    def _write_point(self, point: dict) -> None:
        if self._f is None:
            return
        line = json.dumps(point, separators=(",", ":"), default=str) + "\n"
        # the io lock EXISTS to serialize journal writes (writer thread vs
        # close); the scrape path never takes it — the queue decouples
        # them — so holding it across file I/O is the design, not a stall
        # hazard (the trace.py flush discipline). A write error costs the
        # point (counted in dropped), never the instrumented path.
        with self._io_lock:
            if self._closed:
                with self._lock:
                    self.dropped += 1
                return
            try:
                if self._written + len(line) > self._max_bytes:
                    self._rotate()  # graft-lint: disable=GL004
                self._written += len(line)
                self._f.write(line)  # graft-lint: disable=GL004
                self._f.flush()  # graft-lint: disable=GL004
            except OSError:
                with self._lock:
                    self.dropped += 1

    def _rotate(self) -> None:
        """Flight-recorder retention at the size cap (the trace.py scheme):
        the current journal becomes ``<proc>.0.jsonl`` and a fresh file
        starts — the NEWEST window survives, disk stays ~2x the cap."""
        try:
            self._f.close()
        except Exception:
            pass
        base, ext = os.path.splitext(self.path)
        os.replace(self.path, base + ".0" + ext)
        self._f = open(self.path, "w", encoding="utf-8")
        self._written = 0

    # --- lifecycle ------------------------------------------------------------

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Wait (bounded) until every enqueued point has been written and
        observed — shutdown calls this so a final scrape (and any SLO trip
        it causes) lands before the verdict files are read."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._pending == 0:
                    return True
            if self._stop.is_set() and not self._thread.is_alive():
                return False  # writer gone; waiting cannot help
            time.sleep(0.005)
        return False

    def close(self, join_timeout_s: float = 2.0) -> None:
        self.drain(timeout_s=join_timeout_s)
        self._stop.set()
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=max(join_timeout_s, 0.0))
        with self._io_lock:
            self._closed = True
            if self._f is not None:
                try:
                    self._f.close()
                except Exception:
                    pass


# --- process-global arming (the trace/hbm/health pattern) ---------------------

_recorder: SeriesRecorder | None = None


def active_recorder() -> SeriesRecorder | None:
    return _recorder


def install(recorder: SeriesRecorder) -> SeriesRecorder:
    global _recorder
    if _recorder is not None and _recorder is not recorder:
        _recorder.close()
    _recorder = recorder
    return recorder


def uninstall() -> None:
    global _recorder
    if _recorder is not None:
        _recorder.close()
        _recorder = None


def sample(**args: Any) -> None:
    """The hot-path seam (train/serve step loops). Disarmed: one global
    load + ``None`` compare. Call sites must pass precomputed names only
    (graft-lint GL005 enforces this like the trace/chaos/hbm/health hooks)."""
    r = _recorder
    if r is not None:
        r.sample(**args)


def install_from_env(proc: str = "") -> SeriesRecorder | None:
    """Arm this process from the ``TONY_OBS_SERIES*`` env the AM exported.
    Defaults apply standalone — a bare fit() or engine records to the
    in-memory ring (and feeds an armed SLO engine) without a job; under a
    job (TONY_APP_DIR) points journal to ``<app_dir>/series/<proc>.jsonl``.
    Idempotent; ``TONY_OBS_SERIES=0`` disables. Also wires the SLO engine
    (obs/slo.py) as an observer when ``TONY_SLO`` names active targets —
    ONE arming point for the live stack."""
    if _recorder is not None:
        return _recorder
    if os.environ.get(ENV_ENABLED, "") == "0":
        return None

    def _env_int(key: str, default: int) -> int:
        try:
            return int(os.environ.get(key, "") or default)
        except ValueError:
            return default

    from tony_tpu.obs import trace

    proc = trace.sanitize_proc(proc) if proc else trace.default_proc_name()
    app_dir = os.environ.get("TONY_APP_DIR", "")
    path = os.path.join(app_dir, "series", f"{proc}.jsonl") if app_dir else None
    try:
        recorder = install(SeriesRecorder(
            path, proc,
            sample_every=_env_int(ENV_SAMPLE, 16),
            max_journal_mb=_env_int(ENV_JOURNAL_MB, 16),
        ))
    except OSError:
        log.warning("could not open series journal under %s", app_dir,
                    exc_info=True)
        return None
    from tony_tpu.obs import slo

    slo.attach_from_env(recorder, proc=proc)
    return recorder


# --- read paths (tony top, portal, SLO forensics, tests) ----------------------


def read_series(series_dir: str,
                tail_bytes: int | None = None) -> dict[str, list[dict]]:
    """Per-process points under a ``series/`` dir (proc -> time-ordered
    points). Rotated windows (``<proc>.0.jsonl``) merge into the same
    process; torn trailing lines (a SIGKILLed writer) are skipped, not
    fatal. ONE reader for ``tony top``, ``/api/series``, and tests.

    ``tail_bytes`` bounds the read per file: seek that far from the end
    and drop the first (possibly partial) line. A live viewer redrawing
    every few seconds must not re-parse a journal sitting at its
    multi-MB rotation cap to render the last 120 points."""
    out: dict[str, list[dict]] = {}
    try:
        names = sorted(os.listdir(series_dir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        proc = name[:-len(".jsonl")]
        if proc.endswith(".0"):
            proc = proc[:-2]
        points = out.setdefault(proc, [])
        try:
            # binary mode: byte-offset seeks are only well-defined there,
            # and a partial UTF-8 sequence at the cut decodes leniently
            with open(os.path.join(series_dir, name), "rb") as f:
                if tail_bytes is not None:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    if size > tail_bytes:
                        f.seek(size - tail_bytes)
                        f.readline()  # drop the partial first line
                    else:
                        f.seek(0)
                for raw in f:
                    line = raw.decode("utf-8", errors="replace").strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail from a killed process
                    if isinstance(rec, dict):
                        points.append(rec)
        except OSError:
            continue
    for points in out.values():
        # two hosts' clocks can disagree; WITHIN a proc the journal is
        # append-ordered, so a stable sort on ts keeps skewed-but-ordered
        # windows intact instead of interleaving them wrongly
        points.sort(key=lambda p: float(p.get("ts", 0.0) or 0.0))
    return out


def freshness(app_dir: str, *, now: float | None = None) -> dict[str, dict]:
    """Per-proc journal freshness WITHOUT parsing the journals: file
    mtime is the last-write proxy (the writer flushes per point), size a
    rough volume signal. The fleet ``/api/series`` summary reads this —
    stat calls, not tens of MB of JSON per scrape."""
    now = time.time() if now is None else now
    out: dict[str, dict] = {}
    sdir = os.path.join(app_dir, "series")
    try:
        names = sorted(os.listdir(sdir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        proc = name[:-len(".jsonl")]
        if proc.endswith(".0"):
            proc = proc[:-2]
        try:
            st = os.stat(os.path.join(sdir, name))
        except OSError:
            continue
        rec = out.setdefault(proc, {"age_s": None, "bytes": 0})
        age = round(max(now - st.st_mtime, 0.0), 1)
        rec["age_s"] = age if rec["age_s"] is None else min(rec["age_s"], age)
        rec["bytes"] += st.st_size
    return out


def fleet_rollup(app_dir: str, *, tail: int = 120,
                 now: float | None = None) -> dict[str, Any]:
    """The app-level live view: per-proc series tails with explicit
    staleness. ``age_s`` is clamped at 0 — a clock-skewed host whose last
    point is "in the future" reports fresh, never a negative age (and
    never hides a genuinely stale sibling)."""
    now = time.time() if now is None else now
    # bounded per-file read: the ``tail`` newest points fit comfortably
    # in the tail window (points are small flat dicts), and a journal at
    # its multi-MB rotation cap must not be re-parsed per redraw
    procs = read_series(
        os.path.join(app_dir, "series"), tail_bytes=max(tail, 1) * 4096
    )
    out: dict[str, Any] = {"ts": now, "procs": {}}
    for proc, points in sorted(procs.items()):
        if not points:
            continue
        last = points[-1]
        last_ts = float(last.get("ts", 0.0) or 0.0)
        out["procs"][proc] = {
            "n": len(points),
            "last_ts": last_ts,
            "age_s": round(max(now - last_ts, 0.0), 1),
            "latest": {k: v for k, v in last.items() if k != "ts"},
            "points": points[-tail:],
        }
    return out


__all__ = [
    "ENV_ENABLED", "ENV_JOURNAL_MB", "ENV_SAMPLE", "SeriesRecorder",
    "active_recorder", "fleet_rollup", "freshness", "install",
    "install_from_env", "read_series", "sample", "uninstall",
]

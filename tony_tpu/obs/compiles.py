"""Compile ledger: an always-on journal of every XLA compile in a process.

analysis/sanitize.py proved the shape: a ``jax.monitoring`` duration
listener counting ``backend_compile`` events is cheap enough to leave
installed forever. This module grows that counter into a *ledger* — every
backend compile lands as an entry with its duration, wall time, and the
function name the instrumented call site attributed (the monitoring event
itself is anonymous, so attribution rides a thread-local :meth:`label`
scope the compile-ahead thread / engine wrap around their compiles).

AOT-compiled entry points (the train step via fit()'s compile-ahead, the
decode step via the serve engine, bench.py's measured sections) call
:func:`record_aot` with the compiled executable, which additionally
records ``memory_analysis()`` (temp/argument/output/code bytes — the
measured memory plan) and ``cost_analysis()`` FLOPs — the numbers bench
MFU and the gqa_capacity slot budget are derived from, replacing hand
formulas.

Each process snapshots its ledger to ``<app_dir>/compiles/<proc>.json``
at fit()/engine shutdown (and inside the OOM forensics dump);
``tony compiles <app_id>`` merges them into one report.

The sanitize watchdog's ``compile_count()`` now reads this ledger's
counter, so one listener serves both the budget check and the journal.

jax is imported lazily: only :func:`get_ledger` needs it, and the CLI
read path (:func:`read_app_ledgers`) must work in processes without a
device.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Any

BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def aot_analysis(compiled) -> dict[str, Any]:
    """memory_analysis + cost_analysis of a compiled executable as plain
    numbers; parts a backend doesn't expose are simply absent."""
    out: dict[str, Any] = {}
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            out.update(
                temp_bytes=int(ma.temp_size_in_bytes),
                argument_bytes=int(ma.argument_size_in_bytes),
                output_bytes=int(ma.output_size_in_bytes),
                alias_bytes=int(ma.alias_size_in_bytes),
                generated_code_bytes=int(ma.generated_code_size_in_bytes),
            )
    except Exception:
        pass
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            for key, name in (("flops", "flops"),
                              ("bytes accessed", "bytes_accessed")):
                if key in ca:
                    out[name] = float(ca[key])
    except Exception:
        pass
    return out


class CompileLedger:
    """Bounded in-memory journal + monotonic compile counter."""

    def __init__(self, max_entries: int = 2048):
        self._entries: deque = deque(maxlen=max(int(max_entries), 64))
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.backend_compiles = 0  # monotonic, never trimmed with the deque

    # --- attribution ----------------------------------------------------------

    @contextlib.contextmanager
    def label(self, name: str):
        """Attribute backend-compile events fired on THIS thread inside the
        block to ``name`` (jax's monitoring event carries no function name;
        the call site that triggers the compile knows it)."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(name)
        try:
            yield
        finally:
            stack.pop()

    def _current_label(self) -> str:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else ""

    # --- recording ------------------------------------------------------------

    def on_event(self, event: str, duration: float) -> None:
        if event != BACKEND_COMPILE_EVENT:
            return
        entry = {
            "ts": time.time(),
            "kind": "backend",
            "fn": self._current_label(),
            "dur_s": round(float(duration), 4),
        }
        with self._lock:
            self.backend_compiles += 1
            self._entries.append(entry)

    def record_aot(self, fn: str, compiled, dur_s: float = 0.0) -> dict:
        """Journal an ahead-of-time compile with its measured memory plan,
        FLOPs, and the collective set extracted from its optimized HLO
        (obs/comms.py: op kind, payload bytes, replica groups — what the
        step-anatomy report pairs measured device-trace time against);
        returns the entry (bench reuses the numbers)."""
        entry = {
            "ts": time.time(),
            "kind": "aot",
            "fn": fn,
            "dur_s": round(float(dur_s), 4),
            **aot_analysis(compiled),
        }
        try:
            from tony_tpu.obs.comms import extract_collectives

            colls = extract_collectives(compiled)
            if colls:
                entry["collectives"] = colls
        except Exception:
            pass
        with self._lock:
            self._entries.append(entry)
        return entry

    # --- reading --------------------------------------------------------------

    def entries(self, kind: str = "") -> list[dict]:
        with self._lock:
            snap = list(self._entries)
        if kind:
            snap = [e for e in snap if e.get("kind") == kind]
        return snap

    def to_dict(self) -> dict:
        return {
            "backend_compiles": self.backend_compiles,
            "entries": self.entries(),
        }


# --- process-global ledger ---------------------------------------------------

_ledger: CompileLedger | None = None
_install_lock = threading.Lock()


def get_ledger() -> CompileLedger:
    """The process ledger; first call installs the (permanent, cheap)
    monitoring listener — jax.monitoring has no per-listener removal, so
    it registers exactly once and watchdogs compare counter snapshots."""
    global _ledger
    if _ledger is not None:
        return _ledger
    with _install_lock:
        if _ledger is None:
            ledger = CompileLedger()
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                lambda event, duration, **_kw: ledger.on_event(event, duration)
            )
            _ledger = ledger
    return _ledger


def snapshot_to_app_dir(proc: str = "",
                        ledger: CompileLedger | None = None) -> str:
    """Atomically journal the ledger under the job's app dir when running
    inside a tony-tpu job (TONY_APP_DIR); returns the path ('' outside).
    The ledger is process-scoped, so the snapshot carries the bare proc
    name — a train-then-serve process overwrites its own file with a
    superset, never another component's."""
    app_dir = os.environ.get("TONY_APP_DIR", "")
    if not app_dir:
        return ""
    from tony_tpu.obs.trace import default_proc_name, sanitize_proc

    proc = sanitize_proc(proc) if proc else default_proc_name()
    led = ledger if ledger is not None else _ledger
    if led is None:
        return ""
    path = os.path.join(app_dir, "compiles", f"{proc}.json")
    payload = {"proc": proc, **led.to_dict()}
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".tmp", "w", encoding="utf-8") as f:
            json.dump(payload, f, default=str)
        os.replace(path + ".tmp", path)
    except OSError:
        return ""
    return path


def read_app_ledgers(app_dir: str) -> dict[str, dict]:
    """Every process's ledger snapshot under an app dir (``tony compiles``
    and the portal read path); proc name -> payload."""
    cdir = os.path.join(app_dir, "compiles")
    out: dict[str, dict] = {}
    if not os.path.isdir(cdir):
        return out
    for name in sorted(os.listdir(cdir)):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(cdir, name), encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(payload, dict):
            out[payload.get("proc") or name[:-5]] = payload
    return out


def summarize(ledgers: dict[str, dict]) -> dict:
    """The ``tony compiles`` report: per-process counts/durations plus the
    AOT entries with their measured memory plans."""
    procs = {}
    for proc, payload in sorted(ledgers.items()):
        entries = payload.get("entries", []) or []
        backend = [e for e in entries if e.get("kind") == "backend"]
        aot = [e for e in entries if e.get("kind") == "aot"]
        procs[proc] = {
            "backend_compiles": payload.get("backend_compiles", len(backend)),
            "compile_time_s": round(
                sum(float(e.get("dur_s", 0.0)) for e in backend), 3
            ),
            "aot_entry_points": aot,
            "entries": entries,
        }
    return {
        "processes": procs,
        "total_backend_compiles": sum(
            p["backend_compiles"] for p in procs.values()
        ),
    }


__all__ = [
    "BACKEND_COMPILE_EVENT", "CompileLedger", "aot_analysis", "get_ledger",
    "read_app_ledgers", "snapshot_to_app_dir", "summarize",
]

"""Comms ledger: what the compiled program moves over ICI/DCN.

Every parallelism decision this repo will ever prove (Megatron-style
scaling, arXiv:2104.04473) comes down to the compute/collective split of
the step — so the collective set must be a first-class, always-derivable
fact, not something eyeballed out of an HLO dump. This module extracts it
from the artifacts the compile ledger (obs/compiles.py) already holds:
every AOT-compiled entry point's optimized HLO names its collectives with
their result shapes and replica groups, and :func:`extract_collectives`
turns that text into rows — op name, kind, payload bytes, replica groups.

The rows pair with *measured* time two ways:

- the anatomy report (obs/anatomy.py) matches captured device-trace events
  to the rows BY OP NAME (XLA names its trace events after the HLO ops —
  ``all-reduce.1`` in the HLO is ``all-reduce.1`` on the timeline), giving
  achieved bandwidth per collective and the compute-overlap fraction;
- ``cost_analysis()`` bytes ride the ledger entry for a static
  cross-check.

Stdlib-only on purpose: the extraction runs in the process that compiled
(duck-typed ``compiled.as_text()``), and the read paths run in deviceless
CLI processes on ledger snapshots.
"""

from __future__ import annotations

import re
from typing import Any

# HLO op kinds that move data between participants. Async forms
# (``all-reduce-start`` / ``-done``) normalise onto the base kind; the
# ``-done`` half is skipped (same transfer, already counted at start).
COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "ragged-all-to-all",
    "collective-permute",
    "collective-broadcast",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "f8e4m3fnuz": 1, "f8e5m2fnuz": 1, "s4": 1, "u4": 1,
}

# `%all-reduce.1 = f32[1,128]{1,0} all-reduce(...), channel_id=1, ...`
# and the tuple-result / ROOT / async-start variants
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<type>\([^)]*\)|[\w\[\],]+(?:\{[^}]*\})?)\s*"
    r"(?P<op>[\w\-]+)\("
)
_SHAPE_RE = re.compile(r"(?P<dtype>[a-z][a-z0-9]*)\[(?P<dims>[0-9,]*)\]")
_GROUPS_RE = re.compile(
    r"replica_groups=(?P<g>\{\{[^}]*(?:\},\{[^}]*)*\}\}"
    r"|\[[^\]]*\](?:<=\[[^\]]*\])?)"
)


def _kind_of(op: str) -> str | None:
    """Normalised collective kind of an HLO opcode ('' for -done halves,
    None for non-collectives)."""
    base = op
    for suffix in ("-start", "-done"):
        if base.endswith(suffix):
            base = base[: -len(suffix)]
            if base in COLLECTIVE_KINDS:
                return "" if suffix == "-done" else base
            return None
    return base if base in COLLECTIVE_KINDS else None


def shape_bytes(type_text: str) -> int:
    """Total byte size of an HLO result type ('f32[1,128]{1,0}' or a
    tuple '(f32[...], u32[...])'); unknown dtypes count 0 rather than
    guessing."""
    total = 0
    for m in _SHAPE_RE.finditer(type_text):
        size = _DTYPE_BYTES.get(m.group("dtype"))
        if size is None:
            continue
        n = 1
        dims = m.group("dims")
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * size
    return total


def _parse_groups(raw: str) -> list[list[int]] | str:
    """``{{0,1},{2,3}}`` parses to [[0,1],[2,3]]; the iota form
    (``[2,2]<=[4]``) stays a string — it is already compact and exact."""
    if not raw.startswith("{{"):
        return raw
    try:
        return [
            [int(x) for x in grp.split(",") if x != ""]
            for grp in re.findall(r"\{([0-9,\s]*)\}", raw[1:-1])
        ]
    except ValueError:
        return raw


def extract_collectives(compiled: Any) -> list[dict[str, Any]]:
    """Collective rows of one compiled executable (or raw HLO text):
    ``{"name", "kind", "bytes", "result_type", "replica_groups"}`` per
    static HLO op, in program order. ``bytes`` is the result payload —
    for an all-gather that is the post-gather size, for a reduce-scatter
    the post-scatter shard; the per-kind wire cost model lives with the
    bandwidth math in obs/anatomy.py, not here."""
    if isinstance(compiled, str):
        text = compiled
    else:
        try:
            text = compiled.as_text()
        except Exception:
            return []
    rows: list[dict[str, Any]] = []
    for line in text.splitlines():
        if "(" not in line or "=" not in line:
            continue
        m = _OP_RE.match(line)
        if m is None:
            continue
        kind = _kind_of(m.group("op"))
        if not kind:  # None (not a collective) or '' (-done half)
            continue
        gm = _GROUPS_RE.search(line)
        rows.append({
            "name": m.group("name"),
            "kind": kind,
            "bytes": shape_bytes(m.group("type")),
            "result_type": m.group("type"),
            "replica_groups": _parse_groups(gm.group("g")) if gm else "",
        })
    return rows


__all__ = ["COLLECTIVE_KINDS", "extract_collectives", "shape_bytes"]

"""Job-history portal: the tony-portal analogue.

The reference ships a Play-framework web UI that scans the finished-jobs
HDFS dir, parses avro .jhist files, and renders jobs / per-job config /
events / metrics pages (SURVEY.md sections 2 "tony-portal", 3.5). Here the
same read path is a stdlib ThreadingHTTPServer over the apps root: each
application dir carries status.json, config.json, events/*.jhist.jsonl and
logs/ — everything the portal needs, no database.

Endpoints:
    /                    jobs table (HTML)
    /job/<app_id>        job detail: status, tasks, config, events (HTML)
    /job/<app_id>/log/<task>   task log (text)
    /api/jobs            jobs list (JSON)
    /api/job/<app_id>    full detail (JSON)
    /api/serve           fleet gang-serving rollup: per-app request /
                         replay / rejection counts from the frontend
                         ledgers under <app_dir>/serve/ (JSON)
    /api/serve/<app_id>  one app's serving rollup (JSON)
    /api/series          fleet live-series summary: per-app proc/task
                         freshness off the series journals + AM rollup
    /api/series/<app_id> one app's live series (obs/series.py journals +
                         the AM's heartbeat-path rollup), every proc and
                         task labelled with its age_s staleness
    /metrics             Prometheus text exposition over every app's
                         registry snapshots (step time / TTFT / TPOT
                         histograms etc., labelled app= and proc=), plus
                         the portal's own LIVE registry (request counts,
                         chart drops) and a tony_snapshot_age_seconds
                         gauge per snapshot — a dead host's frozen
                         metrics are visibly stale, not current
    /healthz             numerics-health verdicts for every app (JSON;
                         obs/health.py rollup)
    /healthz/<app_id>    one app's verdict rollup — HTTP 200 healthy/
                         unknown, 503 tripped (probe-friendly)

Run:  python -m tony_tpu.obs.portal --port 8080 [--apps-root DIR]
"""

from __future__ import annotations

import argparse
import html
import json
import os
import re
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tony_tpu.am.events import read_history
from tony_tpu.cli.client import default_apps_root

_APP_ID_RE = re.compile(r"^[\w.-]+$")  # path-traversal guard


def _read_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class PortalData:
    """Filesystem read layer (kept separate from HTTP for tests)."""

    def __init__(self, apps_root: str):
        from tony_tpu.obs.registry import Registry

        self.apps_root = apps_root
        # the portal's own metrics, served by /metrics next to the app
        # snapshots: hidden NaNs are what the health sentinel hunts, so a
        # chart filter may drop them from a polyline but must COUNT them
        self.registry = Registry()
        self.nonfinite_dropped = self.registry.counter(
            "tony_portal_nonfinite_dropped",
            "non-finite metric samples excluded from portal charts "
            "(counted, never silently hidden)",
        )
        # render-idempotent accounting: each distinct non-finite sample
        # counts ONCE, however many times its page is re-rendered — the
        # counter must track NaN production, not page views
        self._drop_seen: set[tuple] = set()

    # the full route vocabulary: labels stay bounded however hostile the
    # traffic (a crawler probing /wp-login must not mint counter children)
    _ROUTES = frozenset({"/", "job", "api", "metrics", "healthz"})

    def count_request(self, route: str) -> None:
        """The live half of /metrics: requests served by THIS portal
        process, labelled by NORMALIZED top-level route — proof a scrape
        is hitting a live process, next to the snapshot-derived (and
        staleness-labelled) per-app series."""
        route = route or "/"
        self.registry.counter(
            "tony_portal_requests_total",
            "HTTP requests served by this portal process",
            route=route if route in self._ROUTES else "other",
        ).inc()

    def jobs(self) -> list[dict]:
        out = []
        if not os.path.isdir(self.apps_root):
            return out
        for app_id in sorted(os.listdir(self.apps_root), reverse=True):
            app_dir = os.path.join(self.apps_root, app_id)
            if not os.path.isdir(app_dir):
                continue
            status = _read_json(os.path.join(app_dir, "status.json"))
            config = _read_json(os.path.join(app_dir, "config.json")) or {}
            out.append(
                {
                    "app_id": app_id,
                    "state": (status or {}).get("state", "RUNNING?"),
                    "exit_code": (status or {}).get("exit_code", ""),
                    "framework": config.get("application.framework", ""),
                    "name": config.get("application.name", ""),
                }
            )
        return out

    def job(self, app_id: str) -> dict | None:
        if not _APP_ID_RE.match(app_id):
            return None
        app_dir = os.path.join(self.apps_root, app_id)
        if not os.path.isdir(app_dir):
            return None
        events = []
        ev_dir = os.path.join(app_dir, "events")
        if os.path.isdir(ev_dir):
            for name in sorted(os.listdir(ev_dir)):
                if name.endswith(".jsonl"):
                    try:
                        events.extend(read_history(os.path.join(ev_dir, name)))
                    except (OSError, json.JSONDecodeError):
                        pass
        logs = []
        logs_dir = os.path.join(app_dir, "logs")
        if os.path.isdir(logs_dir):
            logs = sorted(os.listdir(logs_dir))
        self.count_drops(app_id, events)
        return {
            "app_id": app_id,
            "status": _read_json(os.path.join(app_dir, "status.json")),
            "config": _read_json(os.path.join(app_dir, "config.json")),
            "events": events,
            "logs": logs,
        }

    def count_drops(self, app_id: str, events: list[dict]) -> None:
        """Count each distinct non-finite metric sample into
        ``tony_portal_nonfinite_dropped`` exactly once (the journal is
        append-only, so the event index is a stable identity)."""
        import math

        for i, e in enumerate(events):
            if e.get("type") != "METRICS" or not isinstance(
                e.get("samples"), dict
            ):
                continue
            for name, value in e["samples"].items():
                # only floats can be non-finite; bools/ints never are
                if isinstance(value, float) and not math.isfinite(value):
                    key = (app_id, i, str(e.get("task", "")), name)
                    if key not in self._drop_seen:
                        self._drop_seen.add(key)
                        self.nonfinite_dropped.inc()

    def log(self, app_id: str, name: str) -> str | None:
        if not _APP_ID_RE.match(app_id) or os.sep in name or name.startswith("."):
            return None
        path = os.path.join(self.apps_root, app_id, "logs", name)
        try:
            with open(path, errors="replace") as f:
                return f.read()
        except OSError:
            return None

    def metric_snapshots(self) -> list[tuple[dict, list[dict]]]:
        """Every registry snapshot under every app's ``metrics/`` dir, as
        (extra-labels, entries) pairs for registry.render_snapshots — the
        fit()/engine/AM shutdown snapshots become one fleet-wide scrape.

        Each snapshot additionally carries a synthetic
        ``tony_snapshot_age_seconds`` gauge (file mtime age): every series
        derived from that snapshot is thereby staleness-labelled — a dead
        host's frozen histogram scrapes as N-seconds-old data, never as a
        current reading."""
        out: list[tuple[dict, list[dict]]] = []
        if not os.path.isdir(self.apps_root):
            return out
        now = time.time()
        for app_id in sorted(os.listdir(self.apps_root)):
            mdir = os.path.join(self.apps_root, app_id, "metrics")
            if not os.path.isdir(mdir):
                continue
            for name in sorted(os.listdir(mdir)):
                if not name.endswith(".json"):
                    continue
                path = os.path.join(mdir, name)
                snap = _read_json(path)
                if not isinstance(snap, dict):
                    continue
                entries = snap.get("metrics")
                if isinstance(entries, list):
                    try:
                        age = max(now - os.path.getmtime(path), 0.0)
                    except OSError:
                        age = 0.0
                    entries = list(entries) + [{
                        "kind": "gauge",
                        "name": "tony_snapshot_age_seconds",
                        "help": "age of the registry snapshot these "
                                "app/proc series were rendered from "
                                "(stale = a process that stopped writing)",
                        "labels": {},
                        "value": round(age, 1),
                    }]
                    out.append((
                        {"app": app_id, "proc": snap.get("proc", name[:-5])},
                        entries,
                    ))
        return out

    def series_rollup(self, app_id: str) -> dict | None:
        """One app's live series: the per-proc journal rollup
        (obs/series.py ``fleet_rollup``) merged with the AM's heartbeat-
        path rollup — every proc/task labelled with its ``age_s``. None
        for unknown app ids."""
        from tony_tpu.obs.series import fleet_rollup

        if not _APP_ID_RE.match(app_id):
            return None
        app_dir = os.path.join(self.apps_root, app_id)
        if not os.path.isdir(app_dir):
            return None
        roll = fleet_rollup(app_dir)
        out = {"app_id": app_id, "procs": roll["procs"]}
        am_roll = _read_json(os.path.join(app_dir, "series", "am_rollup.json"))
        if isinstance(am_roll, dict):
            # re-label staleness against NOW, not the AM's write time: a
            # dead AM leaves a frozen rollup whose embedded ages lie
            now = time.time()
            tasks = {}
            for tid, rec in (am_roll.get("tasks") or {}).items():
                rec = dict(rec or {})
                last = float(rec.get("last_ts", 0.0) or 0.0)
                rec["age_s"] = round(max(now - last, 0.0), 1)
                tasks[tid] = rec
            out["am_rollup"] = {
                "rollup_age_s": round(
                    max(now - float(am_roll.get("ts", 0.0) or 0.0), 0.0), 1
                ),
                "tasks": tasks,
            }
        return out

    def series_summaries(self) -> dict[str, dict]:
        """Fleet ``/api/series`` view: per-app proc freshness from journal
        mtimes (stat calls only — the fleet scrape must NOT parse every
        journal; the per-app ``/api/series/<id>`` endpoint does the full
        read). Apps with neither journals nor an AM rollup are omitted."""
        from tony_tpu.obs.series import freshness

        out: dict[str, dict] = {}
        if not os.path.isdir(self.apps_root):
            return out
        now = time.time()
        for app_id in sorted(os.listdir(self.apps_root)):
            if not _APP_ID_RE.match(app_id):
                continue
            app_dir = os.path.join(self.apps_root, app_id)
            procs: dict[str, dict] = dict(freshness(app_dir, now=now))
            am_roll = _read_json(
                os.path.join(app_dir, "series", "am_rollup.json")
            )
            if isinstance(am_roll, dict):
                for tid, rec in (am_roll.get("tasks") or {}).items():
                    last = float((rec or {}).get("last_ts", 0.0) or 0.0)
                    procs.setdefault(
                        tid, {"age_s": round(max(now - last, 0.0), 1)}
                    )
            if procs:
                out[app_id] = {"procs": procs}
        return out

    def prometheus(self) -> str:
        from tony_tpu.obs.registry import render_snapshots

        return render_snapshots(
            [({"proc": "portal"}, self.registry.snapshot())]
            + self.metric_snapshots()
        )

    def serve_summary(self, app_id: str) -> dict | None:
        """Roll up one app's gang-serving ledgers (serve/frontend.py
        writes ``<app_dir>/serve/requests_*.json``): request counts by
        finish reason, replays, rejected, worst TTFT — the fleet view of
        the no-request-lost contract. None for unknown app ids, a zeroed
        summary for jobs that never served."""
        if not _APP_ID_RE.match(app_id):
            return None
        app_dir = os.path.join(self.apps_root, app_id)
        if not os.path.isdir(app_dir):
            return None
        out = {
            "app_id": app_id, "requests": 0, "finished": 0, "errors": 0,
            "replays": 0, "rejected": 0, "pending": 0, "ttft_max_s": 0.0,
            "ledgers": [],
            # disaggregated-gang handoff rollup (zeros for classic gangs)
            "handoffs": 0, "handoff_failures": 0,
            "handoff_blocks_shipped": 0, "handoff_bytes": 0,
        }
        serve_dir = os.path.join(app_dir, "serve")
        if not os.path.isdir(serve_dir):
            return out
        for name in sorted(os.listdir(serve_dir)):
            if not (name.startswith("requests_") and name.endswith(".json")):
                continue
            ledger = _read_json(os.path.join(serve_dir, name))
            if not isinstance(ledger, dict):
                continue
            out["ledgers"].append(name)
            out["rejected"] += int(ledger.get("rejected", 0))
            out["pending"] += len(ledger.get("pending", []))
            for entry in ledger.get("requests", []):
                out["requests"] += 1
                reason = entry.get("finish_reason", "")
                if reason in ("eos", "length"):
                    out["finished"] += 1
                elif reason in ("rejected", "draining"):
                    # explicit backpressure — the invariant checker does
                    # not count these as losses, so neither does the fleet
                    # view (a clean job must not chart as erroring)
                    out["rejected"] += 1
                else:
                    out["errors"] += 1
                out["replays"] += int(entry.get("replays", 0))
                out["ttft_max_s"] = max(
                    out["ttft_max_s"], float(entry.get("ttft_s", 0.0))
                )
            for h in ledger.get("handoffs", []):
                if h.get("ok"):
                    out["handoffs"] += 1
                else:
                    out["handoff_failures"] += 1
                out["handoff_blocks_shipped"] += int(h.get("shipped", 0))
                out["handoff_bytes"] += int(h.get("bytes", 0))
        return out

    def serve_summaries(self) -> dict[str, dict]:
        """Per-app serving rollups for the fleet ``/api/serve`` view
        (apps without ledgers are omitted — most jobs train)."""
        out: dict[str, dict] = {}
        if not os.path.isdir(self.apps_root):
            return out
        for app_id in sorted(os.listdir(self.apps_root)):
            s = self.serve_summary(app_id)
            if s is not None and s["ledgers"]:
                out[app_id] = s
        return out

    def health(self, app_id: str) -> dict | None:
        """One app's numerics-health rollup (verdicts + bundle listing,
        obs/health.py layout); None for unknown/invalid app ids."""
        from tony_tpu.obs.health import rollup

        if not _APP_ID_RE.match(app_id):
            return None
        app_dir = os.path.join(self.apps_root, app_id)
        if not os.path.isdir(app_dir):
            return None
        return {"app_id": app_id, **rollup(app_dir)}

    def healths(self) -> dict[str, dict]:
        """Per-app verdict map for the fleet-wide /healthz view. Apps that
        never armed a sentinel report ``unknown`` rather than vanishing —
        absence of a verdict is itself information."""
        out: dict[str, dict] = {}
        if not os.path.isdir(self.apps_root):
            return out
        for app_id in sorted(os.listdir(self.apps_root)):
            if not os.path.isdir(os.path.join(self.apps_root, app_id)):
                continue
            h = self.health(app_id)
            if h is not None:
                out[app_id] = {
                    "verdict": h["verdict"],
                    "rules": h["rules"],
                    "bundles": len(h["bundles"]),
                }
        return out


_PAGE = """<!doctype html><html><head><title>tony-tpu portal</title><style>
body {{ font-family: monospace; margin: 2em; }} table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #999; padding: 4px 10px; text-align: left; }}
.SUCCEEDED {{ color: #080 }} .FAILED {{ color: #b00 }} .KILLED {{ color: #b60 }}
pre {{ background: #f4f4f4; padding: 1em; overflow-x: auto; }}
</style></head><body>{body}</body></html>"""


def _jobs_html(jobs: list[dict]) -> str:
    rows = "".join(
        f"<tr><td><a href='/job/{html.escape(j['app_id'])}'>{html.escape(j['app_id'])}</a></td>"
        f"<td class='{html.escape(str(j['state']))}'>{html.escape(str(j['state']))}</td>"
        f"<td>{html.escape(str(j['exit_code']))}</td>"
        f"<td>{html.escape(str(j['framework']))}</td></tr>"
        for j in jobs
    )
    return _PAGE.format(
        body=f"<h1>tony-tpu jobs</h1><table><tr><th>application</th><th>state</th>"
        f"<th>exit</th><th>framework</th></tr>{rows}</table>"
    )


def _latest_metrics(events: list[dict]) -> dict[str, dict]:
    """task -> latest METRICS samples (the portal's utilisation view; the
    reference charts the utilisation embedded in its history events the same
    way, SURVEY.md section 3.5)."""
    latest: dict[str, dict] = {}
    for e in events:
        if e.get("type") == "METRICS" and isinstance(e.get("samples"), dict):
            latest[str(e.get("task", "?"))] = e["samples"]
    return latest


def _metric_series(events: list[dict]) -> dict[str, dict[str, list[float]]]:
    """task -> metric name -> time-ordered values (for the charts)."""
    import math

    series: dict[str, dict[str, list[float]]] = {}
    for e in events:
        if e.get("type") == "METRICS" and isinstance(e.get("samples"), dict):
            per_task = series.setdefault(str(e.get("task", "?")), {})
            for name, value in e["samples"].items():
                if not isinstance(value, (int, float)) or isinstance(value, bool):
                    continue  # bools would chart as 0/1
                # NaN/Inf (a diverged loss — the moment the operator opens
                # this page) would poison the polyline's min/max into an
                # invisible chart: excluded from the line, but COUNTED
                # once per distinct sample by PortalData.count_drops —
                # hidden NaNs are precisely what the health sentinel hunts
                if not math.isfinite(value):
                    continue
                per_task.setdefault(name, []).append(float(value))
    return series


def _sparkline(values: list[float], w: int = 160, h: int = 28) -> str:
    """Inline SVG polyline — the portal's metrics chart (the reference
    renders utilisation charts from its history events the same way)."""
    if len(values) < 2:
        return ""
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    pts = " ".join(
        f"{2 + i * (w - 4) / (len(values) - 1):.1f},"
        f"{h - 2 - (v - lo) / span * (h - 4):.1f}"
        for i, v in enumerate(values)
    )
    return (
        f"<svg width='{w}' height='{h}' viewBox='0 0 {w} {h}'>"
        f"<polyline points='{pts}' fill='none' stroke='#36c' stroke-width='1.5'/>"
        f"</svg>"
    )


def _charts_html(series: dict[str, dict[str, list[float]]]) -> str:
    chart_metrics = ["tokens_per_sec", "mfu", "loss", "rss_mb", "hbm_mb"]
    rows = ""
    for task in sorted(series):
        cells = ""
        for m in chart_metrics:
            values = series[task].get(m, [])
            svg = _sparkline(values)
            if svg:
                cells += (
                    f"<td>{html.escape(m)}<br>{svg}<br>"
                    f"<small>{_fmt_num(values[0])} → {_fmt_num(values[-1])}"
                    f"</small></td>"
                )
        if cells:
            rows += f"<tr><td>{html.escape(task)}</td>{cells}</tr>"
    if not rows:
        return ""
    return f"<table>{rows}</table>"


def _metrics_html(metrics: dict[str, dict]) -> str:
    if not metrics:
        return "<p>(no metrics reported)</p>"
    # stable column order: the headline numbers first, then the rest
    preferred = ["step", "loss", "tokens_per_sec", "tokens_per_sec_per_chip",
                 "mfu", "grad_norm", "cpu_percent", "rss_mb", "hbm_mb",
                 "hbm_peak_mb"]
    seen = {k for samples in metrics.values() for k in samples}
    cols = [c for c in preferred if c in seen]
    cols += sorted(seen - set(cols))
    head = "".join(f"<th>{html.escape(c)}</th>" for c in cols)
    rows = ""
    for task in sorted(metrics):
        cells = "".join(
            f"<td>{_fmt_num(metrics[task].get(c))}</td>" for c in cols
        )
        rows += f"<tr><td>{html.escape(task)}</td>{cells}</tr>"
    return f"<table><tr><th>task</th>{head}</tr>{rows}</table>"


def _fmt_num(v) -> str:
    if v is None:
        return ""
    if isinstance(v, float):
        return f"{v:.4g}"
    return html.escape(str(v))


def _job_html(detail: dict) -> str:
    app_id = html.escape(detail["app_id"])
    status = detail["status"] or {}
    tasks = "".join(
        f"<tr><td>{html.escape(t['task'])}</td><td class='{html.escape(t['state'])}'>"
        f"{html.escape(t['state'])}</td><td>{t.get('exit_code')}</td>"
        f"<td>{t.get('attempts')}</td></tr>"
        for t in status.get("tasks", [])
    )
    logs = "".join(
        f"<li><a href='/job/{app_id}/log/{html.escape(n)}'>{html.escape(n)}</a></li>"
        for n in detail["logs"]
    )
    events = html.escape(
        "\n".join(json.dumps(e, sort_keys=True) for e in detail["events"])
    )
    config = html.escape(json.dumps(detail["config"] or {}, indent=1, sort_keys=True))
    return _PAGE.format(
        body=f"<h1>{app_id}</h1>"
        f"<p>state: <b class='{html.escape(str(status.get('state')))}'>"
        f"{html.escape(str(status.get('state', 'RUNNING?')))}</b>"
        f" exit={status.get('exit_code')}</p>"
        f"<h2>tasks</h2><table><tr><th>task</th><th>state</th><th>exit</th>"
        f"<th>attempts</th></tr>{tasks}</table>"
        f"<h2>metrics</h2>{_metrics_html(_latest_metrics(detail['events']))}"
        f"{_charts_html(_metric_series(detail['events']))}"
        f"<h2>logs</h2><ul>{logs}</ul>"
        f"<h2>events</h2><pre>{events}</pre>"
        f"<h2>config</h2><pre>{config}</pre>"
        f"<p><a href='/'>&larr; all jobs</a></p>"
    )


def make_handler(data: PortalData):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _send(self, code: int, body: str, ctype: str = "text/html") -> None:
            raw = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", f"{ctype}; charset=utf-8")
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def do_GET(self):  # noqa: N802 (stdlib casing)
            parts = [p for p in self.path.split("/") if p]
            data.count_request(parts[0] if parts else "/")
            if not parts:
                return self._send(200, _jobs_html(data.jobs()))
            if parts[0] == "metrics" and len(parts) == 1:
                return self._send(
                    200, data.prometheus(), "text/plain; version=0.0.4"
                )
            if parts[0] == "healthz":
                if len(parts) == 1:
                    return self._send(
                        200, json.dumps(data.healths()), "application/json"
                    )
                if len(parts) == 2:
                    h = data.health(parts[1])
                    if h is None:
                        return self._send(404, "{}", "application/json")
                    # probe semantics: a tripped verdict is a 503, so a
                    # plain HTTP check (k8s-style) needs no JSON parsing
                    code = 503 if h["verdict"] == "tripped" else 200
                    return self._send(code, json.dumps(h), "application/json")
                return self._send(404, "{}", "application/json")
            if parts[0] == "api":
                if len(parts) == 2 and parts[1] == "jobs":
                    return self._send(200, json.dumps(data.jobs()), "application/json")
                if len(parts) == 2 and parts[1] == "serve":
                    return self._send(
                        200, json.dumps(data.serve_summaries()), "application/json"
                    )
                if len(parts) == 3 and parts[1] == "serve":
                    s = data.serve_summary(parts[2])
                    if s is not None:
                        return self._send(200, json.dumps(s), "application/json")
                    return self._send(404, "{}", "application/json")
                if len(parts) == 2 and parts[1] == "series":
                    return self._send(
                        200, json.dumps(data.series_summaries()),
                        "application/json",
                    )
                if len(parts) == 3 and parts[1] == "series":
                    s = data.series_rollup(parts[2])
                    if s is not None:
                        return self._send(200, json.dumps(s), "application/json")
                    return self._send(404, "{}", "application/json")
                if len(parts) == 3 and parts[1] == "job":
                    detail = data.job(parts[2])
                    if detail is not None:
                        return self._send(200, json.dumps(detail), "application/json")
                return self._send(404, "{}", "application/json")
            if parts[0] == "job" and len(parts) >= 2:
                detail = data.job(parts[1])
                if detail is None:
                    return self._send(404, _PAGE.format(body="<h1>not found</h1>"))
                if len(parts) == 4 and parts[2] == "log":
                    text = data.log(parts[1], parts[3])
                    if text is None:
                        return self._send(404, "not found", "text/plain")
                    return self._send(200, text, "text/plain")
                return self._send(200, _job_html(detail))
            return self._send(404, _PAGE.format(body="<h1>not found</h1>"))

    return Handler


def serve_portal(apps_root: str, port: int = 0, host: str = "127.0.0.1"):
    """Start the portal; returns (server, bound_port). server.serve_forever().

    A configured (non-ephemeral) port goes through the bounded
    bind-with-retry (utils/net.py): a portal restart racing its
    predecessor's TIME_WAIT socket retries briefly instead of crashing or
    silently landing elsewhere.
    """
    from tony_tpu.utils.net import bind_with_retry

    handler = make_handler(PortalData(apps_root))
    servers: list[ThreadingHTTPServer] = []

    def _bind(p: int) -> int:
        servers.append(ThreadingHTTPServer((host, p), handler))
        return servers[-1].server_address[1]

    bound = bind_with_retry(_bind, port, attempts=8)
    return servers[-1], bound


def main() -> None:
    from tony_tpu.config.config import TonyConfig
    from tony_tpu.config.keys import Keys

    p = argparse.ArgumentParser(description="tony-tpu job-history portal")
    p.add_argument(
        "--port", type=int,
        default=TonyConfig(read_env=True).get_int(Keys.PORTAL_PORT, 8080),
        help="defaults to the portal.port config key",
    )
    p.add_argument("--apps-root", default=default_apps_root())
    p.add_argument(
        "--host", default="127.0.0.1",
        help="bind address; 0.0.0.0 exposes the portal (job configs + logs) "
             "to the network — opt in deliberately",
    )
    args = p.parse_args()
    server, port = serve_portal(args.apps_root, args.port, host=args.host)
    print(f"portal serving {args.apps_root} on :{port}")
    server.serve_forever()


if __name__ == "__main__":
    main()

"""Benchmark: Llama train-step throughput on the local chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s/chip", "vs_baseline": N}

The reference publishes no throughput numbers (BASELINE.md: "published": {});
the driver's north star is tokens/sec/chip and >= 45% MFU, so ``vs_baseline``
reports achieved MFU / 0.45 (1.0 = the north-star target). MFU is computed
from the compiled step's measured ``cost_analysis()`` FLOPs (the hand
formula rides along as ``mfu_formula`` with the ratio reported), and every
section runs under a phase-scoped ``HbmWatch`` watermark (obs/hbm.py) so
its HBM numbers are its own, not a cumulative process high-water mark.

The primary line is the 1.35B-param dense train step (the largest dense
config whose AdamW state + activations fit one v5e's 16GB HBM — Llama-2-7B
itself cannot fit a single chip, noted in extra.note). ``extra`` carries two
more benchmark results so they land in the driver's BENCH json without
breaking the one-line contract: a flash-vs-dot attention kernel comparison at
S=8192 and a MoE (GShard top-2) train line, plus a TPU-executed
flash-matches-dot correctness check (the CPU test suite only exercises the
Pallas kernels in interpreter mode).

Tuning provenance (scripts/perf_sweep.py, round 3): remat save_attn_kernel
(keep q/k/v + flash residuals; bwd skips qkv projections, rope, and the
flash fwd kernel) + bf16 Adam first moment (frees 2.7GB to fund those saves)
+ flash blocks 1024/1024 moved single-chip MFU 52.9% -> 58.6%.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp


def _fence(x) -> float:
    # float() (device_get) is the sync point -- block_until_ready is not a
    # reliable fence on the axon relay platform.
    return float(jnp.sum(jax.tree.leaves(x)[0].astype(jnp.float32)))


def _hbm_watch():
    """The bench-wide HbmWatch (obs/hbm.py): phase-scoped watermarks per
    section — each section owns its number (peak_exact says whether it set
    a new process high-water mark), killing the old cumulative-peak caveat
    and its `cum_peak_after_moe` workaround."""
    global _WATCH
    if _WATCH is None:
        from tony_tpu.obs.hbm import HbmWatch

        _WATCH = HbmWatch()
    return _WATCH


_WATCH = None


def train_bench(cfg, batch: int, seq: int, steps: int, mu_dtype,
                label: str = "train") -> dict:
    """One sharded train-step benchmark; returns tok/s + MFU + loss.

    The step is AOT-compiled so its cost_analysis() FLOPs are measured —
    MFU is computed from what XLA actually schedules, with the hand
    formula (train_flops_per_token) reported beside it as `mfu_formula`
    and the ratio as `flops_measured_vs_formula`. The run is wrapped in a
    phase watermark, so the reported HBM keys are scoped to THIS config."""
    from tony_tpu.models.llama import train_flops_per_token
    from tony_tpu.obs.compiles import get_ledger
    from tony_tpu.obs.metrics import StepTimer, chip_peak_flops
    from tony_tpu.parallel.mesh import single_device_mesh
    from tony_tpu.train.trainer import default_optimizer, make_train_state, make_train_step

    watch = _hbm_watch()
    ledger = get_ledger()
    with watch.phase(label) as ph:
        mesh = single_device_mesh()
        opt = default_optimizer(warmup_steps=10, decay_steps=1000, mu_dtype=mu_dtype)
        state = make_train_state(jax.random.key(0), cfg, mesh, opt)
        step = make_train_step(cfg, mesh, opt)
        tokens = jax.random.randint(jax.random.key(1), (batch, seq + 1), 0, cfg.vocab_size)
        inputs, targets = tokens[:, :-1], tokens[:, 1:]

        flops_per_step = 0.0
        t0 = time.perf_counter()
        try:
            with ledger.label(label):
                compiled = step.lower(state, inputs, targets).compile()
            entry = ledger.record_aot(label, compiled, time.perf_counter() - t0)
            flops_per_step = float(entry.get("flops", 0.0))
            step = compiled  # ONE compile, analyses attached
        except Exception:
            pass  # lazy jit fallback: first call below compiles

        state, metrics = step(state, inputs, targets)  # compile/warm
        state, metrics = step(state, inputs, targets)
        float(metrics["loss"])

        flops_formula = train_flops_per_token(cfg, seq)
        flops_measured = flops_per_step / (batch * seq) if flops_per_step else 0.0
        timer = StepTimer(
            flops_per_token=flops_measured or flops_formula,
            tokens_per_step=batch * seq,
            n_chips=1,
        )
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, inputs, targets)
        final_loss = float(metrics["loss"])  # sync fence
        timer.record(time.perf_counter() - t0, steps)
    peak = chip_peak_flops()
    out = {
        "tokens_per_sec_per_chip": round(timer.tokens_per_sec_per_chip, 1),
        # headline MFU from measured FLOPs (cost_analysis) when available
        "mfu": round(timer.mfu(peak), 4),
        "mfu_formula": round(
            timer.tokens_per_sec_per_chip * flops_formula / peak, 4
        ),
        "flops_source": "cost_analysis" if flops_measured else "formula",
        "loss": round(final_loss, 4),
        "batch": batch,
        "seq": seq,
        "steps": steps,
        # phase-scoped HBM watermark (the fused-CE win shows up here)
        **ph.bench_keys(),
    }
    if flops_measured:
        out["flops_per_token_measured"] = round(flops_measured, 1)
        out["flops_per_token_formula"] = round(flops_formula, 1)
        out["flops_measured_vs_formula"] = round(
            flops_measured / flops_formula, 4
        )
    return out


def _timed_scan_grad(attn, q, *, reps: int, steps: int) -> dict:
    """Time ``grad`` of ``reps`` scanned applications of ``attn`` (mirrors
    the model's layer scan so relay dispatch overhead amortises).
    Returns {"ms": N} or {"error": ...}."""

    def loss(qq):
        def body(c, _):
            return attn(c), None

        out, _ = jax.lax.scan(body, qq, None, length=reps)
        return jnp.sum(out.astype(jnp.float32))

    try:
        fn = jax.jit(jax.grad(loss))
        _fence(fn(q)); _fence(fn(q))
        t0 = time.perf_counter()
        for _ in range(steps):
            o = fn(q)
        _fence(o)
        return {"ms": round((time.perf_counter() - t0) / steps * 1e3, 1)}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {str(e)[:120]}"}


def kernel_bench_s8192(steps: int = 8) -> dict:
    """Flash (Pallas) vs dot (XLA) attention at S=8192: fwd+bwd TF/s."""
    from tony_tpu.models.llama import dot_attention
    from tony_tpu.ops.attention import flash_attention

    B, S, H, D = 1, 8192, 16, 128
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, H, D), jnp.bfloat16)
    reps = 24
    fwd = 4 * B * H * S * S * D / 2        # QK^T + PV matmuls, causal half
    flops = 3.5 * fwd * reps               # + bwd: 5 more matmuls = 2.5x fwd

    out = {}
    for name, attn in [
        ("flash", lambda a: flash_attention(a, k, v, causal=True)),
        ("dot", lambda a: dot_attention(a, k, v)),
    ]:
        r = _timed_scan_grad(attn, q, reps=reps, steps=steps)
        if "ms" in r:
            r["tflops"] = round(flops / (r["ms"] / 1e3) / 1e12, 1)
        elif name == "dot":
            # expected: dot materialises the [S,S] fp32 scores -- 4.3GB per
            # layer at S=8192 -- which is exactly the memory wall the flash
            # kernel removes
            r["error"] = (
                "infeasible at S=8192 (materializes 4.3GB scores/layer); "
                + r["error"]
            )
        out[name] = r
    if "tflops" in out.get("flash", {}) and "tflops" in out.get("dot", {}):
        out["flash_speedup"] = round(out["flash"]["tflops"] / out["dot"]["tflops"], 2)
    return out


def gqa_kernel_bench(steps: int = 8) -> dict:
    """GQA via the kernel's BlockSpec index map vs an HBM-materialised K/V
    repeat, at llama3_8b's 32:8 head ratio (B=1, S=4096). Same math and
    near-equal time (both stream the same blocks); the native path's win is
    HBM CAPACITY -- no 4x-wide K/V tensors resident -- which is what lets
    long-sequence GQA configs fit at all."""
    from tony_tpu.ops.attention import flash_attention

    B, S, H, Hkv, D = 1, 4096, 32, 8, 128
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.bfloat16)
    rep = H // Hkv

    out = {
        "blockspec_gqa": _timed_scan_grad(
            lambda a: flash_attention(a, k, v, causal=True), q, reps=8, steps=steps
        ),
        "expanded_kv": _timed_scan_grad(
            lambda a: flash_attention(
                a, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2),
                causal=True,
            ),
            q, reps=8, steps=steps,
        ),
    }
    out["note"] = (
        "times agree within relay run-to-run variance; the BlockSpec path's "
        "advantage is HBM capacity (no 4x-wide K/V resident)"
    )
    return out


def long_context_bench(steps: int = 4) -> dict:
    """Single-chip S=32768 flash attention fwd+bwd — the long-context axis
    the reference never had. 34GB of fp32 scores per layer (32768^2 x 4B x
    8 heads) if materialised; the kernel streams them through VMEM."""
    from tony_tpu.ops.attention import flash_attention

    B, S, H, D = 1, 32768, 8, 128
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, H, D), jnp.bfloat16)
    reps = 2
    fwd = 4 * B * H * S * S * D / 2
    flops = 3.5 * fwd * reps

    r = _timed_scan_grad(
        lambda a: flash_attention(a, k, v, causal=True), q, reps=reps, steps=steps
    )
    if "ms" in r:
        r["tflops"] = round(flops / (r["ms"] / 1e3) / 1e12, 1)
    return r


def fused_ce_matches_dense_on_tpu() -> dict:
    """Fused-CE correctness on REAL hardware (the CPU suite runs the pallas
    kernels in interpreter mode only): value + grads vs the full-logits
    logsumexp reference at a vocab deliberately not divisible by the tiles."""
    from tony_tpu.ops.fused_ce import fused_ce_tokens, reference_ce_tokens

    B, S, D, V = 2, 512, 512, 4000
    ks = jax.random.split(jax.random.key(11), 3)
    h = jax.random.normal(ks[0], (B, S, D), jnp.bfloat16)
    w = (jax.random.normal(ks[1], (D, V), jnp.float32) * 0.05).astype(jnp.bfloat16)
    t = jax.random.randint(ks[2], (B, S), 0, V)

    def mean_ref(h_, w_):
        return jnp.mean(reference_ce_tokens(h_, w_, t))

    out = {}
    lr, gr = jax.value_and_grad(mean_ref, argnums=(0, 1))(h, w)
    for impl in ("scan", "pallas"):
        def mean_fused(h_, w_, impl=impl):
            return jnp.mean(fused_ce_tokens(h_, w_, t, impl=impl, vocab_chunk=512))

        lf, gf = jax.value_and_grad(mean_fused, argnums=(0, 1))(h, w)
        verr = abs(float(lf) - float(lr)) / max(abs(float(lr)), 1e-9)
        gerr = max(
            float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
            for a, b in zip(gf, gr)
        )
        if verr > 1e-3 or gerr > 1e-2:  # bf16 primals; fp32 parity lives in tier-1
            raise AssertionError(f"{impl} CE != dense on TPU: {verr=} {gerr=}")
        out[impl] = {"rel_value_err": round(verr, 8), "max_grad_err": round(gerr, 6)}
    return out


def ce_head_bench(steps: int = 8) -> dict:
    """Loss-head fwd+bwd at bench shapes (h [8,2048,2048], V=32000), dense
    full-logits vs fused scan vs fused pallas. The dense head materialises
    2.1GB of fp32 logits + 2.1GB dlogits at this batch; the fused paths keep
    one [N, Vc] block live."""
    from tony_tpu.ops.fused_ce import fused_ce_tokens, reference_ce_tokens

    B, S, D, V = 8, 2048, 2048, 32000
    ks = jax.random.split(jax.random.key(3), 3)
    h = jax.random.normal(ks[0], (B, S, D), jnp.bfloat16)
    w = (jax.random.normal(ks[1], (D, V), jnp.float32) * 0.02).astype(jnp.bfloat16)
    t = jax.random.randint(ks[2], (B, S), 0, V)

    def timed(lossf):
        try:
            fn = jax.jit(jax.grad(lossf, argnums=(0, 1)))
            _fence(fn(h, w)); _fence(fn(h, w))
            t0 = time.perf_counter()
            for _ in range(steps):
                o = fn(h, w)
            _fence(o)
            return {"ms": round((time.perf_counter() - t0) / steps * 1e3, 1)}
        except Exception as e:
            return {"error": f"{type(e).__name__}: {str(e)[:120]}"}

    out = {}
    for name, lossf in (
        ("dense", lambda a, b: jnp.mean(reference_ce_tokens(a, b, t))),
        ("scan", lambda a, b: jnp.mean(
            fused_ce_tokens(a, b, t, impl="scan", vocab_chunk=4096))),
        ("pallas", lambda a, b: jnp.mean(
            fused_ce_tokens(a, b, t, impl="pallas"))),
    ):
        # phase-scoped watermark per impl: the dense head's logits+dlogits
        # transient is attributed to the dense phase, not inherited by the
        # fused ones (obs/hbm.py attribution rule)
        with _hbm_watch().phase(f"ce_head_{name}") as ph:
            out[name] = timed(lossf)
        hk = ph.bench_keys()
        if hk:
            out[name]["hbm"] = hk
    return out


def flash_matches_dot_on_tpu() -> bool:
    """Correctness of the Pallas kernels on REAL hardware (the CPU suite
    runs them in interpreter mode only)."""
    from tony_tpu.models.llama import dot_attention
    from tony_tpu.ops.attention import flash_attention

    B, S, H, D = 2, 512, 4, 128
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, H, D), jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True, block_q=256, block_k=256)
    want = dot_attention(q, k, v)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))))
    if err > 2e-2:
        raise AssertionError(f"flash != dot on TPU: max abs err {err}")
    return True


def moe_routing_stats(cfg) -> dict:
    """Router health at bench shapes: run the (initialised) router over a
    random activation batch and report what the capacity semantics would
    drop vs what dropless serves (parallel.moe.routing_stats)."""
    from tony_tpu.parallel.moe import MoEConfig, init_moe_params, routing_stats

    mcfg = MoEConfig(
        dim=cfg.dim, ffn_dim=cfg.ffn_dim, n_experts=cfg.n_experts,
        top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
    )
    params = init_moe_params(jax.random.key(5), mcfg)
    x = jax.random.normal(jax.random.key(6), (8 * 2048, cfg.dim), jnp.float32)
    probs = jax.nn.softmax(x @ params["router"], axis=-1)
    return routing_stats(probs, mcfg)


def moe_bench(steps: int = 10) -> dict:
    """MoE train step per dispatch impl: grouped (dropless sorted grouped
    GEMM, scan + pallas kernels) vs the round-4 gather baseline vs the
    einsum reference, plus routing stats (dropped-route fraction, expert
    load imbalance) so the dropless gains are legible in the trajectory.

    4 experts (~1.2B total / ~700M active): the 8-expert preset's AdamW
    state alone exceeds the chip's 16GB. Capacity factor 1.0 for the
    capacity paths (round-4 tuning, docs/PERF.md); irrelevant to grouped.
    Each dispatch runs under its own phase watermark (obs/hbm.py), so the
    per-dispatch HBM keys are scoped to that config — `peak_exact` says
    whether the phase set a new process high-water mark."""
    from tony_tpu.models.llama import LlamaConfig

    def cfg_for(**kw):
        return LlamaConfig.bench_moe(
            n_experts=4, attention_impl="flash",
            remat_policy="save_attn_kernel", moe_capacity_factor=1.0, **kw,
        )

    per_dispatch = {}
    for name, kw in (
        ("grouped", {"moe_dispatch": "grouped"}),
        ("grouped_pallas", {"moe_dispatch": "grouped", "moe_gmm_impl": "pallas"}),
        ("gather", {"moe_dispatch": "gather"}),
        ("einsum", {"moe_dispatch": "einsum"}),
    ):
        try:
            r = train_bench(
                cfg_for(**kw), batch=8, seq=2048, steps=steps,
                mu_dtype=jnp.bfloat16, label=f"moe_{name}",
            )
            per_dispatch[name] = {
                k: r[k]
                for k in ("tokens_per_sec_per_chip", "mfu", "loss",
                          "phase_peak_hbm_gb", "phase_delta_peak_gb",
                          "peak_exact")
                if k in r
            }
        except Exception as e:
            per_dispatch[name] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}

    headline_cfg = cfg_for(moe_dispatch="grouped")
    # headline = first dispatch that actually produced numbers; when every
    # run failed, say so instead of wearing a working dispatch's name (the
    # per-run errors stay visible in per_dispatch)
    headline_name = next(
        (n for n in ("grouped", "gather")
         if "tokens_per_sec_per_chip" in per_dispatch.get(n, {})),
        None,
    )
    out = {
        "n_params": headline_cfg.n_params,
        "n_active_params": headline_cfg.n_active_params,
        "dispatch": headline_name or "all_failed",
        "capacity_factor": 1.0,
        "batch": 8,
        "seq": 2048,
        **(per_dispatch.get(headline_name, {}) if headline_name else {}),
        "per_dispatch": per_dispatch,
    }
    g = per_dispatch.get("grouped", {}).get("tokens_per_sec_per_chip", 0)
    b = per_dispatch.get("gather", {}).get("tokens_per_sec_per_chip", 0)
    if g and b:
        # the PR-4 gate, resolved round 20: `grouped_vs_gather` is a
        # perf-diff-judged ratio (higher-better), and the dispatch
        # decision is recorded as int bits so the diff's flatten (numeric
        # leaves only) holds them to configuration identity — grouped
        # ships as the default exactly while the gate holds
        out["grouped_vs_gather"] = round(g / b, 3)
        out["dispatch_gate_holds"] = int(g > b)
    out["dispatch_default_grouped"] = int(cfg_for().moe_dispatch == "grouped")
    try:
        out["routing"] = moe_routing_stats(headline_cfg)
    except Exception as e:
        out["routing"] = {"error": f"{type(e).__name__}: {str(e)[:120]}"}
    # the ep-combine overlap, OFF vs ON through the real capture path —
    # the MoE counterpart of the `overlap` section ('pallas' = the TPU
    # grouped-GEMM kernel form inside each chunk)
    try:
        out["overlap"] = moe_overlap_bench(
            cfg_for(moe_dispatch="grouped"), batch=8, seq=2048, steps=6,
            impl="pallas",
        )
    except Exception as e:
        out["overlap"] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
    return out


def moe_overlap_bench(cfg=None, batch: int = 8, seq: int = 64,
                      steps: int = 6, impl: str = "scan") -> dict:
    """The MoE ep-combine overlap section: one expert-parallel train step
    captured through the real ProfileController path twice — the grouped
    path's post-FFN combine as the single blocking psum
    (moe_overlap_impl='off') vs decomposed per-token-chunk partial
    combines (ops/moe_overlap) — so per-step exposed-collective share and
    per-collective achieved_gbps for the ep combine land in the committed
    step-anatomy fixtures next to the dense capture. The ON run's chunk
    size is solved from the OFF capture's measured bandwidth
    (chunk_tokens_from_report): the anatomy report drives the knob the
    report then judges, the same loop the `overlap` section closes for
    the fsdp/dp collectives."""
    import dataclasses
    import glob as _glob
    import tempfile

    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.obs import anatomy, comms
    from tony_tpu.obs import profile as profile_mod
    from tony_tpu.ops.moe_overlap import chunk_tokens_from_report, overlap_chunks
    from tony_tpu.parallel.mesh import (
        MeshShape, build_mesh, get_default_mesh, set_default_mesh,
    )
    from tony_tpu.train.trainer import (
        default_optimizer, make_train_state, make_train_step,
    )

    n = len(jax.devices())
    if n < 2:
        return {"error": "moe overlap bench needs >= 2 devices (ep ring)"}
    if cfg is None:
        cfg = LlamaConfig.tiny_moe()
    # ep pair (the combine this section decomposes) + dp over the rest so
    # tokens stay sharded over the data axes, the trainer's MoE shape
    dp = n // 2 if n >= 4 else 1
    if batch % max(dp, 1):
        return {"error": f"batch {batch} does not shard over dp={dp}"}
    prev_mesh = get_default_mesh()
    mesh = build_mesh(MeshShape(ep=2, dp=dp))
    set_default_mesh(mesh)
    opt = default_optimizer(warmup_steps=2, decay_steps=100)
    tokens = jax.random.randint(
        jax.random.key(1), (batch, seq + 1), 0, cfg.vocab_size
    )
    inputs, targets = tokens[:, :-1], tokens[:, 1:]

    def capture(variant_cfg):
        state = make_train_state(jax.random.key(0), variant_cfg, mesh, opt)
        step = make_train_step(variant_cfg, mesh, opt)
        ledger_rows = []
        try:
            compiled = step.lower(state, inputs, targets).compile()
            ledger_rows = comms.extract_collectives(compiled)
            step = compiled
        except Exception:
            pass  # lazy jit fallback: ledger-less capture still reports
        out_root = tempfile.mkdtemp(prefix="tony-moe-overlap-")
        ctl = profile_mod.ProfileController(out_root, "bench", watch=False)
        state, m = step(state, inputs, targets)  # warm outside the window
        _fence(m["loss"])
        ctl.trigger(steps=steps)
        for _ in range(steps + 1):
            ctl.step(fetch_s=0.0)
            state, m = step(state, inputs, targets)
            _fence(m["loss"])
        ctl.finish()
        mpaths = _glob.glob(
            os.path.join(out_root, "bench", "*", "manifest.json")
        )
        if not mpaths:
            return {"error": "no capture manifest landed"}
        with open(mpaths[-1]) as fh:
            manifest = json.load(fh)
        rep = anatomy.proc_report(manifest, ledger_rows)
        sec = {
            "step_ms": rep["per_step_ms"]["step_time_s"],
            "compute_ms": rep["per_step_ms"]["compute_s"],
            "exposed_collective_ms": rep["per_step_ms"]["exposed_collective_s"],
            "loss": round(float(m["loss"]), 4),
        }
        for k in ("overlap_frac", "pure_comm_steps"):
            if k in rep:
                sec[k] = rep[k]
        top = next(
            (r for r in rep["collectives"]
             if r.get("bytes") and r.get("total_s")),
            None,
        )
        if top is not None:
            sec["top_collective"] = {
                "kind": top["kind"], "bytes": top["bytes"],
            }
            if "achieved_gbps" in top:
                sec["top_collective"]["achieved_gbps"] = top["achieved_gbps"]
        return sec

    try:
        off = capture(dataclasses.replace(cfg, moe_overlap_impl="off"))
        if "error" in off:
            return off
        # size the chunk from the OFF capture's measured bandwidth; when
        # the measured size doesn't divide this shape's per-shard rows,
        # fall back to the auto split rather than silently not overlapping
        dtype_bytes = 2 if cfg.dtype == jnp.bfloat16 else 4
        chunk = chunk_tokens_from_report(off, dim=cfg.dim,
                                         dtype_bytes=dtype_bytes)
        t_local = (batch * seq) // dp
        if overlap_chunks(t_local, chunk) is None:
            chunk = 0
        on = capture(dataclasses.replace(
            cfg, moe_overlap_impl=impl, moe_overlap_chunk=chunk,
        ))
    finally:
        set_default_mesh(prev_mesh)
    out = {
        "devices": n,
        "mesh": {"ep": 2, "dp": dp},
        "impl": impl,
        "chunk_tokens": chunk,
        "off": off,
        "on": on,
    }
    if "error" not in on:
        # lift the judged keys to the section top so perf_diff's dotted
        # rules (extra.moe_top2.overlap.*) see them without digging into
        # variants
        if "overlap_frac" in on:
            out["overlap_frac"] = on["overlap_frac"]
        out["exposed_collective_ms"] = on["exposed_collective_ms"]
        if off.get("exposed_collective_ms"):
            out["exposed_ratio"] = round(
                on["exposed_collective_ms"] / off["exposed_collective_ms"], 4
            )
        if off.get("step_ms"):
            out["step_ms_ratio"] = round(on["step_ms"] / off["step_ms"], 4)
        # value-safety receipt: same batch/state both variants — the
        # decomposed combine is an execution schedule, not a new model
        if "loss" in off and "loss" in on:
            out["loss_delta"] = round(abs(on["loss"] - off["loss"]), 6)
    return out


def decode_bench(on_tpu: bool) -> dict:
    """Serving throughput (the decode counterpart of the training
    headline): the continuous-batching engine over a request trace.

    Reports decode tokens/s/chip, TTFT, and slot occupancy for
    (a) sequential batch-1 decode (one slot: the pre-engine serving
    pattern — a request owns the whole 'batch'), (b) all-slots continuous
    batching over the SAME trace, and (c) steady state under a mixed
    arrival trace (new request every other step). Decode at these shapes
    is HBM-bandwidth-bound on the weights, so batching slots is nearly
    free: the full-slot engine targets >= 4x the sequential tokens/s.
    Also times the native-GQA decode kernel vs the repeat-expanded
    reference at the same shapes."""
    import numpy as np

    from tony_tpu.models.llama import LlamaConfig, init_params
    from tony_tpu.ops.decode_attention import (
        decode_attention, reference_decode_attention,
    )
    from tony_tpu.serve import Engine, Request, ServeConfig

    if on_tpu:
        # bench_1b4 trunk at llama3-style 4:1 GQA (16 q heads / 4 kv heads)
        import dataclasses

        cfg = dataclasses.replace(LlamaConfig.bench_1b4(), n_kv_heads=4)
        slots, max_len, block = 8, 1024, 128
        n_req, max_new = 16, 64
        prompt_lens = [64, 128, 192, 256, 384, 512]
        kern_T = 1024
    else:
        cfg = LlamaConfig.tiny()
        slots, max_len, block = 4, 64, 8
        n_req, max_new = 6, 4
        prompt_lens = [3, 5, 9, 14]
        kern_T = 64
    params = init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)

    def trace():
        return [
            Request(
                prompt=rng.integers(
                    0, cfg.vocab_size, prompt_lens[i % len(prompt_lens)]
                ),
                max_new_tokens=max_new,
                rng=i,
            )
            for i in range(n_req)
        ]

    def serve_cfg(s):
        return ServeConfig(slots=s, max_len=max_len, kv_block=block)

    def warmed(s):
        """Engine with every bucket/capacity compile paid before timing:
        the reported tokens/s is steady-state serving, not XLA compiles."""
        eng = Engine(params, cfg, serve_cfg(s))
        eng.run([
            Request(prompt=rng.integers(0, cfg.vocab_size, pl),
                    max_new_tokens=max_new)
            for pl in prompt_lens
        ])
        # a lone short request after the drain reaches the shrunk-capacity
        # compiles the timed trace would otherwise pay mid-run
        eng.run([Request(prompt=rng.integers(0, cfg.vocab_size, prompt_lens[0]),
                         max_new_tokens=2)])
        eng.reset_metrics()
        return eng

    out = {"model": "bench_1b4_gqa16_4" if on_tpu else "tiny",
           "slots": slots, "max_new_tokens": max_new, "n_requests": n_req}

    # (a) sequential batch-1: the trace drains one request at a time
    eng1 = warmed(1)
    eng1.run(trace())
    out["sequential_b1"] = eng1.metrics.summary()

    # (b) full-slot continuous batching, same trace submitted upfront
    engS = warmed(slots)
    engS.run(trace())
    out["continuous"] = engS.metrics.summary()
    s1 = eng1.metrics.tokens_per_sec_per_chip
    sS = engS.metrics.tokens_per_sec_per_chip
    if s1 > 0:
        out["continuous_vs_b1"] = round(sS / s1, 2)

    # (c) steady state under a mixed arrival trace: half the requests
    # queued upfront, one more lands every other decode step
    engM = warmed(slots)
    reqs = trace()
    for r in reqs[: max(1, n_req // 2)]:
        engM.submit(r)
    rest = reqs[max(1, n_req // 2):]
    i = 0
    while engM._queue or engM.n_live or rest:
        if rest and i % 2 == 0:
            engM.submit(rest.pop(0))
        engM.step()
        i += 1
    out["mixed_arrivals"] = engM.metrics.summary()

    # (d) 90%-shared-prefix trace, store on vs off (serve/prefix.py): the
    # cross-request-reuse headline. Requests share a long template prefix
    # and differ only in a short tail; with the store on, admission
    # matches the prefix and prefills only the tail — TTFT and prefill
    # FLOPs (from the compile ledger's AOT cost_analysis) collapse to the
    # tail's. Sequential single-request runs so TTFT is unblurred.
    from tony_tpu.obs.compiles import get_ledger

    # trace lengths chosen so the tail bucket is genuinely smaller than
    # the full-prompt bucket (at the tiny CPU shapes the default request
    # lengths would pad tail and prompt into the same bucket)
    prefix_total = 512 if on_tpu else 56
    shared_len = int(round(0.9 * prefix_total))
    tail_len = prefix_total - shared_len
    shared_prefix = rng.integers(0, cfg.vocab_size, shared_len)

    def prefix_mode(on: bool) -> dict:
        eng = Engine(params, cfg, ServeConfig(
            slots=2, max_len=max_len, kv_block=block, prefix=on,
        ))
        def reqs(seed):
            r2 = np.random.default_rng(seed)
            return [
                Request(
                    prompt=np.concatenate(
                        [shared_prefix,
                         r2.integers(0, cfg.vocab_size, tail_len)]
                    ),
                    max_new_tokens=max_new, rng=seed * 1000 + i,
                )
                for i in range(n_req)
            ]
        for r in reqs(7):   # warm: compiles paid, prefix registered
            eng.run([r])
        eng.reset_metrics()
        ttfts = []
        for r in reqs(8):
            done = eng.run([r])
            ttfts.extend(c.ttft_s for c in done.values())
        ttfts.sort()
        m = eng.metrics.summary()
        return {
            "ttft_p50_s": round(ttfts[len(ttfts) // 2], 5),
            "ttft_p99_s": round(ttfts[-1], 5),
            "prefix_hit_rate": m.get("prefix_hit_rate", 0.0),
        }

    ledger = get_ledger()
    p_on, p_off = prefix_mode(True), prefix_mode(False)
    # prefill FLOPs per request from the ledger's AOT entries: the full
    # bucket the off-mode pays vs the tail bucket the store leaves
    flops_by_name = {
        e["fn"]: e.get("flops", 0.0) for e in ledger.entries("aot")
    }
    full_flops = max(
        (v for k, v in flops_by_name.items()
         if k.startswith("serve.prefill[")), default=0.0,
    )
    tail_flops = max(
        (v for k, v in flops_by_name.items()
         if k.startswith("serve.prefill_tail[")), default=0.0,
    )
    trace_out = {
        "shared_len": shared_len, "tail_len": tail_len,
        "prefix_on": p_on, "prefix_off": p_off,
    }
    if p_off["ttft_p50_s"] > 0:
        trace_out["ttft_p50_ratio"] = round(
            p_on["ttft_p50_s"] / p_off["ttft_p50_s"], 3
        )
    if full_flops > 0 and tail_flops > 0:
        trace_out["prefill_flops_full"] = full_flops
        trace_out["prefill_flops_tail"] = tail_flops
        trace_out["prefill_flops_ratio"] = round(tail_flops / full_flops, 4)
    out["prefix_trace"] = trace_out

    # (e) speculative decoding (serve/spec.py): repeated greedy traffic,
    # spec on vs off at batch 1 and batch `slots`. The first (warm) pass
    # seeds the radix store with the prompt AND the generation, so the
    # timed repeats draft along the observed path at near-full accept —
    # the verify step emits several tokens per forward while each forward
    # stays memory-bound. Headline: tokens/s/slot on/off speedup at b1
    # (target >= 2x), tokens/step, accept rate, and the compile count
    # (ONE extra signature family, never per-draft-length).
    # prompt + generation block-aligned so the warm pass registers the
    # WHOLE path as full radix blocks — the timed repeats then draft to
    # the end of the generation, not just its full-block prefix
    # gen length \equiv 1 (mod block): the LAST generated token's KV is
    # never written (nothing decodes after it), so the path registered at
    # finish is the first plen+gen-1 tokens — this choice makes that a
    # whole number of blocks and the store covers the entire repeat
    spec_new = max_new * 12 + 1
    spec_draft = 15
    spec_prompt = rng.integers(0, cfg.vocab_size, block)

    def spec_mode(on: bool, batch: int) -> dict:
        eng = Engine(params, cfg, ServeConfig(
            slots=batch, max_len=max_len, kv_block=block,
            spec=on, spec_max_draft=spec_draft,
        ))
        def reqs():
            return [
                Request(prompt=spec_prompt, max_new_tokens=spec_new, rng=i)
                for i in range(batch)
            ]
        # warm TWICE: the first pass seeds the store (and pays the full-
        # prefill compiles), the second pays the compiles only a repeat
        # hits (tail prefill at the matched boundary, the spec step at
        # its steady signatures) — the timed pass then measures serving,
        # not XLA
        eng.run(reqs())
        eng.run(reqs())
        eng.reset_metrics()
        eng.run(reqs())
        m = eng.metrics
        r = {
            "tok_s_slot": round(m.tokens_per_sec_per_chip / batch, 1),
            "tokens_per_step": round(m.tokens_per_step, 3),
            "decode_compiles": m.decode_compiles,
        }
        if on:
            r["accept_rate"] = round(m.draft_accept_rate, 4)
        return r

    spec_out: dict = {"max_draft": spec_draft, "gen_tokens": spec_new}
    for batch in (1, slots):
        s_on, s_off = spec_mode(True, batch), spec_mode(False, batch)
        spec_out[f"b{batch}_on"] = s_on
        spec_out[f"b{batch}_off"] = s_off
        if s_off["tok_s_slot"] > 0:
            spec_out[f"speedup_b{batch}"] = round(
                s_on["tok_s_slot"] / s_off["tok_s_slot"], 2
            )
    out["spec_trace"] = spec_out

    # (f) quantized serving (serve.quant.*: block-scaled int8 KV pools in
    # serve/cache.py + weight-only int8 decode matmuls in ops/quant_mm.py):
    # the same warmed trace, quant on vs off. ``tolerance`` is the STATED
    # quant-vs-bf16 logits bound the kernels hold (tests/test_quant.py
    # asserts it; perf-diff treats it as config identity, so loosening it
    # is a diff failure, not drift). Each mode runs under its own HBM
    # phase so peak_hbm_gb is scoped per mode, not inherited.
    QUANT_TOL = 0.08

    def qreqs(seed):
        r2 = np.random.default_rng(seed)
        return [
            Request(
                prompt=r2.integers(
                    0, cfg.vocab_size, prompt_lens[i % len(prompt_lens)]
                ),
                max_new_tokens=max_new, rng=seed * 1000 + i,
            )
            for i in range(n_req)
        ]

    def quant_mode(on: bool) -> dict:
        name = "decode.quant_on" if on else "decode.quant_off"
        with _hbm_watch().phase(name) as ph:
            eng = Engine(params, cfg, ServeConfig(
                slots=slots, max_len=max_len, kv_block=block,
                quant_kv="int8" if on else "", quant_weights=on,
            ))
            eng.run(qreqs(3))  # warm: compiles paid before timing
            eng.reset_metrics()
            eng.run(qreqs(4))
            m = eng.metrics
            r = {
                "tok_s_slot": round(m.tokens_per_sec_per_chip / slots, 1),
                "ttft_avg_s": round(m.ttft_avg_s, 5),
                "kv_bytes_per_token": round(m.kv_bytes_per_token, 1),
            }
            eng.close()
        hk = ph.bench_keys()
        if hk:
            r["peak_hbm_gb"] = hk["phase_peak_hbm_gb"]
        return r

    q_on, q_off = quant_mode(True), quant_mode(False)
    quant_out: dict = {
        "kv_dtype": "int8", "tolerance": QUANT_TOL,
        "quant_on": q_on, "quant_off": q_off,
    }
    if q_off["tok_s_slot"] > 0:
        quant_out["tok_s_ratio"] = round(
            q_on["tok_s_slot"] / q_off["tok_s_slot"], 3
        )
    out["quant"] = quant_out

    # (g) disaggregated prefill/decode (engine chunked prefill +
    # serve/gang.py pool handoff): a mixed-arrival trace where one LONG
    # prompt lands mid-stream among short decoders — the interference
    # headline. Four modes, chunking off/on x colocated/pooled:
    # colocated means one engine prefills AND decodes, so the long
    # prefill stalls every live decoder for the whole prompt unless it
    # is chunked (one chunk interleaved per decode step); pooled means a
    # second engine plays the prefill host — it prefills the long
    # prompt, exports the finished paged blocks, and the payload rides
    # the real wire format (pack/unpack measured as handoff bytes/ms)
    # into the decode engine, so decode-side admission prefix-hits the
    # shipped blocks and the long prompt never runs on the decode mesh.
    # TTFT/TPOT come from per-request completions polled step-by-step
    # (the engine's windowed snapshot is the series recorder's single
    # window — the bench must not consume it), and the warm passes pay
    # BOTH the compiles and the KV-pool growth: the store retains warm
    # blocks, the pool grows once, a drain frees everything, and the
    # timed pass runs at the settled pool shape with zero recompiles.
    from tony_tpu.serve.cache import pack_payload, unpack_payload

    if on_tpu:
        disagg_long, disagg_chunk = 512, 256
    else:
        disagg_long, disagg_chunk = 56, 16

    def disagg_mode(chunked: bool, pooled: bool) -> dict:
        eng = Engine(params, cfg, ServeConfig(
            slots=slots, max_len=max_len, kv_block=block, prefix=True,
            chunk_tokens=disagg_chunk if chunked else 0,
        ))
        hand = {"blocks": 0, "bytes": 0, "ms": 0.0}

        def run_pass(seed: int, timed: bool) -> dict | None:
            r2 = np.random.default_rng(seed)
            long_prompt = r2.integers(0, cfg.vocab_size, disagg_long)
            shorts = [
                Request(
                    prompt=r2.integers(
                        0, cfg.vocab_size, prompt_lens[i % len(prompt_lens)]
                    ),
                    max_new_tokens=max_new, rng=seed * 1000 + i,
                )
                for i in range(n_req)
            ]
            if pooled:
                peng = Engine(params, cfg, ServeConfig(
                    slots=1, max_len=max_len, kv_block=block,
                    prefix=True, pool="prefill",
                ))
                peng.run([Request(prompt=long_prompt, max_new_tokens=1)])
                covered, payload = peng.export_prefix_blocks(long_prompt)
                t0 = time.perf_counter()
                wire = pack_payload(payload)
                eng.adopt_blocks(covered, unpack_payload(
                    wire["k"], wire["v"], wire["shape"], wire["dtype"],
                    wire.get("k_scale", b""), wire.get("v_scale", b""),
                ))
                if timed:
                    hand["blocks"] = payload.n_blocks
                    hand["bytes"] = payload.nbytes
                    hand["ms"] = round((time.perf_counter() - t0) * 1e3, 3)
                peng.close()
            # half the shorts upfront, the long prompt lands at step 2,
            # remaining shorts one every other step — the long prefill
            # hits while every slot is mid-decode
            rids = [eng.submit(r) for r in shorts[: n_req // 2]]
            rest = shorts[n_req // 2:]
            pending = Request(prompt=long_prompt, max_new_tokens=max_new,
                              rng=seed)
            first_seen: dict[int, float] = {}
            finished: dict[int, tuple[float, int, float]] = {}
            i = 0
            while eng._queue or eng.n_live or rest or pending is not None:
                if i == 2 and pending is not None:
                    rids.append(eng.submit(pending))
                    pending = None
                elif rest and i % 2 == 0:
                    rids.append(eng.submit(rest.pop(0)))
                eng.step()
                now = time.perf_counter()
                for rid in rids:
                    if rid in finished:
                        continue
                    c = eng.completion_of(rid)
                    if c is None or not c.tokens:
                        continue
                    first_seen.setdefault(rid, now)
                    if c.finish_reason:
                        finished[rid] = (now, len(c.tokens), c.ttft_s)
                i += 1
            for rid in rids:
                eng.take_completion(rid)
            if not timed:
                return None
            ttfts = sorted(v[2] for v in finished.values())
            tpots = sorted(
                (v[0] - first_seen[rid]) / max(v[1] - 1, 1)
                for rid, v in finished.items()
            )
            return {
                "ttft_p50_s": round(ttfts[len(ttfts) // 2], 5),
                "ttft_p99_s": round(ttfts[-1], 5),
                "tpot_p50_s": round(tpots[len(tpots) // 2], 5),
                "tpot_p99_s": round(tpots[-1], 5),
            }

        def drain_store() -> None:
            while eng._store.evict_lru(eng._pool.release) is not None:
                pass

        run_pass(10, False)   # warm 1: compiles + the one-time pool growth
        drain_store()
        run_pass(11, False)   # warm 2: every signature at the settled shape
        drain_store()
        r = run_pass(12, True)
        eng.close()
        if pooled:
            r["handoff_blocks"] = hand["blocks"]
            r["handoff_bytes"] = hand["bytes"]
            r["handoff_ms"] = hand["ms"]
        return r

    disagg: dict = {"chunk_tokens": disagg_chunk,
                    "long_prompt_tokens": disagg_long}
    for chunked in (False, True):
        for pooled in (False, True):
            key = (("chunked" if chunked else "unchunked")
                   + ("_pooled" if pooled else "_colocated"))
            disagg[key] = disagg_mode(chunked, pooled)
    base_p99 = disagg["unchunked_colocated"].get("tpot_p99_s", 0.0)
    if base_p99 > 0:
        # the chunking headline: how much of the long-prompt TPOT spike
        # chunked prefill removes on a colocated gang (< 1 = bounded)
        disagg["tpot_p99_chunked_ratio"] = round(
            disagg["chunked_colocated"].get("tpot_p99_s", 0.0) / base_p99, 3
        )
    out["disagg"] = disagg

    # native-GQA decode kernel vs the repeat-expanded reference (one
    # decode step of attention at full cache length, layer-scanned so
    # dispatch overhead amortises)
    H, Hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (slots, H, hd), cfg.dtype)
    kc = jax.random.normal(ks[1], (slots, Hkv, kern_T, hd), cfg.dtype)
    vc = jax.random.normal(ks[2], (slots, Hkv, kern_T, hd), cfg.dtype)
    lengths = jnp.full((slots,), kern_T, jnp.int32)
    reps = cfg.n_layers

    def timed(fn):
        def loss(qq):
            def body(c, _):
                return fn(c), None

            o, _ = jax.lax.scan(body, qq, None, length=reps)
            return o

        try:
            f = jax.jit(loss)
            _fence(f(q)); _fence(f(q))
            t0 = time.perf_counter()
            n = 8
            for _ in range(n):
                o = f(q)
            _fence(o)
            return {"ms": round((time.perf_counter() - t0) / n * 1e3, 2)}
        except Exception as e:
            return {"error": f"{type(e).__name__}: {str(e)[:120]}"}

    kern = {
        "native_scan": timed(lambda a: decode_attention(
            a, kc, vc, lengths, impl="scan", block=block)),
        "repeat_reference": timed(lambda a: reference_decode_attention(
            a, kc, vc, lengths)),
    }
    if on_tpu:
        kern["native_pallas"] = timed(lambda a: decode_attention(
            a, kc, vc, lengths, impl="pallas", block=block))
    out["decode_kernel_T%d" % kern_T] = kern
    return out


def gqa_capacity_demo() -> dict:
    """Max concurrent decode slots at bench_1b4 GQA shapes: the native
    n_kv_heads cache vs a repeat-expanded (n_heads-wide) one — the HBM
    headroom the native-GQA decode kernel buys, since the repeat layout
    keeps every slot's K/V resident at n_heads width.

    The budget is DERIVED from the decode step's compiled memory plan
    (serve/capacity.py: params + fixed/per-slot temp + code from
    ``memory_analysis()``, avals only — nothing allocated), replacing the
    old ``hbm * 0.92 - params`` fragmentation guess; the formula numbers
    ride along as ``*_formula`` so the delta stays visible in BENCH json."""
    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.serve.capacity import derive_slot_budget

    import dataclasses

    cfg = dataclasses.replace(LlamaConfig.bench_1b4(), n_kv_heads=4)
    max_len = 2048
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        hbm = int(stats.get("bytes_limit", 16 * 2**30))
    except Exception:
        hbm = 16 * 2**30
    # the superseded guess, kept visible so the measured delta is legible
    param_bytes_formula = cfg.n_params * 2  # bf16 resident weights
    budget_formula = int(hbm * 0.92) - param_bytes_formula
    per_slot_native = 2 * cfg.n_layers * max_len * cfg.n_kv_heads * cfg.head_dim * 2
    per_slot_repeat = 2 * cfg.n_layers * max_len * cfg.n_heads * cfg.head_dim * 2
    out = {
        "model": "bench_1b4_gqa16_4",
        "max_len": max_len,
        "hbm_gb": round(hbm / 2**30, 1),
        "max_slots_native_formula": max(0, budget_formula // per_slot_native),
        "max_slots_repeat_formula": max(0, budget_formula // per_slot_repeat),
    }
    try:
        # shared_prefix_tokens: the prefix-store accounting — slot budget
        # when every request carries a half-max_len shared template prefix
        # (one refcounted physical copy; each slot pays only its tail)
        # quant_kv adds the quantized decode step's own budget (int8
        # pools + scale rows, measured via the same slots=1/2 plan
        # differencing): max_slots_quant and quant_slot_ratio are the
        # capacity headline of ROADMAP item 4
        measured = derive_slot_budget(
            cfg, max_len=max_len, hbm_bytes=hbm,
            shared_prefix_tokens=max_len // 2,
            quant_kv="int8",
        )
        out.update(measured)
        out["param_gb"] = round(measured["param_bytes"] / 2**30, 2)
        if measured["max_slots_native"]:
            out["formula_vs_measured"] = round(
                out["max_slots_native_formula"] / measured["max_slots_native"],
                3,
            )
    except Exception as e:
        # derivation unavailable (platform without memory_analysis): the
        # formula numbers become the headline, labelled as such
        out.update({
            "source": "formula",
            "error": f"{type(e).__name__}: {str(e)[:160]}",
            "param_gb": round(param_bytes_formula / 2**30, 2),
            "kv_bytes_per_slot_native": per_slot_native,
            "kv_bytes_per_slot_repeat": per_slot_repeat,
            "max_slots_native": out["max_slots_native_formula"],
            "max_slots_repeat": out["max_slots_repeat_formula"],
        })
    native, repeat = out["max_slots_native"], out["max_slots_repeat"]
    out["native_vs_repeat"] = round(native / max(repeat, 1), 2)
    return out


def pipeline_bench() -> dict:
    """GPipe vs 1F1B wall-clock + bubble fraction: runs scripts/pp_bench.py
    in a subprocess on the virtual 8-CPU mesh (the pp mesh needs its own
    device count / platform, which must not disturb this process's
    backend). Results land in docs/PERF.md "Pipeline"."""
    import subprocess

    root = os.path.dirname(os.path.abspath(__file__))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": root,
    }
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "scripts", "pp_bench.py")],
            capture_output=True, text=True, timeout=850, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"error": "pp_bench timed out"}
    out = {}
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                r = json.loads(line)
                out[r.pop("schedule")] = r
            except (ValueError, KeyError):
                pass
    if "gpipe" in out and "1f1b" in out and out["1f1b"]["step_ms"]:
        out["gpipe_vs_1f1b"] = round(
            out["gpipe"]["step_ms"] / out["1f1b"]["step_ms"], 3
        )
    if not out:
        out["error"] = (proc.stderr or "no output")[-300:]
    return out


def overlap_bench(cfg, batch: int, seq: int, steps: int, mu_dtype: str) -> dict:
    """fit()-driven input-pipeline benchmark. train_bench() feeds a
    pre-staged device batch (no input pipeline at all); this runs the REAL
    loop — synthetic token stream, H2D placement, metrics — with device
    prefetch off vs on, and reports the host-stall metric
    (``host_blocked_ms_per_step``: wall time the loop waits in
    next(batches)) plus fit()'s startup-phase breakdown (compile vs restore
    vs first-batch, which compile-ahead overlaps)."""
    from tony_tpu.train import DataConfig, FitConfig, fit

    out = {}
    for depth in (0, 2):
        final = fit(FitConfig(
            model=cfg,
            data=DataConfig(
                global_batch=batch, seq_len=seq, vocab_size=cfg.vocab_size,
                prefetch=depth,
            ),
            steps=steps, log_every=steps, warmup_steps=2, mu_dtype=mu_dtype,
        ))
        out[f"prefetch{depth}"] = {
            k: final[k]
            for k in (
                "tokens_per_sec_per_chip", "host_blocked_ms_per_step",
                "host_blocked_frac", "startup",
            )
            if k in final
        }
    p0 = out.get("prefetch0", {}).get("tokens_per_sec_per_chip", 0)
    p2 = out.get("prefetch2", {}).get("tokens_per_sec_per_chip", 0)
    if p0 and p2:
        out["prefetch_speedup"] = round(p2 / p0, 3)
    return out


def submit_latency_bench() -> dict:
    """AM-submit -> first-step latency (the second north-star metric,
    BASELINE.json "metric"): submit a tiny fit() job through the REAL
    client -> AM -> executor path twice — cold (empty XLA cache) and warm
    (the resubmit/elastic-restart case, which loads cached executables).

    Workers run on the CPU backend: the bench process holds the single TPU
    chip, and the orchestration path being measured is identical either
    way (on TPU only the compile segment grows, which is exactly what the
    cache removes)."""
    import tempfile

    from tony_tpu.am.events import submit_latency
    from tony_tpu.cli.client import TonyClient
    from tony_tpu.config.config import TonyConfig

    tmp = tempfile.mkdtemp(prefix="tony-lat-")
    src = os.path.join(tmp, "src")
    os.makedirs(src)
    with open(os.path.join(src, "train.py"), "w") as f:
        f.write(
            "from tony_tpu.models.llama import LlamaConfig\n"
            "from tony_tpu.train import DataConfig, FitConfig, fit\n"
            "fit(FitConfig(model=LlamaConfig.tiny(),\n"
            "    data=DataConfig(global_batch=4, seq_len=64, vocab_size=256),\n"
            "    steps=3, log_every=10, warmup_steps=1))\n"
        )
    out = {}
    # children must not touch the TPU the bench process holds
    saved = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        for run in ("cold", "warm"):
            cfg = TonyConfig.load(overrides={
                "application.stage_dir": os.path.join(tmp, "apps"),
                "application.name": f"lat-{run}",
                "application.framework": "jax",
                "train.jax_cache_dir": os.path.join(tmp, "jax_cache"),
                "job.worker.instances": 1,
                "job.worker.command": "python train.py",
                "job.worker.env": ["JAX_PLATFORMS=cpu"],
            })
            client = TonyClient(cfg, src_dir=src)
            code = client.run(quiet=True)
            if code != 0:
                out[run] = {"error": f"job exited {code}"}
                continue
            out[run] = submit_latency(client.app_dir)
    finally:
        if saved is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = saved
    return out


def health_overhead_bench(steps: int = 20) -> dict:
    """Armed-vs-disarmed step-time delta for the numerics health monitors
    (obs/health.py): the same tiny train step compiled WITH the fused
    value monitors (nonfinite counts, update ratio, per-layer grad RMS,
    batch fingerprint) and WITHOUT, timed back to back. The tiny model
    deliberately OVERSTATES the relative cost — the monitors are a fixed
    set of reductions, so their fraction shrinks as the model grows; a
    regression that makes them expensive shows up here first."""
    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.parallel.mesh import MeshShape, build_mesh
    from tony_tpu.train import trainer as tr

    cfg = LlamaConfig.tiny()
    B, S = 8, 256
    mesh = build_mesh(MeshShape(dp=1))
    opt = tr.default_optimizer(warmup_steps=1, decay_steps=1000)
    inputs = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.key(2), (B, S), 0, cfg.vocab_size)

    def timed(monitors: bool) -> float:
        step = tr.make_train_step(cfg, mesh, opt, monitors=monitors)
        state = tr.make_train_state(jax.random.key(0), cfg, mesh, opt)
        for _ in range(3):  # compile + warm
            state, m = step(state, inputs, targets)
        _fence(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step(state, inputs, targets)
        _fence(m["loss"])
        return (time.perf_counter() - t0) / steps * 1e3

    disarmed_ms = timed(False)
    armed_ms = timed(True)
    return {
        "step_ms_disarmed": round(disarmed_ms, 3),
        "step_ms_armed": round(armed_ms, 3),
        "overhead_frac": round((armed_ms - disarmed_ms) / disarmed_ms, 4),
    }


def anatomy_bench(steps: int = 6) -> dict:
    """Step-anatomy microbench (obs/profile.py + obs/anatomy.py): a
    shard_map matmul+psum loop over every local device, captured under a
    real ProfileController window exactly like `tony profile` would — so
    the judged numbers (overlap_frac higher-better, exposed_collective_ms
    lower-better, achieved_gbps on the dominant collective) come from the
    same capture/report path production uses, and a regression in either
    the overlap behaviour or the anatomy plumbing shows up here."""
    import tempfile

    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from tony_tpu.obs import anatomy, comms
    from tony_tpu.obs import profile as profile_mod
    from tony_tpu.ops.compat import shard_map_compat

    n = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()).reshape(n), ("dp",))

    def f(x, w):
        h = jnp.dot(x, w)
        return jax.lax.psum(h, "dp") if n > 1 else h

    sf = jax.jit(shard_map_compat(
        f, mesh=mesh, in_specs=(P("dp"), P(None, None)),
        out_specs=P(),
    )) if n > 1 else jax.jit(f)
    x = jnp.ones((max(n, 1) * 64, 512), jnp.float32)
    w = jnp.ones((512, 512), jnp.float32)
    compiled = sf.lower(x, w).compile()
    ledger_rows = comms.extract_collectives(compiled)
    out_root = tempfile.mkdtemp(prefix="tony-anatomy-")
    ctl = profile_mod.ProfileController(out_root, "bench", watch=False)
    ctl.trigger(steps=steps)
    y = compiled(x, w)
    _fence(y)  # warm outside the window
    for _ in range(steps + 1):
        ctl.step(fetch_s=0.0)
        y = compiled(x, w)
        _fence(y)
    ctl.finish()
    import glob as _glob

    mpaths = _glob.glob(os.path.join(out_root, "bench", "*", "manifest.json"))
    if not mpaths:
        return {"error": "no capture manifest landed"}
    with open(mpaths[-1]) as fh:
        manifest = json.load(fh)
    rep = anatomy.proc_report(manifest, ledger_rows)
    out = {
        "devices": n,
        "steps": rep["steps"],
        "device_trace": rep["device_trace"],
        "step_ms": rep["per_step_ms"]["step_time_s"],
        "compute_ms": rep["per_step_ms"]["compute_s"],
        "exposed_collective_ms": rep["per_step_ms"]["exposed_collective_s"],
        "host_blocked_ms": rep["per_step_ms"]["host_blocked_s"],
    }
    if "overlap_frac" in rep:
        out["overlap_frac"] = rep["overlap_frac"]
    top = next(
        (r for r in rep["collectives"] if r.get("bytes") and r.get("total_s")),
        None,
    )
    if top is not None:
        out["top_collective"] = {
            "kind": top["kind"],
            "bytes": top["bytes"],
            "mean_us": top.get("mean_us", 0.0),
        }
        if "achieved_gbps" in top:
            out["top_collective"]["achieved_gbps"] = top["achieved_gbps"]
    return out


def collective_overlap_bench(cfg=None, batch: int = 8, seq: int = 64,
                             steps: int = 6, impl: str = "scan") -> dict:
    """The overlap section: one sharded train step captured through the
    real ProfileController path twice — decomposed fsdp collectives +
    bucketed dp grad reduce OFF (GSPMD's blocking weight gathers, single
    fused grad all-reduce) vs ON (ops/overlap ppermute rings +
    bucketed_psum) — so the judged numbers (exposed_collective_ms
    lower-better, overlap_frac higher-better, their off→on ratios) come
    from the same capture/report path `tony profile` uses. The ON run's
    grad-bucket budget is solved from the OFF capture's measured bandwidth
    (bucket_bytes_from_report): the anatomy report drives the knob the
    report then judges — the loop this PR closes."""
    import dataclasses
    import glob as _glob
    import tempfile

    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.obs import anatomy, comms
    from tony_tpu.obs import profile as profile_mod
    from tony_tpu.ops.overlap import bucket_bytes_from_report
    from tony_tpu.parallel.mesh import MeshShape, build_mesh, set_default_mesh
    from tony_tpu.train.trainer import (
        default_optimizer, make_train_state, make_train_step,
    )

    n = len(jax.devices())
    if n < 2:
        return {"error": "collective overlap bench needs >= 2 devices"}
    if cfg is None:
        cfg = LlamaConfig.tiny()
    # fsdp ring (weight gathers) + a dp pair (grad reduce) when devices allow:
    # the two collectives the tentpole decomposes
    dp = 2 if n >= 4 and n % 2 == 0 else 1
    mesh = build_mesh(MeshShape(dp=dp, fsdp=n // dp))
    set_default_mesh(mesh)
    opt = default_optimizer(warmup_steps=2, decay_steps=100)
    tokens = jax.random.randint(
        jax.random.key(1), (batch, seq + 1), 0, cfg.vocab_size
    )
    inputs, targets = tokens[:, :-1], tokens[:, 1:]

    def capture(variant_cfg, bucket_bytes):
        state = make_train_state(jax.random.key(0), variant_cfg, mesh, opt)
        step = make_train_step(
            variant_cfg, mesh, opt, grad_bucket_bytes=bucket_bytes
        )
        ledger_rows = []
        try:
            compiled = step.lower(state, inputs, targets).compile()
            ledger_rows = comms.extract_collectives(compiled)
            step = compiled
        except Exception:
            pass  # lazy jit fallback: ledger-less capture still reports
        out_root = tempfile.mkdtemp(prefix="tony-overlap-")
        ctl = profile_mod.ProfileController(out_root, "bench", watch=False)
        state, m = step(state, inputs, targets)  # warm outside the window
        _fence(m["loss"])
        ctl.trigger(steps=steps)
        for _ in range(steps + 1):
            ctl.step(fetch_s=0.0)
            state, m = step(state, inputs, targets)
            _fence(m["loss"])
        ctl.finish()
        mpaths = _glob.glob(
            os.path.join(out_root, "bench", "*", "manifest.json")
        )
        if not mpaths:
            return {"error": "no capture manifest landed"}
        with open(mpaths[-1]) as fh:
            manifest = json.load(fh)
        rep = anatomy.proc_report(manifest, ledger_rows)
        sec = {
            "step_ms": rep["per_step_ms"]["step_time_s"],
            "compute_ms": rep["per_step_ms"]["compute_s"],
            "exposed_collective_ms": rep["per_step_ms"]["exposed_collective_s"],
            "loss": round(float(m["loss"]), 4),
        }
        for k in ("overlap_frac", "pure_comm_steps"):
            if k in rep:
                sec[k] = rep[k]
        top = next(
            (r for r in rep["collectives"]
             if r.get("bytes") and r.get("total_s")),
            None,
        )
        if top is not None:
            sec["top_collective"] = {
                "kind": top["kind"], "bytes": top["bytes"],
            }
            if "achieved_gbps" in top:
                sec["top_collective"]["achieved_gbps"] = top["achieved_gbps"]
        return sec

    off = capture(dataclasses.replace(cfg, overlap_impl=""), None)
    if "error" in off:
        return off
    bucket_bytes = bucket_bytes_from_report(off, n_layers=cfg.n_layers)
    on = capture(
        dataclasses.replace(cfg, overlap_impl=impl),
        bucket_bytes if dp > 1 else None,
    )
    out = {
        "devices": n,
        "mesh": {"dp": dp, "fsdp": n // dp},
        "impl": impl,
        "grad_bucket_bytes": bucket_bytes,
        "off": off,
        "on": on,
    }
    if "error" not in on:
        # lift the judged keys to the section top so perf_diff's dotted
        # rules (extra.overlap.*) see them without digging into variants
        if "overlap_frac" in on:
            out["overlap_frac"] = on["overlap_frac"]
        out["exposed_collective_ms"] = on["exposed_collective_ms"]
        if off.get("exposed_collective_ms"):
            out["exposed_ratio"] = round(
                on["exposed_collective_ms"] / off["exposed_collective_ms"], 4
            )
        if off.get("step_ms"):
            out["step_ms_ratio"] = round(on["step_ms"] / off["step_ms"], 4)
        # the value-safety receipt: both variants trained on the same
        # batch/state — the decomposition is an execution schedule, not a
        # different model
        if "loss" in off and "loss" in on:
            out["loss_delta"] = round(abs(on["loss"] - off["loss"]), 6)
    return out


def elastic_bench(steps: int = 18, members: int = 2) -> dict:
    """Kill-one-member mid-run (tony_tpu/elastic/, docs/ELASTIC.md): an
    elastic fit over ``members`` device groups shrinks at steps/3 (one
    member "preempted") and grows back at 2*steps/3, under an armed
    tracer. Reports lost steps (the no-cold-restart claim: 0), the
    warm-restart seconds BOTH from the run's own journal and read off
    `tony trace` goodput's restart_s bucket (the elastic.reshard spans),
    and the steady-state step-time ratio after shrink (per-member work is
    constant, so ~1.0 is the target; the dcn2x multislice topology maps
    members onto slices the same way)."""
    import statistics
    import tempfile

    from tony_tpu.config.config import TonyConfig
    from tony_tpu.elastic.protocol import journal_files, read_journal
    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.obs import trace
    from tony_tpu.obs.trace_tool import goodput
    from tony_tpu.train import FitConfig, fit
    from tony_tpu.train.data import DataConfig

    app_dir = tempfile.mkdtemp(prefix="tony-elastic-bench-")
    trace.install_from_config(
        TonyConfig.load(overrides={"trace.sample_steps": 1}),
        app_dir, "elastic-bench", proc="bench_elastic",
    )
    shrink_at, grow_at = steps // 3, (2 * steps) // 3
    seq = 64
    data = DataConfig(global_batch=8, seq_len=seq, vocab_size=256)
    marks: list[dict] = []
    try:
        out = fit(FitConfig(
            model=LlamaConfig.tiny(),
            data=data, steps=steps, log_every=1, warmup_steps=2,
            elastic_members=members,
            elastic_plan={
                shrink_at: tuple(range(members - 1)),
                grow_at: tuple(range(members)),
            },
            elastic_dir=app_dir,
            on_metrics=lambda m: marks.append(dict(m)),
        ))
    finally:
        trace.uninstall()
    g = goodput(app_dir)
    per_member = data.global_batch // members

    def _step_time(phase_members: int, lo: int, hi: int) -> float:
        # per-step wall time from the per-boundary throughput samples:
        # tokens in the window / tokens-per-sec (batch scales with the
        # live membership)
        ts = [
            phase_members * per_member * seq / m["tokens_per_sec"]
            for m in marks
            if lo < m["step"] <= hi and m.get("tokens_per_sec")
        ]
        return statistics.median(ts) if ts else 0.0

    full = _step_time(members, 2, shrink_at)          # warmup excluded
    shrunk = _step_time(members - 1, shrink_at + 1, grow_at)
    lost = sum(
        r.get("lost_steps", 0)
        for p in journal_files(app_dir)
        for r in read_journal(p)
        if r.get("type") == "reshard"
    )
    section = {
        "members": members,
        "steps": steps,
        "reshards": out.get("elastic", {}).get("reshards", 0),
        "lost_steps": lost,
        "restart_s": out.get("elastic", {}).get("reshard_s", 0.0),
        "goodput": {
            "restart_s": g.get("restart_s", 0.0),
            "generation_changes": g.get("generation_changes", 0),
        },
    }
    if full > 0 and shrunk > 0:
        section["step_time_full_ms"] = round(full * 1e3, 2)
        section["step_time_shrunk_ms"] = round(shrunk * 1e3, 2)
        section["shrunk_step_ratio"] = round(shrunk / full, 3)
    return section


def _phased(name: str, fn) -> dict:
    """Run one bench section under its own HBM phase watermark; the
    section's dict gains an ``hbm`` key with the phase-scoped numbers
    (absent on platforms without memory_stats). Errors become the
    section's result, never the bench's."""
    with _hbm_watch().phase(name) as ph:
        try:
            out = fn()
        except Exception as e:
            out = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
    if isinstance(out, dict):
        hk = ph.bench_keys()
        if hk and "hbm" not in out:
            out["hbm"] = hk
    return out


def run_bench() -> dict:
    from tony_tpu.models.llama import LlamaConfig

    on_tpu = jax.devices()[0].platform != "cpu"
    if not on_tpu:  # CPU fallback so the driver always gets a line
        cfg = LlamaConfig.tiny()
        r = train_bench(cfg, batch=4, seq=64, steps=3, mu_dtype=jnp.float32,
                        label="tiny_cpu")
        extra = {"device": jax.devices()[0].device_kind, **r}
        # batch 8: fit()'s default mesh shards batch over every local
        # device (8 virtual CPU devices under the test rig)
        extra["overlap_fit"] = _phased("overlap_fit", lambda: overlap_bench(
            cfg, batch=8, seq=64, steps=6, mu_dtype="float32"
        ))
        extra["decode"] = _phased(
            "decode", lambda: decode_bench(on_tpu=False)
        )
        extra["gqa_capacity"] = _phased("gqa_capacity", gqa_capacity_demo)
        extra["health_overhead"] = _phased(
            "health_overhead", health_overhead_bench
        )
        extra["step_anatomy"] = _phased("step_anatomy", anatomy_bench)
        extra["overlap"] = _phased(
            "overlap", lambda: collective_overlap_bench(cfg, batch=8, seq=64)
        )
        # the MoE ep-combine counterpart through the same capture path
        # (tiny_moe on the virtual-device mesh; the full moe_top2 sweep is
        # TPU-only, but the overlap capture itself must run everywhere)
        extra["moe_top2"] = _phased("moe_top2", lambda: {
            "overlap": moe_overlap_bench(
                LlamaConfig.tiny_moe(), batch=8, seq=64, steps=6, impl="scan"
            ),
        })
        extra["elastic"] = _phased("elastic", elastic_bench)
        return {
            "metric": "llama_tiny_cpu_tokens_per_sec",
            "value": r["tokens_per_sec_per_chip"],
            "unit": "tokens/s/chip",
            "vs_baseline": round(r["mfu"] / 0.45, 4),
            "extra": extra,
        }

    cfg = LlamaConfig.bench_1b4(
        attention_impl="flash", remat_policy="save_attn_kernel",
        ce_impl="scan",  # fused chunked CE: frees the ~2.1GB logits+dlogits
        # transient that made batch 8 OOM at round 3 (docs/PERF.md)
    )
    try:
        main = train_bench(cfg, batch=8, seq=2048, steps=10,
                           mu_dtype=jnp.bfloat16, label="dense_1b4_b8")
        batch_note = "batch 8 (fused CE freed the loss-head transient)"
    except Exception as e:
        # never lose the headline metric to an OOM regression: fall back to
        # the round-3 batch and record why
        main = train_bench(cfg, batch=4, seq=2048, steps=10,
                           mu_dtype=jnp.bfloat16, label="dense_1b4_b4")
        batch_note = f"batch 8 failed ({type(e).__name__}: {str(e)[:120]}); ran batch 4"

    extra = {
        "device": jax.devices()[0].device_kind,
        "n_params": cfg.n_params,
        "remat_policy": cfg.remat_policy,
        "mu_dtype": "bfloat16",
        "ce_impl": cfg.ce_impl,
        "batch_note": batch_note,
        "note": (
            "1.35B is the largest dense config fitting one v5e (16GB HBM) "
            "with AdamW state; llama2_7b needs >56GB and is a multi-chip "
            "config (see dryrun_multichip)"
        ),
        **main,
    }
    try:
        extra["flash_matches_dot_on_tpu"] = flash_matches_dot_on_tpu()
    except Exception as e:
        extra["flash_matches_dot_on_tpu"] = f"{type(e).__name__}: {str(e)[:120]}"
    try:
        extra["fused_ce_matches_dense_on_tpu"] = fused_ce_matches_dense_on_tpu()
    except Exception as e:
        extra["fused_ce_matches_dense_on_tpu"] = f"{type(e).__name__}: {str(e)[:120]}"
    # every section under its own phase watermark: the HBM numbers in each
    # section are scoped to it, never inherited from an earlier one
    extra["ce_head_b8"] = _phased("ce_head_b8", ce_head_bench)
    extra["attn_kernel_s8192"] = _phased("attn_kernel_s8192", kernel_bench_s8192)
    extra["gqa_kernel_32_8"] = _phased("gqa_kernel_32_8", gqa_kernel_bench)
    extra["flash_s32768"] = _phased("flash_s32768", long_context_bench)
    extra["moe_top2"] = _phased("moe_top2", moe_bench)

    def _overlap():
        # same 1.35B config through the REAL input pipeline, prefetch off/on;
        # lifts the stall metric + startup phases to top-level extra keys so
        # the BENCH trajectory tracks them
        # reuse whatever batch the headline run proved fits (8, or the
        # batch-4 fallback) so an OOM can't erase the stall metrics
        return overlap_bench(
            cfg, batch=main["batch"], seq=2048, steps=10, mu_dtype="bfloat16"
        )

    overlap = extra["overlap_fit"] = _phased("overlap_fit", _overlap)
    p2 = overlap.get("prefetch2", {})
    if "host_blocked_ms_per_step" in p2:
        extra["host_blocked_ms_per_step"] = p2["host_blocked_ms_per_step"]
    if "startup" in p2:
        extra["startup_phases"] = p2["startup"]
    # serving: continuous batching vs sequential batch-1 + TTFT + slot
    # occupancy (the decode counterpart of the training headline)
    extra["decode"] = _phased("decode", lambda: decode_bench(on_tpu=True))
    extra["gqa_capacity"] = _phased("gqa_capacity", gqa_capacity_demo)
    extra["health_overhead"] = _phased("health_overhead", health_overhead_bench)
    extra["step_anatomy"] = _phased("step_anatomy", anatomy_bench)
    # decomposed collectives + bucketed grad reduce, off vs on, through the
    # real capture path ('pallas' = the TPU per-chunk kernel form)
    extra["overlap"] = _phased("overlap", lambda: collective_overlap_bench(
        cfg, batch=main["batch"], seq=2048, steps=6, impl="pallas"
    ))
    extra["elastic"] = _phased("elastic", elastic_bench)
    extra["pipeline"] = _phased("pipeline", pipeline_bench)
    extra["submit_to_first_step_s"] = _phased(
        "submit_to_first_step_s", submit_latency_bench
    )

    return {
        "metric": "llama1.4b_train_tokens_per_sec_per_chip",
        "value": main["tokens_per_sec_per_chip"],
        "unit": "tokens/s/chip",
        "vs_baseline": round(main["mfu"] / 0.45, 4),
        "extra": extra,
    }


if __name__ == "__main__":
    try:
        result = run_bench()
    except Exception as e:  # never leave the driver without a line
        result = {
            "metric": "bench_error",
            "value": 0,
            "unit": "tokens/s/chip",
            "vs_baseline": 0.0,
            "extra": {"error": f"{type(e).__name__}: {e}"},
        }
    print(json.dumps(result))
    # canonical on-disk artifact for `tony perf diff <old> <new>` (the
    # cross-run regression gate, obs/perf_diff.py): BENCH_REPORT overrides
    # the destination; failure to write never fails the bench
    try:
        with open(os.environ.get("BENCH_REPORT", "bench_report.json"), "w") as f:
            json.dump(result, f)
    except OSError:
        pass
    sys.exit(0)

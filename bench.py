"""Benchmark: Llama train-step throughput on the local chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s/chip", "vs_baseline": N}

The reference publishes no throughput numbers (BASELINE.md: "published": {});
the driver's north star is tokens/sec/chip and >= 45% MFU, so ``vs_baseline``
reports achieved MFU / 0.45 (1.0 = the north-star target).
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp


def run_bench() -> dict:
    from tony_tpu.models.llama import LlamaConfig, train_flops_per_token
    from tony_tpu.obs.metrics import StepTimer, chip_peak_flops
    from tony_tpu.parallel.mesh import single_device_mesh
    from tony_tpu.train.trainer import default_optimizer, make_train_state, make_train_step

    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        cfg = LlamaConfig.bench_1b4(attention_impl="flash")
        batch, seq, steps = 4, 2048, 10
    else:  # CPU fallback so the driver always gets a line
        cfg = LlamaConfig.tiny()
        batch, seq, steps = 4, 64, 3

    mesh = single_device_mesh()
    opt = default_optimizer(warmup_steps=10, decay_steps=1000)
    state = make_train_state(jax.random.key(0), cfg, mesh, opt)
    step = make_train_step(cfg, mesh, opt)
    tokens = jax.random.randint(jax.random.key(1), (batch, seq + 1), 0, cfg.vocab_size)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]

    # warmup / compile. NOTE: float() (device_get) is the sync point --
    # block_until_ready is not a reliable fence on the axon relay platform.
    state, metrics = step(state, inputs, targets)
    state, metrics = step(state, inputs, targets)
    float(metrics["loss"])

    timer = StepTimer(
        flops_per_token=train_flops_per_token(cfg, seq),
        tokens_per_step=batch * seq,
        n_chips=1,
    )
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, inputs, targets)
    final_loss = float(metrics["loss"])  # sync fence
    timer.record(time.perf_counter() - t0, steps)

    peak = chip_peak_flops()
    mfu = timer.mfu(peak)
    return {
        "metric": "llama1.4b_train_tokens_per_sec_per_chip"
        if on_tpu
        else "llama_tiny_cpu_tokens_per_sec",
        "value": round(timer.tokens_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "device": jax.devices()[0].device_kind,
            "n_params": cfg.n_params,
            "batch": batch,
            "seq": seq,
            "steps": steps,
            "loss": round(final_loss, 4),
        },
    }


if __name__ == "__main__":
    try:
        result = run_bench()
    except Exception as e:  # never leave the driver without a line
        result = {
            "metric": "bench_error",
            "value": 0,
            "unit": "tokens/s/chip",
            "vs_baseline": 0.0,
            "extra": {"error": f"{type(e).__name__}: {e}"},
        }
    print(json.dumps(result))
    sys.exit(0)

"""Flagship example: distributed Llama pretraining under `tony submit`.

Reference parity: tony-examples' mnist-tensorflow / horovod jobs were the
"real training" samples (SURVEY.md section 2 "tony-examples"); this is the
TPU-era equivalent — the same script runs single-chip or multi-host purely
by config (milestone config #4: multi-host JAX Llama DP).

Submit:
    python -m tony_tpu.cli submit --conf examples/llama_pretrain/tony.toml \
        --src-dir examples/llama_pretrain
Standalone (single chip):
    python examples/llama_pretrain/train.py --preset tiny --steps 20
"""

import argparse
import logging
import os

import jax


def main() -> None:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="tiny",
                   choices=["tiny", "bench_410m", "llama2_7b", "llama3_8b"])
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--checkpoint-dir", default=os.environ.get("TONY_CHECKPOINT_DIR", ""))
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument("--attention", default="", help="dot | flash | ring")
    p.add_argument("--ce-impl", default="",
                   help="loss head: scan (fused, default) | pallas | dense")
    p.add_argument("--moe-dispatch", default="",
                   help="MoE dispatch: grouped (dropless) | gather | einsum")
    p.add_argument("--prefetch", type=int, default=2,
                   help="device-prefetch depth (0 = synchronous input path)")
    args = p.parse_args()

    # jax.distributed bootstrap happens inside fit() via the TONY_* env.
    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.train import DataConfig, FitConfig, fit

    model = getattr(LlamaConfig, args.preset)()
    if args.attention:
        from dataclasses import replace

        model = replace(model, attention_impl=args.attention)
    final = fit(
        FitConfig(
            model=model,
            data=DataConfig(
                global_batch=args.global_batch,
                seq_len=args.seq_len,
                vocab_size=model.vocab_size,
                prefetch=args.prefetch,
            ),
            steps=args.steps,
            log_every=max(args.steps // 10, 1),
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            ce_impl=args.ce_impl,
            moe_dispatch=args.moe_dispatch,
        )
    )
    if jax.process_index() == 0:
        print("final:", final)


if __name__ == "__main__":
    main()

"""MNIST-shaped MLP classifier (milestone config #1, JAX edition).

The reference's canonical smoke job is single-worker TensorFlow MNIST via
CLI submit (BASELINE.json configs[0]); this is the same job on the
first-class JAX runtime. The environment is zero-egress, so the dataset is a
deterministic synthetic stand-in with the same (28x28 -> 10) shape; swap
``load_data`` for real MNIST arrays where a download cache exists.

Submit:  python -m tony_tpu.cli submit --conf examples/mnist_jax/tony.toml \
             --src-dir examples/mnist_jax
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import optax

import tony_tpu.runtime.jax_tpu as rt


def load_data(n=4096, seed=0):
    """Synthetic 10-class 'digits': class-dependent blob patterns + noise."""
    rng = np.random.default_rng(seed)
    protos = rng.normal(0, 1, (10, 784)).astype(np.float32)
    labels = rng.integers(0, 10, n)
    x = protos[labels] + rng.normal(0, 2.0, (n, 784)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(labels)


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    rt.initialize()  # no-op standalone; multi-proc under tony submit

    x, y = load_data()
    params = {
        "w1": jax.random.normal(jax.random.key(0), (784, 128)) * 0.05,
        "b1": jnp.zeros(128),
        "w2": jax.random.normal(jax.random.key(1), (128, 10)) * 0.05,
        "b2": jnp.zeros(10),
    }
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def loss_fn(p, xb, yb):
        h = jax.nn.relu(xb @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

    @jax.jit
    def step(p, s, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(p, xb, yb)
        updates, s = opt.update(grads, s)
        return optax.apply_updates(p, updates), s, loss

    loss = None
    for i in range(100):
        idx = jax.random.randint(jax.random.key(i), (256,), 0, x.shape[0])
        params, opt_state, loss = step(params, opt_state, x[idx], y[idx])
    final = float(loss)
    print(f"process {rt.process_id()}: final loss {final:.4f}")
    assert final < 1.5, "training diverged"


if __name__ == "__main__":
    main()

"""PyTorch DDP example (milestone config #3 shape, gloo on CPU).

The reference's Horovod ring-allreduce BERT job maps to torch.distributed
DDP here: the AM-assigned env (MASTER_ADDR/PORT, RANK, WORLD_SIZE — exported
by PyTorchRuntime) drives torch's env:// rendezvous, and the allreduce rides
gloo on CPU. On TPU the same model family runs through the JAX path
(tony_tpu.models + lax.psum over ICI, the BASELINE.json mapping) — this
script is the migration-parity lane for existing torch jobs.

Submit:  python -m tony_tpu.cli submit --conf examples/bert_pytorch/tony.toml \
             --src-dir examples/bert_pytorch
"""

import os

import torch
import torch.distributed as dist
import torch.nn as nn


class TinyBertBlock(nn.Module):
    """One transformer encoder block at BERT-base width (compute shape only)."""

    def __init__(self, dim=768, heads=12):
        super().__init__()
        self.attn = nn.MultiheadAttention(dim, heads, batch_first=True)
        self.ln1 = nn.LayerNorm(dim)
        self.ff = nn.Sequential(nn.Linear(dim, 3072), nn.GELU(), nn.Linear(3072, dim))
        self.ln2 = nn.LayerNorm(dim)

    def forward(self, x):
        a, _ = self.attn(x, x, x, need_weights=False)
        x = self.ln1(x + a)
        return self.ln2(x + self.ff(x))


def main() -> None:
    rank = int(os.environ["RANK"])
    world = int(os.environ["WORLD_SIZE"])
    dist.init_process_group("gloo", rank=rank, world_size=world)
    torch.manual_seed(0)

    model = nn.Sequential(TinyBertBlock(), nn.Linear(768, 2))
    ddp = nn.parallel.DistributedDataParallel(model)
    opt = torch.optim.AdamW(ddp.parameters(), lr=1e-4)
    loss_fn = nn.CrossEntropyLoss()

    for step in range(5):
        x = torch.randn(4, 32, 768)
        y = torch.randint(0, 2, (4,))
        opt.zero_grad()
        out = ddp(x).mean(dim=1)
        loss = loss_fn(out, y)
        loss.backward()  # gloo allreduce happens here
        opt.step()
    print(f"rank {rank}/{world}: final loss {loss.item():.4f}")
    dist.destroy_process_group()


if __name__ == "__main__":
    main()

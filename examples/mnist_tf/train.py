"""TF ps/worker example (milestone config #2 shape, CPU mode).

The reference's ParameterServerStrategy job: TonY exports TF_CONFIG and
TensorFlow self-organises (SURVEY.md section 3.2). Same contract here via
TFRuntime. ps tasks run tf.distribute's coordinator-less server; workers
train a small classifier on synthetic data (zero-egress environment).

Submit:  python -m tony_tpu.cli submit --conf examples/mnist_tf/tony.toml \
             --src-dir examples/mnist_tf
"""

import json
import os


def main() -> None:
    tf_config = json.loads(os.environ["TF_CONFIG"])
    task = tf_config["task"]
    print(f"TF task {task['type']}:{task['index']} cluster={sorted(tf_config['cluster'])}")

    import tensorflow as tf

    if task["type"] == "ps":
        # Parameter servers block serving variables until the job ends; the
        # AM marks ps untracked so worker completion finishes the job.
        server = tf.distribute.Server(
            tf.train.ClusterSpec(tf_config["cluster"]),
            job_name="ps",
            task_index=task["index"],
        )
        server.join()
        return

    # Worker: plain in-process training (MultiWorker/PS strategies need
    # >1 real host to be meaningful; the env contract is what's under test).
    import numpy as np

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048, 784)).astype("float32")
    y = rng.integers(0, 10, 2048)
    model = tf.keras.Sequential(
        [tf.keras.layers.Dense(64, activation="relu"), tf.keras.layers.Dense(10)]
    )
    model.compile(
        optimizer="adam",
        loss=tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True),
    )
    hist = model.fit(x, y, epochs=1, batch_size=128, verbose=0)
    print("final loss:", hist.history["loss"][-1])


if __name__ == "__main__":
    main()

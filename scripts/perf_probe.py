"""Step-decomposition probes on the real chip (run with
PYTHONPATH=/root/repo:/root/.axon_site to keep the axon sitecustomize).

Measures, per remat policy: fwd-only loss time, fwd+bwd time and their
ratio (full recompute ~4x fwd, no recompute ~3x), and MFU — the numbers
behind bench.py's tuning choices. See also /tmp traces via jax.profiler.
"""

import functools
import time

import jax
import jax.numpy as jnp

from tony_tpu.models.llama import LlamaConfig, init_params, loss_from_pairs, train_flops_per_token
from tony_tpu.obs.metrics import chip_peak_flops

B, S = 4, 2048
peak = chip_peak_flops()


def fence(out):
    float(jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32)))


def timeit(fn, *args, n=10, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    fence(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    fence(out)
    return (time.perf_counter() - t0) / n


def main() -> None:
    tokens = jax.random.randint(jax.random.key(1), (B, S + 1), 0, 32000)
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    for policy in ["nothing", "save_attn_kernel", "save_attn_gate"]:
        cfg = LlamaConfig.bench_1b4(attention_impl="flash", remat_policy=policy)
        params = jax.jit(functools.partial(init_params, cfg=cfg))(jax.random.key(0))
        lossf = jax.jit(functools.partial(loss_from_pairs, cfg=cfg))
        gradf = jax.jit(jax.value_and_grad(functools.partial(loss_from_pairs, cfg=cfg)))
        t_fwd = timeit(lossf, params, inp, tgt)
        t_grad = timeit(gradf, params, inp, tgt)
        counted = B * S * train_flops_per_token(cfg, S)
        print(
            f"policy={policy}: fwd {t_fwd*1e3:.1f}ms grad {t_grad*1e3:.1f}ms "
            f"ratio {t_grad/t_fwd:.2f} grad-mfu {counted/t_grad/peak:.3f}",
            flush=True,
        )


if __name__ == "__main__":
    main()

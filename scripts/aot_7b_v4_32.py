"""AOT proof of the north-star config: Llama-2-7B sharded over a v4-32 slice.

The north star (BASELINE.json ``north_star``, SURVEY.md section 6 config #4)
is "multi-host JAX Llama-2-7B data-parallel on a v4-32 at >=45% MFU". No pod
slice is attached to this rig, but JAX can prove the sharding STATICALLY:
lower + compile the full production train step (bf16 params, AdamW with bf16
mu, save_attn_kernel remat, flash attention) for the REAL 7B shapes over a
32-device mesh of virtual CPU devices, then read the compiler's own
per-device buffer assignment (``compiled.memory_analysis()``) against the
v4 chip's 32GB HBM budget.

Two shardings are analyzed:

- ``fsdp32``       -- one slice, params/optimizer sharded 32-way (ZeRO-3).
- ``dcn2xfsdp16``  -- two slices x 16 chips: ``build_multislice_mesh`` puts
  the gradient-allreduce ``dp`` axis across DCN and keeps the
  bandwidth-hungry fsdp all-gathers inside each slice's ICI.

Caveat stated up front: the buffer assignment comes from the CPU backend, so
exact padding/fusion differs from TPU; the point is that the ACTUAL 7B
parameter, optimizer, gradient, and remat-activation buffers partition onto
32 devices with headroom, not a bytes-exact TPU number.

Run: ``python scripts/aot_7b_v4_32.py`` (forces 32 virtual CPU devices).
Emits one JSON line per variant plus a summary verdict line.
"""

from __future__ import annotations

import json
import os
import time

N_DEVICES = 32
V4_HBM_GB = 32.0  # HBM per v4 chip
V4_PEAK_BF16_TFLOPS = 275.0  # per-chip peak, dense bf16

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        f"{_flags} --xla_force_host_platform_device_count={N_DEVICES}".strip()
    )

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402


def analyze(name: str, mesh, cfg, batch: int, seq: int) -> dict:
    from functools import partial

    from tony_tpu.models.llama import init_params, train_flops_per_token
    from tony_tpu.train.trainer import (
        TrainState,
        default_optimizer,
        make_train_step,
    )

    opt = default_optimizer(mu_dtype=jnp.bfloat16)  # the bench configuration
    step = make_train_step(cfg, mesh, opt)
    params_shape = jax.eval_shape(partial(init_params, cfg=cfg), jax.random.key(0))
    opt_shape = jax.eval_shape(opt.init, params_shape)
    state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params=params_shape,
        opt_state=opt_shape,
    )
    tok = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    t0 = time.time()
    compiled = step.lower(state, tok, tok).compile()
    ma = compiled.memory_analysis()
    # outputs alias the donated state; what's left is genuinely new bytes
    per_device = (
        ma.argument_size_in_bytes
        + ma.temp_size_in_bytes
        + ma.output_size_in_bytes
        - ma.alias_size_in_bytes
    )
    tokens = batch * seq
    flops_step = train_flops_per_token(cfg, seq) * tokens
    gb = per_device / (1 << 30)
    result = {
        "variant": name,
        "n_devices": N_DEVICES,
        "per_device_gb": round(gb, 2),
        "hbm_budget_gb": V4_HBM_GB,
        "fits": gb <= V4_HBM_GB,
        "headroom_gb": round(V4_HBM_GB - gb, 2),
        "argument_gb": round(ma.argument_size_in_bytes / (1 << 30), 2),
        "temp_gb": round(ma.temp_size_in_bytes / (1 << 30), 2),
        "batch": batch,
        "seq": seq,
        "tokens_per_step": tokens,
        "tflops_per_step_per_chip": round(flops_step / N_DEVICES / 1e12, 1),
        "step_s_at_45pct_mfu": round(
            flops_step / N_DEVICES / (0.45 * V4_PEAK_BF16_TFLOPS * 1e12), 2
        ),
        "compile_s": round(time.time() - t0, 1),
    }
    print(json.dumps({"aot_7b": result}), flush=True)
    return result


def main() -> None:
    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.parallel.mesh import MeshShape, build_mesh, build_multislice_mesh
    from tony_tpu.train.presets import north_star_7b_v4_32

    cfg, shape, batch, seq = north_star_7b_v4_32()
    assert shape.n_devices == N_DEVICES
    devices = jax.devices()[:N_DEVICES]
    results = [
        analyze("fsdp32", build_mesh(shape, devices=devices), cfg, batch, seq),
        analyze(
            "dcn2xfsdp16",
            build_multislice_mesh(
                MeshShape(fsdp=N_DEVICES // 2), n_slices=2, devices=devices
            ),
            cfg,
            batch,
            seq,
        ),
    ]
    n7b = LlamaConfig.llama2_7b().n_params
    ok = all(r["fits"] for r in results)
    print(
        f"aot_7b verdict: llama2_7b ({n7b/1e9:.2f}B params) v4-32 "
        + ", ".join(f"{r['variant']}: {r['per_device_gb']}GB" for r in results)
        + f" per device <= {V4_HBM_GB:.0f}GB budget -> "
        + ("FITS" if ok else "DOES NOT FIT")
    )
    if not ok:
        raise SystemExit(1)


if __name__ == "__main__":
    main()

"""Single-chip perf sweep for the bench_1b4 train step.

Runs each candidate config in a fresh subprocess (clean HBM, no allocator
carry-over) and appends one JSON line per config to sweep_results.jsonl.

Usage:
  python scripts/perf_sweep.py            # run the default grid
  python scripts/perf_sweep.py --one '{"remat_policy": "save_attn"}'
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

RESULTS = os.path.join(os.path.dirname(__file__), "sweep_results.jsonl")

CHILD = r"""
import json, sys, time
cfg_kw = json.loads(sys.argv[1])
batch = cfg_kw.pop("batch", 4)
steps = cfg_kw.pop("steps", 10)
seq = cfg_kw.pop("seq", 2048)
mu_dtype = cfg_kw.pop("mu_dtype", "float32")
preset = cfg_kw.pop("preset", "bench_1b4")

import jax
import jax.numpy as jnp
import optax
from tony_tpu.models.llama import LlamaConfig, train_flops_per_token
from tony_tpu.obs.metrics import StepTimer, chip_peak_flops
from tony_tpu.parallel.mesh import single_device_mesh
from tony_tpu.train.trainer import make_train_state, make_train_step

cfg_kw.setdefault("attention_impl", "flash")
cfg = getattr(LlamaConfig, preset)(**cfg_kw)
mesh = single_device_mesh()
sched = optax.warmup_cosine_decay_schedule(0.0, 3e-4, 10, 1000)
opt = optax.chain(
    optax.clip_by_global_norm(1.0),
    optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=0.1,
                mu_dtype=getattr(jnp, mu_dtype)),
)
state = make_train_state(jax.random.key(0), cfg, mesh, opt)
step = make_train_step(cfg, mesh, opt)
tokens = jax.random.randint(jax.random.key(1), (batch, seq + 1), 0, cfg.vocab_size)
inputs, targets = tokens[:, :-1], tokens[:, 1:]

state, metrics = step(state, inputs, targets)
state, metrics = step(state, inputs, targets)
float(metrics["loss"])  # sync

timer = StepTimer(train_flops_per_token(cfg, seq), batch * seq, 1)
t0 = time.perf_counter()
for _ in range(steps):
    state, metrics = step(state, inputs, targets)
loss = float(metrics["loss"])  # sync fence
timer.record(time.perf_counter() - t0, steps)
mfu = timer.mfu(chip_peak_flops())
mem = jax.local_devices()[0].memory_stats() or {}
print("RESULT " + json.dumps({
    "tok_s": round(timer.tokens_per_sec_per_chip, 1),
    "mfu": round(mfu, 4),
    "loss": round(loss, 4),
    "peak_hbm_gb": round(mem.get("peak_bytes_in_use", 0) / 2**30, 2),
}))
"""


def run_one(cfg: dict, timeout: int = 600) -> dict:
    try:
        out = subprocess.run(
            [sys.executable, "-c", CHILD, json.dumps(cfg)],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return {"cfg": cfg, "error": f"timeout after {timeout}s"}
    rec = {"cfg": cfg}
    for line in out.stdout.splitlines():
        if line.startswith("RESULT "):
            rec.update(json.loads(line[len("RESULT "):]))
            break
    else:
        tail = (out.stderr or out.stdout).strip().splitlines()[-12:]
        rec["error"] = "\n".join(tail)
    return rec


GRID = [
    # baseline = round-2 shipped config
    {"remat_policy": "nothing"},
    # remat save-point sweep
    {"remat_policy": "save_attn"},
    {"remat_policy": "save_gate"},
    {"remat_policy": "save_attn_gate"},
    {"remat_policy": "checkpoint_dots"},
    # no remat at all (likely OOM at B=4 -- worth knowing)
    {"remat_policy": "nothing", "no_remat": 1},
    # flash tile sweep at the best remat policy guess
    {"remat_policy": "save_attn_gate", "flash_block_q": 256, "flash_block_k": 512},
    {"remat_policy": "save_attn_gate", "flash_block_q": 1024, "flash_block_k": 1024},
    {"remat_policy": "save_attn_gate", "flash_block_q": 512, "flash_block_k": 512},
    {"remat_policy": "save_attn_gate", "flash_block_q": 1024, "flash_block_k": 2048},
    # dot-attention comparison
    {"remat_policy": "save_attn_gate", "attention_impl": "dot"},
    # scan unroll
    {"remat_policy": "save_attn_gate", "scan_unroll": 2},
    {"remat_policy": "save_attn_gate", "scan_unroll": 4},
    # bf16 first moment frees ~2.7GB HBM -> bigger batch may fit
    {"remat_policy": "save_attn_gate", "mu_dtype": "bfloat16", "batch": 8},
    {"remat_policy": "save_attn", "mu_dtype": "bfloat16", "batch": 8},
    {"remat_policy": "nothing", "batch": 8},
]


def main() -> None:
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        grid = [json.loads(sys.argv[2])]
    else:
        grid = GRID
    for cfg in grid:
        if cfg.pop("no_remat", None):
            cfg = {**cfg, "remat": False}
        rec = run_one(cfg)
        with open(RESULTS, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()

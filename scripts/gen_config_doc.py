"""Generate docs/CONFIG.md from the config key registry (single source of
truth: tony_tpu/config/keys.py). Re-run after adding keys."""

import inspect
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tony_tpu.config import keys as K  # noqa: E402


def main() -> None:
    src = inspect.getsource(K.Keys)
    lines = ["# Configuration reference", "",
             "Generated from `tony_tpu/config/keys.py` by "
             "`scripts/gen_config_doc.py` — do not edit by hand.",
             "",
             "Layering (low to high precedence): baked defaults → TOML file "
             "→ `-D key=value` CLI overrides → `TONY_CONF_section__key` env.",
             "", "| key | default | notes |", "|---|---|---|"]
    comment = []
    for raw in src.splitlines():
        line = raw.strip()
        if line.startswith("#"):
            text = line.lstrip("# ")
            if not text.startswith("---"):  # skip section markers
                comment.append(text)
            continue
        m = re.match(r'([A-Z_]+) = "([^"]+)"(?:\s*#\s*(.*))?', line)
        if not m:
            if not line:
                comment = []
            continue
        attr, key, inline = m.groups()
        default = K.DEFAULTS.get(key, "—")
        if default == "":
            default = '""'
        note = (inline or " ".join(comment)).replace("|", "\\|")
        comment = []
        lines.append(f"| `{key}` | `{default}` | {note} |")
    lines += ["",
              "## Per-jobtype keys (`job.<type>.*`)", "",
              "| suffix | meaning |", "|---|---|"]
    suffix_doc = {
        "instances": "container count for this task type",
        "memory_mb": "per-container memory ask",
        "cpus": "per-container vcores",
        "tpu_chips": "per-container TPU chips (the yarn.io/gpu analogue)",
        "command": "the user process to exec",
        "env": "extra env (`[\"K=V\", ...]` or table)",
        "depends_on": "launch gating on another task type",
        "depends_timeout_s": "dependency wait budget",
        "untracked": "excluded from job status (e.g. tensorboard)",
        "node_label": "placement constraint (RemoteBackend host labels)",
    }
    for s in K.JOB_SUFFIXES:
        lines.append(f"| `{s}` | {suffix_doc.get(s, '')} |")
    out = os.path.join(os.path.dirname(__file__), "..", "docs", "CONFIG.md")
    with open(out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {os.path.abspath(out)} ({len(lines)} lines)")


if __name__ == "__main__":
    main()

"""Generate docs/CONFIG.md from the config key registry (single source of
truth: tony_tpu/config/keys.py). Re-run after adding keys, or run with
``--check`` (CI / tier-1) to exit nonzero when docs/CONFIG.md is stale."""

import inspect
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tony_tpu.config import keys as K  # noqa: E402

OUT = os.path.join(os.path.dirname(__file__), "..", "docs", "CONFIG.md")


def build() -> str:
    src = inspect.getsource(K.Keys)
    lines = ["# Configuration reference", "",
             "Generated from `tony_tpu/config/keys.py` by "
             "`scripts/gen_config_doc.py` — do not edit by hand.",
             "",
             "Layering (low to high precedence): baked defaults → TOML file "
             "→ `-D key=value` CLI overrides → `TONY_CONF_section__key` env.",
             "", "| key | default | notes |", "|---|---|---|"]
    comment = []
    for raw in src.splitlines():
        line = raw.strip()
        if line.startswith("#"):
            text = line.lstrip("# ")
            if not text.startswith("---"):  # skip section markers
                comment.append(text)
            continue
        m = re.match(r'([A-Z0-9_]+) = "([^"]+)"(?:\s*#\s*(.*))?', line)
        if not m:
            if not line:
                comment = []
            continue
        attr, key, inline = m.groups()
        default = K.DEFAULTS.get(key, "—")
        if default == "":
            default = '""'
        note = (inline or " ".join(comment)).replace("|", "\\|")
        comment = []
        lines.append(f"| `{key}` | `{default}` | {note} |")
    lines += ["",
              "## Per-jobtype keys (`job.<type>.*`)", "",
              "| suffix | meaning |", "|---|---|"]
    suffix_doc = {
        "instances": "container count for this task type",
        "memory_mb": "per-container memory ask",
        "cpus": "per-container vcores",
        "tpu_chips": "per-container TPU chips (the yarn.io/gpu analogue)",
        "command": "the user process to exec",
        "env": "extra env (`[\"K=V\", ...]` or table)",
        "depends_on": "launch gating on another task type",
        "depends_timeout_s": "dependency wait budget",
        "untracked": "excluded from job status (e.g. tensorboard)",
        "node_label": "placement constraint (RemoteBackend host labels)",
    }
    for s in K.JOB_SUFFIXES:
        lines.append(f"| `{s}` | {suffix_doc.get(s, '')} |")
    lines += _data_config_section()
    lines += _fit_config_section()
    lines += _serve_config_section()
    return "\n".join(lines) + "\n"


def main(argv: list[str]) -> int:
    content = build()
    if "--check" in argv:
        try:
            with open(OUT) as f:
                current = f.read()
        except FileNotFoundError:
            current = ""
        if current != content:
            print(
                f"{os.path.abspath(OUT)} is stale — rerun "
                "scripts/gen_config_doc.py",
                file=sys.stderr,
            )
            return 1
        print(f"{os.path.abspath(OUT)} is up to date")
        return 0
    with open(OUT, "w") as f:
        f.write(content)
    print(f"wrote {os.path.abspath(OUT)} ({content.count(chr(10))} lines)")
    return 0


def _data_config_section() -> list[str]:
    """Document fit()'s input-pipeline knobs (`data.*` on DataConfig): they
    are Python-API fields set in the training script, not job-file keys,
    but belong in the same reference."""
    import dataclasses

    from tony_tpu.train.data import DataConfig

    notes = {
        "global_batch": "global batch size, divided evenly across processes",
        "seq_len": "tokens per sequence (targets are inputs shifted by one)",
        "vocab_size": "synthetic-stream vocabulary (Zipf marginals)",
        "seed": "synthetic-stream seed; generation is keyed per (seed, step) "
                "so checkpoint resume continues the stream exactly",
        "path": "flat binary int32 token file; empty selects the synthetic "
                "stream",
        "native": "route token files through the C++ prefetching loader "
                  "(shuffled epochs) when it can build; False pins the "
                  "numpy mmap path",
        "prefetch": "device-prefetch depth: batches N+1..N+depth are "
                    "host-generated and device-placed on a background "
                    "thread while the device runs step N; 0 pins the "
                    "synchronous legacy path. Stream order and loss "
                    "trajectory are identical either way (docs/PERF.md "
                    "\"Overlap\")",
    }
    lines = ["", "## Training data (`DataConfig`, Python API)", "",
             "Set on `FitConfig.data` in the training script (e.g. "
             "`DataConfig(prefetch=4)`); these are not job-file keys.", "",
             "| field | default | notes |", "|---|---|---|"]
    for f in dataclasses.fields(DataConfig):
        default = f.default
        default = '""' if default == "" else f"{default}"
        lines.append(
            f"| `data.{f.name}` | `{default}` | "
            f"{notes.get(f.name, '').replace('|', chr(92) + '|')} |"
        )
    return lines


def _fit_config_section() -> list[str]:
    """Document fit()'s trainer knobs (`FitConfig`, scalar fields only —
    `model`/`data`/`rules`/`mesh_shape` are structured Python values with
    their own references)."""
    import dataclasses

    from tony_tpu.train.loop import FitConfig

    notes = {
        "steps": "optimizer steps to run",
        "log_every": "metrics log/push cadence (the first step always logs)",
        "checkpoint_dir": "orbax checkpoint root; empty disables checkpoints",
        "checkpoint_every": "save cadence in steps (0 = only the final save)",
        "checkpoint_keep": "checkpoints retained (older ones pruned)",
        "lr": "peak learning rate (warmup-cosine schedule)",
        "warmup_steps": "linear warmup steps to peak lr",
        "pp_microbatches": "pipeline microbatches when mesh_shape.pp > 1 "
                           "(0 -> 2 per stage)",
        "pp_schedule": "gpipe (autodiff bwd, O(M) activations) \\| 1f1b "
                       "(interleaved bwd, O(P) activations)",
        "resume": "restore from checkpoint_dir when a checkpoint exists",
        "compile_ahead": "AOT-compile the train step on a worker thread "
                         "during startup (docs/PERF.md \"Overlap\")",
        "mu_dtype": "Adam first-moment dtype (float32 \\| bfloat16); bf16 "
                    "frees 2 bytes/param of HBM",
        "ce_impl": "loss-head override: empty keeps model.ce_impl; scan / "
                   "pallas select the fused chunked CE (no [B,S,V] logits "
                   "transient — docs/PERF.md \"Fused cross-entropy\"), "
                   "dense the legacy full-logits head. Chunk/tile sizes: "
                   "`LlamaConfig.ce_vocab_chunk` / `ce_block_n` / "
                   "`ce_block_v`",
        "moe_dispatch": "MoE dispatch override: empty keeps "
                        "model.moe_dispatch; grouped selects the dropless "
                        "sorted grouped GEMM (no capacity slots, no dropped "
                        "tokens — docs/PERF.md \"Grouped MoE\"), gather / "
                        "einsum the fixed-capacity paths. Kernel choice: "
                        "`LlamaConfig.moe_gmm_impl` (scan \\| pallas)",
        "overlap_impl": "comm/compute overlap override: empty keeps "
                        "model.overlap_impl; scan / pallas stream the fsdp "
                        "weight all-gathers through the decomposed "
                        "ppermute-ring matmuls instead of blocking up "
                        "front (`tony_tpu.ops.overlap` — docs/PERF.md "
                        "\"Overlap (collectives)\")",
        "grad_bucket_mb": "dp gradient-reduction bucket size in MiB (0 "
                          "keeps GSPMD's single fused all-reduce); > 0 "
                          "switches to the manual-dp bucketed path — one "
                          "collective per ~bucket of grad leaves, each "
                          "dispatching as its layers' backward completes. "
                          "Size from the measured anatomy report: "
                          "`ops.overlap.bucket_bytes_from_report`. Needs "
                          "dp > 1, pp == 1",
        "moe_group_block": "grouped-GEMM row tile override (0 keeps "
                           "`model.moe_group_block`); each expert's ragged "
                           "token group pads up to a multiple of this",
        "moe_overlap_impl": "overlapped expert-parallel combine override: "
                            "empty keeps `model.moe_overlap_impl`; scan / "
                            "pallas decompose the post-FFN ep psum into "
                            "per-token-chunk partial combines that overlap "
                            "the next chunk's grouped FFN "
                            "(`tony_tpu.ops.moe_overlap` — docs/PERF.md "
                            "\"Round 20\"). Needs ep > 1 and grouped "
                            "dispatch; declines cleanly otherwise",
        "moe_overlap_chunk": "tokens per combine chunk (0 sizes from the "
                             "measured anatomy report via "
                             "`ops.moe_overlap.chunk_tokens_from_report`, "
                             "or auto-picks a divisor); must divide the "
                             "per-shard token count and leave >= 2 chunks, "
                             "else the single-psum path is kept",
        "elastic_members": "elastic gang size at full strength (0 disables; "
                           ">= 2 makes the mesh runtime-swappable — dp maps "
                           "to members and shrinks/grows at generation "
                           "boundaries, docs/ELASTIC.md). In-job this arms "
                           "from the TONY_ELASTIC* env",
        "elastic_dir": "generation-broadcast + journal root; empty uses "
                       "TONY_APP_DIR (the shared app dir the AM writes "
                       "generation.json into)",
        "elastic_shadow_steps": "async device->host checkpoint-shadow "
                                "stride in steps (0 -> env/default 16); "
                                "each shadow briefly holds one extra state "
                                "replica on device",
    }
    # structured Python values with their own references (elastic_plan is
    # the scripted {step: members} membership plan bench/tests drive)
    skip = {"model", "data", "rules", "mesh_shape", "on_metrics",
            "elastic_plan"}
    lines = ["", "## Trainer (`FitConfig`, Python API)", "",
             "Set on `fit(FitConfig(...))` in the training script; these are "
             "not job-file keys. `model` (LlamaConfig), `data` (DataConfig "
             "above), `mesh_shape` (MeshShape) and `rules` carry the "
             "structured configs.", "",
             "| field | default | notes |", "|---|---|---|"]
    for f in dataclasses.fields(FitConfig):
        if f.name in skip:
            continue
        default = f.default
        default = '""' if default == "" else f"{default}"
        lines.append(f"| `{f.name}` | `{default}` | {notes.get(f.name, '')} |")
    return lines


def _serve_config_section() -> list[str]:
    """Document the decode engine's knobs (`ServeConfig`, Python API —
    docs/SERVE.md has the architecture and sizing guidance)."""
    import dataclasses

    from tony_tpu.serve.engine import ServeConfig

    notes = {
        "slots": "concurrent decode slots (the static batch width of the "
                 "one jitted decode step); a finished request frees its "
                 "slot for the admission queue",
        "max_len": "longest prompt+generation admitted (0 -> "
                   "model.max_seq_len)",
        "kv_block": "KV cache block size: capacity grows/shrinks in "
                    "multiples of this and the decode kernel tiles the "
                    "sequence by it (docs/SERVE.md)",
        "prefill_buckets": "prompt pad lengths — prefill compiles once per "
                           "bucket (bounded compile count); () -> powers "
                           "of two from 16 up to max_len",
        "decode_impl": "decode attention kernel: scan (pure XLA, default) "
                       "\\| pallas (TPU kernel, interpreted on CPU) — "
                       "tony_tpu.ops.decode_attention",
        "max_top_k": "static top-k slice width for sampling; per-request "
                     "top_k clamps to it, and top-p-only requests use it "
                     "as the bounded nucleus candidate set",
        "shrink": "release cache blocks when the live maximum drops to "
                  "half the capacity (each capacity change recompiles the "
                  "decode step once)",
    }
    lines = ["", "## Serving (`ServeConfig`, Python API)", "",
             "Set on `Engine(params, cfg, ServeConfig(...))` "
             "(tony_tpu.serve); `generate()` builds one internally. These "
             "are not job-file keys.", "",
             "| field | default | notes |", "|---|---|---|"]
    for f in dataclasses.fields(ServeConfig):
        default = f.default
        default = '""' if default == "" else f"{default}"
        lines.append(f"| `{f.name}` | `{default}` | {notes.get(f.name, '')} |")
    return lines


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

#!/usr/bin/env python
"""CI entry point for graft-lint: exit nonzero on NEW findings.

Sits next to ``gen_config_doc.py --check`` in the tier-1 gate family:
``tests/test_lint.py::test_codebase_is_lint_clean`` runs the same check
in-process. Usage::

    python scripts/lint.py                 # lint tony_tpu/ vs the baseline
    python scripts/lint.py --update-baseline   # re-record the baseline
"""

import os
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

from tony_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    os.chdir(REPO)  # paths (and the default baseline) are repo-relative
    argv = sys.argv[1:]
    if not any(a for a in argv if not a.startswith("-")):
        argv = ["tony_tpu"] + argv
    sys.exit(main(argv))

"""GPipe vs 1F1B: wall-clock + compiled-FLOP comparison on the virtual
8-CPU mesh (relative numbers; the schedules' compute graphs are identical
on TPU, only the per-tick costs scale).

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      PYTHONPATH=/root/repo python scripts/pp_bench.py

A vocab-sized head (32k) on a small trunk makes schedule waste visible:
a schedule that runs the lm head on every stage every tick pays P x
(M+2P-2)/M times the useful head FLOPs.
"""

import json
import time

import jax
import jax.numpy as jnp

from tony_tpu.models.llama import LlamaConfig
from tony_tpu.parallel.mesh import MeshShape, build_mesh
from tony_tpu.parallel.sharding import DEFAULT_RULES
from tony_tpu.train.trainer import (
    default_optimizer, make_train_state, make_train_step, pp_rules,
)

PP, M = 4, 8
B, S = 16, 128


def run(schedule: str) -> dict:
    cfg = LlamaConfig(
        vocab_size=32000, dim=256, n_layers=8, n_heads=8, n_kv_heads=8,
        ffn_dim=688, max_seq_len=S, attention_impl="dot",
        dtype=jnp.float32,  # CPU bench; bf16 trips an XLA-CPU promotion bug
    )
    mesh = build_mesh(MeshShape(pp=PP, fsdp=2))
    opt = default_optimizer(warmup_steps=1, decay_steps=100)
    rules = pp_rules(dict(DEFAULT_RULES))
    state = make_train_state(jax.random.key(0), cfg, mesh, opt, rules)
    step = make_train_step(
        cfg, mesh, opt, rules, n_microbatches=M, pp_schedule=schedule
    )
    toks = jax.random.randint(jax.random.key(1), (B, S + 1), 0, cfg.vocab_size)
    inp, tgt = toks[:, :-1], toks[:, 1:]

    lowered = jax.jit(step).lower(state, inp, tgt)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # older jax wraps the dict in a list
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", -1)) if cost else -1.0

    state2, m = step(state, inp, tgt)  # compile+run once
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    n = 5
    for _ in range(n):
        state2, m = step(state2, inp, tgt)
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / n
    mem = compiled.memory_analysis()
    return {
        "schedule": schedule,
        "step_ms": round(dt * 1e3, 1),
        "compiled_gflops": round(flops / 1e9, 2),
        # the schedule's idle fraction: each stage sits out (P-1) of the
        # (M + P-1) ticks (GPipe and non-interleaved 1F1B share the flush
        # bubble; 1F1B's win is O(P) activation memory, visible in temp)
        "bubble_frac": round((PP - 1) / (M + PP - 1), 4),
        "pp": PP,
        "microbatches": M,
        "temp_mb": round(mem.temp_size_in_bytes / 2**20, 1),
        "loss": round(float(m["loss"]), 4),
    }


if __name__ == "__main__":
    for schedule in ("gpipe", "1f1b"):
        print(json.dumps(run(schedule)), flush=True)

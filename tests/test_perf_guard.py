"""Tier-1 perf guards: the step loop must stay stall-free and the loss head
must stay fused.

Overlap guard: a data-layer or loop change that re-serializes host input
work against the device step (dropping the prefetch wrap, adding a blocking
sync inside the loop, an accidentally-quadratic sampler) shows up here as
host-blocked wall time. The threshold is deliberately generous — the CPU CI
rig shares two cores between the "device" step and the producer thread —
but a fully re-serialized loop (host_blocked_frac ~= host work / step time)
clears it by an order of magnitude on the failure side.

Loss-head memory guard: a head change that re-materialises [B, S, V] logits
(or lets autodiff build a full dlogits) shows up in the compiled step's
temp-buffer assignment, measured without running anything.
"""

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models.llama import LlamaConfig
from tony_tpu.parallel.mesh import MeshShape, build_mesh
from tony_tpu.train import DataConfig, FitConfig, fit
from tony_tpu.train import trainer

# generous: tolerate CI noise and GIL contention; a reserialized input
# path on this config measures well above it (see docs/PERF.md "Overlap")
MAX_HOST_BLOCKED_FRAC = 0.30


def test_steady_state_loop_is_not_host_blocked():
    final = fit(FitConfig(
        model=LlamaConfig.tiny(),
        data=DataConfig(global_batch=4, seq_len=32, vocab_size=256),  # prefetch=2 default
        mesh_shape=MeshShape(fsdp=2),
        steps=25,
        log_every=25,
        lr=5e-3,
        warmup_steps=2,
    ))
    assert np.isfinite(final["final_loss"])
    # the stall metric must exist (bench.py and the BENCH trajectory key on
    # it) and stay under the overlap budget
    assert "host_blocked_ms_per_step" in final
    assert "host_blocked_frac" in final
    assert final["host_blocked_frac"] < MAX_HOST_BLOCKED_FRAC, (
        f"step loop is {final['host_blocked_frac']:.0%} host-blocked "
        f"(host {final['host_blocked_ms_per_step']}ms/step) — input work is "
        "no longer overlapped with the device step"
    )
    # startup phases are reported (compile-ahead instrumentation)
    assert "compile_s" in final.get("startup", {})
    assert "first_batch_s" in final.get("startup", {})


def test_loss_head_stays_fused_in_memory():
    """Lower + compile the tiny-model train step (vocab scaled up so the
    loss head dominates) and assert the compiled temp footprint stays below
    the full-logits bound — one [B, S, V] fp32 tensor. The dense head
    measures ~3.7x that bound on this config (logits + dlogits + fusion
    slack), the fused head ~0.9x, so a head regression that re-materialises
    logits fails with a wide margin while leaving headroom for benign
    scheduling noise in the rest of the step."""
    B, S = 8, 128
    cfg = dataclasses.replace(
        LlamaConfig.tiny(), vocab_size=8192, max_seq_len=S, ce_vocab_chunk=512
    )
    mesh = build_mesh(MeshShape(dp=1))
    opt = trainer.default_optimizer(warmup_steps=1, decay_steps=10)
    state = trainer.make_train_state(jax.random.key(0), cfg, mesh, opt)
    toks = jax.ShapeDtypeStruct((B, S), jnp.int32)

    def temp_bytes(c):
        step = trainer.make_train_step(c, mesh, opt)
        compiled = step.lower(state, toks, toks).compile()
        return compiled.memory_analysis().temp_size_in_bytes

    full_logits = B * S * cfg.vocab_size * 4  # one fp32 [B, S, V]
    fused = temp_bytes(cfg)  # ce_impl='scan' is the default train path
    assert fused < full_logits, (
        f"fused train step temp {fused / 2**20:.1f}MiB >= full-logits bound "
        f"{full_logits / 2**20:.1f}MiB — the loss head is materialising "
        "vocab-sized tensors again"
    )
    # and the guard itself is meaningful: the dense head blows the bound
    dense = temp_bytes(dataclasses.replace(cfg, ce_impl="dense"))
    assert dense > 2 * fused, (fused, dense)


def test_grouped_moe_dispatch_stays_below_einsum_tensors():
    """Lower + compile a grad of the MoE block at a shape where the one-hot
    dispatch/combine tensors dominate, and assert the grouped (dropless)
    path's compiled temp footprint stays below what the einsum dispatch
    materialises for routing alone — two [T, E, C] fp32 tensors. A grouped-
    path regression that re-materialises capacity-slot tensors (or lets the
    sort blow up into per-expert one-hots) fails this without running a
    step; the einsum path itself exceeds the bound, proving it's tight."""
    import jax.numpy as jnp

    from tony_tpu.parallel.moe import MoEConfig, init_moe_params, moe_block

    T, D = 4096, 128
    base = MoEConfig(dim=D, ffn_dim=2 * D, n_experts=8, top_k=2)
    params = init_moe_params(jax.random.key(0), base, dtype=jnp.float32)
    x = jax.ShapeDtypeStruct((1, T, D), jnp.float32)

    def temp_bytes(cfg):
        def loss(p, xx):
            y, aux = moe_block(p, xx, cfg)
            return jnp.sum(y * y) + aux

        compiled = jax.jit(jax.value_and_grad(loss)).lower(params, x).compile()
        return compiled.memory_analysis().temp_size_in_bytes

    dispatch_tensors = 2 * T * base.n_experts * base.capacity(T) * 4
    grouped = temp_bytes(dataclasses.replace(base, dispatch="grouped"))
    assert grouped < dispatch_tensors, (
        f"grouped MoE temp {grouped / 2**20:.1f}MiB >= einsum dispatch-tensor "
        f"bound {dispatch_tensors / 2**20:.1f}MiB — the dropless path is "
        "materialising capacity-sized routing tensors again"
    )
    einsum = temp_bytes(dataclasses.replace(base, dispatch="einsum"))
    assert einsum > dispatch_tensors, (grouped, einsum, dispatch_tensors)


def test_decode_step_reads_kv_proportional_to_active_blocks():
    """Compile the serve engine's decode step over block caches of growing
    capacity and assert (via XLA cost analysis, nothing executed) that its
    bytes accessed scale with the ACTIVE block count, not max_len: a
    regression that re-points decode attention at a max_len-sized buffer
    (the old generate.py ring cache) blows the small-capacity bound by the
    full KV footprint. At these shapes the full-capacity step accesses
    ~5x the one-block step; the guard asserts 2.5x headroom on both
    sides."""
    from tony_tpu.models.llama import LlamaConfig, init_params
    from tony_tpu.serve import Engine, ServeConfig
    from tony_tpu.serve.cache import create_cache

    slots, block, max_len = 4, 16, 512
    cfg = dataclasses.replace(LlamaConfig.tiny(), max_seq_len=max_len)
    params = init_params(jax.random.key(0), cfg)
    eng = Engine(params, cfg, ServeConfig(
        slots=slots, max_len=max_len, kv_block=block,
    ))

    def bytes_at(n_blocks):
        # paged form: a pool of slots * n_blocks physical blocks (plus
        # scratch) attended through an n_blocks-wide table — the active
        # footprint a trace with n_blocks-long rows actually holds
        cache = create_cache(cfg, slots, 1 + slots * n_blocks, block)
        table = jnp.zeros((slots, n_blocks), jnp.int32)
        compiled = jax.jit(eng._decode_impl).lower(
            params, cache, table, eng.state
        ).compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        return float(ca["bytes accessed"])

    small = bytes_at(1)
    full = bytes_at(max_len // block)
    # one full-length read of k+v (the cache the old decode walked per step)
    kv_full = (
        2 * cfg.n_layers * slots * cfg.n_kv_heads * max_len * cfg.head_dim * 4
    )
    assert small < full / 2.5, (
        f"decode step over a 1-block cache accesses {small / 2**20:.1f}MiB "
        f"vs {full / 2**20:.1f}MiB at full capacity — decode traffic no "
        "longer scales with the active prefix"
    )
    # and the full-capacity cost is dominated by the KV buffers (the guard
    # is measuring the cache, not fixed per-step overhead)
    assert full - small > kv_full, (small, full, kv_full)


def test_disarmed_trace_span_is_within_noise_of_noop():
    """The trace spine's no-op contract: a span call on a DISARMED tracer
    is one global load + None compare returning a shared no-op object —
    cheap enough to compile into the train/serve hot paths. Guarded two
    ways: absolute per-call cost (generous for CI noise; an accidentally
    armed tracer pays dict/deque/time work well above it) and zero
    recording side effects."""
    import time

    from tony_tpu.obs import trace

    assert trace.active_tracer() is None  # the default state
    N = 50_000
    # warm up, then measure the full with-statement round trip; best of 5
    # so a CI scheduler hiccup in one repeat cannot fail the guard
    for _ in range(1000):
        with trace.span("x"):
            pass
    per_call = math.inf
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(N):
            with trace.span("x"):
                pass
        per_call = min(per_call, (time.perf_counter() - t0) / N)
    assert per_call < 5e-6, (
        f"disarmed trace.span costs {per_call * 1e9:.0f}ns/call — the no-op "
        "path regressed (is something arming a tracer or allocating?)"
    )
    # and it really is the shared no-op: nothing recorded anywhere
    assert trace.span("x") is trace.NOOP_SPAN
    trace.instant("x")  # no-op, no error


def test_fit_loop_stays_unblocked_with_tracing_armed(tmp_path):
    """The armed contract: with the trace spine recording at the default
    sampling stride AND the HBM observatory AND the numerics sentinel
    AND the live-series recorder sampling at their default strides
    (in-graph value monitors fused into the step, rule engine and series
    writer evaluating async), the tiny-model fit loop must still clear
    the host-blocked overlap budget — all four hooks are always-on in
    jobs, so their cost rides inside the same tier-1 guard as the data
    path."""
    from tony_tpu.obs import hbm, health, series, trace

    tracer = trace.install(trace.Tracer(
        str(tmp_path / "trace" / "guard.jsonl"), "guard", "guardtrace",
        sample_steps=16,  # the trace.sample_steps default
    ))
    # a stats fake so the CPU rig exercises the full armed path (real
    # reading + gauge + counter-track emission) at the default stride
    hbm.install(hbm.HbmWatch(
        stats_fn=lambda: [("dev0", {
            "bytes_in_use": 1 << 30, "peak_bytes_in_use": 2 << 30,
        })],
        sample_every=16,  # the obs.hbm.sample_steps default
    ))
    health.install(health.HealthSentinel(
        sample_every=16,  # the obs.health.sample_steps default
    ))
    series.uninstall()
    series.install(series.SeriesRecorder(
        str(tmp_path / "series" / "guard.jsonl"), "guard",
        sample_every=16,  # the obs.series.sample_steps default
    ))
    try:
        final = fit(FitConfig(
            model=LlamaConfig.tiny(),
            data=DataConfig(global_batch=4, seq_len=32, vocab_size=256),
            mesh_shape=MeshShape(fsdp=2),
            steps=25,
            log_every=25,
            lr=5e-3,
            warmup_steps=2,
        ))
        series.active_recorder().drain()
    finally:
        trace.uninstall()
        hbm.uninstall()
        health.uninstall()
        series.uninstall()
    assert np.isfinite(final["final_loss"])
    assert final["host_blocked_frac"] < MAX_HOST_BLOCKED_FRAC, (
        f"step loop is {final['host_blocked_frac']:.0%} host-blocked with "
        "tracing + memory + health + series sampling armed — a spine is "
        "stalling the loop"
    )
    # the sentinel evaluated real samples and found a clean run
    assert final["health_verdict"] == "healthy"
    # the series recorder scraped fit's source into its journal: step
    # progress plus the built-in HBM reading from the armed (fake) watch
    from tony_tpu.obs.series import read_series

    points = read_series(str(tmp_path / "series"))["guard"]
    assert points, "the fit loop never scraped the series"
    assert points[-1]["step"] == 25          # the shutdown force_sample
    assert points[-1]["hbm_live_bytes"] == 1 << 30
    assert any("goodput_frac" in p for p in points)
    # the spine actually recorded: fit root + sampled step spans, and the
    # step-time distribution made it into the final report
    import json

    recs = [json.loads(l) for l in open(tmp_path / "trace" / "guard.jsonl")
            if l.strip()]
    names = {r.get("name") for r in recs if r.get("ph") == "X"}
    assert "train.fit" in names and "train.step" in names
    steps = [r for r in recs if r.get("name") == "train.step"]
    assert all(r["args"]["every"] == 16 for r in steps)
    assert final["step_time_p99_s"] >= final["step_time_p50_s"] > 0
    # the memory observatory recorded too: per-device counter-track rows
    # in the same journal (the `tony trace` memory timeline)
    counters = [r for r in recs if r.get("ph") == "C"]
    assert counters and counters[0]["name"] == "hbm.dev0"
    assert counters[0]["args"]["live_gb"] == 1.0


def test_disarmed_hbm_sample_is_within_noise_of_noop():
    """The HBM observatory's no-op contract (the trace-span twin): a
    sample() call with no watch armed is one global load + None compare —
    cheap enough to sit in the train/serve step loops unconditionally.
    graft-lint GL005 holds the call-site side of the same contract."""
    import time

    from tony_tpu.obs import hbm

    hbm.uninstall()  # other tests/fit runs may have armed the process
    N = 50_000
    for _ in range(1000):
        hbm.sample()
    per_call = math.inf
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(N):
            hbm.sample()
        per_call = min(per_call, (time.perf_counter() - t0) / N)
    assert per_call < 5e-6, (
        f"disarmed hbm.sample costs {per_call * 1e9:.0f}ns/call — the "
        "no-op path regressed (is something arming a watch or allocating?)"
    )
    # and the armed-but-off-stride path is one counter bump, no reading
    calls = []
    watch = hbm.install(hbm.HbmWatch(
        stats_fn=lambda: calls.append(1) or [], sample_every=1000,
    ))
    try:
        for _ in range(999):
            hbm.sample()
        assert calls == []  # stats never read off-stride
        hbm.sample()
        assert len(calls) == 1
        assert watch is hbm.active_watch()
    finally:
        hbm.uninstall()


def test_disarmed_health_sample_is_within_noise_of_noop():
    """The numerics sentinel's no-op contract (the trace-span/hbm-sample
    twin): a sample() call with no sentinel armed is one global load +
    None compare — cheap enough to sit in the train/serve step loops
    unconditionally. graft-lint GL005 holds the call-site side of the
    same contract (tests/test_lint.py has the health fixtures)."""
    import time

    from tony_tpu.obs import health

    health.uninstall()  # other tests/fit runs may have armed the process
    N = 50_000
    for _ in range(1000):
        health.sample()
    per_call = math.inf
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(N):
            health.sample()
        per_call = min(per_call, (time.perf_counter() - t0) / N)
    assert per_call < 5e-6, (
        f"disarmed health.sample costs {per_call * 1e9:.0f}ns/call — the "
        "no-op path regressed (is something arming a sentinel or allocating?)"
    )
    # armed-but-off-stride: one counter bump, nothing enqueued
    sentinel = health.install(health.HealthSentinel(sample_every=1000))
    try:
        for _ in range(999):
            health.sample(metrics={})
        assert sentinel._pending == 0 and sentinel._q.empty()
        health.sample(metrics={})
        assert sentinel.drain(timeout_s=5.0)
        assert sentinel is health.active_sentinel()
    finally:
        health.uninstall()


def test_disarmed_profile_capture_is_within_noise_of_noop(tmp_path):
    """The coordinated profiler's no-op contract (the fifth twin): a
    maybe_capture() call with no controller armed is one global load +
    None compare — cheap enough to sit in the train/serve step loops
    unconditionally. graft-lint GL005 holds the call-site side of the
    same contract (tests/test_lint.py has the profile fixtures)."""
    import time

    from tony_tpu.obs import profile

    profile.uninstall()  # other tests may have armed the process
    N = 50_000
    for _ in range(1000):
        profile.maybe_capture()
    per_call = math.inf
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(N):
            profile.maybe_capture()
        per_call = min(per_call, (time.perf_counter() - t0) / N)
    assert per_call < 5e-6, (
        f"disarmed profile.maybe_capture costs {per_call * 1e9:.0f}ns/call — "
        "the no-op path regressed (is something arming a controller or "
        "allocating?)"
    )
    # armed-but-idle (no broadcast window): two attribute compares, no
    # window ever opens, nothing lands on disk
    ctl = profile.install(profile.ProfileController(
        str(tmp_path / "profile"), "guard", watch=False,
    ))
    try:
        per_call = math.inf
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(N):
                profile.maybe_capture()
            per_call = min(per_call, (time.perf_counter() - t0) / N)
        assert per_call < 5e-6, (
            f"armed-idle profile.maybe_capture costs {per_call * 1e9:.0f}"
            "ns/call — the off-window path regressed"
        )
        assert ctl._req is None and ctl._pending is None
        assert not (tmp_path / "profile" / "guard").exists()
        assert ctl is profile.active_controller()
    finally:
        profile.uninstall()


def test_disarmed_series_sample_is_within_noise_of_noop():
    """The live-series recorder's no-op contract (the fourth twin): a
    sample() call with no recorder armed is one global load + None
    compare — cheap enough to sit in the train/serve step loops
    unconditionally. graft-lint GL005 holds the call-site side of the
    same contract (tests/test_lint.py has the series fixtures)."""
    import time

    from tony_tpu.obs import series

    series.uninstall()  # other tests/fit runs may have armed the process
    N = 50_000
    for _ in range(1000):
        series.sample()
    per_call = math.inf
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(N):
            series.sample()
        per_call = min(per_call, (time.perf_counter() - t0) / N)
    assert per_call < 5e-6, (
        f"disarmed series.sample costs {per_call * 1e9:.0f}ns/call — the "
        "no-op path regressed (is something arming a recorder or "
        "allocating?)"
    )
    # armed-but-off-stride: one counter bump, no source is ever scraped
    calls = []
    rec = series.install(series.SeriesRecorder(
        None, "guard", sample_every=1000,
    ))
    rec.attach("probe", lambda: calls.append(1) or {"v": 1.0})
    try:
        for _ in range(999):
            series.sample()
        assert calls == []  # sources never scraped off-stride
        series.sample()
        assert len(calls) == 1
        assert rec is series.active_recorder()
    finally:
        series.uninstall()

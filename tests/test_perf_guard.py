"""Tier-1 overlap guard: the steady-state step loop must stay stall-free.

A data-layer or loop change that re-serializes host input work against the
device step (dropping the prefetch wrap, adding a blocking sync inside the
loop, an accidentally-quadratic sampler) shows up here as host-blocked
wall time. The threshold is deliberately generous — the CPU CI rig shares
two cores between the "device" step and the producer thread — but a fully
re-serialized loop (host_blocked_frac ~= host work / step time) clears it
by an order of magnitude on the failure side.
"""

import numpy as np

from tony_tpu.models.llama import LlamaConfig
from tony_tpu.parallel.mesh import MeshShape
from tony_tpu.train import DataConfig, FitConfig, fit

# generous: tolerate CI noise and GIL contention; a reserialized input
# path on this config measures well above it (see docs/PERF.md "Overlap")
MAX_HOST_BLOCKED_FRAC = 0.30


def test_steady_state_loop_is_not_host_blocked():
    final = fit(FitConfig(
        model=LlamaConfig.tiny(),
        data=DataConfig(global_batch=4, seq_len=32, vocab_size=256),  # prefetch=2 default
        mesh_shape=MeshShape(fsdp=2),
        steps=25,
        log_every=25,
        lr=5e-3,
        warmup_steps=2,
    ))
    assert np.isfinite(final["final_loss"])
    # the stall metric must exist (bench.py and the BENCH trajectory key on
    # it) and stay under the overlap budget
    assert "host_blocked_ms_per_step" in final
    assert "host_blocked_frac" in final
    assert final["host_blocked_frac"] < MAX_HOST_BLOCKED_FRAC, (
        f"step loop is {final['host_blocked_frac']:.0%} host-blocked "
        f"(host {final['host_blocked_ms_per_step']}ms/step) — input work is "
        "no longer overlapped with the device step"
    )
    # startup phases are reported (compile-ahead instrumentation)
    assert "compile_s" in final.get("startup", {})
    assert "first_batch_s" in final.get("startup", {})

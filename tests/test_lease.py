"""Shared ResourceManager (LeaseStore): cross-job arbitration semantics.

The reference's L0 is YARN's RM — one authority for every job's containers
(SURVEY.md section 1 L0, section 3.1). These tests pin the rebuilt
equivalent: gang-atomic FIFO grants over a file-locked store, queue-then-run
and clean-rejection behavior, crash reaping, and the backend integration
that makes two concurrent submissions against one inventory impossible to
double-book.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from tony_tpu.cluster.backend import InsufficientResources, Resource
from tony_tpu.cluster.lease import GangAsk, LeaseStore


def res(chips=0, mem=64, cpus=1):
    return Resource(memory_mb=mem, cpus=cpus, tpu_chips=chips)


def store(tmp_path, **kw):
    return LeaseStore(str(tmp_path / "rm"), **kw)


def test_gang_atomic_grant_and_packing(tmp_path):
    s = store(tmp_path)
    s.register_hosts({"h1": res(4, 256, 8), "h2": res(4, 256, 8)})
    packing = s.reserve_gang(
        "app1", [GangAsk(res(4)), GangAsk(res(4))], timeout_s=0
    )
    assert [h for _, h in packing] == ["h1", "h2"]
    avail = s.available()
    assert avail["h1"].tpu_chips == 0 and avail["h2"].tpu_chips == 0


def test_second_job_rejected_with_holder_names(tmp_path):
    s = store(tmp_path)
    s.register_hosts({"h1": res(4, 256, 8)})
    s.reserve_gang("job-a", [GangAsk(res(4))], timeout_s=0)
    with pytest.raises(InsufficientResources, match="job-a holds 1 leases"):
        s.reserve_gang("job-b", [GangAsk(res(4))], timeout_s=0)


def test_second_job_queues_then_runs_fifo(tmp_path):
    """The headline semantics: job B queues behind job A and is granted the
    moment A releases — no double-booking in between."""
    s = store(tmp_path)
    s.register_hosts({"h1": res(4, 256, 8)})
    s.reserve_gang("job-a", [GangAsk(res(4))], timeout_s=0)
    granted_at = {}

    def job_b():
        s2 = store(tmp_path)  # separate handle, same store
        s2.reserve_gang("job-b", [GangAsk(res(4))], timeout_s=30)
        granted_at["b"] = time.monotonic()

    t = threading.Thread(target=job_b)
    t.start()
    time.sleep(0.5)
    assert "b" not in granted_at, "job B was granted while A held the chips"
    release_at = time.monotonic()
    s.release_app("job-a")
    t.join(10)
    assert granted_at["b"] >= release_at


def test_fifo_order_between_waiters(tmp_path):
    """Two queued jobs are granted in enqueue order, not wakeup luck."""
    s = store(tmp_path)
    s.register_hosts({"h1": res(4, 256, 8)})
    s.reserve_gang("job-a", [GangAsk(res(4))], timeout_s=0)
    order = []
    enqueued_b = threading.Event()

    def waiter(app_id, wait_first=None):
        s2 = store(tmp_path)
        if wait_first is not None:
            assert wait_first.wait(10)
            time.sleep(0.3)  # ensure b's ticket is truly in the store first
        s2.reserve_gang(app_id, [GangAsk(res(2))], timeout_s=30)
        order.append(app_id)
        enqueued_b.set() if app_id == "job-b" else None

    tb = threading.Thread(target=waiter, args=("job-b",))
    tb.start()
    time.sleep(0.3)
    tc = threading.Thread(target=waiter, args=("job-c",))
    tc.start()
    time.sleep(0.5)
    s.release_app("job-a")  # 4 chips free: both b and c now fit
    tb.join(10)
    tc.join(10)
    assert order[0] == "job-b"


def test_gang_asks_never_interleave_into_deadlock(tmp_path):
    """Each job reserves its WHOLE gang atomically, so two 2-chip jobs on a
    3-chip host serialize instead of each grabbing one chip and hanging."""
    s = store(tmp_path)
    s.register_hosts({"h1": res(3, 256, 8)})
    done = []

    def job(app_id):
        s2 = store(tmp_path)
        s2.reserve_gang(
            app_id, [GangAsk(res(1)), GangAsk(res(1))], timeout_s=20
        )
        time.sleep(0.2)
        s2.release_app(app_id)
        done.append(app_id)

    ts = [threading.Thread(target=job, args=(f"job-{i}",)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert sorted(done) == ["job-0", "job-1", "job-2"]


def test_idempotent_reentry_and_gang_id_separation(tmp_path):
    s = store(tmp_path)
    s.register_hosts({"h1": res(8, 256, 8)})
    ask = [GangAsk(res(2))]
    p1 = s.reserve_gang("app", ask, gang_id="containers", timeout_s=0)
    p2 = s.reserve_gang("app", ask, gang_id="containers", timeout_s=0)
    assert p1 == p2
    # same shape under a different gang_id is a SECOND reservation
    s.reserve_gang("app", ask, gang_id="am", timeout_s=0)
    assert s.available()["h1"].tpu_chips == 4


def test_infeasible_gang_fails_fast_not_at_timeout(tmp_path):
    s = store(tmp_path)
    s.register_hosts({"h1": res(4, 256, 8)})
    t0 = time.monotonic()
    with pytest.raises(InsufficientResources, match="never be placed"):
        s.reserve_gang("app", [GangAsk(res(8))], timeout_s=60)
    assert time.monotonic() - t0 < 5


def test_label_and_pin_and_candidates(tmp_path):
    s = store(tmp_path)
    s.register_hosts(
        {"h1": res(4, 256, 8), "h2": res(4, 256, 8), "h3": res(4, 256, 8)},
        {"h2": "big"},
    )
    (_, h) = s.reserve_gang(
        "a", [GangAsk(res(1), node_label="big")], timeout_s=0
    )[0]
    assert h == "h2"
    (_, h) = s.reserve_gang("b", [GangAsk(res(1), host="h3")], timeout_s=0)[0]
    assert h == "h3"
    # candidates restrict packing to the asking job's own inventory
    (_, h) = s.reserve_gang(
        "c", [GangAsk(res(1), candidates=("h3",))], timeout_s=0
    )[0]
    assert h == "h3"


def test_dead_owner_reaped_lease_and_ticket(tmp_path):
    """A job whose process dies is reaped by the next locked operation:
    both its granted leases and its queued ticket (a dead ticket at the
    FIFO head would otherwise block everyone forever)."""
    root = str(tmp_path / "rm")
    code = f"""
import sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from tony_tpu.cluster.lease import GangAsk, LeaseStore
from tony_tpu.cluster.backend import Resource
s = LeaseStore({root!r})
s.register_hosts({{"h1": Resource(256, 4, 8)}})
s.reserve_gang("dead-holder", [GangAsk(Resource(64, 1, 8))], timeout_s=0)
try:
    # queues behind itself-on-h1: enqueue then time out, leaving... no —
    # die abruptly WHILE queued, before any dequeue cleanup can run
    s.reserve_gang("dead-waiter", [GangAsk(Resource(64, 1, 4))], timeout_s=60)
except BaseException:
    pass
"""
    # run holder+waiter in a child, SIGKILL it mid-queue
    proc = subprocess.Popen([sys.executable, "-c", code])
    deadline = time.time() + 20
    s = store(tmp_path)
    # wait until the child has its lease AND its queued ticket in the store
    while time.time() < deadline:
        try:
            summary = LeaseStore(root).summary()
        except Exception:
            summary = {"apps": {}, "queue": []}
        if "dead-holder" in summary["apps"] and summary["queue"]:
            break
        time.sleep(0.1)
    else:
        proc.kill()
        pytest.fail("child never reached queued state")
    proc.kill()
    proc.wait()
    # the next locked op by a survivor reaps both the lease and the ticket
    deadline = time.time() + 10
    while time.time() < deadline:
        summary = LeaseStore(root).summary()
        if not summary["apps"] and not summary["queue"]:
            break
        time.sleep(0.2)
    assert not summary["apps"] and not summary["queue"]
    # and the capacity is actually reusable
    LeaseStore(root).reserve_gang("next", [GangAsk(res(8))], timeout_s=0)


def test_capacity_conflict_keeps_first_registration(tmp_path):
    s = store(tmp_path)
    s.register_hosts({"h1": res(4, 256, 8)})
    s2 = store(tmp_path)
    s2.register_hosts({"h1": res(8, 256, 8)})  # wider claim ignored
    s2.reserve_gang("app", [GangAsk(res(4))], timeout_s=0)
    with pytest.raises(InsufficientResources):
        s2.reserve_gang("app2", [GangAsk(res(1))], timeout_s=0)


# --- backend integration ----------------------------------------------------


def test_local_backends_cannot_double_book(tmp_path):
    """Two LocalProcessBackends (two jobs, same machine, same store): the
    second job's gang queues; without the store both would have believed
    they owned the full chip inventory."""
    from tony_tpu.cluster.local import LocalProcessBackend

    cap = res(4, 4096, 16)
    b1 = LocalProcessBackend(
        cap, lease_store=store(tmp_path), app_id="job-1"
    )
    b2 = LocalProcessBackend(
        cap, lease_store=store(tmp_path), app_id="job-2",
        rm_queue_timeout_s=30,
    )
    b1.start()
    b2.start()
    asks = [(res(4), "")]
    b1.reserve_job(asks, timeout_s=5)
    granted = threading.Event()

    def second():
        b2.reserve_job(asks)  # uses rm_queue_timeout_s
        granted.set()

    t = threading.Thread(target=second)
    t.start()
    time.sleep(0.5)
    assert not granted.is_set(), "second job granted while first held chips"
    b1.stop()  # releases job-1's leases
    t.join(15)
    assert granted.is_set()
    b2.stop()


def test_remote_backends_cannot_double_book(tmp_path):
    """Two RemoteBackends over the same single-slot host set: the second
    allocate()s only after the first job's leases are released, and every
    container launch stays within store-leased budget."""
    from tony_tpu.cluster.remote import LocalTransport, RemoteBackend

    def backend(app_id, timeout):
        b = RemoteBackend(
            ["127.0.0.1"],
            transport=LocalTransport(),
            host_capacity=res(4, 4096, 16),
            lease_store=store(tmp_path),
            app_id=app_id,
            rm_queue_timeout_s=timeout,
        )
        b.start()
        return b

    from tony_tpu.cluster.backend import ContainerRequest

    def creq(i):
        return ContainerRequest(
            task_type="w",
            task_index=i,
            resource=res(4),
            argv=[sys.executable, "-c", "import time; time.sleep(30)"],
            env={},
            log_path=str(tmp_path / f"c{i}.log"),
        )

    b1 = backend("job-1", 5)
    b2 = backend("job-2", 20)
    b1.reserve_job([(res(4), "")], timeout_s=5)
    c1 = b1.allocate(creq(0))
    assert c1.state.name == "RUNNING"
    # job-2: chips are leased to job-1 -> gang queues; with timeout 0 the
    # on-demand path in allocate() rejects cleanly instead of double-booking
    with pytest.raises(InsufficientResources, match="job-1 holds"):
        b2.allocate(creq(1))
    granted = threading.Event()

    def second():
        b2.reserve_job([(res(4), "")])
        granted.set()

    t = threading.Thread(target=second)
    t.start()
    time.sleep(0.5)
    assert not granted.is_set()
    b1.stop()
    t.join(25)
    assert granted.is_set()
    c2 = b2.allocate(creq(1))
    assert c2.state.name == "RUNNING"
    b2.stop()


def test_backend_without_store_unchanged(tmp_path):
    """No cluster.rm_root -> exactly the old per-job inventory behavior."""
    from tony_tpu.cluster.local import LocalProcessBackend

    b = LocalProcessBackend(res(4, 4096, 16))
    b.start()
    b.reserve_job([(res(4), "")], timeout_s=5)  # no-op without a store
    b.reserve(res(0, 64, 1))
    assert b.available().tpu_chips == 4
    b.stop()


def test_external_release_while_queued_rejects_cleanly(tmp_path):
    """`tony rm-status --release` on a QUEUED app must surface as a clean
    InsufficientResources in the waiting reserve_gang, not a crash."""
    s = store(tmp_path)
    s.register_hosts({"h1": res(4, 256, 8)})
    s.reserve_gang("holder", [GangAsk(res(4))], timeout_s=0)
    err = {}

    def waiter():
        s2 = store(tmp_path)
        try:
            s2.reserve_gang("victim", [GangAsk(res(4))], timeout_s=30)
        except InsufficientResources as e:
            err["e"] = str(e)

    t = threading.Thread(target=waiter)
    t.start()
    deadline = time.time() + 10
    while time.time() < deadline and not store(tmp_path).summary()["queue"]:
        time.sleep(0.05)
    store(tmp_path).force_release_app("victim")
    t.join(10)
    assert "released externally" in err["e"]


def test_summary_reports_granted_host(tmp_path):
    """Leases in the rm-status view must carry the host they were PACKED
    onto, not the ask's (usually empty) pin field."""
    s = store(tmp_path)
    s.register_hosts({"h1": res(4, 256, 8)})
    s.reserve_gang("app", [GangAsk(res(2))], timeout_s=0)
    leases = s.summary()["apps"]["app"]["leases"]
    assert leases[0]["host"] == "h1"


def test_remote_placement_honors_store_packing(tmp_path):
    """A job whose cluster.hosts order differs from the store's
    registration order must place each container on the host the store
    PACKED its ask onto — greedy re-packing over budgets would strand the
    big ask (2-chip ask stealing the 4-chip ask's host)."""
    from tony_tpu.cluster.backend import ContainerRequest
    from tony_tpu.cluster.remote import LocalTransport, RemoteBackend

    # job A fixes the store's registration order: h1 then h2
    store(tmp_path).register_hosts(
        {"h1": res(4, 4096, 16), "h2": res(4, 4096, 16)}
    )
    b = RemoteBackend(
        ["h2", "h1"],  # opposite order to the store
        transport=LocalTransport(),
        host_capacity=res(4, 4096, 16),
        lease_store=store(tmp_path),
        app_id="job-b",
    )
    b.start()
    b.reserve_job([(res(2), ""), (res(4), "")], timeout_s=5)

    def creq(i, chips):
        return ContainerRequest(
            task_type="w", task_index=i, resource=res(chips),
            argv=[sys.executable, "-c", "import time; time.sleep(20)"],
            env={}, log_path=str(tmp_path / f"c{i}.log"),
        )

    c_small = b.allocate(creq(0, 2))
    c_big = b.allocate(creq(1, 4))  # must not be stranded
    # store packs first-fit in ITS order: 2-chip -> h1, 4-chip -> h2
    assert c_small.host == "h1"
    assert c_big.host == "h2"
    b.stop()


# --- lease TTL / cross-host liveness -----------------------------------------


def test_cross_host_ttl_expiry_frees_chips(tmp_path):
    """A 'remote' owner (faked hostname) that stops renewing is reaped by
    TTL expiry: a second job acquires after the TTL with NO operator
    action — the cross-host crash case pid checks cannot cover."""
    root = str(tmp_path / "rm")
    remote = LeaseStore(root, owner_host="far-away-host", lease_ttl_s=0.5)
    remote.register_hosts({"h1": res(4, 256, 8)})
    remote.reserve_gang("remote-job", [GangAsk(res(4))], timeout_s=0)
    s = LeaseStore(root)
    with pytest.raises(InsufficientResources):  # not yet expired
        s.reserve_gang("job-b", [GangAsk(res(4))], timeout_s=0)
    # ...but once the TTL lapses, the waiter is granted automatically
    s.reserve_gang("job-b", [GangAsk(res(4))], timeout_s=10)
    assert "remote-job" not in s.summary()["apps"]


def test_cross_host_renewal_keeps_lease_alive(tmp_path):
    """An owner that RENEWS on schedule is never TTL-reaped, even from a
    host where its pid cannot be checked."""
    root = str(tmp_path / "rm")
    remote = LeaseStore(root, owner_host="far-away-host", lease_ttl_s=0.6)
    remote.register_hosts({"h1": res(4, 256, 8)})
    remote.reserve_gang("remote-job", [GangAsk(res(4))], timeout_s=0)
    s = LeaseStore(root)
    deadline = time.time() + 1.8  # three TTLs
    while time.time() < deadline:
        remote.renew_app("remote-job")
        time.sleep(0.05)
    with pytest.raises(InsufficientResources, match="remote-job holds"):
        s.reserve_gang("job-b", [GangAsk(res(4))], timeout_s=0)


def test_local_liveness_beats_ttl(tmp_path):
    """A same-host owner whose process is verifiably ALIVE keeps its leases
    past the TTL without renewing — the pid check is authoritative, the
    timer only covers owners it cannot see."""
    s = store(tmp_path, lease_ttl_s=0.3)
    s.register_hosts({"h1": res(4, 256, 8)})
    s.reserve_gang("wedged-but-alive", [GangAsk(res(4))], timeout_s=0)
    time.sleep(0.8)
    with pytest.raises(InsufficientResources, match="wedged-but-alive"):
        store(tmp_path).reserve_gang("job-b", [GangAsk(res(4))], timeout_s=0)


def test_release_refuses_live_foreign_owner_force_overrides(tmp_path):
    """release_app only drops entries the caller owns (or dead/expired
    ones); a live sibling's leases need force_release_app — one job's
    teardown can never yank another's chips."""
    root = str(tmp_path / "rm")
    remote = LeaseStore(root, owner_host="far-away-host")  # ttl 0: immortal
    remote.register_hosts({"h1": res(4, 256, 8)})
    remote.reserve_gang("their-job", [GangAsk(res(4))], timeout_s=0)
    s = LeaseStore(root)
    assert s.release_app("their-job") is False
    assert "their-job" in s.summary()["apps"]
    s.force_release_app("their-job")
    assert "their-job" not in s.summary()["apps"]


def test_reentry_after_dead_predecessor_takes_ownership(tmp_path):
    """An AM restart re-enters its reservation as a NEW process once the
    predecessor is provably gone (TTL lapsed here; pid-reaped when
    same-host): the entry is reaped and the successor's re-reservation
    lands on the same packing under its own ownership."""
    root = str(tmp_path / "rm")
    old = LeaseStore(root, owner_host="dead-am-host", lease_ttl_s=0.3)
    old.register_hosts({"h1": res(4, 256, 8)})
    p1 = old.reserve_gang("app", [GangAsk(res(4))], timeout_s=0)
    time.sleep(0.4)  # predecessor's TTL lapses without renewal
    new = LeaseStore(root, lease_ttl_s=0.5)
    p2 = new.reserve_gang("app", [GangAsk(res(4))], timeout_s=0)
    assert [h for _, h in p1] == [h for _, h in p2]
    owner = new.summary()["apps"]["app"]["owner"]
    assert owner.startswith(f"{os.uname().nodename}:")
    assert new.release_app("app") is True  # the successor owns it now


def test_reentry_refuses_takeover_from_live_incumbent(tmp_path):
    """ADVICE round 5: a duplicate submit with the same app_id/gang/asks
    must NOT steal a live incumbent's reservation — that double-books the
    chips until the incumbent's next renew fences it. The re-entry is
    refused with a pointer at force_release_app, and the incumbent keeps
    its leases."""
    from tony_tpu.cluster.lease import LeaseStoreError

    root = str(tmp_path / "rm")
    incumbent = LeaseStore(root, owner_host="other-submit-host", lease_ttl_s=600)
    incumbent.register_hosts({"h1": res(4, 256, 8)})
    incumbent.reserve_gang("app", [GangAsk(res(4))], timeout_s=0)
    dup = LeaseStore(root, lease_ttl_s=600)
    with pytest.raises(LeaseStoreError, match="refusing ownership takeover"):
        dup.reserve_gang("app", [GangAsk(res(4))], timeout_s=0)
    owner = LeaseStore(root).summary()["apps"]["app"]["owner"]
    assert owner.startswith("other-submit-host:")
    # the operator override still clears the way for a legitimate restart
    dup.force_release_app("app")
    dup.reserve_gang("app", [GangAsk(res(4))], timeout_s=0)
    assert dup.release_app("app") is True


def test_refused_takeover_dequeues_its_own_ticket(tmp_path):
    """A duplicate submit that QUEUED behind its incumbent must drop its
    ticket when the takeover is refused — like every other rejection
    path, or the dead ticket would block the FIFO head for everyone."""
    from tony_tpu.cluster.lease import LeaseStoreError

    root = str(tmp_path / "rm")
    blocker = LeaseStore(root)
    blocker.register_hosts({"h1": res(8, 256, 8)})
    blocker.reserve_gang("blocker", [GangAsk(res(8))], timeout_s=0)
    results = {}

    def run(name, host):
        s = LeaseStore(root, owner_host=host, poll_interval_s=0.05)
        try:
            s.reserve_gang("dup", [GangAsk(res(8))], timeout_s=30)
            results[name] = "granted"
        except LeaseStoreError:
            results[name] = "refused"

    t1 = threading.Thread(target=run, args=("incumbent", "host-b"))
    t1.start()
    # the incumbent's ticket must be queued before the duplicate enqueues
    deadline = time.time() + 10
    while time.time() < deadline and not LeaseStore(root).summary()["queue"]:
        time.sleep(0.05)
    t2 = threading.Thread(target=run, args=("duplicate", "host-c"))
    t2.start()
    time.sleep(0.3)  # both queued, FIFO order incumbent -> duplicate
    blocker.release_app("blocker")
    t1.join(15)
    t2.join(15)
    assert results == {"incumbent": "granted", "duplicate": "refused"}
    summary = LeaseStore(root).summary()
    assert summary["queue"] == []  # the refused duplicate left no ticket
    assert summary["apps"]["dup"]["owner"].startswith("host-b:")


def test_release_gang_returns_single_reservation(tmp_path):
    """release_gang hands back ONE gang (the losing-on-demand rollback
    path) while the app's other reservations stay live; releasing the
    last gang drops the app entry so ownership never outlives holdings."""
    s = store(tmp_path)
    s.register_hosts({"h1": res(8, 256, 8)})
    s.reserve_gang("app", [GangAsk(res(4))], gang_id="containers", timeout_s=0)
    s.reserve_gang("app", [GangAsk(res(2))], gang_id="ondemand:w:0", timeout_s=0)
    assert s.release_gang("app", "ondemand:w:0") is True
    leases = s.summary()["apps"]["app"]["leases"]
    assert len(leases) == 1 and leases[0]["tpu_chips"] == 4
    assert s.available()["h1"].tpu_chips == 4
    assert s.release_gang("app", "containers") is True
    assert "app" not in s.summary()["apps"]
    # a foreign live owner's gang is refused (same rule as release_app)
    far = LeaseStore(str(tmp_path / "rm"), owner_host="far-away")
    far.reserve_gang("theirs", [GangAsk(res(2))], timeout_s=0)
    assert s.release_gang("theirs", "containers") is False
    assert "theirs" in s.summary()["apps"]


def test_local_budget_check_and_claim_are_atomic(tmp_path):
    """Two concurrent allocate()s racing for the last budget slice: exactly
    ONE may claim it; the loser must go through the store (which is full)
    and reject — never consume private capacity past the leased budget."""
    import sys as _sys

    from tony_tpu.cluster.backend import ContainerRequest
    from tony_tpu.cluster.local import LocalProcessBackend
    from tony_tpu.utils.net import local_host

    root = tmp_path
    other = store(root)
    b = LocalProcessBackend(
        res(4, 4096, 16), lease_store=store(root), app_id="job-a"
    )
    b.start()  # registers this host: 4 chips
    # a sibling job holds 2 of the 4 chips
    other.reserve_gang(
        "sibling", [GangAsk(res(2), host=local_host())], timeout_s=0
    )
    b.reserve_job([(res(2), "")], timeout_s=5)  # our budget: the other 2

    def creq(i):
        return ContainerRequest(
            task_type="w", task_index=i, resource=res(2),
            argv=[_sys.executable, "-c", "import time; time.sleep(15)"],
            env={}, log_path=str(tmp_path / f"c{i}.log"),
        )

    results = [None, None]

    def run(i):
        try:
            results[i] = b.allocate(creq(i))
        except InsufficientResources as e:
            results[i] = e

    threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20)
    ok = [r for r in results if not isinstance(r, Exception)]
    rejected = [r for r in results if isinstance(r, InsufficientResources)]
    assert len(ok) == 1 and len(rejected) == 1, results
    b.stop()


def test_ondemand_lease_slice_is_used_not_stranded(tmp_path):
    """An on-demand lease's packing is recorded as a claimable slot: a
    later matching container lands on the STORE-PACKED host, instead of
    greedily re-packing onto leftover gang budget while the leased slice
    strands on the packed host for the rest of the job."""
    import sys as _sys

    from tony_tpu.cluster.backend import ContainerRequest
    from tony_tpu.cluster.remote import LocalTransport, RemoteBackend

    b = RemoteBackend(
        ["h1", "h2"],
        transport=LocalTransport(),
        host_capacity=res(4, 4096, 16),
        lease_store=store(tmp_path),
        app_id="job-a",
    )
    b.start()
    b.reserve_job([(res(4), "")], timeout_s=5)  # gang: 4 chips -> h1

    def creq(i, chips, cmd="pass"):
        return ContainerRequest(
            task_type="w", task_index=i, resource=res(chips),
            argv=[_sys.executable, "-c", cmd],
            env={}, log_path=str(tmp_path / f"c{i}.log"),
        )

    def wait_done(cid):
        deadline = time.time() + 15
        while time.time() < deadline:
            c = next(c for c in b.containers() if c.container_id == cid)
            if c.state.name in ("COMPLETED", "RELEASED"):
                return
            time.sleep(0.05)
        raise AssertionError(f"{cid} never finished")

    c0 = b.allocate(creq(0, 4, "import time; time.sleep(20)"))  # fills h1
    assert c0.host == "h1"
    c1 = b.allocate(creq(1, 2))  # no budget left -> on-demand, packed h2
    assert c1.host == "h2"
    wait_done(c1.container_id)
    b.release(c0.container_id)
    # the on-demand slice on h2 is still leased to this job; a matching
    # ask must reuse it rather than strand it
    c2 = b.allocate(creq(2, 2, "import time; time.sleep(5)"))
    assert c2.host == "h2"
    b.stop()


def test_owner_fences_when_leases_revoked(tmp_path):
    """The owner side of TTL safety: a job whose leases vanish from the
    store (operator release / TTL reaping) learns it on its next renewal
    and must fence — renew_leases() returns False for the AM to act on."""
    from tony_tpu.cluster.local import LocalProcessBackend

    b = LocalProcessBackend(
        res(4, 4096, 16),
        lease_store=store(tmp_path, lease_ttl_s=0.2),
        app_id="job-a",
    )
    b.start()
    b.reserve_job([(res(2), "")], timeout_s=5)
    assert b.renew_leases() is True
    store(tmp_path).force_release_app("job-a")
    time.sleep(0.06)  # past the ttl/4 renew throttle
    assert b.renew_leases() is False

"""HF checkpoint conversion: weights from transformers' LlamaForCausalLM
must produce matching logits through our forward (the migration lane for
existing torch checkpoints), and the mapping must round-trip."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from tony_tpu.models.convert import from_hf_state_dict, to_hf_state_dict
from tony_tpu.models.llama import LlamaConfig, forward


def _tiny_pair():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = LlamaConfig.tiny()
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.dim,
        intermediate_size=cfg.ffn_dim,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads,
        max_position_embeddings=cfg.max_seq_len,
        rms_norm_eps=cfg.norm_eps,
        rope_theta=cfg.rope_theta,
        attention_bias=False,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    return cfg, model


def test_logits_match_transformers():
    torch = pytest.importorskip("torch")
    cfg, model = _tiny_pair()
    params = from_hf_state_dict(model.state_dict(), cfg)
    tokens = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16))
    with torch.no_grad():
        want = model(torch.tensor(tokens)).logits.float().numpy()
    got = np.asarray(forward(params, jnp.asarray(tokens), cfg))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_state_dict_roundtrip():
    torch = pytest.importorskip("torch")
    cfg, model = _tiny_pair()
    params = from_hf_state_dict(model.state_dict(), cfg)
    back = to_hf_state_dict(params, cfg)
    sd = {k: v.detach().float().numpy() for k, v in model.state_dict().items()}
    assert set(back) == set(sd)
    for k in sd:
        np.testing.assert_allclose(back[k], sd[k], atol=1e-6, err_msg=k)


def test_strict_shape_and_key_errors():
    cfg, model = _tiny_pair()
    sd = dict(model.state_dict())
    bad = dict(sd)
    del bad["model.norm.weight"]
    with pytest.raises(KeyError, match="norm.weight"):
        from_hf_state_dict(bad, cfg)
    import torch

    bad = dict(sd)
    bad["model.embed_tokens.weight"] = torch.zeros(7, 7)
    with pytest.raises(ValueError, match="embed_tokens"):
        from_hf_state_dict(bad, cfg)


def test_tied_embeddings_fallback():
    """tie_word_embeddings checkpoints (Llama 3.2 1B/3B, TinyLlama) omit
    lm_head.weight; conversion must use embed_tokens as the head."""
    torch = pytest.importorskip("torch")
    cfg, model = _tiny_pair()
    sd = dict(model.state_dict())
    del sd["lm_head.weight"]
    params = from_hf_state_dict(sd, cfg)
    want = sd["model.embed_tokens.weight"].detach().float().numpy().T
    np.testing.assert_allclose(np.asarray(params["lm_head"]), want, atol=1e-6)

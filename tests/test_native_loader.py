"""Native (C++) token-loader tests: build with g++, validate vs numpy."""

import numpy as np
import pytest

from tony_tpu.train import native_loader

pytestmark = pytest.mark.skipif(
    not native_loader.available(), reason="g++/native build unavailable"
)


@pytest.fixture
def token_file(tmp_path):
    # 64 windows of (seq_len+1)=9 tokens, values = window index
    windows = np.repeat(np.arange(64, dtype=np.int32)[:, None], 9, axis=1)
    path = tmp_path / "tokens.bin"
    windows.ravel().tofile(path)
    return str(path)


def test_epoch_covers_every_window_once(token_file):
    with native_loader.NativeTokenLoader(token_file, seq_len=8, batch=4) as ldr:
        assert ldr.steps_per_epoch == 16
        seen = []
        for _ in range(ldr.steps_per_epoch):
            batch = ldr.next()
            assert batch.shape == (4, 9)
            # each row is a constant-valued window
            assert (batch == batch[:, :1]).all()
            seen.extend(batch[:, 0].tolist())
        assert sorted(seen) == list(range(64))  # exact cover, shuffled order
        assert seen != list(range(64))          # actually shuffled


def test_deterministic_given_seed(token_file):
    with native_loader.NativeTokenLoader(token_file, seq_len=8, batch=4, seed=7) as a:
        first = [a.next().copy() for _ in range(5)]
    with native_loader.NativeTokenLoader(token_file, seq_len=8, batch=4, seed=7) as b:
        second = [b.next().copy() for _ in range(5)]
    for x, y in zip(first, second):
        np.testing.assert_array_equal(x, y)


def test_seek_resumes_exactly(token_file):
    with native_loader.NativeTokenLoader(token_file, seq_len=8, batch=4, seed=1) as a:
        stream = [a.next().copy() for _ in range(8)]
    with native_loader.NativeTokenLoader(token_file, seq_len=8, batch=4, seed=1) as b:
        b.seek(5)
        resumed = [b.next().copy() for _ in range(3)]
    for x, y in zip(stream[5:], resumed):
        np.testing.assert_array_equal(x, y)


def test_sharding_partitions_windows(token_file):
    seen = []
    for shard in range(2):
        with native_loader.NativeTokenLoader(
            token_file, seq_len=8, batch=4, n_shards=2, shard_id=shard
        ) as ldr:
            assert ldr.steps_per_epoch == 8
            for _ in range(ldr.steps_per_epoch):
                seen.extend(ldr.next()[:, 0].tolist())
    assert sorted(seen) == list(range(64))  # shards are disjoint + complete


def test_open_rejects_too_small_file(tmp_path):
    path = tmp_path / "small.bin"
    np.arange(10, dtype=np.int32).tofile(path)
    with pytest.raises(ValueError):
        native_loader.NativeTokenLoader(str(path), seq_len=8, batch=4)

"""AM unit tests (no subprocesses): heartbeat accounting, spec-poll liveness.

Regression coverage for the round-1 advisor finding: executors only start
their heartbeat thread after registration, so a gang that is slow to fully
assemble must stay alive through GetClusterSpec polls alone.
"""

import time

import pytest

from tony_tpu.am.app_master import ApplicationMaster
from tony_tpu.am.session import TaskState
from tony_tpu.config.config import TonyConfig
from tony_tpu.rpc import pb


@pytest.fixture
def am(tmp_path):
    cfg = TonyConfig.load(
        overrides={
            "application.name": "t",
            "application.framework": "generic",
            "job.worker.instances": 2,
            "job.worker.command": "true",
            "task.heartbeat_interval_ms": 100,
            "task.max_missed_heartbeats": 5,
        }
    )
    a = ApplicationMaster(cfg, "app_test", str(tmp_path))
    yield a
    a.events.close()


def _age(am, job, idx, seconds):
    t = am.session.task(job, idx)
    t.last_heartbeat = time.monotonic() - seconds


def test_spec_poll_keeps_registered_task_alive(am):
    # worker:0 registered early; worker:1 is still PENDING (slow gang).
    am.session.register("worker", 0, "h", 1000, 0)
    _age(am, "worker", 0, 100.0)  # way past interval*max_missed = 0.5s
    # a spec poll arrives (gang not ready -> not ready response, but alive)
    resp = am.GetClusterSpec(pb.GetClusterSpecRequest(job_name="worker", index=0), None)
    assert not resp.ready
    am._check_heartbeats()
    assert am.session.task("worker", 0).state == TaskState.REGISTERED


def test_stale_registered_task_without_polls_is_lost(am):
    am.session.register("worker", 0, "h", 1000, 0)
    _age(am, "worker", 0, 100.0)
    am._check_heartbeats()
    assert am.session.task("worker", 0).state == TaskState.LOST


def test_heartbeat_rpc_refreshes_and_aborts_stale_attempt(am):
    am.session.register("worker", 0, "h", 1000, 0)
    _age(am, "worker", 0, 100.0)
    r = am.Heartbeat(pb.HeartbeatRequest(job_name="worker", index=0, attempt=0), None)
    assert r.action == pb.HeartbeatResponse.NONE
    am._check_heartbeats()
    assert am.session.task("worker", 0).state == TaskState.REGISTERED
    # stale attempt is ordered to abort
    r = am.Heartbeat(pb.HeartbeatRequest(job_name="worker", index=0, attempt=7), None)
    assert r.action == pb.HeartbeatResponse.ABORT


def test_cluster_spec_marks_running_when_gang_ready(am):
    am.session.register("worker", 0, "h", 1000, 0)
    am.session.register("worker", 1, "h", 1001, 0)
    resp = am.GetClusterSpec(pb.GetClusterSpecRequest(job_name="worker", index=0), None)
    assert resp.ready and resp.num_processes == 2
    assert am.session.task("worker", 0).state == TaskState.RUNNING
    assert am.session.task("worker", 1).state == TaskState.REGISTERED

"""Config-system tests.

Mirrors the reference's conf-parsing unit tests plus its defaults-vs-docs
consistency test (SURVEY.md sections 4 and 5).
"""

import os

import pytest

from tony_tpu.config import DEFAULTS, Keys, TaskTypeSpec, TonyConfig, job_key


def test_defaults_layer():
    cfg = TonyConfig()
    assert cfg.get_str(Keys.APPLICATION_FRAMEWORK) == "jax"
    assert cfg.get_int(Keys.TASK_HEARTBEAT_INTERVAL_MS) == 1000
    assert cfg.get_bool(Keys.APPLICATION_SECURITY_ENABLED) is False
    assert cfg.get_str(Keys.SCHEDULER_MODE) == "GANG"


def test_every_default_key_is_a_registered_key():
    registered = {
        v for k, v in vars(Keys).items() if not k.startswith("_") and isinstance(v, str)
    }
    assert set(DEFAULTS) <= registered


def test_toml_layer_overrides_defaults(tmp_path):
    toml = tmp_path / "tony.toml"
    toml.write_text(
        """
[application]
name = "mnist"
framework = "tensorflow"

[job.worker]
instances = 4
memory_mb = 4096
tpu_chips = 1
command = "python train.py"

[job.ps]
instances = 2
depends_on = ""

[job.tensorboard]
instances = 1
untracked = true
"""
    )
    cfg = TonyConfig.load(toml)
    assert cfg.get_str(Keys.APPLICATION_NAME) == "mnist"
    assert cfg.get_str(Keys.APPLICATION_FRAMEWORK) == "tensorflow"
    assert sorted(cfg.job_types()) == ["ps", "tensorboard", "worker"]
    w = cfg.task_spec("worker")
    assert w == TaskTypeSpec(
        name="worker",
        instances=4,
        memory_mb=4096,
        tpu_chips=1,
        command="python train.py",
    )
    assert cfg.task_spec("tensorboard").untracked is True
    # defaults still visible underneath
    assert cfg.get_int(Keys.TASK_MAX_MISSED_HEARTBEATS) == 25


def test_cli_overrides_beat_toml(tmp_path):
    toml = tmp_path / "tony.toml"
    toml.write_text("[job.worker]\ninstances = 4\n")
    cfg = TonyConfig.load(toml, overrides=["job.worker.instances=8", "am.rpc_port=5555"])
    assert cfg.task_spec("worker").instances == 8
    assert cfg.get_int(Keys.AM_RPC_PORT) == 5555


def test_cli_override_type_inference():
    cfg = TonyConfig.load(
        overrides=["a.b=true", "a.c=3", "a.d=1.5", "a.e=hello", "a.f=false"]
    )
    assert cfg.get("a.b") is True
    assert cfg.get("a.c") == 3
    assert cfg.get("a.d") == 1.5
    assert cfg.get("a.e") == "hello"
    assert cfg.get("a.f") is False


def test_env_override(monkeypatch):
    monkeypatch.setenv("TONY_CONF_application__name", "from-env")
    cfg = TonyConfig.load(read_env=True)
    assert cfg.get_str(Keys.APPLICATION_NAME) == "from-env"


def test_json_roundtrip_ships_identical_config(tmp_path):
    toml = tmp_path / "tony.toml"
    toml.write_text("[job.worker]\ninstances = 3\nenv = [\"A=1\", \"B=2\"]\n")
    cfg = TonyConfig.load(toml, overrides=["x.y=42"])
    clone = TonyConfig.from_json(cfg.to_json())
    assert clone.to_dict() == cfg.to_dict()
    assert clone.task_spec("worker").env == {"A": "1", "B": "2"}


def test_get_list_accepts_csv_and_lists():
    cfg = TonyConfig({"l1": "a, b ,c", "l2": ["x", "y"]})
    assert cfg.get_list("l1") == ["a", "b", "c"]
    assert cfg.get_list("l2") == ["x", "y"]
    assert cfg.get_list("missing", ["d"]) == ["d"]


def test_job_key_templating():
    assert job_key("evaluator", "tpu_chips") == "job.evaluator.tpu_chips"


def test_bad_override_raises():
    with pytest.raises(ValueError):
        TonyConfig.load(overrides=["no-equals-sign"])


def test_env_entry_without_equals_raises():
    cfg = TonyConfig({"job.w.env": ["FOO"]})
    with pytest.raises(ValueError, match="FOO"):
        cfg.task_spec("w")


def test_untracked_string_false_is_false():
    cfg = TonyConfig({"job.tb.untracked": "false", "job.tb2.untracked": "true"})
    assert cfg.task_spec("tb").untracked is False
    assert cfg.task_spec("tb2").untracked is True


def test_job_suffixes_match_taskspec_fields():
    import dataclasses
    from tony_tpu.config.keys import JOB_SUFFIXES

    fields = {f.name for f in dataclasses.fields(TaskTypeSpec)} - {"name"}
    assert fields == set(JOB_SUFFIXES)


def test_minitoml_subset_matches_tomllib_semantics():
    """The 3.10 fallback reader must agree with stdlib tomllib on the
    subset it supports — same values in, same values (or an error) out."""
    from tony_tpu.config import _minitoml as m

    doc = (
        "# header comment\n"
        "[application]\n"
        'name = "mnist"  # trailing comment\n'
        "timeout_s = 300\n"
        "ratio = 1.5\n"
        "flag = true\n"
        "[job.worker]\n"
        "instances = 2\n"
        "command = \"python -c \\\"print('hi # not a comment')\\\"\"\n"
        "env = [\"A=1\", \"B=#2\",\n"
        "       \"C=3\"]\n"
        "tag = 'lit#eral'\n"
    )
    got = m.loads(doc)
    assert got["application"] == {
        "name": "mnist", "timeout_s": 300, "ratio": 1.5, "flag": True
    }
    w = got["job"]["worker"]
    assert w["instances"] == 2
    assert w["command"] == 'python -c "print(\'hi # not a comment\')"'
    assert w["env"] == ["A=1", "B=#2", "C=3"]
    assert w["tag"] == "lit#eral"
    # anything beyond the subset fails loudly — never a half-parsed config
    for bad in (
        "[[jobs]]\nx = 1\n",              # arrays of tables
        "x = {a = 1}\n",                  # inline tables
        'x = """multi"""\n',              # multi-line strings
        'x = "bad \\q escape"\n',         # invalid escape (tomllib rejects too)
        "x = wat\n",                      # bare garbage value
    ):
        with pytest.raises(m.TOMLDecodeError):
            m.loads(bad)


def test_no_dead_config_keys():
    """Every advertised Keys.* constant must have a consumer outside
    keys.py — a config surface that silently ignores documented keys is
    worse than a smaller honest one."""
    import re
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    names = []
    for line in open(os.path.join(repo, "tony_tpu", "config", "keys.py")):
        m = re.match(r'\s+([A-Z_]+) = "', line)
        if m:
            names.append(m.group(1))
    assert len(names) > 25  # sanity: the registry is still the registry
    out = subprocess.run(
        ["grep", "-rn", "--include=*.py", "-E", r"Keys\.[A-Z_]+",
         os.path.join(repo, "tony_tpu"), os.path.join(repo, "tests")],
        capture_output=True, text=True,
    ).stdout
    dead = [
        n for n in names
        if not any(
            f"Keys.{n}" in l for l in out.splitlines() if "config/keys.py" not in l
        )
    ]
    assert dead == [], f"config keys defined but consumed nowhere: {dead}"


def test_config_doc_is_not_stale():
    """docs/CONFIG.md is generated (scripts/gen_config_doc.py); a knob added
    to DataConfig/FitConfig/keys.py without regenerating the doc fails here
    — run `python scripts/gen_config_doc.py` to fix. The subprocess runs the
    script's --check mode exactly as CI would."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "gen_config_doc.py"),
         "--check"],
        capture_output=True, text=True, env=env, cwd=repo,
    )
    assert out.returncode == 0, f"stale docs/CONFIG.md:\n{out.stderr}{out.stdout}"

"""Chaos subsystem: injected faults against real jobs + invariant checking.

Every scenario runs a genuine client -> AM -> executor job (the E2E
posture of test_e2e.py) with a declarative ``chaos.*`` fault schedule
armed inside the AM/executor processes, then verifies BOTH the expected
recovery behavior and a zero-violation invariant report — the recovery
contract as CI instead of prose (docs/CHAOS.md).
"""

import json
import os
import threading
import time

import pytest

from tony_tpu.chaos import (
    active_injector,
    chaos_hook,
    install_from_config,
    parse_faults,
    uninstall,
)
from tony_tpu.chaos.invariants import check_invariants
from tony_tpu.cli.client import TonyClient
from tony_tpu.config.config import TonyConfig

FAST = {
    "task.heartbeat_interval_ms": 200,
    "task.max_missed_heartbeats": 10,
    "application.timeout_s": 90,
}


def chaos_submit(tmp_path, overrides, faults):
    """Run one job under a fault schedule; returns (code, app_dir, report)."""
    cfg = TonyConfig.load(
        overrides={
            **FAST,
            "application.stage_dir": str(tmp_path),
            "application.framework": "generic",
            "chaos.enabled": True,
            "chaos.faults": json.dumps(faults),
            **overrides,
        }
    )
    client = TonyClient(cfg)
    code = client.run(quiet=True)
    report = check_invariants(
        [client.app_dir], rm_root=str(overrides.get("cluster.rm_root", ""))
    )
    return code, client.app_dir, report


def read_status(app_dir):
    with open(os.path.join(app_dir, "status.json")) as f:
        return json.load(f)


def events_of(app_dir):
    from tony_tpu.am.events import read_history

    ev_dir = os.path.join(app_dir, "events")
    files = [f for f in os.listdir(ev_dir) if f.endswith(".jsonl")]
    assert len(files) == 1
    return read_history(os.path.join(ev_dir, files[0]))


# --- the no-op contract ------------------------------------------------------


def test_hooks_are_noops_when_chaos_absent():
    """Acceptance criterion: with no chaos config, nothing arms and every
    hook returns None — the entire subsystem is one global-load + compare
    on the hot paths."""
    assert active_injector() is None
    assert chaos_hook("am.tick", attempt=0) is None
    assert chaos_hook("lease.locked") is None
    assert install_from_config(TonyConfig(), role="am") is False
    assert active_injector() is None
    # enabled but empty schedule: still inert
    assert install_from_config(
        TonyConfig({"chaos.enabled": True}), role="am"
    ) is False
    # schedule present but gate off: still inert
    assert install_from_config(
        TonyConfig({"chaos.faults": '[{"type": "kill_am", "at_count": 1}]'}),
        role="am",
    ) is False
    assert active_injector() is None


def test_fault_schedule_validation():
    with pytest.raises(ValueError, match="unknown fault type"):
        parse_faults('[{"type": "meteor_strike"}]')
    with pytest.raises(ValueError, match="not valid JSON"):
        parse_faults("{nope")
    with pytest.raises(ValueError, match="unknown field"):
        parse_faults('[{"type": "kill_am", "at_tick": 3}]')
    with pytest.raises(ValueError, match="needs an explicit 'point'"):
        parse_faults('[{"type": "delay_point", "delay_ms": 5}]')
    specs = parse_faults(
        '[{"type": "kill_container", "task": "worker:0", "at_count": 2}]'
    )
    assert specs[0].point == "executor.beat"
    assert specs[0].role == "executor"
    assert specs[0].attempt == 0  # kill faults default to attempt 0


def test_role_and_window_filtering():
    cfg = TonyConfig(
        {
            "chaos.enabled": True,
            "chaos.faults": json.dumps(
                [{"type": "drop_heartbeats", "task": "worker:0",
                  "from_count": 2, "to_count": 3}]
            ),
        }
    )
    try:
        assert install_from_config(cfg, role="executor") is True
        hook = lambda **kw: chaos_hook("executor.beat", **kw)  # noqa: E731
        assert hook(task="worker:0") is None          # count 1: before window
        assert hook(task="worker:1") is None          # count 2: wrong task
        assert hook(task="worker:0") is not None      # count 3: fires
        assert hook(task="worker:0") is None          # count 4: past window
    finally:
        uninstall()


# --- scenario 1: kill-container -> gang restart ------------------------------


def test_chaos_kill_container_gang_restart(tmp_path):
    """SIGKILL worker:0's container (executor + user process group) at its
    2nd heartbeat; the gang restart policy relaunches the whole job and it
    succeeds on attempt 1 — with a clean invariant report (monotonic
    generations, terminal status)."""
    code, app_dir, report = chaos_submit(
        tmp_path,
        {
            "application.name": "chaos-killc",
            "restart.policy": "gang",
            "restart.max_worker_restarts": 2,
            "job.worker.instances": 2,
            "job.worker.command": 'python -c "import time; time.sleep(2)"',
        },
        [{"type": "kill_container", "task": "worker:0", "at_count": 2}],
    )
    assert code == 0
    status = read_status(app_dir)
    assert status["state"] == "SUCCEEDED"
    # the kill really happened: every task went around twice
    assert all(t["attempts"] == 2 for t in status["tasks"])
    assert any(e["type"] == "GANG_RESTART" for e in events_of(app_dir))
    assert report.ok, report.to_json()


# --- scenario 2: kill-AM -> attempt recovery with lease re-ownership ---------


def test_chaos_kill_am_attempt_recovery(tmp_path):
    """SIGKILL the AM at supervision tick 3 (containers allocated and
    journalled, leases held in the shared store). The client relaunches
    attempt 1, which reaps the orphaned containers, takes over the store
    reservation (the dead predecessor's entry is pid-reaped, the
    re-reservation lands under the new owner), bumps the generation, and
    the job succeeds. Store must be empty afterwards."""
    rm_root = str(tmp_path / "rm")
    code, app_dir, report = chaos_submit(
        tmp_path,
        {
            "application.name": "chaos-killam",
            "am.retry_count": 1,
            "cluster.rm_root": rm_root,
            "job.worker.instances": 2,
            "job.worker.command": 'python -c "import time; time.sleep(4)"',
        },
        [{"type": "kill_am", "at_count": 3}],
    )
    assert code == 0
    assert read_status(app_dir)["state"] == "SUCCEEDED"
    with open(os.path.join(app_dir, "am.state.json")) as f:
        snap = json.load(f)
    assert snap["am_attempt"] == 1  # the kill consumed attempt 0
    assert snap["generation"] >= 1
    assert report.ok, report.to_json()
    # all leases returned by the successor's teardown
    from tony_tpu.cluster.lease import LeaseStore

    summary = LeaseStore(rm_root).summary()
    assert not summary["apps"] and not summary["queue"]


# --- scenario 3: hang-store -> fence with client-visible FAILED --------------


def test_chaos_hang_store_fences_and_client_sees_failed(tmp_path):
    """The ADVICE round-5 medium bug, end-to-end: the lease store hangs
    forever in open()/flock (hard-mount partition). The AM's lease keeper
    goes silent, the staleness fence fires at ttl/2, and — this is the
    fixed part — teardown SKIPS the lease release that used to wedge the
    AM in the same flock, so status.json lands and the client sees FAILED
    instead of hanging until its own timeout."""
    rm_root = str(tmp_path / "rm")
    t0 = time.monotonic()
    code, app_dir, report = chaos_submit(
        tmp_path,
        {
            "application.name": "chaos-hang",
            "cluster.rm_root": rm_root,
            "cluster.lease_ttl_s": 2,
            "application.timeout_s": 60,
            "job.worker.instances": 1,
            "job.worker.command": 'python -c "import time; time.sleep(30)"',
        },
        # every store access blocks 120s once the job is running; only the
        # AM is partitioned — the fence must come from staleness, not luck
        [{"type": "hang_store", "after_s": 3, "duration_s": 120, "role": "am"}],
    )
    took = time.monotonic() - t0
    assert code != 0
    status = read_status(app_dir)  # exists at all == the wedge is fixed
    assert status["state"] == "FAILED"
    assert "leases lost" in status["diagnostics"]
    # fenced at ~ttl/2 after the hang, not at the 30s worker sleep or the
    # 60s app timeout (the old wedge ran the client into its timeout)
    assert took < 25, f"fence path took {took:.1f}s — teardown blocked on the hung store?"
    assert report.ok, report.to_json()


# --- scenario 4: drop-heartbeats -> missed-heartbeat loss detection ----------


def test_chaos_drop_heartbeats_task_lost(tmp_path):
    """Suppress worker:0's executor->AM heartbeats from beat 3 on while
    its user process keeps running: the AM's missed-heartbeat accounting
    must mark the task LOST, fail the job, and release the container."""
    code, app_dir, report = chaos_submit(
        tmp_path,
        {
            "application.name": "chaos-hbdrop",
            "task.heartbeat_interval_ms": 100,
            "task.max_missed_heartbeats": 5,
            "job.worker.instances": 1,
            "job.worker.command": 'python -c "import time; time.sleep(30)"',
        },
        [{"type": "drop_heartbeats", "task": "worker:0", "from_count": 3}],
    )
    assert code != 0
    status = read_status(app_dir)
    assert status["state"] == "FAILED"
    assert status["tasks"][0]["state"] == "LOST"
    assert report.ok, report.to_json()


# --- scenario 5: partition-host -> survivor reaping, no double-booking -------


def test_chaos_partition_survivor_reaps_without_double_booking(tmp_path):
    """Job A's AM is partitioned from the shared store (access raises for
    that one owner); A fences and dies. Job B, sharing the store and
    needing A's chips, reaps A's dead-owner entries and runs to success —
    capacity transfers through reaping, never through double-booking
    (checked over BOTH jobs' artifacts plus the store)."""
    rm_root = str(tmp_path / "rm")
    results = {}

    def run_a():
        results["a"] = chaos_submit(
            tmp_path,
            {
                "application.name": "chaos-part-a",
                "cluster.rm_root": rm_root,
                "cluster.lease_ttl_s": 2,
                "application.timeout_s": 60,
                "job.worker.instances": 1,
                "job.worker.tpu_chips": 64,  # the full local inventory
                "job.worker.command": 'python -c "import time; time.sleep(30)"',
            },
            [{"type": "partition_host", "after_s": 3, "role": "am"}],
        )

    ta = threading.Thread(target=run_a)
    ta.start()
    time.sleep(4.0)  # A is running and holds every chip; partition begins
    cfg_b = TonyConfig.load(
        overrides={
            **FAST,
            "application.stage_dir": str(tmp_path),
            "application.framework": "generic",
            "application.name": "chaos-part-b",
            "cluster.rm_root": rm_root,
            "am.allocation_timeout_s": 60,
            "job.worker.instances": 1,
            "job.worker.tpu_chips": 64,
            "job.worker.command": 'python -c "pass"',
        }
    )
    client_b = TonyClient(cfg_b)
    code_b = client_b.run(quiet=True)
    ta.join(90)
    code_a, dir_a, _ = results["a"]
    assert code_a != 0 and read_status(dir_a)["state"] == "FAILED"
    assert code_b == 0 and read_status(client_b.app_dir)["state"] == "SUCCEEDED"
    report = check_invariants([dir_a, client_b.app_dir], rm_root=rm_root)
    assert report.ok, report.to_json()
    from tony_tpu.cluster.lease import LeaseStore

    summary = LeaseStore(rm_root).summary()
    assert not summary["apps"] and not summary["queue"]


# --- scenario 6: delay-rpc -> control plane tolerates latency ----------------


def test_chaos_delay_rpc_job_still_succeeds(tmp_path):
    """Seeded latency on every served control-plane RPC: the job must
    still assemble its gang and succeed — registration/heartbeat paths
    tolerate a slow AM."""
    code, app_dir, report = chaos_submit(
        tmp_path,
        {
            "application.name": "chaos-rpcdelay",
            "chaos.seed": 7,
            "job.worker.instances": 2,
            "job.worker.command": 'python -c "pass"',
        },
        [{"type": "delay_rpc", "delay_ms": 25, "jitter_ms": 25}],
    )
    assert code == 0
    assert read_status(app_dir)["state"] == "SUCCEEDED"
    assert report.ok, report.to_json()


# --- the CLI / runner surface ------------------------------------------------


def test_tony_chaos_cli_runs_and_reports(tmp_path, capsys):
    """`tony chaos`: schedule via --faults, job runs under injection, the
    invariant report prints as JSON, exit code reflects report + --expect."""
    from tony_tpu.cli.main import main as cli_main

    conf = tmp_path / "job.toml"
    conf.write_text(
        '[application]\nname = "chaos-cli"\nframework = "generic"\n'
        f'stage_dir = "{tmp_path}"\ntimeout_s = 90\n'
        "[task]\nheartbeat_interval_ms = 200\n"
        "[job.worker]\ninstances = 1\n"
        'command = "python -c \\"pass\\""\n'
    )
    rc = cli_main(
        [
            "chaos", "--conf", str(conf), "--quiet",
            "--faults", '[{"type": "delay_rpc", "delay_ms": 10}]',
            "--expect", "SUCCEEDED",
        ]
    )
    out = capsys.readouterr().out
    payload = json.loads(out[out.index("{"):])
    assert rc == 0
    assert payload["state"] == "SUCCEEDED"
    assert payload["report"]["ok"] is True
    # a malformed schedule fails before submitting anything
    rc = cli_main(
        ["chaos", "--conf", str(conf), "--faults", '[{"type": "nope"}]']
    )
    assert rc == 2


# --- satellite regressions at the backend layer ------------------------------


def test_fenced_backend_skips_lease_release(tmp_path):
    """After fence_leases(), stop() must not touch the store: the entries
    stay for pid/TTL reaping (releasing could block forever on the very
    store whose unreachability caused the fence)."""
    from tony_tpu.cluster.backend import Resource
    from tony_tpu.cluster.lease import LeaseStore
    from tony_tpu.cluster.local import LocalProcessBackend

    store = LeaseStore(str(tmp_path / "rm"), lease_ttl_s=600)
    b = LocalProcessBackend(
        Resource(4096, 4, 16), lease_store=store, app_id="fenced-job"
    )
    b.start()
    b.reserve_job([(Resource(64, 1, 4), "")], timeout_s=5)
    b.fence_leases()
    t0 = time.monotonic()
    b.stop()
    assert time.monotonic() - t0 < 5  # and it must not block either
    assert "fenced-job" in LeaseStore(str(tmp_path / "rm")).summary()["apps"]


def test_ondemand_losing_leases_released_not_stranded(tmp_path):
    """ADVICE round 5 (remote.py:587 family): when the store's view of a
    host is wider than the local inventory (another job registered it
    first), on-demand grants can never be claimed locally. The acquire
    loop must fail bounded AND hand every losing lease back — not strand
    them for the job's lifetime."""
    from tony_tpu.cluster.backend import (
        ContainerRequest, InsufficientResources, Resource,
    )
    from tony_tpu.cluster.lease import LeaseStore
    from tony_tpu.cluster.local import LocalProcessBackend
    from tony_tpu.utils.net import local_host

    root = str(tmp_path / "rm")
    # a foreign job pinned this host's capacity WIDER than reality
    LeaseStore(root, owner_host="first-registrar").register_hosts(
        {local_host(): Resource(1 << 20, 256, 64)}
    )
    b = LocalProcessBackend(
        Resource(4096, 4, 4),  # the real machine: only 4 chips
        lease_store=LeaseStore(root),
        app_id="overask-job",
    )
    b.start()
    req = ContainerRequest(
        task_type="w", task_index=0, resource=Resource(64, 1, 8),
        argv=["true"], env={}, log_path="",
    )
    with pytest.raises(InsufficientResources):
        b.allocate(req)  # store grants 8 chips; local capacity can't claim
    # the losing on-demand lease went back to the store
    summary = LeaseStore(root).summary()
    assert "overask-job" not in summary["apps"], summary
    b.stop()


def test_remote_ondemand_retry_is_bounded_and_releases(tmp_path, monkeypatch):
    """The RemoteBackend mirror: if grants never become claimable locally,
    the loop gives up after ONDEMAND_MAX_ATTEMPTS store grants and leaves
    zero leases behind."""
    from tony_tpu.cluster.backend import (
        ContainerRequest, InsufficientResources, Resource,
    )
    from tony_tpu.cluster.lease import LeaseStore
    from tony_tpu.cluster.remote import LocalTransport, RemoteBackend

    root = str(tmp_path / "rm")
    b = RemoteBackend(
        ["h1"],
        transport=LocalTransport(),
        host_capacity=Resource(4096, 4, 8),
        lease_store=LeaseStore(root),
        app_id="remote-overask",
    )
    b.start()
    grants = []
    orig_claim = RemoteBackend._claim_gang_slot

    def never_claim(self, request, cid):
        grants.append(cid)
        return None  # simulate every local claim losing

    monkeypatch.setattr(RemoteBackend, "_claim_gang_slot", never_claim)
    monkeypatch.setattr(
        RemoteBackend, "_place",
        lambda self, request: (_ for _ in ()).throw(
            InsufficientResources("forced")
        ),
    )
    req = ContainerRequest(
        task_type="w", task_index=0, resource=Resource(64, 1, 4),
        argv=["true"], env={}, log_path="",
    )
    with pytest.raises(InsufficientResources, match="never claimable"):
        b.allocate(req)
    # one claim try before the loop + one per bounded on-demand attempt
    assert len(grants) == RemoteBackend.ONDEMAND_MAX_ATTEMPTS + 1
    monkeypatch.setattr(RemoteBackend, "_claim_gang_slot", orig_claim)
    summary = LeaseStore(root).summary()
    assert "remote-overask" not in summary["apps"], summary
    b.stop()


def test_lease_ttl_clamped_against_heartbeat(tmp_path, caplog):
    """make_backend warns-and-clamps a TTL below 4x the heartbeat interval
    (a config that would let a healthy cross-host owner self-fence)."""
    import logging

    from tony_tpu.cluster import make_backend

    cfg = TonyConfig(
        {
            "cluster.rm_root": str(tmp_path / "rm"),
            "cluster.lease_ttl_s": 0.5,
            "task.heartbeat_interval_ms": 1000,
        }
    )
    with caplog.at_level(logging.WARNING, logger="tony_tpu.cluster"):
        b = make_backend("local", cfg, app_id="clamped")
    assert b.lease_ttl_s() == 4.0
    assert any("clamping TTL" in r.message for r in caplog.records)
    # a sane TTL passes through untouched
    cfg2 = TonyConfig(
        {"cluster.rm_root": str(tmp_path / "rm2"), "cluster.lease_ttl_s": 600}
    )
    assert make_backend("local", cfg2, app_id="ok").lease_ttl_s() == 600.0


def test_generation_monotonicity_follows_journal_order(tmp_path):
    """AM-recovery and gang-restart generations interleave in emit order:
    METADATA(recovered=1) then GANG_RESTART(2) is monotonic; the reverse
    numbering is a violation."""

    def job_with(events):
        d = tmp_path / f"gen-{len(os.listdir(tmp_path)) if tmp_path.exists() else 0}"
        d.mkdir()
        (d / "status.json").write_text(
            json.dumps({"state": "SUCCEEDED", "exit_code": 0, "tasks": []})
        )
        ev = d / "events"
        ev.mkdir()
        lines = [json.dumps(e) for e in events] + [
            json.dumps({"type": "APPLICATION_FINISHED", "ts": 3, "state": "SUCCEEDED"})
        ]
        (ev / f"{d.name}.jhist.jsonl").write_text("\n".join(lines) + "\n")
        return str(d)

    ok_dir = job_with(
        [
            {"type": "METADATA", "ts": 1, "recovered_generation": 1},
            {"type": "GANG_RESTART", "ts": 2, "generation": 2},
        ]
    )
    assert check_invariants([ok_dir]).ok
    bad_dir = job_with(
        [
            {"type": "GANG_RESTART", "ts": 1, "generation": 2},
            {"type": "METADATA", "ts": 2, "recovered_generation": 1},
        ]
    )
    report = check_invariants([bad_dir])
    assert any(v.invariant == "generation-monotonic" for v in report.violations)


def test_invariant_checker_flags_violations(tmp_path):
    """The checker itself must fail loudly on broken artifacts — a checker
    that cannot see violations proves nothing."""
    # job dir with no status.json at all (the wedge symptom)
    wedged = tmp_path / "wedged-app"
    wedged.mkdir()
    report = check_invariants([str(wedged)])
    assert not report.ok
    assert any(v.invariant == "terminal-status" for v in report.violations)
    # a store entry with no reclaim path: live (our own) owner, terminal job
    done = tmp_path / "done-app"
    done.mkdir()
    (done / "status.json").write_text(
        json.dumps({"state": "SUCCEEDED", "exit_code": 0, "tasks": []})
    )
    ev = done / "events"
    ev.mkdir()
    (ev / "done-app.jhist.jsonl").write_text(
        json.dumps({"type": "APPLICATION_FINISHED", "ts": 0, "state": "SUCCEEDED"})
        + "\n"
    )
    from tony_tpu.cluster.backend import Resource
    from tony_tpu.cluster.lease import GangAsk, LeaseStore

    root = str(tmp_path / "rm")
    s = LeaseStore(root, lease_ttl_s=0)  # no TTL: nothing will ever reap this
    s.register_hosts({"h1": Resource(256, 4, 8)})
    s.reserve_gang("done-app", [GangAsk(Resource(64, 1, 4))], timeout_s=0)
    report = check_invariants([str(done)], rm_root=root)
    assert any(v.invariant == "lease-no-strand" for v in report.violations), (
        report.to_json()
    )

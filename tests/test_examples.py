"""CI for the shipped examples: every examples/*/tony.toml must submit and
succeed end-to-end through the real CLI path.

The examples are the user-facing contract (the reference's tony-examples,
SURVEY.md section 2); each maps to a BASELINE.md milestone config. Tests
shrink step counts via -D overrides but change nothing else, so a rotted
example fails here before a user finds it.
"""

import os
import sys

import pytest

from tony_tpu.cli.main import main as cli_main

EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"
)


def submit_example(name: str, tmp_path, extra: list[str] = ()) -> int:
    ex_dir = os.path.join(EXAMPLES, name)
    argv = [
        "submit",
        "--conf", os.path.join(ex_dir, "tony.toml"),
        "--src-dir", ex_dir,
        "-D", f"application.stage_dir={tmp_path}",
        "--quiet",
    ]
    for d in extra:
        argv += ["-D", d]
    return cli_main(argv)


@pytest.mark.slow
def test_example_mnist_jax(tmp_path):
    """Milestone config #1: single-worker MNIST via CLI submit."""
    assert submit_example("mnist_jax", tmp_path) == 0


@pytest.mark.slow
def test_example_mnist_tf(tmp_path):
    """Milestone config #2 shape: TF ps+worker, FCFS, TF_CONFIG contract."""
    pytest.importorskip("tensorflow")
    assert submit_example("mnist_tf", tmp_path) == 0


@pytest.mark.slow
def test_example_llama_pretrain(tmp_path):
    """Flagship: 2-process DP llama via fit() on the virtual CPU mesh."""
    code = cli_main([
        "submit",
        "--conf", os.path.join(EXAMPLES, "llama_pretrain", "tony.toml"),
        "--src-dir", os.path.join(EXAMPLES, "llama_pretrain"),
        "-D", f"application.stage_dir={tmp_path}",
        "-D", ("job.worker.command=python train.py --preset tiny --steps 4 "
               "--global-batch 8 --seq-len 64"),
        "--quiet",
    ])
    assert code == 0


# worker-log fragments that identify the ONE known-benign failure mode:
# torch.distributed's gloo rendezvous cannot resolve/connect in an offline
# sandbox. Anything else (import errors, crashed training code, submission
# machinery) is a real failure and must fail the test.
GLOO_OFFLINE_SIGNATURES = (
    # specific rendezvous/transport markers only — a bare "gloo" would also
    # match example source lines quoted in unrelated tracebacks
    "connectFullMesh",
    "ProcessGroupGloo",
    "DistNetworkError",
    "Connection refused",
    "Network is unreachable",
    "No route to host",
    "Name or service not known",
    "Temporary failure in name resolution",
)


@pytest.mark.slow
def test_example_bert_pytorch(tmp_path):
    """Milestone config #3 shape: torch DDP gloo rendezvous from the
    PyTorchRuntime env contract. A nonzero exit is expected (xfail) ONLY
    for the known gloo-offline signature; any other failure is real."""
    pytest.importorskip("torch")
    code = submit_example("bert_pytorch", tmp_path)
    if code != 0:
        # surface the worker logs either way, and decide from their content
        combined = []
        apps = [d for d in os.listdir(tmp_path) if os.path.isdir(tmp_path / d)]
        for app in apps:
            logs = tmp_path / app / "logs"
            if logs.is_dir():
                for n in sorted(os.listdir(logs)):
                    text = open(logs / n, errors="replace").read()
                    combined.append(text)
                    sys.stderr.write(f"===== {n}\n" + text[-2000:])
        text = "\n".join(combined)
        if any(sig in text for sig in GLOO_OFFLINE_SIGNATURES):
            pytest.xfail(f"bert_pytorch example exited {code} (gloo offline)")
        if not text.strip():
            # workers died before writing any log: nothing to attribute the
            # failure to either way — keep the conservative xfail
            pytest.xfail(f"bert_pytorch example exited {code} (no worker logs)")
        pytest.fail(
            f"bert_pytorch example exited {code} without the gloo-offline "
            "signature — not the known-benign rendezvous failure"
        )

"""KV-cache decode + generation tests: cache path must match full forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import llama
from tony_tpu.models.generate import KVCache, forward_with_cache, generate


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    return cfg, params


def test_prefill_matches_forward(setup):
    cfg, params = setup
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    expect = llama.forward(params, tokens, cfg)
    cache = KVCache.create(cfg, 2, 32)
    got, _ = forward_with_cache(params, tokens, cache, jnp.int32(0), cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=1e-4)


def test_prefill_last_only_matches_full_projection(setup):
    """generate()'s prefill path projects ONLY the last position through
    lm_head ([B,1,V] instead of [B,S,V] fp32): same sampled logits, same
    cache, no prompt-sized logits transient."""
    cfg, params = setup
    tokens = jax.random.randint(jax.random.key(8), (2, 16), 0, cfg.vocab_size)
    full, cache_full = forward_with_cache(
        params, tokens, KVCache.create(cfg, 2, 32), jnp.int32(0), cfg
    )
    last, cache_last = forward_with_cache(
        params, tokens, KVCache.create(cfg, 2, 32), jnp.int32(0), cfg,
        last_only=True,
    )
    assert last.shape == (2, 1, cfg.vocab_size)
    np.testing.assert_allclose(
        np.asarray(last[:, 0]), np.asarray(full[:, -1]), atol=1e-5
    )
    np.testing.assert_array_equal(np.asarray(cache_last.k), np.asarray(cache_full.k))


def test_incremental_decode_matches_forward(setup):
    """Logits from one-token-at-a-time decoding must equal the full forward
    pass at every position — the KV cache is exact, not approximate."""
    cfg, params = setup
    tokens = jax.random.randint(jax.random.key(2), (1, 12), 0, cfg.vocab_size)
    expect = llama.forward(params, tokens, cfg)

    cache = KVCache.create(cfg, 1, 16)
    logits_steps = []
    for i in range(tokens.shape[1]):
        step_logits, cache = forward_with_cache(
            params, tokens[:, i : i + 1], cache, jnp.int32(i), cfg
        )
        logits_steps.append(step_logits[:, 0])
    got = jnp.stack(logits_steps, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=2e-4)


def test_greedy_generation_deterministic_and_shaped(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(3), (2, 5), 0, cfg.vocab_size)
    out1 = generate(params, prompt, cfg, max_new_tokens=8)
    out2 = generate(params, prompt, cfg, max_new_tokens=8)
    assert out1.shape == (2, 13)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    np.testing.assert_array_equal(np.asarray(out1[:, :5]), np.asarray(prompt))


def test_greedy_matches_forward_argmax(setup):
    """First generated token == argmax of the full-forward last-position
    logits (cache prefill consistency at the generation boundary)."""
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(4), (2, 7), 0, cfg.vocab_size)
    out = generate(params, prompt, cfg, max_new_tokens=1)
    expect = jnp.argmax(llama.forward(params, prompt, cfg)[:, -1], axis=-1)
    np.testing.assert_array_equal(np.asarray(out[:, -1]), np.asarray(expect))


def test_sampled_generation_respects_top_k(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(5), (1, 4), 0, cfg.vocab_size)
    out = generate(
        params, prompt, cfg, max_new_tokens=6, temperature=0.8, top_k=1,
        rng=jax.random.key(9),
    )
    # top_k=1 sampling degenerates to greedy
    greedy = generate(params, prompt, cfg, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(greedy))


def test_top_p_restricts_to_nucleus():
    """top_p sampling only ever emits tokens from the smallest prefix whose
    cumulative probability reaches p."""
    from tony_tpu.models.generate import _sample

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    seen = set()
    for i in range(64):
        tok = _sample(logits, temperature=1.0, top_k=0, top_p=0.6,
                      rng=jax.random.key(i))
        seen.add(int(tok[0]))
    # 0.5 alone < 0.6, so token 1 joins the nucleus; 2 and 3 never can
    assert seen <= {0, 1}
    assert 0 in seen


def _legacy_sample(logits, temperature, top_k, top_p, rng):
    """The pre-round-9 sampler: full-vocab descending jnp.sort per call
    (V log V per decode step) — kept verbatim as the value oracle for the
    sort-free lax.top_k rewrite."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0 or top_p > 0.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        if top_k > 0:
            kth = sorted_logits[:, top_k - 1][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
            sorted_logits = jnp.where(
                sorted_logits < kth, -jnp.inf, sorted_logits
            )
        if top_p > 0.0:
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep_sorted = (cum - probs) < top_p
            cutoff = jnp.min(
                jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1
            )[:, None]
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def test_sample_matches_legacy_sort_impl_topk():
    """The sort-free sampler (lax.top_k + scatter-back) draws EXACTLY the
    legacy full-sort sampler's tokens for any top_k config: identical
    masked logits, identical categorical call, same rng."""
    from tony_tpu.models.generate import _sample

    logits = jax.random.normal(jax.random.key(0), (8, 500)) * 3.0
    for temperature, top_k, top_p in [
        (0.7, 10, 0.0), (1.0, 1, 0.0), (1.3, 40, 0.9), (0.5, 499, 0.3),
    ]:
        for seed in range(5):
            rng = jax.random.key(seed)
            want = _legacy_sample(logits, temperature, top_k, top_p, rng)
            got = _sample(logits, temperature, top_k, top_p, rng)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sample_matches_legacy_sort_impl_top_p_only():
    """top-p without top_k uses the bounded default-k slice; for vocab <=
    DEFAULT_NUCLEUS_K the slice is the whole sorted vocab, so the nucleus
    cutoff — and the draws — match the legacy sampler exactly."""
    from tony_tpu.models.generate import DEFAULT_NUCLEUS_K, _sample

    V = DEFAULT_NUCLEUS_K
    logits = jax.random.normal(jax.random.key(1), (6, V)) * 2.0
    for top_p in (0.3, 0.7, 0.95):
        for seed in range(5):
            rng = jax.random.key(100 + seed)
            want = _legacy_sample(logits, 0.9, 0, top_p, rng)
            got = _sample(logits, 0.9, 0, top_p, rng)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sample_tokens_vectorises_heterogeneous_rows():
    """The engine's per-row sampler: greedy rows equal argmax regardless of
    key; top_k=1 rows are deterministic; truncated rows only emit admitted
    tokens."""
    from tony_tpu.models.generate import sample_tokens

    logits = jax.random.normal(jax.random.key(2), (4, 64)) * 2.0
    rngs = jax.random.key_data(jax.random.split(jax.random.key(3), 4))
    temp = jnp.asarray([0.0, 1.0, 0.8, 1.2], jnp.float32)
    top_k = jnp.asarray([0, 1, 3, 0], jnp.int32)
    top_p = jnp.asarray([0.0, 0.0, 0.0, 0.5], jnp.float32)
    toks = sample_tokens(logits, temp, top_k, top_p, rngs)
    assert int(toks[0]) == int(jnp.argmax(logits[0]))
    assert int(toks[1]) == int(jnp.argmax(logits[1]))  # top_k=1 == greedy
    top3 = set(np.asarray(jax.lax.top_k(logits[2], 3)[1]))
    assert int(toks[2]) in top3


def test_eos_rows_stick():
    """Rows that emit eos keep emitting it (static-shape early stop)."""
    from tony_tpu.models.generate import generate
    from tony_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig.tiny()
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    # greedy with eos_id equal to whatever the first generated token is:
    # every subsequent token must then repeat it
    first = generate(params, prompt, cfg, max_new_tokens=1)[0, -1]
    out = generate(params, prompt, cfg, max_new_tokens=6, eos_id=int(first))
    tail = np.asarray(out[0, 3:])
    assert (tail == int(first)).all(), tail

"""Decomposed fsdp collectives + bucketed dp grad reduce (ops/overlap.py).

The SURVEY harness idiom: every ring decomposition is compared against the
dense single-device reference on small shapes — value AND grad, for both
impls ('scan' pure-XLA, 'pallas' interpret-mode kernels) and both shard
dims. The trainer-side contract is stronger than allclose: bucketing a
grad all-reduce is a schedule, not an approximation, so the bucketed loss
trajectory must be BITWISE-identical to the single-collective one.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tony_tpu.ops.compat import shard_map_compat as _shard_map
from tony_tpu.ops.overlap import (
    all_gather_matmul_local,
    bucket_bytes_from_report,
    bucket_plan,
    bucketed_psum,
    matmul_reduce_scatter_local,
    overlap_matmul,
)
from tony_tpu.parallel.mesh import MeshShape, build_mesh, set_default_mesh

IMPLS = ("scan", "pallas")


def _fsdp_mesh():
    return build_mesh(MeshShape(fsdp=4, tp=2))


class TestRingOps:
    """Ring all-gather-matmul / matmul-reduce-scatter vs the dense form."""

    @pytest.mark.parametrize("impl", IMPLS)
    @pytest.mark.parametrize("gather_dim", [0, 1])
    def test_all_gather_matmul_value_and_grad(self, impl, gather_dim):
        mesh = _fsdp_mesh()
        x = jax.random.normal(jax.random.key(0), (8, 16), jnp.float32)
        w = jax.random.normal(jax.random.key(1), (16, 24), jnp.float32)
        x_spec = P("fsdp", None)
        w_spec = P("fsdp", None) if gather_dim == 0 else P(None, "fsdp")

        def ring(x, w):
            return _shard_map(
                lambda xl, wl: all_gather_matmul_local(
                    xl, wl, "fsdp", gather_dim, impl
                ),
                mesh=mesh, in_specs=(x_spec, w_spec), out_specs=x_spec,
                axis_names={"fsdp"},
            )(x, w)

        np.testing.assert_allclose(
            np.asarray(ring(x, w)), np.asarray(x @ w), rtol=1e-5, atol=1e-5
        )
        # grad symmetry: the custom_vjp's mirrored rings vs autodiff of x @ w
        loss = lambda f: lambda a, b: (jnp.sin(f(a, b))).sum()
        gx, gw = jax.grad(loss(ring), argnums=(0, 1))(x, w)
        rx, rw = jax.grad(loss(lambda a, b: a @ b), argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("impl", IMPLS)
    @pytest.mark.parametrize("scatter_dim", [0, 1])
    def test_matmul_reduce_scatter_value_and_grad(self, impl, scatter_dim):
        mesh = _fsdp_mesh()
        x = jax.random.normal(jax.random.key(2), (8, 16), jnp.float32)
        g = jax.random.normal(jax.random.key(3), (8, 24), jnp.float32)
        in_spec = P("fsdp", None)  # batch rows around the ring
        out_spec = P("fsdp", None) if scatter_dim == 0 else P(None, "fsdp")

        def rs(x, g):
            return _shard_map(
                lambda xl, gl: matmul_reduce_scatter_local(
                    xl, gl, "fsdp", scatter_dim, impl
                ),
                mesh=mesh, in_specs=(in_spec, in_spec), out_specs=out_spec,
                axis_names={"fsdp"},
            )(x, g)

        np.testing.assert_allclose(
            np.asarray(rs(x, g)), np.asarray(x.T @ g), rtol=1e-5, atol=1e-5
        )
        loss = lambda f: lambda a, b: (jnp.sin(f(a, b))).sum()
        gx, gg = jax.grad(loss(rs), argnums=(0, 1))(x, g)
        rx, rg = jax.grad(loss(lambda a, b: a.T @ b), argnums=(0, 1))(x, g)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gg), np.asarray(rg),
                                   rtol=1e-4, atol=1e-5)

    def test_unknown_impl_raises(self):
        with pytest.raises(ValueError, match="unknown overlap impl"):
            all_gather_matmul_local(
                jnp.ones((4, 4)), jnp.ones((4, 4)), "fsdp", 0, "mosaic"
            )


class TestOverlapMatmulEntry:
    """The GSPMD-context router: applies when it can, None when it can't."""

    def test_matches_plain_matmul_3d(self):
        mesh = _fsdp_mesh()
        x = jax.random.normal(jax.random.key(0), (8, 4, 16), jnp.float32)
        w = jax.random.normal(jax.random.key(1), (16, 24), jnp.float32)
        y = overlap_matmul(x, w, gather_dim=0, impl="scan", mesh=mesh)
        assert y is not None
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(x @ w), rtol=1e-5, atol=1e-5
        )

    def test_fallbacks_return_none(self):
        mesh = _fsdp_mesh()
        x = jnp.ones((8, 16))
        # no mesh anywhere -> None
        set_default_mesh(None)
        try:
            assert overlap_matmul(x, jnp.ones((16, 8)), gather_dim=0) is None
        finally:
            set_default_mesh(mesh)
        # indivisible gathered dim (17 % fsdp=4) -> None
        assert overlap_matmul(
            x, jnp.ones((16, 17)), gather_dim=1, mesh=mesh
        ) is None
        # axis size 1 -> None
        mesh_tp = build_mesh(MeshShape(tp=8))
        assert overlap_matmul(
            x, jnp.ones((16, 8)), gather_dim=0, mesh=mesh_tp
        ) is None

    def test_none_inside_manual_region(self):
        """Inside an enclosing shard_map (a pp stage, the bucketed-dp
        trainer region) the ring must NOT try to re-bind the fsdp axis —
        the router declines and the caller runs the plain matmul."""
        mesh = _fsdp_mesh()
        w = jnp.ones((16, 24))
        seen = []

        def f(xl):
            seen.append(
                overlap_matmul(xl, w, gather_dim=0, impl="scan", mesh=mesh)
            )
            return xl

        _shard_map(
            f, mesh=mesh, in_specs=(P("fsdp", None),),
            out_specs=P("fsdp", None), axis_names={"fsdp"},
        )(jnp.ones((8, 16)))
        assert seen == [None]


class TestBucketing:
    def test_bucket_plan_order_preserving_greedy(self):
        assert bucket_plan([4, 4, 4], 8) == [[0, 1], [2]]
        assert bucket_plan([4, 4, 4], 100) == [[0, 1, 2]]
        # an oversize leaf gets its own bucket, never split
        assert bucket_plan([2, 10, 2, 2], 4) == [[0], [1], [2, 3]]
        assert bucket_plan([], 8) == []
        with pytest.raises(ValueError, match="positive"):
            bucket_plan([1], 0)

    def test_bucketed_psum_bitwise_equals_whole_tree_psum(self):
        mesh = _fsdp_mesh()
        tree = {
            "a": jax.random.normal(jax.random.key(0), (8, 8), jnp.float32),
            "b": jax.random.normal(jax.random.key(1), (8, 16), jnp.float32),
            "c": jax.random.normal(jax.random.key(2), (8,), jnp.float32),
        }
        spec = {"a": P("fsdp", None), "b": P("fsdp", None), "c": P("fsdp")}

        def run(fn):
            return _shard_map(
                fn, mesh=mesh, in_specs=(spec,), out_specs=spec,
                axis_names={"fsdp"},
            )(tree)

        whole = run(lambda t: jax.tree.map(
            lambda x: jax.lax.psum(x, "fsdp"), t
        ))
        # 40 bytes per bucket forces several buckets; grouping is exact
        bucketed = run(lambda t: bucketed_psum(t, "fsdp", bucket_bytes=40))
        for k in tree:
            assert np.array_equal(np.asarray(whole[k]),
                                  np.asarray(bucketed[k]))

    def test_bucketed_psum_inside_1f1b_style_manual_region(self):
        """The trainer's bucketed reduce runs inside the SAME kind of
        full-manual region the 1F1B schedule owns: a nested in-region call
        must still be exact (one psum per bucket over the live axis)."""
        mesh = build_mesh(MeshShape(dp=2, fsdp=4))
        x = jax.random.normal(jax.random.key(0), (8, 4), jnp.float32)

        def region(xl):
            # a manual region over dp (the 1F1B/bucketed-trainer shape):
            # reduce a 2-leaf tree in 1-leaf buckets
            t = {"w": xl * 2.0, "b": xl.sum(-1)}
            return bucketed_psum(t, "dp", bucket_bytes=1)["w"]

        got = _shard_map(
            region, mesh=mesh, in_specs=(P("dp", None),),
            out_specs=P("dp", None), axis_names={"dp"},
        )(x)
        # psum over dp of (local x * 2): each dp half sees the other's rows
        expect = np.concatenate([np.asarray(x[4:]), np.asarray(x[:4])]) * 2.0
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(x) * 2.0 + expect, rtol=1e-6
        )

    def test_bucket_bytes_from_report_sizing_and_clamps(self):
        # the committed fixture shape drives the knob
        sec = {"compute_ms": 2.8, "top_collective": {"achieved_gbps": 0.85}}
        assert bucket_bytes_from_report(sec, n_layers=4) == 1 << 20  # clamp lo
        big = {"compute_ms": 100.0, "top_collective": {"achieved_gbps": 600.0}}
        assert bucket_bytes_from_report(big, n_layers=1) == 128 << 20
        mid = {"compute_ms": 30.0, "top_collective": {"achieved_gbps": 2.0}}
        # 2e9 B/s * (2/3 * 30e-3 / 2) s = 2e7 B (to fp rounding of 2/3)
        assert abs(bucket_bytes_from_report(mid, n_layers=2) - 2e7) <= 1
        # no measurement -> the default budget
        assert bucket_bytes_from_report(None, n_layers=4) == 8 << 20
        assert bucket_bytes_from_report({}, n_layers=4) == 8 << 20
        assert bucket_bytes_from_report(sec, n_layers=0) == 8 << 20


class TestModelAndTrainer:
    """End to end: llama with overlap_impl on, and the bucketed trainer."""

    @pytest.mark.parametrize("impl", IMPLS)
    def test_llama_loss_matches_plain(self, impl):
        from tony_tpu.models.llama import LlamaConfig, init_params, loss_fn

        mesh = _fsdp_mesh()
        set_default_mesh(mesh)
        cfg = LlamaConfig.tiny()
        params = init_params(jax.random.key(0), cfg)
        toks = jax.random.randint(
            jax.random.key(1), (8, 33), 0, cfg.vocab_size
        )
        base = float(loss_fn(params, toks, cfg))
        ov = float(loss_fn(
            params, toks, dataclasses.replace(cfg, overlap_impl=impl)
        ))
        # f32 ring accumulation reorders sums: equal to ~1e-6, not bitwise
        assert abs(base - ov) < 2e-5

    @pytest.mark.slow  # ~20s: three full dense fits; the psum-of-tuple
    # bitwise contract stays tier-1 at unit level
    # (TestBucketing.test_bucketed_psum_bitwise_equals_whole_tree_psum)
    # and at trainer level on the MoE model
    # (test_moe_overlap.TestTrainerComposition) — round 20 offsets
    def test_bucketed_trainer_loss_trajectory_bitwise_identical(self):
        """Bucketing the dp grad reduce is a schedule change only: within
        the manual decomposition, one big bucket and many small buckets
        produce BITWISE-identical loss trajectories (a psum of a tuple IS
        the tuple of psums). Against the GSPMD trainer the reduction
        ORDER differs (global mean vs psum-of-local-means), so that
        comparison is allclose-tight, not bitwise — the last-ulp drift
        shows up a few optimizer steps in.
        """
        from tony_tpu.models.llama import LlamaConfig
        from tony_tpu.train.trainer import (
            default_optimizer, make_train_state, make_train_step,
        )

        cfg = LlamaConfig.tiny()
        mesh = build_mesh(MeshShape(dp=2, fsdp=2, tp=2))
        opt = default_optimizer(warmup_steps=1, decay_steps=10)
        toks = jax.random.randint(
            jax.random.key(7), (8, 33), 0, cfg.vocab_size
        )

        def run(bucket_bytes, steps=3):
            state = make_train_state(jax.random.key(0), cfg, mesh, opt)
            step = make_train_step(
                cfg, mesh, opt, grad_bucket_bytes=bucket_bytes
            )
            losses = []
            for _ in range(steps):
                state, m = step(state, toks[:, :-1], toks[:, 1:])
                losses.append(float(m["loss"]))
            return losses

        gspmd = run(None)          # partitioner-inserted single all-reduce
        one = run(1 << 30)         # manual region, one big bucket
        many = run(64 << 10)       # manual region, many small buckets
        assert one == many         # bucket count never changes the values
        np.testing.assert_allclose(gspmd, one, rtol=1e-5)
        assert all(np.isfinite(v) for v in gspmd)

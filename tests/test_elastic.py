"""Elastic training: topology, shadowing, data contract, membership
protocol, invariants, lease events, checkpoint crash-safety, the
in-process elastic fit, and the tier-1 e2e (client -> AM -> chief +
member gang, chaos kill_container mid-step -> shrink -> grow-back).

docs/ELASTIC.md is the narrative these tests pin.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tony_tpu.am.events import EventType
from tony_tpu.chaos.invariants import check_invariants
from tony_tpu.elastic import (
    ElasticBatchStream,
    ElasticController,
    ElasticJournal,
    ElasticSettings,
    ElasticTopology,
    GenerationRecord,
    ShadowStore,
    read_generation,
    read_history,
    read_journal,
    reshard_state,
    write_generation,
)
from tony_tpu.elastic.protocol import journal_path
from tony_tpu.train.data import DataConfig


# --- topology -----------------------------------------------------------------


class TestTopology:
    def test_mesh_tracks_membership(self):
        import jax

        topo = ElasticTopology(2)
        full = topo.mesh_for((0, 1))
        assert dict(full.shape)["dp"] == 2
        assert full.size == len(jax.devices())
        shrunk = topo.mesh_for((1,))
        assert dict(shrunk.shape)["dp"] == 1
        assert shrunk.size == len(jax.devices()) // 2
        # member 1's group is preserved verbatim (relayouts move whole
        # member groups; the dp coordinate IS the member rank)
        assert set(shrunk.devices.ravel()) == set(topo.member_devices(1))

    def test_per_member_shape_must_keep_dp_one(self):
        from tony_tpu.parallel.mesh import MeshShape

        with pytest.raises(ValueError, match="member axis"):
            ElasticTopology(2, per_member=MeshShape(dp=2, fsdp=2))

    def test_indivisible_devices_rejected(self):
        with pytest.raises(ValueError, match="divisible"):
            ElasticTopology(3)  # 8 devices / 3 members


# --- checkpoint shadow --------------------------------------------------------


class TestShadow:
    def test_fence_capture_is_exact_and_resharding_roundtrips(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        topo = ElasticTopology(2)
        full, shrunk = topo.mesh_for((0, 1)), topo.mesh_for((0,))
        x = jax.device_put(
            np.arange(64, dtype=np.float32).reshape(8, 8),
            NamedSharding(full, P(("dp", "fsdp"))),
        )
        store = ShadowStore(interval_steps=2)
        try:
            host = store.capture_sync(7, {"w": x})
            np.testing.assert_array_equal(host["w"], np.arange(64).reshape(8, 8))
            assert store.snapshot()[0] == 7
            # donation: the SAME host replica lands on the shrunk mesh
            moved = reshard_state(
                host, {"w": NamedSharding(shrunk, P(("dp", "fsdp")))}
            )
            np.testing.assert_array_equal(
                np.asarray(moved["w"]), host["w"]
            )
            assert moved["w"].sharding.mesh.size == shrunk.size
        finally:
            store.close()

    def test_async_stride_shadow(self):
        import jax

        store = ShadowStore(interval_steps=4)
        try:
            assert not store.maybe_update(3, {})        # off-stride
            assert store.maybe_update(4, {"v": jax.numpy.ones((4,))})
            store.drain()
            deadline = time.monotonic() + 5
            while store.snapshot() is None and time.monotonic() < deadline:
                time.sleep(0.01)
            step, host = store.snapshot()
            assert step == 4
            np.testing.assert_array_equal(host["v"], np.ones((4,)))
        finally:
            store.close()


# --- membership-aware data stream --------------------------------------------


class TestElasticStream:
    CFG = DataConfig(global_batch=4, seq_len=8, vocab_size=64, prefetch=0)

    def test_survivor_positions_never_move(self):
        """The no-repeat/no-skip contract by construction: after shrink +
        grow, member 0's consumed rows are exactly what an uninterrupted
        stream would have produced, and the dead member's skipped range
        is the pure boundary interval."""
        s = ElasticBatchStream(self.CFG, 2, (0, 1))
        ref = ElasticBatchStream(self.CFG, 2, (0, 1))
        got = [np.asarray(next(s)[0]) for _ in range(3)]        # steps 0..2
        s.reshard((0,), None)                                    # kill member 1
        got += [np.asarray(next(s)[0]) for _ in range(2)]        # steps 3..4
        delta = s.reshard((0, 1), None)                          # grow back
        got += [np.asarray(next(s)[0]) for _ in range(2)]        # steps 5..6
        assert s.skipped == {1: [[3, 5]]}
        assert delta == {1: (3, 5)}
        for step in range(7):
            want = np.asarray(next(ref)[0])
            if 3 <= step < 5:
                # shrunk: only member 0's rows, identical values
                np.testing.assert_array_equal(got[step], want[:2])
            else:
                np.testing.assert_array_equal(got[step], want)

    def test_token_files_not_supported_yet(self):
        with pytest.raises(NotImplementedError):
            ElasticBatchStream(
                DataConfig(global_batch=4, seq_len=8, path="/tmp/x.bin"),
                2, (0, 1),
            )


# --- protocol: generations + controller + journal ----------------------------


class TestProtocol:
    def test_broadcast_roundtrip_and_history(self, tmp_path):
        app = str(tmp_path)
        write_generation(app, GenerationRecord(0, (0, 1), "start"))
        write_generation(
            app, GenerationRecord(1, (0,), "shrink", dead=(1,), reason="kill")
        )
        latest = read_generation(app)
        assert latest.generation == 1 and latest.members == (0,)
        hist = read_history(app)
        assert [r.generation for r in hist] == [0, 1]
        assert hist[1].boundary == "shrink"

    def test_controller_fences_on_new_generation(self, tmp_path):
        app = str(tmp_path)
        ctl = ElasticController(
            ElasticSettings(members=2, app_dir=app), watch=False
        )
        try:
            write_generation(app, GenerationRecord(0, (0, 1), "start"))
            ctl.check()
            assert ctl.pending() is None and ctl.generation == 0
            write_generation(
                app, GenerationRecord(1, (0,), "shrink", dead=(1,))
            )
            ctl.check()
            rec = ctl.pending()
            assert rec is not None and rec.members == (0,)
            ctl.applied(rec)
            assert ctl.pending() is None
            assert ctl.members == (0,) and ctl.generation == 1
            # a stale re-read never re-arms the same generation
            ctl.check()
            assert ctl.pending() is None
        finally:
            ctl.close()

    def test_journal_roundtrip(self, tmp_path):
        path = str(tmp_path / "journal_m0.jsonl")
        j = ElasticJournal(path, member=0, members=2)
        j.step(0, 0, (0, 1))
        j.loss(0, 0, 1.25, 42)
        j.reshard(
            generation=1, at_step=1, boundary="shrink", members=(0,),
            dead=(1,), skipped={1: (1, -1)}, reshard_s=0.5,
        )
        j.close()
        recs = read_journal(path)
        kinds = [r["type"] for r in recs]
        assert kinds == ["meta", "step", "loss", "reshard"]
        assert recs[2]["fp"] == 42
        assert recs[3]["skipped"] == {"1": [1, -1]}


# --- invariants: firing + non-firing fixtures --------------------------------


def _mk_terminal_app(tmp_path, name="app-elastic"):
    """A minimal terminal app dir the invariant checker accepts."""
    from tony_tpu.am.events import EventWriter

    app = tmp_path / name
    (app / "elastic").mkdir(parents=True)
    with open(app / "status.json", "w") as f:
        json.dump({"state": "SUCCEEDED", "exit_code": 0, "app_id": name}, f)
    w = EventWriter(name, str(app / "events"))
    w.emit(EventType.APPLICATION_FINISHED, state="SUCCEEDED")
    w.close()
    return app


def _write_journal(app, records, member=0):
    path = journal_path(str(app), member)
    with open(path, "w") as f:
        f.write(json.dumps({
            "type": "meta", "member": member, "members": 2,
            "tolerance": {"window": 4, "z": 4.0, "frac": 0.25},
        }) + "\n")
        for r in records:
            f.write(json.dumps(r) + "\n")


def _clean_records():
    """A well-formed shrink-then-grow journal: contiguous steps, declared
    skips, smooth losses, distinct fingerprints."""
    recs = []
    for s in range(3):
        recs.append({"type": "step", "step": s, "gen": 0, "members": [0, 1]})
        recs.append({"type": "loss", "step": s, "gen": 0,
                     "loss": 5.0 - 0.01 * s, "fp": 100 + s})
    recs.append({"type": "reshard", "gen": 1, "at_step": 3,
                 "boundary": "shrink", "members": [0], "dead": [1],
                 "added": [], "skipped": {"1": [3, -1]}, "reshard_s": 0.4,
                 "lost_steps": 0})
    for s in range(3, 6):
        recs.append({"type": "step", "step": s, "gen": 1, "members": [0]})
        recs.append({"type": "loss", "step": s, "gen": 1,
                     "loss": 5.0 - 0.01 * s, "fp": 100 + s})
    recs.append({"type": "reshard", "gen": 2, "at_step": 6,
                 "boundary": "grow", "members": [0, 1], "dead": [],
                 "added": [1], "skipped": {"1": [3, 6]}, "reshard_s": 0.4,
                 "lost_steps": 0})
    for s in range(6, 9):
        recs.append({"type": "step", "step": s, "gen": 2, "members": [0, 1]})
        recs.append({"type": "loss", "step": s, "gen": 2,
                     "loss": 5.0 - 0.01 * s, "fp": 100 + s})
    return recs


class TestElasticInvariants:
    def _violations(self, tmp_path, records, invariant):
        app = _mk_terminal_app(tmp_path)
        _write_journal(app, records)
        report = check_invariants(str(app))
        return [v for v in report.violations if v.invariant == invariant]

    def test_clean_journal_reports_clean(self, tmp_path):
        app = _mk_terminal_app(tmp_path)
        _write_journal(app, _clean_records())
        report = check_invariants(str(app))
        assert report.ok, report.to_json()

    def test_repeated_step_fires(self, tmp_path):
        recs = _clean_records()
        dup = next(r for r in recs if r["type"] == "step" and r["step"] == 2)
        recs.insert(recs.index(dup) + 1, dict(dup))
        v = self._violations(tmp_path, recs, "elastic-no-data-loss")
        assert v and "repeated" in v[0].detail

    def test_skipped_step_fires(self, tmp_path):
        recs = [r for r in _clean_records()
                if not (r["type"] in ("step", "loss") and r["step"] == 4)]
        v = self._violations(tmp_path, recs, "elastic-no-data-loss")
        assert v and "skipped" in v[0].detail

    def test_membership_change_without_boundary_fires(self, tmp_path):
        recs = [r for r in _clean_records() if r["type"] != "reshard"]
        v = self._violations(tmp_path, recs, "elastic-no-data-loss")
        assert v and "without a declared reshard" in v[0].detail

    def test_undeclared_gap_fires(self, tmp_path):
        recs = []
        for r in _clean_records():
            if r["type"] == "reshard":
                r = dict(r)
                r["skipped"] = {}  # the gap exists but is not declared
            recs.append(r)
        v = self._violations(tmp_path, recs, "elastic-no-data-loss")
        assert v and "silently lost" in v[0].detail

    def test_repeated_fingerprint_fires(self, tmp_path):
        recs = []
        for r in _clean_records():
            if r["type"] == "loss" and r["step"] == 4:
                r = dict(r, fp=103)  # same fp as step 3
            recs.append(r)
        v = self._violations(tmp_path, recs, "elastic-no-data-loss")
        assert v and "fingerprint repeated" in v[0].detail

    def test_loss_discontinuity_fires(self, tmp_path):
        recs = []
        for r in _clean_records():
            if r["type"] == "loss" and r["step"] >= 6:
                r = dict(r, loss=9.5)  # jump at the grow boundary
            recs.append(r)
        v = self._violations(tmp_path, recs, "elastic-loss-continuity")
        assert v and "discontinuity" in v[0].detail

    def test_nonfinite_loss_after_boundary_fires(self, tmp_path):
        recs = []
        for r in _clean_records():
            if r["type"] == "loss" and r["step"] == 3:
                r = dict(r, loss=float("nan"))
            recs.append(r)
        v = self._violations(tmp_path, recs, "elastic-loss-continuity")
        assert v and "non-finite" in v[0].detail


# --- lease store: training-gang grow/shrink + event audit ---------------------


class TestLeaseElastic:
    def test_shrink_matches_the_real_container_ask(self, tmp_path):
        from tony_tpu.cluster.backend import Resource
        from tony_tpu.cluster.lease import GangAsk, LeaseStore

        store = LeaseStore(str(tmp_path / "rm"))
        store.register_hosts({"h1": Resource(8192, 16, 16)})
        chief = GangAsk(Resource(2048, 4, 0))
        worker = GangAsk(Resource(1024, 2, 4))
        store.reserve_gang(
            "train-app", [chief, worker, worker], gang_id="containers",
            timeout_s=0,
        )
        # ask-matched shrink frees a WORKER lease even though the chief's
        # ask is not last... and an unmatched ask frees nothing
        assert store.shrink_gang("train-app", "containers", ask=worker) == "h1"
        assert store.shrink_gang(
            "train-app", "containers", ask=GangAsk(Resource(9, 9, 9))
        ) is None
        leases = store.summary()["apps"]["train-app"]["leases"]
        assert len(leases) == 2
        # grow-back re-leases the same real ask
        assert store.grow_gang("train-app", "containers", worker) == "h1"
        assert len(store.summary()["apps"]["train-app"]["leases"]) == 3

    def test_shrink_pins_the_dead_members_host(self, tmp_path):
        """In a homogeneous gang the ask VALUE cannot name the dead
        member; the host pin must pick its lease, never a survivor's."""
        from tony_tpu.cluster.backend import Resource
        from tony_tpu.cluster.lease import GangAsk, LeaseStore

        store = LeaseStore(str(tmp_path / "rm"))
        one = Resource(1024, 2, 4)
        store.register_hosts({"h1": one, "h2": one})
        ask = GangAsk(one)
        store.reserve_gang("train-app", [ask, ask], gang_id="containers",
                           timeout_s=0)  # first-fit: one lease per host
        assert store.shrink_gang(
            "train-app", "containers", ask=ask, host="h1"
        ) == "h1"
        leases = store.summary()["apps"]["train-app"]["leases"]
        assert [lease["host"] for lease in leases] == ["h2"]
        # an unknown host frees nothing
        assert store.shrink_gang(
            "train-app", "containers", ask=ask, host="h9"
        ) is None

    def test_foreign_owner_refused_for_training_gangs(self, tmp_path):
        from tony_tpu.cluster.backend import Resource
        from tony_tpu.cluster.lease import GangAsk, LeaseStore

        store = LeaseStore(str(tmp_path / "rm"), lease_ttl_s=600)
        store.register_hosts({"h1": Resource(8192, 16, 16)})
        ask = GangAsk(Resource(1024, 2, 4))
        store.reserve_gang("train-app", [ask, ask], gang_id="containers",
                           timeout_s=0)
        foreign = LeaseStore(str(tmp_path / "rm"), owner_host="elsewhere",
                             lease_ttl_s=600)
        assert foreign.grow_gang("train-app", "containers", ask) is None
        assert foreign.shrink_gang("train-app", "containers", ask=ask) is None
        # the incumbent still holds both leases
        assert len(store.summary()["apps"]["train-app"]["leases"]) == 2

    def test_events_audited_by_invariant_checker(self, tmp_path):
        from tony_tpu.cluster.backend import Resource
        from tony_tpu.cluster.lease import GangAsk, LeaseStore, STATE_FILE

        rm = str(tmp_path / "rm")
        store = LeaseStore(rm)
        store.register_hosts({"h1": Resource(8192, 16, 16)})
        ask = GangAsk(Resource(1024, 2, 4))
        store.reserve_gang("train-app", [ask, ask], gang_id="containers",
                           timeout_s=0)
        assert store.shrink_gang("train-app", "containers", ask=ask) == "h1"
        assert store.grow_gang("train-app", "containers", ask) == "h1"
        with open(os.path.join(rm, STATE_FILE)) as f:
            state = json.load(f)
        assert [e["op"] for e in state["events"]] == ["shrink", "grow"]
        store.release_app("train-app")
        app = _mk_terminal_app(tmp_path)
        report = check_invariants(str(app), rm_root=rm)
        assert report.ok, report.to_json()
        # a corrupted event log (unregistered host) is a violation
        state["events"].append(
            {"ts": time.time(), "op": "grow", "app_id": "x",
             "gang_id": "g", "host": "ghost", "owner": "a:1"}
        )
        with open(os.path.join(rm, STATE_FILE), "w") as f:
            json.dump(state, f)
        report = check_invariants(str(app), rm_root=rm)
        bad = [v for v in report.violations
               if v.invariant == "lease-events-audit"]
        assert bad and "unregistered host" in bad[0].detail


# --- runtime validation -------------------------------------------------------


def test_elastic_runtime_rejects_member_type_sorting_before_chief():
    """Member ranks come from the sorted-type rank table and the AM
    treats rank 0 as the trainer — a member type sorting before 'chief'
    would silently swap the roles, so validate refuses it."""
    from tony_tpu.config.config import TonyConfig
    from tony_tpu.runtime import ElasticRuntime

    cfg = TonyConfig.load(overrides={
        "application.framework": "elastic",
        "job.chief.instances": 1,
        "job.chief.command": "python train.py",
        "job.agents.instances": 1,
        "job.agents.command": "python -m tony_tpu.elastic.member",
    })
    with pytest.raises(ValueError, match="sorts before"):
        ElasticRuntime().validate(cfg)


# --- checkpoint crash-safety --------------------------------------------------


_KILL_MID_SAVE = """
import os, sys
import numpy as np
import jax
from tony_tpu.train.checkpoint import CheckpointManager

d = sys.argv[1]
m = CheckpointManager(d, keep=3)
small = {"w": jax.numpy.arange(8, dtype=jax.numpy.float32)}
m.save(1, small, force=True)
m.wait()  # step 1 is durable
# a LARGE state so the async save is provably in flight when we die
big = {"w": jax.numpy.ones((24, 1024, 1024), jax.numpy.float32)}
m.save(2, big, force=True)
print("SAVING", flush=True)
os.kill(os.getpid(), 9)  # SIGKILL mid-save: the elastic preemption shape
"""


def test_checkpoint_kill_mid_save_never_corrupts_latest(tmp_path):
    """SIGKILL during an async save must never corrupt the latest
    checkpoint: in-progress saves live in a tmp dir until an atomic
    rename, the reopened manager reaps the leftovers, and restore()
    comes back from the last durable step bit-exact — never from a torn
    step 2 (an unreadable newest step falls back instead of wedging)."""
    import jax

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_MID_SAVE, str(ckpt)],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]

    from tony_tpu.train.checkpoint import CheckpointManager

    m = CheckpointManager(str(ckpt), keep=3)
    # no interrupted-save tmp dirs survive the reopen
    assert not any(".orbax-checkpoint-tmp" in n for n in os.listdir(ckpt))
    template = {"w": jax.numpy.zeros((8,), jax.numpy.float32)}
    state, step = m.restore(template)
    assert step == 1, "the interrupted step-2 save must not be visible"
    np.testing.assert_array_equal(np.asarray(state["w"]), np.arange(8))
    m.close()


# --- in-process elastic fit ---------------------------------------------------


@pytest.fixture(scope="module")
def elastic_fit_runs(tmp_path_factory):
    """ONE shrink+grow elastic fit and one no-fault twin (same seed, same
    substreams) — shared by the assertions below; compiles are the cost
    (the class is slow-marked: the tier-1 e2e covers the same contract
    through the real AM path, and tier-1 runs close to its timeout)."""
    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.train import FitConfig, fit

    base = dict(
        model=LlamaConfig.tiny(),
        data=DataConfig(global_batch=8, seq_len=32, vocab_size=128),
        steps=12, log_every=1, warmup_steps=2, elastic_members=2,
    )
    fault_dir = str(tmp_path_factory.mktemp("elastic-fault"))
    fault = fit(FitConfig(
        **base, elastic_plan={4: (0,), 8: (0, 1)}, elastic_dir=fault_dir,
    ))
    ref = fit(FitConfig(**base))
    return fault, ref, fault_dir


@pytest.mark.slow
class TestElasticFit:
    def test_shrink_grow_summary(self, elastic_fit_runs):
        fault, _, _ = elastic_fit_runs
        e = fault["elastic"]
        assert e["reshards"] == 2
        assert e["generation"] == 2
        assert e["members"] == [0, 1]
        assert e["reshard_s"] > 0

    def test_journal_passes_elastic_invariants(self, elastic_fit_runs,
                                               tmp_path):
        _, _, fault_dir = elastic_fit_runs
        app = _mk_terminal_app(tmp_path)
        # adopt the real run's journal into a terminal app dir
        src = journal_path(fault_dir, 0)
        dst = journal_path(str(app), 0)
        with open(src) as f, open(dst, "w") as g:
            g.write(f.read())
        report = check_invariants(str(app))
        assert report.ok, report.to_json()
        recs = read_journal(dst)
        reshards = [r for r in recs if r["type"] == "reshard"]
        assert [r["boundary"] for r in reshards] == ["shrink", "grow"]
        assert reshards[0]["skipped"] == {"1": [4, -1]}
        assert reshards[1]["skipped"] == {"1": [4, 8]}
        assert all(r["lost_steps"] == 0 for r in reshards)

    def test_loss_continuity_vs_no_fault_run(self, elastic_fit_runs):
        """Survivors continued the SAME run: the faulted trajectory ends
        in the same neighbourhood as the uninterrupted twin (shared
        substreams make the pre-fault halves identical)."""
        fault, ref, _ = elastic_fit_runs
        assert np.isfinite(fault["final_loss"])
        assert abs(fault["final_loss"] - ref["final_loss"]) < 0.5


# --- end-to-end: preemption survived without a cold restart -------------------


@pytest.mark.slow  # ~34s: full client->AM->2-member process stack; the
# shrink/grow trainer contract stays tier-1 via TestProtocol /
# TestElasticInvariants / TestLeaseElastic, and the fit-level shrink-grow
# trajectory already lives in the slow tier (TestElasticFit) — round 20
# offsets for the moe-overlap suite
def test_elastic_job_end_to_end(tmp_path):
    """Acceptance e2e (ISSUE 14): a REAL client -> AM -> 2-member
    elastic training job. Chaos kill_container takes the member agent's
    host down only once training is provably mid-step (on_file armed by
    the trainer's own metrics hook); the AM declares a shrink generation,
    the trainer reshards dp 2 -> 1 and keeps stepping, grow-back
    relaunches the member and dp expands again — all with zero lost
    steps, a clean invariant report (loss continuity, no data
    repeated/skipped, health sentinel untripped), and the merged `tony
    trace` showing the generation-change spans in the restart_s bucket.
    """
    from tony_tpu.cli.client import TonyClient
    from tony_tpu.cli.main import main as cli_main
    from tony_tpu.config.config import TonyConfig
    from tony_tpu.obs.trace_tool import goodput, load_journals

    src = tmp_path / "src"
    src.mkdir()
    marker = tmp_path / "training-underway"
    (src / "train.py").write_text(
        "import logging, os, time\n"
        "logging.basicConfig(level=logging.INFO)\n"
        "from tony_tpu.train import fit, FitConfig\n"
        "from tony_tpu.train.data import DataConfig\n"
        "from tony_tpu.models.llama import LlamaConfig\n"
        "def pace(m):\n"
        "    # pacing keeps the run alive across the shrink->grow window\n"
        "    # and arms the chaos kill only once training is mid-step\n"
        "    if m['step'] >= 3:\n"
        f"        open({str(marker)!r}, 'a').close()\n"
        "    time.sleep(0.1)\n"
        "out = fit(FitConfig(\n"
        "    model=LlamaConfig.tiny(),\n"
        "    data=DataConfig(global_batch=8, seq_len=32, vocab_size=128),\n"
        "    steps=120, log_every=1, warmup_steps=2,\n"
        "    on_metrics=pace))\n"
        "e = out.get('elastic') or {}\n"
        "print('ELASTIC SUMMARY', e)\n"
        "assert e.get('reshards', 0) >= 2, e\n"
        "assert e.get('members') == [0, 1], e\n"
    )
    cfg = TonyConfig.load(overrides={
        "task.heartbeat_interval_ms": 200,
        "task.max_missed_heartbeats": 10,
        "application.timeout_s": 240,
        "application.stage_dir": str(tmp_path),
        "application.name": "elastic-e2e",
        "application.framework": "elastic",
        "elastic.grow_retry_s": 0.5,
        "elastic.poll_interval_s": 0.1,
        "elastic.shadow_interval_steps": 4,
        "job.chief.instances": 1,
        "job.chief.command": f"{sys.executable} train.py",
        "job.chief.env": ["JAX_PLATFORMS=cpu"],
        "job.worker.instances": 1,
        "job.worker.command": f"{sys.executable} -m tony_tpu.elastic.member",
        # the preemption: SIGKILL the member agent's container at its
        # next heartbeat after training is provably underway
        "chaos.enabled": True,
        "chaos.faults": json.dumps([{
            "type": "kill_container", "task": "worker:0",
            "from_count": 1, "on_file": str(marker),
        }]),
        "trace.sample_steps": 1,
    })
    client = TonyClient(cfg, src_dir=str(src))
    code = client.run(quiet=True)
    app_dir = client.app_dir
    if code != 0:
        logs_dir = os.path.join(app_dir, "logs")
        for n in sorted(os.listdir(logs_dir)):
            print(f"===== {n}", open(os.path.join(logs_dir, n),
                                     errors="replace").read()[-3000:])
    assert code == 0

    # membership history: start -> shrink (member 1 dead) -> grow (back)
    hist = read_history(app_dir)
    boundaries = [r.boundary for r in hist]
    assert boundaries[:1] == ["start"]
    assert "shrink" in boundaries and "grow" in boundaries
    shrink = next(r for r in hist if r.boundary == "shrink")
    grow = next(r for r in hist if r.boundary == "grow")
    assert shrink.members == (0,) and shrink.dead == (1,)
    assert grow.members == (0, 1) and grow.added == (1,)
    gens = [r.generation for r in hist]
    assert gens == sorted(gens) and len(set(gens)) == len(gens)

    # journal evidence: dp shrank and grew with zero lost steps; the
    # health monitors' batch fingerprints rode the loss records
    recs = read_journal(journal_path(app_dir, 0))
    reshards = [r for r in recs if r["type"] == "reshard"]
    assert [r["boundary"] for r in reshards] == ["shrink", "grow"]
    assert all(r["lost_steps"] == 0 for r in reshards)
    assert any("fp" in r for r in recs if r["type"] == "loss")

    # the post-mortem is clean: loss continuity, no data loss, health
    # sentinel untripped, events/generations consistent
    report = check_invariants(app_dir)
    assert report.ok, report.to_json()

    # history events carry the boundaries
    ev_types = {e.get("type") for e in _events_of(app_dir)}
    assert EventType.ELASTIC_SHRINK in ev_types
    assert EventType.ELASTIC_GROW in ev_types

    # merged trace: the generation changes are restart_s, read straight
    # off the elastic.reshard spans; the chaos kill instant landed in the
    # member executor's journal before the SIGKILL
    procs = load_journals(os.path.join(app_dir, "trace"))
    g = goodput(app_dir, procs)
    assert g["generation_changes"] == 2
    assert g["restart_s"] > 0
    chief = [p for p in procs if p["proc"].startswith("chief_0_user")]
    spans = [s["name"] for p in chief for s in p["spans"]]
    assert spans.count("elastic.reshard") == 2
    kills = [
        i for p in procs for i in p["instants"]
        if i["name"] == "chaos.kill_container"
    ]
    assert len(kills) == 1

    # the audit CLI reads the same story
    assert cli_main(["elastic", app_dir]) == 0


def _events_of(app_dir):
    from tony_tpu.am.events import read_history as read_jhist

    ev_dir = os.path.join(app_dir, "events")
    out = []
    for n in sorted(os.listdir(ev_dir)):
        if n.endswith(".jsonl"):
            out.extend(read_jhist(os.path.join(ev_dir, n)))
    return out

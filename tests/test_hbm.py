"""Tests for the HBM observatory (obs/hbm.py), the compile ledger
(obs/compiles.py), the counter-track trace merge, capacity derivation
(serve/capacity.py), and the OOM forensics flow."""

import gzip
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.obs import hbm, trace
from tony_tpu.obs.compiles import (
    aot_analysis, get_ledger, read_app_ledgers, snapshot_to_app_dir,
    summarize,
)


class FakeStats:
    """Deterministic per-device stats provider: tests script the live /
    cumulative-peak sequence the real allocator would produce."""

    def __init__(self, *readings):
        self.readings = list(readings)
        self.i = 0

    def push(self, *readings):
        self.readings.extend(readings)

    def __call__(self):
        r = self.readings[min(self.i, len(self.readings) - 1)]
        self.i += 1
        return [
            ("dev0", {"bytes_in_use": live, "peak_bytes_in_use": peak,
                      "bytes_limit": 1000})
            for live, peak in [r]
        ]


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends disarmed (fit()/engine runs elsewhere in
    the suite may have armed the process-global watch)."""
    hbm.uninstall()
    yield
    hbm.uninstall()


class TestPhaseWatermarks:
    def test_phase_that_advances_cumulative_peak_owns_it(self):
        # enter at live=100 (cum peak 150); inside, the allocator peaks at
        # 400; exit at live=120 — the phase owns the 400 mark exactly
        stats = FakeStats((100, 150), (120, 400))
        watch = hbm.HbmWatch(stats_fn=stats)
        with watch.phase("alloc") as ph:
            pass
        rec = ph.result["devices"]["dev0"]
        assert rec["peak_bytes"] == 400 and rec["peak_exact"] is True
        assert rec["delta_peak_bytes"] == 300  # above the entering live
        assert rec["live_start_bytes"] == 100
        assert rec["live_end_bytes"] == 120
        assert rec["live_delta_bytes"] == 20
        assert rec["limit_bytes"] == 1000

    def test_phase_under_an_earlier_peak_never_inherits_it(self):
        # THE caveat this class kills: the cumulative counter still says
        # 400 (an earlier phase's mark), but this phase only touched
        # 120->180 live — it must report a live-bound peak, not 400
        stats = FakeStats((120, 400), (180, 400))
        watch = hbm.HbmWatch(stats_fn=stats)
        with watch.phase("quiet") as ph:
            pass
        rec = ph.result["devices"]["dev0"]
        assert rec["peak_exact"] is False
        assert rec["peak_bytes"] == 180  # max(live_start, live_end)
        assert rec["delta_peak_bytes"] == 60

    def test_consecutive_phases_are_independently_scoped(self):
        stats = FakeStats((0, 0), (0, 500), (10, 500), (20, 500))
        watch = hbm.HbmWatch(stats_fn=stats)
        with watch.phase("big") as big:
            pass
        with watch.phase("small") as small:
            pass
        assert big.result["devices"]["dev0"]["peak_bytes"] == 500
        assert big.result["devices"]["dev0"]["peak_exact"] is True
        # the second phase does NOT report the first one's 500
        assert small.result["devices"]["dev0"]["peak_bytes"] == 20
        assert small.result["devices"]["dev0"]["peak_exact"] is False
        assert [p["name"] for p in watch.phases] == ["big", "small"]

    def test_bench_keys_flatten_device0(self):
        watch = hbm.HbmWatch(stats_fn=FakeStats((0, 0), (2**30, 2 * 2**30)))
        with watch.phase("p") as ph:
            pass
        keys = ph.bench_keys()
        assert keys["phase_peak_hbm_gb"] == 2.0
        assert keys["live_end_gb"] == 1.0
        assert keys["peak_exact"] is True
        # no stats -> no keys (platforms without memory_stats)
        watch2 = hbm.HbmWatch(stats_fn=lambda: [])
        with watch2.phase("p") as ph2:
            pass
        assert ph2.bench_keys() == {}

    def test_watermark_across_real_device_allocations(self):
        """On platforms exposing memory_stats (real TPU/GPU), an explicit
        allocation inside a phase must show up in its delta; elsewhere the
        default stats source yields nothing and the phase stays empty."""
        watch = hbm.HbmWatch()
        nbytes = 4 * 2**20
        with watch.phase("alloc") as ph:
            arr = jnp.ones((nbytes // 4,), jnp.float32)
            arr.block_until_ready()
        if not ph.result["devices"]:
            pytest.skip("platform exposes no memory_stats")
        rec = next(iter(ph.result["devices"].values()))
        assert rec["delta_peak_bytes"] >= nbytes
        del arr


class TestSampling:
    def test_stride_and_history(self):
        stats = FakeStats((10, 10))
        watch = hbm.HbmWatch(stats_fn=stats, sample_every=4, history=8)
        got = [watch.sample() for _ in range(8)]
        assert sum(1 for g in got if g is not None) == 2  # every 4th
        assert len(watch.history) == 2
        assert watch.history[0]["dev0"]["live_bytes"] == 10

    def test_sample_updates_registry_gauges(self):
        from tony_tpu.obs.registry import Registry

        reg = Registry()
        watch = hbm.HbmWatch(
            stats_fn=FakeStats((7, 9)), registry=reg, sample_every=1
        )
        watch.sample()
        snap = {(e["name"], e["labels"].get("device")): e["value"]
                for e in reg.snapshot()}
        assert snap[("tony_hbm_live_bytes", "dev0")] == 7
        assert snap[("tony_hbm_peak_bytes", "dev0")] == 9

    def test_module_seam_disarmed_is_inert_and_armed_records(self):
        assert hbm.active_watch() is None
        hbm.sample()  # no-op, no error
        watch = hbm.install(hbm.HbmWatch(stats_fn=FakeStats((1, 1)),
                                         sample_every=1))
        hbm.sample()
        assert len(watch.history) == 1

    def test_install_from_env_gating(self, monkeypatch):
        monkeypatch.setenv(hbm.ENV_ENABLED, "0")
        assert hbm.install_from_env() is None
        monkeypatch.setenv(hbm.ENV_ENABLED, "1")
        monkeypatch.setenv(hbm.ENV_SAMPLE, "7")
        monkeypatch.setenv(hbm.ENV_HISTORY, "33")
        watch = hbm.install_from_env()
        assert watch is not None and watch.sample_every == 7
        assert watch.history.maxlen == 33
        # idempotent: a second arm keeps the installed watch
        assert hbm.install_from_env() is watch


class TestCounterTracks:
    def test_samples_land_as_counter_rows_in_merged_chrome_trace(self, tmp_path):
        """The acceptance path: armed tracer + armed watch -> ph:"C" rows
        in the journal -> a per-device memory counter track in the merged
        Chrome trace (valid JSON, numeric series)."""
        from tony_tpu.obs.trace_tool import load_journals, merge_chrome

        tracer = trace.Tracer(
            str(tmp_path / "trace" / "w.jsonl"), "w", "t",
            flush_interval_s=999.0,
        )
        trace.install(tracer)
        try:
            watch = hbm.install(hbm.HbmWatch(
                stats_fn=FakeStats((2**30, 2**30), (2 * 2**30, 3 * 2**30)),
                sample_every=1,
            ))
            watch.sample()
            watch.sample()
        finally:
            trace.uninstall()
        procs = load_journals(str(tmp_path / "trace"))
        assert len(procs[0]["counters"]) == 2
        merged = merge_chrome(str(tmp_path), procs)
        json.dumps(merged)  # serializable end-to-end
        counters = [e for e in merged["traceEvents"] if e["ph"] == "C"]
        assert len(counters) == 2
        c = counters[0]
        assert c["name"] == "hbm.dev0" and c["pid"] >= 1
        assert c["args"]["live_gb"] == 1.0 and c["args"]["peak_gb"] == 1.0
        assert counters[1]["args"]["peak_gb"] == 3.0


class TestCompileLedger:
    def test_exactly_one_entry_per_fresh_compile_zero_on_cache_hit(self):
        ledger = get_ledger()
        x = jnp.arange(11.0)  # pays its own compiles before the window
        f = jax.jit(lambda v: v * 2.5 + 1)
        n0 = ledger.backend_compiles
        f(x).block_until_ready()
        assert ledger.backend_compiles - n0 == 1  # exactly one fresh
        n1 = ledger.backend_compiles
        f(x).block_until_ready()
        assert ledger.backend_compiles - n1 == 0  # cache hit journals nothing

    def test_label_attributes_the_compile(self):
        ledger = get_ledger()
        x = jnp.arange(5.0)
        with ledger.label("my.entry"):
            jax.jit(lambda v: v - 0.5)(x)
        mine = [e for e in ledger.entries("backend") if e["fn"] == "my.entry"]
        assert len(mine) == 1 and mine[0]["dur_s"] >= 0
        # outside the scope, entries are anonymous again
        jax.jit(lambda v: v + 0.25)(x)
        assert ledger.entries()[-1]["fn"] == ""

    def test_record_aot_captures_memory_plan_and_flops(self):
        ledger = get_ledger()
        aval = jax.ShapeDtypeStruct((64, 64), jnp.float32)
        compiled = jax.jit(lambda a: a @ a).lower(aval).compile()
        entry = ledger.record_aot("mm64", compiled, 0.5)
        assert entry["kind"] == "aot" and entry["fn"] == "mm64"
        assert entry["argument_bytes"] == 64 * 64 * 4
        assert entry["output_bytes"] == 64 * 64 * 4
        assert entry["flops"] > 0
        assert ledger.entries("aot")[-1] == entry
        # the standalone analysis helper agrees
        assert aot_analysis(compiled)["argument_bytes"] == 64 * 64 * 4

    def test_sanitize_compile_count_is_the_ledger_counter(self):
        """One listener serves watchdog and journal: they cannot disagree."""
        from tony_tpu.analysis import sanitize

        ledger = get_ledger()
        assert sanitize.compile_count() == ledger.backend_compiles
        jax.jit(lambda v: v * 7)(jnp.arange(3.0))
        assert sanitize.compile_count() == ledger.backend_compiles

    def test_snapshot_roundtrip_and_cli_report(self, tmp_path, monkeypatch, capsys):
        from tony_tpu.cli.main import main as cli_main

        app_dir = tmp_path / "app-1"
        app_dir.mkdir()
        monkeypatch.setenv("TONY_APP_DIR", str(app_dir))
        monkeypatch.setenv("TONY_TRACE_PROC", "worker_0_user_a0")
        ledger = get_ledger()
        with ledger.label("roundtrip"):
            jax.jit(lambda v: v / 3)(jnp.arange(9.0))
        path = snapshot_to_app_dir()
        assert path.endswith(os.path.join("compiles", "worker_0_user_a0.json"))
        ledgers = read_app_ledgers(str(app_dir))
        assert "worker_0_user_a0" in ledgers
        summary = summarize(ledgers)
        proc = summary["processes"]["worker_0_user_a0"]
        assert proc["backend_compiles"] >= 1
        assert any(e["fn"] == "roundtrip" for e in proc["entries"])
        # the CLI prints the same report
        assert cli_main(["compiles", str(app_dir)]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["total_backend_compiles"] >= 1
        # and exits 1 when there is nothing to report
        empty = tmp_path / "app-2"
        empty.mkdir()
        assert cli_main(["compiles", str(empty)]) == 1


class TestCapacityDerivation:
    def test_budget_refuses_unmeasured_backends(self, monkeypatch):
        """No memory_analysis -> raise (bench falls back to the labelled
        formula), never a zero-margin budget wearing the measured label."""
        from tony_tpu.models.llama import LlamaConfig
        from tony_tpu.serve import capacity

        monkeypatch.setattr(capacity, "aot_analysis", lambda compiled: {})
        with pytest.raises(RuntimeError, match="no memory_analysis"):
            capacity.derive_slot_budget(
                LlamaConfig.tiny(), max_len=32, hbm_bytes=2**28, kv_block=16
            )

    def test_slot_budget_from_memory_analysis(self):
        """The measured budget replaces the 0.92 guess: components are
        positive and consistent, the repeat layout admits fewer slots by
        roughly the GQA factor, and more HBM means more slots."""
        from tony_tpu.models.llama import LlamaConfig
        from tony_tpu.serve.capacity import derive_slot_budget

        cfg = LlamaConfig.tiny()  # 4:2 GQA -> repeat factor 2
        b = derive_slot_budget(cfg, max_len=64, hbm_bytes=256 * 2**20,
                               kv_block=16)
        assert b["source"] == "memory_analysis"
        assert b["param_bytes"] > 0
        assert b["kv_bytes_per_slot_repeat"] == (
            b["kv_bytes_per_slot_native"] * cfg.n_heads // cfg.n_kv_heads
        )
        assert 0 < b["max_slots_repeat"] <= b["max_slots_native"]
        bigger = derive_slot_budget(cfg, max_len=64,
                                    hbm_bytes=512 * 2**20, kv_block=16)
        assert bigger["max_slots_native"] > b["max_slots_native"]

    def test_decode_step_analysis_measures_the_cache(self):
        """argument bytes grow with capacity by exactly the added KV bytes
        — the analysis is reading the real compiled plan, not a formula."""
        from tony_tpu.models.llama import LlamaConfig
        from tony_tpu.serve.capacity import decode_step_analysis

        cfg = LlamaConfig.tiny()
        small = decode_step_analysis(cfg, slots=2, capacity=16, kv_block=16)
        big = decode_step_analysis(cfg, slots=2, capacity=64, kv_block=16)
        # paged layout: the argument side is the physical-block pool plus
        # the per-slot block table — growth is exactly their sum
        assert big["argument_bytes"] - small["argument_bytes"] == (
            (big["cache_bytes"] - small["cache_bytes"])
            + (big["table_bytes"] - small["table_bytes"])
        )


class TestOomForensics:
    def _arm(self, tmp_path, monkeypatch):
        app_dir = tmp_path / "app-oom"
        app_dir.mkdir()
        monkeypatch.setenv("TONY_APP_DIR", str(app_dir))
        monkeypatch.setenv("TONY_TRACE_PROC", "worker_0_user_a0")
        watch = hbm.install(hbm.HbmWatch(
            stats_fn=FakeStats((100, 900)), sample_every=1
        ))
        with watch.phase("before"):
            pass
        watch.sample()
        return app_dir

    def test_resource_exhausted_dumps_and_reraises(self, tmp_path, monkeypatch):
        app_dir = self._arm(tmp_path, monkeypatch)
        err = RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 12345 bytes"
        )
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            with hbm.oom_guard("fit"):
                raise err
        files = hbm.forensics_files(str(app_dir))
        assert "worker_0_user_a0_fit.json" in files
        with open(app_dir / "oom" / "worker_0_user_a0_fit.json") as f:
            report = json.load(f)
        assert report["where"] == "fit"
        assert "RESOURCE_EXHAUSTED" in report["error"]
        # the watermark history and ledger rode along
        assert report["hbm"]["phases"][0]["name"] == "before"
        assert report["hbm"]["history"]
        assert "backend_compiles" in report.get("compiles", {})
        # the device memory profile is ONE gzip layer over the pprof proto
        # (device_memory_profile returns gzipped bytes; dump_oom must not
        # wrap them again or pprof cannot read the artifact)
        prof = app_dir / "oom" / "worker_0_user_a0_fit.memprof.pb.gz"
        if prof.exists():
            proto = gzip.decompress(prof.read_bytes())
            assert not proto.startswith(b"\x1f\x8b"), "double-gzipped profile"

    def test_non_oom_errors_pass_through_untouched(self, tmp_path, monkeypatch):
        app_dir = self._arm(tmp_path, monkeypatch)
        with pytest.raises(ValueError):
            with hbm.oom_guard("fit"):
                raise ValueError("not a memory problem")
        assert hbm.forensics_files(str(app_dir)) == []

    def test_engine_run_oom_lands_in_app_dir(self, tmp_path, monkeypatch):
        """The wired path: an engine whose decode step dies of (simulated)
        RESOURCE_EXHAUSTED writes forensics from inside run()."""
        from tony_tpu.models.llama import LlamaConfig, init_params
        from tony_tpu.serve import Engine, Request, ServeConfig

        app_dir = self._arm(tmp_path, monkeypatch)
        cfg = LlamaConfig.tiny()
        params = init_params(jax.random.key(0), cfg)
        eng = Engine(params, cfg, ServeConfig(slots=2, max_len=32, kv_block=8))

        def boom():
            raise RuntimeError("RESOURCE_EXHAUSTED: out of HBM")

        monkeypatch.setattr(eng, "_decode_once", boom)
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            eng.run([Request(prompt=np.arange(4), max_new_tokens=4)])
        assert any(
            name.endswith("engine.run.json")
            for name in hbm.forensics_files(str(app_dir))
        )

    def test_chaos_result_lists_forensics(self, tmp_path):
        from tony_tpu.chaos.invariants import InvariantReport
        from tony_tpu.chaos.runner import ChaosRunResult

        (tmp_path / "oom").mkdir()
        (tmp_path / "oom" / "worker_0_user_a0_fit.json").write_text("{}")
        r = ChaosRunResult(
            app_id="a", app_dir=str(tmp_path), exit_code=1, state="FAILED",
            report=InvariantReport(),
            oom_forensics=hbm.forensics_files(str(tmp_path)),
        )
        assert r.to_dict()["oom_forensics"] == ["worker_0_user_a0_fit.json"]


class TestShutdownSummaries:
    def test_fit_final_report_carries_ledger_lines(self, tmp_path, monkeypatch):
        """fit()'s final dict and ledger snapshot: compile count from the
        ledger, peak-HBM when the platform (here: a fake) reports stats."""
        from tony_tpu.models.llama import LlamaConfig
        from tony_tpu.parallel.mesh import MeshShape
        from tony_tpu.train import DataConfig, FitConfig, fit

        app_dir = tmp_path / "app-fit"
        app_dir.mkdir()
        monkeypatch.setenv("TONY_APP_DIR", str(app_dir))
        monkeypatch.setenv("TONY_TRACE_PROC", "worker_0_user_a0")
        hbm.install(hbm.HbmWatch(
            stats_fn=FakeStats((2**30, 3 * 2**30)), sample_every=4
        ))
        final = fit(FitConfig(
            model=LlamaConfig.tiny(),
            data=DataConfig(global_batch=4, seq_len=16, vocab_size=128),
            mesh_shape=MeshShape(fsdp=2),
            steps=4, log_every=4, warmup_steps=1,
        ))
        assert final["xla_compiles"] >= 1  # the train step compiled
        # run-scoped peak: the fake's cumulative counter (3GB) never
        # advanced during the run, so the run reports its own live bound
        # (1GB), NOT the inherited process peak — the attribution rule
        assert final["peak_hbm_gb"] == 1.0
        assert final["peak_hbm_exact"] is False
        # the HBM gauges landed in the job-history metrics snapshot (the
        # portal /metrics source), not only on the process-global registry
        snap_path = app_dir / "metrics" / "worker_0_user_a0_fit.json"
        with open(snap_path) as f:
            snap = json.load(f)
        gauges = {m["name"]: m["value"] for m in snap["metrics"]
                  if m["name"].startswith("tony_hbm_")}
        assert gauges["tony_hbm_live_bytes"] == 2**30
        assert gauges["tony_hbm_peak_bytes"] == 3 * 2**30
        # the process ledger landed for `tony compiles`
        ledgers = read_app_ledgers(str(app_dir))
        assert "worker_0_user_a0" in ledgers
        aot = [e for e in ledgers["worker_0_user_a0"]["entries"]
               if e.get("kind") == "aot"]
        assert any(e["fn"] == "train.step" for e in aot)
        step_entry = next(e for e in aot if e["fn"] == "train.step")
        # the measured memory plan is attached (compile-ahead AOT path)
        assert step_entry["argument_bytes"] > 0

    def test_engine_close_carries_ledger_lines(self):
        from tony_tpu.models.llama import LlamaConfig, init_params
        from tony_tpu.serve import Engine, Request, ServeConfig

        cfg = LlamaConfig.tiny()
        params = init_params(jax.random.key(0), cfg)
        eng = Engine(params, cfg, ServeConfig(slots=2, max_len=32, kv_block=8))
        eng.run([Request(prompt=np.arange(3), max_new_tokens=3, rng=0)])
        s = eng.close()
        assert s["xla_compiles"] >= 1  # prefill + decode compiled
        # the decode step's AOT entry carries its measured memory plan
        aot = get_ledger().entries("aot")
        decode = [e for e in aot if e["fn"].startswith("serve.decode[")]
        assert decode and decode[-1]["argument_bytes"] > 0

"""Tests for portal, proxy, auth, the metrics pipeline, and the trace spine."""

import json
import math
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import grpc
import pytest

from tony_tpu.obs import trace
from tony_tpu.obs.portal import PortalData, serve_portal
from tony_tpu.obs.proxy import ProxyServer
from tony_tpu.obs.registry import Registry, render_snapshots, write_snapshot
from tony_tpu.rpc import ApplicationRpcClient, ApplicationRpcServicer, pb, serve
from tony_tpu.rpc.auth import mint_token, read_token


@pytest.fixture
def fake_app(tmp_path):
    app_dir = tmp_path / "job-1"
    (app_dir / "logs").mkdir(parents=True)
    (app_dir / "events").mkdir()
    (app_dir / "logs" / "worker_0_attempt0.log").write_text("hello log\n")
    (app_dir / "status.json").write_text(json.dumps({
        "state": "SUCCEEDED", "exit_code": 0,
        "tasks": [{"task": "worker:0", "state": "SUCCEEDED", "exit_code": 0,
                   "attempts": 1, "log": ""}],
    }))
    (app_dir / "config.json").write_text(json.dumps({
        "application.name": "j", "application.framework": "jax"}))
    (app_dir / "events" / "job-1.jhist.jsonl").write_text(
        json.dumps({"type": "APPLICATION_INITED", "ts": 1.0, "app_id": "job-1"}) + "\n"
        + json.dumps({"type": "METRICS", "ts": 2.0, "app_id": "job-1",
                      "task": "worker:0",
                      "samples": {"mfu": 0.41, "tokens_per_sec": 1200.5,
                                  "rss_mb": 512.0, "hbm_mb": 9001.0}}) + "\n"
        + json.dumps({"type": "METRICS", "ts": 3.0, "app_id": "job-1",
                      "task": "worker:0",
                      "samples": {"mfu": 0.52, "tokens_per_sec": 1400.0,
                                  "rss_mb": 520.0, "hbm_mb": 9002.0}}) + "\n"
    )
    return tmp_path


class TestPortal:
    def test_data_layer(self, fake_app):
        data = PortalData(str(fake_app))
        jobs = data.jobs()
        assert [j["app_id"] for j in jobs] == ["job-1"]
        detail = data.job("job-1")
        assert detail["status"]["state"] == "SUCCEEDED"
        assert detail["events"][0]["type"] == "APPLICATION_INITED"
        assert data.log("job-1", "worker_0_attempt0.log") == "hello log\n"
        # traversal guards
        assert data.job("../etc") is None
        assert data.log("job-1", "../status.json") is None

    def test_http_endpoints(self, fake_app):
        server, port = serve_portal(str(fake_app), port=0, host="127.0.0.1")
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            def get(path):
                with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
                    return r.status, r.read().decode()

            status, body = get("/api/jobs")
            assert status == 200 and json.loads(body)[0]["app_id"] == "job-1"
            status, body = get("/job/job-1")
            assert status == 200 and "SUCCEEDED" in body
            # metrics table: latest sample per task, not a raw JSON dump
            assert "<h2>metrics</h2>" in body
            assert "0.52" in body and "1400" in body and "9002" in body
            from tony_tpu.obs.portal import PortalData, _latest_metrics

            detail = PortalData(str(fake_app)).job("job-1")
            latest = _latest_metrics(detail["events"])
            assert latest["worker:0"]["mfu"] == 0.52  # superseded 0.41 gone
            # history charts: a sparkline polyline per charted metric
            assert "<svg" in body and "polyline" in body
            from tony_tpu.obs.portal import _metric_series

            series = _metric_series(detail["events"])
            assert series["worker:0"]["mfu"] == [0.41, 0.52]
            status, body = get("/job/job-1/log/worker_0_attempt0.log")
            assert status == 200 and body == "hello log\n"
            with pytest.raises(urllib.error.HTTPError):
                get("/job/nope")
        finally:
            server.shutdown()


def test_tpu_metrics_source_shape():
    """The device-metrics source yields well-formed Samples on platforms
    exposing memory_stats, and degrades to [] (never raises) elsewhere —
    bench.py's environment exercises the populated path on the real chip."""
    from tony_tpu.obs.monitor import TaskMonitor
    from tony_tpu.obs.tpu_metrics import tpu_memory_samples, tpu_metrics_dict

    samples = tpu_memory_samples()
    for name, value, ts in samples:
        assert name.startswith("hbm_") and value >= 0 and ts > 0
    d = tpu_metrics_dict()
    assert set(d) == {name for name, _, _ in samples}
    # plugs into the monitor's extra_sources seam
    mon = TaskMonitor(extra_sources=[tpu_memory_samples])
    names = {name for name, _, _ in mon.sample()}
    assert "rss_mb" in names


def test_proxy_relays_bytes():
    # echo server as the "in-container service"
    backend = socket.socket()
    backend.bind(("127.0.0.1", 0))
    backend.listen(1)
    bport = backend.getsockname()[1]

    def echo():
        conn, _ = backend.accept()
        data = conn.recv(1024)
        conn.sendall(b"echo:" + data)
        conn.close()

    threading.Thread(target=echo, daemon=True).start()
    proxy = ProxyServer(f"127.0.0.1:{bport}").start()
    try:
        c = socket.create_connection(("127.0.0.1", proxy.port), timeout=5)
        c.sendall(b"ping")
        assert c.recv(1024) == b"echo:ping"
        c.close()
    finally:
        proxy.stop()
        backend.close()


class TestAuth:
    def test_mint_and_read_roundtrip(self, tmp_path):
        token = mint_token(str(tmp_path))
        assert read_token(str(tmp_path)) == token
        assert oct(os.stat(tmp_path / "app.token").st_mode & 0o777) == "0o600"

    def test_rpc_rejects_without_token(self):
        class S(ApplicationRpcServicer):
            def Heartbeat(self, request, context):
                return pb.HeartbeatResponse()

        server, port = serve(S(), port=0, token="sekrit")
        try:
            with ApplicationRpcClient(f"127.0.0.1:{port}") as bad:
                with pytest.raises(grpc.RpcError) as e:
                    bad.heartbeat("w", 0)
                assert e.value.code() == grpc.StatusCode.UNAUTHENTICATED
            with ApplicationRpcClient(f"127.0.0.1:{port}", token="wrong") as bad:
                with pytest.raises(grpc.RpcError):
                    bad.heartbeat("w", 0)
            with ApplicationRpcClient(f"127.0.0.1:{port}", token="sekrit") as good:
                good.heartbeat("w", 0)
        finally:
            server.stop(0)


def test_secure_job_end_to_end(tmp_path):
    """application.security.enabled: full submit->AM->executor path with
    token-authenticated RPC (the milestone the reference gates on
    tony.application.security.enabled)."""
    from tony_tpu.cli.client import TonyClient
    from tony_tpu.config.config import TonyConfig

    cfg = TonyConfig.load(overrides={
        "application.name": "secure",
        "application.framework": "generic",
        "application.security.enabled": True,
        "application.stage_dir": str(tmp_path),
        "application.timeout_s": 60,
        "job.worker.instances": 1,
        "job.worker.command": 'python -c "pass"',
    })
    client = TonyClient(cfg)
    assert client.run(quiet=True) == 0
    assert (tmp_path / client.app_id / "app.token").exists()


def test_diagnostics_context(monkeypatch, tmp_path):
    """diagnostics.enabled -> TONY_TPU_DIAGNOSTICS -> a real
    cloud-tpu-diagnostics stack-trace context around fit()."""
    import contextlib

    from tony_tpu.obs.diagnostics import diagnostics_context

    # off by default: nullcontext
    monkeypatch.delenv("TONY_TPU_DIAGNOSTICS", raising=False)
    assert isinstance(diagnostics_context(), contextlib.nullcontext)
    # on: the REAL library context (not the fallback nullcontext — this
    # image ships cloud-tpu-diagnostics and the glue must actually engage);
    # 1s interval so the collection daemon joins promptly at exit
    monkeypatch.setenv("TONY_TPU_DIAGNOSTICS", "1")
    monkeypatch.setenv("TONY_TPU_DIAGNOSTICS_INTERVAL_S", "1")
    ctx = diagnostics_context()
    assert not isinstance(ctx, contextlib.nullcontext)
    with ctx:
        pass
    # env glue: the runtime exports the flag from the config key
    from tony_tpu.config.config import TonyConfig
    from tony_tpu.runtime import make_runtime
    from tony_tpu.runtime.base import TaskIdentity

    cfg = TonyConfig({"diagnostics.enabled": True})
    ident = TaskIdentity(
        job_name="worker", index=0, cluster_spec={"worker": ["h:1"]},
        coordinator_address="h:1", process_id=0, num_processes=1, generation=0,
    )
    env = make_runtime("generic").build_env(ident, cfg)
    assert env.get("TONY_TPU_DIAGNOSTICS") == "1"


# --- the distributed trace spine (obs/trace.py; docs/OBS.md) -----------------


@pytest.fixture
def armed_tracer(tmp_path):
    """A real tracer armed process-globally, always disarmed afterwards."""
    tracer = trace.Tracer(
        str(tmp_path / "trace" / "test_proc.jsonl"), "test_proc", "trace01",
        sample_steps=4, flush_interval_s=0.05,
    )
    trace.install(tracer)
    try:
        yield tracer
    finally:
        trace.uninstall()


def read_journal(path):
    recs = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            if line.strip():
                recs.append(json.loads(line))
    return recs


class TestTraceSpine:
    def test_span_lifecycle_and_nesting(self, tmp_path, armed_tracer):
        """Context-managed spans nest per thread (child's psid == parent's
        sid), manual spans end explicitly, instants are zero-duration, and
        everything journals with wall-anchored monotonic timestamps."""
        t_before = time.time() * 1e6
        with trace.span("outer", phase="x") as outer:
            with trace.span("inner") as inner:
                time.sleep(0.01)
            trace.instant("marker", why="test")
        manual = armed_tracer.span("manual")
        time.sleep(0.002)
        manual.end(result="done")
        trace.uninstall()
        recs = read_journal(tmp_path / "trace" / "test_proc.jsonl")
        meta = recs[0]
        assert meta["ph"] == "M" and meta["proc"] == "test_proc"
        assert meta["trace"] == "trace01"
        by_name = {r["name"]: r for r in recs if r["ph"] == "X"}
        assert set(by_name) == {"outer", "inner", "manual"}
        # parent/child: inner under outer; manual is a root
        assert by_name["inner"]["psid"] == outer.sid
        assert by_name["outer"]["sid"] == outer.sid
        assert by_name["outer"]["psid"] == ""
        assert by_name["manual"]["psid"] == ""
        assert by_name["manual"]["args"]["result"] == "done"
        # timing: inner inside outer, durations sane, wall-anchored
        o, i = by_name["outer"], by_name["inner"]
        assert o["ts"] <= i["ts"] and i["dur"] >= 10_000
        assert o["dur"] >= i["dur"]
        assert o["ts"] >= t_before - 5e6
        inst = [r for r in recs if r["ph"] == "i"]
        assert len(inst) == 1 and inst[0]["name"] == "marker"
        assert o["ts"] <= inst[0]["ts"] <= o["ts"] + o["dur"]

    def test_rpc_hop_propagates_context(self, armed_tracer, tmp_path):
        """The cross-process edge: a client span's id rides gRPC metadata
        and the server's dispatch span parents on it (client and server
        share one armed tracer here, so both sides land in one journal)."""

        class S(ApplicationRpcServicer):
            def Heartbeat(self, request, context):
                return pb.HeartbeatResponse()

        server, port = serve(S(), port=0)
        try:
            with ApplicationRpcClient(f"127.0.0.1:{port}") as client:
                with trace.span("caller"):
                    client.heartbeat("w", 0)
        finally:
            server.stop(0)
        trace.uninstall()
        recs = read_journal(tmp_path / "trace" / "test_proc.jsonl")
        by_name = {r["name"]: r for r in recs if r["ph"] == "X"}
        caller = by_name["caller"]
        cl = by_name["rpc.client/Heartbeat"]
        sv = by_name["rpc.server/Heartbeat"]
        assert cl["psid"] == caller["sid"]
        assert sv["psid"] == cl["sid"]  # crossed the wire via metadata
        assert sv["args"]["method"] == "Heartbeat"

    def test_disarmed_span_is_inert(self):
        assert trace.active_tracer() is None
        sp = trace.span("anything", k=1)
        assert sp is trace.NOOP_SPAN
        with sp:
            pass
        sp.end()
        trace.instant("nothing")
        trace.flush()

    def test_journal_rotation_keeps_newest(self, tmp_path):
        """At the size cap the journal rotates (flight-recorder retention):
        the NEWEST events survive — a post-mortem needs the crash window,
        not day one — disk stays bounded at two windows, and load_journals
        merges the rotated window back into one process entry."""
        from tony_tpu.obs.trace_tool import load_journals

        tracer = trace.Tracer(
            str(tmp_path / "trace" / "rot.jsonl"), "rot", "t",
            flush_interval_s=999.0,
        )
        tracer._max_bytes = 4096
        for i in range(200):
            tracer.span(f"s{i:04d}").end()
        tracer.close()
        files = sorted(os.listdir(tmp_path / "trace"))
        assert files == ["rot.0.jsonl", "rot.jsonl"]
        procs = load_journals(str(tmp_path / "trace"))
        assert len(procs) == 1 and procs[0]["proc"] == "rot"
        names = {s["name"] for s in procs[0]["spans"]}
        assert "s0199" in names      # the crash window survived
        assert "s0000" not in names  # the oldest window was dropped
        # append-mode reopen (re-arm cycle / relaunch reusing the proc
        # name) must count the existing bytes or the disk bound breaks
        existing = os.path.getsize(tmp_path / "trace" / "rot.jsonl")
        assert existing > 0
        tracer2 = trace.Tracer(
            str(tmp_path / "trace" / "rot.jsonl"), "rot", "t",
            flush_interval_s=999.0,
        )
        assert tracer2._written >= existing
        tracer2.close()

    def test_close_joins_flusher_and_flushes_residual_ring(self, tmp_path):
        """Shutdown hardening: close() JOINS the daemon flusher (bounded)
        before the final drain, so nothing is in flight, then flushes the
        residual ring. The flush interval here is far longer than the test,
        so the drain loop never woke on its own — every span below can only
        have landed via the close path, the one short-lived processes
        (CLI tools, chaos-killed children that catch the signal) rely on."""
        tracer = trace.Tracer(
            str(tmp_path / "trace" / "cli.jsonl"), "cli", "t",
            flush_interval_s=999.0,
        )
        for i in range(32):
            tracer.span(f"w{i:02d}").end()
        t0 = time.perf_counter()
        tracer.close(join_timeout_s=5.0)
        assert time.perf_counter() - t0 < 10.0  # bounded: exit never hangs
        assert not tracer._thread.is_alive()    # the flusher actually joined
        assert tracer.dropped == 0
        recs = read_journal(tmp_path / "trace" / "cli.jsonl")
        names = {r["name"] for r in recs if r["ph"] == "X"}
        assert names == {f"w{i:02d}" for i in range(32)}
        tracer.close()  # idempotent: the second close is a no-op

    def test_close_with_wedged_flusher_stays_bounded(self, tmp_path):
        """The other half of the shutdown contract: when the flusher is
        wedged mid-write (hard-mounted FS) and the bounded join times out,
        close() must NOT touch the journal — the wedged thread may hold
        the io lock, and blocking on it would hang process exit, the very
        thing the bounded join exists to prevent. The abandoned window is
        counted in ``dropped``."""
        tracer = trace.Tracer(
            str(tmp_path / "trace" / "wedge.jsonl"), "wedge", "t",
            flush_interval_s=0.01,
        )
        gate = threading.Event()
        entered = threading.Event()
        orig = tracer._write_line

        def stuck(rec):
            entered.set()
            gate.wait()       # the write that never returns
            orig(rec)

        tracer._write_line = stuck
        tracer.span("in.flight").end()
        assert entered.wait(5.0)       # flusher is now wedged under _io_lock
        tracer.span("abandoned").end()
        t0 = time.perf_counter()
        tracer.close(join_timeout_s=0.2)
        assert time.perf_counter() - t0 < 5.0   # returned, did not deadlock
        assert tracer.dropped >= 1              # the abandoned window counted
        gate.set()                              # let the daemon die

    def test_short_lived_process_atexit_flushes_last_window(self, tmp_path):
        """The atexit contract end-to-end: a real short-lived process arms
        a tracer, records one span, and exits WITHOUT calling close().
        The registered atexit close must join the flusher and land the
        span — the flush interval is longer than the process lifetime, so
        nothing else can have written it."""
        journal = tmp_path / "trace" / "shortlived.jsonl"
        code = (
            "from tony_tpu.obs import trace\n"
            f"tr = trace.install(trace.Tracer({str(journal)!r}, "
            "'shortlived', 't', flush_interval_s=999.0))\n"
            "tr.span('last.window').end()\n"
        )
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, "-c", code], cwd=repo,
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        recs = read_journal(journal)
        assert any(r.get("name") == "last.window" for r in recs), recs

    def test_rotation_under_concurrent_writers_yields_parseable_journals(
            self, tmp_path):
        """Two threads spanning across rotation boundaries: every retained
        journal must be parseable JSONL with no interleaved/torn lines —
        the io lock serializes writes and rotation swaps files atomically,
        so a reader (tony trace mid-run, or post-mortem) never sees a
        corrupt window."""
        tracer = trace.Tracer(
            str(tmp_path / "trace" / "cw.jsonl"), "cw", "t",
            flush_interval_s=0.001,  # flusher races the writers for real
        )
        tracer._max_bytes = 4096     # a few rotations over the test
        pad = "x" * 64

        def writer(tag):
            for i in range(300):
                tracer.span(f"{tag}{i:03d}", pad=pad).end()

        threads = [threading.Thread(target=writer, args=(t,)) for t in "ab"]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tracer.close()
        files = sorted(os.listdir(tmp_path / "trace"))
        assert "cw.jsonl" in files and "cw.0.jsonl" in files  # it DID rotate
        names = set()
        for fname in files:
            with open(tmp_path / "trace" / fname, encoding="utf-8") as f:
                for line in f:
                    assert line.endswith("\n"), f"torn line in {fname}"
                    rec = json.loads(line)  # raises on interleaved garbage
                    if rec.get("ph") == "X":
                        assert rec["name"][0] in "ab"
                        names.add(rec["name"])
        # the newest window survived rotation. Only the LAST writer's final
        # span is guaranteed retained: if the GIL runs one thread to
        # completion first, flight-recorder retention (newest ~2 windows)
        # correctly discards that thread's records entirely.
        assert "a299" in names or "b299" in names

    def test_emergency_flush_journals_open_spans(self, tmp_path, armed_tracer):
        """The pre-SIGKILL path: spans still open when a chaos kill fires
        are journaled as begin-only records with an ``fts`` kill-time proxy
        (they are what the fault interrupted), and merge_chrome renders
        them as Chrome B events."""
        from tony_tpu.obs.trace_tool import load_journals, merge_chrome

        killed = armed_tracer.span("outer.killed")  # never ends: the SIGKILL
        trace.emergency_flush()  # what chaos does right before the kill
        procs = load_journals(str(tmp_path / "trace"))
        assert procs[0]["opens"], "open span missing from emergency flush"
        o = procs[0]["opens"][0]
        assert o["name"] == "outer.killed" and o["fts"] >= o["ts"]
        merged = merge_chrome(str(tmp_path), procs)
        b = next(e for e in merged["traceEvents"] if e["ph"] == "B")
        assert b["name"] == "outer.killed" and b["args"]["killed"] is True
        # a fault the process SURVIVES: the span completes, and the merge
        # keeps only the finished X record (no duplicate begin-only ghost)
        killed.end()
        armed_tracer.flush()
        procs = load_journals(str(tmp_path / "trace"))
        assert not procs[0]["opens"]
        assert any(s["name"] == "outer.killed" for s in procs[0]["spans"])

    def test_close_journals_open_spans(self, tmp_path):
        """Normal shutdown rescues un-ended spans too: a root span whose
        holder was unwound by an exception (Ctrl-C'd supervise loop) must
        not vanish from the merge — close() journals it begin-only, once."""
        from tony_tpu.obs.trace_tool import load_journals

        tracer = trace.Tracer(
            str(tmp_path / "trace" / "am.jsonl"), "am", "t",
            flush_interval_s=999.0,
        )
        tracer.span("am.run", attempt=0)  # never .end(): interrupted
        tracer.close()
        procs = load_journals(str(tmp_path / "trace"))
        opens = procs[0]["opens"]
        assert [o["name"] for o in opens] == ["am.run"]
        assert opens[0]["fts"] >= opens[0]["ts"]

    def test_env_arming_roundtrip(self, tmp_path, monkeypatch):
        """install_from_env arms from the AM-exported contract and the
        default parent roots this process under the launcher's span."""
        monkeypatch.setenv(trace.ENV_DIR, str(tmp_path / "trace"))
        monkeypatch.setenv(trace.ENV_TRACE_ID, "abcd")
        monkeypatch.setenv(trace.ENV_PROC, "worker_0_user_a0")
        monkeypatch.setenv(trace.ENV_PARENT, "feedbeef")
        monkeypatch.setenv(trace.ENV_SAMPLE, "8")
        monkeypatch.setenv(trace.ENV_RING, "128")
        monkeypatch.setenv(trace.ENV_JOURNAL_MB, "7")
        tracer = trace.install_from_env()
        try:
            assert tracer is not None
            assert tracer.trace_id == "abcd" and tracer.sample_steps == 8
            assert tracer.ring_size == 128 and tracer.max_journal_mb == 7
            with trace.span("root_here"):
                pass
        finally:
            trace.uninstall()
        recs = read_journal(tmp_path / "trace" / "worker_0_user_a0.jsonl")
        root = next(r for r in recs if r.get("name") == "root_here")
        assert root["psid"] == "feedbeef"


class TestRegistry:
    def test_prometheus_exposition_conformance(self):
        """TYPE lines, histogram bucket monotonicity + cumulative le
        semantics, _sum/_count agreement, label rendering."""
        reg = Registry()
        c = reg.counter("tony_test_total", "a counter", method="Beat")
        c.inc(); c.inc(2)
        g = reg.gauge("tony_test_depth", "a gauge")
        g.set(7)
        h = reg.histogram("tony_ttft_seconds", "ttft")
        for v in (0.002, 0.002, 0.03, 0.2, 4.0, 100.0):
            h.observe(v)
        text = reg.render()
        lines = text.strip().splitlines()
        assert "# TYPE tony_test_total counter" in lines
        assert "# TYPE tony_test_depth gauge" in lines
        assert "# TYPE tony_ttft_seconds histogram" in lines
        assert 'tony_test_total{method="Beat"} 3' in lines
        assert "tony_test_depth 7" in lines
        # HELP precedes TYPE for each family
        for name in ("tony_test_total", "tony_ttft_seconds"):
            assert lines.index(f"# HELP {name} " + dict(
                tony_test_total="a counter", tony_ttft_seconds="ttft",
            )[name]) < lines.index([l for l in lines if l.startswith(f"# TYPE {name}")][0])
        # bucket counts are cumulative and monotonic, +Inf == _count
        buckets = []
        for line in lines:
            if line.startswith("tony_ttft_seconds_bucket"):
                le = line.split('le="')[1].split('"')[0]
                buckets.append((le, int(line.rsplit(" ", 1)[1])))
        assert buckets[-1][0] == "+Inf"
        counts = [b[1] for b in buckets]
        assert counts == sorted(counts)  # monotone
        count_line = next(l for l in lines if l.startswith("tony_ttft_seconds_count"))
        assert int(count_line.rsplit(" ", 1)[1]) == 6 == counts[-1]
        sum_line = next(l for l in lines if l.startswith("tony_ttft_seconds_sum"))
        assert math.isclose(float(sum_line.rsplit(" ", 1)[1]), 104.234)
        # bucketed quantiles are ordered and bracket the data
        assert 0 < h.quantile(0.5) <= h.quantile(0.95) <= h.quantile(0.99)

    def test_portal_metrics_endpoint(self, tmp_path):
        """The portal /metrics endpoint re-renders every app's registry
        snapshots as one labelled Prometheus scrape."""
        reg = Registry()
        reg.histogram("tony_step_time_seconds", "step time").observe(0.12)
        reg.histogram("tony_ttft_seconds", "ttft").observe(0.05)
        app_dir = tmp_path / "job-metrics"
        (app_dir / "metrics").mkdir(parents=True)
        write_snapshot(
            str(app_dir / "metrics" / "worker_0_user.json"), reg,
            proc="worker_0_user",
        )
        server, port = serve_portal(str(tmp_path), port=0, host="127.0.0.1")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/plain")
                body = r.read().decode()
        finally:
            server.shutdown()
        assert "# TYPE tony_step_time_seconds histogram" in body
        assert "# TYPE tony_ttft_seconds histogram" in body
        assert 'app="job-metrics"' in body and 'proc="worker_0_user"' in body
        assert 'le="+Inf"' in body
        # render_snapshots merges multiple snapshots under one TYPE header
        snaps = PortalData(str(tmp_path)).metric_snapshots()
        text = render_snapshots(snaps + snaps)
        assert text.count("# TYPE tony_step_time_seconds histogram") == 1

    def test_render_skips_malformed_entries(self):
        """One malformed snapshot entry (older format, hand-edited file)
        must not take down the fleet-wide scrape."""
        good = {"kind": "gauge", "name": "tony_ok", "help": "", "labels": {},
                "value": 1.0}
        text = render_snapshots([({}, [
            None, 42, {"no": "name"},
            {"kind": "histogram", "name": "tony_broken", "labels": {}},  # no bounds
            good,
        ])])
        assert "tony_ok 1" in text
        assert "tony_broken_bucket" not in text


class TestTraceMerge:
    def _write_journal(self, trace_dir, proc, pid, recs):
        trace_dir.mkdir(parents=True, exist_ok=True)
        with open(trace_dir / f"{proc}.jsonl", "w") as f:
            f.write(json.dumps({"ph": "M", "proc": proc, "pid": pid,
                                "trace": "t"}) + "\n")
            for r in recs:
                f.write(json.dumps(r) + "\n")

    def test_merge_emits_valid_chrome_trace(self, tmp_path):
        from tony_tpu.obs.trace_tool import merge_chrome

        tdir = tmp_path / "trace"
        self._write_journal(tdir, "am_a0", 100, [
            {"ph": "X", "name": "am.run", "ts": 1_000_000, "dur": 5_000_000,
             "tid": 1, "sid": "a", "psid": "", "args": {}},
        ])
        self._write_journal(tdir, "worker_0_exec_a0", 200, [
            {"ph": "X", "name": "executor.register", "ts": 1_200_000,
             "dur": 10_000, "tid": 2, "sid": "b", "psid": "a", "args": {}},
            {"ph": "i", "name": "chaos.drop_heartbeats", "ts": 2_000_000,
             "tid": 2, "args": {"point": "executor.beat"}},
        ])
        # a torn tail (SIGKILLed writer) must be skipped, not fatal
        with open(tdir / "worker_0_exec_a0.jsonl", "a") as f:
            f.write('{"ph": "X", "name": "torn')
        merged = merge_chrome(str(tmp_path))
        events = merged["traceEvents"]
        json.dumps(merged)  # serializable end-to-end
        pids = {e["pid"] for e in events}
        assert len(pids) == 2
        names = {e["pid"]: e["args"]["name"] for e in events
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert set(names.values()) == {"am_a0", "worker_0_exec_a0"}
        for e in events:
            assert e["ph"] in ("M", "X", "i")
            if e["ph"] == "X":
                assert isinstance(e["ts"], int) and isinstance(e["dur"], int)
        xs = [e for e in events if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"am.run", "executor.register"}
        inst = [e for e in events if e["ph"] == "i"]
        assert inst[0]["name"] == "chaos.drop_heartbeats"

    def test_straggler_flagging(self, tmp_path):
        from tony_tpu.obs.trace_tool import stragglers

        ev_dir = tmp_path / "events"
        ev_dir.mkdir()
        events = []
        for step in (10, 20, 30):
            events.append({"type": "METRICS", "ts": 100.0 + step,
                           "task": "worker:0", "samples": {"step": step}})
            events.append({"type": "METRICS", "ts": 100.0 + step,
                           "task": "worker:1", "samples": {"step": step // 3}})
        with open(ev_dir / "app.jhist.jsonl", "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        flags = stragglers(str(tmp_path))
        assert [f["task"] for f in flags] == ["worker:1"]
        assert flags[0]["behind_steps"] == 20
        assert flags[0]["steps_per_s"] > 0

    def test_goodput_prices_sigkilled_restart(self, tmp_path):
        """A kill_container'd attempt leaves only a begin-only user_process
        record (emergency-flushed pre-SIGKILL): its ``fts`` kill-time proxy
        must price the relaunch hole, or restart_s reports 0 for exactly
        the restart type the flight recorder exists to measure."""
        from tony_tpu.obs.trace_tool import goodput

        tdir = tmp_path / "trace"
        # attempt 0: killed at t=3s, 2s into its user process (B + fts)
        self._write_journal(tdir, "worker_0_exec_a0", 200, [
            {"ph": "B", "name": "executor.user_process", "ts": 1_000_000,
             "fts": 3_000_000, "sid": "u0", "psid": "",
             "args": {"task": "worker:0", "attempt": 0}},
        ])
        # attempt 1: relaunched at t=5s, runs 4s to completion
        self._write_journal(tdir, "worker_0_exec_a1", 201, [
            {"ph": "X", "name": "executor.user_process", "ts": 5_000_000,
             "dur": 4_000_000, "tid": 1, "sid": "u1", "psid": "",
             "args": {"task": "worker:0", "attempt": 1}},
            {"ph": "X", "name": "am.gang_restart", "ts": 3_100_000,
             "dur": 1_000_000, "tid": 1, "sid": "g", "psid": "", "args": {}},
        ])
        g = goodput(str(tmp_path))
        assert g["restarts"] == 1
        assert g["restart_s"] == pytest.approx(2.0)  # kill t=3s -> relaunch t=5s
        # the window opens at the killed attempt's begin-only span (t=1s),
        # not at the first COMPLETED span (t=3.1s)
        assert g["window_s"] == pytest.approx(8.0)  # 1s -> 9s


def test_trace_chaos_job_end_to_end(tmp_path):
    """The acceptance scenario: a real client->AM->executor job under a
    chaos schedule, with the user process joining the trace. The merged
    Chrome trace must contain spans from THREE processes (AM, executor,
    user) on one shared timeline, with the injected fault's instant event
    landing between the heartbeat spans it interrupted."""
    from tony_tpu.cli.client import TonyClient
    from tony_tpu.cli.main import main as cli_main
    from tony_tpu.config.config import TonyConfig
    from tony_tpu.obs.trace_tool import load_journals, merge_chrome, report

    user = (
        'python -c "'
        "import time; from tony_tpu.obs import trace; "
        "trace.install_from_env(); "
        "s = trace.span('user.work'); s.__enter__(); time.sleep(1.2); "
        "s.__exit__(None, None, None); trace.uninstall()\""
    )
    cfg = TonyConfig.load(overrides={
        "application.name": "trace-chaos",
        "application.framework": "generic",
        "application.stage_dir": str(tmp_path),
        "application.timeout_s": 90,
        "task.heartbeat_interval_ms": 200,
        "task.max_missed_heartbeats": 25,
        "chaos.enabled": True,
        "chaos.faults": json.dumps(
            [{"type": "drop_heartbeats", "task": "worker:0", "at_count": 2}]
        ),
        "job.worker.instances": 1,
        "job.worker.command": user,
    })
    client = TonyClient(cfg)
    assert client.run(quiet=True) == 0
    app_dir = client.app_dir
    procs = load_journals(os.path.join(app_dir, "trace"))
    by_proc = {p["proc"]: p for p in procs}
    assert "am_a0" in by_proc
    assert "worker_0_exec_a0" in by_proc
    assert "worker_0_user_a0" in by_proc
    # all three share ONE trace id
    assert len({p["trace"] for p in procs}) == 1
    # the user span nests (transitively) under the executor's user_process
    exec_spans = {s["sid"]: s for s in by_proc["worker_0_exec_a0"]["spans"]}
    user_work = next(
        s for s in by_proc["worker_0_user_a0"]["spans"]
        if s["name"] == "user.work"
    )
    parent = exec_spans[user_work["psid"]]
    assert parent["name"] == "executor.user_process"
    # the fault fired as an instant event in the executor, BETWEEN the
    # heartbeat spans it interrupted (beat 2 dropped; beats 1 and 3 real)
    instants = by_proc["worker_0_exec_a0"]["instants"]
    fault = next(i for i in instants if i["name"] == "chaos.drop_heartbeats")
    beats = sorted(
        (s for s in by_proc["worker_0_exec_a0"]["spans"]
         if s["name"] == "rpc.client/Heartbeat"),
        key=lambda s: s["ts"],
    )
    assert len(beats) >= 2
    assert any(b["ts"] + b["dur"] <= fault["ts"] for b in beats)
    assert any(b["ts"] >= fault["ts"] for b in beats)
    # AM spans sit on the same wall-anchored timeline
    am_run = next(s for s in by_proc["am_a0"]["spans"] if s["name"] == "am.run")
    assert am_run["ts"] <= user_work["ts"]
    assert am_run["ts"] + am_run["dur"] >= user_work["ts"] + user_work["dur"]
    # `tony trace` merges it all into one valid Chrome-trace JSON
    out = os.path.join(str(tmp_path), "merged.json")
    assert cli_main(["trace", app_dir, "--out", out]) == 0
    with open(out) as f:
        merged = json.load(f)
    span_pids = {e["pid"] for e in merged["traceEvents"] if e["ph"] == "X"}
    assert len(span_pids) >= 3
    # merge_chrome/report agree with the CLI output
    assert merge_chrome(app_dir)["traceEvents"]
    rep = report(app_dir)
    assert rep["goodput"]["window_s"] > 0
    # the AM journaled a registry snapshot (served-RPC counters)
    am_snap = os.path.join(app_dir, "metrics", "am_a0.json")
    assert os.path.exists(am_snap)
    with open(am_snap) as f:
        snap = json.load(f)
    assert any(
        m["name"] == "tony_rpc_requests_total" and m["labels"].get("method") == "Heartbeat"
        for m in snap["metrics"]
    )

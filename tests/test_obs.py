"""Tests for portal, proxy, auth, and the metrics pipeline."""

import json
import os
import socket
import threading
import urllib.request

import grpc
import pytest

from tony_tpu.obs.portal import PortalData, serve_portal
from tony_tpu.obs.proxy import ProxyServer
from tony_tpu.rpc import ApplicationRpcClient, ApplicationRpcServicer, pb, serve
from tony_tpu.rpc.auth import mint_token, read_token


@pytest.fixture
def fake_app(tmp_path):
    app_dir = tmp_path / "job-1"
    (app_dir / "logs").mkdir(parents=True)
    (app_dir / "events").mkdir()
    (app_dir / "logs" / "worker_0_attempt0.log").write_text("hello log\n")
    (app_dir / "status.json").write_text(json.dumps({
        "state": "SUCCEEDED", "exit_code": 0,
        "tasks": [{"task": "worker:0", "state": "SUCCEEDED", "exit_code": 0,
                   "attempts": 1, "log": ""}],
    }))
    (app_dir / "config.json").write_text(json.dumps({
        "application.name": "j", "application.framework": "jax"}))
    (app_dir / "events" / "job-1.jhist.jsonl").write_text(
        json.dumps({"type": "APPLICATION_INITED", "ts": 1.0, "app_id": "job-1"}) + "\n"
        + json.dumps({"type": "METRICS", "ts": 2.0, "app_id": "job-1",
                      "task": "worker:0",
                      "samples": {"mfu": 0.41, "tokens_per_sec": 1200.5,
                                  "rss_mb": 512.0, "hbm_mb": 9001.0}}) + "\n"
        + json.dumps({"type": "METRICS", "ts": 3.0, "app_id": "job-1",
                      "task": "worker:0",
                      "samples": {"mfu": 0.52, "tokens_per_sec": 1400.0,
                                  "rss_mb": 520.0, "hbm_mb": 9002.0}}) + "\n"
    )
    return tmp_path


class TestPortal:
    def test_data_layer(self, fake_app):
        data = PortalData(str(fake_app))
        jobs = data.jobs()
        assert [j["app_id"] for j in jobs] == ["job-1"]
        detail = data.job("job-1")
        assert detail["status"]["state"] == "SUCCEEDED"
        assert detail["events"][0]["type"] == "APPLICATION_INITED"
        assert data.log("job-1", "worker_0_attempt0.log") == "hello log\n"
        # traversal guards
        assert data.job("../etc") is None
        assert data.log("job-1", "../status.json") is None

    def test_http_endpoints(self, fake_app):
        server, port = serve_portal(str(fake_app), port=0, host="127.0.0.1")
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            def get(path):
                with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
                    return r.status, r.read().decode()

            status, body = get("/api/jobs")
            assert status == 200 and json.loads(body)[0]["app_id"] == "job-1"
            status, body = get("/job/job-1")
            assert status == 200 and "SUCCEEDED" in body
            # metrics table: latest sample per task, not a raw JSON dump
            assert "<h2>metrics</h2>" in body
            assert "0.52" in body and "1400" in body and "9002" in body
            from tony_tpu.obs.portal import PortalData, _latest_metrics

            detail = PortalData(str(fake_app)).job("job-1")
            latest = _latest_metrics(detail["events"])
            assert latest["worker:0"]["mfu"] == 0.52  # superseded 0.41 gone
            # history charts: a sparkline polyline per charted metric
            assert "<svg" in body and "polyline" in body
            from tony_tpu.obs.portal import _metric_series

            series = _metric_series(detail["events"])
            assert series["worker:0"]["mfu"] == [0.41, 0.52]
            status, body = get("/job/job-1/log/worker_0_attempt0.log")
            assert status == 200 and body == "hello log\n"
            with pytest.raises(urllib.error.HTTPError):
                get("/job/nope")
        finally:
            server.shutdown()


def test_tpu_metrics_source_shape():
    """The device-metrics source yields well-formed Samples on platforms
    exposing memory_stats, and degrades to [] (never raises) elsewhere —
    bench.py's environment exercises the populated path on the real chip."""
    from tony_tpu.obs.monitor import TaskMonitor
    from tony_tpu.obs.tpu_metrics import tpu_memory_samples, tpu_metrics_dict

    samples = tpu_memory_samples()
    for name, value, ts in samples:
        assert name.startswith("hbm_") and value >= 0 and ts > 0
    d = tpu_metrics_dict()
    assert set(d) == {name for name, _, _ in samples}
    # plugs into the monitor's extra_sources seam
    mon = TaskMonitor(extra_sources=[tpu_memory_samples])
    names = {name for name, _, _ in mon.sample()}
    assert "rss_mb" in names


def test_proxy_relays_bytes():
    # echo server as the "in-container service"
    backend = socket.socket()
    backend.bind(("127.0.0.1", 0))
    backend.listen(1)
    bport = backend.getsockname()[1]

    def echo():
        conn, _ = backend.accept()
        data = conn.recv(1024)
        conn.sendall(b"echo:" + data)
        conn.close()

    threading.Thread(target=echo, daemon=True).start()
    proxy = ProxyServer(f"127.0.0.1:{bport}").start()
    try:
        c = socket.create_connection(("127.0.0.1", proxy.port), timeout=5)
        c.sendall(b"ping")
        assert c.recv(1024) == b"echo:ping"
        c.close()
    finally:
        proxy.stop()
        backend.close()


class TestAuth:
    def test_mint_and_read_roundtrip(self, tmp_path):
        token = mint_token(str(tmp_path))
        assert read_token(str(tmp_path)) == token
        assert oct(os.stat(tmp_path / "app.token").st_mode & 0o777) == "0o600"

    def test_rpc_rejects_without_token(self):
        class S(ApplicationRpcServicer):
            def Heartbeat(self, request, context):
                return pb.HeartbeatResponse()

        server, port = serve(S(), port=0, token="sekrit")
        try:
            with ApplicationRpcClient(f"127.0.0.1:{port}") as bad:
                with pytest.raises(grpc.RpcError) as e:
                    bad.heartbeat("w", 0)
                assert e.value.code() == grpc.StatusCode.UNAUTHENTICATED
            with ApplicationRpcClient(f"127.0.0.1:{port}", token="wrong") as bad:
                with pytest.raises(grpc.RpcError):
                    bad.heartbeat("w", 0)
            with ApplicationRpcClient(f"127.0.0.1:{port}", token="sekrit") as good:
                good.heartbeat("w", 0)
        finally:
            server.stop(0)


def test_secure_job_end_to_end(tmp_path):
    """application.security.enabled: full submit->AM->executor path with
    token-authenticated RPC (the milestone the reference gates on
    tony.application.security.enabled)."""
    from tony_tpu.cli.client import TonyClient
    from tony_tpu.config.config import TonyConfig

    cfg = TonyConfig.load(overrides={
        "application.name": "secure",
        "application.framework": "generic",
        "application.security.enabled": True,
        "application.stage_dir": str(tmp_path),
        "application.timeout_s": 60,
        "job.worker.instances": 1,
        "job.worker.command": 'python -c "pass"',
    })
    client = TonyClient(cfg)
    assert client.run(quiet=True) == 0
    assert (tmp_path / client.app_id / "app.token").exists()


def test_diagnostics_context(monkeypatch, tmp_path):
    """diagnostics.enabled -> TONY_TPU_DIAGNOSTICS -> a real
    cloud-tpu-diagnostics stack-trace context around fit()."""
    import contextlib

    from tony_tpu.obs.diagnostics import diagnostics_context

    # off by default: nullcontext
    monkeypatch.delenv("TONY_TPU_DIAGNOSTICS", raising=False)
    assert isinstance(diagnostics_context(), contextlib.nullcontext)
    # on: the REAL library context (not the fallback nullcontext — this
    # image ships cloud-tpu-diagnostics and the glue must actually engage);
    # 1s interval so the collection daemon joins promptly at exit
    monkeypatch.setenv("TONY_TPU_DIAGNOSTICS", "1")
    monkeypatch.setenv("TONY_TPU_DIAGNOSTICS_INTERVAL_S", "1")
    ctx = diagnostics_context()
    assert not isinstance(ctx, contextlib.nullcontext)
    with ctx:
        pass
    # env glue: the runtime exports the flag from the config key
    from tony_tpu.config.config import TonyConfig
    from tony_tpu.runtime import make_runtime
    from tony_tpu.runtime.base import TaskIdentity

    cfg = TonyConfig({"diagnostics.enabled": True})
    ident = TaskIdentity(
        job_name="worker", index=0, cluster_spec={"worker": ["h:1"]},
        coordinator_address="h:1", process_id=0, num_processes=1, generation=0,
    )
    env = make_runtime("generic").build_env(ident, cfg)
    assert env.get("TONY_TPU_DIAGNOSTICS") == "1"

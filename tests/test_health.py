"""Numerics health sentinel: in-graph monitors, anomaly rules, forensics.

Covers the third flight-recorder axis (docs/OBS.md "Numerics health"):
the fused value monitors compute the right numbers, every anomaly rule
trips on its designed signal and latches, a trip produces a parseable
forensics bundle + verdict file + trace instant, the portal /healthz and
`tony health` surface the verdict, the chaos invariant checker refuses to
report clean over a tripped verdict, and a real chaos-style job proves
injection -> trip -> forensics end to end across processes.
"""

import json
import os
import sys
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.obs import health, trace
from tony_tpu.obs.health import HealthRules, HealthSentinel


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends disarmed (fit()/Engine arm the process
    global from env; leakage across tests would blend rule windows)."""
    health.uninstall()
    yield
    health.uninstall()


def make_sentinel(tmp_path=None, rules=None, **kw):
    kw.setdefault("sample_every", 1)
    return health.install(HealthSentinel(
        rules or HealthRules(),
        app_dir=str(tmp_path) if tmp_path is not None else "",
        proc="worker_0_user_a0",
        **kw,
    ))


def feed(sentinel, samples):
    for s in samples:
        sentinel.sample(**s)
    assert sentinel.drain(timeout_s=10.0)


def train_sample(step, loss, grad_norm=1.0, **h):
    metrics = {"step": step, "loss": loss, "grad_norm": grad_norm}
    metrics.update({f"health/{k}": v for k, v in h.items()})
    return {"metrics": metrics}


# --- in-graph monitors --------------------------------------------------------


class TestGraphMonitors:
    def test_nonfinite_counts_and_update_ratio(self):
        loss = jnp.float32(jnp.nan)
        grads = {"a": jnp.array([1.0, jnp.inf, jnp.nan]), "b": jnp.zeros((4,))}
        params = {"a": jnp.array([1.0, 2.0, jnp.nan]), "b": jnp.ones((4,))}
        updates = {"a": jnp.full((3,), 0.1), "b": jnp.full((4,), 0.1)}
        out = jax.jit(health.graph_monitors)(
            loss, grads, params, updates, jnp.zeros((2, 4), jnp.int32)
        )
        assert float(out["health/nonfinite_loss"]) == 1.0
        assert float(out["health/nonfinite_grads"]) == 2.0
        assert float(out["health/nonfinite_params"]) == 1.0
        # |Δ|/|θ| with a NaN'd param norm propagates NaN (itself a signal)
        assert not np.isfinite(float(out["health/update_ratio"]))
        clean = jax.jit(health.graph_monitors)(
            jnp.float32(1.0),
            {"a": jnp.ones((3,))}, {"a": jnp.full((3,), 2.0)},
            {"a": jnp.full((3,), 0.2)}, jnp.zeros((2, 4), jnp.int32),
        )
        assert float(clean["health/nonfinite_grads"]) == 0.0
        np.testing.assert_allclose(
            float(clean["health/update_ratio"]), 0.1, rtol=1e-5
        )

    def test_int_leaves_are_ignored(self):
        # token tables / step counters must not poison the float reductions
        grads = {"a": jnp.ones((3,)), "steps": jnp.zeros((2,), jnp.int32)}
        out = health.graph_monitors(
            jnp.float32(0.0), grads, grads, grads,
            jnp.zeros((1, 2), jnp.int32),
        )
        assert float(out["health/nonfinite_grads"]) == 0.0

    def test_layer_grad_rms_attributes_the_bad_layer(self):
        L = 4
        layers = {"w": jnp.ones((L, 8, 8)), "b": jnp.zeros((L, 8))}
        grads = {"layers": layers, "lm_head": jnp.ones((8, 8))}
        rms = health.layer_grad_rms(grads)
        assert rms.shape == (L,)
        # poison layer 2: its RMS blows up, the others stay put
        bad = {"layers": {"w": layers["w"].at[2].set(100.0), "b": layers["b"]},
               "lm_head": grads["lm_head"]}
        rms_bad = np.asarray(health.layer_grad_rms(bad))
        assert int(np.argmax(rms_bad)) == 2
        assert rms_bad[2] > 10 * rms_bad[1]
        assert health.layer_grad_rms({"lm_head": jnp.ones((4,))}) is None

    def test_batch_fingerprint_semantics(self):
        a = jnp.arange(32, dtype=jnp.int32).reshape(2, 16)
        b = a[::-1]  # same tokens, permuted rows
        fa = int(health.batch_fingerprint(a))
        assert fa == int(health.batch_fingerprint(a))  # deterministic
        assert fa != int(health.batch_fingerprint(b))  # position-weighted
        assert fa != int(health.batch_fingerprint(a + 1))

    def test_decode_monitors_per_slot(self):
        V = 64
        logits = np.zeros((3, V), np.float32)
        logits[1, 5] = np.nan
        logits[2, 7] = 1000.0  # collapsed one-hot distribution
        out = jax.jit(health.decode_monitors)(jnp.asarray(logits))
        nf = np.asarray(out["logits_nonfinite"])
        ent = np.asarray(out["entropy"])
        assert nf.tolist() == [0.0, 1.0, 0.0]
        assert abs(ent[0] - np.log(V)) < 1e-3  # uniform row: ln V nats
        assert ent[2] < 1e-3                   # one-hot row: ~0


# --- the rule engine ----------------------------------------------------------


class TestRuleEngine:
    def test_nonfinite_trips_dumps_bundle_and_verdict(self, tmp_path):
        s = make_sentinel(tmp_path)
        feed(s, [
            train_sample(1, 2.0, nonfinite_grads=0.0),
            train_sample(2, float("nan"), nonfinite_loss=1.0,
                         nonfinite_grads=3.0, batch_fingerprint=77.0),
        ])
        assert s.verdict == "tripped"
        assert s.trip_counts() == {"nonfinite": 1}
        files = health.forensics_files(str(tmp_path))
        assert files == ["worker_0_user_a0_nonfinite_step2.trip.json"]
        with open(tmp_path / "health" / files[0]) as f:
            bundle = json.load(f)
        assert bundle["rule"] == "nonfinite"
        assert bundle["step"] == 2
        assert bundle["detail"]["counts"]["nonfinite_grads"] == 3.0
        # the last-k ring carries the trajectory INTO the bad step
        assert [r["step"] for r in bundle["ring"]] == [1, 2]
        assert bundle["batch"]["stream_step"] == 2
        verdicts = health.read_verdicts(str(tmp_path))
        assert verdicts["worker_0_user_a0"]["verdict"] == "tripped"
        assert "nonfinite" in verdicts["worker_0_user_a0"]["rules"]

    def test_trips_latch_one_bundle_per_cause(self, tmp_path):
        s = make_sentinel(tmp_path)
        feed(s, [train_sample(i, float("nan")) for i in range(1, 6)])
        assert s.trip_counts() == {"nonfinite": 5}  # counted...
        assert len(health.forensics_files(str(tmp_path))) == 1  # ...one bundle

    def test_loss_spike_z_score(self, tmp_path):
        s = make_sentinel(tmp_path, HealthRules(min_samples=8, loss_spike_z=8.0))
        stable = [train_sample(i, 2.0 + 0.01 * (i % 3)) for i in range(1, 20)]
        feed(s, stable)
        assert s.verdict == "healthy"
        feed(s, [train_sample(20, 50.0)])
        assert s.trip_counts() == {"loss_spike": 1}
        detail = s.summary()["detail"]["loss_spike"]
        assert detail["z"] > 8.0 and detail["loss"] == 50.0

    def test_grad_explosion_and_collapse(self, tmp_path):
        s = make_sentinel(tmp_path, HealthRules(grad_explode=100.0))
        feed(s, [train_sample(1, 2.0, grad_norm=1e6)])
        assert "grad_explosion" in s.trip_counts()
        s2 = make_sentinel(tmp_path, HealthRules(collapse_k=3))
        feed(s2, [train_sample(i, 2.0, grad_norm=0.0) for i in range(1, 3)])
        assert "grad_collapse" not in s2.trip_counts()  # needs k consecutive
        feed(s2, [train_sample(3, 2.0, grad_norm=0.0)])
        assert "grad_collapse" in s2.trip_counts()

    def test_stagnation_needs_a_full_flat_window(self, tmp_path):
        s = make_sentinel(tmp_path, HealthRules(window=8))
        feed(s, [train_sample(i, 3.0) for i in range(1, 8)])
        assert s.verdict == "healthy"  # window not yet full
        feed(s, [train_sample(i, 3.0) for i in range(8, 12)])
        assert "stagnation" in s.trip_counts()
        # a moving loss never stagnates
        s2 = make_sentinel(tmp_path, HealthRules(window=8))
        feed(s2, [train_sample(i, 3.0 - 0.01 * i) for i in range(1, 30)])
        assert s2.verdict == "healthy"

    def test_repeated_batch_fingerprint(self, tmp_path):
        s = make_sentinel(tmp_path, HealthRules(repeat_k=3))
        feed(s, [
            train_sample(1, 2.0, batch_fingerprint=11.0),
            train_sample(2, 2.0, batch_fingerprint=22.0),
            train_sample(3, 2.0, batch_fingerprint=22.0),
        ])
        assert s.verdict == "healthy"  # only 2 consecutive
        feed(s, [train_sample(4, 2.0, batch_fingerprint=22.0)])
        assert "repeated_batch" in s.trip_counts()
        assert s.summary()["detail"]["repeated_batch"]["consecutive"] == 3

    def test_step_rewind_resets_rolling_windows(self, tmp_path):
        """A second run re-entering the process (bench sweeps) must not be
        z-scored against the previous run's loss trajectory — and its
        forensics bundle must carry only ITS OWN trajectory, not the
        previous run's ring tail or per-layer snapshot."""
        s = make_sentinel(tmp_path, HealthRules(min_samples=8))
        feed(s, [train_sample(i, 100.0, layer_grad_rms=[9.0, 9.0])
                 for i in range(1, 20)])
        # new run starts at step 1 with a completely different loss scale
        feed(s, [train_sample(i, 2.0 + 0.01 * i) for i in range(1, 4)])
        assert "loss_spike" not in s.trip_counts()
        feed(s, [train_sample(4, float("nan"))])
        name = health.forensics_files(str(tmp_path))[0]
        with open(tmp_path / "health" / name) as f:
            bundle = json.load(f)
        # ring holds run 2's steps only; run 1's layer snapshot is gone
        assert [r["step"] for r in bundle["ring"]] == [1, 2, 3, 4]
        assert bundle["layer_grad_rms"] is None

    def test_partial_metrics_without_loss_never_trip_nonfinite(self, tmp_path):
        """Absence is not NaN: a custom step loop sampling only a subset
        of metrics (no 'loss'/'grad_norm' keys) must not latch a tripped
        verdict on data it simply did not report."""
        s = make_sentinel(tmp_path)
        feed(s, [{"metrics": {"step": i}} for i in range(1, 6)])
        feed(s, [{"metrics": {}}])
        assert s.verdict == "healthy"
        # a PRESENT NaN still trips
        feed(s, [{"metrics": {"step": 7, "grad_norm": float("nan")}}])
        assert "nonfinite" in s.trip_counts()

    def test_checkpoint_pointer_lands_in_bundle(self, tmp_path):
        ckpt = tmp_path / "ckpt"
        (ckpt / "4").mkdir(parents=True)
        (ckpt / "8").mkdir()
        s = make_sentinel(tmp_path, checkpoint_dir=str(ckpt))
        feed(s, [train_sample(1, float("nan"))])
        name = health.forensics_files(str(tmp_path))[0]
        with open(tmp_path / "health" / name) as f:
            bundle = json.load(f)
        assert bundle["checkpoint"] == {"dir": str(ckpt), "latest_step": 8}

    def test_registry_carries_trips_and_verdict(self, tmp_path):
        from tony_tpu.obs.registry import Registry

        live = Registry()
        s = make_sentinel(tmp_path, registry=live)
        feed(s, [train_sample(1, float("nan"))])
        assert live.counter("tony_health_trips_total", rule="nonfinite").value == 1
        assert live.gauge("tony_health_verdict").value == 1.0
        run = Registry()
        s.export(run)
        assert run.counter("tony_health_trips_total", rule="nonfinite").value == 1


# --- serve-side rules ---------------------------------------------------------


class TestServeRules:
    def test_logits_nonfinite_attributes_the_request(self, tmp_path):
        s = make_sentinel(tmp_path)
        feed(s, [{
            "metrics": {"logits_nonfinite": [0.0, 4.0], "entropy": [3.0, 3.0]},
            "slot_rids": [7, 9], "live_slots": [0, 1],
        }])
        assert s.trip_counts() == {"serve_nonfinite": 1}
        detail = s.summary()["detail"]["serve_nonfinite"]
        assert detail["rid"] == 9 and detail["slot"] == 1

    def test_dead_slot_garbage_never_trips(self, tmp_path):
        s = make_sentinel(tmp_path)
        feed(s, [{
            "metrics": {"logits_nonfinite": [0.0, 99.0], "entropy": [3.0, 0.0]},
            "slot_rids": [3, None], "live_slots": [0],  # slot 1 is free
        }])
        assert s.verdict == "healthy"

    def test_entropy_floor_needs_consecutive_low_samples(self, tmp_path):
        s = make_sentinel(tmp_path, HealthRules(entropy_k=3, entropy_floor=0.05))
        low = {"metrics": {"logits_nonfinite": [0.0], "entropy": [0.001]},
               "slot_rids": [5], "live_slots": [0]}
        ok = {"metrics": {"logits_nonfinite": [0.0], "entropy": [4.0]},
              "slot_rids": [5], "live_slots": [0]}
        feed(s, [low, low, ok, low, low])
        assert s.verdict == "healthy"  # the recovery reset the run
        feed(s, [low])
        assert "entropy_floor" in s.trip_counts()
        assert s.summary()["detail"]["entropy_floor"]["rid"] == 5

    def test_engine_nonfinite_logits_trip_end_to_end(self, tmp_path, monkeypatch):
        """The wired path: a NaN'd model serving real requests trips the
        sentinel from inside the jitted decode step's fused monitors, with
        the offending request attributed, and close() reports it."""
        from tony_tpu.models.llama import LlamaConfig, init_params
        from tony_tpu.serve import Engine, Request, ServeConfig

        monkeypatch.setenv("TONY_APP_DIR", str(tmp_path))
        s = make_sentinel(tmp_path)
        cfg = LlamaConfig.tiny()
        params = dict(init_params(jax.random.key(0), cfg))
        params["final_norm"] = params["final_norm"] * jnp.nan
        eng = Engine(params, cfg, ServeConfig(slots=2, max_len=32, kv_block=8))
        eng.run([Request(prompt=np.arange(1, 5), max_new_tokens=4)])
        summary = eng.close()
        assert summary["health_verdict"] == "tripped"
        assert "serve_nonfinite" in summary["health_trips"]
        assert s.summary()["detail"]["serve_nonfinite"]["rid"] == 0
        assert health.forensics_files(str(tmp_path))

    def test_engine_degenerate_sampler_trips_entropy_floor(self, tmp_path):
        """A collapsed output distribution (one-hot logits — the repetition
        -loop signature) trips the entropy-floor detector after k steps."""
        from tony_tpu.models.llama import LlamaConfig, init_params
        from tony_tpu.serve import Engine, Request, ServeConfig

        s = make_sentinel(tmp_path, HealthRules(entropy_k=3))
        cfg = LlamaConfig.tiny()
        params = dict(init_params(jax.random.key(0), cfg))
        lm = np.zeros(params["lm_head"].shape, np.float32)
        lm[:, 7] = 100.0
        params["lm_head"] = jnp.asarray(lm)
        eng = Engine(params, cfg, ServeConfig(slots=2, max_len=64, kv_block=8))
        eng.run([Request(prompt=np.arange(1, 5), max_new_tokens=16)])
        eng.close()
        assert "entropy_floor" in s.trip_counts()
        assert s.summary()["detail"]["entropy_floor"]["rid"] == 0

    def test_disarmed_engine_compiles_no_monitors(self, monkeypatch):
        """With the sentinel disabled the decode step returns an empty
        monitor dict — the monitors are a compile-time choice, not a
        masked cost (the engine arms itself from env by default)."""
        from tony_tpu.models.llama import LlamaConfig, init_params
        from tony_tpu.serve import Engine, Request, ServeConfig

        monkeypatch.setenv(health.ENV_ENABLED, "0")
        cfg = LlamaConfig.tiny()
        params = init_params(jax.random.key(0), cfg)
        eng = Engine(params, cfg, ServeConfig(slots=2, max_len=32, kv_block=8))
        assert eng._monitors is False
        out = eng._decode_impl(params, eng.cache, eng._table_dev, eng.state)
        assert out[3] == {}


# --- fit() integration --------------------------------------------------------


class TestFitIntegration:
    def _fit(self, steps=12, **kw):
        from tony_tpu.models.llama import LlamaConfig
        from tony_tpu.parallel.mesh import MeshShape
        from tony_tpu.train import DataConfig, FitConfig, fit

        return fit(FitConfig(
            model=LlamaConfig.tiny(),
            data=DataConfig(global_batch=4, seq_len=32, vocab_size=256),
            mesh_shape=MeshShape(fsdp=2),
            steps=steps, log_every=steps, warmup_steps=2, **kw,
        ))

    def test_injected_nan_trips_and_instant_sits_between_step_spans(
        self, tmp_path, monkeypatch
    ):
        """The acceptance path in-process: TONY_CHAOS_NAN_STEP poisons the
        loss from step 5, the sentinel trips `nonfinite`, the forensics
        bundle + verdict land under <app_dir>/health/, fit()'s final report
        carries the verdict, and the health.nonfinite trace instant sits
        between the train.step spans it interrupted."""
        monkeypatch.setenv("TONY_CHAOS_NAN_STEP", "5")
        monkeypatch.setenv("TONY_APP_DIR", str(tmp_path))
        monkeypatch.setenv("TONY_TRACE_PROC", "worker_0_user_a0")
        make_sentinel(tmp_path)
        tracer = trace.install(trace.Tracer(
            str(tmp_path / "trace" / "worker_0_user_a0.jsonl"),
            "worker_0_user_a0", "healthtrace", sample_steps=1,
        ))
        try:
            final = self._fit(steps=12)
        finally:
            trace.uninstall()
        assert final["health_verdict"] == "tripped"
        assert final["health_trips"] == {"nonfinite": pytest.approx(8, abs=4)}
        files = health.forensics_files(str(tmp_path))
        assert files == ["worker_0_user_a0_nonfinite_step5.trip.json"]
        with open(tmp_path / "health" / files[0]) as f:
            bundle = json.load(f)
        assert bundle["rule"] == "nonfinite"
        assert bundle["step"] == 5
        assert bundle["layer_grad_rms"]  # per-layer stats rode along
        assert [r["step"] for r in bundle["ring"]] == list(range(1, 6))
        # tony_health_* reached the job-history metrics snapshot
        with open(tmp_path / "metrics" / "worker_0_user_a0_fit.json") as f:
            snap = json.load(f)
        by_name = {(m["name"], tuple(sorted(m["labels"].items()))): m
                   for m in snap["metrics"]}
        assert by_name[("tony_health_verdict", ())]["value"] == 1.0
        # the instant sits between the step spans it interrupted
        recs = [json.loads(l) for l in
                open(tmp_path / "trace" / "worker_0_user_a0.jsonl")
                if l.strip()]
        instants = [r for r in recs
                    if r.get("ph") == "i" and r["name"] == "health.nonfinite"]
        assert len(instants) == 1 and instants[0]["args"]["step"] == 5
        steps = sorted(
            (r for r in recs
             if r.get("ph") == "X" and r["name"] == "train.step"),
            key=lambda r: r["ts"],
        )
        ts = instants[0]["ts"]
        assert steps[0]["ts"] < ts < steps[-1]["ts"] + steps[-1]["dur"]

    def test_clean_fit_reports_healthy(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TONY_APP_DIR", str(tmp_path))
        monkeypatch.setenv("TONY_TRACE_PROC", "worker_0_user_a0")
        make_sentinel(tmp_path)
        final = self._fit(steps=8)
        assert final["health_verdict"] == "healthy"
        assert "health_trips" not in final
        assert health.forensics_files(str(tmp_path)) == []
        verdicts = health.read_verdicts(str(tmp_path))
        assert verdicts["worker_0_user_a0"]["verdict"] == "healthy"

    @pytest.mark.slow  # pays a full fit to assert an absent report key; the
    # disarmed-compiles-nothing contract is covered by the serve-side
    # disarmed test and install_from_env is asserted inline (870s budget)
    def test_health_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv(health.ENV_ENABLED, "0")
        assert health.install_from_env() is None
        final = self._fit(steps=4)
        assert "health_verdict" not in final


# --- portal /healthz + drop counter -------------------------------------------


class TestPortal:
    def _mk_app(self, root, app_id, verdict=None, status="SUCCEEDED"):
        app = root / app_id
        app.mkdir(parents=True, exist_ok=True)
        (app / "status.json").write_text(json.dumps(
            {"state": status, "exit_code": 0 if status == "SUCCEEDED" else 1,
             "tasks": []}
        ))
        if verdict is not None:
            (app / "health").mkdir(exist_ok=True)
            (app / "health" / "verdict_worker_0.json").write_text(json.dumps({
                "verdict": verdict, "proc": "worker_0",
                "rules": {"nonfinite": {"trips": 2, "step": 5}}
                if verdict == "tripped" else {},
            }))
            if verdict == "tripped":
                (app / "health" / "worker_0_nonfinite_step5.trip.json"
                 ).write_text("{}")
        return app

    def test_healthz_endpoints(self, tmp_path):
        from tony_tpu.obs.portal import serve_portal

        self._mk_app(tmp_path, "app-ok", verdict="healthy")
        self._mk_app(tmp_path, "app-bad", verdict="tripped")
        self._mk_app(tmp_path, "app-old")  # predates the sentinel
        server, port = serve_portal(str(tmp_path), port=0, host="127.0.0.1")
        import threading

        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz"
            ) as r:
                fleet = json.loads(r.read())
            assert fleet["app-ok"]["verdict"] == "healthy"
            assert fleet["app-bad"]["verdict"] == "tripped"
            assert fleet["app-bad"]["rules"] == {"nonfinite": 2}
            assert fleet["app-old"]["verdict"] == "unknown"
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz/app-ok"
            ) as r:
                assert json.loads(r.read())["verdict"] == "healthy"
            # a tripped app answers 503: probe-friendly without parsing
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz/app-bad"
                )
            assert exc.value.code == 503
            detail = json.loads(exc.value.read())
            assert detail["bundles"] == ["worker_0_nonfinite_step5.trip.json"]
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz/no-such-app"
                )
        finally:
            server.shutdown()

    def test_nonfinite_metric_drops_are_counted_not_hidden(self, tmp_path):
        """The satellite fix: the chart filter still excludes NaN/Inf from
        polylines (they'd poison the min/max) but every drop lands in the
        tony_portal_nonfinite_dropped counter on /metrics — counted once
        per distinct sample, not once per page render (an auto-refreshing
        dashboard must not inflate the counter)."""
        from tony_tpu.obs.portal import PortalData, _metric_series

        app = self._mk_app(tmp_path, "app-nan")
        (app / "events").mkdir()
        (app / "events" / "e.jhist.jsonl").write_text(
            json.dumps({"type": "METRICS", "task": "worker:0",
                        "samples": {"loss": 1.5, "mfu": 0.4}}) + "\n"
            + json.dumps({"type": "METRICS", "task": "worker:0",
                          "samples": {"loss": float("nan"),
                                      "mfu": float("inf")}}) + "\n"
            + json.dumps({"type": "METRICS", "task": "worker:0",
                          "samples": {"loss": 1.7, "mfu": 0.41}}) + "\n"
        )
        data = PortalData(str(tmp_path))
        detail = data.job("app-nan")
        series = _metric_series(detail["events"])
        # finite values still chart; the poisoned sample is excluded
        assert series["worker:0"]["loss"] == [1.5, 1.7]
        assert data.nonfinite_dropped.value == 2.0
        # re-rendering the same page counts nothing new...
        data.job("app-nan")
        data.job("app-nan")
        assert data.nonfinite_dropped.value == 2.0
        # ...a genuinely new poisoned sample does
        with open(app / "events" / "e.jhist.jsonl", "a") as f:
            f.write(json.dumps({"type": "METRICS", "task": "worker:0",
                                "samples": {"loss": float("-inf")}}) + "\n")
        data.job("app-nan")
        assert data.nonfinite_dropped.value == 3.0
        assert "tony_portal_nonfinite_dropped" in data.prometheus()


# --- tony health CLI ----------------------------------------------------------


class TestCli:
    def test_rollup_exit_codes_and_bundles(self, tmp_path, capsys):
        from tony_tpu.cli.main import main

        app = tmp_path / "app-h"
        (app / "health").mkdir(parents=True)
        (app / "health" / "verdict_worker_0.json").write_text(json.dumps({
            "verdict": "tripped", "proc": "worker_0",
            "rules": {"loss_spike": {"trips": 1, "step": 9, "z": 11.2}},
        }))
        (app / "health" / "worker_0_loss_spike_step9.trip.json").write_text(
            json.dumps({"rule": "loss_spike", "step": 9, "ring": []})
        )
        assert main(["health", str(app), "--bundles"]) == 1  # tripped
        out = json.loads(capsys.readouterr().out)
        assert out["verdict"] == "tripped"
        assert out["rules"] == {"loss_spike": 1}
        assert out["bundle_contents"][
            "worker_0_loss_spike_step9.trip.json"]["step"] == 9
        # healthy app: exit 0
        (app / "health" / "verdict_worker_0.json").write_text(json.dumps({
            "verdict": "healthy", "proc": "worker_0", "rules": {},
        }))
        os.remove(app / "health" / "worker_0_loss_spike_step9.trip.json")
        assert main(["health", str(app)]) == 0
        # no health data at all: exit 2, absence is not read as healthy
        bare = tmp_path / "app-bare"
        bare.mkdir()
        assert main(["health", str(bare)]) == 2


# --- chaos invariant: tripped verdicts cannot report clean --------------------


class TestInvariant:
    def _mk_terminal_app(self, tmp_path, state="SUCCEEDED", verdict=None):
        from tony_tpu.am.events import EventType

        app = tmp_path / "app-inv"
        (app / "events").mkdir(parents=True)
        code = 0 if state == "SUCCEEDED" else 1
        (app / "status.json").write_text(json.dumps(
            {"state": state, "exit_code": code, "tasks": []}
        ))
        (app / "events" / "a.jhist.jsonl").write_text(json.dumps(
            {"type": EventType.APPLICATION_FINISHED, "state": state}
        ) + "\n")
        if verdict is not None:
            (app / "health").mkdir()
            (app / "health" / "verdict_worker_0.json").write_text(json.dumps({
                "verdict": verdict, "proc": "worker_0",
                "rules": {"nonfinite": {"trips": 3}}
                if verdict == "tripped" else {},
            }))
        return app

    def test_succeeded_with_tripped_verdict_is_a_violation(self, tmp_path):
        from tony_tpu.chaos.invariants import check_invariants

        app = self._mk_terminal_app(tmp_path, "SUCCEEDED", verdict="tripped")
        report = check_invariants(str(app))
        assert not report.ok
        v = [x for x in report.violations
             if x.invariant == "health-verdict-surfaced"]
        assert len(v) == 1
        assert "silently ruined" in v[0].detail
        assert "nonfinite" in v[0].detail

    def test_died_with_tripped_verdict_is_a_violation(self, tmp_path):
        from tony_tpu.chaos.invariants import check_invariants

        app = self._mk_terminal_app(tmp_path, "FAILED", verdict="tripped")
        report = check_invariants(str(app))
        assert any(
            x.invariant == "health-verdict-surfaced" for x in report.violations
        )

    def test_healthy_verdict_stays_clean(self, tmp_path):
        from tony_tpu.chaos.invariants import check_invariants

        app = self._mk_terminal_app(tmp_path, "SUCCEEDED", verdict="healthy")
        report = check_invariants(str(app))
        assert report.ok, report.to_json()


# --- end-to-end: chaos-style NaN-injection job --------------------------------


def test_health_chaos_job_end_to_end(tmp_path):
    """Tier-1 acceptance: a REAL client -> AM -> executor job runs fit()
    with a NaN injected at step 5 (the numerics chaos seam rides the
    worker env exactly like a chaos fault schedule). Default sampling
    strides prove the trip lands within one stride; the forensics bundle
    is parseable from the app dir; `tony health` rolls the verdict up;
    the invariant checker refuses to report the run clean; and the merged
    trace carries the health instant between the step spans."""
    from tony_tpu.chaos.invariants import check_invariants
    from tony_tpu.cli.client import TonyClient
    from tony_tpu.cli.main import main as cli_main
    from tony_tpu.config.config import TonyConfig

    src = tmp_path / "src"
    src.mkdir()
    (src / "train.py").write_text(
        "import logging\n"
        "logging.basicConfig(level=logging.INFO)\n"
        "from tony_tpu.train import fit, FitConfig\n"
        "from tony_tpu.train.data import DataConfig\n"
        "from tony_tpu.models.llama import LlamaConfig\n"
        "out = fit(FitConfig(\n"
        "    model=LlamaConfig.tiny(),\n"
        "    data=DataConfig(global_batch=8, seq_len=32, vocab_size=128),\n"
        "    steps=24, log_every=8, warmup_steps=2))\n"
        "print('HEALTH VERDICT', out.get('health_verdict'))\n"
    )
    cfg = TonyConfig.load(overrides={
        "task.heartbeat_interval_ms": 200,
        "task.max_missed_heartbeats": 10,
        "application.timeout_s": 240,
        "application.stage_dir": str(tmp_path),
        "application.name": "nan-chaos",
        "application.framework": "jax",
        "job.worker.instances": 1,
        "job.worker.command": f"{sys.executable} train.py",
        # the numerics fault + every-step trace spans so the instant's
        # position between steps is assertable; health knobs stay DEFAULT
        # (obs.health.sample_steps=16) — the injected NaN at step 5 must
        # trip by sample step 16, i.e. within one sampling stride
        "job.worker.env": [
            "JAX_PLATFORMS=cpu", "TONY_CHAOS_NAN_STEP=5",
        ],
        "trace.sample_steps": 1,
    })
    client = TonyClient(cfg, src_dir=str(src))
    code = client.run(quiet=True)
    app_dir = client.app_dir
    if code != 0:
        logs_dir = os.path.join(app_dir, "logs")
        for n in sorted(os.listdir(logs_dir)):
            print(f"===== {n}", open(os.path.join(logs_dir, n),
                                     errors="replace").read()[-2000:])
    assert code == 0  # the job "succeeds" — that IS the silent-ruin case

    # the bundle landed and parses
    bundles = health.forensics_files(app_dir)
    assert len(bundles) == 1 and "nonfinite" in bundles[0]
    with open(os.path.join(app_dir, "health", bundles[0])) as f:
        bundle = json.load(f)
    assert bundle["rule"] == "nonfinite"
    # default stride 16: the step-16 sample sees the step-5 NaN — the trip
    # lands within one sampling stride of the first sampled bad step
    assert 5 <= bundle["step"] <= 16
    assert bundle["ring"]  # the trajectory into the trip rode along

    # the verdict reaches `tony health` (exit 1 = tripped)
    assert cli_main(["health", app_dir]) == 1

    # the invariant checker refuses to report this run clean
    report = check_invariants(app_dir)
    assert any(
        v.invariant == "health-verdict-surfaced" for v in report.violations
    ), report.to_json()

    # the health instant sits between the step spans it interrupted in
    # the worker's journal
    trace_dir = os.path.join(app_dir, "trace")
    worker = [n for n in os.listdir(trace_dir) if n.startswith("worker_0")]
    recs = []
    for name in worker:
        with open(os.path.join(trace_dir, name)) as f:
            recs += [json.loads(l) for l in f if l.strip()]
    instants = [r for r in recs
                if r.get("ph") == "i" and r["name"] == "health.nonfinite"]
    assert len(instants) == 1
    steps = sorted(
        (r for r in recs if r.get("ph") == "X" and r["name"] == "train.step"),
        key=lambda r: r["ts"],
    )
    ts = instants[0]["ts"]
    assert steps[0]["ts"] < ts < steps[-1]["ts"] + steps[-1]["dur"]

"""Model + trainer tests on the 8-device virtual CPU mesh (see conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import llama
from tony_tpu.parallel.mesh import MeshShape, build_mesh
from tony_tpu.train import trainer


@pytest.fixture(scope="module")
def tiny():
    return llama.LlamaConfig.tiny()


@pytest.fixture(scope="module")
def tiny_params(tiny):
    return llama.init_params(jax.random.key(0), tiny)


def test_devices_are_virtual_cpu():
    assert len(jax.devices()) == 8


def test_forward_shape_and_dtype(tiny, tiny_params):
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = llama.forward(tiny_params, tokens, tiny)
    assert logits.shape == (2, 16, tiny.vocab_size)
    assert logits.dtype == jnp.float32


def test_param_count_matches_config(tiny, tiny_params):
    counted = sum(x.size for x in jax.tree.leaves(tiny_params))
    assert counted == tiny.n_params


def test_logical_axes_tree_matches_params(tiny, tiny_params):
    axes = llama.logical_axes(tiny)
    p_struct = jax.tree.structure(tiny_params)
    a_struct = jax.tree.structure(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert p_struct == a_struct
    # every axes tuple has one name per array dim
    for arr, ax in zip(
        jax.tree.leaves(tiny_params),
        jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple)),
    ):
        assert arr.ndim == len(ax)


def test_causality(tiny, tiny_params):
    """Changing a future token must not change past logits."""
    t1 = jnp.zeros((1, 16), jnp.int32)
    t2 = t1.at[0, 10].set(7)
    l1 = llama.forward(tiny_params, t1, tiny)
    l2 = llama.forward(tiny_params, t2, tiny)
    np.testing.assert_allclose(l1[0, :10], l2[0, :10], atol=1e-5)
    assert not np.allclose(l1[0, 10:], l2[0, 10:], atol=1e-5)


def test_gqa_matches_mha_with_duplicated_kv_weights():
    """GQA with kv-head weights duplicated per group must equal full MHA."""
    import dataclasses

    gqa_cfg = llama.LlamaConfig.tiny()  # n_heads=4, n_kv_heads=2
    mha_cfg = dataclasses.replace(gqa_cfg, n_kv_heads=gqa_cfg.n_heads)
    rep = gqa_cfg.n_heads // gqa_cfg.n_kv_heads
    hd = gqa_cfg.head_dim

    gqa_params = llama.init_params(jax.random.key(1), gqa_cfg)
    mha_params = jax.tree.map(lambda x: x, gqa_params)
    for w in ("wk", "wv"):
        g = gqa_params["layers"][w]  # [L, dim, n_kv*hd]
        L, d, _ = g.shape
        # duplicate each kv head `rep` times along the head axis
        expanded = jnp.repeat(g.reshape(L, d, gqa_cfg.n_kv_heads, hd), rep, axis=2)
        mha_params["layers"][w] = expanded.reshape(L, d, mha_cfg.n_kv_heads * hd)

    tokens = jax.random.randint(jax.random.key(2), (2, 16), 0, gqa_cfg.vocab_size)
    out_gqa = llama.forward(gqa_params, tokens, gqa_cfg)
    out_mha = llama.forward(mha_params, tokens, mha_cfg)
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_mha), atol=1e-4)


def test_rope_rotation_preserves_norm(tiny):
    cos, sin = llama.rope_table(tiny, 8)
    x = jax.random.normal(jax.random.key(0), (1, 8, 2, tiny.head_dim))
    y = llama.apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(y), axis=-1),
        rtol=1e-5,
    )


@pytest.mark.parametrize(
    "shape",
    [
        # the single-axis / two-axis shapes are slow-marked: each full fit
        # costs ~6s and their axes are exercised by the 3-axis shapes here
        # plus the sharding/overlap suites (tier-1 runs close to its 870s
        # timeout)
        MeshShape(dp=2, fsdp=2, tp=2),
        pytest.param(MeshShape(fsdp=8), marks=pytest.mark.slow),
        pytest.param(MeshShape(dp=4, tp=2), marks=pytest.mark.slow),
        MeshShape(fsdp=2, tp=2, sp=2),
    ],
)
def test_train_loss_decreases_on_mesh(shape, tiny):
    """The keystone model test: sharded init + jitted step on a real mesh;
    loss must fall on a memorisable batch. Exercises DP grad-psum, FSDP
    param sharding, and TP activation collectives depending on shape."""
    mesh = build_mesh(shape)
    opt = trainer.default_optimizer(lr=1e-2, warmup_steps=1, decay_steps=100)
    state = trainer.make_train_state(jax.random.key(0), tiny, mesh, opt)
    step = trainer.make_train_step(tiny, mesh, opt)
    tokens = jax.random.randint(jax.random.key(1), (8, 33), 0, tiny.vocab_size)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    losses = []
    for _ in range(8):
        state, metrics = step(state, inputs, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses
    assert int(jax.device_get(state.step)) == 8


def test_sharded_state_actually_sharded(tiny):
    mesh = build_mesh(MeshShape(fsdp=4, tp=2))
    opt = trainer.default_optimizer()
    state = trainer.make_train_state(jax.random.key(0), tiny, mesh, opt)
    w1 = state.params["layers"]["w1"]  # ("layers","embed","ffn") -> (None,fsdp,tp)
    assert len(w1.sharding.device_set) == 8
    # each shard holds 1/8 of the array
    assert w1.addressable_shards[0].data.size == w1.size // 8


def test_opt_state_sharding_matches_params_when_shapes_collide():
    """Params with identical shapes but different specs (wq vs wo when
    n_heads*head_dim == dim) must each get their own sharding for Adam
    moments -- a shape-based match would transpose one of them."""
    import dataclasses

    cfg = dataclasses.replace(
        llama.LlamaConfig.tiny(), n_heads=4, n_kv_heads=4, dim=64
    )  # wq and wo both (L, 64, 64)
    mesh = build_mesh(MeshShape(fsdp=4, tp=2))
    opt = trainer.default_optimizer()
    shardings = trainer.state_shardings(cfg, mesh, opt)
    p = shardings.params["layers"]
    assert p["wq"].spec != p["wo"].spec  # sanity: they differ
    mu = None
    for leaf in jax.tree.leaves(
        shardings.opt_state, is_leaf=lambda x: isinstance(x, dict)
    ):
        if isinstance(leaf, dict) and "layers" in leaf:
            mu = leaf
            break
    assert mu is not None
    assert mu["layers"]["wq"].spec == p["wq"].spec
    assert mu["layers"]["wo"].spec == p["wo"].spec


def test_unimplemented_attention_impl_raises_clearly():
    import dataclasses

    cfg = dataclasses.replace(llama.LlamaConfig.tiny(), attention_impl="nope")
    params = llama.init_params(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="nope"):
        llama.forward(params, jnp.zeros((1, 8), jnp.int32), cfg)


def test_mesh_shape_validation():
    with pytest.raises(ValueError):
        build_mesh(MeshShape(dp=16))  # needs more devices than exist
    with pytest.raises(ValueError):
        MeshShape(dp=0)
    # undersized shapes truncate (with a warning) rather than raise
    assert build_mesh(MeshShape(dp=3)).size == 3


def test_train_flops_positive(tiny):
    assert llama.train_flops_per_token(tiny, 64) > 6 * tiny.n_params

"""Fused chunked cross-entropy head: parity vs the full-logits reference.

Value AND grad parity in fp32 on CPU (the pallas kernels run in interpreter
mode, see conftest), covering: vocab sizes not divisible by the chunk/tile,
chunk-size invariance, the model-level loss paths (dense vs fused), the MoE
aux term, and an ``sp``-sharded mesh run through the real train step.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import llama
from tony_tpu.ops.fused_ce import fused_ce_tokens, reference_ce_tokens
from tony_tpu.parallel.mesh import MeshShape, build_mesh
from tony_tpu.train import trainer

B, S, D, V = 2, 24, 32, 100  # V deliberately not a multiple of any tile below


@pytest.fixture(scope="module")
def hwt():
    ks = jax.random.split(jax.random.key(0), 3)
    h = jax.random.normal(ks[0], (B, S, D), jnp.float32)
    w = jax.random.normal(ks[1], (D, V), jnp.float32) * 0.1
    t = jax.random.randint(ks[2], (B, S), 0, V)
    return h, w, t


IMPLS = [
    ("scan", dict(vocab_chunk=32)),          # 3 full chunks + tail of 4
    ("scan", dict(vocab_chunk=7)),           # ragged small chunks
    ("scan", dict(vocab_chunk=1000)),        # single chunk > V
    ("pallas", dict(block_n=32, block_v=64)),  # padded last vocab tile
    ("pallas", dict(block_n=64, block_v=128)),
]
IDS = ["scan32", "scan7", "scan1000", "pallas32x64", "pallas64x128"]


@pytest.mark.parametrize("impl,kw", IMPLS, ids=IDS)
def test_value_matches_reference(hwt, impl, kw):
    h, w, t = hwt
    ref = reference_ce_tokens(h, w, t)
    got = fused_ce_tokens(h, w, t, impl=impl, **kw)
    assert got.shape == (B, S) and got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("impl,kw", IMPLS, ids=IDS)
def test_grads_match_reference(hwt, impl, kw):
    h, w, t = hwt

    def loss_fused(h_, w_):
        return jnp.mean(fused_ce_tokens(h_, w_, t, impl=impl, **kw))

    def loss_ref(h_, w_):
        return jnp.mean(reference_ce_tokens(h_, w_, t))

    got = jax.grad(loss_fused, argnums=(0, 1))(h, w)
    ref = jax.grad(loss_ref, argnums=(0, 1))(h, w)
    for g, e, name in zip(got, ref, ("dh", "d_lm_head")):
        assert g.shape == e.shape and g.dtype == e.dtype, name
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), rtol=1e-5, atol=1e-6, err_msg=name
        )


def test_chunk_size_invariance(hwt):
    """Changing the chunk must not change the loss (nor its grads) beyond
    fp32 reduction-order noise — the acceptance bar for swapping tile sizes
    freely on different chips."""
    h, w, t = hwt

    def lg(vc):
        def loss(h_, w_):
            return jnp.mean(fused_ce_tokens(h_, w_, t, impl="scan", vocab_chunk=vc))

        l, g = jax.value_and_grad(loss, argnums=(0, 1))(h, w)
        return l, g

    l_ref, g_ref = lg(100)  # one exact chunk
    for vc in (7, 32, 64, 99):
        l, g = lg(vc)
        np.testing.assert_allclose(float(l), float(l_ref), rtol=1e-5)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )


def test_pallas_ragged_rows(hwt):
    """Rows not a multiple of block_n: the row-masked dW accumulation must
    not pick up the grid's padding rows."""
    _, w, _ = hwt
    ks = jax.random.split(jax.random.key(5), 2)
    h = jax.random.normal(ks[0], (3, 18, D), jnp.float32)  # N=54, blocks of 32
    t = jax.random.randint(ks[1], (3, 18), 0, V)

    def loss(h_, w_, impl):
        return jnp.mean(fused_ce_tokens(h_, w_, t, impl=impl,
                                        block_n=32, block_v=64, vocab_chunk=64))

    lp, gp = jax.value_and_grad(lambda a, b: loss(a, b, "pallas"), argnums=(0, 1))(h, w)
    lr = jnp.mean(reference_ce_tokens(h, w, t))
    gr = jax.grad(lambda a, b: jnp.mean(reference_ce_tokens(a, b, t)),
                  argnums=(0, 1))(h, w)
    np.testing.assert_allclose(float(lp), float(lr), rtol=1e-5)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_unknown_impl_raises(hwt):
    h, w, t = hwt
    with pytest.raises(ValueError, match="nope"):
        fused_ce_tokens(h, w, t, impl="nope")


# --- model-level loss paths ---------------------------------------------------


def _grad_err(a, b):
    errs = jax.tree.map(lambda x, y: float(jnp.max(jnp.abs(x - y))), a, b)
    return max(jax.tree.leaves(errs))


@pytest.mark.parametrize("impl", ["scan", "pallas"])
def test_loss_from_pairs_matches_dense(impl):
    """The default train loss (fused) equals the legacy dense head, value
    and grads, on the tiny fp32 model."""
    cfg = dataclasses.replace(
        llama.LlamaConfig.tiny(), ce_impl=impl, ce_vocab_chunk=100,
        ce_block_n=32, ce_block_v=128,
    )
    cfg_d = dataclasses.replace(cfg, ce_impl="dense")
    params = llama.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 17), 0, cfg.vocab_size)
    inp, tgt = toks[:, :-1], toks[:, 1:]

    lf, gf = jax.value_and_grad(llama.loss_from_pairs)(params, inp, tgt, cfg)
    ld, gd = jax.value_and_grad(llama.loss_from_pairs)(params, inp, tgt, cfg_d)
    assert abs(float(lf) - float(ld)) < 1e-5 * abs(float(ld))
    assert _grad_err(gf, gd) < 1e-5


def test_moe_aux_path_matches_dense():
    """MoE: the aux load-balancing term must ride the fused head unchanged
    (and differ from the bare CE, i.e. actually be present)."""
    cfg = llama.LlamaConfig.tiny_moe()  # fused scan default
    cfg_d = dataclasses.replace(cfg, ce_impl="dense")
    params = llama.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 17), 0, cfg.vocab_size)
    inp, tgt = toks[:, :-1], toks[:, 1:]

    lf, gf = jax.value_and_grad(llama.loss_from_pairs)(params, inp, tgt, cfg)
    ld, gd = jax.value_and_grad(llama.loss_from_pairs)(params, inp, tgt, cfg_d)
    assert abs(float(lf) - float(ld)) < 1e-5 * abs(float(ld))
    assert _grad_err(gf, gd) < 1e-5
    # the aux term is live: a bare-CE config yields a different loss
    h, aux = llama.hidden_states_with_aux(params, inp, cfg)
    bare = float(jnp.mean(llama.ce_tokens(h, params["lm_head"], tgt, cfg)))
    assert float(aux) > 0 and abs(float(lf) - bare) > 1e-9


def test_gpipe_head_matches_model_loss():
    """trainer._ce_head (shared by both pipeline schedules) must equal the
    model-level fused loss on identical inputs."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 17), 0, cfg.vocab_size)
    inp, tgt = toks[:, :-1], toks[:, 1:]
    # trunk WITHOUT the final norm (the head applies it)
    x = params["tok_emb"][inp]
    cos, sin = llama.rope_table(cfg, inp.shape[1])
    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        x, _ = llama.transformer_block(x, lp, cfg, cos, sin)
    head = trainer._ce_head(params["final_norm"], params["lm_head"], x, tgt, cfg)
    full = llama.loss_from_pairs(params, inp, tgt, cfg)
    np.testing.assert_allclose(float(head), float(full), rtol=1e-6)


def test_sp_sharded_train_step_matches_dense_head():
    """The fused head on an sp(+tp+fsdp)-sharded mesh: the real jitted train
    step's loss trajectory must match the dense head on the SAME mesh — the
    seq-axis sharding stays aligned through the chunked loss. (Same mesh on
    both sides: vocab-sharded param init is mesh-dependent on some jax
    builds, so a cross-mesh comparison would test the RNG, not the head.)"""
    cfg = llama.LlamaConfig.tiny()  # fused scan default
    toks = jax.random.randint(jax.random.key(1), (8, 33), 0, cfg.vocab_size)
    inp, tgt = toks[:, :-1], toks[:, 1:]

    def run(cfg):
        mesh = build_mesh(MeshShape(fsdp=2, tp=2, sp=2))
        opt = trainer.default_optimizer(lr=1e-2, warmup_steps=1, decay_steps=100)
        state = trainer.make_train_state(jax.random.key(0), cfg, mesh, opt)
        step = trainer.make_train_step(cfg, mesh, opt)
        losses = []
        for _ in range(4):
            state, m = step(state, inp, tgt)
            losses.append(float(m["loss"]))
        return losses

    fused = run(cfg)
    dense = run(dataclasses.replace(cfg, ce_impl="dense"))
    np.testing.assert_allclose(fused, dense, rtol=2e-4)
    assert fused[-1] < fused[0]


# --- nonfinite-input robustness (the numerics-health contract) ----------------


@pytest.mark.parametrize("impl,kw", IMPLS, ids=IDS)
def test_poisoned_rows_propagate_nonfinite_like_dense(hwt, impl, kw):
    """A NaN/Inf hidden state must PROPAGATE into that row's loss (never be
    masked away by the chunked max/logsumexp rewrites) and must not leak
    into other rows — the per-token nonfinite mask matches the dense
    reference exactly, and the finite tokens still value-match. The health
    sentinel (obs/health.py) counts nonfinite losses; a kernel that
    silently laundered a NaN would blind it."""
    h, w, t = hwt
    hp = h.at[0, 3].set(jnp.nan).at[1, 5].set(jnp.inf)
    ref = np.asarray(reference_ce_tokens(hp, w, t))
    got = np.asarray(fused_ce_tokens(hp, w, t, impl=impl, **kw))
    # the dense reference poisons exactly the poisoned rows
    assert np.argwhere(~np.isfinite(ref)).tolist() == [[0, 3], [1, 5]]
    np.testing.assert_array_equal(np.isfinite(got), np.isfinite(ref))
    finite = np.isfinite(ref)
    np.testing.assert_allclose(got[finite], ref[finite], rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize(
    "impl,kw",
    [("scan", dict(vocab_chunk=32)), ("pallas", dict(block_n=32, block_v=64))],
    ids=["scan32", "pallas32x64"],
)
def test_poisoned_weights_propagate_through_values_and_grads(hwt, impl, kw):
    """A NaN in lm_head touches every token through the logsumexp — values
    AND both grads must go nonfinite exactly like the dense reference (the
    custom_vjp bwd recomputes chunk logits; a masked recompute would
    produce a clean-looking gradient from poisoned weights)."""
    h, w, t = hwt
    wp = w.at[2, 9].set(jnp.nan)

    ref_v = np.asarray(reference_ce_tokens(h, wp, t))
    got_v = np.asarray(fused_ce_tokens(h, wp, t, impl=impl, **kw))
    assert not np.isfinite(ref_v).any()
    assert not np.isfinite(got_v).any()

    def loss_fused(h_, w_):
        return jnp.mean(fused_ce_tokens(h_, w_, t, impl=impl, **kw))

    def loss_ref(h_, w_):
        return jnp.mean(reference_ce_tokens(h_, w_, t))

    got = jax.grad(loss_fused, argnums=(0, 1))(h, wp)
    ref = jax.grad(loss_ref, argnums=(0, 1))(h, wp)
    for g, e, name in zip(got, ref, ("dh", "d_lm_head")):
        assert not np.isfinite(np.asarray(e)).any(), f"ref {name} stayed finite"
        assert not np.isfinite(np.asarray(g)).any(), (
            f"fused {name} masked the poisoned weights back to finite values"
        )

"""RemoteBackend tests: placement/inventory/labels, transport seam, release,
and the full submit -> gang -> restart E2E flow through the local transport.

The production transport is ssh; the local transport fakes only the wire, so
every backend code path here (and the AM/executor stack above it in the E2E
cases) is genuine — the same testing posture as the reference's MiniCluster
(SURVEY.md section 4).
"""

import os
import sys
import time

import pytest

from tony_tpu.cluster.backend import ContainerRequest, InsufficientResources, Resource
from tony_tpu.cluster.remote import (
    LocalTransport,
    RemoteBackend,
    SshTransport,
    make_transport,
)
from tony_tpu.cluster.tpu_vm import TpuVmBackend, chips_per_host_for


def req(name="worker", idx=0, chips=0, label="", argv=None, log_path=""):
    return ContainerRequest(
        task_type=name,
        task_index=idx,
        resource=Resource(memory_mb=64, cpus=1, tpu_chips=chips),
        argv=argv or [sys.executable, "-c", "print('hi')"],
        env={},
        log_path=log_path,
        node_label=label,
    )


def make_backend_2hosts(**kwargs):
    kwargs.setdefault("transport", LocalTransport())
    kwargs.setdefault(
        "host_capacity", Resource(memory_mb=256, cpus=4, tpu_chips=4)
    )
    b = RemoteBackend(["127.0.0.1", "localhost"], **kwargs)
    b.start()
    return b


def test_placement_fills_hosts_in_order(tmp_path):
    b = make_backend_2hosts()
    try:
        c1 = b.allocate(req(idx=0, chips=4))
        c2 = b.allocate(req(idx=1, chips=4))
        assert c1.host == "127.0.0.1"
        assert c2.host == "localhost"  # first host's chips are taken
        with pytest.raises(InsufficientResources):
            b.allocate(req(idx=2, chips=1))
    finally:
        b.stop()
    # capacity reclaimed on stop/exit
    assert b.available().tpu_chips == 8


def test_node_labels_constrain_placement():
    b = RemoteBackend(
        ["127.0.0.1", "localhost"],
        transport=LocalTransport(),
        host_capacity=Resource(256, 4, 4),
        host_labels={"localhost": "highmem"},
    )
    b.start()
    try:
        c = b.allocate(req(label="highmem"))
        assert c.host == "localhost"
        with pytest.raises(ValueError):
            b.allocate(req(label="no-such-label"))
    finally:
        b.stop()


def test_completion_callback_and_exit_code(tmp_path):
    b = make_backend_2hosts()
    done = []
    b.set_completion_callback(lambda c, code: done.append((c.container_id, code)))
    log_path = str(tmp_path / "c.log")
    c = b.allocate(
        req(argv=[sys.executable, "-c", "print('out'); raise SystemExit(7)"],
            log_path=log_path)
    )
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and not done:
        time.sleep(0.05)
    b.stop()
    assert done == [(c.container_id, 7)]
    # output streamed into the local per-container log
    assert "out" in open(log_path).read()


def test_release_kills_process_group(tmp_path):
    b = make_backend_2hosts()
    done = []
    b.set_completion_callback(lambda c, code: done.append(code))
    c = b.allocate(req(argv=[sys.executable, "-c", "import time; time.sleep(300)"]))
    assert c.pid > 0
    b.release(c.container_id)
    # released containers never fire the completion callback
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if not _pid_alive(c.pid):
            break
        time.sleep(0.05)
    assert not _pid_alive(c.pid)
    b.stop()
    assert done == []


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


def test_ssh_transport_command_shape():
    t = SshTransport()
    cmd = t._remote_command(
        ["python", "-m", "tony_tpu.executor"], {"A": "x y", "B": "1"}
    )
    # setsid group, pid echo for remote kill, env exported, argv quoted
    assert cmd.startswith("setsid sh -c 'echo $$; exec env ")
    assert "A='x y'" in cmd and "B=1" in cmd
    assert "python -m tony_tpu.executor" in cmd


def test_make_transport_names():
    assert isinstance(make_transport("local"), LocalTransport)
    assert isinstance(make_transport("ssh"), SshTransport)
    with pytest.raises(ValueError):
        make_transport("carrier-pigeon")


def test_tpu_vm_is_remote_with_discovery_glue():
    # explicit hosts: fully functional RemoteBackend with chip inventory
    b = TpuVmBackend(
        ["127.0.0.1"], accelerator_type="v5litepod-8", transport=LocalTransport()
    )
    assert b.total_capacity().tpu_chips == 8
    assert chips_per_host_for("v4-32") == 4
    # discovery path: raises with instructions (no cloud API here)
    with pytest.raises(RuntimeError, match="cluster.hosts"):
        TpuVmBackend(accelerator_type="v4-32")


# --- E2E through the AM stack (the RemoteBackend MiniCluster posture) --------


FAST = {
    "task.heartbeat_interval_ms": 200,
    "task.max_missed_heartbeats": 10,
    "application.timeout_s": 90,
    "cluster.backend": "remote",
    "cluster.hosts": "127.0.0.1,127.0.0.1",
    "cluster.remote_transport": "local",
}


def submit_remote(tmp_path, overrides, src_dir=""):
    from tony_tpu.cli.client import TonyClient
    from tony_tpu.config.config import TonyConfig

    cfg = TonyConfig.load(
        overrides={**FAST, "application.stage_dir": str(tmp_path), **overrides}
    )
    client = TonyClient(cfg, src_dir=src_dir)
    code = client.run(quiet=True)
    return code, client.app_dir


def test_e2e_remote_backend_gang(tmp_path):
    """Full submit -> gang barrier -> cluster spec -> success through the
    RemoteBackend (NM-equivalent remote-launch path, VERDICT item 1)."""
    code, app_dir = submit_remote(
        tmp_path,
        {
            "application.name": "remote-ok",
            "application.framework": "generic",
            "job.worker.instances": 2,
            "job.worker.command": (
                'python -c "import os, json; '
                "spec = json.loads(os.environ['TONY_CLUSTER_SPEC']); "
                'assert len(spec[\'worker\']) == 2"'
            ),
        },
    )
    assert code == 0
    import json as _json

    with open(os.path.join(app_dir, "status.json")) as f:
        status = _json.load(f)
    assert status["state"] == "SUCCEEDED"
    # log streaming produced local per-container logs
    logs = os.listdir(os.path.join(app_dir, "logs"))
    assert any(n.startswith("worker_0") for n in logs)


def test_e2e_remote_backend_gang_restart(tmp_path):
    """Worker crash under restart.policy=gang through the RemoteBackend:
    the whole gang is released on the remote hosts and re-launched."""
    marker = tmp_path / "attempt.marker"
    script = (
        f'python -c "import os, sys, time; p={str(marker)!r}; '
        "open(p, 'a').write('x'); time.sleep(1); "
        "sys.exit(3 if os.environ['TONY_GENERATION'] == '0' "
        "and os.environ['TONY_TASK_INDEX'] == '0' else 0)\""
    )
    code, app_dir = submit_remote(
        tmp_path,
        {
            "application.name": "remote-restart",
            "application.framework": "generic",
            "restart.policy": "gang",
            "restart.max_worker_restarts": 2,
            "job.worker.instances": 2,
            "job.worker.command": script,
        },
    )
    assert code == 0
    # both workers ran at least twice (gang restart relaunches everyone)
    assert len(open(marker).read()) >= 3


def test_fits_one_fast_fails_per_host_impossible():
    """Aggregate capacity can mask per-host impossibility: 8 chips over two
    4-chip hosts fit no 8-chip container — the scheduler must fail fast."""
    b = make_backend_2hosts()  # 2 hosts x 4 chips
    try:
        assert b.fits_one(Resource(64, 1, 4))
        assert not b.fits_one(Resource(64, 1, 8))
        from tony_tpu.am.scheduler import SchedulerHooks, TaskScheduler
        from tony_tpu.am.session import Session
        from tony_tpu.config.config import TaskTypeSpec

        spec = TaskTypeSpec(name="worker", instances=1, memory_mb=64, cpus=1,
                            tpu_chips=8, command="true")
        session = Session({"worker": spec})
        sched = TaskScheduler(
            session, b, SchedulerHooks(lambda s, i: None, lambda *a: None),
            allocation_timeout_s=30,
        )
        t0 = time.monotonic()
        with pytest.raises(InsufficientResources, match="no single host"):
            sched.schedule_all({"worker": spec})
        assert time.monotonic() - t0 < 5  # fast, not the allocation timeout
    finally:
        b.stop()


def test_e2e_remote_backend_localization(tmp_path):
    """cluster.localize: the app dir is copied per host over the transport
    (HDFS-localisation analogue) and containers run against the copy — no
    shared-FS assumption. Two distinct host aliases -> two per-host copies."""
    root = tmp_path / "localized"
    check = (
        'python -c "import os, json; '
        "d = os.environ['TONY_APP_DIR']; "
        f"assert d.startswith({str(root)!r}), d; "
        "assert os.path.isfile(os.environ['TONY_CONF_PATH']); "
        "assert os.path.isfile(os.path.join(d, 'src', 'hello.txt')); "
        'json.load(open(os.environ[\'TONY_CONF_PATH\']))"'
    )
    src = tmp_path / "src"
    src.mkdir()
    (src / "hello.txt").write_text("hi")
    # make_backend reads cluster.localize; localize_root is injected by
    # monkey-proxy: use env-free path via config? The backend computes
    # <root>/<host>/<app_id>; pin root through the backend kwarg by
    # pre-seeding make_backend via cluster config below.
    from tony_tpu.cli.client import TonyClient
    from tony_tpu.config.config import TonyConfig
    import tony_tpu.cluster.remote as remote_mod

    cfg = TonyConfig.load(overrides={
        **FAST,
        "application.stage_dir": str(tmp_path),
        "application.name": "localize",
        "application.framework": "generic",
        "cluster.hosts": "127.0.0.1,localhost",
        "cluster.localize": True,
        # placement is first-fit: oversize the ask so one worker fills a
        # host and the second spills to the other alias (forcing two copies)
        "job.worker.memory_mb": 600000,
        "job.worker.instances": 2,
        "job.worker.command": check,
    })
    old_root = None
    client = TonyClient(cfg, src_dir=str(src))
    # point the AM's backend at the scratch root via env (read by the AM
    # process through the config it inherits)
    cfg.set("cluster.localize_root", str(root))
    code = client.run(quiet=True)
    assert code == 0
    # one copy per distinct host alias
    assert sorted(os.listdir(root)) == ["127.0.0.1", "localhost"]
    for host in ("127.0.0.1", "localhost"):
        apps = os.listdir(root / host)
        assert len(apps) == 1
        assert os.path.isfile(root / host / apps[0] / "config.json")


@pytest.mark.slow
def test_e2e_remote_localized_elastic_resume(tmp_path):
    """The pod-slice production story in one test: RemoteBackend + per-host
    localization (no shared-FS assumption for the app dir) + a real fit()
    job that dies mid-training, gang-restarts, and resumes from the last
    orbax checkpoint."""
    import sys

    root = tmp_path / "localized"
    ckpt = tmp_path / "ckpt"  # checkpoints themselves stay on a shared path
    src = tmp_path / "src"
    src.mkdir()
    (src / "train.py").write_text(
        "import logging, os\n"
        "logging.basicConfig(level=logging.INFO)\n"
        "from tony_tpu.train import fit, FitConfig\n"
        "from tony_tpu.train.data import DataConfig\n"
        "from tony_tpu.models.llama import LlamaConfig\n"
        "assert os.environ['TONY_APP_DIR'].startswith(%r), os.environ['TONY_APP_DIR']\n"
        "gen = os.environ.get('TONY_GENERATION', '0')\n"
        "ck = os.environ['TONY_CHECKPOINT_DIR']\n"
        "def durable():\n"
        "    return os.path.isdir(ck) and any(d.isdigit() for d in os.listdir(ck))\n"
        "def maybe_crash(m):\n"
        "    if gen == '0' and m['step'] >= 4 and durable():\n"
        "        os._exit(1)\n"
        "out = fit(FitConfig(model=LlamaConfig.tiny(),\n"
        "    data=DataConfig(global_batch=8, seq_len=32, vocab_size=128),\n"
        "    steps=8, log_every=1, on_metrics=maybe_crash))\n"
        "print('TRAINING DONE', out)\n" % str(root)
    )
    code, app_dir = submit_remote(
        tmp_path,
        {
            "application.name": "remote-elastic",
            "application.framework": "jax",
            "application.timeout_s": 240,
            "cluster.localize": True,
            "cluster.localize_root": str(root),
            "restart.policy": "gang",
            "restart.max_worker_restarts": 2,
            "checkpoint.dir": str(ckpt),
            "checkpoint.interval_steps": 2,
            "job.worker.instances": 1,
            "job.worker.command": f"{sys.executable} train.py",
            "job.worker.env": ["JAX_PLATFORMS=cpu"],
        },
        src_dir=str(src),
    )
    logs_dir = os.path.join(app_dir, "logs")
    if code != 0:
        for n in sorted(os.listdir(logs_dir)):
            print(f"===== {n}",
                  open(os.path.join(logs_dir, n), errors="replace").read()[-2000:])
    assert code == 0
    attempt1 = [n for n in os.listdir(logs_dir) if "attempt1" in n]
    assert attempt1, os.listdir(logs_dir)
    text = open(os.path.join(logs_dir, attempt1[0]), errors="replace").read()
    assert "resumed from checkpoint step" in text
    assert "TRAINING DONE" in text
    # the localized copy was actually used (per-host dir exists with src)
    hosts = os.listdir(root)
    assert hosts
    app = os.listdir(root / hosts[0])[0]
    assert os.path.isfile(root / hosts[0] / app / "src" / "train.py")


def test_concurrent_allocate_waits_for_localization(tmp_path):
    """Two allocations racing the same (host, app): the second must block
    until the first's copy COMPLETES, never launch against a half-copied
    app dir (remote.py _localize_app in-flight event)."""
    import threading

    app = tmp_path / "app"
    app.mkdir()
    (app / "config.json").write_text("{}")
    (app / "payload.bin").write_bytes(b"x" * 64)
    root = tmp_path / "localized"

    started = threading.Event()
    release = threading.Event()

    class SlowTransport(LocalTransport):
        def localize(self, host, src_dir, dst_dir):
            started.set()
            assert release.wait(10), "test deadlock"
            super().localize(host, src_dir, dst_dir)

    b = RemoteBackend(
        ["127.0.0.1"],
        transport=SlowTransport(),
        host_capacity=Resource(memory_mb=256, cpus=4, tpu_chips=0),
        localize=True,
        localize_root=str(root),
    )
    b.start()
    env = {"TONY_APP_DIR": str(app), "TONY_APP_ID": "app-1"}
    seen = []

    def alloc(i):
        r = req(idx=i, log_path=str(tmp_path / f"c{i}.log"))
        r.env.update(env)
        c = b.allocate(r)
        # at launch time the localized copy must be complete
        dst = root / "127.0.0.1" / "app-1"
        seen.append((dst / "payload.bin").exists())
        return c

    try:
        t1 = threading.Thread(target=alloc, args=(0,))
        t1.start()
        assert started.wait(10)
        t2 = threading.Thread(target=alloc, args=(1,))
        t2.start()
        time.sleep(0.3)  # give t2 the chance to (wrongly) skip the wait
        assert not seen, "an allocation launched before the copy finished"
        release.set()
        t1.join(10)
        t2.join(10)
        assert seen == [True, True]
    finally:
        release.set()
        b.stop()


def test_failed_localization_retried_by_waiter(tmp_path):
    """A failing first copy must not let a waiting allocation fall through:
    the waiter joins/starts a retry and launches only after a COMPLETED
    copy (the copier's own allocate raises)."""
    import threading

    app = tmp_path / "app"
    app.mkdir()
    (app / "config.json").write_text("{}")
    (app / "payload.bin").write_bytes(b"x" * 64)
    root = tmp_path / "localized"

    calls = []
    gate = threading.Event()

    class FlakyTransport(LocalTransport):
        def localize(self, host, src, dst):
            calls.append(1)
            if len(calls) == 1:
                gate.set()
                time.sleep(0.2)
                raise OSError("simulated copy failure")
            super().localize(host, src, dst)

    b = RemoteBackend(
        ["127.0.0.1"],
        transport=FlakyTransport(),
        host_capacity=Resource(memory_mb=256, cpus=4, tpu_chips=0),
        localize=True,
        localize_root=str(root),
    )
    b.start()
    env = {"TONY_APP_DIR": str(app), "TONY_APP_ID": "app-1"}
    results = {}

    def alloc(i):
        r = req(idx=i, log_path=str(tmp_path / f"c{i}.log"))
        r.env.update(env)
        try:
            b.allocate(r)
            dst = root / "127.0.0.1" / "app-1" / "payload.bin"
            results[i] = ("ok", dst.exists())
        except OSError as e:
            results[i] = ("fail", str(e))

    try:
        t1 = threading.Thread(target=alloc, args=(0,))
        t1.start()
        assert gate.wait(5)
        t2 = threading.Thread(target=alloc, args=(1,))
        t2.start()
        t1.join(15)
        t2.join(15)
        assert results[0][0] == "fail", results
        assert results[1] == ("ok", True), results
        assert len(calls) == 2
    finally:
        b.stop()


def test_e2e_remote_concurrent_jobs_share_rm_store(tmp_path):
    """Cross-job arbitration on the REMOTE backend: two jobs submitted
    concurrently against one 4-chip host through cluster.rm_root — the
    second queues in the shared store and succeeds after the first, chips
    never double-booked (the YARN-RM parity path, SURVEY.md section 1 L0)."""
    import threading
    import time as _time

    rm_root = str(tmp_path / "rm")
    results = {}
    t0 = _time.monotonic()

    def run_job(name, sleep_s):
        code, app_dir = submit_remote(
            tmp_path,
            {
                "application.name": name,
                "application.framework": "generic",
                "cluster.hosts": "127.0.0.1",
                "cluster.rm_root": rm_root,
                "am.allocation_timeout_s": 60,
                "job.worker.instances": 1,
                "job.worker.tpu_chips": 4,  # the whole host
                "job.worker.command": (
                    f'python -c "import time; time.sleep({sleep_s})"'
                ),
            },
        )
        results[name] = (code, app_dir, _time.monotonic() - t0)

    ta = threading.Thread(target=run_job, args=("rmr-first", 3))
    ta.start()
    _time.sleep(1.0)
    tb = threading.Thread(target=run_job, args=("rmr-second", 0))
    tb.start()
    ta.join(90)
    tb.join(90)
    code_a, _, _ = results["rmr-first"]
    code_b, _, dur_b = results["rmr-second"]
    assert code_a == 0 and code_b == 0
    assert dur_b > 3.0  # B waited out A's sleep; never ran beside it
    from tony_tpu.cluster.lease import LeaseStore

    summary = LeaseStore(rm_root).summary()
    assert not summary["apps"] and not summary["queue"]

"""Overlapped expert-parallel MoE combine (tony_tpu/ops/moe_overlap.py +
the parallel.moe ``overlap_impl`` wiring; docs/PERF.md "Round 20").

The decomposed combine is a SCHEDULE change: per-token-chunk psums of
disjoint row slices are elementwise the single full-width psum, so on the
deterministic CPU backend the scan form must be BITWISE against the plain
ep path — any drift means the decomposition changed the math, not the
schedule. The pallas form swaps the grouped-GEMM kernel inside each chunk,
so values are allclose within the grouped_mm tolerance instead. Gradients
ride the custom_vjp whose backward is the matching per-chunk collective;
they must match the unsharded reference exactly like the plain ep path
does (atol 1e-4 — f32 accumulation-order drift across chunk boundaries).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tony_tpu.ops.compat import shard_map_compat
from tony_tpu.ops.moe_overlap import chunk_tokens_from_report, overlap_chunks
from tony_tpu.parallel.mesh import MeshShape, build_mesh, set_default_mesh
from tony_tpu.parallel.moe import MoEConfig, init_moe_params, moe_block

BASE = MoEConfig(dim=32, ffn_dim=64, n_experts=4, top_k=2,
                 capacity_factor=8.0, dispatch="grouped")


@pytest.fixture(scope="module")
def params():
    return init_moe_params(jax.random.key(0), BASE, dtype=jnp.float32)


@pytest.fixture(scope="module")
def x():
    # T=48 tokens; over the ep=2 x fsdp=2 mesh the fsdp axis carries the
    # batch, so each shard owns t_local=24 rows (auto-split: 4 chunks of 6)
    return jax.random.normal(jax.random.key(1), (2, 24, 32), jnp.float32)


def _ep_mesh():
    return build_mesh(MeshShape(ep=2, fsdp=2))


def _run(params, x, cfg):
    def loss(p, xx):
        y, aux = moe_block(p, xx, cfg)
        return jnp.sum(y * y) + aux

    y, aux = jax.jit(lambda p, a: moe_block(p, a, cfg))(params, x)
    grads = jax.jit(jax.grad(loss))(params, x)
    return y, aux, grads


# --- chunk planning -----------------------------------------------------------


class TestChunkPlanning:
    def test_overlap_chunks_auto_and_pinned(self):
        # auto: largest clean split in {4, 3, 2}
        assert overlap_chunks(24, 0) == 4
        assert overlap_chunks(9, 0) == 3
        assert overlap_chunks(10, 0) == 2
        # pinned chunk size -> t_local / chunk chunks
        assert overlap_chunks(24, 6) == 4
        assert overlap_chunks(24, 12) == 2

    def test_overlap_chunks_declines(self):
        # the decline legs: nothing to split, indivisible chunk, chunk
        # swallowing every row (a 1-chunk "decomposition" is the plain psum)
        assert overlap_chunks(1, 0) is None
        assert overlap_chunks(7, 0) is None          # prime row count, auto
        assert overlap_chunks(24, 7) is None         # 24 % 7 != 0
        assert overlap_chunks(24, 24) is None
        assert overlap_chunks(24, 48) is None

    def test_chunk_tokens_from_report_sizing_and_clamps(self):
        # 0.8 GB/s x (4ms/2) window = 1.6e6 bytes / (1024 dim x 2B)
        # = 781 tokens -> rounded down to the 256 multiple below
        rep = {"compute_ms": 4.0, "top_collective": {"achieved_gbps": 0.8}}
        assert chunk_tokens_from_report(rep, dim=1024, dtype_bytes=2) == 768
        # clamps: a starved link floors at 256, a fat one caps at 8192
        slow = {"compute_ms": 4.0, "top_collective": {"achieved_gbps": 0.001}}
        assert chunk_tokens_from_report(slow, dim=1024, dtype_bytes=2) == 256
        fast = {"compute_ms": 50.0, "top_collective": {"achieved_gbps": 90.0}}
        assert chunk_tokens_from_report(fast, dim=1024, dtype_bytes=2) == 8192
        # no measured bandwidth (ledger-less capture) -> the default
        assert chunk_tokens_from_report({}, dim=1024) == 2048
        assert chunk_tokens_from_report(None, dim=1024) == 2048
        assert chunk_tokens_from_report({"compute_ms": 4.0}, dim=1024) == 2048


# --- parity on the ep mesh ----------------------------------------------------


class TestOverlapParity:
    def test_scan_bitwise_vs_plain_ep(self, params, x):
        """scan overlap vs the single-psum ep path: forward BITWISE (the
        chunked psums are the same sums over the same disjoint rows),
        grads vs the unsharded reference within the ep path's own
        tolerance."""
        set_default_mesh(None)
        ref_cfg = dataclasses.replace(BASE, overlap_impl="off")
        _, _, ref_g = _run(params, x, ref_cfg)

        mesh = _ep_mesh()
        set_default_mesh(mesh)
        try:
            plain_y, plain_aux, _ = _run(params, x, ref_cfg)
            ov_y, ov_aux, ov_g = _run(
                params, x, dataclasses.replace(BASE, overlap_impl="scan")
            )
        finally:
            set_default_mesh(None)
        assert float(ov_aux) == float(plain_aux)  # routing stays outside
        np.testing.assert_array_equal(np.asarray(ov_y), np.asarray(plain_y))
        for k in ref_g:
            np.testing.assert_allclose(
                np.asarray(ov_g[k]), np.asarray(ref_g[k]), atol=1e-4,
                err_msg=k,
            )

    def test_pallas_allclose_vs_plain_ep(self, params, x):
        """pallas overlap (interpret mode on CPU) swaps the per-chunk
        grouped-GEMM kernel: values allclose at the grouped_mm tolerance
        (tile-local f32 accumulation order), grads at the ep tolerance."""
        mesh = _ep_mesh()
        set_default_mesh(mesh)
        try:
            plain_y, _, plain_g = _run(
                params, x, dataclasses.replace(BASE, overlap_impl="off")
            )
            ov_y, _, ov_g = _run(
                params, x, dataclasses.replace(BASE, overlap_impl="pallas")
            )
        finally:
            set_default_mesh(None)
        np.testing.assert_allclose(
            np.asarray(ov_y), np.asarray(plain_y), atol=2e-5
        )
        for k in plain_g:
            np.testing.assert_allclose(
                np.asarray(ov_g[k]), np.asarray(plain_g[k]), atol=1e-4,
                err_msg=k,
            )

    def test_chunk_size_invariance(self, params, x):
        """Any clean split gives bitwise the same answer: the chunk count
        is a schedule knob, never a semantic one."""
        mesh = _ep_mesh()
        set_default_mesh(mesh)
        try:
            runs = [
                _run(params, x,
                     dataclasses.replace(BASE, overlap_impl="scan",
                                         overlap_chunk=c))[0]
                for c in (0, 8)  # 4 / 3 chunks of t_local=24
            ]
        finally:
            set_default_mesh(None)
        for other in runs[1:]:
            np.testing.assert_array_equal(
                np.asarray(runs[0]), np.asarray(other)
            )


# --- fallback triad -----------------------------------------------------------


class TestFallbacks:
    def test_indivisible_chunk_declines_to_single_psum(self, params, x):
        """overlap_chunk=7 does not divide t_local=24: the overlap declines
        and the ep path runs its plain single psum — bitwise identical."""
        mesh = _ep_mesh()
        set_default_mesh(mesh)
        try:
            plain_y, _, _ = _run(
                params, x, dataclasses.replace(BASE, overlap_impl="off")
            )
            ov_y, _, _ = _run(
                params, x,
                dataclasses.replace(BASE, overlap_impl="scan",
                                    overlap_chunk=7),
            )
        finally:
            set_default_mesh(None)
        np.testing.assert_array_equal(np.asarray(ov_y), np.asarray(plain_y))

    def test_no_ep_axis_falls_back_to_plain_grouped(self, params, x):
        """No default mesh (and so no ep axis): overlap_impl is inert and
        the grouped path runs unsharded — bitwise identical to off."""
        set_default_mesh(None)
        plain_y, plain_aux, _ = _run(
            params, x, dataclasses.replace(BASE, overlap_impl="off")
        )
        ov_y, ov_aux, _ = _run(
            params, x, dataclasses.replace(BASE, overlap_impl="scan")
        )
        assert float(ov_aux) == float(plain_aux)
        np.testing.assert_array_equal(np.asarray(ov_y), np.asarray(plain_y))

    def test_declines_inside_manual_region(self, params, x):
        """Inside an enclosing shard_map (a pp stage, the bucketed-dp
        trainer region) the ep path — overlap included — must not try to
        re-bind the ep axis: it declines to the plain grouped FFN and the
        values match the unsharded run."""
        set_default_mesh(None)
        cfg = dataclasses.replace(BASE, overlap_impl="scan")
        expect_y, _ = moe_block(params, x, cfg)

        mesh = _ep_mesh()
        set_default_mesh(mesh)
        try:
            def f(p, xx):
                y, _ = moe_block(p, xx, cfg)
                return y

            got = shard_map_compat(
                f, mesh=mesh, in_specs=(P(), P()), out_specs=P()
            )(params, x)
        finally:
            set_default_mesh(None)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expect_y), atol=1e-5
        )

    def test_unknown_overlap_impl_raises(self, params, x):
        with pytest.raises(ValueError, match="overlap impl"):
            moe_block(params, x,
                      dataclasses.replace(BASE, overlap_impl="turbo"))


# --- nonfinite propagation ----------------------------------------------------


class TestNonfinite:
    @pytest.mark.parametrize("impl", ["scan", "pallas"])
    def test_poisoned_tokens_propagate_like_plain_ep(self, params, x, impl):
        """A nan/inf activation row must poison exactly the same output
        rows through the overlapped combine as through the single psum —
        chunking must neither launder a nonfinite value (a masked-out
        where() eating the nan) nor smear it across chunk boundaries."""
        bad = jnp.asarray(x).at[0, 5, :].set(jnp.nan).at[1, 11, :].set(jnp.inf)
        mesh = _ep_mesh()
        set_default_mesh(mesh)
        try:
            plain_y, _, _ = _run(
                params, bad, dataclasses.replace(BASE, overlap_impl="off")
            )
            ov_y, _, _ = _run(
                params, bad, dataclasses.replace(BASE, overlap_impl=impl)
            )
        finally:
            set_default_mesh(None)
        plain_fin = np.isfinite(np.asarray(plain_y))
        ov_fin = np.isfinite(np.asarray(ov_y))
        np.testing.assert_array_equal(ov_fin, plain_fin)
        assert not plain_fin[0, 5].any()  # the poison actually landed
        np.testing.assert_allclose(
            np.asarray(ov_y)[plain_fin], np.asarray(plain_y)[plain_fin],
            atol=2e-5,
        )


# --- trainer composition ------------------------------------------------------


class TestTrainerComposition:
    def test_moe_trains_with_bucketed_dp_grads(self):
        """MoE + the manual-dp bucketed grad reduce compose: inside the
        bucketed region the ep/overlap path declines (manual region), the
        MoE param grads ride `bucketed_psum` as ordinary tree leaves, and
        the trajectory is bitwise-invariant to the bucket count and
        allclose to the GSPMD trainer."""
        from tony_tpu.models.llama import LlamaConfig
        from tony_tpu.train.trainer import (
            default_optimizer, make_train_state, make_train_step,
        )

        cfg = LlamaConfig.tiny_moe(moe_overlap_impl="scan")
        mesh = build_mesh(MeshShape(dp=2, ep=2))
        set_default_mesh(mesh)
        opt = default_optimizer(warmup_steps=1, decay_steps=10)
        toks = jax.random.randint(
            jax.random.key(7), (8, 33), 0, cfg.vocab_size
        )

        def run(bucket_bytes, steps=3):
            state = make_train_state(jax.random.key(0), cfg, mesh, opt)
            step = make_train_step(
                cfg, mesh, opt, grad_bucket_bytes=bucket_bytes
            )
            losses = []
            for _ in range(steps):
                state, m = step(state, toks[:, :-1], toks[:, 1:])
                losses.append(float(m["loss"]))
            return losses

        try:
            gspmd = run(None)      # partitioner-inserted single all-reduce
            one = run(1 << 30)     # manual region, one big bucket
            many = run(64 << 10)   # manual region, many small buckets
        finally:
            set_default_mesh(None)
        assert one == many         # bucket count never changes the values
        # vs GSPMD the MoE compute itself restructures (the manual-dp
        # region declines the ep shard_map, so expert partials reduce in
        # a different order), not just the grad reduce — wider f32 drift
        # than the dense trainer's 1e-5
        np.testing.assert_allclose(gspmd, one, rtol=1e-4)
        assert all(np.isfinite(v) for v in gspmd)

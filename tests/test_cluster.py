"""Tests for the local cluster backend and scheduler."""

import sys
import time

import pytest

from tony_tpu.am.scheduler import (
    AllocationTimeout,
    DependencyTimeout,
    SchedulerHooks,
    TaskScheduler,
)
from tony_tpu.am.session import Session, TaskState
from tony_tpu.cluster import (
    ContainerRequest,
    InsufficientResources,
    LocalProcessBackend,
    Resource,
)
from tony_tpu.config.config import TaskTypeSpec


def wait_for(pred, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def req(task_type="worker", index=0, argv=None, mem=64):
    return ContainerRequest(
        task_type=task_type,
        task_index=index,
        resource=Resource(mem, 1, 0),
        argv=argv or [sys.executable, "-c", "pass"],
    )


class TestLocalProcessBackend:
    def test_completion_callback_and_reclaim(self):
        done = []
        b = LocalProcessBackend(capacity=Resource(1024, 8, 0))
        b.set_completion_callback(lambda c, code: done.append((c.request.task_id, code)))
        b.start()
        b.allocate(req(argv=[sys.executable, "-c", "raise SystemExit(3)"]))
        assert wait_for(lambda: done == [("worker:0", 3)])
        assert b.available().memory_mb == 1024
        b.stop()

    def test_insufficient_resources(self):
        b = LocalProcessBackend(capacity=Resource(100, 1, 0))
        b.start()
        with pytest.raises(InsufficientResources):
            b.allocate(req(mem=200))
        b.stop()

    def test_release_kills_without_callback(self):
        done = []
        b = LocalProcessBackend(capacity=Resource(1024, 8, 0))
        b.set_completion_callback(lambda c, code: done.append(code))
        b.start()
        c = b.allocate(req(argv=[sys.executable, "-c", "import time; time.sleep(60)"]))
        b.release(c.container_id)
        assert wait_for(lambda: b.available().memory_mb == 1024)
        time.sleep(0.2)
        assert done == []  # released containers fire no completion
        b.stop()

    def test_tpu_resource_accounting(self):
        b = LocalProcessBackend(capacity=Resource(1024, 8, 4))
        b.start()
        r = ContainerRequest("w", 0, Resource(64, 1, 4),
                             [sys.executable, "-c", "import time; time.sleep(30)"])
        c = b.allocate(r)
        assert b.available().tpu_chips == 0
        with pytest.raises(InsufficientResources):
            b.allocate(ContainerRequest("w", 1, Resource(64, 1, 1), ["true"]))
        b.release(c.container_id)
        assert wait_for(lambda: b.available().tpu_chips == 4)
        b.stop()


def make_sched(specs, capacity=Resource(1 << 16, 64, 0), timeout=5.0):
    session = Session(specs)
    backend = LocalProcessBackend(capacity=capacity)
    backend.start()

    def make_request(spec, index):
        return ContainerRequest(
            spec.name, index, Resource(spec.memory_mb, spec.cpus, spec.tpu_chips),
            [sys.executable, "-c", "import time; time.sleep(30)"],
        )

    sched = TaskScheduler(
        session, backend, SchedulerHooks(make_request, lambda *a: None),
        allocation_timeout_s=timeout, poll_interval_s=0.05,
    )
    return session, backend, sched


class TestTaskScheduler:
    def test_allocates_all(self):
        specs = {"worker": TaskTypeSpec(name="worker", instances=3, memory_mb=64)}
        session, backend, sched = make_sched(specs)
        sched.schedule_all(specs)
        assert all(t.state == TaskState.ALLOCATED for t in session.tasks.values())
        backend.stop()

    def test_dependency_gates_launch(self):
        specs = {
            "ps": TaskTypeSpec(name="ps", instances=1, memory_mb=64),
            "worker": TaskTypeSpec(
                name="worker", instances=1, memory_mb=64, depends_on="ps",
                depends_timeout_s=10,
            ),
        }
        session, backend, sched = make_sched(specs)
        import threading

        t = threading.Thread(target=sched.schedule_all, args=(specs,), daemon=True)
        t.start()
        assert wait_for(lambda: session.task("ps", 0).state == TaskState.ALLOCATED)
        time.sleep(0.3)
        # worker must wait: ps allocated but not REGISTERED yet
        assert session.task("worker", 0).state == TaskState.PENDING
        session.register("ps", 0, "h", 1, 0)
        assert wait_for(lambda: session.task("worker", 0).state == TaskState.ALLOCATED)
        t.join(timeout=5)
        backend.stop()

    def test_dependency_timeout(self):
        specs = {
            "ps": TaskTypeSpec(name="ps", instances=1, memory_mb=64),
            "worker": TaskTypeSpec(
                name="worker", instances=1, memory_mb=64, depends_on="ps",
                depends_timeout_s=1,
            ),
        }
        _, backend, sched = make_sched(specs, timeout=30.0)
        with pytest.raises(DependencyTimeout):
            sched.schedule_all(specs)  # ps never registers
        backend.stop()

    def test_capacity_check_upfront(self):
        specs = {"worker": TaskTypeSpec(name="worker", instances=4, memory_mb=64)}
        _, backend, sched = make_sched(specs, capacity=Resource(128, 64, 0))
        with pytest.raises(InsufficientResources):
            sched.schedule_all(specs)
        backend.stop()

    def test_allocation_timeout_when_inventory_held(self):
        # total fits capacity but a zombie holds half: allocation times out
        specs = {"worker": TaskTypeSpec(name="worker", instances=2, memory_mb=64)}
        session, backend, sched = make_sched(
            specs, capacity=Resource(192, 64, 0), timeout=1.0
        )
        backend.allocate(req("zombie", 0, [sys.executable, "-c", "import time; time.sleep(30)"], mem=128))
        with pytest.raises(AllocationTimeout):
            sched.schedule_all(specs)
        backend.stop()

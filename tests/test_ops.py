"""Flash-attention kernel correctness (interpreter mode on CPU)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.ops import flash_attention


def ref_attention(q, k, v, causal=True):
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        S = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((S, S), bool))[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, -1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.fixture(scope="module")
def qkv():
    B, S, H, D = 2, 128, 4, 32
    ks = jax.random.split(jax.random.key(0), 3)
    return tuple(jax.random.normal(k, (B, S, H, D)) for k in ks)


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
def test_forward_matches_dense(qkv, causal):
    q, k, v = qkv
    got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    expect = ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=1e-5)


def test_gradients_match_dense(qkv):
    q, k, v = qkv

    def loss_flash(a, b, c):
        return jnp.sum(flash_attention(a, b, c, block_q=32, block_k=32) ** 2)

    def loss_ref(a, b, c):
        return jnp.sum(ref_attention(a, b, c) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    expect = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, e in zip(got, expect):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e), atol=5e-5)


def test_block_divisibility_enforced(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=48, block_k=48)


def test_model_integration_flash_impl():
    from tony_tpu.models.llama import LlamaConfig, forward, init_params

    cfg_flash = LlamaConfig.tiny(attention_impl="flash")
    cfg_dot = LlamaConfig.tiny()
    params = init_params(jax.random.key(0), cfg_dot)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_dot.vocab_size)
    got = forward(params, tokens, cfg_flash)
    expect = forward(params, tokens, cfg_dot)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=2e-4)


# --- GQA: K/V carry fewer heads than Q; kernel reads each kv head via its
# BlockSpec index map instead of an HBM-materialised repeat -----------------


@pytest.fixture(scope="module")
def qkv_gqa():
    B, S, H, Hkv, D = 2, 128, 4, 2, 32
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, Hkv, D))
    v = jax.random.normal(ks[2], (B, S, Hkv, D))
    return q, k, v


def _expand_kv(x, rep):
    return jnp.repeat(x, rep, axis=2)


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
def test_gqa_forward_matches_dense(qkv_gqa, causal):
    q, k, v = qkv_gqa
    rep = q.shape[2] // k.shape[2]
    got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    expect = ref_attention(q, _expand_kv(k, rep), _expand_kv(v, rep), causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=1e-5)


def test_gqa_gradients_match_dense(qkv_gqa):
    q, k, v = qkv_gqa
    rep = q.shape[2] // k.shape[2]

    def loss_flash(a, b, c):
        return jnp.sum(flash_attention(a, b, c, block_q=32, block_k=32) ** 2)

    def loss_ref(a, b, c):
        # reference expands kv in HBM; its kv grads sum over the group, which
        # is exactly what the kernel's accumulated dk/dv must equal
        out = ref_attention(a, _expand_kv(b, rep), _expand_kv(c, rep))
        return jnp.sum(out**2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    expect = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, e in zip(got, expect):
        assert g.shape == e.shape
        np.testing.assert_allclose(np.asarray(g), np.asarray(e), atol=5e-5)


def test_gqa_heads_not_multiple_raises(qkv_gqa):
    q, k, v = qkv_gqa
    k3 = jnp.concatenate([k, k[:, :, :1]], axis=2)  # 3 kv heads vs 4 q heads
    with pytest.raises(ValueError):
        flash_attention(q, k3, k3, block_q=32, block_k=32)

"""Live time-series recorder (obs/series.py) + fleet read paths + the
engine stats surface + `tony top` rendering.

The SLO engine's own rule/windowing behaviour lives in tests/test_slo.py;
the disarmed-seam cost guards live in tests/test_perf_guard.py; the GL005
call-site contract in tests/test_lint.py."""

import json
import os
import time

from tony_tpu.obs import series
from tony_tpu.obs.registry import Histogram, HistogramWindow


def _mkrec(tmp_path, **kw):
    kw.setdefault("sample_every", 1)
    return series.SeriesRecorder(
        str(tmp_path / "series" / "p0.jsonl"), "p0", **kw
    )


class TestRecorder:
    def test_scrape_merges_sources_and_journals(self, tmp_path):
        rec = _mkrec(tmp_path)
        rec.attach("a", lambda: {"x": 1.0})
        rec.attach("b", lambda: {"y": 2.0})
        point = rec.force_sample(step=3)
        assert point["x"] == 1.0 and point["y"] == 2.0 and point["step"] == 3
        assert "ts" in point
        rec.detach("b")
        rec.force_sample()
        assert rec.drain()
        rec.close()
        procs = series.read_series(str(tmp_path / "series"))
        assert list(procs) == ["p0"]
        assert len(procs["p0"]) == 2
        assert procs["p0"][0]["y"] == 2.0
        assert "y" not in procs["p0"][1]  # detached source gone

    def test_stride_counts_and_broken_source_is_isolated(self, tmp_path):
        rec = _mkrec(tmp_path, sample_every=4)
        calls = []
        rec.attach("good", lambda: calls.append(1) or {"ok": 1.0})

        def boom():
            raise RuntimeError("source died")

        rec.attach("bad", boom)
        for _ in range(7):
            rec.sample()
        assert len(calls) == 1  # one stride hit in 7 calls at stride 4
        assert rec.ring[-1]["ok"] == 1.0  # the broken source cost itself only
        rec.close()

    def test_rotation_keeps_newest_window(self, tmp_path):
        rec = _mkrec(tmp_path, max_journal_mb=1)
        # ~64KB per point x 40 > 2MB: forces at least one rotation
        blob = "x" * 65536
        for i in range(40):
            rec.force_sample(i=i, pad=blob)
        assert rec.drain(timeout_s=10.0)
        rec.close()
        names = sorted(os.listdir(tmp_path / "series"))
        assert "p0.jsonl" in names and "p0.0.jsonl" in names
        points = series.read_series(str(tmp_path / "series"))["p0"]
        # the NEWEST point always survives rotation; the oldest rolled off
        assert points[-1]["i"] == 39
        assert points[0]["i"] > 0

    def test_torn_tail_is_skipped(self, tmp_path):
        rec = _mkrec(tmp_path)
        rec.force_sample(i=1)
        rec.drain()
        rec.close()
        path = tmp_path / "series" / "p0.jsonl"
        with open(path, "a") as f:
            f.write('{"ts": 99, "i":')  # SIGKILL mid-line
        points = series.read_series(str(tmp_path / "series"))["p0"]
        assert [p["i"] for p in points] == [1]

    def test_observer_sees_points_on_writer_thread(self, tmp_path):
        rec = _mkrec(tmp_path)
        import threading

        seen = []
        rec.add_observer(lambda p: seen.append((threading.get_ident(), p)))
        rec.force_sample(v=7)
        assert rec.drain()
        rec.close()
        assert len(seen) == 1
        tid, point = seen[0]
        assert point["v"] == 7
        assert tid != threading.get_ident()  # evaluated off the hot path


class TestFleetRollup:
    def test_staleness_labels_and_clock_skew(self, tmp_path):
        sdir = tmp_path / "series"
        sdir.mkdir()
        now = time.time()
        # host A: fresh but with a clock 120s AHEAD (skewed into the future)
        (sdir / "a.jsonl").write_text(
            json.dumps({"ts": now + 120, "step": 5}) + "\n"
        )
        # host B: dead for 10 minutes
        (sdir / "b.jsonl").write_text(
            "".join(
                json.dumps({"ts": now - 660 + i, "step": i}) + "\n"
                for i in range(3)
            )
        )
        roll = series.fleet_rollup(str(tmp_path), now=now)
        # skewed-ahead host clamps to 0, never negative (and never hides b)
        assert roll["procs"]["a"]["age_s"] == 0.0
        assert roll["procs"]["b"]["age_s"] > 600
        assert roll["procs"]["b"]["latest"]["step"] == 2
        assert roll["procs"]["b"]["n"] == 3

    def test_missing_dir_is_empty_not_error(self, tmp_path):
        assert series.fleet_rollup(str(tmp_path))["procs"] == {}
        assert series.read_series(str(tmp_path / "nope")) == {}
        assert series.freshness(str(tmp_path)) == {}

    def test_freshness_is_stat_only(self, tmp_path):
        sdir = tmp_path / "series"
        sdir.mkdir()
        (sdir / "w.jsonl").write_text('{"ts": 1}\n')
        (sdir / "w.0.jsonl").write_text('{"ts": 0}\n')  # rotated window
        old = time.time() - 100
        os.utime(sdir / "w.jsonl", (old, old))
        os.utime(sdir / "w.0.jsonl", (old - 500, old - 500))
        fresh = series.freshness(str(tmp_path))
        # one entry per proc (rotated window merged), newest mtime wins
        assert list(fresh) == ["w"]
        assert 90 < fresh["w"]["age_s"] < 120
        assert fresh["w"]["bytes"] > 0


class TestHistogramWindow:
    def test_delta_quantiles_are_windowed(self):
        h = Histogram("t", {}, buckets=(0.1, 1.0, 10.0))
        win = HistogramWindow()
        for _ in range(10):
            h.observe(0.05)  # warmup: all tiny
        d1 = win.delta(h)
        assert d1["count"] == 10 and d1["p99"] <= 0.1
        for _ in range(10):
            h.observe(5.0)  # the incident window: all slow
        d2 = win.delta(h)
        assert d2["count"] == 10
        # the WINDOW shows the incident; the cumulative view dilutes it
        assert d2["p50"] > 1.0
        assert h.quantile(0.5) <= 1.0
        # empty window: zeros, no stale carryover
        d3 = win.delta(h)
        assert d3["count"] == 0 and d3["p50"] == 0.0

    def test_replaced_histogram_rebaselines(self):
        win = HistogramWindow()
        h1 = Histogram("t", {}, buckets=(1.0,))
        h1.observe(0.5)
        assert win.delta(h1)["count"] == 1
        h2 = Histogram("t", {}, buckets=(1.0,))  # reset_metrics analogue
        h2.observe(0.5)
        d = win.delta(h2)
        assert d["count"] == 1  # not negative, not 0


class TestInstallFromEnv:
    def test_journal_under_app_dir_and_disable(self, tmp_path, monkeypatch):
        series.uninstall()
        monkeypatch.setenv("TONY_APP_DIR", str(tmp_path))
        monkeypatch.setenv(series.ENV_SAMPLE, "1")
        monkeypatch.setenv("TONY_TRACE_PROC", "worker_0_user")
        try:
            rec = series.install_from_env()
            assert rec is series.active_recorder()
            assert rec.sample_every == 1
            rec.attach("t", lambda: {"v": 1.0})
            rec.force_sample()
            rec.drain()
        finally:
            series.uninstall()
        procs = series.read_series(str(tmp_path / "series"))
        assert "worker_0_user" in procs
        # disabled: nothing arms
        monkeypatch.setenv(series.ENV_ENABLED, "0")
        assert series.install_from_env() is None
        series.uninstall()


class TestPortalSeries:
    def test_api_series_rollup_merges_journals_and_am(self, tmp_path):
        from tony_tpu.obs.portal import PortalData

        app = tmp_path / "app-1"
        sdir = app / "series"
        sdir.mkdir(parents=True)
        now = time.time()
        (sdir / "worker_0_user.jsonl").write_text(
            json.dumps({"ts": now, "step": 7, "queue_depth": 2}) + "\n"
        )
        (sdir / "am_rollup.json").write_text(json.dumps({
            "ts": now - 300,
            "tasks": {"remote:0": {
                "last_ts": now - 300, "age_s": 0.0,  # the AM's stale lie
                "points": [{"ts": now - 300, "step": 3}],
            }},
        }))
        data = PortalData(str(tmp_path))
        roll = data.series_rollup("app-1")
        assert roll["procs"]["worker_0_user"]["latest"]["step"] == 7
        # staleness re-labelled against NOW, not the AM's write time
        assert roll["am_rollup"]["tasks"]["remote:0"]["age_s"] > 250
        assert roll["am_rollup"]["rollup_age_s"] > 250
        fleet = data.series_summaries()
        assert set(fleet["app-1"]["procs"]) == {"worker_0_user", "remote:0"}
        assert data.series_rollup("no-such-app") is None

    def test_metrics_snapshots_carry_age_gauge(self, tmp_path):
        from tony_tpu.obs.portal import PortalData

        mdir = tmp_path / "app-1" / "metrics"
        mdir.mkdir(parents=True)
        (mdir / "w.json").write_text(json.dumps({
            "proc": "w",
            "metrics": [{"kind": "counter", "name": "tony_x_total",
                         "help": "", "labels": {}, "value": 1}],
        }))
        old = time.time() - 500
        os.utime(mdir / "w.json", (old, old))
        data = PortalData(str(tmp_path))
        text = data.prometheus()
        assert "tony_x_total" in text
        # the snapshot-derived series are staleness-labelled
        line = next(
            l for l in text.splitlines()
            if l.startswith("tony_snapshot_age_seconds{")
        )
        assert 'app="app-1"' in line and 'proc="w"' in line
        assert float(line.rsplit(" ", 1)[1]) > 400
        # and the portal's own LIVE registry is served alongside
        data.count_request("metrics")
        assert "tony_portal_requests_total" in data.prometheus()


class TestEngineStatsSnapshot:
    def test_snapshot_is_the_one_stats_surface(self, tmp_path):
        import jax

        from tony_tpu.models.llama import LlamaConfig, init_params
        from tony_tpu.serve.engine import Engine, Request, ServeConfig

        series.uninstall()
        rec = series.install(series.SeriesRecorder(
            str(tmp_path / "series" / "serve.jsonl"), "serve", sample_every=1,
        ))
        try:
            cfg = LlamaConfig.tiny()
            eng = Engine(
                init_params(jax.random.key(0), cfg), cfg,
                ServeConfig(slots=2, max_len=64),
            )
            snap0 = eng.stats_snapshot()
            assert snap0["queue_depth"] == 0 and snap0["slots"] == 2
            done = eng.run([
                Request(prompt=[1, 2, 3], max_new_tokens=4),
                Request(prompt=[4, 5], max_new_tokens=4),
            ])
            assert len(done) == 2
            snap = eng.stats_snapshot()
            assert snap["requests_finished"] == 2
            assert snap["generated_tokens"] >= 8
            assert snap["ttft_p99_s"] > 0  # cumulative quantiles present
            eng.close()
        finally:
            series.uninstall()
        # the decode loop scraped the engine source into the journal
        points = series.read_series(str(tmp_path / "series"))["serve"]
        assert points, "decode steps never scraped the series"
        assert any("occupancy" in p for p in points)
        # windowed quantiles landed (ttft observed within the run)
        assert any(p.get("ttft_p99_s", 0) > 0 for p in points)


class TestTonyTop:
    def test_once_frame_renders_rows_slo_and_staleness(self, tmp_path):
        from tony_tpu.obs.top import build_view, render, sparkline

        app = tmp_path / "app-top"
        sdir = app / "series"
        sdir.mkdir(parents=True)
        now = time.time()
        (sdir / "decode_0_user.jsonl").write_text("".join(
            json.dumps({
                "ts": now - 10 + i, "queue_depth": i, "occupancy": 0.5,
                "ttft_p99_s": 0.2,
            }) + "\n"
            for i in range(8)
        ))
        (sdir / "decode_1_user.jsonl").write_text(
            json.dumps({"ts": now - 120, "queue_depth": 0}) + "\n"
        )
        slo_dir = app / "slo"
        slo_dir.mkdir()
        (slo_dir / "verdict_decode_0_user.json").write_text(json.dumps({
            "verdict": "tripped", "proc": "decode_0_user",
            "slos": {"ttft_p99_s": {"trips": 4}},
        }))
        (app / "status.json").write_text(
            json.dumps({"state": "RUNNING", "exit_code": "", "tasks": []})
        )
        view = build_view(str(app), now=now)
        rows = {r["proc"]: r for r in view["rows"]}
        assert view["slo"]["verdict"] == "tripped"
        assert rows["decode_0_user"]["slo"] == "TRIP:ttft_p99_s"
        assert rows["decode_1_user"]["slo"] == "ok"
        assert rows["decode_1_user"]["stale"]  # 120s-old series marked
        assert rows["decode_0_user"]["trend"]  # sparkline data present
        frame = render(view)
        assert "decode_0_user" in frame and "TRIP:ttft_p99_s" in frame
        assert "ttft_p99" in frame  # the column header
        # sparkline maths: monotone values render monotone glyphs
        s = sparkline([0, 1, 2, 3])
        assert len(s) == 4 and s[0] == "▁" and s[-1] == "█"
        assert sparkline([]) == ""
        assert sparkline([2, 2, 2]) == "▄▄▄"

    def test_run_top_once_exits_zero(self, tmp_path, capsys):
        from tony_tpu.obs.top import run_top

        (tmp_path / "status.json").write_text(
            json.dumps({"state": "SUCCEEDED", "exit_code": 0, "tasks": []})
        )
        assert run_top(str(tmp_path), once=True) == 0
        out = capsys.readouterr().out
        assert "tony top" in out and "no series yet" in out

"""Round-trip tests for the gRPC control plane."""

import threading

import grpc
import pytest

from tony_tpu.rpc import ApplicationRpcClient, ApplicationRpcServicer, pb, serve


class EchoServicer(ApplicationRpcServicer):
    def __init__(self):
        self.registered = []
        self.results = []
        self.metrics = []
        self.lock = threading.Lock()

    def RegisterWorkerSpec(self, request, context):
        with self.lock:
            self.registered.append((request.job_name, request.index, request.host, request.port))
        return pb.RegisterWorkerSpecResponse(accepted=True)

    def GetClusterSpec(self, request, context):
        return pb.GetClusterSpecResponse(
            ready=True,
            spec_json='{"worker": ["h:1"]}',
            coordinator_address="h:1",
            process_id=request.index,
            num_processes=2,
            generation=3,
        )

    def Heartbeat(self, request, context):
        return pb.HeartbeatResponse(action=pb.HeartbeatResponse.NONE)

    def RegisterExecutionResult(self, request, context):
        with self.lock:
            self.results.append((request.job_name, request.index, request.exit_code))
        return pb.RegisterExecutionResultResponse(acknowledged=True)

    def PushMetrics(self, request, context):
        with self.lock:
            self.metrics.extend((s.name, s.value) for s in request.samples)
        return pb.Empty()

    def GetApplicationStatus(self, request, context):
        return pb.GetApplicationStatusResponse(state="RUNNING", exit_code=0)


@pytest.fixture
def rpc_pair():
    servicer = EchoServicer()
    server, port = serve(servicer, port=0)
    client = ApplicationRpcClient(f"127.0.0.1:{port}")
    yield servicer, client
    client.close()
    server.stop(0)


def test_register_and_spec_roundtrip(rpc_pair):
    servicer, client = rpc_pair
    resp = client.register_worker_spec("worker", 1, "myhost", 4242)
    assert resp.accepted
    assert servicer.registered == [("worker", 1, "myhost", 4242)]
    spec = client.get_cluster_spec("worker", 1)
    assert spec.ready and spec.process_id == 1 and spec.num_processes == 2
    assert spec.generation == 3


def test_result_and_metrics(rpc_pair):
    servicer, client = rpc_pair
    client.register_execution_result("worker", 0, 7, message="boom")
    assert servicer.results == [("worker", 0, 7)]
    client.push_metrics("worker", 0, [("cpu_percent", 55.5, 123.0)])
    assert servicer.metrics == [("cpu_percent", 55.5)]


def test_heartbeat_and_status(rpc_pair):
    _, client = rpc_pair
    assert client.heartbeat("worker", 0).action == pb.HeartbeatResponse.NONE
    assert client.get_application_status().state == "RUNNING"


def test_unimplemented_method_raises(rpc_pair):
    _, client = rpc_pair
    with pytest.raises(grpc.RpcError):
        client.get_task_infos()

"""Utils tests: port probing and logged subprocess lifecycle."""

import io
import socket

from tony_tpu.utils import LoggedProc, find_free_port, run_logged


def test_find_free_port_is_bindable():
    port = find_free_port()
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", port))


def test_run_logged_captures_full_output_and_exit_code():
    buf = io.BytesIO()
    lp = run_logged(
        'python -c "import sys; [print(i) for i in range(50)]; sys.exit(3)"',
        log_prefix="[w-0] ",
        stdout=buf,
    )
    assert isinstance(lp, LoggedProc)
    code = lp.wait(timeout=30)
    assert code == 3
    lines = buf.getvalue().decode().strip().splitlines()
    assert len(lines) == 50  # tail not lost: wait() drains the pump
    assert lines[0] == "[w-0] 0" and lines[-1] == "[w-0] 49"


def test_run_logged_argv_form():
    buf = io.BytesIO()
    lp = run_logged(["python", "-c", "print('argv ok')"], stdout=buf)
    assert lp.wait(timeout=30) == 0
    assert b"argv ok" in buf.getvalue()

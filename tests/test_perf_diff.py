"""`tony perf diff` (obs/perf_diff.py): the cross-run regression gate.

The committed fixtures under tests/fixtures/perf/ ARE the tier-1 gate:
the identity diff must stay green, and the regression fixture (tok/s
down ~22%, decode TTFT p99 up ~3.4x) must stay red — a rule change that
stops flagging either breaks here, loudly."""

import json
import os

import pytest

from tony_tpu.obs.perf_diff import (
    DEFAULT_RULES, diff, diff_files, flatten, load_report, rule_for,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "perf")
BASE = os.path.join(FIXTURES, "bench_base.json")
REGRESSED = os.path.join(FIXTURES, "bench_regressed.json")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestFlattenAndRules:
    def test_flatten_numeric_leaves_only(self):
        flat = flatten({
            "a": 1, "b": {"c": 2.5, "d": "s", "e": True, "f": [1, 2]},
        })
        assert flat == {"a": 1.0, "b.c": 2.5}  # strings/bools/lists excluded

    def test_rule_directions(self):
        assert rule_for("extra.tokens_per_sec_per_chip")[0] == "higher"
        assert rule_for("extra.decode.full_slot.ttft_p99_s")[0] == "lower"
        assert rule_for("extra.loss")[0] == "lower"
        assert rule_for("extra.peak_hbm_gb")[0] == "lower"
        assert rule_for("extra.n_params")[0] == "config"
        assert rule_for("extra.batch")[0] == "config"
        assert rule_for("vs_baseline")[0] == "skip"
        assert rule_for("extra.xla_compiles")[0] == "lower"
        assert rule_for("extra.gqa_capacity.slots")[0] == "higher"
        # headroom is higher-better DESPITE carrying 'hbm': a collapse
        # must flag as a regression, not pass as a memory improvement
        assert rule_for("decode_0.hbm_headroom_frac")[0] == "higher"
        # step anatomy (obs/anatomy.py): overlap + achieved bandwidth are
        # higher-better, exposed collective time lower-better
        assert rule_for("extra.step_anatomy.overlap_frac")[0] == "higher"
        assert rule_for(
            "extra.step_anatomy.top_collective.achieved_gbps"
        )[0] == "higher"
        assert rule_for(
            "extra.step_anatomy.exposed_collective_ms"
        )[0] == "lower"
        # the payload is program configuration, not a measurement: a
        # sharding change's bigger all-reduce must report as
        # config_changed, never as a memory regression
        assert rule_for(
            "extra.step_anatomy.top_collective.bytes"
        )[0] == "config"
        # decomposed-collective overlap (ops/overlap.py, bench `overlap`
        # section): the on/off exposed and step-time ratios are
        # lower-better (drifting toward 1.0 means the decomposition
        # stopped paying); the grad-bucket budget is sized FROM the
        # measured bandwidth, so it is configuration identity, never a
        # memory metric; the within-run loss delta is a value-safety
        # cross-check (~0), never judged relatively
        assert rule_for("extra.overlap.overlap_frac")[0] == "higher"
        assert rule_for("extra.overlap.exposed_collective_ms")[0] == "lower"
        assert rule_for("extra.overlap.exposed_ratio")[0] == "lower"
        assert rule_for("extra.overlap.step_ms_ratio")[0] == "lower"
        assert rule_for("extra.overlap.grad_bucket_bytes")[0] == "config"
        assert rule_for("extra.overlap.loss_delta")[0] == "skip"
        assert rule_for("extra.overlap.on.pure_comm_steps")[0] == "skip"
        assert rule_for(
            "extra.overlap.on.top_collective.achieved_gbps"
        )[0] == "higher"
        # prefix store (serve/prefix.py): hit rate is higher-better; the
        # on/off TTFT and prefill-FLOPs ratios are lower-better (a ratio
        # drifting toward 1.0 means the reuse stopped paying); residency
        # is trace-shaped, never judged
        assert rule_for(
            "extra.decode.prefix_trace.prefix_on.prefix_hit_rate"
        )[0] == "higher"
        assert rule_for(
            "extra.decode.prefix_trace.ttft_p50_ratio"
        )[0] == "lower"
        assert rule_for(
            "extra.decode.prefix_trace.prefill_flops_ratio"
        )[0] == "lower"
        assert rule_for(
            "extra.decode.prefix_trace.prefix_on.ttft_p99_s"
        )[0] == "lower"
        assert rule_for("decode_0.prefix_resident_mb")[0] == "skip"
        # speculative decoding (serve/spec.py): tokens/step, accept rate
        # and the on/off speedup are higher-better; rollbacks are
        # trace-shaped; the draft depth is configuration; the compile
        # count falls through to the zero-tolerance compile rule
        assert rule_for(
            "extra.decode.spec_trace.b1_on.tokens_per_step"
        )[0] == "higher"
        assert rule_for(
            "extra.decode.spec_trace.b1_on.accept_rate"
        )[0] == "higher"
        assert rule_for("extra.decode.spec_trace.speedup_b1")[0] == "higher"
        assert rule_for("decode_0.spec_rollbacks")[0] == "skip"
        assert rule_for("extra.decode.spec_trace.max_draft")[0] == "config"
        assert rule_for(
            "extra.decode.spec_trace.b1_on.decode_compiles"
        )[0] == "lower"
        # quantized serving (serve/cache.py, bench decode.quant +
        # gqa_capacity): the measured slot budget and the quant/bf16
        # ratio are higher-better — they carry no memory token, so
        # without their own rule a budget collapse would go unjudged;
        # the stated accuracy tolerance and KV dtype are configuration
        # identity (loosening the tolerance must be a visible config
        # change, never judged "within tolerance")
        assert rule_for("extra.gqa_capacity.max_slots_quant")[0] == "higher"
        assert rule_for("extra.gqa_capacity.max_slots_native")[0] == "higher"
        assert rule_for("extra.gqa_capacity.quant_slot_ratio")[0] == "higher"
        assert rule_for("extra.decode.quant.tolerance")[0] == "config"
        assert rule_for("extra.decode.quant.quant_on.tok_s_slot")[0] == "higher"
        assert rule_for(
            "extra.decode.quant.quant_on.kv_bytes_per_token"
        )[0] == "lower"
        assert rule_for(
            "extra.decode.quant.quant_on.peak_hbm_gb"
        )[0] == "lower"
        # disaggregated serving (bench decode.disagg): the chunked/
        # unchunked TPOT-p99 ratio is lower-better (drifting toward 1.0
        # means chunked prefill stopped bounding the long-prompt
        # interference); the chunk size and scenario prompt length are
        # configuration identity; the handoff payload is trace-shaped —
        # bytes/blocks (and the per-host shipped/adopted/freed counters
        # in series rollups) must never be judged as memory, while the
        # handoff wall time stays a judged latency
        assert rule_for(
            "extra.decode.disagg.tpot_p99_chunked_ratio"
        )[0] == "lower"
        assert rule_for(
            "extra.decode.disagg.chunked_colocated.tpot_p99_s"
        )[0] == "lower"
        assert rule_for("extra.decode.disagg.chunk_tokens")[0] == "config"
        assert rule_for(
            "extra.decode.disagg.long_prompt_tokens"
        )[0] == "config"
        assert rule_for(
            "extra.decode.disagg.unchunked_pooled.handoff_bytes"
        )[0] == "skip"
        assert rule_for(
            "extra.decode.disagg.unchunked_pooled.handoff_blocks"
        )[0] == "skip"
        assert rule_for("decode_0.handoff_shipped_blocks")[0] == "skip"
        assert rule_for(
            "extra.decode.disagg.unchunked_pooled.handoff_ms"
        )[0] == "lower"
        # MoE fast path (bench moe_top2, round 20): the grouped/gather
        # throughput ratio is higher-better — the PR-4 bench gate,
        # finally judged instead of parked in a docstring; the dispatch
        # decision flags are configuration identity, so a silent flip
        # back to gather surfaces as config_changed, never as a
        # throughput footnote; the overlap subsection (chunked ep
        # combine OFF/ON) rides the decomposed-collective rules above,
        # and its chunk size — derived from the OFF capture's measured
        # bandwidth — is configuration, not a metric
        assert rule_for("extra.moe_top2.grouped_vs_gather")[0] == "higher"
        assert rule_for("extra.moe_top2.dispatch_gate_holds")[0] == "config"
        assert rule_for(
            "extra.moe_top2.dispatch_default_grouped"
        )[0] == "config"
        assert rule_for("extra.moe_top2.mfu")[0] == "higher"
        assert rule_for(
            "extra.moe_top2.tokens_per_sec_per_chip"
        )[0] == "higher"
        assert rule_for("extra.moe_top2.overlap.chunk_tokens")[0] == "config"
        assert rule_for("extra.moe_top2.overlap.exposed_ratio")[0] == "lower"
        assert rule_for(
            "extra.moe_top2.overlap.exposed_collective_ms"
        )[0] == "lower"
        assert rule_for("extra.moe_top2.overlap.step_ms_ratio")[0] == "lower"
        assert rule_for("extra.moe_top2.overlap.overlap_frac")[0] == "higher"
        assert rule_for("extra.moe_top2.overlap.loss_delta")[0] == "skip"

    def test_headroom_collapse_is_a_regression(self):
        v = diff(
            {"p": {"hbm_headroom_frac": 0.5}},
            {"p": {"hbm_headroom_frac": 0.1}},
        )
        assert not v["ok"]
        assert v["regressions"][0]["key"] == "p.hbm_headroom_frac"


class TestVerdict:
    def test_identity_diff_is_green(self):
        base = load_report(BASE)
        v = diff(base, base)
        assert v["ok"] and v["regressions"] == [] and v["compared"] > 5
        assert v["config_changed"] == []

    def test_regression_fixture_is_red_with_the_right_keys(self):
        v = diff_files(BASE, REGRESSED)
        assert not v["ok"]
        keys = {r["key"] for r in v["regressions"]}
        assert "extra.tokens_per_sec_per_chip" in keys
        assert "extra.decode.full_slot.ttft_p99_s" in keys
        assert "extra.mfu" in keys
        # the anatomy section gates too: an overlap collapse, a grown
        # exposed-collective cost, and a bandwidth drop all flag
        assert "extra.step_anatomy.overlap_frac" in keys
        assert "extra.step_anatomy.exposed_collective_ms" in keys
        assert "extra.step_anatomy.top_collective.achieved_gbps" in keys
        # the overlap section gates too: a collapse of the decomposed
        # rings (overlap_frac down, exposed time back up, the on/off
        # ratios drifting past 1.0) all flag
        assert "extra.overlap.overlap_frac" in keys
        assert "extra.overlap.exposed_ratio" in keys
        assert "extra.overlap.step_ms_ratio" in keys
        assert "extra.overlap.on.exposed_collective_ms" in keys
        # the elastic section gates too: warm-restart cost (both the
        # journal number and the trace-goodput one) and the post-shrink
        # step-time ratio all flag
        assert "extra.elastic.restart_s" in keys
        assert "extra.elastic.goodput.restart_s" in keys
        assert "extra.elastic.shrunk_step_ratio" in keys
        # the prefix-store section gates too: a hit-rate collapse, the
        # on/off TTFT ratio drifting past 1.0, and tail FLOPs growing back
        # toward the full-prompt cost all flag
        assert "extra.decode.prefix_trace.prefix_on.prefix_hit_rate" in keys
        assert "extra.decode.prefix_trace.ttft_p50_ratio" in keys
        assert "extra.decode.prefix_trace.prefill_flops_ratio" in keys
        # the speculative-decoding section gates too: an accept-rate
        # collapse drags tokens/step and the on/off speedup with it
        assert "extra.decode.spec_trace.b1_on.accept_rate" in keys
        assert "extra.decode.spec_trace.b1_on.tokens_per_step" in keys
        assert "extra.decode.spec_trace.speedup_b1" in keys
        # the quantized-serving section gates too: a slot-budget collapse
        # (the capacity headline) and the vanished on/off throughput
        # advantage both flag
        assert "extra.gqa_capacity.max_slots_quant" in keys
        assert "extra.gqa_capacity.quant_slot_ratio" in keys
        assert "extra.decode.quant.tok_s_ratio" in keys
        # the disaggregated-serving section gates too: the chunked TPOT
        # tail blowing back toward the unchunked one (the interference
        # chunking exists to bound) and a slowed handoff both flag; the
        # unchanged payload size stays silent (trace-shaped, skipped)
        assert "extra.decode.disagg.chunked_colocated.tpot_p99_s" in keys
        assert "extra.decode.disagg.tpot_p99_chunked_ratio" in keys
        assert "extra.decode.disagg.chunked_pooled.handoff_ms" in keys
        assert "extra.decode.disagg.chunked_pooled.handoff_bytes" not in keys
        # the MoE fast-path section gates too: the grouped-dispatch
        # advantage vanishing, the MFU headline sliding back to the
        # gather-era number, and the overlapped ep combine re-exposing
        # its collective (ratio drifting toward the OFF capture) all
        # flag; the dispatch flags and chunk size are unchanged, so the
        # red report carries no config noise alongside them
        assert "extra.moe_top2.grouped_vs_gather" in keys
        assert "extra.moe_top2.mfu" in keys
        assert "extra.moe_top2.tokens_per_sec_per_chip" in keys
        assert "extra.moe_top2.overlap.exposed_ratio" in keys
        assert "extra.moe_top2.overlap.overlap_frac" in keys
        assert "extra.moe_top2.overlap.on.exposed_collective_ms" in keys
        assert "extra.moe_top2.dispatch_default_grouped" not in keys
        assert not any("moe_top2" in c["key"] for c in v["config_changed"])
        # within-tolerance drift is NOT flagged
        assert "extra.loss" not in keys          # +0.04% << 2%
        assert "extra.peak_hbm_gb" not in keys   # +1.5% << 10%
        # worst regression leads the report (the fixture's 6.6x tail-FLOPs
        # blowup outranks the 3.4x TTFT one)
        assert v["regressions"][0]["key"] == (
            "extra.decode.prefix_trace.prefill_flops_ratio"
        )

    def test_improvements_and_direction(self):
        base = load_report(BASE)
        better = json.loads(json.dumps(base))
        better["extra"]["tokens_per_sec_per_chip"] *= 1.2
        better["extra"]["decode"]["full_slot"]["ttft_p99_s"] *= 0.5
        v = diff(base, better)
        assert v["ok"]
        keys = {r["key"] for r in v["improvements"]}
        assert "extra.tokens_per_sec_per_chip" in keys
        assert "extra.decode.full_slot.ttft_p99_s" in keys

    def test_config_changes_reported_separately(self):
        base = load_report(BASE)
        changed = json.loads(json.dumps(base))
        changed["extra"]["batch"] = 8
        v = diff(base, changed)
        assert v["ok"]  # a config change is not a perf regression...
        assert v["config_changed"] == [
            {"key": "extra.batch", "old": 4.0, "new": 8.0}
        ]  # ...but it is never hidden

    def test_compile_count_regression_has_zero_tolerance(self):
        base = load_report(BASE)
        worse = json.loads(json.dumps(base))
        worse["extra"]["xla_compiles"] = 4
        v = diff(base, worse)
        assert any(
            r["key"] == "extra.xla_compiles" for r in v["regressions"]
        )

    def test_tol_scale_relaxes_the_gate(self):
        v = diff_files(BASE, REGRESSED, tol_scale=100.0)
        assert v["ok"]

    def test_unjudged_keys_are_listed_not_dropped(self):
        v = diff({"weird_quantity": 1.0}, {"weird_quantity": 2.0})
        assert v["ok"] and v["unjudged"] == ["weird_quantity"]


class TestInputShapes:
    def test_loads_real_driver_bench_wrappers(self):
        """The committed BENCH_r*.json at the repo root are first-class
        inputs; the identity diff over the newest one stays green."""
        path = os.path.join(REPO, "BENCH_r05.json")
        if not os.path.exists(path):
            pytest.skip("no BENCH_r05.json in this checkout")
        report = load_report(path)
        assert report["metric"] == "llama1.4b_train_tokens_per_sec_per_chip"
        flat = flatten(report)
        assert "extra.tokens_per_sec_per_chip" in flat
        assert diff(report, report)["ok"]

    def test_loads_series_rollups(self, tmp_path):
        def rollup(ttft):
            return {
                "procs": {
                    "decode_0": {
                        "points": [
                            {"ts": i, "ttft_p99_s": ttft, "queue_depth": 2}
                            for i in range(5)
                        ],
                    }
                }
            }

        old_p, new_p = tmp_path / "old.json", tmp_path / "new.json"
        old_p.write_text(json.dumps(rollup(0.1)))
        new_p.write_text(json.dumps(rollup(0.5)))
        v = diff_files(str(old_p), str(new_p))
        assert not v["ok"]
        assert v["regressions"][0]["key"] == "decode_0.ttft_p99_s"

    def test_unusable_input_is_a_clean_error(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("[1, 2]")
        with pytest.raises(ValueError):
            load_report(str(bad))


class TestCli:
    def test_tony_perf_diff_exit_codes(self, tmp_path, capsys):
        from tony_tpu.cli.main import main

        assert main(["perf", "diff", BASE, BASE]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["ok"] is True
        assert main(["perf", "diff", BASE, REGRESSED]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["ok"] is False and out["regressions"]
        assert main(
            ["perf", "diff", BASE, str(tmp_path / "missing.json")]
        ) == 2

    def test_first_rule_match_wins_is_ordered(self):
        # ordering sanity: the config rule outranks the latency catch-all,
        # or `steps`-ish keys would be judged as latencies
        idx = {kind: i for i, (_, kind, _) in enumerate(DEFAULT_RULES)}
        assert idx["config"] < idx["lower"]

"""Unit tests for the AM session state machine (TonySession analogue)."""

from tony_tpu.am.session import JobState, Session, TaskState
from tony_tpu.config.config import TaskTypeSpec


def make_specs(**kwargs) -> dict[str, TaskTypeSpec]:
    out = {}
    for name, n in kwargs.items():
        untracked = name.startswith("tb")
        out[name] = TaskTypeSpec(name=name, instances=n, untracked=untracked)
    return out


def test_task_table_and_registration():
    s = Session(make_specs(worker=2, ps=1))
    assert len(s.tasks) == 3
    assert not s.all_registered()
    assert s.register("worker", 0, "h1", 1000, attempt=0)
    assert s.register("worker", 1, "h2", 1001, attempt=0)
    assert not s.all_registered()
    assert s.register("ps", 0, "h3", 1002, attempt=0)
    assert s.all_registered()
    # unknown task / stale attempt rejected
    assert not s.register("worker", 5, "h", 1, attempt=0)
    assert not s.register("worker", 0, "h", 1, attempt=3)


def test_cluster_spec_json_shape():
    s = Session(make_specs(worker=2, ps=1))
    s.register("worker", 0, "h1", 1000, 0)
    s.register("worker", 1, "h2", 1001, 0)
    s.register("ps", 0, "h3", 1002, 0)
    import json

    spec = json.loads(s.cluster_spec_json())
    assert spec == {"worker": ["h1:1000", "h2:1001"], "ps": ["h3:1002"]}


def test_rank_table_deterministic_and_excludes_untracked():
    s = Session(make_specs(worker=2, ps=1, tb=1))
    table = s.rank_table()
    # sorted type order: ps < tb(excluded) < worker
    assert table == {"ps:0": 0, "worker:0": 1, "worker:1": 2}
    s.register("ps", 0, "h", 1, 0)
    assert s.coordinator_task().task_id == "ps:0"


def test_final_status_untracked_never_fails_job():
    s = Session(make_specs(worker=1, tb=1))
    s.on_task_completed("worker", 0, 0)
    s.on_task_completed("tb", 0, 137)
    assert s.job_done()
    state, code = s.final_status()
    assert state == JobState.SUCCEEDED and code == 0


def test_final_status_propagates_failure_code():
    s = Session(make_specs(worker=2))
    s.on_task_completed("worker", 0, 0)
    s.on_task_completed("worker", 1, 7)
    state, code = s.final_status()
    assert state == JobState.FAILED and code == 7


def test_chief_semantics():
    s = Session(make_specs(chief=1, worker=2), chief_type="chief")
    s.on_task_completed("chief", 0, 0)
    # workers still running, but chief done -> job done & succeeded
    assert s.job_done()
    state, code = s.final_status()
    assert state == JobState.SUCCEEDED and code == 0


def test_gang_reset_bumps_attempts_and_generation():
    s = Session(make_specs(worker=2))
    s.register("worker", 0, "h", 1, 0)
    s.on_task_completed("worker", 1, 1)
    reset = s.reset_for_restart(None)
    assert len(reset) == 2
    assert s.generation == 1
    for t in s.tasks.values():
        assert t.state == TaskState.PENDING
        assert t.attempt == 1
        assert t.host == "" and t.exit_code is None
    # old-attempt registration now rejected
    assert not s.register("worker", 0, "h", 1, attempt=0)
    assert s.register("worker", 0, "h", 1, attempt=1)


def test_partial_reset_only_named_types():
    s = Session(make_specs(worker=2, ps=1))
    s.on_task_completed("worker", 0, 1)
    reset = s.reset_for_restart({"worker"})
    assert {t.task_id for t in reset} == {"worker:0", "worker:1"}
    assert s.task("ps", 0).attempt == 0


def test_touch_refreshes_liveness_and_rejects_stale():
    s = Session(make_specs(worker=2))
    s.register("worker", 0, "h", 1, 0)
    t = s.task("worker", 0)
    before = t.last_heartbeat
    assert before > 0
    assert s.touch("worker", 0)                  # attempt-agnostic (spec poll)
    assert s.touch("worker", 0, attempt=0)       # current attempt
    assert not s.touch("worker", 0, attempt=3)   # stale attempt
    assert not s.touch("worker", 9)              # unknown task
    assert t.last_heartbeat >= before


def test_mark_running_transition_only_from_registered():
    s = Session(make_specs(worker=1))
    t = s.task("worker", 0)
    s.mark_running("worker", 0)            # PENDING: no-op
    assert t.state == TaskState.PENDING
    s.register("worker", 0, "h", 1, 0)
    s.mark_running("worker", 0)
    assert t.state == TaskState.RUNNING
    assert t.started_at > 0
    s.on_task_completed("worker", 0, 0)
    s.mark_running("worker", 0)            # terminal: no-op
    assert t.state == TaskState.SUCCEEDED


def test_concurrent_registration_heartbeat_restart_stress():
    """Pin the all-mutation-under-session-lock discipline: hammer register /
    touch / completion from many threads across a concurrent gang restart and
    assert the table ends consistent (no partial resets, no stale survivors).
    """
    import threading

    s = Session(make_specs(worker=8))
    stop = threading.Event()
    errors: list[Exception] = []

    def worker_thread(i: int) -> None:
        try:
            while not stop.is_set():
                t = s.task("worker", i)
                attempt = t.attempt
                s.register("worker", i, f"h{i}", 1000 + i, attempt)
                s.touch("worker", i, attempt)
                s.mark_running("worker", i)
                s.cluster_spec_json()
                s.rank_table()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker_thread, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for _ in range(20):
        s.reset_for_restart(None)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors
    gen = s.generation
    assert gen == 20
    attempts = {t.attempt for t in s.tasks.values()}
    assert attempts == {20}

"""SLO engine (obs/slo.py): burn-rate windowing edge cases, the latch +
flushed-instant contract, the chaos `slo-surfaced` invariant, and the
end-to-end serve-engine trip."""

import json
import os
import time

from tony_tpu.obs import series, slo, trace
from tony_tpu.obs.slo import SloConfig, SloEngine


def _engine(tmp_path=None, **cfg):
    cfg.setdefault("ttft_p99_s", 0.5)
    cfg.setdefault("fast_window_s", 60.0)
    cfg.setdefault("slow_window_s", 3600.0)
    cfg.setdefault("min_points", 3)
    return SloEngine(
        SloConfig(**cfg),
        app_dir=str(tmp_path) if tmp_path is not None else "",
        proc="t0",
    )


def _feed(eng, values, key="ttft_p99_s", t0=None, dt=1.0):
    t0 = time.time() if t0 is None else t0
    for i, v in enumerate(values):
        eng.observe({"ts": t0 + i * dt, key: v})


class TestConfig:
    def test_roundtrip_and_active(self):
        cfg = SloConfig(ttft_p99_s=0.5, goodput_floor=0.8)
        again = SloConfig.from_json(cfg.to_json())
        assert again == cfg
        assert sorted(cfg.active()) == ["goodput_floor", "ttft_p99_s"]
        assert SloConfig().active() == []  # nothing contracted by default

    def test_from_config_reads_slo_keys(self):
        from tony_tpu.config.config import TonyConfig
        from tony_tpu.config.keys import Keys

        c = TonyConfig()
        c.set(Keys.SLO_TTFT_P99_S, 0.25)
        c.set(Keys.SLO_FAST_WINDOW_S, 30)
        cfg = SloConfig.from_config(c)
        assert cfg.ttft_p99_s == 0.25 and cfg.fast_window_s == 30.0
        assert cfg.budget_frac == 0.1  # defaults ride along

    def test_attach_from_env(self, tmp_path, monkeypatch):
        slo.uninstall()
        rec = series.SeriesRecorder(None, "t")
        # no env: nothing armed
        monkeypatch.delenv(slo.ENV_SLO, raising=False)
        assert slo.attach_from_env(rec) is None
        # inactive targets: nothing armed
        monkeypatch.setenv(slo.ENV_SLO, SloConfig().to_json())
        assert slo.attach_from_env(rec) is None
        # active target: engine rides the recorder as an observer
        monkeypatch.setenv(
            slo.ENV_SLO,
            SloConfig(ttft_p99_s=0.001, min_points=1).to_json(),
        )
        eng = slo.attach_from_env(rec)
        try:
            assert eng is slo.active_engine()
            rec.force_sample(ttft_p99_s=5.0)
            rec.drain()
            assert eng.trip_counts()  # the observer really evaluates
        finally:
            slo.uninstall()
            rec.close()


class TestWindowing:
    def test_empty_series_never_trips(self, tmp_path):
        eng = _engine(tmp_path)
        assert eng.verdict == "met"
        # points without the watched metric are no-data, not violations
        _feed(eng, [None] * 5, key="unrelated")
        assert eng.verdict == "met"

    def test_single_sample_window_is_a_blip_not_a_page(self, tmp_path):
        eng = _engine(tmp_path)
        _feed(eng, [9.9])  # violently bad, but one sample
        assert eng.verdict == "met"
        _feed(eng, [9.9], t0=time.time() + 1)
        assert eng.verdict == "met"  # still under min_points=3

    def test_trips_within_one_fast_window_and_reports_burn(self, tmp_path):
        eng = _engine(tmp_path)
        t0 = time.time()
        _feed(eng, [2.0, 2.0, 2.0], t0=t0)  # 3 bad points over 2s << 60s
        assert eng.verdict == "tripped"
        detail = eng.summary()["detail"]["ttft_p99_s"]
        assert detail["fast_bad_frac"] == 1.0
        assert detail["burn_fast"] == 10.0  # 1.0 bad / 0.1 budget
        assert detail["worst"] == 2.0
        assert detail["fast_points"] == 3

    def test_under_budget_never_trips(self, tmp_path):
        eng = _engine(tmp_path, budget_frac=0.5)
        # 1/4 bad: under the 50% budget in both windows
        _feed(eng, [0.1, 0.1, 9.0, 0.1])
        assert eng.verdict == "met"

    def test_below_direction_goodput_floor(self, tmp_path):
        eng = _engine(tmp_path, ttft_p99_s=0.0, goodput_floor=0.8)
        _feed(eng, [0.95, 0.9, 0.93], key="goodput_frac")
        assert eng.verdict == "met"
        _feed(eng, [0.2, 0.3, 0.25], key="goodput_frac",
              t0=time.time() + 10)
        assert eng.verdict == "tripped"
        assert "goodput_floor" in eng.trip_counts()

    def test_clock_skewed_out_of_order_points_window_consistently(self, tmp_path):
        """Two hosts' journals merged with skewed clocks: points arrive
        out of ts order. The engine windows off the newest ts seen and
        must neither crash nor evict the live window."""
        eng = _engine(tmp_path, min_points=3, fast_window_s=300.0)
        t0 = time.time()
        eng.observe({"ts": t0 + 120, "ttft_p99_s": 2.0})   # host A, fast clock
        eng.observe({"ts": t0, "ttft_p99_s": 2.0})         # host B, behind
        eng.observe({"ts": t0 + 1, "ttft_p99_s": 2.0})
        eng.observe({"ts": t0 + 121, "ttft_p99_s": 2.0})
        assert eng.verdict == "tripped"
        # a skew WIDER than the fast window correctly keeps the behind
        # host's points out of the fast count (no cross-clock blending)
        eng_narrow = _engine(tmp_path, min_points=3, fast_window_s=60.0)
        eng_narrow.observe({"ts": t0 + 120, "ttft_p99_s": 2.0})
        eng_narrow.observe({"ts": t0, "ttft_p99_s": 2.0})
        eng_narrow.observe({"ts": t0 + 1, "ttft_p99_s": 2.0})
        eng_narrow.observe({"ts": t0 + 121, "ttft_p99_s": 2.0})
        assert eng_narrow.verdict == "met"
        # ancient stragglers beyond the slow window are evicted, not kept
        eng2 = _engine(tmp_path, slow_window_s=100.0)
        eng2.observe({"ts": t0, "ttft_p99_s": 0.1})
        eng2.observe({"ts": t0 + 500, "ttft_p99_s": 0.1})
        assert len(eng2._points) == 1  # the old point aged out

    def test_latch_one_bundle_counted_repeats(self, tmp_path):
        eng = _engine(tmp_path)
        _feed(eng, [2.0] * 3)
        assert eng.trip_counts()["ttft_p99_s"] == 1
        _feed(eng, [2.0] * 4, t0=time.time() + 5)
        assert eng.trip_counts()["ttft_p99_s"] == 5  # repeats counted...
        bundles = slo.forensics_files(str(tmp_path))
        assert bundles == ["t0_ttft_p99_s.trip.json"]  # ...one bundle


class TestTripSurfaces:
    def test_trip_writes_verdict_bundle_metrics_and_flushed_instant(
        self, tmp_path
    ):
        """The latch must survive a chaos SIGKILL: the slo.<name> trace
        instant is ON DISK the moment _trip returns — no close(), no
        flusher-thread grace."""
        from tony_tpu.obs.registry import Registry

        trace.uninstall()
        tracer = trace.install(trace.Tracer(
            str(tmp_path / "trace" / "t0.jsonl"), "t0", "tr",
            flush_interval_s=3600.0,  # the daemon flusher will NOT help
        ))
        reg = Registry()
        eng = SloEngine(
            SloConfig(ttft_p99_s=0.5, min_points=3),
            app_dir=str(tmp_path), proc="t0", registry=reg,
        )
        try:
            _feed(eng, [2.0, 2.0, 2.0])
            assert eng.verdict == "tripped"
            # instant already journaled (flushed at trip, pre-kill)
            recs = [
                json.loads(l)
                for l in open(tmp_path / "trace" / "t0.jsonl")
                if l.strip()
            ]
            instants = [r for r in recs if r.get("ph") == "i"]
            assert any(r["name"] == "slo.ttft_p99_s" for r in instants)
        finally:
            trace.uninstall()
        # verdict + bundle on disk
        verdicts = slo.read_verdicts(str(tmp_path))
        assert verdicts["t0"]["verdict"] == "tripped"
        assert "ttft_p99_s" in verdicts["t0"]["slos"]
        bundle_path = tmp_path / "slo" / "t0_ttft_p99_s.trip.json"
        bundle = json.loads(bundle_path.read_text())
        assert bundle["detail"]["worst"] == 2.0
        assert bundle["window"]  # the series slice at trip rode along
        # registry metrics
        snap = {
            (e["name"], tuple(sorted(e["labels"].items()))): e
            for e in reg.snapshot()
        }
        assert snap[("tony_slo_verdict", ())]["value"] == 1.0
        assert snap[
            ("tony_slo_trips_total", (("slo", "ttft_p99_s"),))
        ]["value"] >= 1
        # rollup verdict
        assert slo.rollup(str(tmp_path))["verdict"] == "tripped"

    def test_met_verdict_is_recorded_distinguishably(self, tmp_path):
        eng = _engine(tmp_path)
        eng.write_verdict()
        roll = slo.rollup(str(tmp_path))
        assert roll["verdict"] == "met"
        assert slo.rollup(str(tmp_path / "nothing"))["verdict"] == "unwatched"


class TestChaosInvariant:
    def _job(self, tmp_path, name, slo_verdict):
        d = tmp_path / name
        d.mkdir()
        (d / "status.json").write_text(
            json.dumps({"state": "SUCCEEDED", "exit_code": 0, "tasks": []})
        )
        ev = d / "events"
        ev.mkdir()
        (ev / f"{name}.jhist.jsonl").write_text(json.dumps(
            {"type": "APPLICATION_FINISHED", "ts": 0, "state": "SUCCEEDED"}
        ) + "\n")
        if slo_verdict:
            sdir = d / "slo"
            sdir.mkdir()
            (sdir / "verdict_w0.json").write_text(json.dumps(slo_verdict))
        return str(d)

    def test_tripped_slo_can_never_report_clean(self, tmp_path):
        from tony_tpu.chaos.invariants import check_invariants

        clean = self._job(tmp_path, "clean", {
            "verdict": "met", "proc": "w0", "slos": {},
        })
        assert check_invariants([clean]).ok
        bad = self._job(tmp_path, "bad", {
            "verdict": "tripped", "proc": "w0",
            "slos": {"ttft_p99_s": {"trips": 9}},
        })
        report = check_invariants([bad])
        assert not report.ok
        v = [x for x in report.violations if x.invariant == "slo-surfaced"]
        assert len(v) == 1
        assert "ttft_p99_s" in v[0].detail and "w0" in v[0].detail


class TestEndToEnd:
    def test_serve_engine_trips_ttft_slo_within_one_fast_window(
        self, tmp_path, monkeypatch
    ):
        """The acceptance shape in-process: a decode engine whose real
        TTFT violates a (deliberately impossible) ttft_p99_s contract
        trips the SLO within one fast window of serving; the verdict,
        bundle, series journal, and `tony top` frame all agree."""
        import jax

        from tony_tpu.models.llama import LlamaConfig, init_params
        from tony_tpu.obs.top import build_view, render
        from tony_tpu.serve.engine import Engine, Request, ServeConfig

        app_dir = tmp_path / "app-e2e"
        app_dir.mkdir()
        monkeypatch.setenv("TONY_APP_DIR", str(app_dir))
        monkeypatch.setenv("TONY_TRACE_PROC", "decode_0_user")
        monkeypatch.setenv(series.ENV_SAMPLE, "1")  # scrape every step
        monkeypatch.setenv(slo.ENV_SLO, SloConfig(
            ttft_p99_s=1e-6,       # no real prefill can meet this
            fast_window_s=30.0,    # one fast window bounds the whole run
            min_points=3,
        ).to_json())
        series.uninstall()
        slo.uninstall()
        try:
            cfg = LlamaConfig.tiny()
            # slots=1 + tiny budgets: every request is its own admission
            # wave, so ttft deltas land on many scrape points
            eng = Engine(
                init_params(jax.random.key(0), cfg), cfg,
                ServeConfig(slots=1, max_len=64),
            )
            t0 = time.time()
            eng.run([
                Request(prompt=[1, 2, 3], max_new_tokens=2)
                for _ in range(5)
            ])
            summary = eng.close()
            assert time.time() - t0 < 30.0, "run outgrew the fast window"
            assert summary["slo_verdict"] == "tripped"
            assert "ttft_p99_s" in summary["slo_trips"]
            engine = slo.active_engine()
            assert engine is not None and engine.verdict == "tripped"
        finally:
            series.uninstall()
            slo.uninstall()
        # verdict + bundle under the app dir
        verdicts = slo.read_verdicts(str(app_dir))
        assert any(v["verdict"] == "tripped" for v in verdicts.values())
        assert slo.forensics_files(str(app_dir))
        # series journaled under the app dir
        procs = series.read_series(str(app_dir / "series"))
        assert "decode_0_user" in procs
        # and `tony top` renders the tripped run from those artifacts
        view = build_view(str(app_dir))
        assert view["slo"]["verdict"] == "tripped"
        row = next(
            r for r in view["rows"] if r["proc"] == "decode_0_user"
        )
        assert row["slo"].startswith("TRIP:")
        assert "ttft_p99_s" in row["slo"]
        frame = render(view)
        assert "TRIP:ttft_p99_s" in frame

"""Env-contract tests for the framework runtime adapters.

Parity definition per SURVEY.md section 7 hard part #4: env-contract +
lifecycle equivalence with the reference's TFRuntime / PyTorchRuntime /
HorovodRuntime, with JaxTpuRuntime as the first-class TPU path.
"""

import json

import pytest

from tony_tpu.config.config import TonyConfig
from tony_tpu.runtime import TaskIdentity, make_runtime


@pytest.fixture
def identity():
    return TaskIdentity(
        job_name="worker",
        index=1,
        cluster_spec={"ps": ["h0:2000"], "worker": ["h1:2001", "h2:2002"]},
        coordinator_address="h0:2000",
        process_id=2,
        num_processes=3,
        generation=1,
    )


def test_generic_runtime_base_env(identity):
    env = make_runtime("generic").build_env(identity, TonyConfig())
    spec = json.loads(env["TONY_CLUSTER_SPEC"])
    assert spec["worker"] == ["h1:2001", "h2:2002"]
    assert env["TONY_PROCESS_ID"] == "2"
    assert env["TONY_NUM_PROCESSES"] == "3"
    assert env["TONY_COORDINATOR_ADDR"] == "h0:2000"


def test_tf_config_contract(identity):
    env = make_runtime("tensorflow").build_env(identity, TonyConfig())
    tf_config = json.loads(env["TF_CONFIG"])
    assert tf_config["cluster"] == {
        "ps": ["h0:2000"],
        "worker": ["h1:2001", "h2:2002"],
    }
    assert tf_config["task"] == {"type": "worker", "index": 1}


def test_pytorch_contract(identity):
    env = make_runtime("pytorch").build_env(identity, TonyConfig())
    assert env["MASTER_ADDR"] == "h0"
    assert env["MASTER_PORT"] == "2000"
    assert env["RANK"] == "2"
    assert env["WORLD_SIZE"] == "3"
    assert env["LOCAL_RANK"] == "0"


def test_horovod_contract(identity):
    env = make_runtime("horovod").build_env(identity, TonyConfig())
    assert env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] == "h0"
    assert env["HOROVOD_GLOO_RENDEZVOUS_PORT"] == "2000"
    assert env["HOROVOD_RANK"] == "2"
    assert env["HOROVOD_SIZE"] == "3"
    assert env["HOROVOD_LOCAL_SIZE"] == "1"
    assert env["HOROVOD_CONTROLLER"] == "gloo"


def test_jax_contract(identity):
    env = make_runtime("jax").build_env(identity, TonyConfig())
    assert env["JAX_COORDINATOR_ADDRESS"] == "h0:2000"
    assert env["JAX_PROCESS_ID"] == "2"
    assert env["JAX_NUM_PROCESSES"] == "3"


def test_unknown_framework_rejected():
    with pytest.raises(ValueError):
        make_runtime("mxnet-nope")


def test_jax_initialize_noop_outside_job(monkeypatch):
    from tony_tpu.runtime import jax_tpu

    monkeypatch.delenv(jax_tpu.ENV_COORDINATOR, raising=False)
    jax_tpu.initialize()  # must not raise or touch jax.distributed
    assert not jax_tpu.in_tony_job()


def test_horovod_rendezvous_kv_protocol():
    """The AM-side gloo rendezvous store: PUT stores, GET polls (404 until
    present), DELETE drops a scope — the wire contract gloo clients use."""
    import urllib.error
    import urllib.request

    from tony_tpu.runtime.horovod_driver import RendezvousServer

    srv = RendezvousServer(host="127.0.0.1").start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        # GET before PUT -> 404 (gloo retries on this)
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{base}/job0/rank0", timeout=5)
        assert e.value.code == 404
        req = urllib.request.Request(
            f"{base}/job0/rank0", data=b"addr-of-rank-0", method="PUT"
        )
        assert urllib.request.urlopen(req, timeout=5).status == 200
        with urllib.request.urlopen(f"{base}/job0/rank0", timeout=5) as r:
            assert r.read() == b"addr-of-rank-0"
        assert len(srv) == 1
        req = urllib.request.Request(f"{base}/job0", method="DELETE")
        urllib.request.urlopen(req, timeout=5)
        assert len(srv) == 0
        # scope-exact delete: /job1 must not wipe /job10
        for scope in ("job1", "job10"):
            req = urllib.request.Request(
                f"{base}/{scope}/rank0", data=b"x", method="PUT"
            )
            urllib.request.urlopen(req, timeout=5)
        req = urllib.request.Request(f"{base}/job1", method="DELETE")
        urllib.request.urlopen(req, timeout=5)
        with urllib.request.urlopen(f"{base}/job10/rank0", timeout=5) as r:
            assert r.read() == b"x"
        # clear() (worker restart): everything 404s again
        srv.clear()
        assert len(srv) == 0
    finally:
        srv.stop()


def test_horovod_env_prefers_am_rendezvous(identity, monkeypatch):
    monkeypatch.setenv("TONY_AM_ADDR", "am-host:5000")
    monkeypatch.setenv("TONY_HOROVOD_RENDEZVOUS_PORT", "7100")
    env = make_runtime("horovod").build_env(identity, TonyConfig())
    assert env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] == "am-host"
    assert env["HOROVOD_GLOO_RENDEZVOUS_PORT"] == "7100"

"""graft-lint: checker unit fixtures, the tier-1 zero-findings gate, and
the runtime sanitizer (tony_tpu/analysis/; docs/ANALYSIS.md).

Every checker has at least one firing and one non-firing fixture: the
known-bad snippet MUST produce its code and the known-good twin MUST NOT —
the zero-findings gate is only trustworthy if both directions hold.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tony_tpu.analysis import Baseline, lint_paths, load_project, run_checkers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_src(tmp_path, sources: dict[str, str], select: str = ""):
    """Write fixture modules, lint them, return findings (optionally one
    checker code only)."""
    d = tmp_path / "fixture"
    d.mkdir(exist_ok=True)
    for name, src in sources.items():
        (d / name).write_text(textwrap.dedent(src))
    project = load_project([str(d)])
    return run_checkers(project, select=[select] if select else ())


def codes(findings):
    return [f.code for f in findings]


# --- GL001 host-sync-in-jit ---------------------------------------------------


class TestGL001:
    def test_fires_on_item_in_jit_reachable_helper(self, tmp_path):
        """.item() two call-graph hops below a jax.jit entry fires."""
        fs = lint_src(tmp_path, {"mod.py": """
            import jax
            import jax.numpy as jnp

            def helper(x):
                return x.sum().item()

            def entry(x):
                return helper(x) + 1

            step = jax.jit(entry)
        """}, select="GL001")
        assert codes(fs) == ["GL001"]
        assert "helper" in fs[0].symbol and ".item()" in fs[0].message

    def test_fires_on_float_of_tracer_and_device_get(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import jax
            import jax.numpy as jnp

            def entry(x):
                y = jnp.exp(x)
                a = float(y)          # host sync on a traced value
                b = jax.device_get(y) # host sync
                return a + b.sum()

            step = jax.jit(entry)
        """}, select="GL001")
        assert sorted(f.detail.split("#")[0] for f in fs) == [
            "float()", "jax.device_get"
        ]

    def test_silent_on_unjitted_code_and_static_reads(self, tmp_path):
        """The same syncs outside any jit path, and float() of static
        values / .shape reads inside one, must NOT fire."""
        fs = lint_src(tmp_path, {"mod.py": """
            import jax
            import jax.numpy as jnp

            def driver(x):
                return x.sum().item()  # not jit-reachable: fine

            def entry(x, cfg_lr):
                scale = float(cfg_lr)      # static python value
                rows = x.shape[0]          # static under tracing
                return jnp.exp(x) * scale * rows

            step = jax.jit(entry)
        """}, select="GL001")
        assert fs == []

    def test_real_engine_decode_path_is_traced(self):
        """The live tree's six jitted hot paths are reachable: the decode
        step's transitive callees (sampling, kernels) are in the traced
        closure — the gate actually covers them."""
        project = load_project([os.path.join(REPO, "tony_tpu")])
        for probe in (
            "tony_tpu.serve.engine:_decode_step",
            "tony_tpu.models.generate:sample_tokens",
            "tony_tpu.ops.decode_attention:decode_attention",
            "tony_tpu.models.llama:loss_from_pairs",
            "tony_tpu.ops.fused_ce:fused_ce_tokens",
        ):
            assert project.is_traced(probe), probe


# --- GL002 recompile-hazard ---------------------------------------------------


class TestGL002:
    def test_fires_on_jit_in_loop_and_jit_of_lambda(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import jax

            def run(xs, f):
                out = []
                for x in xs:
                    out.append(jax.jit(f)(x))     # fresh jit per iteration
                return out

            def run2(x):
                g = jax.jit(lambda v: v + 1)      # fresh lambda per call
                return g(x)
        """}, select="GL002")
        assert sorted(f.detail for f in fs) == ["jit-in-loop", "jit-of-lambda"]

    def test_fires_on_shape_keyed_jit_of_partial_in_loop(self, tmp_path):
        """The bucketed-collective regression shape (ops/overlap.py's
        scheduler is exactly this): per step, per bucket, a fresh
        `jit(partial(...))` — the partial is a new object every iteration
        so the jit cache key never repeats and every bucket recompiles
        every step."""
        fs = lint_src(tmp_path, {"mod.py": """
            import functools
            import jax
            from jax import lax

            def reduce_buckets(buckets, axis):
                out = []
                for b in buckets:
                    f = jax.jit(functools.partial(lax.psum, axis_name=axis))
                    out.append(f(b))
                return out
        """}, select="GL002")
        assert [f.detail for f in fs] == ["shape-keyed-jit-in-loop"]

    def test_fires_on_jit_of_partial_built_and_called_per_dispatch(self, tmp_path):
        """The per-dispatch twin of the in-loop case — the MoE routing
        shape: a dispatch helper that re-wraps its kernel around the
        current config in the same expression that calls it. No loop in
        sight, but the caller IS the loop (one routing call per step), so
        every dispatch pays a full recompile."""
        fs = lint_src(tmp_path, {"mod.py": """
            import functools
            import jax

            def _expert_ffn(x, w, n_experts):
                return x @ w

            def route_tokens(x, w, n_experts):
                return jax.jit(functools.partial(_expert_ffn, n_experts=n_experts))(x, w)
        """}, select="GL002")
        assert [f.detail for f in fs] == ["jit-per-dispatch"]
        assert "route_tokens" in fs[0].symbol

    def test_silent_on_hoisted_jit_of_partial(self, tmp_path):
        """The FIX shapes must not fire: a jit-of-partial built once
        outside the loop (the serve/engine.py AOT-family idiom) and
        dispatched per bucket, or memoized per distinct static plan."""
        fs = lint_src(tmp_path, {"mod.py": """
            import functools
            import jax
            from jax import lax

            def reduce_buckets(buckets, axis):
                f = jax.jit(functools.partial(lax.psum, axis_name=axis))
                return [f(b) for b in buckets]

            def reduce_memoized(buckets, axis, cache):
                out = []
                for b in buckets:
                    key = tuple(x.shape for x in b)
                    if key not in cache:
                        cache[key] = _build(axis)
                    out.append(cache[key](b))
                return out

            def _build(axis):
                return jax.jit(functools.partial(lax.psum, axis_name=axis))
        """}, select="GL002")
        assert fs == []

    def test_fires_on_branch_on_tracer(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import jax
            import jax.numpy as jnp

            def entry(x):
                y = jnp.sum(x)
                if y > 0:                  # concretizes the tracer
                    return y
                return -y

            step = jax.jit(entry)
        """}, select="GL002")
        assert [f.detail for f in fs] == ["branch-on-tracer:if"]

    def test_fires_on_unhashable_static_default(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import jax

            def f(x, opts=[1, 2]):
                return x

            g = jax.jit(f, static_argnums=(1,))
        """}, select="GL002")
        assert [f.detail for f in fs] == ["static-unhashable:opts"]

    def test_silent_on_module_level_jit_and_static_branches(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import jax
            import jax.numpy as jnp

            def entry(x, n):
                if n > 4:                # python value: static branch
                    return jnp.exp(x)
                if x.shape[0] > 2:       # shape: static under tracing
                    return x
                return -x

            step = jax.jit(entry, static_argnums=(1,))

            def driver(xs):
                y = jnp.sum(xs)
                if y.shape:              # static metadata read
                    return y
                return y
        """}, select="GL002")
        assert fs == []


# --- GL003 donation-reuse -----------------------------------------------------


class TestGL003:
    def test_fires_on_read_after_donate(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import jax

            def fn(state, batch):
                return state + batch

            step = jax.jit(fn, donate_argnums=(0,))

            def run(state, batch):
                new = step(state, batch)
                return state + new       # state's buffer was donated
        """}, select="GL003")
        assert len(fs) == 1
        assert "donated" in fs[0].detail and "state" in fs[0].message

    def test_silent_on_rebind(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import jax

            def fn(state, batch):
                return state + batch

            step = jax.jit(fn, donate_argnums=(0,))

            def run(state, batches):
                for b in batches:
                    state = step(state, b)   # rebind: canonical donate use
                return state

            def run2(state, batch):
                out = step(state, batch)
                state = out                  # rebound before any read
                return state
        """}, select="GL003")
        assert fs == []


# --- GL004 lock-discipline ----------------------------------------------------


class TestGL004:
    def test_fires_on_sleep_and_unbounded_get_under_lock(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import queue
            import threading
            import time

            _lock = threading.Lock()
            _queue = queue.Queue()

            def f():
                with _lock:
                    time.sleep(1.0)

            def g():
                with _lock:
                    item = _queue.get()
                return item
        """}, select="GL004")
        assert len(fs) == 2
        assert any("time.sleep" in f.message for f in fs)
        assert any("queue" in f.message for f in fs)

    def test_fires_one_hop_deep_and_on_rpcish_calls(self, tmp_path):
        """A helper's blocking call counts against the caller's lock, and
        backend/client attribute calls are RPC-ish blockers."""
        fs = lint_src(tmp_path, {"mod.py": """
            import threading

            class AM:
                def __init__(self, backend):
                    self._lock = threading.Lock()
                    self.backend = backend

                def _helper(self, f):
                    data = f.read()
                    return data

                def tick(self, f):
                    with self._lock:
                        self.backend.release("c1")
                        self._helper(f)
        """}, select="GL004")
        details = sorted(f.detail for f in fs)
        assert any("backend" in d for d in details)
        assert any("via" in d for d in details)

    def test_fires_on_lock_order_inversion(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import threading

            class S:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def one(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def two(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
        """}, select="GL004")
        assert any("inversion" in f.detail for f in fs)

    def test_silent_on_collect_then_release_shape(self, tmp_path):
        """The canonical fix (snapshot under the lock, block outside) and
        bounded waits must not fire."""
        fs = lint_src(tmp_path, {"mod.py": """
            import threading
            import time

            class AM:
                def __init__(self, backend, q):
                    self._lock = threading.Lock()
                    self.backend = backend
                    self._queue = q

                def tick(self):
                    with self._lock:
                        cids = list(range(3))
                        item = self._queue.get(timeout=1.0)
                    for c in cids:
                        self.backend.release(c)
                    time.sleep(0.1)
                    return item
        """}, select="GL004")
        assert fs == []

    def test_inline_suppression_is_honoured(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import threading
            import time

            _lock = threading.Lock()

            def f():
                with _lock:
                    # the sleep IS the feature here (test shim)
                    time.sleep(0.1)  # graft-lint: disable=GL004
        """}, select="GL004")
        assert fs == []


# --- GL005 disarmed-hook-cost -------------------------------------------------


class TestGL005:
    def test_fires_on_eager_expensive_args(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import json
            from tony_tpu.obs import trace
            from tony_tpu.chaos import chaos_hook

            def hot(payload, point):
                trace.instant("step", data=json.dumps(payload))
                chaos_hook(point, ctx=build_ctx(payload))

            def build_ctx(p):
                return dict(p)
        """}, select="GL005")
        assert len(fs) == 2
        assert all("disarmed" in f.message for f in fs)

    def test_silent_when_guarded_or_cheap(self, tmp_path):
        fs = lint_src(tmp_path, {"mod.py": """
            import json
            from tony_tpu.obs import trace

            def hot(payload, rid, slot):
                trace.instant("step", rid=rid, slot=slot)  # cheap args
                tracer = trace.active_tracer()
                if tracer is not None:
                    # armed check already paid: eager args are fine
                    trace.instant("step", data=json.dumps(payload))
                    tracer.span("x", data=json.dumps(payload))
        """}, select="GL005")
        assert fs == []

    def test_hbm_sample_seam_holds_the_same_contract(self, tmp_path):
        """The HBM observatory's sample() seam (obs/hbm.py) is a hook site
        like trace.span: expensive arguments fire, bare calls are clean."""
        fs = lint_src(tmp_path, {"mod.py": """
            from tony_tpu.obs import hbm

            def hot_loop(step):
                hbm.sample()                    # the wired call shape: clean
                hbm.sample(note=describe(step))  # eager call arg: fires

            def describe(step):
                return {"step": step}
        """}, select="GL005")
        assert len(fs) == 1
        assert "disarmed" in fs[0].message and fs[0].line == 6

    def test_health_sample_seam_holds_the_same_contract(self, tmp_path):
        """The numerics sentinel's sample() seam (obs/health.py) is the
        third observatory hook: precomputed-name arguments (the wired call
        shape in train/loop.py and serve/engine.py) are clean; an argument
        that allocates or calls before the armed check fires."""
        fs = lint_src(tmp_path, {"mod.py": """
            from tony_tpu.obs import health

            def hot_loop(step, metrics, slot_rids):
                # the wired call shapes: bare names, nothing evaluated
                health.sample(metrics=metrics)
                health.sample(metrics=metrics, slot_rids=slot_rids)
                # eager call argument: evaluated even when disarmed — fires
                health.sample(metrics=summarize(metrics))
                # comprehension argument: ditto — fires
                health.sample(slot_rids=[r for r in slot_rids])

            def summarize(m):
                return dict(m)
        """}, select="GL005")
        assert len(fs) == 2
        assert all("disarmed" in f.message for f in fs)
        assert sorted(f.line for f in fs) == [9, 11]

    def test_profile_capture_seam_holds_the_same_contract(self, tmp_path):
        """The coordinated profiler's maybe_capture() seam (obs/profile.py)
        is the fifth observatory hook: the wired call shapes (bare call in
        the serve engine, precomputed fetch_s name in fit()) are clean; an
        argument that calls or allocates before the armed check fires."""
        fs = lint_src(tmp_path, {"mod.py": """
            from tony_tpu.obs import profile

            def hot_loop(step, fetch_s):
                # the wired call shapes: bare call / bare names
                profile.maybe_capture()
                profile.maybe_capture(fetch_s=fetch_s)
                # eager call argument: evaluated even when disarmed — fires
                profile.maybe_capture(note=describe(step))
                # comprehension argument: ditto — fires
                profile.maybe_capture(vals=[v for v in (step,)])

            def describe(step):
                return {"step": step}
        """}, select="GL005")
        assert len(fs) == 2
        assert all("disarmed" in f.message for f in fs)
        assert sorted(f.line for f in fs) == [9, 11]

    def test_series_sample_seam_holds_the_same_contract(self, tmp_path):
        """The live-series recorder's sample() seam (obs/series.py) is the
        fourth observatory hook: the wired call shapes (bare call in the
        serve/frontend loops, precomputed names in fit()) are clean; an
        argument that calls or allocates before the armed check fires."""
        fs = lint_src(tmp_path, {"mod.py": """
            from tony_tpu.obs import series

            def hot_loop(step, stats):
                # the wired call shapes: bare call / bare names
                series.sample()
                series.sample(step=step)
                # eager call argument: evaluated even when disarmed — fires
                series.sample(stats=scrape(stats))
                # comprehension argument: ditto — fires
                series.sample(vals=[v for v in stats])

            def scrape(s):
                return dict(s)
        """}, select="GL005")
        assert len(fs) == 2
        assert all("disarmed" in f.message for f in fs)
        assert sorted(f.line for f in fs) == [9, 11]


# --- suppression / baseline machinery ----------------------------------------


class TestMachinery:
    SRC = {"mod.py": """
        import threading
        import time

        _lock = threading.Lock()

        def f():
            with _lock:
                time.sleep(1.0)
    """}

    def test_baseline_covers_by_fingerprint_not_line(self, tmp_path):
        fs = lint_src(tmp_path, self.SRC, select="GL004")
        assert len(fs) == 1
        bl = Baseline({fs[0].fingerprint: "known debt"})
        shifted = dict(self.SRC)
        shifted["mod.py"] = "# a new leading comment shifts every line\n" + \
            textwrap.dedent(self.SRC["mod.py"])
        d = tmp_path / "fixture"
        (d / "mod.py").write_text(shifted["mod.py"])
        fs2 = run_checkers(load_project([str(d)]), select=["GL004"])
        assert len(fs2) == 1 and fs2[0].line != fs[0].line
        assert bl.covers(fs2[0])  # same fingerprint despite the line shift

    def test_baseline_save_keeps_justifications(self, tmp_path):
        fs = lint_src(tmp_path, self.SRC, select="GL004")
        path = str(tmp_path / "bl.json")
        bl = Baseline({fs[0].fingerprint: "why it is ok"}, path)
        bl.save(findings=fs)
        reloaded = Baseline.load(path)
        assert reloaded.entries[fs[0].fingerprint] == "why it is ok"

    def test_single_file_lint_matches_directory_fingerprints(self):
        """Fingerprints anchor at the repo root no matter the argument
        shape: linting one changed file must cover the same baseline
        entries as the whole-tree lint (else per-file CI/dev lints report
        grandfathered findings as new)."""
        baseline = Baseline.load(os.path.join(REPO, "graft_lint_baseline.json"))
        new, old = lint_paths(
            [os.path.join(REPO, "tony_tpu", "cluster", "lease.py")], baseline
        )
        assert new == [], "\n".join(f.render() for f in new)
        assert {f.fingerprint for f in old} <= set(baseline.entries)
        assert all(f.path == "tony_tpu/cluster/lease.py" for f in old)

    def test_cli_json_format_and_exit_codes(self, tmp_path, capsys):
        from tony_tpu.analysis.cli import main as lint_main

        d = tmp_path / "fixture"
        d.mkdir()
        (d / "mod.py").write_text(textwrap.dedent(self.SRC["mod.py"]))
        rc = lint_main([str(d), "--baseline", "none", "--format", "json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1 and [f["code"] for f in out["new"]] == ["GL004"]
        (d / "mod.py").write_text("x = 1\n")
        assert lint_main([str(d), "--baseline", "none"]) == 0


# --- the tier-1 gate ----------------------------------------------------------


@pytest.mark.lint
def test_codebase_is_lint_clean():
    """`tony lint tony_tpu/` on the shipped tree: ZERO non-baselined
    findings — the same stale-doc gate shape as gen_config_doc --check.
    A new finding means: fix it, suppress it inline with a justifying
    comment, or baseline it with a justification (docs/ANALYSIS.md)."""
    baseline = Baseline.load(os.path.join(REPO, "graft_lint_baseline.json"))
    new, old = lint_paths([os.path.join(REPO, "tony_tpu")], baseline)
    assert new == [], "new graft-lint findings:\n" + "\n".join(
        f.render() for f in new
    )


@pytest.mark.lint
def test_baseline_entries_are_current_and_justified():
    """Every baseline entry must still match a live finding (no stale
    grandfathering) and carry a real justification."""
    baseline = Baseline.load(os.path.join(REPO, "graft_lint_baseline.json"))
    _, old = lint_paths([os.path.join(REPO, "tony_tpu")], baseline)
    live = {f.fingerprint for f in old}
    stale = set(baseline.entries) - live
    assert not stale, f"baseline entries no longer firing: {sorted(stale)}"
    for fp, why in baseline.entries.items():
        assert why and "TODO" not in why, f"unjustified baseline entry: {fp}"


@pytest.mark.lint
def test_scripts_lint_entry_point():
    """The CI wrapper exits 0 on the shipped tree."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# --- runtime sanitizer (analysis/sanitize.py) ---------------------------------


class TestSanitizer:
    def test_disabled_is_noop(self, monkeypatch):
        from tony_tpu.analysis import sanitize

        monkeypatch.delenv(sanitize.ENV_FLAG, raising=False)
        with sanitize.sanitized_loop("probe") as watchdog:
            assert watchdog is None

    def test_watchdog_trips_on_steady_state_compile(self, monkeypatch):
        """A fresh jit inside the sanitized region is the recompile-per-
        step failure mode; the watchdog must raise."""
        import jax
        import jax.numpy as jnp

        from tony_tpu.analysis import sanitize

        monkeypatch.setenv(sanitize.ENV_FLAG, "1")
        with pytest.raises(sanitize.SanitizeError, match="compile"):
            with sanitize.sanitized_loop("probe", max_compiles=0) as watchdog:
                jax.jit(lambda v: v * 2)(jnp.ones(3)).block_until_ready()
                watchdog.check()

    def test_sanitized_fit_tiny_triggers_neither(self, monkeypatch):
        """The guarded tiny-model training loop runs to completion under
        GRAFT_SANITIZE=1: no implicit D2H transfer, no steady-state
        compile — the loop honours the contract the lint enforces
        statically."""
        from tony_tpu.analysis import sanitize
        from tony_tpu.models.llama import LlamaConfig
        from tony_tpu.parallel.mesh import MeshShape
        from tony_tpu.train.data import DataConfig
        from tony_tpu.train.loop import FitConfig, fit

        monkeypatch.setenv(sanitize.ENV_FLAG, "1")
        out = fit(FitConfig(
            model=LlamaConfig.tiny(),
            data=DataConfig(global_batch=4, seq_len=16, vocab_size=256),
            mesh_shape=MeshShape(fsdp=2),
            steps=4, log_every=2,
        ))
        assert out["steps"] == 4 and out["final_loss"] == out["final_loss"]

    def test_sanitized_warm_engine_decode_triggers_neither(self, monkeypatch):
        """A warmed engine (compiles already paid) drains a trace under
        GRAFT_SANITIZE=1 without tripping either sanitizer arm."""
        import jax
        import numpy as np

        from tony_tpu.analysis import sanitize
        from tony_tpu.models.llama import LlamaConfig, init_params
        from tony_tpu.serve.engine import Engine, Request, ServeConfig

        cfg = LlamaConfig.tiny()
        params = init_params(jax.random.key(0), cfg)
        engine = Engine(params, cfg, ServeConfig(
            slots=2, max_len=64, prefill_buckets=(8,)
        ))
        reqs = lambda seed: [  # noqa: E731
            Request(prompt=np.arange(1, 6), max_new_tokens=4,
                    temperature=0.7, rng=seed + i)
            for i in range(3)
        ]
        warm = engine.run(reqs(0))          # pays every compile
        assert all(len(c.tokens) == 4 for c in warm.values())
        monkeypatch.setenv(sanitize.ENV_FLAG, "1")
        out = engine.run(reqs(10))          # sanitized: same signatures
        assert all(len(c.tokens) == 4 for c in out.values())
        engine.close()

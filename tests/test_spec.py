"""Speculative decoding (serve/spec.py): drafts, the rejection rule, the
G-query decode kernels, and engine parity.

The load-bearing claim is *draw-for-draw identity*: with deterministic
drafts the engine's rejection rule emits exactly the tokens autoregressive
decoding would sample with the same per-slot rng chain — so every test
here reduces to "spec on == spec off", greedy and sampled, with the
prefix store live, through both decode kernels.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import llama
from tony_tpu.models.generate import generate, sample_tokens
from tony_tpu.ops.decode_attention import (
    decode_attention, reference_decode_attention,
)
from tony_tpu.serve import Engine, Request, ServeConfig
from tony_tpu.serve.cache import SCRATCH_BLOCK, blocks_for, scatter_block_kv
from tony_tpu.serve.engine import _SlotState
from tony_tpu.serve.prefix import PrefixStore
from tony_tpu.serve.spec import ngram_propose, propose_drafts, verify_and_accept


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompts(cfg, lengths, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size, n).astype(np.int32) for n in lengths]


# --- draft sources ------------------------------------------------------------


def _store(block=4):
    st = PrefixStore(block=block, block_bytes=1)
    return st


def test_longest_extension_walks_stored_path():
    st = _store()
    seq = list(range(100, 112))  # 3 full blocks of 4
    st.insert(seq, [1, 2, 3], retain=lambda pid: None)
    # context ending on a block boundary: the extension is the next chunks
    assert st.longest_extension(seq[:4], 8) == seq[4:12]
    assert st.longest_extension(seq[:8], 2) == seq[8:10]  # max_k truncates
    # full stored path: nothing beyond it
    assert st.longest_extension(seq, 4) == []


def test_longest_extension_mid_block():
    """A context ending mid-block extends with the remainder of the
    partially-entered chunk, then onward along the tree — and a mid-block
    extension END (no children) returns the short remainder, not a padded
    or truncated-to-zero draft."""
    st = _store()
    seq = list(range(100, 112))
    st.insert(seq, [1, 2, 3], retain=lambda pid: None)
    # ctx ends 2 tokens into block 1: remainder of that chunk + block 2
    assert st.longest_extension(seq[:6], 8) == seq[6:12]
    # ctx ends 1 token into the LAST block: the extension is the chunk's
    # 3-token remainder and nothing more — the mid-block end case
    assert st.longest_extension(seq[:9], 8) == seq[9:12]
    assert st.longest_extension(seq[:11], 8) == seq[11:12]


def test_longest_extension_unknown_context_is_empty():
    st = _store()
    seq = list(range(100, 112))
    st.insert(seq, [1, 2, 3], retain=lambda pid: None)
    assert st.longest_extension([1, 2, 3], 4) == []          # off-tree
    assert st.longest_extension(seq[:5] + [0], 4) == []      # diverges
    assert st.longest_extension(seq + [0], 4) == []          # past the path
    assert st.longest_extension(seq[:4], 0) == []            # k=0


def test_longest_extension_prefers_hotter_children():
    st = _store(block=2)
    st.insert([1, 2, 3, 4], [1, 2], retain=lambda pid: None)
    st.insert([1, 2, 9, 9], [1, 3], retain=lambda pid: None)
    # touch the [3, 4] branch so it outranks [9, 9] on hits
    st.match([1, 2, 3, 4], limit=4)
    assert st.longest_extension([1, 2], 2) == [3, 4]


def test_longest_extension_is_read_only():
    """Drafting must not perturb eviction order or hit-rate accounting."""
    st = _store()
    seq = list(range(100, 112))
    st.insert(seq, [1, 2, 3], retain=lambda pid: None)
    before = (st._clock, st.hit_tokens, st.prompt_tokens)
    st.longest_extension(seq[:6], 8)
    assert (st._clock, st.hit_tokens, st.prompt_tokens) == before


def test_ngram_propose_prompt_lookup():
    ctx = [5, 6, 7, 1, 2, 3, 9, 5, 6, 7]
    # trailing [5, 6, 7] occurred at the start: propose what followed it
    assert ngram_propose(ctx, 4) == [1, 2, 3, 9]
    assert ngram_propose(ctx, 2) == [1, 2]
    # most RECENT earlier occurrence wins
    ctx2 = [4, 8, 1, 4, 8, 2, 4, 8]
    assert ngram_propose(ctx2, 1) == [2]
    # no earlier occurrence of any trailing n-gram -> no draft
    assert ngram_propose([1, 2, 3, 4], 4) == []
    assert ngram_propose([1, 2], 0) == []


def test_propose_drafts_source_pinning():
    st = _store()
    seq = list(range(100, 112))
    st.insert(seq, [1, 2, 3], retain=lambda pid: None)
    ctx = seq[:6]
    assert propose_drafts(ctx, st, 4, "prefix") == seq[6:10]
    assert propose_drafts(ctx, st, 4, "auto") == seq[6:10]
    # ngram-only ignores the store (ctx has no self-repeats -> empty)
    assert propose_drafts(ctx, st, 4, "ngram") == []
    # auto falls back to ngram when the store has nothing
    rep = [3, 4, 5, 3, 4]
    assert propose_drafts(rep, st, 2, "auto") == [5, 3]
    assert propose_drafts(rep, None, 2, "auto") == [5, 3]


# --- the rejection rule -------------------------------------------------------


def _mk_state(S, rngs, temp=0.0, eos=-1, done=False):
    return _SlotState(
        last_tok=jnp.zeros((S,), jnp.int32),
        rng=jnp.asarray(rngs, jnp.uint32),
        temp=jnp.full((S,), temp, jnp.float32),
        top_k=jnp.zeros((S,), jnp.int32),
        top_p=jnp.zeros((S,), jnp.float32),
        eos=jnp.full((S,), eos, jnp.int32),
        done=jnp.full((S,), done, bool),
        live=jnp.ones((S,), bool),
    )


def _reference_chain(logits, drafts, draft_len, state, max_top_k):
    """Per-row pure-python reference: run the 1-wide step's rng chain
    (split -> sample with key 0 -> carry key 1) position by position,
    stopping at the first draft disagreement or emitted eos — exactly
    what autoregressive decoding would emit across these G steps.
    ``drafts=None`` free-runs the chain (every position "agrees")."""
    S, G, _ = logits.shape
    out = []
    for s in range(S):
        carry = state.rng[s]
        emitted = []
        for g in range(G):
            both = jax.random.split(carry)
            t = int(sample_tokens(
                logits[s:s + 1, g], state.temp[s:s + 1], state.top_k[s:s + 1],
                state.top_p[s:s + 1], both[0][None], max_k=max_top_k,
            )[0])
            carry = both[1]
            emitted.append(t)
            if int(state.eos[s]) >= 0 and t == int(state.eos[s]):
                break
            if drafts is None:
                continue
            if g < G - 1 and g < int(draft_len[s]) and t == int(drafts[s, g]):
                continue
            break
        out.append((emitted, np.asarray(carry)))
    return out


@pytest.mark.parametrize("temp", [0.0, 0.9])
def test_verify_and_accept_matches_reference_chain(temp):
    S, G, V = 4, 5, 32
    ks = jax.random.split(jax.random.key(3), 2)
    logits = jax.random.normal(ks[0], (S, G, V), jnp.float32) * 3
    state = _mk_state(S, np.arange(S * 2).reshape(S, 2) + 1, temp=temp)
    # row 0: drafts that agree with the target everywhere (accept all);
    # row 1: garbage drafts (accept none); rows 2/3: random + short
    free = _reference_chain(logits, None, None, state, 64)
    drafts = np.full((S, G - 1), V + 5, np.int32)
    drafts[0] = free[0][0][:G - 1]
    drafts[2] = np.asarray(jax.random.randint(ks[1], (G - 1,), 0, V))
    drafts[3] = drafts[2]
    draft_len = np.asarray([G - 1, G - 1, G - 1, 2], np.int32)
    drafts_j, dlen_j = jnp.asarray(drafts), jnp.asarray(draft_len)

    T, n_emit, n_acc, last_tok, new_rng, done = verify_and_accept(
        logits, drafts_j, dlen_j, state, max_top_k=64,
    )
    ref = _reference_chain(logits, drafts, draft_len, state, 64)
    for s, (emitted, carry) in enumerate(ref):
        n = int(n_emit[s])
        assert n == len(emitted), s
        assert [int(t) for t in T[s, :n]] == emitted, s
        assert int(last_tok[s]) == emitted[-1], s
        assert np.array_equal(np.asarray(new_rng[s]), carry), s
        assert int(n_acc[s]) == n - 1
        assert not bool(done[s])
    # row 0 accepted every draft position
    assert int(n_emit[0]) == G


def test_verify_and_accept_eos_truncates_accepted_span():
    """An eos emitted INSIDE the accepted draft prefix truncates emission
    at the eos (inclusive) and marks the row done — exactly where the
    1-wide step would have stopped."""
    S, G, V = 1, 4, 16
    logits = jax.random.normal(jax.random.key(5), (S, G, V), jnp.float32)
    state = _mk_state(S, [[7, 8]])
    ref = _reference_chain(logits, None, None, state, 16)[0][0]
    # greedy targets known: make every draft agree, then set eos to the
    # token the target emits at position 1
    drafts = np.asarray([ref[:G - 1]], np.int32)
    eos = ref[1]
    state = _mk_state(S, [[7, 8]], eos=eos)
    T, n_emit, n_acc, last_tok, new_rng, done = verify_and_accept(
        logits, jnp.asarray(drafts), jnp.asarray([G - 1], jnp.int32),
        state, max_top_k=16,
    )
    assert int(n_emit[0]) == 2 and bool(done[0])
    assert int(last_tok[0]) == eos
    # the carry advanced exactly 2 splits
    c = state.rng[0]
    for _ in range(2):
        c = jax.random.split(c)[1]
    assert np.array_equal(np.asarray(new_rng[0]), np.asarray(c))


def test_verify_and_accept_done_row_sticks_at_eos():
    S, G, V = 2, 3, 16
    logits = jax.random.normal(jax.random.key(6), (S, G, V), jnp.float32)
    state = _mk_state(S, [[1, 2], [3, 4]], eos=9, done=True)
    T, n_emit, _, last_tok, _, done = verify_and_accept(
        logits, jnp.zeros((S, G - 1), jnp.int32),
        jnp.zeros((S,), jnp.int32), state, max_top_k=16,
    )
    assert bool(done.all())
    assert int(last_tok[0]) == 9 and int(last_tok[1]) == 9
    assert int(n_emit[0]) == 1  # emitted eos, then truncated


# --- G-query decode kernels ---------------------------------------------------


@pytest.mark.parametrize("impl", ["scan", "pallas"])
def test_multi_query_decode_attention_matches_reference(impl):
    """Both kernels at G query positions match the repeat-expanded
    reference: query g of row b attends positions < lengths[b]-(G-1)+g,
    at ragged lengths including the minimum (lengths == G) and a full
    row."""
    B, G, H, Hkv, hd, T, block = 4, 3, 8, 2, 16, 64, 16
    ks = jax.random.split(jax.random.key(11), 3)
    q = jax.random.normal(ks[0], (B, G, H, hd), jnp.float32)
    k = jax.random.normal(ks[1], (B, Hkv, T, hd), jnp.float32)
    v = jax.random.normal(ks[2], (B, Hkv, T, hd), jnp.float32)
    lengths = jnp.asarray([3, 17, 33, 64], jnp.int32)
    ref = reference_decode_attention(q, k, v, lengths)
    got = decode_attention(q, k, v, lengths, impl=impl, block=block)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-6, rtol=1e-5
    )


@pytest.mark.parametrize("impl", ["scan", "pallas"])
def test_multi_query_paged_shared_tables_and_scratch_tails(impl):
    """The paged G-query form through block tables where (a) two rows
    SHARE physical blocks (prefix sharing live during a spec step) and
    (b) table tails beyond each row's length point at the scratch block
    — neither sharing nor scratch garbage may leak into any query
    position."""
    B, G, H, Hkv, hd, block, P, M = 3, 4, 4, 2, 8, 8, 6, 4
    ks = jax.random.split(jax.random.key(13), 3)
    q = jax.random.normal(ks[0], (B, G, H, hd), jnp.float32)
    k_pool = jax.random.normal(ks[1], (P, Hkv, block, hd), jnp.float32)
    v_pool = jax.random.normal(ks[2], (P, Hkv, block, hd), jnp.float32)
    # rows 0 and 1 share blocks 1, 2 (a common prefix); tails at scratch
    tables = jnp.asarray([
        [1, 2, 3, SCRATCH_BLOCK],
        [1, 2, 4, 5],
        [5, SCRATCH_BLOCK, SCRATCH_BLOCK, SCRATCH_BLOCK],
    ], jnp.int32)
    lengths = jnp.asarray([18, 30, 7], jnp.int32)
    got = decode_attention(
        q, k_pool, v_pool, lengths, tables=tables, impl=impl, block=block,
    )
    # reference: gather each row's contiguous K/V through its table
    kc = k_pool[tables].transpose(0, 2, 1, 3, 4).reshape(B, Hkv, M * block, hd)
    vc = v_pool[tables].transpose(0, 2, 1, 3, 4).reshape(B, Hkv, M * block, hd)
    ref = reference_decode_attention(q, kc, vc, lengths)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-6, rtol=1e-5
    )


@pytest.mark.parametrize("impl", ["scan", "pallas"])
def test_g_zero_slice_matches_single_query(impl):
    """The G-wide kernel's g=0 output IS the 1-wide kernel's output at
    the matching length — the spec step's position-0 compute is the
    autoregressive step's. The scan path is gated BITWISE; pallas runs
    interpreted through XLA on CPU, where fusion choices can reassociate
    at ULP level, so it gets a near-zero tolerance instead (on TPU the
    grid cell runs the identical instruction sequence)."""
    B, G, H, Hkv, hd, T, block = 2, 3, 4, 2, 8, 32, 8
    ks = jax.random.split(jax.random.key(17), 3)
    q1 = jax.random.normal(ks[0], (B, H, hd), jnp.float32)
    k = jax.random.normal(ks[1], (B, Hkv, T, hd), jnp.float32)
    v = jax.random.normal(ks[2], (B, Hkv, T, hd), jnp.float32)
    lengths1 = jnp.asarray([9, 25], jnp.int32)
    qG = jnp.concatenate(
        [q1[:, None], jnp.ones((B, G - 1, H, hd), jnp.float32)], axis=1
    )
    one = decode_attention(q1, k, v, lengths1, impl=impl, block=block)
    wide = decode_attention(
        qG, k, v, lengths1 + (G - 1), impl=impl, block=block,
    )
    if impl == "scan":
        assert np.array_equal(np.asarray(one), np.asarray(wide[:, 0]))
    else:
        np.testing.assert_allclose(
            np.asarray(one), np.asarray(wide[:, 0]), atol=1e-7, rtol=1e-6,
        )


def test_scatter_block_kv_multi_position():
    """[S, G] scatter lands each position in its named (block, offset)
    and the [S] 1-D form stays the classic one-token write."""
    P, Hkv, block, hd = 4, 2, 4, 3
    pool = jnp.zeros((P, Hkv, block, hd), jnp.float32)
    new = jnp.arange(2 * 2 * Hkv * hd, dtype=jnp.float32).reshape(2, 2, Hkv, hd)
    pids = jnp.asarray([[1, 1], [2, 3]], jnp.int32)
    offs = jnp.asarray([[0, 1], [3, 0]], jnp.int32)
    out = scatter_block_kv(pool, new, pids, offs)
    for s in range(2):
        for g in range(2):
            np.testing.assert_array_equal(
                np.asarray(out[pids[s, g], :, offs[s, g], :]),
                np.asarray(new[s, g]),
            )
    one = scatter_block_kv(
        pool, new[:, 0], jnp.asarray([1, 2]), jnp.asarray([2, 2])
    )
    np.testing.assert_array_equal(np.asarray(one[1, :, 2, :]), np.asarray(new[0, 0]))
    np.testing.assert_array_equal(np.asarray(one[2, :, 2, :]), np.asarray(new[1, 0]))


# --- engine parity ------------------------------------------------------------


@pytest.mark.slow  # ~20s: double submission of four prompts across three
# configs; impls_agree + the verify_and_accept reference-chain tests keep
# spec parity under tier-1
def test_engine_spec_matches_generate_greedy(setup):
    """Greedy engine output with spec on equals spec off equals solo
    generate() — on the SECOND submission of each prompt too, when the
    radix store (prefix sharing + the trie draft source) is live."""
    cfg, params = setup
    prompts = _prompts(cfg, [3, 9, 14, 5])
    budgets = [6, 5, 7, 4]

    def run(spec):
        eng = Engine(params, cfg, ServeConfig(
            slots=2, max_len=64, kv_block=8, spec=spec, spec_max_draft=4,
        ))
        out = []
        for _ in range(2):  # second round: prefix store + trie are warm
            res = eng.run([
                Request(prompt=p, max_new_tokens=m)
                for p, m in zip(prompts, budgets)
            ])
            out.append([res[r].tokens for r in sorted(res)])
        return out

    on, off = run(True), run(False)
    assert on == off
    for i, (p, m) in enumerate(zip(prompts, budgets)):
        solo = generate(params, jnp.asarray(p)[None], cfg, max_new_tokens=m)
        assert on[0][i] == list(np.asarray(solo[0, len(p):]))
        assert on[1][i] == on[0][i]


@pytest.mark.slow  # re-pays a full spec-engine build for the sampled variant
# of the greedy spec parity test above; the rejection rule's key-chain
# behaviour is covered by the verify_and_accept unit family (tier-1 budget)
def test_engine_spec_matches_generate_sampled(setup):
    """Same rng -> same tokens with speculation on: the rejection rule
    consumes the per-slot key chain exactly as the 1-wide step does, so
    sampled output is draw-for-draw identical, drafts accepted or not."""
    cfg, params = setup
    prompts = _prompts(cfg, [4, 9, 6], seed=1)
    kwargs = [
        dict(temperature=0.8, top_k=7),
        dict(temperature=1.2, top_p=0.9),
        dict(temperature=0.6, top_k=5, top_p=0.7),
    ]
    keys = [jax.random.key(40 + i) for i in range(3)]
    row_keys = [jax.random.split(k, 1)[0] for k in keys]

    def run(spec, source):
        eng = Engine(params, cfg, ServeConfig(
            slots=2, max_len=64, kv_block=8, spec=spec, spec_max_draft=4,
            spec_draft_source=source,
        ))
        out = []
        for _ in range(2):
            rids = [
                eng.submit(Request(prompt=p, max_new_tokens=5, rng=rk, **kw))
                for p, rk, kw in zip(prompts, row_keys, kwargs)
            ]
            res = eng.run()
            out.append([res[r].tokens for r in rids])
        return out

    off = run(False, "auto")
    for source in ("auto", "prefix", "ngram"):
        assert run(True, source) == off, source
    for i, (p, k) in enumerate(zip(prompts, keys)):
        solo = generate(
            params, jnp.asarray(p)[None], cfg, max_new_tokens=5,
            rng=k, **kwargs[i],
        )
        assert off[0][i] == list(np.asarray(solo[0, len(p):]))


@pytest.mark.slow  # re-pays a full spec-engine build; eos-inside-span
# truncation + done-row latching is covered by the verify_and_accept unit
# family and greedy engine parity rides every decode (870s budget)
def test_engine_spec_eos_inside_accepted_draft(setup):
    """An eos landing INSIDE an accepted multi-token span finishes the
    request at exactly the spec-off position — no overshoot tokens leak
    into the completion past the eos."""
    cfg, params = setup
    p = _prompts(cfg, [8], seed=3)[0]
    solo = generate(params, jnp.asarray(p)[None], cfg, max_new_tokens=10)
    gen = list(np.asarray(solo[0, len(p):]))
    # pick an eos deep enough that accepted drafts can cover it
    eos = gen[4]
    want = gen[:gen.index(eos) + 1]
    eng = Engine(params, cfg, ServeConfig(
        slots=1, max_len=64, kv_block=8, spec=True, spec_max_draft=4,
    ))
    # warm WITHOUT the eos so the trie holds the full path, then the
    # timed request drafts across the eos position
    eng.run([Request(prompt=p, max_new_tokens=10)])
    res = eng.run([Request(prompt=p, max_new_tokens=10, eos_id=int(eos))])
    assert res[1].finish_reason == "eos"
    assert res[1].tokens == want


def test_engine_spec_decode_impls_agree(setup):
    cfg, params = setup
    prompts = _prompts(cfg, [3, 10], seed=6)
    outs = {}
    for impl in ("scan", "pallas"):
        eng = Engine(params, cfg, ServeConfig(
            slots=2, max_len=32, kv_block=8, decode_impl=impl,
            spec=True, spec_max_draft=3,
        ))
        res = eng.run([Request(prompt=p, max_new_tokens=5) for p in prompts])
        res2 = eng.run([Request(prompt=p, max_new_tokens=5) for p in prompts])
        outs[impl] = (
            [res[i].tokens for i in sorted(res)],
            [res2[i].tokens for i in sorted(res2)],
        )
    assert outs["scan"] == outs["pallas"]
    assert outs["scan"][0] == outs["scan"][1]


# --- compile ledger / metrics -------------------------------------------------


@pytest.mark.slow  # 20 warm submissions through a full engine build; the
# signature-family shape (every spec key mirrors a plain (blocks, attended)
# key) is the cheap half and the ledger bound follows from it (870s budget)
def test_spec_compile_count_is_bounded(setup):
    """Speculation adds at most a MIRROR of the plain decode signature
    family (one fixed G per engine) — never a per-draft-length or
    per-request signature."""
    cfg, params = setup
    eng = Engine(params, cfg, ServeConfig(
        slots=2, max_len=40, kv_block=8, prefill_buckets=(8, 16, 24),
        spec=True, spec_max_draft=4,
    ))
    lengths = [2, 3, 5, 7, 8, 9, 12, 15, 17, 21]
    for _ in range(2):
        for p in _prompts(cfg, lengths, seed=3):
            eng.submit(Request(prompt=p, max_new_tokens=3))
        eng.run()
    m_axis = 1 + int(np.ceil(np.log2(blocks_for(40, 8))))
    p_axis = 1 + int(np.ceil(np.log2(eng._pool_cap)))
    assert eng.metrics.decode_compiles <= 2 * (m_axis + p_axis)
    # every spec signature is keyed exactly like a plain one
    assert all(len(sig) == 2 for sig in eng._spec_fns)


@pytest.mark.slow  # re-pays a full spec-engine build to read gauge fields;
# record_spec arithmetic is unit-covered and the counters ride every parity
# test above (tier-1 runs close to its 870s timeout)
def test_spec_metrics_and_snapshot(setup):
    cfg, params = setup
    eng = Engine(params, cfg, ServeConfig(
        slots=2, max_len=64, kv_block=8, spec=True, spec_max_draft=4,
    ))
    p = _prompts(cfg, [8], seed=4)[0]
    eng.run([Request(prompt=p, max_new_tokens=9)])
    eng.run([Request(prompt=p, max_new_tokens=9)])  # trie-drafted repeat
    m = eng.metrics
    assert m.draft_proposed > 0
    assert m.draft_accepted > 0
    assert m.spec_rollbacks == m.draft_proposed - m.draft_accepted
    assert 0 < m.draft_accept_rate <= 1
    assert m.tokens_per_step > 1.0  # accepted drafts beat 1 token/step
    snap = eng.stats_snapshot()
    assert snap["tokens_per_step"] == round(m.tokens_per_step, 4)
    assert snap["draft_accept_rate"] == round(m.draft_accept_rate, 4)
    assert snap["spec_rollbacks"] == float(m.spec_rollbacks)
    summ = m.summary()
    assert summ["draft_accept_rate"] > 0
    assert summ["tokens_per_step"] > 1.0
    # registry counters: accepted never exceeds proposed
    reg = eng.registry
    prop = reg.counter("tony_serve_draft_proposed_total").value
    acc = reg.counter("tony_serve_draft_accepted_total").value
    assert prop == m.draft_proposed and acc == m.draft_accepted


@pytest.mark.slow  # ~8s interaction test; spec parity and the health
# monitors each have their own cheaper tier-1 coverage
def test_spec_accepted_drafts_do_not_trip_health(setup, tmp_path):
    """Accepted multi-token steps report the autoregressive frontier's
    logits to the health monitors — a healthy model serving repeats with
    near-full acceptance must not trip serve_nonfinite or entropy_floor."""
    from tony_tpu.obs import health
    from tony_tpu.obs.health import HealthRules, HealthSentinel

    s = health.install(HealthSentinel(
        HealthRules(), app_dir=str(tmp_path), proc="worker_0_user_a0",
        sample_every=1,
    ))
    try:
        cfg, params = setup
        eng = Engine(params, cfg, ServeConfig(
            slots=2, max_len=64, kv_block=8, spec=True, spec_max_draft=4,
        ))
        p = _prompts(cfg, [8], seed=5)[0]
        for _ in range(3):
            eng.run([Request(prompt=p, max_new_tokens=9)])
        assert eng.metrics.draft_accepted > 0
        assert s.trip_counts() == {}
    finally:
        health.install(None)
